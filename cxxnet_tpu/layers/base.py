"""Layer protocol, shared hyper-parameters, weight init, and the registry.

Design (TPU-first, not a translation):

* The reference mutates 4-D ``Node`` buffers in place and hand-writes
  ``Backprop`` per layer (``/root/reference/src/layer/layer.h:161-279``).
  Here a layer is three *pure* functions — ``infer_shape``, ``init_params``,
  ``apply`` — over immutable arrays; ``jax.grad`` of the graph's loss
  replaces every hand-written backprop, and XLA fuses the elementwise
  chains that mshadow expression templates used to fuse.

* Data layout is **NHWC** (TPU-native) instead of the reference's NCHW.
  Image nodes are ``(N, H, W, C)``; flat "matrix" nodes are ``(N, D)``
  (the reference stores them as ``(N, 1, 1, D)``, layer.h:30-54).

* Per-layer weights are a flat dict tagged ``wmat`` / ``bias`` — the same
  tag scheme the reference's weight visitors use
  (``/root/reference/src/layer/visitor.h``), which the updaters rely on for
  per-tag hyper-parameter overrides (``wmat:lr``, ``bias:wd``).

Randomness is functional: ``apply`` receives an optional PRNG key; layers
that need train-time noise (dropout, insanity, prelu noise) fold it.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Shape = Tuple[int, ...]
Params = Dict[str, jnp.ndarray]


class LayerParam:
    """Shared layer hyper-parameters + weight initialization.

    Parity: ``/root/reference/src/layer/param.h:15-138`` (names, defaults,
    and the gaussian / xavier-uniform / kaiming init rules).
    """

    def __init__(self) -> None:
        self.init_sigma = 0.01
        self.init_uniform = -1.0
        self.init_sparse = 10
        self.init_bias = 0.0
        self.random_type = 0  # 0 gaussian, 1 uniform/xavier, 2 kaiming
        self.num_hidden = 0
        self.num_channel = 0
        self.num_group = 1
        self.kernel_width = 0
        self.kernel_height = 0
        self.stride = 1
        self.pad_x = 0
        self.pad_y = 0
        self.no_bias = 0
        self.silent = 0
        self.num_input_channel = 0
        self.num_input_node = 0
        self.temp_col_max = 64 << 18

    def set_param(self, name: str, val: str) -> None:
        if name == "init_sigma":
            self.init_sigma = float(val)
        elif name == "init_uniform":
            self.init_uniform = float(val)
        elif name == "init_bias":
            self.init_bias = float(val)
        elif name == "init_sparse":
            self.init_sparse = int(val)
        elif name == "random_type":
            table = {"gaussian": 0, "uniform": 1, "xavier": 1, "kaiming": 2}
            if val not in table:
                raise ValueError(f"invalid random_type {val!r}")
            self.random_type = table[val]
        elif name == "nhidden":
            self.num_hidden = int(val)
        elif name == "nchannel":
            self.num_channel = int(val)
        elif name == "ngroup":
            self.num_group = int(val)
        elif name == "kernel_size":
            self.kernel_width = self.kernel_height = int(val)
        elif name == "kernel_height":
            self.kernel_height = int(val)
        elif name == "kernel_width":
            self.kernel_width = int(val)
        elif name == "stride":
            self.stride = int(val)
        elif name == "pad":
            self.pad_y = self.pad_x = int(val)
        elif name == "pad_y":
            self.pad_y = int(val)
        elif name == "pad_x":
            self.pad_x = int(val)
        elif name == "no_bias":
            self.no_bias = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "temp_col_max":
            self.temp_col_max = int(val) << 18

    def rand_init_weight(
        self, key: jax.Array, shape: Shape, in_num: int, out_num: int
    ) -> jnp.ndarray:
        """Draw an initial weight tensor (param.h:113-138 rules)."""
        if self.random_type == 0:
            return self.init_sigma * jax.random.normal(key, shape, jnp.float32)
        if self.random_type == 1:
            a = math.sqrt(3.0 / (in_num + out_num))
            if self.init_uniform > 0:
                a = self.init_uniform
            return jax.random.uniform(key, shape, jnp.float32, -a, a)
        if self.random_type == 2:
            if self.num_hidden > 0:
                sigma = math.sqrt(2.0 / self.num_hidden)
            else:
                sigma = math.sqrt(
                    2.0 / (self.num_channel * self.kernel_width * self.kernel_height)
                )
            return sigma * jax.random.normal(key, shape, jnp.float32)
        raise ValueError(f"unsupported random_type {self.random_type}")


class Layer:
    """Base class of all layer types.

    Subclasses override ``infer_shape`` (shape inference + validation, the
    analog of the reference's ``InitConnection``), ``init_params`` and
    ``apply``.  ``apply`` maps a list of input arrays to a list of output
    arrays and must be traceable under ``jax.jit``.
    """

    # registered config-file type name, e.g. "conv"
    type_name: str = ""
    # True for loss layers (self-loop in reference configs)
    is_loss: bool = False
    # param tags kept float32 under mixed precision (norm scales/biases
    # whose math runs in f32 — a bf16 round-trip would only lose bits);
    # whole-layer exemptions live in FunctionalNet._f32_param_keys
    f32_tags: frozenset = frozenset()

    def __init__(self) -> None:
        self.param = LayerParam()

    def set_param(self, name: str, val: str) -> None:
        self.param.set_param(name, val)

    # --- protocol -------------------------------------------------------
    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        raise NotImplementedError

    def init_params(self, key: jax.Array, in_shapes: Sequence[Shape]) -> Params:
        return {}

    def apply(
        self,
        params: Params,
        inputs: Sequence[jnp.ndarray],
        *,
        train: bool = False,
        rng: Optional[jax.Array] = None,
        step: Optional[jnp.ndarray] = None,
    ) -> List[jnp.ndarray]:
        raise NotImplementedError

    # --- helpers --------------------------------------------------------
    def _check_arity(self, in_shapes: Sequence[Shape], n_in: int) -> None:
        if len(in_shapes) != n_in:
            raise ValueError(
                f"{self.type_name}: expected {n_in} input(s), got {len(in_shapes)}"
            )


class LossLayer(Layer):
    """Base of the self-loop loss layers.

    The reference loss layers transform their node in place on forward
    (e.g. softmax probabilities) and *inject* the gradient
    ``(transform(x) - y) * grad_scale / (batch_size * update_period)`` on
    backprop (``loss/loss_layer_base-inl.hpp:60-103``).  Functionally that
    is exactly the gradient of ``loss() = grad_scale * L(x, y) /
    (batch_size * update_period)`` for a suitable ``L``; each subclass
    defines ``L`` so that ``jax.grad`` reproduces the reference gradient
    bit-for-bit in expectation.
    """

    is_loss = True

    def __init__(self) -> None:
        super().__init__()
        self.target = "label"
        self.grad_scale = 1.0

    def set_param(self, name: str, val: str) -> None:
        if name == "target":
            self.target = val
        elif name == "grad_scale":
            self.grad_scale = float(val)
        else:
            super().set_param(name, val)

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        return [tuple(in_shapes[0])]

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        # forward transform only; gradient comes from loss()
        return [self.transform(inputs[0])]

    # subclass API
    def transform(self, x: jnp.ndarray) -> jnp.ndarray:
        """Forward transform (prediction output), e.g. softmax probs."""
        return x

    def loss(self, x: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
        """Summed (not averaged) loss; the trainer scales by
        ``grad_scale / (batch_size * update_period)``."""
        raise NotImplementedError

    def loss_masked(
        self,
        x: jnp.ndarray,
        labels: jnp.ndarray,
        weight: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        """``loss`` with an optional per-row weight vector ``(N,)``.

        The static-shape analog of the reference's ``AdjustBatchSize``
        (``neural_net-inl.hpp:266-277``): a short final train batch is
        zero-padded to the compiled batch size and the padded rows are
        masked out of the loss, so they contribute exactly zero gradient.
        Implemented generically by vmapping the subclass ``loss`` over
        rows — subclasses only ever define the summed form.
        """
        if weight is None:
            return self.loss(x, labels)
        per_row = jax.vmap(
            lambda xi, yi: self.loss(xi[None], yi[None])
        )(x, labels)
        return jnp.sum(per_row * weight.astype(per_row.dtype))


# ----------------------------------------------------------------------
# registry
# ----------------------------------------------------------------------
_REGISTRY: Dict[str, Callable[[], Layer]] = {}


def register(cls):
    """Class decorator: register a Layer under its ``type_name``."""
    assert cls.type_name, f"{cls} missing type_name"
    _REGISTRY[cls.type_name] = cls
    return cls


def create_layer(type_name: str) -> Layer:
    """Factory by config name.

    Parity: ``GetLayerType`` (layer.h:322-361) + ``CreateLayer_``
    (layer_impl-inl.hpp:36-76).  ``pairtest-A-B`` composes two layer types;
    ``shared[...]`` is resolved by the graph builder, not here.
    """
    if type_name.startswith("pairtest-"):
        from .pairtest import PairTestLayer

        rest = type_name[len("pairtest-"):]
        if "-" not in rest:
            raise ValueError(
                f'unknown layer type: "{type_name}" (pairtest needs '
                f"pairtest-<master>-<slave>)"
            )
        master_name, slave_name = rest.split("-", 1)
        return PairTestLayer(create_layer(master_name), create_layer(slave_name))
    if type_name == "torch" and type_name not in _REGISTRY:
        # plugin layer, loaded on demand (the reference gates its caffe
        # adapter behind CXXNET_USE_CAFFE_ADAPTOR the same way)
        from ..plugin import torch_adapter  # noqa: F401 - registers "torch"
    if type_name not in _REGISTRY:
        raise ValueError(f'unknown layer type: "{type_name}"')
    return _REGISTRY[type_name]()


def layer_types() -> List[str]:
    return sorted(_REGISTRY)
