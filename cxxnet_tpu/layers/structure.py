"""Graph-structural layers: split, concat, ch_concat.

Parity sources:
* split — ``/root/reference/src/layer/split_layer-inl.hpp`` (1→n copy
  forward; gradient sum handled by autodiff here)
* concat / ch_concat — ``/root/reference/src/layer/concat_layer-inl.hpp``
  (2–4 inputs; ``concat`` joins the mshadow dim-3 axis — the feature axis
  of flat nodes / width of images; ``ch_concat`` joins channels)
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp

from .base import Layer, Shape, register


@register
class SplitLayer(Layer):
    type_name = "split"

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        # number of outputs is set by the graph builder via n_split
        n = getattr(self, "n_split", 1)
        return [tuple(in_shapes[0]) for _ in range(n)]

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        n = getattr(self, "n_split", 1)
        return [inputs[0] for _ in range(n)]


class _ConcatBase(Layer):
    def _axis(self, shape: Shape) -> int:
        raise NotImplementedError

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        if not (2 <= len(in_shapes) <= 4):
            raise ValueError(f"{self.type_name}: supports 2-4 inputs, got {len(in_shapes)}")
        ax = self._axis(in_shapes[0])
        base = list(in_shapes[0])
        total = 0
        for s in in_shapes:
            if len(s) != len(base):
                raise ValueError(f"{self.type_name}: rank mismatch")
            for j in range(len(base)):
                if j != ax and s[j] != base[j]:
                    raise ValueError(f"{self.type_name}: shape mismatch on axis {j}")
            total += s[ax]
        base[ax] = total
        return [tuple(base)]

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        return [jnp.concatenate(list(inputs), axis=self._axis(inputs[0].shape))]


@register
class ConcatLayer(_ConcatBase):
    """Feature concat: last axis of flat nodes, width axis of images."""

    type_name = "concat"

    def _axis(self, shape: Shape) -> int:
        return 1 if len(shape) == 2 else 2  # (N,D) features | NHWC width


@register
class ChConcatLayer(_ConcatBase):
    """Channel concat (NHWC last axis) — the inception-block join."""

    type_name = "ch_concat"

    def _axis(self, shape: Shape) -> int:
        if len(shape) != 4:
            raise ValueError("ch_concat: input must be an NHWC image node")
        return 3


@register
class ElemwiseSumLayer(Layer):
    """n-ary elementwise sum (residual connections; no reference analog —
    the reference's CNNs had none, transformer blocks need them)."""

    type_name = "eltwise_sum"

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        if len(in_shapes) < 2:
            raise ValueError("eltwise_sum: needs at least 2 inputs")
        first = tuple(in_shapes[0])
        for s in in_shapes[1:]:
            if tuple(s) != first:
                raise ValueError(
                    f"eltwise_sum: shape mismatch {tuple(s)} vs {first}"
                )
        return [first]

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        out = inputs[0]
        for x in inputs[1:]:
            out = out + x
        return [out]
