"""The layer zoo: every layer type of the reference, as pure JAX functions.

Importing this package populates the registry; use ``create_layer(name)``.
"""

from .base import (  # noqa: F401
    Layer,
    LayerParam,
    LossLayer,
    Params,
    Shape,
    create_layer,
    layer_types,
    register,
)
from . import (  # noqa: F401
    conv,
    elemwise,
    embed,
    linear,
    loss,
    sequence,
    structure,
)
from .pairtest import PairTestLayer  # noqa: F401
