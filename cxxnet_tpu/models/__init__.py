"""Model zoo: reference-format ``.conf`` builders.

The reference ships models *as config files* (``example/MNIST``,
``example/ImageNet``, ``example/kaggle_bowl``); this package generates the
same networks programmatically in the identical config grammar, so they
run through the normal config → graph → jit pipeline.  Builders return
conf *text*; feed it to ``cxxnet_tpu.config.parse_pairs`` / the CLI.

Parity sources (structure, hyper-parameters, schedules):
* MNIST MLP — ``/root/reference/example/MNIST/MNIST.conf``
* MNIST conv (LeNet-style) — ``/root/reference/example/MNIST/MNIST_CONV.conf``
* AlexNet — ``/root/reference/example/ImageNet/ImageNet.conf``
* kaggle plankton — ``/root/reference/example/kaggle_bowl/bowl.conf``
* GoogLeNet / VGG-16 — not shipped by the reference (its README names
  them as goals); built here from the papers as the benchmark models
  (BASELINE.json: images/sec/chip on GoogLeNet).
"""

from .builders import (  # noqa: F401
    alexnet_conf,
    googlenet_conf,
    kaggle_bowl_conf,
    mnist_conv_conf,
    mnist_mlp_conf,
    resnet50_conf,
    resnet101_conf,
    resnet152_conf,
    transformer_conf,
    transformer_lm_conf,
    vgg16_conf,
    vgg19_conf,
)

MODEL_BUILDERS = {
    "mnist_mlp": mnist_mlp_conf,
    "mnist_conv": mnist_conv_conf,
    "alexnet": alexnet_conf,
    "googlenet": googlenet_conf,
    "vgg16": vgg16_conf,
    "vgg19": vgg19_conf,
    "resnet50": resnet50_conf,
    "resnet101": resnet101_conf,
    "resnet152": resnet152_conf,
    "kaggle_bowl": kaggle_bowl_conf,
    "transformer": transformer_conf,
    "transformer_lm": transformer_lm_conf,
}
