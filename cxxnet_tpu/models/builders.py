"""Conf-text builders for the model zoo.  See package docstring."""

from __future__ import annotations

from typing import List


def _iter_block(
    kind: str, nsample: int, input_shape: str, nclass: int, threadbuffer: bool = False
) -> str:
    """A synthetic data/eval section (benchmarks; real runs swap in
    mnist/imgbin sections with the same keys)."""
    tb = "iter = threadbuffer\n" if threadbuffer else ""
    return (
        f"{kind} = {'train' if kind == 'data' else 'test'}\n"
        "iter = synthetic\n"
        f"  nsample = {nsample}\n"
        f"  input_shape = {input_shape}\n"
        f"  nclass = {nclass}\n"
        "  label_width = 1\n"
        f"{tb}iter = end\n"
    )


def _tail(
    batch_size: int,
    input_shape: str,
    num_round: int,
    eta: float = 0.01,
    extra: str = "",
    dev: str = "tpu",
    scan_steps: int = 8,
) -> str:
    # scan_steps: the CLI runs k batches as ONE device program
    # (doc/tasks.md); the trainer ignores the key in programmatic use
    return (
        f"input_shape = {input_shape}\n"
        f"batch_size = {batch_size}\n"
        f"dev = {dev}\n"
        f"num_round = {num_round}\n"
        f"max_round = {num_round}\n"
        "updater = sgd\n"
        f"eta = {eta}\n"
        "momentum = 0.9\n"
        "wd = 0.0005\n"
        f"scan_steps = {scan_steps}\n"
        "metric = error\n"
        "eval_train = 1\n"
        "print_step = 100\n"
        f"{extra}"
    )


# ---------------------------------------------------------------------------
def mnist_mlp_conf(
    batch_size: int = 100, synthetic: bool = True, dev: str = "tpu"
) -> str:
    """3-layer MLP (MNIST.conf parity: fullc 160 → sigmoid → fullc 10)."""
    data = (
        _iter_block("data", 6400, "1,1,784", 10)
        + _iter_block("eval", 1600, "1,1,784", 10)
        if synthetic
        else ""
    )
    return data + (
        "netconfig = start\n"
        "layer[0->1] = fullc:fc1\n"
        "  nhidden = 160\n"
        "  init_sigma = 0.01\n"
        "layer[1->2] = sigmoid:se1\n"
        "layer[2->3] = fullc:fc2\n"
        "  nhidden = 10\n"
        "  init_sigma = 0.01\n"
        "layer[3->3] = softmax\n"
        "netconfig = end\n"
    ) + _tail(batch_size, "1,1,784", 15, eta=0.1, dev=dev, extra="wd = 0.0\n")


def mnist_conv_conf(
    batch_size: int = 100, synthetic: bool = True, dev: str = "tpu"
) -> str:
    """LeNet-style conv net (MNIST_CONV.conf parity)."""
    data = (
        _iter_block("data", 6400, "1,28,28", 10)
        + _iter_block("eval", 1600, "1,28,28", 10)
        if synthetic
        else ""
    )
    return data + (
        "netconfig = start\n"
        "layer[0->1] = conv:cv1\n"
        "  kernel_size = 3\n"
        "  pad = 1\n"
        "  stride = 2\n"
        "  nchannel = 32\n"
        "  random_type = xavier\n"
        "  no_bias = 0\n"
        "layer[1->2] = max_pooling\n"
        "  kernel_size = 3\n"
        "  stride = 2\n"
        "layer[2->3] = flatten\n"
        "layer[3->3] = dropout\n"
        "  threshold = 0.5\n"
        "layer[3->4] = fullc:fc1\n"
        "  nhidden = 100\n"
        "  init_sigma = 0.01\n"
        "layer[4->5] = sigmoid:se1\n"
        "layer[5->6] = fullc:fc2\n"
        "  nhidden = 10\n"
        "  init_sigma = 0.01\n"
        "layer[6->6] = softmax\n"
        "netconfig = end\n"
    ) + _tail(batch_size, "1,28,28", 15, eta=0.1, dev=dev, extra="wd = 0.0\n")


# ---------------------------------------------------------------------------
def alexnet_conf(
    batch_size: int = 256,
    num_class: int = 1000,
    synthetic: bool = True,
    nsample: int = 0,
    dev: str = "tpu",
    input_size: int = 227,
    compute_dtype: str = "bfloat16",
) -> str:
    """AlexNet (ImageNet.conf parity: grouped convs, LRN, dropout FCs).

    ``input_size`` shrinks the input for CPU-feasible fixtures (ceil-mode
    pooling keeps every stage valid down to ~67px); 227 is the paper/
    reference shape."""
    shape = f"3,{input_size},{input_size}"
    nsample = nsample or batch_size * 4
    data = (
        _iter_block("data", nsample, shape, num_class, threadbuffer=True)
        + _iter_block("eval", batch_size * 2, shape, num_class)
        if synthetic
        else ""
    )
    lrn = (
        "  local_size = 5\n"
        "  alpha = 0.001\n"
        "  beta = 0.75\n"
        "  knorm = 1\n"
    )
    net = (
        "netconfig = start\n"
        "layer[0->1] = conv:conv1\n"
        "  kernel_size = 11\n  stride = 4\n  nchannel = 96\n"
        "layer[1->2] = relu\n"
        "layer[2->3] = max_pooling\n  kernel_size = 3\n  stride = 2\n"
        "layer[3->4] = lrn\n" + lrn +
        "layer[4->5] = conv:conv2\n"
        "  ngroup = 2\n  nchannel = 256\n  kernel_size = 5\n  pad = 2\n"
        "layer[5->6] = relu\n"
        "layer[6->7] = max_pooling\n  kernel_size = 3\n  stride = 2\n"
        "layer[7->8] = lrn\n" + lrn +
        "layer[8->9] = conv:conv3\n"
        "  nchannel = 384\n  kernel_size = 3\n  pad = 1\n"
        "layer[9->10] = relu\n"
        "layer[10->11] = conv:conv4\n"
        "  nchannel = 384\n  ngroup = 2\n  kernel_size = 3\n  pad = 1\n"
        "layer[11->12] = relu\n"
        "layer[12->13] = conv:conv5\n"
        "  nchannel = 256\n  ngroup = 2\n  kernel_size = 3\n  pad = 1\n"
        "  init_bias = 1.0\n"
        "layer[13->14] = relu\n"
        "layer[14->15] = max_pooling\n  kernel_size = 3\n  stride = 2\n"
        "layer[15->16] = flatten\n"
        "layer[16->17] = fullc:fc6\n"
        "  nhidden = 4096\n  init_sigma = 0.005\n  init_bias = 1.0\n"
        "layer[17->18] = relu\n"
        "layer[18->18] = dropout\n  threshold = 0.5\n"
        "layer[18->19] = fullc:fc7\n"
        "  nhidden = 4096\n  init_sigma = 0.005\n  init_bias = 1.0\n"
        "layer[19->20] = relu\n"
        "layer[20->20] = dropout\n  threshold = 0.5\n"
        f"layer[20->21] = fullc:fc8\n  nhidden = {num_class}\n"
        "layer[21->21] = softmax\n"
        "netconfig = end\n"
    )
    extra = (
        "metric = rec@1\nmetric = rec@5\n"
        "wmat:lr = 0.01\nwmat:wd = 0.0005\n"
        "bias:wd = 0.000\nbias:lr = 0.02\n"
        "lr:schedule = expdecay\nlr:gamma = 0.1\nlr:step = 100000\n"
        f"compute_dtype = {compute_dtype}\n"
    )
    return data + net + _tail(batch_size, shape, 45, eta=0.01, dev=dev, extra=extra)


# ---------------------------------------------------------------------------
def _inception(x: str, m: str, c1: int, c3r: int, c3: int, c5r: int, c5: int,
               cp: int) -> str:
    """One GoogLeNet inception module: 4 branches ch_concat'd to node m."""

    def conv(src: str, dst: str, tag: str, k: int, ch: int, pad: int) -> str:
        # kaiming, not xavier: every branch conv feeds a relu, and xavier
        # halves activation variance per relu layer — measured signal
        # collapse of ~2x per inception block by i5b (the vanishing the
        # paper's auxiliary heads existed to patch); He-init keeps the
        # forward signal unit-scale through all 9 modules
        return (
            f"layer[{src}->{dst}] = conv:{tag}\n"
            f"  kernel_size = {k}\n  nchannel = {ch}\n  pad = {pad}\n"
            "  random_type = kaiming\n"
        )

    s = conv(x, f"{m}_c1", f"{m}_1x1", 1, c1, 0)
    s += f"layer[+1:{m}_b1] = relu\n"
    s += conv(x, f"{m}_c3r", f"{m}_3x3r", 1, c3r, 0)
    s += f"layer[+1:{m}_b2r] = relu\n"
    s += conv(f"{m}_b2r", f"{m}_c3", f"{m}_3x3", 3, c3, 1)
    s += f"layer[+1:{m}_b2] = relu\n"
    s += conv(x, f"{m}_c5r", f"{m}_5x5r", 1, c5r, 0)
    s += f"layer[+1:{m}_b3r] = relu\n"
    s += conv(f"{m}_b3r", f"{m}_c5", f"{m}_5x5", 5, c5, 2)
    s += f"layer[+1:{m}_b3] = relu\n"
    s += (
        f"layer[{x}->{m}_p] = max_pooling\n"
        "  kernel_size = 3\n  stride = 1\n  pad = 1\n"
    )
    s += conv(f"{m}_p", f"{m}_pp", f"{m}_pool_proj", 1, cp, 0)
    s += f"layer[+1:{m}_b4] = relu\n"
    s += f"layer[{m}_b1,{m}_b2,{m}_b3,{m}_b4->{m}] = ch_concat\n"
    return s


def googlenet_conf(
    batch_size: int = 128,
    num_class: int = 1000,
    input_size: int = 224,
    synthetic: bool = True,
    nsample: int = 0,
    dev: str = "tpu",
    compute_dtype: str = "bfloat16",
) -> str:
    """GoogLeNet (inception v1) — the BASELINE.json benchmark model.

    Szegedy et al. 2014, table 1; main classifier only (the two auxiliary
    heads exist for vanishing-gradient relief the TPU build doesn't need
    at this depth; they are train-time-only and dropped at inference).
    """
    shape = f"3,{input_size},{input_size}"
    nsample = nsample or batch_size * 4
    data = (
        _iter_block("data", nsample, shape, num_class, threadbuffer=True)
        + _iter_block("eval", batch_size * 2, shape, num_class)
        if synthetic
        else ""
    )
    lrn = (
        "  local_size = 5\n  alpha = 0.0001\n  beta = 0.75\n  knorm = 1\n"
    )
    net = (
        "netconfig = start\n"
        "layer[0->c1] = conv:conv1\n"
        "  kernel_size = 7\n  stride = 2\n  pad = 3\n  nchannel = 64\n"
        "  random_type = kaiming\n"
        "layer[+1:c1r] = relu\n"
        "layer[c1r->p1] = max_pooling\n  kernel_size = 3\n  stride = 2\n"
        "layer[p1->n1] = lrn\n" + lrn +
        "layer[n1->c2r] = conv:conv2_reduce\n"
        "  kernel_size = 1\n  nchannel = 64\n  random_type = kaiming\n"
        "layer[+1:c2rr] = relu\n"
        "layer[c2rr->c2] = conv:conv2\n"
        "  kernel_size = 3\n  pad = 1\n  nchannel = 192\n"
        "  random_type = kaiming\n"
        "layer[+1:c2a] = relu\n"
        "layer[c2a->n2] = lrn\n" + lrn +
        "layer[n2->p2] = max_pooling\n  kernel_size = 3\n  stride = 2\n"
        + _inception("p2", "i3a", 64, 96, 128, 16, 32, 32)
        + _inception("i3a", "i3b", 128, 128, 192, 32, 96, 64)
        + "layer[i3b->p3] = max_pooling\n  kernel_size = 3\n  stride = 2\n"
        + _inception("p3", "i4a", 192, 96, 208, 16, 48, 64)
        + _inception("i4a", "i4b", 160, 112, 224, 24, 64, 64)
        + _inception("i4b", "i4c", 128, 128, 256, 24, 64, 64)
        + _inception("i4c", "i4d", 112, 144, 288, 32, 64, 64)
        + _inception("i4d", "i4e", 256, 160, 320, 32, 128, 128)
        + "layer[i4e->p4] = max_pooling\n  kernel_size = 3\n  stride = 2\n"
        + _inception("p4", "i5a", 256, 160, 320, 32, 128, 128)
        + _inception("i5a", "i5b", 384, 192, 384, 48, 128, 128)
        + f"layer[i5b->pool5] = avg_pooling\n"
        f"  kernel_size = {max(1, input_size // 32)}\n  stride = 1\n"
        "layer[pool5->pool5] = dropout\n  threshold = 0.4\n"
        "layer[pool5->flat] = flatten\n"
        f"layer[flat->fc] = fullc:loss3_classifier\n"
        f"  nhidden = {num_class}\n  random_type = xavier\n"
        "layer[fc->fc] = softmax\n"
        "netconfig = end\n"
    )
    extra = (
        "metric = rec@1\nmetric = rec@5\n"
        "wmat:lr = 0.01\nwmat:wd = 0.0002\n"
        "bias:lr = 0.02\nbias:wd = 0.0\n"
        "lr:schedule = polydecay\nlr:alpha = 0.5\nlr:max_round = 2400000\n"
        f"compute_dtype = {compute_dtype}\n"
    )
    return data + net + _tail(batch_size, shape, 100, eta=0.01, dev=dev, extra=extra)


def _transformer_blocks(
    prev: str,
    nlayer: int,
    nhead: int,
    dim: int,
    causal: int,
    seq_parallel: int,
    attn_impl: str = "auto",
) -> tuple:
    """Shared pre-norm block emission for transformer_conf /
    transformer_lm_conf: layer_norm -> attention -> residual ->
    layer_norm -> 4x MLP -> residual, per block.  Returns
    ``(conf_text, last_node)``."""
    s = ""
    for i in range(nlayer):
        b = f"b{i}"
        s += (
            f"layer[{prev}->{b}_n1] = layer_norm:{b}_ln1\n"
            f"layer[{b}_n1->{b}_a] = attention:{b}_attn\n"
            f"  nhead = {nhead}\n"
            f"  causal = {causal}\n"
            f"  seq_parallel = {seq_parallel}\n"
            f"  attn_impl = {attn_impl}\n"
            "  init_sigma = 0.02\n"
            f"layer[{prev},{b}_a->{b}_r1] = eltwise_sum\n"
            f"layer[{b}_r1->{b}_n2] = layer_norm:{b}_ln2\n"
            f"layer[{b}_n2->{b}_h] = fullc:{b}_fc1\n"
            f"  nhidden = {dim * 4}\n  init_sigma = 0.02\n"
            f"layer[+1:{b}_g] = gelu\n"
            f"layer[{b}_g->{b}_o] = fullc:{b}_fc2\n"
            f"  nhidden = {dim}\n  init_sigma = 0.02\n"
            f"layer[{b}_r1,{b}_o->{b}_r2] = eltwise_sum\n"
        )
        prev = f"{b}_r2"
    return s, prev


def transformer_lm_conf(
    vocab: int = 256,
    seq_len: int = 128,
    dim: int = 128,
    nhead: int = 4,
    nlayer: int = 2,
    text_file: str = "",
    batch_size: int = 16,
    num_round: int = 10,
    seq_parallel: int = 0,
    dev: str = "tpu",
    compute_dtype: str = "bfloat16",
    attn_impl: str = "auto",
    eta: float = 0.003,
) -> str:
    """Byte-level causal transformer language model.

    New TPU-first scope (the reference has no sequence models): the full
    LM pipeline — ``text`` iterator (byte windows + next-byte labels),
    ``embedding`` with learned positions, pre-norm causal blocks (flash
    attention via ``attn_impl``, sequence parallelism via
    ``seq_parallel``), a per-position softmax over the vocabulary, and
    per-token error/logloss metrics.  ``task = generate`` samples from a
    trained checkpoint (cli.py).
    """
    data = ""
    if text_file:
        data = (
            "data = train\n"
            "iter = text\n"
            f"  filename = {text_file}\n"
            f"  seq_len = {seq_len}\n"
            "  shuffle = 1\n"
            "iter = end\n"
        )
    s = (
        "netconfig = start\n"
        "layer[0->emb] = embedding:embed\n"
        f"  nvocab = {vocab}\n"
        f"  nhidden = {dim}\n"
        "  pos = learned\n"
        "  init_sigma = 0.02\n"
    )
    blocks, prev = _transformer_blocks(
        "emb", nlayer, nhead, dim, 1, seq_parallel, attn_impl
    )
    s += blocks
    s += (
        f"layer[{prev}->nf] = layer_norm:ln_f\n"
        f"layer[nf->logits] = fullc:lm_head\n"
        f"  nhidden = {vocab}\n  init_sigma = 0.02\n"
        "layer[logits->logits] = softmax\n"
        # per-token mean: the loss sums over T positions, so scale by
        # 1/T to keep eta in the familiar per-instance range
        f"  grad_scale = {1.0 / seq_len!r}\n"
        "netconfig = end\n"
    )
    extra = (
        f"compute_dtype = {compute_dtype}\n"
        f"label_width = {seq_len}\n"
        f"label_vec[0,{seq_len}) = label\n"
        "metric = logloss\n"
        # transformers want Adam; override _tail's sgd+momentum
        "updater = adam\n"
        "wd = 0.0\n"
    )
    return data + s + _tail(
        batch_size, f"1,1,{seq_len}", num_round, eta=eta, dev=dev,
        extra=extra,
    )


def _res_bottleneck(prev: str, name: str, cin: int, cmid: int, cout: int,
                    stride: int) -> str:
    """Bottleneck residual block: 1x1 reduce -> 3x3 -> 1x1 expand, each
    conv + batch_norm + relu (relu after the residual add), projection
    shortcut when shape changes (He et al. 2015)."""
    s = ""
    def cbr(src, dst, ch, k, st, pad, tag, relu=True):
        t = (
            f"layer[{src}->{dst}_c] = conv:{tag}_conv\n"
            f"  kernel_size = {k}\n  stride = {st}\n  pad = {pad}\n"
            f"  nchannel = {ch}\n  no_bias = 1\n  random_type = kaiming\n"
            f"layer[{dst}_c->{dst}] = batch_norm:{tag}_bn\n"
        )
        if relu:
            t += f"layer[{dst}->{dst}] = relu\n"
        return t

    s += cbr(prev, f"{name}_a", cmid, 1, stride, 0, f"{name}_a")
    s += cbr(f"{name}_a", f"{name}_b", cmid, 3, 1, 1, f"{name}_b")
    s += cbr(f"{name}_b", f"{name}_c", cout, 1, 1, 0, f"{name}_c",
             relu=False)
    if cin != cout or stride != 1:
        s += cbr(prev, f"{name}_p", cout, 1, stride, 0, f"{name}_proj",
                 relu=False)
        short = f"{name}_p"
    else:
        short = prev
    s += (
        f"layer[{short},{name}_c->{name}] = eltwise_sum\n"
        f"layer[{name}->{name}] = relu\n"
    )
    return s


_RESNET_BLOCKS = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}


def resnet101_conf(**kw) -> str:
    """ResNet-101 — the [3, 4, 23, 3] depth of He et al. 2015 table 1."""
    return resnet50_conf(depth=101, **kw)


def resnet152_conf(**kw) -> str:
    """ResNet-152 — the [3, 8, 36, 3] depth of He et al. 2015 table 1."""
    return resnet50_conf(depth=152, **kw)


def resnet50_conf(
    batch_size: int = 128,
    num_class: int = 1000,
    input_size: int = 224,
    synthetic: bool = True,
    nsample: int = 0,
    dev: str = "tpu",
    compute_dtype: str = "bfloat16",
    depth: int = 50,
) -> str:
    """ResNet-50/101/152 (He et al. 2015, table 1) — bottleneck blocks,
    batch-norm everywhere, projection shortcuts at stage boundaries.
    New-scope zoo entry (the reference predates ResNets); built from the
    paper like the GoogLeNet/VGG entries.  ``depth`` picks the stage
    plan (50: [3,4,6,3], 101: [3,4,23,3], 152: [3,8,36,3]).
    """
    if input_size % 32:
        raise ValueError(
            f"resnet50_conf: input_size={input_size} must be a multiple "
            "of 32 (the stage chain downsamples 5x; anything else leaves "
            "the final avg pool non-global)"
        )
    shape = f"3,{input_size},{input_size}"
    nsample = nsample or batch_size * 4
    data = (
        _iter_block("data", nsample, shape, num_class, threadbuffer=True)
        + _iter_block("eval", batch_size * 2, shape, num_class)
        if synthetic
        else ""
    )
    net = (
        "netconfig = start\n"
        "layer[0->c1] = conv:conv1\n"
        "  kernel_size = 7\n  stride = 2\n  pad = 3\n  nchannel = 64\n"
        "  no_bias = 1\n  random_type = kaiming\n"
        "layer[c1->b1] = batch_norm:bn1\n"
        "layer[b1->b1] = relu\n"
        # pad 0: the framework's ceil-shape pooling (reference parity)
        # with pad 1 would give 57x57; unpadded k3 s2 on 112 lands on
        # the paper's 56x56
        "layer[b1->p1] = max_pooling\n  kernel_size = 3\n  stride = 2\n"
    )
    prev, cin = "p1", 64
    if depth not in _RESNET_BLOCKS:
        raise ValueError(
            f"resnet depth must be one of {sorted(_RESNET_BLOCKS)}, "
            f"got {depth}"
        )
    b0, b1, b2, b3 = _RESNET_BLOCKS[depth]
    stages = [(b0, 64, 256, 1), (b1, 128, 512, 2), (b2, 256, 1024, 2),
              (b3, 512, 2048, 2)]
    for si, (blocks, cmid, cout, stride) in enumerate(stages):
        for bi in range(blocks):
            name = f"s{si}b{bi}"
            st = stride if bi == 0 else 1
            net += _res_bottleneck(prev, name, cin, cmid, cout, st)
            prev, cin = name, cout
    net += (
        f"layer[{prev}->pool] = avg_pooling\n"
        f"  kernel_size = {max(1, input_size // 32)}\n  stride = 1\n"
        "layer[pool->flat] = flatten\n"
        f"layer[flat->fc] = fullc:fc1000\n"
        f"  nhidden = {num_class}\n  random_type = xavier\n"
        "layer[fc->fc] = softmax\n"
        "netconfig = end\n"
    )
    extra = (
        "metric = rec@1\nmetric = rec@5\n"
        "wmat:lr = 0.1\nwmat:wd = 0.0001\n"
        # one-pass E[x^2]-E[x]^2 batch-norm statistics: the 53 BNs read
        # their activations once instead of twice (stats in f32 either
        # way); measured 68.3 -> 63.6 ms/step on the v5e b128 step
        # (doc/performance.md ResNet bisection)
        "bn_stats = onepass\n"
        f"compute_dtype = {compute_dtype}\n"
    )
    return data + net + _tail(batch_size, shape, 90, eta=0.1, dev=dev,
                              extra=extra)



# ---------------------------------------------------------------------------
def vgg19_conf(**kw) -> str:
    """VGG-19 (configuration E, Simonyan & Zisserman 2014)."""
    return vgg16_conf(depth=19, **kw)


def vgg16_conf(
    batch_size: int = 64,
    num_class: int = 1000,
    input_size: int = 224,
    synthetic: bool = True,
    nsample: int = 0,
    dev: str = "tpu",
    compute_dtype: str = "bfloat16",
    depth: int = 16,
) -> str:
    """VGG-16/19 (configurations D/E, Simonyan & Zisserman 2014)."""
    shape = f"3,{input_size},{input_size}"
    nsample = nsample or batch_size * 4
    data = (
        _iter_block("data", nsample, shape, num_class, threadbuffer=True)
        + _iter_block("eval", batch_size * 2, shape, num_class)
        if synthetic
        else ""
    )
    blocks: List[str] = []
    node = "0"
    idx = 0
    if depth == 16:
        plan = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]
    elif depth == 19:
        plan = [(2, 64), (2, 128), (4, 256), (4, 512), (4, 512)]
    else:
        raise ValueError(f"vgg depth must be 16 or 19, got {depth}")
    for b, (reps, ch) in enumerate(plan, start=1):
        for r in range(1, reps + 1):
            dst = f"c{b}_{r}"
            blocks.append(
                f"layer[{node}->{dst}] = conv:conv{b}_{r}\n"
                f"  kernel_size = 3\n  pad = 1\n  nchannel = {ch}\n"
                "  random_type = xavier\n"
                f"layer[+1:{dst}r] = relu\n"
            )
            node = f"{dst}r"
            idx += 1
        blocks.append(
            f"layer[{node}->pool{b}] = max_pooling\n"
            "  kernel_size = 2\n  stride = 2\n"
        )
        node = f"pool{b}"
    net = (
        "netconfig = start\n"
        + "".join(blocks)
        + f"layer[{node}->flat] = flatten\n"
        "layer[flat->f6] = fullc:fc6\n"
        "  nhidden = 4096\n  init_sigma = 0.01\n"
        "layer[+1:f6r] = relu\n"
        "layer[f6r->f6r] = dropout\n  threshold = 0.5\n"
        "layer[f6r->f7] = fullc:fc7\n"
        "  nhidden = 4096\n  init_sigma = 0.01\n"
        "layer[+1:f7r] = relu\n"
        "layer[f7r->f7r] = dropout\n  threshold = 0.5\n"
        f"layer[f7r->f8] = fullc:fc8\n  nhidden = {num_class}\n"
        "  init_sigma = 0.01\n"
        "layer[f8->f8] = softmax\n"
        "netconfig = end\n"
    )
    extra = (
        "metric = rec@1\nmetric = rec@5\n"
        f"compute_dtype = {compute_dtype}\n"
    )
    return data + net + _tail(batch_size, shape, 74, eta=0.01, dev=dev, extra=extra)


# ---------------------------------------------------------------------------
def kaggle_bowl_conf(
    batch_size: int = 64, synthetic: bool = True, dev: str = "tpu",
    compute_dtype: str = "float32",
) -> str:
    """NDSB plankton convnet (bowl.conf parity: 40×40×3, 121 classes).

    Default stays f32 (the net is tiny — its 5-minute-GPU-training-run
    claim is the BASELINE target, and logloss parity matters more than
    step time); pass ``compute_dtype="bfloat16"`` for throughput runs.
    """
    shape = "3,40,40"
    data = (
        _iter_block("data", 3200, shape, 121)
        + _iter_block("eval", 640, shape, 121)
        if synthetic
        else ""
    )
    net = (
        "netconfig = start\n"
        "layer[0->1] = conv:conv1\n"
        "  kernel_size = 5\n  pad = 2\n  nchannel = 32\n"
        "  random_type = xavier\n"
        "layer[1->2] = relu\n"
        "layer[2->3] = max_pooling\n  kernel_size = 3\n  stride = 2\n"
        "layer[3->4] = conv:conv2\n"
        "  kernel_size = 3\n  pad = 1\n  nchannel = 64\n"
        "  random_type = xavier\n"
        "layer[4->5] = relu\n"
        "layer[5->6] = max_pooling\n  kernel_size = 3\n  stride = 2\n"
        "layer[6->7] = conv:conv3\n"
        "  kernel_size = 3\n  pad = 1\n  nchannel = 128\n"
        "  random_type = xavier\n"
        "layer[7->8] = relu\n"
        "layer[8->9] = conv:conv4\n"
        "  kernel_size = 3\n  pad = 1\n  nchannel = 128\n"
        "  random_type = xavier\n"
        "layer[9->10] = relu\n"
        "layer[10->11] = max_pooling\n  kernel_size = 3\n  stride = 2\n"
        "layer[11->12] = flatten\n"
        "layer[12->13] = fullc:fc1\n"
        "  nhidden = 512\n  init_sigma = 0.01\n"
        "layer[13->14] = relu\n"
        "layer[14->14] = dropout\n  threshold = 0.5\n"
        "layer[14->15] = fullc:fc2\n"
        "  nhidden = 121\n  init_sigma = 0.01\n"
        "layer[15->15] = softmax\n"
        "netconfig = end\n"
    )
    extra = (
        "metric = logloss\n"
        f"compute_dtype = {compute_dtype}\n"
    )
    return data + net + _tail(batch_size, shape, 100, eta=0.01, dev=dev, extra=extra)


# ---------------------------------------------------------------------------
def transformer_conf(
    batch_size: int = 32,
    seq_len: int = 128,
    dim: int = 128,
    nhead: int = 4,
    nlayer: int = 2,
    num_class: int = 10,
    causal: int = 0,
    seq_parallel: int = 0,
    synthetic: bool = False,
    nsample: int = 0,
    dev: str = "tpu",
    compute_dtype: str = "bfloat16",
    pipeline_parallel: int = 0,
    n_microbatch: int = 4,
    attn_impl: str = "auto",
) -> str:
    """Pre-norm transformer encoder classifier over dense sequences.

    New TPU-first scope (the reference has no sequence models): blocks of
    layer_norm -> attention -> residual -> layer_norm -> mlp -> residual,
    then mean pooling and a softmax head.  ``seq_parallel=1`` runs ring
    attention with the sequence sharded over the mesh model axis
    (``ops/attention.py``).

    ``pipeline_parallel >= 1`` declares the SAME block stack as a
    ``pipe_transformer`` layer (stacked params) so it can run as a GPipe
    pipeline over the mesh model axis; ``pipeline_parallel = 1`` keeps
    pipelining off (plain scanned stack) with identical math — the
    parity pair for tests.
    """
    nsample = nsample or batch_size * 4
    data = ""
    if synthetic:
        for kind, n in (("data", nsample), ("eval", batch_size * 2)):
            data += (
                f"{kind} = {'train' if kind == 'data' else 'test'}\n"
                "iter = synthetic\n"
                f"  nsample = {n}\n"
                f"  input_shape = 1,{seq_len},{dim}\n"
                f"  nclass = {num_class}\n"
                "  layout = seq\n"
                "iter = end\n"
            )
    s = "netconfig = start\n"
    if pipeline_parallel >= 1 and seq_parallel:
        raise ValueError(
            "transformer_conf: seq_parallel (ring attention) and "
            "pipeline_parallel are mutually exclusive — both shard over "
            "the mesh model axis"
        )
    if pipeline_parallel >= 1:
        s += (
            "layer[0->blocks] = pipe_transformer:blocks\n"
            f"  nblock = {nlayer}\n"
            f"  nhead = {nhead}\n"
            f"  causal = {causal}\n"
            f"  ffn_hidden = {dim * 4}\n"
            f"  pipeline_parallel = {1 if pipeline_parallel > 1 else 0}\n"
            f"  n_microbatch = {n_microbatch}\n"
            "  init_sigma = 0.02\n"
        )
        prev = "blocks"
        per_layer_blocks = range(0)
    else:
        prev = "0"
        per_layer_blocks = range(nlayer)
    if len(per_layer_blocks):
        blocks, prev = _transformer_blocks(
            prev, nlayer, nhead, dim, causal, seq_parallel, attn_impl
        )
        s += blocks
    s += (
        f"layer[{prev}->pool] = seq_pool\n"
        f"layer[pool->fc] = fullc:head\n"
        f"  nhidden = {num_class}\n  init_sigma = 0.02\n"
        "layer[fc->fc] = softmax\n"
        "netconfig = end\n"
        "input_layout = seq\n"
    )
    extra = f"compute_dtype = {compute_dtype}\n"
    if pipeline_parallel > 1:
        extra += f"model_parallel = {pipeline_parallel}\n"
    return data + s + _tail(
        batch_size, f"1,{seq_len},{dim}", 10, eta=0.01, dev=dev, extra=extra
    )
