"""cxxnet-style ``.conf`` grammar: tokenizer, pair stream, section splitting.

The whole framework is driven by a single *ordered* stream of ``name = value``
pairs read from a config file plus CLI overrides.  Order is semantic:

* ``data = <tag>`` / ``eval = <tag>`` / ``pred = <file>`` open an iterator
  section that runs until ``iter = end``; everything inside belongs to that
  iterator chain.
* ``netconfig = start`` .. ``netconfig = end`` delimits the layer graph;
  inside it, keys following a ``layer[...] = ...`` line bind to that layer.
* everything else is a global default applied to every layer / updater /
  iterator.

Grammar parity with the reference implementation
(``/root/reference/src/utils/config.h:20-141``):

* tokens are separated by spaces / tabs / newlines
* ``#`` starts a comment running to end of line
* ``"..."`` is a single-line string token (backslash escapes, no newlines)
* ``'...'`` is a multi-line string token (backslash escapes)
* ``=`` is always its own token
* a setting is the token triplet ``name = value`` on one logical line

Section-splitting parity: ``/root/reference/src/cxxnet_main.cpp:214-264``.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Sequence, Tuple

ConfigEntry = Tuple[str, str]


class ConfigError(ValueError):
    """Malformed configuration text."""


_EQ = object()       # sentinel token: bare '='
_NEWLINE = object()  # sentinel token: logical line break


def _tokenize(text: str) -> Iterator[object]:
    """Yield string tokens, ``_EQ`` for '=', and ``_NEWLINE`` markers.

    Newline markers are emitted between lines (collapsed) so the pair
    assembler can enforce that ``name = value`` does not span lines, the
    same restriction the reference tokenizer enforces via its ``new_line``
    flag (``config.h:97-140``).
    """
    i, n = 0, len(text)
    buf: List[str] = []
    pending_newline = False
    out: List[object] = []  # emit queue drained by the outer loop

    def emit(tok: object) -> None:
        nonlocal pending_newline
        if pending_newline:
            out.append(_NEWLINE)
            pending_newline = False
        out.append(tok)

    def flush() -> None:
        nonlocal buf
        if buf:
            emit("".join(buf))
            buf = []

    while i < n:
        ch = text[i]
        if ch == "#":
            # comment to end of line
            while i < n and text[i] not in "\r\n":
                i += 1
            continue
        if ch in "\r\n":
            flush()
            pending_newline = True
            i += 1
        elif ch in " \t":
            flush()
            i += 1
        elif ch == "=":
            flush()
            emit(_EQ)
            i += 1
        elif ch in "\"'":
            if buf:
                raise ConfigError("string literal may not directly follow a token")
            quote = ch
            i += 1
            s: List[str] = []
            while True:
                if i >= n:
                    raise ConfigError("unterminated string literal")
                c = text[i]
                if c == "\\":
                    if i + 1 >= n:
                        raise ConfigError("unterminated string escape")
                    s.append(text[i + 1])
                    i += 2
                    continue
                if c == quote:
                    i += 1
                    break
                if quote == '"' and c in "\r\n":
                    raise ConfigError("unterminated single-line string")
                s.append(c)
                i += 1
            emit("".join(s))
        else:
            buf.append(ch)
            i += 1
        yield from out
        out.clear()
    flush()
    yield from out


def parse_pairs(text: str) -> List[ConfigEntry]:
    """Parse config text into an ordered list of ``(name, value)`` pairs."""
    out: List[ConfigEntry] = []
    toks = _tokenize(text)
    # stream assembler: NAME '=' VALUE with no newline between them
    name = None          # current pending name token
    have_eq = False
    for tok in toks:
        if tok is _NEWLINE:
            if name is not None and not have_eq:
                raise ConfigError(f"dangling token {name!r}: expected '=' on same line")
            if have_eq:
                raise ConfigError(f"missing value for {name!r}")
            continue
        if tok is _EQ:
            if name is None:
                raise ConfigError("'=' without a preceding name")
            if have_eq:
                raise ConfigError(f"duplicate '=' after {name!r}")
            have_eq = True
            continue
        # plain token
        if name is None:
            name = tok
        elif have_eq:
            out.append((name, tok))
            name, have_eq = None, False
        else:
            raise ConfigError(f"expected '=' after {name!r}, got {tok!r}")
    if name is not None:
        raise ConfigError(f"dangling token {name!r} at end of config")
    return out


def parse_file(path: str) -> List[ConfigEntry]:
    with open(path, "r", encoding="utf-8") as f:
        return parse_pairs(f.read())


def parse_cli_overrides(args: Sequence[str]) -> List[ConfigEntry]:
    """``name=value`` command-line overrides, appended after the file entries.

    Parity: ``/root/reference/src/cxxnet_main.cpp:67-72``.
    """
    out: List[ConfigEntry] = []
    for a in args:
        if "=" in a:
            name, val = a.split("=", 1)
            if name and val:
                out.append((name.strip(), val.strip()))
    return out


@dataclasses.dataclass
class IteratorSection:
    """One ``data``/``eval``/``pred`` iterator section from the config."""

    kind: str                  # 'data' | 'eval' | 'pred'
    tag: str                   # eval name, or pred output filename
    entries: List[ConfigEntry]


@dataclasses.dataclass
class SplitConfig:
    """Config split into iterator sections and the remaining global stream."""

    global_entries: List[ConfigEntry]
    sections: List[IteratorSection]

    def find(self, kind: str) -> List[IteratorSection]:
        return [s for s in self.sections if s.kind == kind]


def split_sections(cfg: Sequence[ConfigEntry]) -> SplitConfig:
    """Split the ordered stream into iterator sections and global entries.

    Matches the flag machine of the reference driver
    (``cxxnet_main.cpp:214-254``): ``data``/``eval``/``pred`` set the mode,
    ``iter = end`` closes the open section, everything outside sections is a
    global entry (including the whole netconfig block).
    """
    global_entries: List[ConfigEntry] = []
    sections: List[IteratorSection] = []
    mode = 0  # 0 global, else open section
    tag = ""
    cur: List[ConfigEntry] = []
    kind_of = {1: "data", 2: "eval", 3: "pred"}
    for name, val in cfg:
        if name in ("data", "eval", "pred"):
            if mode != 0:
                raise ConfigError(
                    f"'{name} = {val}' opens a new iterator section while the "
                    f"previous '{kind_of[mode]}' section is missing 'iter = end'"
                )
            mode = {"data": 1, "eval": 2, "pred": 3}[name]
            tag, cur = val, []
            continue
        if name == "iter" and val == "end":
            if mode == 0:
                raise ConfigError("'iter = end' outside an iterator section")
            sections.append(IteratorSection(kind_of[mode], tag, cur))
            mode, tag, cur = 0, "", []
            continue
        if mode == 0:
            global_entries.append((name, val))
        else:
            cur.append((name, val))
    if mode != 0:
        raise ConfigError("iterator section not closed by 'iter = end'")
    return SplitConfig(global_entries, sections)


def cfg_get(cfg: Sequence[ConfigEntry], name: str, default: str | None = None) -> str | None:
    """Last-wins lookup of a key in an ordered entry stream."""
    out = default
    for n, v in cfg:
        if n == name:
            out = v
    return out


@dataclasses.dataclass
class TenantSection:
    """One ``[tenant:<name>]`` block: ``tenant = <name>`` .. ``tenant = end``.

    Everything between the opener and the closer belongs to the tenant —
    its ``model_dir``, feedback-log location, and any per-tenant
    overrides of the loop/publish/iterator keys (applied LAST over the
    shared globals, so the usual last-entry-wins rule resolves them).
    """

    name: str
    entries: List[ConfigEntry]


def split_tenant_sections(
    cfg: Sequence[ConfigEntry],
) -> Tuple[List[ConfigEntry], List[TenantSection]]:
    """Strip ``tenant = <name>`` .. ``tenant = end`` blocks out of the
    ordered stream; returns ``(remaining_entries, tenant_sections)``.

    The remaining stream is what the shared planes (netconfig, data/eval
    sections, serve keys) parse from; each tenant's effective config is
    ``remaining + section.entries`` (``loop/tenant.py``).  Iterator and
    netconfig sections may not open inside a tenant block — a tenant
    customizes the shared sections by overriding their keys (e.g.
    ``seed_data``), it does not define new ones."""
    rest: List[ConfigEntry] = []
    tenants: List[TenantSection] = []
    cur: List[ConfigEntry] | None = None
    cur_name = ""
    seen = set()
    for name, val in cfg:
        if name == "tenant":
            if val == "end":
                if cur is None:
                    raise ConfigError("'tenant = end' outside a tenant section")
                tenants.append(TenantSection(cur_name, cur))
                cur, cur_name = None, ""
            else:
                if cur is not None:
                    raise ConfigError(
                        f"'tenant = {val}' opens a new tenant section while "
                        f"[tenant:{cur_name}] is missing 'tenant = end'")
                if not val or val in seen:
                    raise ConfigError(
                        f"tenant name {val!r} is empty or duplicated")
                seen.add(val)
                cur, cur_name = [], val
            continue
        if cur is not None:
            if name in ("data", "eval", "pred", "netconfig"):
                raise ConfigError(
                    f"'{name} = {val}' inside [tenant:{cur_name}]: tenants "
                    "override the shared sections' keys, they do not open "
                    "their own sections")
            cur.append((name, val))
        else:
            rest.append((name, val))
    if cur is not None:
        raise ConfigError(
            f"tenant section [tenant:{cur_name}] not closed by 'tenant = end'")
    return rest, tenants
