"""NetTrainer: the INetTrainer equivalent, jit-compiled end to end.

Parity: ``INetTrainer`` (``/root/reference/src/nnet/nnet.h:18-92``) and
``CXXNetThreadTrainer`` (``/root/reference/src/nnet/nnet_impl-inl.hpp``):
``SetParam / InitModel / SaveModel / LoadModel / CopyModelFrom /
StartRound / Update(batch) / Evaluate / Predict / ExtractFeature /
SetWeight / GetWeight``.

TPU-first architecture: where the reference spawns one pthread + CUDA
stream per GPU and aggregates gradients through the mshadow-ps parameter
server, here the whole train step — forward, backward, gradient
accumulation, updater math — is ONE jitted function.  Data parallelism is
sharding the batch over a ``jax.sharding.Mesh`` (``parallel/``): XLA
inserts the ICI all-reduce that replaces push/pull, and its latency-hiding
scheduler overlaps it with backprop the way the reference's per-layer
AsyncUpdater priorities did.

Semantics preserved:
* ``update_period`` gradient accumulation with the reference's counters:
  ``epoch_counter`` (number of applied updates — the updaters' schedule
  clock) advances once per ``update_period`` micro-batches.
* checkpoint = net structure + epoch counter + weights; updater state is
  NOT saved by default (reference behavior — momentum restarts on
  resume); ``save_ustate = 1`` opts into exact resume (momentum/adam
  moments + the training RNG key ride along in the blob).
* ``CopyModelFrom`` copies name-matched layers only, resets the epoch.
* prediction output is argmax (multi-column) or the raw scalar.
"""

from __future__ import annotations

import io as _io
import json
import struct
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..io.data import DataBatch
from ..obs import device as obs_device
from ..parallel import MeshPlan, make_mesh
from ..parallel.distributed import fetch_array, fetch_local_rows
from ..updater import Updater, create_updater
from ..utils import checkpoint as ckpt
from ..utils.checkpoint import MODEL_MAGIC, DivergenceError  # noqa: F401
from ..utils.metric import MetricSet
from .graph import NetGraph
from .net import FunctionalNet


class NetTrainer:
    def __init__(self) -> None:
        self.cfg: List[Tuple[str, str]] = []
        self.net: Optional[FunctionalNet] = None
        self.graph: Optional[NetGraph] = None
        self.params = None
        self.ustates = None
        self.updaters: Dict[Tuple[str, str], Updater] = {}
        self.epoch_counter = 0
        self.sample_counter = 0
        self.round = 0
        self.batch_size = 0
        self.update_period = 1
        self.eval_train = 1
        self.silent = 0
        self.seed = 0
        self.dev = "tpu"
        self.model_parallel = 1
        self.update_on_server = 0
        self.zero = 0
        self.det_reduce = 0
        # async data-parallel (parallel/async_ps, doc/parallel.md
        # "Async data-parallel"): per-group overlapped gradient
        # exchange + bounded-staleness updates
        self.async_overlap = 0
        self.async_groups = 0       # 0 = auto parameter-count buckets
        self.staleness = 0          # bounded staleness (aggregates)
        self.async_resync_period = 1  # hard re-sync barrier period
        self._async = None          # lazily built AsyncStepper
        self.save_ustate = 0
        self.divergence_policy = ""  # "" off | "abort" | "rollback"
        self.inject_nan_step = -1  # fault-injection hook (tests only)
        # finite loss-spike gate (integrity plane, doc/robustness.md):
        # a finite loss > ratio * rolling-median trips DivergenceError
        self.divergence_loss_ratio = 0.0   # 0 = off; else must be > 1
        self.inject_spike_step = -1   # fault-injection hook (tests only)
        self.inject_shadow_mismatch = 0  # one-shot shadow-audit hook
        self._loss_window: List[float] = []  # recent finite losses
        # quantized inference (doc/performance.md "Quantized inference"):
        # quant_scheme is set when the params pytree holds reduced-
        # precision kernels (int8 codes + scales, or bf16 casts) — the
        # trainer is then INFERENCE-ONLY; _quant_requested records the
        # conf's `quant` key, applied after init/load when the loaded
        # artifact is not already quantized
        self.quant_scheme = ""
        self.quant_plan = None
        self._quant_requested = ""
        self.mesh_plan: Optional[MeshPlan] = None
        self.aux = {}  # non-gradient layer state (BN running stats)
        self.metric = MetricSet()
        self.train_metric = MetricSet()
        self._grad_accum = None
        self._rng_key = None
        self._jit_cache: Dict[tuple, object] = {}
        self._staged = None  # double-buffered device feed (stage_batch)

    # ------------------------------------------------------------------
    def set_param(self, name: str, val: str) -> None:
        if name == "batch_size":
            self.batch_size = int(val)
        elif name == "update_period":
            self.update_period = int(val)
        elif name == "eval_train":
            self.eval_train = int(val)
        elif name == "silent":
            self.silent = int(val)
        elif name == "seed":
            self.seed = int(val)
        elif name == "dev":
            self.dev = val
        elif name == "model_parallel":
            self.model_parallel = int(val)
        elif name == "update_on_server":
            # reference: SGD runs on the PS (nnet_ps_server.cpp); here the
            # optimizer state is ZeRO-1-sharded over the data axis instead
            self.update_on_server = int(val)
        elif name == "det_reduce":
            # pin the cross-replica gradient-reduction ORDER (elastic
            # pods, doc/parallel.md): the fused step's reduction is
            # re-expressed with shard_map — per-shard partial gradients
            # all-gathered and folded in fixed shard order — so the
            # summed bits depend only on the data-axis size, never on
            # the collectives implementation or process layout
            if int(val) not in (0, 1):
                raise ValueError(f"det_reduce={val}: must be 0 or 1")
            self.det_reduce = int(val)
        elif name == "async_overlap":
            # overlapped per-group gradient exchange (the mshadow-ps
            # async heritage, parallel/async_ps): the fused step splits
            # into per-shard backward + one async collective per
            # gradient-exchange group, applies overlapping exchanges
            if int(val) not in (0, 1):
                raise ValueError(f"async_overlap={val}: must be 0 or 1")
            self.async_overlap = int(val)
        elif name == "async_groups":
            if int(val) < 0:
                raise ValueError(
                    f"async_groups={val}: must be >= 0 (0 = auto)")
            self.async_groups = int(val)
        elif name == "staleness":
            # bounded staleness: slow replicas apply k-step-old reduced
            # aggregates instead of blocking; 0 = synchronous semantics
            # (bitwise — the parity suite pins it)
            if int(val) < 0:
                raise ValueError(f"staleness={val}: must be >= 0")
            self.staleness = int(val)
        elif name == "async_resync_period":
            if int(val) < 1:
                raise ValueError(
                    f"async_resync_period={val}: must be >= 1")
            self.async_resync_period = int(val)
        elif name == "compile_cache_dir":
            # persistent XLA compilation cache: restarts/reloads reuse
            # compiled programs instead of re-jitting (utils/compile_cache)
            from ..utils import compile_cache

            compile_cache.enable(val, silent=bool(self.silent))
        elif name == "save_ustate":
            # opt-in exact resume: checkpoint updater state (momentum /
            # adam moments) too.  Default 0 keeps reference parity —
            # "Updater state is NOT checkpointed; resume restarts
            # momentum from zero" (SURVEY §5 checkpoint notes)
            self.save_ustate = int(val)
        elif name == "divergence_policy":
            # NaN/Inf loss guard: "" disables (no per-step host sync),
            # abort|rollback enable the check; the response lives in the
            # task driver (cli.py) which catches DivergenceError
            if val not in ("", "off", "abort", "rollback"):
                raise ValueError(
                    f"divergence_policy={val!r}: must be abort or rollback"
                )
            self.divergence_policy = "" if val == "off" else val
        elif name == "inject_nan_step":
            # fault-injection harness: treat the loss at this epoch as
            # NaN (one transient blow-up) so recovery paths are testable
            self.inject_nan_step = int(val)
        elif name == "divergence_loss_ratio":
            # finite loss-spike gate (doc/robustness.md): with
            # divergence_policy set, a FINITE loss exceeding
            # ratio * rolling-median of recent losses trips the same
            # DivergenceError path NaN does — the PR-13 lesson that a
            # blow-up can stay finite for many rounds.  0 disables.
            r = float(val)
            if r and r <= 1.0:
                raise ValueError(
                    f"divergence_loss_ratio={val}: must be > 1 "
                    "(or 0 to disable)")
            self.divergence_loss_ratio = r
        elif name == "inject_spike_step":
            # fault-injection harness: scale the loss at this epoch to
            # a finite spike (one-shot), testing the loss-ratio gate
            self.inject_spike_step = int(val)
        elif name == "inject_shadow_mismatch":
            # fault-injection harness: perturb the shadow executable's
            # next comparison (one-shot), testing the shadow-audit path
            self.inject_shadow_mismatch = int(val)
        elif name == "kernel_lib":
            # on-chip kernel library selector (ops/kernels/): validate
            # here so a typo fails at conf parse, then flow the value to
            # the net via cfg -> graph defcfg like every other key
            from ..ops import kernels as _klib

            _klib.parse_mode(val)
        elif name == "quant":
            # inference-time weight precision: "" / 0 off, int8 (per-
            # channel scales + bf16 fallback) or bf16 (straight cast).
            # A pre-exported .quant.model artifact wins over this key;
            # on a plain checkpoint the quantization happens at load,
            # UNGATED (use task=export_quant for the gated artifact).
            if val in ("", "0", "off", "none"):
                self._quant_requested = ""
            elif val in ("int8", "bf16"):
                self._quant_requested = val
            else:
                raise ValueError(
                    f"quant={val!r}: supported schemes are int8 and "
                    "bf16 (0/off disables)")
        elif name in ("zero", "fsdp", "shard_weight_update"):
            # zero = 1: optimizer state sharded over the data axis
            # (update_on_server's modern spelling); zero = 3 / fsdp = 1:
            # params themselves sharded too (MeshPlan.fsdp_sharding).
            # shard_weight_update = 1 is the conf-level name for the
            # ZeRO-1 cross-replica weight-update sharding (arXiv
            # 2004.13336): reduce-scatter gradients, each replica
            # updates its 1/N shard, gather the new weights.
            # ZeRO-2 has no distinct GSPMD expression here: gradients
            # are transient inside the fused step, so 2 would silently
            # equal 1 — reject it rather than mislead.
            if name == "fsdp":
                if int(val) not in (0, 1):
                    raise ValueError(f"fsdp={val}: must be 0 or 1")
                z = 3 if int(val) else 0
            elif name == "shard_weight_update":
                if int(val) not in (0, 1):
                    raise ValueError(
                        f"shard_weight_update={val}: must be 0 or 1")
                z = 1 if int(val) else 0
            else:
                z = int(val)
            if z not in (0, 1, 3):
                raise ValueError(
                    f"{name}={val}: supported levels are 0, 1 "
                    "(state sharding) and 3 (FSDP param sharding)"
                )
            self.zero = z
        if self.metric.try_add_from_config(name, val):
            self.train_metric.try_add_from_config(name, val)
        self.cfg.append((name, val))

    def set_params(self, entries: Sequence[Tuple[str, str]]) -> None:
        for n, v in entries:
            if v == "default":
                continue
            self.set_param(n, v)

    # ------------------------------------------------------------------
    def _build_net(self, graph: Optional[NetGraph] = None) -> None:
        if graph is None:
            graph = NetGraph()
        graph.configure(self.cfg)
        self.graph = graph
        self._jit_cache.clear()  # drop closures over any previous net/mesh
        self._staged = None      # staged transfers belong to the old net
        self._async = None       # async programs close over the old net
        self.net = FunctionalNet(graph)
        if self.net.batch_size:
            self.batch_size = self.net.batch_size
        else:
            self.net.batch_size = self.batch_size
        self.update_period = max(self.update_period, self.net.update_period)
        self.net.update_period = self.update_period

    def _build_updaters(self) -> None:
        assert self.net is not None and self.graph is not None
        self.updaters = {}
        ustates = {}
        for i, spec in enumerate(self.graph.layers):
            key = self.net.param_key[i]
            if spec.type_name == "shared" or key not in self.params:
                continue
            ustates[key] = {}
            for tag, w in self.params[key].items():
                up = create_updater(self.graph.updater_type, tag)
                for n, v in self.graph.defcfg:
                    up.set_param(n, v)
                for n, v in self.graph.layercfg[i]:
                    up.set_param(n, v)
                self.updaters[(key, tag)] = up
                ustates[key][tag] = up.init_state(w)
        self.ustates = ustates

    def _bind_mesh_to_layers(self) -> None:
        """Hand the mesh plan to layers that run their own collectives
        (ring attention's shard_map needs the Mesh object)."""
        for lay in self.net.layer_objs:
            if hasattr(lay, "bind_mesh"):
                lay.bind_mesh(self.mesh_plan)

    def _check_metric_nodes(self) -> None:
        """Fail fast on a bad ``metric[field,node]`` node name — the
        reference checks at InitModel (nnet_impl-inl.hpp:369-370), not at
        the first evaluation."""
        for mset in (self.metric, self.train_metric):
            for node in mset.nodes:
                if node is None:
                    continue
                try:
                    self.graph.node_index_of(node)
                except (KeyError, ValueError) as e:
                    raise ValueError(
                        f"metric[...,{node}]: cannot find node name "
                        f"{node!r} in the net graph"
                    ) from e

    def init_model(self) -> None:
        self._build_net()
        self._check_metric_nodes()
        self._build_mesh()
        self._bind_mesh_to_layers()
        self._rng_key = jax.random.PRNGKey(self.seed)
        self._rng_key, sub = jax.random.split(self._rng_key)
        self.params = self.net.init_params(sub, self.batch_size)
        self.aux = self.net.init_aux(self.batch_size)
        self._validate_det_reduce()
        self._build_updaters()
        self.epoch_counter = 0
        self.sample_counter = 0
        self._grad_accum = None
        self._maybe_quantize()
        self._place_state()

    def _build_mesh(self) -> None:
        """dev=tpu:0-3 → ('data','model') mesh; the mshadow-ps replacement."""
        self.mesh_plan = make_mesh(self.dev, self.model_parallel)
        if self.batch_size:
            self.mesh_plan.check_batch(self.batch_size)
        if self.net is not None:
            # bind the platform the programs will actually run on (NOT
            # the process default backend — dev=cpu on a TPU host must
            # read as cpu): auto branch-embed keys on it
            try:
                devs = self.mesh_plan.mesh.devices.reshape(-1)
                self.net.exec_backend = str(devs[0].platform)
            except Exception:  # noqa: BLE001 - fall back to the probe
                pass

    def _sh(self):
        """(replicated, data-sharded, per-extra) shardings for the mesh."""
        plan = self.mesh_plan
        if plan is None:
            self._build_mesh()
            plan = self.mesh_plan
        rep, dsh = plan.replicated(), plan.data_sharding()
        return rep, dsh, (dsh,) * self._n_extras()

    def _param_sh(self):
        """Sharding pytrees for (params, ustates): tensor-parallel weight
        placement over the mesh's model axis (pure DP → all replicated);
        ``zero = 1`` (or the reference-named ``update_on_server = 1``)
        additionally ZeRO-1-shards the updater state over the data axis;
        ``zero = 3`` / ``fsdp = 1`` shards the params themselves
        (MeshPlan.fsdp_sharding) — GSPMD inserts the per-layer
        all-gathers and gradient reduce-scatters."""
        plan = self.mesh_plan
        spec = lambda v: plan.param_sharding(np.shape(v))  # noqa: E731
        sspec = lambda v: plan.state_sharding(np.shape(v))  # noqa: E731
        fspec = lambda v: plan.fsdp_sharding(np.shape(v))  # noqa: E731
        if self.zero >= 3:
            psh = jax.tree_util.tree_map(fspec, self.params)
        else:
            psh = jax.tree_util.tree_map(spec, self.params)
        if self.update_on_server or self.zero >= 1:
            ush = jax.tree_util.tree_map(sspec, self.ustates)
        else:
            ush = jax.tree_util.tree_map(spec, self.ustates)
        return psh, ush

    def _place_state(self) -> None:
        """Explicitly place params / updater state / aux onto their mesh
        shardings (one ``jax.device_put`` per pytree).

        Called at the end of ``init_model`` / ``load_model`` /
        ``copy_model_from`` so the train state LIVES in its SPMD layout
        from step 0 rather than only after the first donated step
        resharded it: ZeRO-sharded runs get their ~1/N per-device
        params+state footprint immediately (the memory headroom is
        available for the first compile, which is when XLA sizes its
        temporary buffers), donation in the fused step is alias-clean
        (inputs already match ``in_shardings`` — no hidden copy), and a
        checkpoint written on one mesh re-shards onto the CURRENT mesh
        at load (resume on a different device count just works).
        Placement only — bitwise no-op on the training math."""
        if self.params is None or self.mesh_plan is None:
            self._export_state_bytes()
            return
        if self.mesh_plan.n_devices > 1:
            psh, ush = self._param_sh()
            self.params = jax.device_put(self.params, psh)
            if self.ustates:
                self.ustates = jax.device_put(self.ustates, ush)
            if self.aux:
                rep = self.mesh_plan.replicated()
                self.aux = jax.device_put(
                    self.aux,
                    jax.tree_util.tree_map(lambda _: rep, self.aux),
                )
        self._export_state_bytes()

    def state_shard_bytes(self):
        """Per-device addressable bytes of params + updater state, plus
        the replicated-equivalent total.

        Returns ``(per_device, total)`` where ``per_device`` maps
        ``"platform:id"`` to the bytes of train state RESIDENT on that
        device and ``total`` is what one full replica costs — the
        denominator of the ZeRO memory win (per-device ≈ total/N when
        every dim shards; unshardable leaves keep it slightly above).
        """
        per_device: Dict[str, float] = {}
        total = 0
        for tree in (self.params, self.ustates):
            for leaf in jax.tree_util.tree_leaves(tree or {}):
                nbytes = getattr(leaf, "nbytes", None)
                if nbytes is None:
                    nbytes = int(np.asarray(leaf).nbytes)
                total += int(nbytes)
                shards = getattr(leaf, "addressable_shards", None)
                if shards:
                    for s in shards:
                        dev = f"{s.device.platform}:{s.device.id}"
                        per_device[dev] = (
                            per_device.get(dev, 0) + int(s.data.nbytes)
                        )
                else:
                    per_device["host:0"] = (
                        per_device.get("host:0", 0) + int(nbytes)
                    )
        return per_device, total

    def _export_state_bytes(self) -> None:
        """Publish ``train_state_shard_bytes{device}`` (and the
        replicated-total gauge) so the ZeRO memory win is observable
        next to ``xla_device_memory_bytes`` — fail-open like the rest
        of the device plane."""
        try:
            per_device, total = self.state_shard_bytes()
            obs_device.set_train_state_bytes(per_device, total)
        except Exception:  # noqa: BLE001 - telemetry must never raise
            pass

    # ------------------------------------------------------------------
    # jitted step functions (built lazily, cached per (train, accum) kind)
    def _n_extras(self) -> int:
        return self.graph.extra_data_num if self.graph else 0

    @staticmethod
    def _apply_updates(updaters, params, ustates, grads, epoch,
                       gspec=None, kernels=None):
        """Per-tensor updater math over the param pytree (trace-time loop).

        ``gspec`` (shape → NamedSharding, set for ZeRO runs on a
        non-trivial mesh) pins each gradient to the updater state's
        data-axis sharding before the update math: the cross-replica
        gradient sum then lands sharded (reduce-scatter, or all-reduce
        + local slice where the backend lacks the fused pattern — this
        jaxlib's CPU partitioner does the latter), the updater applies
        shard-locally (each replica updates only its 1/N slice —
        momentum/Adam moments never materialize whole), and the
        program's replicated ``out_shardings`` on the new weights
        becomes the trailing all-gather — the arXiv 2004.13336
        weight-update-sharding dataflow, expressed purely as sharding
        annotations.  Placement only; the parity suites pin the math.
        """
        new_p = {}
        new_s = {}
        for key, tags in params.items():
            new_p[key] = {}
            new_s[key] = {}
            for tag, w in tags.items():
                up = updaters[(key, tag)]
                g = grads[key][tag]
                if gspec is not None:
                    g = jax.lax.with_sharding_constraint(
                        g, gspec(np.shape(w)))
                if (kernels is not None
                        and kernels.active("zero_update", w=w,
                                           updater=up)):
                    # the fused Pallas update step (ops/kernels/
                    # update_step.py): one VMEM pass over (w, g, m)
                    # instead of the op-by-op elementwise chain.  Same
                    # schedule spelling as SGDUpdater.apply; bit-equal
                    # to the stock lowering (tests/test_kernels.py).
                    from ..ops.kernels import update_step as _kup

                    p = up.param
                    w2, m2 = _kup.sgd_update(
                        w, g, ustates[key][tag]["m"],
                        p.learning_rate(epoch).astype(w.dtype),
                        p.momentum_at(epoch).astype(w.dtype),
                        wd=p.wd, clip=p.clip_gradient,
                        interpret=kernels.interpret)
                    new_p[key][tag] = w2
                    new_s[key][tag] = {"m": m2}
                    continue
                w2, s2 = up.apply(w, g, ustates[key][tag], epoch)
                new_p[key][tag] = w2
                new_s[key][tag] = s2
        return new_p, new_s

    def _update_kernels(self):
        """The kernel library's bound selector for the UPDATE side of
        the step programs (``zero_update``), or None.  Gated to
        single-device meshes: a Pallas call inside a multi-device GSPMD
        program has no partitioning rule in this jaxlib, and the ZeRO
        sharded-update path relies exactly on those annotations — the
        stock elementwise chain stays the spelling there."""
        if self.net is None:
            return None
        plan = self.mesh_plan
        if plan is not None and plan.n_devices > 1:
            return None
        kb = self.net.bound_kernels()
        # bind only when the selector can ever fire (avoids a dead
        # closure arg re-tracing the step on verdict edits)
        return kb if kb.selector.mode != "off" else None

    def _grad_spec(self):
        """The gradient sharding hook for :meth:`_apply_updates`: the
        state sharding on ZeRO runs over a real mesh, else None (a
        1-device mesh must stay annotation-free — see ``_jit``)."""
        plan = self.mesh_plan
        if (plan is None or plan.n_devices <= 1
                or not (self.update_on_server or self.zero >= 1)):
            return None
        return lambda shape: plan.state_sharding(shape)

    def _det_active(self) -> bool:
        """Is the pinned-order (shard_map) reduction in effect?  On a
        1-device mesh there is no cross-replica reduction to pin, so
        the key is a documented no-op there."""
        return bool(self.det_reduce and self.mesh_plan is not None
                    and self.mesh_plan.n_devices > 1)

    def _async_active(self) -> bool:
        """Is the overlapped per-group exchange (``async_overlap = 1``)
        in effect?  Same 1-device no-op contract as ``det_reduce`` —
        with no cross-replica exchange there is nothing to overlap, and
        ``staleness`` has no collective to absorb."""
        return bool(self.async_overlap and self.mesh_plan is not None
                    and self.mesh_plan.n_devices > 1
                    and not self.quant_scheme)

    def _row_separable_problems(self) -> list:
        """Constraints shared by every shard_map step re-expression
        (``det_reduce`` and ``async_overlap``): the forward runs per
        data shard, so only row-separable math qualifies — pure data
        parallelism (no model axis), replicated state (no ZeRO
        annotations inside the manual region), no extra-data nodes, no
        cross-batch aux state (BN running stats would silently become
        per-shard statistics), the fused single-update path, and no
        stochastic layers (the replicated per-shard rng would correlate
        noise masks across shards)."""
        problems = []
        if self.mesh_plan.n_model != 1:
            problems.append(f"model_parallel={self.mesh_plan.n_model} "
                            "(needs pure data parallelism)")
        if self.zero or self.update_on_server:
            problems.append(f"zero={self.zero} (needs replicated state)")
        if self.update_period != 1:
            problems.append(f"update_period={self.update_period} "
                            "(needs the fused single-update step)")
        if self._n_extras():
            problems.append("extra data nodes")
        if self.aux:
            problems.append("aux (batch-norm style) layer state — "
                            "per-shard batch statistics would diverge")
        stochastic = sorted({
            spec.type_name for spec in self.graph.layers
            if spec.type_name in ("dropout", "insanity",
                                  "insanity_max_pooling")
        })
        if stochastic:
            # the shard_map region replicates the rng across shards, so
            # every shard would draw the SAME noise pattern for its
            # rows — silently different stochasticity than the global
            # draw of the default step, varying with mesh size
            problems.append(
                f"stochastic layers {stochastic} (per-shard rng would "
                "correlate noise masks across data shards)")
        return problems

    def _validate_det_reduce(self) -> None:
        """``det_reduce = 1`` constraints, checked at model build time
        (see :meth:`_row_separable_problems`) — and the async-overlap
        twin, which shares the identical shard_map contract."""
        if self._det_active():
            problems = self._row_separable_problems()
            if problems:
                raise ValueError(
                    "det_reduce=1 is incompatible with: "
                    + "; ".join(problems)
                    + " (doc/parallel.md 'Determinism contract')")
        self._validate_async()

    def _validate_async(self) -> None:
        """``async_overlap = 1`` constraints (doc/parallel.md "Async
        data-parallel"): the same row-separable shard_map contract as
        ``det_reduce``, plus the async-only key coherence checks."""
        if self.staleness and not self.async_overlap:
            raise ValueError(
                f"staleness={self.staleness} requires async_overlap=1 "
                "(the synchronous step has no aggregate buffer to "
                "delay; doc/parallel.md 'Async data-parallel')")
        if not self._async_active():
            return
        problems = self._row_separable_problems()
        if problems:
            raise ValueError(
                "async_overlap=1 is incompatible with: "
                + "; ".join(problems)
                + " (doc/parallel.md 'Async data-parallel')")

    def _shard_grad_fn(self):
        """The per-shard summed-loss gradient closure: grad of THIS
        data shard's rows' summed loss, plus the per-shard loss and
        out-node rows.  SHARED by the ``det_reduce`` fold step below
        and the async per-group exchange (``parallel/async_ps``) —
        the ``staleness = 0`` bitwise-parity contract depends on both
        re-expressions tracing the IDENTICAL backward, so there is
        exactly one copy of it."""
        net = self.net
        out_idx = net.out_node_index()

        def per_shard_grad(params, data, labels, mask, rng, epoch):
            def sum_loss(p):
                nodes, loss, _ = net.forward(
                    p, data, labels=labels, extras=(), train=True,
                    rng=rng, step=epoch, aux={}, return_aux=True,
                    sample_mask=mask,
                )
                return loss, nodes[out_idx].astype(jnp.float32)

            (loss, out), g = jax.value_and_grad(
                sum_loss, has_aux=True)(params)
            return g, loss, out

        return per_shard_grad

    def _det_grad_fn(self):
        """The shard_map re-expression of the step's cross-replica
        gradient reduction (SNIPPETS.md [3] is the pattern): each data
        shard computes the gradient of ITS rows' summed loss, the
        partials are all-gathered over the ``data`` axis, and the
        global gradient is an explicitly ORDERED fold over shard index
        — ``((g0 + g1) + g2) + ...`` unrolled at trace time — so the
        reduction order (and therefore every result bit) is pinned by
        the data-axis size alone, independent of the collectives
        implementation, process layout, or partitioner mood.  The loss
        layers already sum (not average) over rows, so the fold IS the
        global gradient with no renormalization."""
        plan = self.mesh_plan
        n = plan.n_data
        per_shard_grad = self._shard_grad_fn()
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def per_shard(params, data, labels, mask, rng, epoch):
            g, loss, out = per_shard_grad(
                params, data, labels, mask, rng, epoch)

            def fold(x):
                parts = jax.lax.all_gather(x, "data")
                acc = parts[0]
                for i in range(1, n):
                    acc = acc + parts[i]
                return acc

            grads = jax.tree_util.tree_map(fold, g)
            return grads, fold(loss), out

        return shard_map(
            per_shard, mesh=plan.mesh,
            in_specs=(P(), P("data"), P("data"), P("data"), P(), P()),
            out_specs=(P(), P(), P("data")),
            check_rep=False,
        )

    def _loss_and_out(self, params, aux, data, labels, mask, rng, epoch,
                      extras):
        """(loss, (out_node, new_aux)) with train=True — fused/fwd_train."""
        net = self.net
        nodes, loss, new_aux = net.forward(
            params, data, labels=labels, extras=extras,
            train=True, rng=rng, step=epoch, aux=aux, return_aux=True,
            sample_mask=mask,
        )
        # metrics consume the out node on host: always hand back f32
        return loss, (nodes[net.out_node_index()].astype(jnp.float32), new_aux)

    def _jit(self, fn, in_shardings, out_shardings, donate_argnums=(),
             kind="program", data_arg=None):
        """jit with shardings only when the mesh is non-trivial.

        On a single-device mesh the NamedSharding annotations are pure
        constraint noise — measured on the v5e (transformer LM b8
        T=2048): sharding-annotated scan steps ran ~30x slower than the
        same program without annotations (layout constraints defeat
        XLA's scan buffer aliasing/fusion), so 1-device jits drop them.

        Every program is wrapped for device telemetry
        (``obs/device.py``): the first call per argument-shape
        signature records the program's estimated FLOPs/bytes and
        cold-call time as ``xla_program_*{kind,bucket}``, where
        ``bucket`` is the leading dim of argument ``data_arg``.  A
        straight pass-through when ``device_telemetry = 0``.
        """
        plan = self.mesh_plan
        if plan is not None and plan.n_devices > 1:
            jf = jax.jit(fn, in_shardings=in_shardings,
                         out_shardings=out_shardings,
                         donate_argnums=donate_argnums)
        else:
            jf = jax.jit(fn, donate_argnums=donate_argnums)
        return obs_device.instrument(jf, kind, data_arg=data_arg)

    def _fused_step_fn(self):
        """fwd + bwd + updater math as ONE donated SPMD program.

        Used when ``update_period == 1`` (the common case): XLA sees the
        whole step, fuses update math into backprop epilogues, and
        overlaps the data-parallel gradient all-reduce with backprop —
        the reference needed AsyncUpdater priorities for this
        (``async_updater-inl.hpp:94-127``); here it is the latency-hiding
        scheduler's job.
        """
        if "fused" not in self._jit_cache:
            updaters = dict(self.updaters)
            rep, dsh, ex = self._sh()
            psh, ush = self._param_sh()
            loss_and_out = self._loss_and_out
            apply_updates = self._apply_updates
            gspec = self._grad_spec()
            ukern = self._update_kernels()
            det_grad = self._det_grad_fn() if self._det_active() else None

            def step(params, ustates, aux, data, labels, mask, rng, epoch,
                     extras):
                if det_grad is not None:
                    grads, loss, out = det_grad(params, data, labels,
                                                mask, rng, epoch)
                    new_aux = aux
                else:
                    (loss, (out, new_aux)), grads = jax.value_and_grad(
                        lambda p: loss_and_out(
                            p, aux, data, labels, mask, rng, epoch, extras
                        ),
                        has_aux=True,
                    )(params)
                new_p, new_s = apply_updates(updaters, params, ustates,
                                             grads, epoch, gspec=gspec,
                                             kernels=ukern)
                return new_p, new_s, new_aux, loss, out

            self._jit_cache["fused"] = self._jit(
                step,
                (psh, ush, rep, dsh, dsh, dsh, rep, rep, ex),
                (psh, ush, rep, rep, dsh),
                donate_argnums=(0, 1, 2),
                kind="train_fused", data_arg=3,
            )
        return self._jit_cache["fused"]

    def _scan_step_fn(self, n_steps: int, per_step_data: bool,
                      with_out: bool):
        """K fused train steps as ONE device program (``lax.scan``).

        TPU-first: host dispatch cost is per-*program*, not per-step —
        on a tunneled/remote runtime each execute RPC costs ~100ms+, so
        per-batch dispatch (the reference's ``Update(batch)`` loop,
        ``cxxnet_main.cpp:170-185``) caps throughput regardless of how
        fast the chip is.  Scanning the fused step K times on device
        amortizes dispatch to nothing while keeping identical per-step
        semantics: same updater math, same epoch advance per step, a
        fresh folded RNG per step.

        ``per_step_data=False`` closes over ONE staged batch reused every
        step (synthetic/benchmark mode); otherwise ``xs`` is the
        ``[K, B, ...]`` step-stacked data/labels.
        """
        key = ("scan", n_steps, per_step_data, with_out)
        if key not in self._jit_cache:
            updaters = dict(self.updaters)
            rep, dsh, _ = self._sh()
            sdsh = self.mesh_plan.data_sharding(axis=1)
            psh, ush = self._param_sh()
            loss_and_out = self._loss_and_out
            apply_updates = self._apply_updates
            gspec = self._grad_spec()
            ukern = self._update_kernels()
            det_grad = self._det_grad_fn() if self._det_active() else None

            def one_step(params, ustates, aux, data, labels, rng, epoch):
                if det_grad is not None:
                    mask = jnp.ones((data.shape[0],), jnp.float32)
                    grads, loss, out = det_grad(params, data, labels,
                                                mask, rng, epoch)
                    new_aux = aux
                else:
                    (loss, (out, new_aux)), grads = jax.value_and_grad(
                        lambda p: loss_and_out(
                            p, aux, data, labels, None, rng, epoch, ()
                        ),
                        has_aux=True,
                    )(params)
                new_p, new_s = apply_updates(
                    updaters, params, ustates, grads, epoch, gspec=gspec,
                    kernels=ukern
                )
                return new_p, new_s, new_aux, loss, out

            def step(params, ustates, aux, data, labels, rng, epoch):
                def body(carry, xs):
                    p, s, a, k, e = carry
                    k, sub = jax.random.split(k)
                    d, l = xs if per_step_data else (data, labels)
                    p, s, a, loss, out = one_step(p, s, a, d, l, sub, e)
                    y = (loss, out) if with_out else loss
                    return (p, s, a, k, e + 1), y

                carry, ys = jax.lax.scan(
                    body, (params, ustates, aux, rng, epoch),
                    (data, labels) if per_step_data else None,
                    length=None if per_step_data else n_steps,
                )
                return carry + (ys,)

            data_sh = (sdsh, sdsh) if per_step_data else (dsh, dsh)

            ys_sh = (rep, sdsh) if with_out else rep
            self._jit_cache[key] = self._jit(
                step,
                (psh, ush, rep) + data_sh + (rep, rep),
                (psh, ush, rep, rep, rep, ys_sh),
                donate_argnums=(0, 1, 2),
                kind="train_scan", data_arg=3,
            )
        return self._jit_cache[key]

    def update_scan(self, data, labels, n_steps: Optional[int] = None,
                    sync: bool = True, check_steps: bool = True):
        """Run K train steps in ONE dispatched device program.

        Two modes, both requiring full ``batch_size`` batches and
        ``update_period == 1`` (use :meth:`update` otherwise):

        * ``data`` of shape ``[K, B, ...]`` — each scan step consumes its
          own micro-batch (the staged-chunk training path);
        * ``data`` of shape ``[B, ...]`` with ``n_steps=K`` — the same
          staged batch is reused every step (synthetic benchmark mode).

        Returns the per-step f32 losses, shape ``[K]`` — a host
        ``np.ndarray`` when ``sync=True``, a ``jax.Array`` otherwise.
        With ``sync=False`` (requires ``eval_train`` off — per-step train
        metrics must fetch outputs, which is a full sync, so the combo
        raises instead of silently serializing) the losses come back as a
        device array WITHOUT draining the dispatch queue — the caller
        overlaps host work (decode/augment of the next chunk) with the
        device scan and fences later (``sync()`` or ``np.asarray`` on the
        result).  This is the two-stage ThreadBuffer overlap
        (``iter_thread_imbin_x-inl.hpp:203-354``) in its TPU form: the
        host side of the double buffer is the input pipeline, the device
        side is the in-flight scan program.
        """
        assert self.net is not None, "init_model/load_model first"
        self._check_trainable()
        if not sync and self.eval_train:
            raise ValueError(
                "update_scan(sync=False) cannot overlap with eval_train: "
                "per-step train metrics fetch the scan outputs (a full "
                "sync); pass sync=True or set eval_train = 0"
            )
        if self.update_period != 1:
            raise ValueError("update_scan requires update_period == 1")
        if self._async_active():
            raise ValueError(
                "update_scan is the fused multi-step program — it "
                "cannot interleave the per-group async exchange; use "
                "update() (scan_steps=1) with async_overlap=1"
            )
        if self._n_extras():
            raise ValueError(
                "update_scan does not support extra_data nodes; use update()"
            )
        if self.eval_train and self.train_metric.need_nodes():
            raise ValueError(
                "update_scan cannot score node-bound train metrics "
                "(metric[field,node] with eval_train); use update()"
            )
        in_ndim = len(self.net.input_node_shape(self.batch_size))
        data_arr = data if hasattr(data, "ndim") else np.asarray(data)
        per_step = data_arr.ndim == in_ndim + 1
        if per_step:
            k = int(data_arr.shape[0])
            if n_steps is not None and n_steps != k:
                raise ValueError(
                    f"n_steps={n_steps} != leading data axis {k}"
                )
        else:
            if n_steps is None:
                raise ValueError(
                    "single-batch mode needs n_steps (or pass [K,B,...])"
                )
            k = int(n_steps)
        if jax.process_count() > 1:
            # multi-host: each process feeds its LOCAL [K, B/nproc, ...]
            # stack; the global step-stacks are assembled over the batch
            # axis (the DCN-spanning analog of _to_device).  K must match
            # on every process (the iterators' equal-steps contract) —
            # verified with a cheap allgather so a mismatched tail chunk
            # fails fast instead of deadlocking the SPMD collectives.
            local = self.batch_size // jax.process_count()
            got = data_arr.shape[1] if per_step else data_arr.shape[0]
            if got != local:
                raise ValueError(
                    f"distributed update_scan: each process must feed "
                    f"batch_size/process_count = {local} rows, got {got}"
                )
            if check_steps:
                # fail fast instead of deadlocking; collective, so it
                # costs a cross-host rendezvous per call — a caller whose
                # iterators already guarantee equal K (the CLI's
                # equal-steps contract) passes check_steps=False to keep
                # the async overlap unbroken
                from jax.experimental import multihost_utils

                ks = np.asarray(
                    multihost_utils.process_allgather(
                        np.asarray([k], np.int32)
                    )
                ).reshape(-1)
                if not (ks == k).all():
                    raise ValueError(
                        f"distributed update_scan: step counts differ "
                        f"across processes "
                        f"({sorted(set(int(v) for v in ks))}); every "
                        "process must scan the same K"
                    )
        with_out = bool(self.eval_train)
        fn = self._scan_step_fn(k, per_step, with_out)
        first_epoch = self.epoch_counter
        step0 = jnp.asarray(first_epoch, jnp.int32)
        (self.params, self.ustates, self.aux, self._rng_key, _end, ys) = fn(
            self.params, self.ustates, self.aux,
            self._stage_scan(data, per_step, count_rows=True),
            self._stage_scan(labels, per_step),
            self._next_rng(), step0,
        )
        self.epoch_counter += k
        if self.divergence_policy:
            # guard fetches the per-step losses — with sync=False this
            # serializes the async overlap (the cost of the check)
            self._guard_loss(ys[0] if with_out else ys, first_epoch, k)
        if with_out:
            losses, outs = ys
            outs_np = self._local_scan_rows(outs)
            labels_np = np.asarray(labels)
            if not per_step:
                labels_np = np.broadcast_to(
                    labels_np, (k,) + labels_np.shape
                )
            for i in range(k):
                self.train_metric.add_eval(
                    outs_np[i], labels_np[i], self._label_ranges()
                )
        else:
            losses = ys
            if not sync:
                return losses  # async: device array, queue not drained
        return np.asarray(jax.device_get(losses))

    def _stage_scan(self, x, per_step: bool, count_rows: bool = False):
        """Host stack → device array for update_scan; multi-process runs
        assemble the global array from per-process shards ([K, B, ...]
        step-stacks shard on batch axis 1; one staged batch is exactly
        the _to_device case)."""
        if not per_step:
            return self._to_device(x, count_rows=count_rows)
        if jax.process_count() == 1:
            return jnp.asarray(x)
        return jax.make_array_from_process_local_data(
            self.mesh_plan.data_sharding(axis=1), np.asarray(x)
        )

    @staticmethod
    def _local_scan_rows(outs) -> np.ndarray:
        """[K, B, ...] global scan output → this process's batch rows."""
        if jax.process_count() == 1:
            return np.asarray(jax.device_get(outs))
        return fetch_local_rows(outs, axis=1)

    def _grad_fn(self):
        if "grad" not in self._jit_cache:
            net = self.net

            def loss_fn(params, aux, data, labels, mask, rng, step, extras):
                _, loss, new_aux = net.forward(
                    params, data, labels=labels, extras=extras,
                    train=True, rng=rng, step=step, aux=aux, return_aux=True,
                    sample_mask=mask,
                )
                return loss, new_aux

            rep, dsh, ex = self._sh()
            psh, _ = self._param_sh()
            self._jit_cache["grad"] = self._jit(
                jax.value_and_grad(loss_fn, has_aux=True),
                (psh, rep, dsh, dsh, dsh, rep, rep, ex),
                ((rep, rep), psh),
                kind="train_grad", data_arg=2,
            )
        return self._jit_cache["grad"]

    def _fwd_train_fn(self):
        """value_and_grad + output node (for eval_train metrics)."""
        if "fwd_train" not in self._jit_cache:
            loss_and_out = self._loss_and_out

            def f(params, aux, data, labels, mask, rng, step, extras):
                (loss, (out, new_aux)), grads = jax.value_and_grad(
                    lambda p: loss_and_out(
                        p, aux, data, labels, mask, rng, step, extras
                    ),
                    has_aux=True,
                )(params)
                return loss, out, new_aux, grads

            rep, dsh, ex = self._sh()
            psh, _ = self._param_sh()
            self._jit_cache["fwd_train"] = self._jit(
                f,
                (psh, rep, dsh, dsh, dsh, rep, rep, ex),
                (rep, dsh, rep, psh),
                kind="train_fwd", data_arg=2,
            )
        return self._jit_cache["fwd_train"]

    def _eval_fn(self):
        if "eval" not in self._jit_cache:
            net = self.net
            out_idx = net.out_node_index()

            def f(params, aux, data, extras):
                nodes, _ = net.forward(
                    params, data, extras=extras, train=False, aux=aux
                )
                return nodes[out_idx].astype(jnp.float32)

            rep, dsh, ex = self._sh()
            psh, _ = self._param_sh()
            self._jit_cache["eval"] = self._jit(
                f, (psh, rep, dsh, ex), dsh, kind="eval", data_arg=2
            )
        return self._jit_cache["eval"]

    def _node_fn(self, node_id: int):
        key = ("node", node_id)
        if key not in self._jit_cache:
            net = self.net

            def f(params, aux, data, extras):
                nodes, _ = net.forward(
                    params, data, extras=extras, train=False, aux=aux
                )
                return nodes[node_id].astype(jnp.float32)

            rep, dsh, ex = self._sh()
            psh, _ = self._param_sh()
            self._jit_cache[key] = self._jit(
                f, (psh, rep, dsh, ex), dsh, kind="extract", data_arg=2
            )
        return self._jit_cache[key]

    def _apply_fn(self):
        if "apply" not in self._jit_cache:
            updaters = dict(self.updaters)
            apply_updates = self._apply_updates
            gspec = self._grad_spec()
            ukern = self._update_kernels()

            def f(params, ustates, grads, epoch):
                return apply_updates(updaters, params, ustates, grads,
                                     epoch, gspec=gspec, kernels=ukern)

            rep = self._sh()[0]
            psh, ush = self._param_sh()
            self._jit_cache["apply"] = self._jit(
                f,
                (psh, ush, psh, rep),
                (psh, ush),
                kind="update_apply",
            )
        return self._jit_cache["apply"]

    # ------------------------------------------------------------------
    def _guard_loss(self, losses, first_epoch: int, n_steps: int = 1) -> None:
        """NaN/Inf divergence guard (active when ``divergence_policy`` is
        set): fetch the step's loss(es), raise :class:`DivergenceError`
        on any non-finite value.  Each call forces a device sync, so the
        guard trades the async dispatch overlap for blow-up detection —
        that is why it is opt-in.

        ``inject_nan_step`` (fault-injection harness) makes the loss at
        that epoch read as NaN once, so recovery paths are testable
        without waiting for a real numeric blow-up."""
        arr = np.asarray(jax.device_get(losses), np.float64).reshape(-1)
        inj = self.inject_nan_step
        if inj >= 0 and first_epoch <= inj < first_epoch + n_steps:
            self.inject_nan_step = -1  # one-shot: a transient fault
            arr = arr.copy()
            arr[min(inj - first_epoch, max(arr.size - 1, 0))] = np.nan
        inj = self.inject_spike_step
        if inj >= 0 and first_epoch <= inj < first_epoch + n_steps:
            self.inject_spike_step = -1  # one-shot: a transient spike
            arr = arr.copy()
            i = min(inj - first_epoch, max(arr.size - 1, 0))
            # finite but far beyond any plausible ratio gate
            arr[i] = max(abs(arr[i]), 1.0) * 1e6
        finite = np.isfinite(arr)
        if not finite.all():
            bad = int(np.flatnonzero(~finite)[0])
            epoch = first_epoch + min(bad, n_steps - 1)
            raise DivergenceError(
                f"divergence guard: non-finite loss {arr[bad]!r} at update "
                f"{epoch} (round {self.round}, policy "
                f"{self.divergence_policy or 'abort'})",
                loss=arr, epoch=epoch,
            )
        self._guard_loss_ratio(arr, first_epoch)

    _SPIKE_WINDOW = 32   # rolling finite-loss history length
    _SPIKE_MIN_SAMPLES = 8   # gate stays disarmed until this many

    def _guard_loss_ratio(self, arr: np.ndarray, first_epoch: int) -> None:
        """Finite loss-spike gate (``divergence_loss_ratio``): a loss
        exceeding ratio x the rolling median of recent finite losses is
        a divergence verdict even though every value is finite — the
        PR-13 staleness blow-up stayed finite for whole rounds.  The
        spike itself is NOT admitted into the history (a genuine
        blow-up must not drag the median up and re-legitimize itself);
        the window rides the trainer, so a divergence rollback (which
        rebuilds the trainer) restarts it cleanly disarmed."""
        ratio = self.divergence_loss_ratio
        if not ratio:
            return
        hist = self._loss_window
        for i, v in enumerate(arr):
            v = float(v)
            if len(hist) >= self._SPIKE_MIN_SAMPLES:
                med = float(np.median(hist))
                if abs(v) > ratio * max(abs(med), 1e-12):
                    epoch = first_epoch + i
                    raise DivergenceError(
                        f"divergence guard: finite loss spike {v:g} > "
                        f"{ratio:g} x rolling median {med:g} at update "
                        f"{epoch} (round {self.round}, policy "
                        f"{self.divergence_policy or 'abort'})",
                        loss=arr, epoch=epoch,
                    )
            hist.append(v)
            if len(hist) > self._SPIKE_WINDOW:
                del hist[0]

    def weights_finite(self) -> bool:
        """True when every parameter tensor is free of NaN/Inf — the
        divergence-rollback sanity check: a CRC-valid checkpoint can
        still carry a baked-in blow-up (the last update of the round it
        captured went non-finite AFTER its loss was measured).
        COLLECTIVE in multi-process runs (``fetch_array`` allgathers),
        so every process computes the identical verdict."""
        for slots in self.params.values():
            for w in slots.values():
                if not np.isfinite(fetch_array(w)).all():
                    return False
        return True

    def scale_learning_rate(self, factor: float) -> None:
        """Multiply every updater's base learning rate by ``factor``
        (divergence-rollback backoff).  Clears the jit cache — compiled
        steps bake the schedule constants in."""
        for up in self.updaters.values():
            up.param.base_lr *= factor
        self._jit_cache.clear()
        self._async = None  # async programs bake the schedule in too

    # ------------------------------------------------------------------
    # async data-parallel (parallel/async_ps, doc/parallel.md)
    def _async_stepper(self):
        """The lazily built :class:`~cxxnet_tpu.parallel.async_ps.step.
        AsyncStepper` driving the overlapped per-group exchange; rebuilt
        whenever the net/mesh/jit cache is (programs close over both)."""
        if self._async is None:
            from ..parallel.async_ps import AsyncStepper

            self._async = AsyncStepper(self)
        return self._async

    def async_round_end(self, round_: int) -> bool:
        """Round-boundary fence for async mode — and, every
        ``async_resync_period`` rounds, the hard re-sync barrier
        (staleness buffers drained first).  No-op when async mode is
        off or no async step ran yet.  Returns True on a resync."""
        if self._async is None or not self._async_active():
            return False
        return self._async.round_end(round_)

    def async_abandon(self, generation: Optional[int] = None,
                      reason: str = "rebuild") -> int:
        """Elastic rebuild hook: discard every pending (in-flight)
        gradient aggregate and move the async updater to a new
        membership generation, so an aggregate reduced by a dead
        generation's collectives is never applied to the rebuilt
        mesh's weights.  Returns the number of aggregates dropped."""
        if self._async is None:
            return 0
        return self._async.updater.reset_staleness(
            generation=generation, reason=reason)

    def async_snapshot(self) -> Optional[dict]:
        """Pipeline telemetry block (pending depths, pushes/applies,
        overlap fraction) — ``None`` outside async mode."""
        if self._async is None:
            return None
        return self._async.snapshot()

    def start_round(self, round_: int) -> None:
        self.round = round_
        # integrity-plane chaos site (doc/robustness.md "Integrity
        # plane"): a `bitflip` armed here flips a real bit in a live
        # train-state tensor on THIS process — the injected silent data
        # corruption the fingerprint vote must catch and quarantine
        from ..utils.faults import fault_point

        fault_point("device.state", self)

    def inject_bitflip(self, rng) -> dict:
        """Flip one bit of one element of one live parameter tensor —
        the ``device.state:bitflip`` fault payload hook.  Deterministic
        in ``rng`` (the spec's ``fault_seed``-derived stream): leaf
        choice over the sorted param tree, then element, then bit, so a
        chaos schedule replays to the same flipped bit.  The flip is
        applied to exactly ONE addressable replica copy of the chosen
        element (an rng-chosen local device — a single device-memory
        fault), via per-device rewrite + reassembly under the original
        sharding — a real in-memory corruption, not a simulated
        verdict, and a strict minority the replica vote can name."""
        assert self.params is not None, "init_model/load_model first"
        leaves = [(f"{k}/{t}", k, t)
                  for k in sorted(self.params)
                  for t in sorted(self.params[k])]
        name, key, tag = leaves[rng.randrange(len(leaves))]
        arr = self.params[key][tag]
        shape = tuple(int(d) for d in arr.shape)
        n = int(np.prod(shape)) if shape else 1
        elem = rng.randrange(n)
        itembits = np.dtype(arr.dtype).itemsize * 8
        bit = rng.randrange(min(itembits, 32))
        shards = getattr(arr, "addressable_shards", None)
        if not shards:
            flat = np.asarray(arr).reshape(-1)
            word = flat[elem:elem + 1].copy().view(
                f"u{flat.dtype.itemsize}")
            word ^= word.dtype.type(1 << bit)
            flat = flat.copy()
            flat[elem] = word.view(flat.dtype)[0]
            self.params[key][tag] = jnp.asarray(flat.reshape(shape))
        else:
            coord = np.unravel_index(elem, shape) if shape else ()
            ordered = sorted(shards, key=lambda s: s.device.id)
            holders = []  # (position, local coordinate) of replicas
            for pos, s in enumerate(ordered):
                inside = True
                lcoord = []
                for d, sl in enumerate(s.index):
                    start = sl.start or 0
                    stop = sl.stop if sl.stop is not None else shape[d]
                    if not (start <= coord[d] < stop):
                        inside = False
                        break
                    lcoord.append(coord[d] - start)
                if inside:
                    holders.append((pos, tuple(lcoord)))
            hit_pos, hit_coord = holders[rng.randrange(len(holders))]
            pieces = []
            hit_device = ordered[hit_pos].device
            for pos, s in enumerate(ordered):
                local = np.asarray(s.data)
                if pos == hit_pos:
                    local = local.copy()
                    word = local[hit_coord].reshape(1).view(
                        f"u{local.dtype.itemsize}")
                    word ^= word.dtype.type(1 << bit)
                    local[hit_coord] = word.view(local.dtype)[0]
                pieces.append(jax.device_put(local, s.device))
            self.params[key][tag] = (
                jax.make_array_from_single_device_arrays(
                    shape, arr.sharding, pieces))
        detail = {
            "tensor": name, "elem": int(elem), "bit": int(bit),
            "process": jax.process_index(),
            "device": (hit_device.id if shards else None),
        }
        if not self.silent:
            print(f"[faults] bitflip injected: tensor={name} "
                  f"elem={elem} bit={bit} device={detail['device']} "
                  f"process={detail['process']}", flush=True)
        return detail

    def sync(self) -> None:
        """Block until all dispatched device work is done (step timing).

        Instrumented as the ``mesh.replica`` fault site: a ``hang``
        here models a peer wedged inside a collective (the elastic
        deadline must surface :class:`ReplicaLossError` in bounded
        time), an ``ioerror`` models the abrupt connection-reset a
        SIGKILLed peer produces — reproducible in-process, no real
        process needs to die (doc/robustness.md)."""
        from ..utils.faults import fault_point

        fault_point("mesh.replica")
        if self.params is not None:
            jax.block_until_ready(self.params)

    def check_weight_sync(self, tol: float = 0.0) -> float:
        """Cross-process weight-consistency check — the reference's
        ``test_on_server = 1`` discipline (each worker pulls the server
        copy and compares to its local weights,
        ``/root/reference/src/updater/async_updater-inl.hpp:148-153``)
        re-expressed for SPMD: there is no server copy, so each process
        fingerprints the locally addressable shard of every replicated
        parameter (float64 sum + sum of squares per leaf) and the
        fingerprints are allgathered across the process group.  Replicas
        that drifted (a bad collective, host memory fault, divergent
        dispatch order) produce differing rows.

        Parameters sharded across devices (model parallel / ZeRO-3) get
        the same guard at shard granularity: each device's shard is
        fingerprinted together with the *logical slice* of the global
        array it holds (``Shard.index``), and every replica of the same
        slice — wherever it lives in the mesh — must agree bit-exactly.
        Slices with a single replica have nothing to compare and
        contribute nothing, so a pure-TP axis is quiet while TP x DP
        (the common case) checks the DP replicas of every TP shard.

        Returns the max abs fingerprint deviation across replicas
        (0.0 single-process single-device); raises RuntimeError when it
        exceeds ``tol``.
        """
        assert self.params is not None, "init_model/load_model first"
        if jax.process_count() == 1 and len(jax.local_devices()) == 1:
            return 0.0  # nothing to compare; skip the host transfers

        def _slice_key(index) -> tuple:
            return tuple(
                (s.start, s.stop, s.step) if isinstance(s, slice) else s
                for s in index
            )

        def _check_groups(keys, fps, where: str) -> float:
            groups: dict = {}
            for k, fpv in zip(keys, fps):
                groups.setdefault(k, []).append(fpv)
            worst = 0.0
            for k, g in groups.items():
                if len(g) < 2:
                    continue
                g = np.asarray(g, np.float64)
                d = float(np.abs(g - g[0]).max())
                worst = max(worst, d)
                if d > tol:
                    name, idx = k
                    raise RuntimeError(
                        f"weight-sync check failed: parameter {name} "
                        f"slice {idx} differs across {where} replicas "
                        f"by {d:g} (tol {tol:g}) — sharded weights have "
                        "diverged"
                    )
            return worst

        rows = []
        shard_rows: list = []   # per (sharded leaf, local device) fingerprints
        shard_keys: list = []   # matching (leaf, slice) group keys
        shard_leaves: list = []  # (name, sharding, shape) in traversal order
        for key in sorted(self.params):
            for tag in sorted(self.params[key]):
                arr = self.params[key][tag]
                sh = getattr(arr, "sharding", None)
                if sh is not None and not sh.is_fully_replicated:
                    for s in sorted(getattr(arr, "addressable_shards", []),
                                    key=lambda s: s.device.id):
                        local = np.asarray(s.data, dtype=np.float64)
                        shard_rows.append([local.sum(), (local * local).sum()])
                        shard_keys.append((f"{key}/{tag}",
                                           _slice_key(s.index)))
                    shard_leaves.append((f"{key}/{tag}", sh, arr.shape))
                    continue
                shards = getattr(arr, "addressable_shards", None)
                if not shards:
                    local = np.asarray(arr, dtype=np.float64)
                    rows.append([local.sum(), (local * local).sum()])
                    continue
                # every LOCAL device holds a full replica: fingerprint
                # each and require intra-process equality too (a single
                # corrupted on-device replica must not hide behind its
                # healthy neighbours)
                fps = []
                for s in shards:
                    local = np.asarray(s.data, dtype=np.float64)
                    fps.append([local.sum(), (local * local).sum()])
                intra = float(
                    np.abs(np.asarray(fps) - np.asarray(fps[0])).max()
                )
                if intra > tol:
                    raise RuntimeError(
                        f"weight-sync check failed: parameter {key}/{tag} "
                        f"differs across LOCAL devices by {intra:g} "
                        f"(tol {tol:g}) — an on-device replica is corrupt"
                    )
                rows.append(fps[0])

        # sharded leaves, intra-process: local replicas of the same slice
        dev_sharded = _check_groups(shard_keys, shard_rows, "local-device")

        fp = np.asarray(rows, np.float64).reshape(-1)
        if jax.process_count() == 1:
            return dev_sharded

        # sharded leaves, cross-process: every process holds the same
        # number of shard rows (uniform local device counts over one
        # mesh), so the fingerprints allgather as a dense block; the
        # matching keys are recomputed per peer from the sharding's
        # global device->slice map (devices_indices_map is deterministic
        # and identical on every process).
        from jax.experimental import multihost_utils

        if shard_rows:
            sfp = np.ascontiguousarray(
                np.asarray(shard_rows, np.float64).reshape(-1)
            ).view(np.uint32)
            all_sfp = np.asarray(
                multihost_utils.process_allgather(sfp)
            ).view(np.float64).reshape(-1, 2)
            all_keys = []
            for p in range(jax.process_count()):
                for name, sh, shape in shard_leaves:
                    imap = sh.devices_indices_map(shape)
                    for d in sorted(
                        (d for d in imap if d.process_index == p),
                        key=lambda d: d.id,
                    ):
                        all_keys.append((name, _slice_key(imap[d])))
            assert len(all_keys) == all_sfp.shape[0], (
                "shard fingerprint/key count mismatch across processes"
            )
            dev_sharded = max(
                dev_sharded,
                _check_groups(all_keys, list(all_sfp), "cross-process"),
            )

        # gather the f64 fingerprints as uint32 words: process_allgather
        # round-trips through jax.device_put, which (x64 mode off — the
        # repo default) would silently truncate float64 to float32 and
        # let sub-f32-resolution drift pass the tol=0 bit-exactness check
        words = np.ascontiguousarray(fp).view(np.uint32)
        all_words = np.asarray(multihost_utils.process_allgather(words))
        all_fp = all_words.view(np.float64).reshape(
            jax.process_count(), -1
        )
        dev = float(np.abs(all_fp - all_fp[0]).max()) if fp.size else 0.0
        if dev > tol:
            raise RuntimeError(
                f"weight-sync check failed: max fingerprint deviation "
                f"{dev:g} across {jax.process_count()} processes "
                f"(tol {tol:g}) — replicated weights have diverged"
            )
        return max(dev, dev_sharded)

    # ------------------------------------------------------------------
    # shadow-step audit (integrity plane, doc/robustness.md)
    def _shadow_fn(self, which: str):
        """One of the TWO independently traced grad executables: same
        python function, two separate ``jax.jit`` objects, so jax
        traces and XLA compiles each from scratch.  A deterministic
        miscompile that lowers the traces differently (the PR-9 GSPMD
        concat class), or a core that computes the same executable
        differently across runs, breaks the bitwise A/B compare."""
        key = ("shadow", which)
        if key not in self._jit_cache:
            loss_and_out = self._loss_and_out

            def f(params, aux, data, labels, mask, rng, step, extras):
                (loss, (_out, _new_aux)), grads = jax.value_and_grad(
                    lambda p: loss_and_out(
                        p, aux, data, labels, mask, rng, step, extras
                    ),
                    has_aux=True,
                )(params)
                return loss, grads

            rep, dsh, ex = self._sh()
            psh, _ = self._param_sh()
            self._jit_cache[key] = self._jit(
                f, (psh, rep, dsh, dsh, dsh, rep, rep, ex), (rep, psh),
                kind=f"shadow_{which}", data_arg=2,
            )
        return self._jit_cache[key]

    @staticmethod
    def _local_bytes(x) -> bytes:
        """Concatenated bytes of the locally addressable data of ``x``
        in device-id order — the unit of the bitwise A/B compare (works
        for replicated, ZeRO-sharded, and host arrays alike)."""
        shards = getattr(x, "addressable_shards", None)
        if not shards:
            return np.ascontiguousarray(np.asarray(x)).tobytes()
        return b"".join(
            np.ascontiguousarray(np.asarray(s.data)).tobytes()
            for s in sorted(shards, key=lambda s: s.device.id))

    def shadow_step(self, round_: int):
        """Re-execute a sampled grad step through two independently
        traced executables on identical probe inputs and compare loss +
        every gradient leaf bitwise.  COLLECTIVE on a multi-process
        mesh (both executions are SPMD programs; every rank must call
        at the same round).  Returns None when the executions agree, a
        ``{"tensor", "detail"}`` mismatch record otherwise.  Skipped
        (returns None) for nets with extra input nodes — the probe
        generator only commits the primary input."""
        assert self.net is not None, "init_model/load_model first"
        if self._n_extras():
            return None
        in_shape = self.net.input_node_shape(self.batch_size)
        local_rows = self.batch_size // max(jax.process_count(), 1)
        rng_np = np.random.RandomState(
            (0x5AD0 ^ (round_ * 2654435761)) & 0x7FFFFFFF)
        data_np = rng_np.random_sample(
            (local_rows,) + tuple(in_shape[1:])).astype(np.float32)
        label_np = np.zeros((local_rows, 1), np.float32)
        mask_np = np.ones(local_rows, np.float32)
        data, labels, mask, extras = self._transfer_batch(
            data_np, label_np, mask_np, ())
        rng = jax.random.PRNGKey(round_ & 0x7FFFFFFF)
        step = jnp.asarray(self.epoch_counter, jnp.int32)
        args_a = (self.params, self.aux, data, labels, mask, rng, step,
                  extras)
        loss_a, grads_a = self._shadow_fn("a")(*args_a)
        # the second executable runs on a DIFFERENT device where one is
        # free (trivial mesh + >1 local device): a per-core fault then
        # shows up as A-vs-B instead of reproducing on both legs
        dev_b = None
        plan = self.mesh_plan
        if ((plan is None or plan.n_devices == 1)
                and len(jax.local_devices()) > 1):
            dev_b = jax.local_devices()[1]
        if dev_b is not None:
            args_b = jax.device_put(args_a, dev_b)
        else:
            args_b = args_a
        loss_b, grads_b = self._shadow_fn("b")(*args_b)
        la, lb = self._local_bytes(loss_a), self._local_bytes(loss_b)
        if self.inject_shadow_mismatch:
            self.inject_shadow_mismatch = 0  # one-shot
            lb = bytes([lb[0] ^ 0x10]) + lb[1:]
        if la != lb:
            return {"tensor": "loss",
                    "detail": (f"shadow loss mismatch at round {round_}: "
                               f"{la.hex()} vs {lb.hex()}")}
        for key in sorted(grads_a):
            for tag in sorted(grads_a[key]):
                if (self._local_bytes(grads_a[key][tag])
                        != self._local_bytes(grads_b[key][tag])):
                    return {"tensor": f"{key}/{tag}",
                            "detail": ("shadow grad mismatch at round "
                                       f"{round_}: {key}/{tag}")}
        return None

    def _next_rng(self) -> jax.Array:
        self._rng_key, sub = jax.random.split(self._rng_key)
        return sub

    def _h2d_sharding(self):
        """The explicit H2D placement for batch-major host arrays: the
        mesh's data sharding (``jax.device_put`` target), or None when
        no mesh exists yet (fall back to ``jnp.asarray``)."""
        plan = self.mesh_plan
        return plan.data_sharding() if plan is not None else None

    def _to_device(self, x: np.ndarray, count_rows: bool = False,
                   own: bool = False) -> jax.Array:
        """Batch-major host array → (possibly multi-process) global array.

        Single process: explicit sharding-aware ``jax.device_put`` onto
        the mesh's data axis (replacing the former plain
        ``jnp.asarray`` — the exact site of the bisected jaxlib
        ``batched_device_put`` flake), so the array arrives already
        placed where jit's in_shardings want it.  ``device_put`` may
        ALIAS host memory (CPU zero-copy), so the source is copied
        first unless ``own=True`` promises the caller's buffer is never
        reused/mutated (iterator buffers ARE reused by ``next()``).
        Multi-process (jax.distributed job): this process holds only its
        shard of the global batch; assemble the global array over the
        data axis (the DCN-spanning-mesh analog of the reference's
        per-worker data sharding, SURVEY §2.8).

        Timed as the ``h2d`` pipeline stage (dispatch + host-side copy;
        the device-side completion overlaps async and is billed to
        ``device_wait`` at the next fence).  ``count_rows`` is set only
        for THE data tensor of a batch — labels/mask/extras bill their
        time but no rows, so the stage's rows/sec stays the true batch
        rate instead of 3-4x it.
        """
        from ..utils.profiler import pipeline_stats
        import time as _time

        t0 = _time.perf_counter()
        if jax.process_count() == 1:
            sh = self._h2d_sharding()
            if sh is None:
                out = jnp.asarray(x)
            else:
                src = x if own else np.array(x, copy=True)
                out = jax.device_put(src, sh)
        else:
            out = jax.make_array_from_process_local_data(
                self.mesh_plan.data_sharding(), np.asarray(x)
            )
        rows = (x.shape[0] if count_rows and getattr(x, "ndim", 0) else 0)
        pipeline_stats().add("h2d", _time.perf_counter() - t0, rows=rows)
        return out

    def _transfer_batch(self, data_np, label_np, mask_np, extras_np,
                        own: bool = False):
        """One sharding-aware H2D for a whole train batch.

        Single-process with a mesh: ONE batched ``jax.device_put`` of
        the (data, labels, mask, extras) pytree onto the data sharding
        — one dispatch instead of four, and the natural unit the
        double-buffered feed stages ahead of time.  Other
        configurations fall back to per-array :meth:`_to_device`.
        Returns ``(data, labels, mask, extras)`` device arrays; billed
        to the ``h2d`` stage with the batch's row count."""
        from ..utils.profiler import pipeline_stats
        import time as _time

        sh = self._h2d_sharding()
        if jax.process_count() != 1 or sh is None:
            data = self._to_device(data_np, count_rows=True, own=own)
            labels = self._to_device(label_np, own=own)
            mask = self._to_device(mask_np, own=own)
            extras = tuple(self._to_device(e, own=own) for e in extras_np)
            return data, labels, mask, extras
        t0 = _time.perf_counter()
        leaves = (data_np, label_np, mask_np) + tuple(extras_np)
        if not own:
            # device_put may alias host memory (CPU zero-copy); copy
            # anything we do not own — same cost jnp.asarray paid
            leaves = tuple(np.array(a, copy=True) for a in leaves)
        placed = jax.device_put(leaves, sh)
        data, labels, mask = placed[0], placed[1], placed[2]
        extras = tuple(placed[3:])
        pipeline_stats().add("h2d", _time.perf_counter() - t0,
                             rows=data_np.shape[0])
        return data, labels, mask, extras

    def stage_batch(self, batch: DataBatch) -> bool:
        """Double-buffered device feed: begin the (async) H2D of the
        NEXT batch while the current step still executes, so transfer
        overlaps compute instead of serializing with the next dispatch.

        The caller MUST own ``batch``'s arrays (no iterator buffer
        reuse) — the transfer aliases them zero-copy where the backend
        allows.  The staged transfer is consumed by the next
        :meth:`update` call carrying the SAME batch object; any other
        batch simply transfers normally and the staged arrays are
        dropped.  Returns True when staged (single-process with a mesh
        only — the multi-process assembly path fences internally)."""
        if jax.process_count() != 1 or self._h2d_sharding() is None:
            return False
        data_np, label_np, extras_np, mask_np, n_real = (
            self._pad_train_batch(batch)
        )
        arrays = self._transfer_batch(data_np, label_np, mask_np,
                                      extras_np, own=True)
        self._staged = (batch, arrays, n_real)
        return True

    def _pad_train_batch(self, batch: DataBatch):
        """Zero-pad a short final train batch to the compiled batch size.

        The static-shape AdjustBatchSize (``neural_net-inl.hpp:266-277``):
        XLA programs are compiled for one batch shape, so instead of
        re-jitting for every tail size, pad up and hand the step a 0/1
        sample mask that zeroes the padded rows' loss contribution.  Two
        sources of dead rows are masked:

        * a hand-fed short batch (wrapper API) — padded up here;
        * the IO chain's full-size final batch whose trailing
          ``num_batch_padd`` rows are filler (``io/batch.py`` with
          ``round_batch=0``) — already full-size, only masked.

        Returns ``(data, label, extras, mask, n_real)``.
        """
        n = batch.data.shape[0]
        bs = self.batch_size or n
        if jax.process_count() > 1:
            # multi-process: update() receives this process's shard of the
            # global batch (see _to_device); padding must happen upstream
            local = bs // jax.process_count()
            if n != local:
                raise ValueError(
                    f"distributed run: each process must feed exactly "
                    f"batch_size/process_count = {local} rows, got {n}; "
                    "use round_batch=1 in the data iterator"
                )
            # this process's iterator pads its own tail (round_batch=0):
            # mask those filler rows here exactly like the single-process
            # branch; per-process masks concatenate into the global mask
            n_real = n - int(batch.num_batch_padd or 0)
            mask = np.ones(local, np.float32)
            if n_real < n:
                mask[n_real:] = 0.0
            return (batch.data, batch.label, tuple(batch.extra_data),
                    mask, n_real)
        if n == bs:
            n_real = n - int(batch.num_batch_padd or 0)
            mask = np.ones(bs, np.float32)
            if n_real < n:
                mask[n_real:] = 0.0
            return (batch.data, batch.label, tuple(batch.extra_data),
                    mask, n_real)
        if n > bs:
            raise ValueError(
                f"train batch of {n} rows exceeds batch_size={bs}"
            )
        pad = bs - n

        def _pad(a):
            a = np.asarray(a)
            return np.concatenate(
                [a, np.zeros((pad,) + a.shape[1:], a.dtype)], axis=0
            )

        mask = np.concatenate(
            [np.ones(n, np.float32), np.zeros(pad, np.float32)]
        )
        return (_pad(batch.data), _pad(batch.label),
                tuple(_pad(e) for e in batch.extra_data), mask, n)

    def _node_pred_cache(self, data, extras, n_real):
        """Eval-mode forwards for the train metric's node-bound entries,
        run on the CURRENT (pre-update) weights — call before the fused
        step, which donates the param buffers.  Every metric then scores
        the same weight version.  Deliberate divergence from the
        reference: its eval_req snapshots come from the TRAIN forward
        (dropout noise included, nnet_impl-inl.hpp:363-372); here the
        node forward runs eval-mode, so on stochastic nets a node-bound
        metric and the default metric can differ even on the out node."""
        cache = {}
        for node in self.train_metric.nodes:
            if node is not None and node not in cache:
                fn = self._metric_node_fn(node)
                cache[node] = fetch_local_rows(
                    fn(self.params, self.aux, data, extras)
                )[:n_real]
        return cache

    def _train_metric_preds(self, out, n_real, node_cache):
        """Per-metric predictions for eval_train: the step's own output
        for default entries, the precomputed node forwards for
        ``metric[field,node]`` entries (no extra compute otherwise)."""
        base = fetch_local_rows(out)[:n_real]
        if not node_cache:
            return base
        cache = {None: base, **node_cache}
        return [cache[node] for node in self.train_metric.nodes]

    def _maybe_quantize(self) -> None:
        """Apply the conf's ``quant`` scheme to freshly built f32 params
        (no-op when unrequested or already quantized).  This is the
        UNGATED on-load path — serving processes have no held-out data
        to gate on; the event makes that visible."""
        if not self._quant_requested or self.quant_scheme:
            return
        from . import quant as nquant
        from ..obs import events as obs_events

        plan = nquant.build_plan(self, self._quant_requested)
        if not plan:
            return
        nquant.apply_plan(self, plan, self._quant_requested)
        obs_events.emit(
            "quant.on_load", scheme=self.quant_scheme,
            layers=len(plan), gated=False)

    def _check_trainable(self) -> None:
        if self.quant_scheme:
            raise ValueError(
                f"this trainer serves a quantized model "
                f"({self.quant_scheme}) and is inference-only — "
                "gradients through int8 codes are meaningless; train "
                "on the f32 checkpoint and re-export")

    def update(self, batch: DataBatch) -> None:
        """One micro-batch: fwd/bwd + (every update_period-th call) update."""
        assert self.net is not None, "init_model/load_model first"
        self._check_trainable()
        staged, self._staged = self._staged, None
        if staged is not None and staged[0] is batch:
            # double-buffered feed: this batch's H2D was issued by
            # stage_batch while the PREVIOUS step executed
            (data, labels, mask, extras), n_real = staged[1], staged[2]
        else:
            data_np, label_np, extras_np, mask_np, n_real = (
                self._pad_train_batch(batch)
            )
            data, labels, mask, extras = self._transfer_batch(
                data_np, label_np, mask_np, extras_np
            )
        step = jnp.asarray(self.epoch_counter, jnp.int32)
        node_cache = {}
        if self.eval_train and self.train_metric.need_nodes():
            node_cache = self._node_pred_cache(data, extras, n_real)
        if self._async_active():
            # overlapped per-group exchange (parallel/async_ps): the
            # host never blocks here — fences belong to
            # async_round_end (and the opt-in divergence guard / train
            # metrics below, which fetch and therefore sync)
            stepper = self._async_stepper()
            losses, out = stepper.step(
                data, labels, mask, self._next_rng(), self.epoch_counter)
            if self.divergence_policy or self.eval_train:
                # these fetches fence the pipeline every step — billed
                # against the round's overlap fraction so the gauge
                # cannot report a fully-overlapped round that is
                # effectively synchronous
                t0 = time.perf_counter()
                if self.divergence_policy:
                    self._guard_loss(losses, self.epoch_counter)
                if self.eval_train:
                    self.train_metric.add_eval(
                        self._train_metric_preds(out, n_real, node_cache),
                        np.asarray(batch.label)[:n_real],
                        self._label_ranges(),
                    )
                stepper.add_blocked(time.perf_counter() - t0)
            self.epoch_counter += 1
            obs_device.maybe_sample_step(self.epoch_counter, self.sync)
            return
        if self.update_period == 1:
            # fused SPMD fast path: fwd+bwd+update in one donated program
            (self.params, self.ustates, self.aux, loss, out) = (
                self._fused_step_fn()(
                    self.params, self.ustates, self.aux, data, labels,
                    mask, self._next_rng(), step, extras,
                )
            )
            if self.divergence_policy:
                self._guard_loss(loss, self.epoch_counter)
            if self.eval_train:
                self.train_metric.add_eval(
                    self._train_metric_preds(out, n_real, node_cache),
                    np.asarray(batch.label)[:n_real],
                    self._label_ranges(),
                )
            self.epoch_counter += 1
            # sampled device fence (device_sample_every = N): every Nth
            # update blocks here and the wait lands in the
            # train_step_device_seconds histogram; off by default — a
            # fence breaks the async dispatch overlap
            obs_device.maybe_sample_step(self.epoch_counter, self.sync)
            return
        if self.eval_train:
            loss, out, self.aux, grads = self._fwd_train_fn()(
                self.params, self.aux, data, labels, mask,
                self._next_rng(), step, extras,
            )
            self.train_metric.add_eval(
                self._train_metric_preds(out, n_real, node_cache),
                np.asarray(batch.label)[:n_real],
                self._label_ranges(),
            )
        else:
            (loss, self.aux), grads = self._grad_fn()(
                self.params, self.aux, data, labels, mask,
                self._next_rng(), step, extras,
            )
        if self.divergence_policy:
            # accumulation path: catch the blow-up per micro-batch,
            # BEFORE the bad gradient is folded into the accumulator
            self._guard_loss(loss, self.epoch_counter)
        if self._grad_accum is None:
            self._grad_accum = grads
        else:
            self._grad_accum = jax.tree_util.tree_map(
                jnp.add, self._grad_accum, grads
            )
        self.sample_counter += 1
        if self.sample_counter >= self.update_period:
            self.params, self.ustates = self._apply_fn()(
                self.params,
                self.ustates,
                self._grad_accum,
                jnp.asarray(self.epoch_counter, jnp.int32),
            )
            self._grad_accum = None
            self.sample_counter = 0
            self.epoch_counter += 1
            obs_device.maybe_sample_step(self.epoch_counter, self.sync)

    def update_all(self, data: np.ndarray, labels: np.ndarray) -> None:
        """numpy-in convenience (wrapper API ``CXNNetUpdateBatch``)."""
        self.update(DataBatch(data=np.asarray(data), label=np.asarray(labels)))

    # ------------------------------------------------------------------
    def _label_ranges(self) -> Dict[str, Tuple[int, int]]:
        g = self.graph
        return {name: g.label_range[i] for name, i in g.label_name_map.items()}

    def _run_sharded(self, fn, data: np.ndarray, extras=()) -> np.ndarray:
        """Call a data-sharded jit, zero-padding a partial final batch to a
        multiple of the data-axis size (the XLA-static-shapes analog of the
        reference's AdjustBatchSize, SURVEY §7 hard part (f)) and trimming
        the result."""
        n = data.shape[0]
        nd = self.mesh_plan.n_data if self.mesh_plan else 1
        pad = (-n) % nd
        if pad:
            data = np.concatenate([data, np.zeros((pad,) + data.shape[1:],
                                                  data.dtype)], axis=0)
            extras = tuple(
                np.concatenate([e, np.zeros((pad,) + e.shape[1:], e.dtype)], 0)
                for e in extras
            )
        out = fetch_local_rows(
            fn(self.params, self.aux, self._to_device(data, count_rows=True),
               tuple(self._to_device(e) for e in extras))
        )
        return out[:n] if pad else out

    def _metric_node_fn(self, node):
        """Forward fn for one metric's node selector (None = final out) —
        the per-metric ``eval_req`` binding, nnet_impl-inl.hpp:363-372."""
        if node is None:
            return self._eval_fn()
        return self._node_fn(self.graph.node_index_of(node))

    def evaluate(self, iter_eval, data_name: str) -> str:
        """Round-end evaluation; format parity ``\\tname-metric:value``.

        Multi-process: every process evaluates its own (sharded) rows
        and the metric counters are summed across the job before
        printing, so the line reports the GLOBAL metric on each rank."""
        ret = ""
        if self.eval_train:
            self.train_metric.reduce_across_processes()
            ret += self.train_metric.print("train")
            self.train_metric.clear()
        if iter_eval is None:
            return ret
        if len(self.metric) == 0:
            return ret
        self.metric.clear()
        fns = [self._metric_node_fn(n) for n in self.metric.nodes]
        iter_eval.before_first()
        while iter_eval.next():
            batch = iter_eval.value()
            data = np.asarray(batch.data)
            extras = tuple(batch.extra_data)
            n = batch.batch_size - batch.num_batch_padd
            outs, preds = {}, []
            for fn in fns:
                if id(fn) not in outs:
                    outs[id(fn)] = self._run_sharded(fn, data, extras)[:n]
                preds.append(outs[id(fn)])
            self.metric.add_eval(preds, batch.label[:n], self._label_ranges())
        self.metric.reduce_across_processes()
        ret += self.metric.print(data_name)
        return ret

    def predict_fn(self, node_id: Optional[int] = None):
        """The PURE, shape-stable inference function — the compiled
        primitive the serving subsystem caches (``serve/cache.py``):
        ``f(params, aux, data, extras) -> f32 out rows`` with eval-mode
        forward semantics and no trainer state captured mutably (params
        and aux are explicit arguments, so a hot-swapped model is just a
        different first argument).  XLA specializes per input shape;
        callers that control the batch shape (power-of-two buckets)
        control the compile count.  ``node_id`` selects a feature node
        (``resolve_feature_node``); ``None`` is the final output."""
        return self._eval_fn() if node_id is None else self._node_fn(node_id)

    def resolve_feature_node(self, node_name: str) -> int:
        """``top[-k]`` / node-name → node index (ExtractFeature rules)."""
        g = self.graph
        if node_name.startswith("top[-"):
            offset = int(node_name[len("top[-"):-1])
            nnode = g.num_nodes
            if not (1 <= offset <= nnode):
                raise ValueError("ExtractFeature: offset out of node range")
            return nnode - offset
        return g.node_index_of(node_name)

    @staticmethod
    def predict_from_scores(out: np.ndarray) -> np.ndarray:
        """Raw out-node rows → per-instance predictions: argmax
        (multi-column), the raw scalar (1-column), or the per-position
        ``(N, T)`` argmax id matrix for sequence models."""
        if out.ndim == 3:
            return out.argmax(axis=-1).astype(np.float32)
        out2d = out.reshape(out.shape[0], -1)
        if out2d.shape[1] == 1:
            return out2d[:, 0]
        return out2d.argmax(axis=1).astype(np.float32)

    def predict(self, batch: DataBatch) -> np.ndarray:
        """Per-instance prediction: argmax, or raw value for 1-col output.

        Sequence models (``(N, T, V)`` out node) predict per position —
        the result is the ``(N, T)`` argmax id matrix."""
        out = self._run_sharded(
            self._eval_fn(), np.asarray(batch.data), tuple(batch.extra_data)
        )
        return self.predict_from_scores(out)

    def extract_feature(self, batch: DataBatch, node_name: str) -> np.ndarray:
        node_id = self.resolve_feature_node(node_name)
        return self._run_sharded(
            self._node_fn(node_id), np.asarray(batch.data),
            tuple(batch.extra_data),
        )

    # ------------------------------------------------------------------
    # weight access (wrapper API parity: 2-D views, visitor tag scheme)
    def get_weight(self, layer_name: str, tag: str) -> np.ndarray:
        i = self.graph.layer_index_of(layer_name)
        key = self.net.param_key[i]
        if key not in self.params or tag not in self.params[key]:
            return np.zeros((0, 0), np.float32)
        w = fetch_array(self.params[key][tag])
        return self._to_2d(w, self.graph.layers[i].type_name, tag)

    def set_weight(self, weight: np.ndarray, layer_name: str, tag: str) -> None:
        if tag not in ("wmat", "bias"):
            raise ValueError("tag must be wmat or bias")
        i = self.graph.layer_index_of(layer_name)
        key = self.net.param_key[i]
        cur = fetch_array(self.params[key][tag])
        new = self._from_2d(np.asarray(weight, np.float32), cur.shape,
                            self.graph.layers[i].type_name, tag)
        plan = self.mesh_plan
        if plan is not None and plan.n_devices > 1:
            # keep the leaf on its SPMD placement (a hand-set weight
            # must not silently break the sharded-state invariant)
            spec = (plan.fsdp_sharding if self.zero >= 3
                    else plan.param_sharding)(new.shape)
            self.params[key][tag] = jax.device_put(new, spec)
        else:
            self.params[key][tag] = jnp.asarray(new)

    @staticmethod
    def _to_2d(w: np.ndarray, type_name: str, tag: str) -> np.ndarray:
        """Flatten to the reference visitor's 2-D view: conv wmat becomes
        (cout, cin_g*kh*kw) in (cin, kh, kw) minor order (the
        unpack_patch2col layout); everything else row-major."""
        if type_name == "conv" and tag == "wmat" and w.ndim == 4:
            kh, kw, ci, co = w.shape
            return w.transpose(3, 2, 0, 1).reshape(co, ci * kh * kw)
        if w.ndim == 1:
            return w[None, :]
        return w.reshape(w.shape[0], -1)

    @staticmethod
    def _from_2d(w2: np.ndarray, shape, type_name: str, tag: str) -> np.ndarray:
        if type_name == "conv" and tag == "wmat" and len(shape) == 4:
            kh, kw, ci, co = shape
            return w2.reshape(co, ci, kh, kw).transpose(2, 3, 1, 0)
        return w2.reshape(shape)

    # ------------------------------------------------------------------
    # checkpointing: magic | json header | npz params
    #
    # npz cannot represent ml_dtypes natively (bfloat16 round-trips as
    # raw void bytes), so bfloat16 leaves — the quantized artifacts' 2x
    # fallback kernels — are stored as uint16 words under a "~bf16"
    # name suffix and re-viewed at read time.
    _BF16_SUFFIX = "~bf16"

    @classmethod
    def _read_model_file(cls, path: str):
        """Parse a checkpoint → (header, params, aux, ustates) where
        params/aux are ``{key: {tag: ndarray}}`` and ustates (present
        only for ``save_ustate=1`` checkpoints) is
        ``{key: {tag: {slot: ndarray}}}``."""
        with open(path, "rb") as f:
            magic = f.read(8)
            if magic != MODEL_MAGIC:
                raise ValueError(f"{path}: not a cxxnet-tpu model file")
            (hlen,) = struct.unpack("<I", f.read(4))
            header = json.loads(f.read(hlen).decode("utf-8"))
            blob = f.read()
        npz = np.load(_io.BytesIO(blob))
        params: Dict[str, dict] = {}
        aux: Dict[str, dict] = {}
        ust: Dict[str, dict] = {}
        for k in npz.files:
            arr = npz[k]
            if k.endswith(cls._BF16_SUFFIX):
                import ml_dtypes

                k = k[:-len(cls._BF16_SUFFIX)]
                arr = arr.view(ml_dtypes.bfloat16)
            key, tag = k.rsplit("/", 1)
            if key.startswith("ust:"):
                tagname, slot = tag.split("@", 1)
                ust.setdefault(key[4:], {}).setdefault(tagname, {})[
                    slot
                ] = arr
            elif key.startswith("aux:"):
                aux.setdefault(key[4:], {})[tag] = arr
            else:
                params.setdefault(key, {})[tag] = arr
        return header, params, aux, ust

    def checkpoint_bytes(self) -> bytes:
        """Serialize the full checkpoint to one byte string.

        COLLECTIVE in multi-process runs: assembling sharded arrays
        (``fetch_array``) allgathers across the job, so EVERY process
        must call this even when only rank 0 writes the file (the
        driver's discipline — ``cli.py::_save_model``)."""
        if self._async is not None:
            # checkpoints are SYNCHRONOUS states: apply every pending
            # staleness aggregate first (every process drains the same
            # buffer contents, so the collective apply order agrees),
            # then PullWait every group — the serializer below reads
            # the weights on host — then serialize; a resumed run
            # restarts the pipeline
            up = self._async.updater
            up.drain()
            for gid in range(len(up.groups)):
                up.pull_wait(gid)
        header = {
            "structure": json.loads(self.graph.structure_to_json()),
            "epoch_counter": self.epoch_counter,
        }
        if self.quant_scheme:
            # quantized artifact: load_model restores the scheme/plan so
            # the served programs (and the bucket-cache key) know what
            # precision they run — see nnet/quant.py
            header["quant"] = {
                "scheme": self.quant_scheme,
                "scales_dtype": "float32",
                "layers": dict(self.quant_plan or {}),
            }
        if self.save_ustate and self._rng_key is not None:
            # exact resume includes the training rng stream (dropout /
            # insanity noise), not just optimizer state; the impl name is
            # recorded so a process with a different jax_default_prng_impl
            # reconstructs the same stream rather than silently diverging
            header["rng_key"] = np.asarray(
                jax.random.key_data(self._rng_key)
            ).tolist()
            header["rng_impl"] = str(
                jax.config.jax_default_prng_impl
            )
        hjson = json.dumps(header).encode("utf-8")
        buf = _io.BytesIO()
        flat = {}

        def _store(name: str, w) -> None:
            arr = fetch_array(w)
            if arr.dtype.name == "bfloat16":
                # npz-safe spelling: uint16 words + name suffix (see
                # _read_model_file)
                flat[name + self._BF16_SUFFIX] = arr.view(np.uint16)
            else:
                flat[name] = arr

        for key, tags in self.params.items():
            for tag, w in tags.items():
                _store(f"{key}/{tag}", w)
        for key, tags in self.aux.items():
            for tag, w in tags.items():
                _store(f"aux:{key}/{tag}", w)
        if self.save_ustate:
            for key, tags in self.ustates.items():
                for tag, slots in tags.items():
                    for slot, w in slots.items():
                        _store(f"ust:{key}/{tag}@{slot}", w)
        np.savez(buf, **flat)
        out = _io.BytesIO()
        out.write(MODEL_MAGIC)
        out.write(struct.pack("<I", len(hjson)))
        out.write(hjson)
        out.write(buf.getvalue())
        return out.getvalue()

    def net_fp(self) -> str:
        """Fingerprint of the current net structure (manifest field)."""
        return ckpt.net_fingerprint(self.graph.structure_to_json())

    def mesh_manifest(self) -> Optional[dict]:
        """The SPMD layout that writes checkpoints (manifest ``mesh``
        field) — informational, since the payload is always gathered
        full arrays and load re-shards onto the current mesh."""
        if self.mesh_plan is None:
            return None
        return {
            "n_data": self.mesh_plan.n_data,
            "n_model": self.mesh_plan.n_model,
            "zero": self.zero,
            "processes": jax.process_count(),
        }

    def save_model(self, path: str, round_: Optional[int] = None,
                   manifest: bool = True) -> None:
        """Atomic checkpoint write (temp + fsync + rename) plus a sidecar
        manifest carrying CRC32 / size / round / net fingerprint, so a
        kill mid-write can never leave a loadable-looking truncation."""
        blob = self.checkpoint_bytes()
        if manifest:
            quant = None
            if self.quant_scheme:
                plan = self.quant_plan or {}
                quant = {
                    "scheme": self.quant_scheme,
                    "scales_dtype": "float32",
                    "int8_layers": sum(1 for v in plan.values()
                                       if v == "int8"),
                    "bf16_layers": sum(1 for v in plan.values()
                                       if v == "bf16"),
                }
            ckpt.write_checkpoint(
                path, blob,
                round_=self.round if round_ is None else round_,
                net_fp=self.net_fp(),
                save_ustate=self.save_ustate,
                mesh=self.mesh_manifest(),
                quant=quant,
            )
        else:
            ckpt.atomic_write_bytes(path, blob)

    def load_model(self, path: str) -> None:
        if not any(n == "netconfig" for n, _ in self.cfg):
            raise ValueError(
                "load_model: set the model conf first (checkpoints store "
                "the net STRUCTURE; layer settings come from the conf — "
                "reference parity: pred.conf carries the full netconfig "
                "section).  Net(cfg=conf_text) / set_params(...) before "
                "load_model."
            )
        header, raw, raw_aux, raw_ust = self._read_model_file(path)
        graph = NetGraph.structure_from_json(json.dumps(header["structure"]))
        self._build_net(graph)
        self._check_metric_nodes()
        self._build_mesh()
        self._bind_mesh_to_layers()
        self.epoch_counter = int(header["epoch_counter"])
        self.sample_counter = 0
        self._grad_accum = None  # drop any half-window from before load
        if "rng_key" in header:
            self._rng_key = jax.random.wrap_key_data(
                jnp.asarray(header["rng_key"], jnp.uint32),
                impl=header.get(
                    "rng_impl", str(jax.config.jax_default_prng_impl)
                ),
            )
        else:
            self._rng_key = jax.random.PRNGKey(self.seed + 1)
        self.params = {
            key: {tag: jnp.asarray(w) for tag, w in tags.items()}
            for key, tags in raw.items()
        }
        q = header.get("quant")
        if q:
            # pre-exported quantized artifact (nnet/quant.py): the codes
            # / scales / bf16 kernels loaded verbatim above ARE the
            # serving params; record the scheme for dispatch + identity
            self.quant_scheme = str(q.get("scheme", "int8"))
            self.quant_plan = dict(q.get("layers") or {})
        else:
            self.quant_scheme = ""
            self.quant_plan = None
        self.aux = self.net.init_aux(self.batch_size)
        for key, tags in raw_aux.items():
            if key in self.aux:
                self.aux[key] = {t: jnp.asarray(w) for t, w in tags.items()}
        self.net.infer_shapes(self.batch_size)
        self._validate_det_reduce()
        self._build_updaters()
        # exact resume (save_ustate=1 checkpoints): restore momentum /
        # adam moments where shapes match the rebuilt updaters
        for key, tags in raw_ust.items():
            if key not in self.ustates:
                continue
            for tag, slots in tags.items():
                cur = self.ustates[key].get(tag)
                if cur is None:
                    continue
                if set(slots) == set(cur) and all(
                    slots[sl].shape == cur[sl].shape for sl in slots
                ):
                    self.ustates[key][tag] = {
                        sl: jnp.asarray(w) for sl, w in slots.items()
                    }
        # a conf-level quant key on a PLAIN checkpoint: quantize now
        # (ungated — doc/performance.md); a quantized artifact wins
        self._maybe_quantize()
        # checkpoints hold GATHERED (full) arrays — re-shard onto the
        # CURRENT mesh, whatever mesh (or process count) wrote them
        self._place_state()

    def copy_model_from(self, path: str) -> None:
        """Finetune: fresh init, then copy name-matched layers' weights
        (nnet_impl-inl.hpp:101-134); epoch restarts at 0."""
        self.init_model()
        header, old_params, _old_aux, _old_ust = self._read_model_file(path)
        old = NetGraph.structure_from_json(json.dumps(header["structure"]))
        old_keys = {}
        for i, spec in enumerate(old.layers):
            if spec.name:
                tagk = spec.name if spec.name else spec.type_name
                old_keys[spec.name] = f"l{i}_{tagk}"
        for j, spec in enumerate(self.graph.layers):
            if not spec.name or spec.name not in old_keys:
                continue
            okey = old_keys[spec.name]
            nkey = self.net.param_key[j]
            if okey in old_params and nkey in self.params:
                src = old_params[okey]
                dst = self.params[nkey]
                if all(tag in src and src[tag].shape == np.asarray(dst[tag]).shape
                       for tag in dst):
                    if not self.silent:
                        print(f"Copying layer {spec.name}")
                    for tag in dst:
                        dst[tag] = jnp.asarray(src[tag])
        self.epoch_counter = 0
        self._place_state()  # copied leaves land on the mesh shardings
