"""Autoregressive text generation from a trained language model.

Shared by the CLI (``task = generate``) and the Python API
(``wrapper.Net.generate``).  Two decode paths over the same trained
parameters:

* **KV-cache incremental decoding** (``cache=True``, default): a decode
  twin of the trained net — identical structure and parameter shapes,
  input ``(1, 1)``, ``decode = 1`` routing embedding/attention through
  absolute positions with per-layer key/value caches carried as aux
  state — runs one jitted single-token step per position: O(T) per
  token.  Used when prompt + gen_len fit the training window.
* **Sliding window** (``cache=False``, or the fallback when the net
  cannot grow caches / the prompt fills the window): the full
  static-``T`` forward re-runs per token, context right-aligned —
  O(T^2) per token, no length cap.

Both produce identical greedy outputs inside the window
(``tests/test_lm.py``).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class NoDecodeSupport(Exception):
    """The decode twin grew no KV caches — fall back to windows."""


def sample_token(p_row: np.ndarray, rng: np.random.RandomState,
                 temp: float, topk: int = 0, topp: float = 0.0) -> int:
    """Greedy (``temp == 0``) or log-space temperature sampling
    (``p^(1/temp)`` computed max-subtracted so low temperatures never
    underflow to all-zeros), optionally truncated to the ``topk``
    highest-probability tokens and/or the ``topp`` nucleus (smallest
    set of tokens whose probability mass reaches ``topp``)."""
    if temp <= 0:
        return int(np.argmax(p_row))
    lp = np.log(np.maximum(np.asarray(p_row, np.float64), 1e-300)) / temp
    lp -= lp.max()
    pe = np.exp(lp)
    pe /= pe.sum()
    if topk and topk < len(pe):
        cut = np.argsort(pe)[:-topk]
        pe[cut] = 0.0
        pe /= pe.sum()
    if 0.0 < topp < 1.0:
        order = np.argsort(-pe)
        csum = np.cumsum(pe[order])
        keep_n = int(np.searchsorted(csum, topp) + 1)
        drop = order[keep_n:]
        pe[drop] = 0.0
        pe /= pe.sum()
    return int(rng.choice(len(pe), p=pe))


def generate_windowed(tr, ctx: List[int], gen_len: int, temp: float,
                      rng: np.random.RandomState, topk: int = 0,
                      topp: float = 0.0) -> str:
    """Sliding-window generation: re-run the trained net's full forward
    per token (the context occupies positions ``0..L-1``; causal masking
    makes the tail padding invisible, so one compiled program serves
    every step)."""
    from ..io.data import DataBatch

    t = tr.graph.input_shape[-1]
    ctx = list(ctx)
    out_bytes = []
    for _ in range(gen_len):
        window = ctx[-t:]
        ln = len(window)
        data = np.zeros((1, t), np.float32)
        data[0, :ln] = window
        probs = tr.extract_feature(
            DataBatch(data=data, label=None), "top[-1]"
        )[0, ln - 1]
        nxt = sample_token(probs, rng, temp, topk, topp)
        ctx.append(nxt)
        out_bytes.append(nxt)
    return bytes(out_bytes).decode("utf-8", "replace")


def _decode_twin(tr):
    """(decode trainer, jitted single-token step, fresh aux) — cached on
    ``tr`` so repeated ``generate`` calls pay net construction and jit
    compilation once; invalidated when the params object changes (a new
    training step or load swaps the pytree)."""
    import jax
    import jax.numpy as jnp

    from .trainer import NetTrainer

    cached = getattr(tr, "_decode_twin_cache", None)
    if cached is not None and cached[0] is tr.params:
        return cached[1], cached[2]

    t_train = tr.graph.input_shape[-1]
    dec_cfg = []
    for n, v in tr.cfg:
        if n == "input_shape":
            v = "1,1,1"
        elif n == "batch_size":
            v = "1"
        elif n in ("dev", "model_parallel", "seq_parallel", "zero",
                   "fsdp", "update_on_server"):
            # the decode twin is a single-device batch-1 loop; the
            # training run's mesh/SP/sharding settings would make init
            # fail (batch 1 can't split) or be meaningless
            continue
        dec_cfg.append((n, v))
    dec_cfg += [("decode", "1"), ("decode_window", str(t_train)),
                ("seq_parallel", "0")]
    dec = NetTrainer()
    dec.set_params(dec_cfg)
    try:
        dec.init_model()
    except ValueError as e:
        # e.g. non-causal attention can't decode incrementally
        raise NoDecodeSupport(str(e)) from e
    for key in dec.params:
        if key not in tr.params:
            raise ValueError(f"decode net key {key} missing from model")
        dec.params[key] = tr.params[key]
    net = dec.net
    out_idx = net.out_node_index()
    if not net.init_aux(1):
        # no layer grew a KV cache (e.g. pipe_transformer blocks ignore
        # decode=) — incremental stepping would silently see one token
        # at a time
        raise NoDecodeSupport(
            "net has no KV-cache-capable layers"
        )

    @jax.jit
    def step_fn(params, aux, tok, pos):
        nodes, _, new_aux = net.forward(
            params, tok, train=False, aux=aux, return_aux=True, step=pos
        )
        return nodes[out_idx].astype(jnp.float32), new_aux

    tr._decode_twin_cache = (tr.params, dec, step_fn)
    return dec, step_fn


def generate_cached(tr, ctx: List[int], gen_len: int, temp: float,
                    rng: np.random.RandomState, topk: int = 0,
                    topp: float = 0.0) -> str:
    """KV-cache incremental decoding; raises :class:`NoDecodeSupport`
    when the net cannot run it (no cache-capable layers, non-causal
    attention)."""
    import jax.numpy as jnp

    dec, step_fn = _decode_twin(tr)
    aux = dec.net.init_aux(1)
    out_bytes = []
    probs = None
    for pos, tok in enumerate(ctx):
        tok_a = np.asarray([[tok]], np.float32)
        probs, aux = step_fn(dec.params, aux, tok_a,
                             jnp.asarray(pos, jnp.int32))
    pos = len(ctx)
    for _ in range(gen_len):
        nxt = sample_token(np.asarray(probs)[0, 0], rng, temp, topk, topp)
        out_bytes.append(nxt)
        if len(out_bytes) == gen_len:
            break
        tok_a = np.asarray([[nxt]], np.float32)
        probs, aux = step_fn(dec.params, aux, tok_a,
                             jnp.asarray(pos, jnp.int32))
        pos += 1
    return bytes(out_bytes).decode("utf-8", "replace")


def generate(tr, prompt: str = "", gen_len: int = 256, temp: float = 0.0,
             cache: bool = True, seed: Optional[int] = None,
             topk: int = 0, topp: float = 0.0,
             silent: bool = True) -> str:
    """Generate ``gen_len`` bytes continuing ``prompt`` from a trained
    byte-level language model (``tr`` is a NetTrainer with a loaded or
    trained model).

    The KV-cache path serves requests that fit the training window
    (prompt + gen_len <= T); anything longer falls back to the
    cap-free sliding-window path, so ``gen_len`` is always honored.
    """
    if tr.graph is None:
        raise ValueError("generate: init_model/load_model first")
    ctx = list(prompt.encode("utf-8")) or [ord("\n")]
    rng = np.random.RandomState(tr.seed if seed is None else seed)
    t_train = tr.graph.input_shape[-1]
    if cache and len(ctx) + gen_len <= t_train:
        try:
            return generate_cached(tr, ctx, gen_len, temp, rng,
                                   topk, topp)
        except NoDecodeSupport as e:
            if not silent:
                print(f"gen_cache: {e or 'not supported by this net'}; "
                      "using the sliding-window path")
    elif cache and not silent:
        print(f"gen_cache: prompt ({len(ctx)}) + gen_len ({gen_len}) "
              f"exceeds the KV window ({t_train}); using the "
              "sliding-window path (set gen_cache = 0 to silence this)")
    return generate_windowed(tr, ctx, gen_len, temp, rng, topk, topp)
