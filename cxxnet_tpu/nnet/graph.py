"""Net structure configuration: the ``netconfig=start..end`` graph parser.

Parity: ``/root/reference/src/nnet/nnet_config.h`` —

* ``layer[src->dst] = type:name`` with comma-separated node lists
* ``layer[+1]`` (new anonymous node after the top), ``layer[+1:tag]``
  (new named node), ``layer[+0]`` (self-loop: out node == in node)
* node ``0`` is the input, named ``in`` (also addressable as ``0``);
  ``extra_data_num`` adds ``in_1..in_k`` side-input nodes
* ``shared[tag]`` layers reuse the params of the earlier layer named
  ``tag`` (``primary_layer_index``)
* keys following a ``layer[...]`` line bind to that layer; keys outside
  netconfig are global defaults applied to every layer first
  (``neural_net-inl.hpp:252-264`` applies defcfg, then layercfg)
* ``label_vec[a,b) = name`` declares named label fields over column
  ranges of the batch label matrix (``nnet_config.h:192-203``); field
  ``label`` = column 0 by default.

The parsed structure is serialized as JSON inside the model checkpoint
(the reference writes a binary blob, ``SaveNet``/``LoadNet``
``nnet_config.h:126-191``); JSON keeps the same information.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Dict, List, Sequence, Tuple

ConfigEntry = Tuple[str, str]

_LABEL_VEC_RE = re.compile(r"label_vec\[(\d+),(\d+)\)")


@dataclasses.dataclass
class LayerSpec:
    type_name: str                 # config layer type ("conv", "shared", ...)
    name: str                      # optional tag ("" if anonymous)
    primary: int                   # primary layer index if shared, else -1
    nindex_in: List[int]
    nindex_out: List[int]

    @property
    def is_self_loop(self) -> bool:
        return self.nindex_in == self.nindex_out

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(d: dict) -> "LayerSpec":
        return LayerSpec(**d)


class NetGraph:
    """Parsed network structure + per-layer / global config streams."""

    def __init__(self) -> None:
        self.node_names: List[str] = []
        self.node_name_map: Dict[str, int] = {}
        self.layers: List[LayerSpec] = []
        self.layer_name_map: Dict[str, int] = {}
        self.layercfg: List[List[ConfigEntry]] = []
        self.defcfg: List[ConfigEntry] = []
        self.input_shape: Tuple[int, int, int] = (0, 0, 0)  # (C, H, W)
        self.input_layout = "auto"  # auto: flat/NHWC by shape; seq: (N,T,D)
        self.extra_data_num = 0
        self.extra_shape: List[Tuple[int, int, int]] = []
        self.updater_type = "sgd"
        # label fields: name -> index into label_range
        self.label_name_map: Dict[str, int] = {"label": 0}
        self.label_range: List[Tuple[int, int]] = [(0, 1)]
        self._initialized = False

    # ------------------------------------------------------------------
    def configure(self, cfg: Sequence[ConfigEntry]) -> "NetGraph":
        """Parse an ordered global config stream (nnet_config.h:207-289).

        May be called again on a loaded structure: the layer lines are then
        validated against the stored graph instead of re-creating it.
        """
        self.defcfg = []
        self.layercfg = [[] for _ in self.layers]
        if not self.node_names:
            self._add_node("in")
        self.node_name_map.setdefault("0", 0)

        netcfg_mode = 0      # 0 outside, 1 inside netconfig, 2 after a layer line
        cfg_top_node = 0
        cfg_layer_index = 0

        for name, val in cfg:
            if name == "extra_data_num":
                num = int(val)
                for i in range(num):
                    nm = f"in_{i + 1}"
                    if nm not in self.node_name_map:
                        self._add_node(nm)
                self.extra_data_num = num
            if name.startswith("extra_data_shape["):
                x, y, z = (int(t) for t in val.split(","))
                self.extra_shape.append((x, y, z))
            if not self._initialized and name == "input_shape":
                parts = val.split(",")
                if len(parts) != 3:
                    raise ValueError(
                        "input_shape must be three comma-separated ints, e.g. 1,1,200"
                    )
                z, y, x = (int(p) for p in parts)
                self.input_shape = (z, y, x)
            if not self._initialized and name == "input_layout":
                if val not in ("auto", "seq"):
                    raise ValueError("input_layout must be auto or seq")
                self.input_layout = val
            if netcfg_mode != 2:
                self._set_global_param(name, val)
            if name == "netconfig" and val == "start":
                netcfg_mode = 1
            if name == "netconfig" and val == "end":
                netcfg_mode = 0
            if name.startswith("layer["):
                info = self._parse_layer_line(name, val, cfg_top_node, cfg_layer_index)
                netcfg_mode = 2
                if not self._initialized:
                    assert len(self.layers) == cfg_layer_index, "NetGraph inconsistent"
                    self.layers.append(info)
                    self.layercfg.append([])
                else:
                    if cfg_layer_index >= len(self.layers):
                        raise ValueError("config layer index exceeds stored structure")
                    if self.layers[cfg_layer_index] != info:
                        raise ValueError(
                            "config does not match existing network structure: "
                            f"layer {cfg_layer_index} is {self.layers[cfg_layer_index]}, "
                            f"config says {info}"
                        )
                cfg_top_node = (
                    info.nindex_out[0] if len(info.nindex_out) == 1 else -1
                )
                cfg_layer_index += 1
                continue
            if netcfg_mode == 2:
                if self.layers[cfg_layer_index - 1].type_name == "shared":
                    raise ValueError(
                        "do not set parameters on a shared layer; set them on the primary"
                    )
                self.layercfg[cfg_layer_index - 1].append((name, val))
            else:
                self.defcfg.append((name, val))
        self._initialized = True
        return self

    # ------------------------------------------------------------------
    def _add_node(self, name: str) -> int:
        idx = len(self.node_names)
        self.node_names.append(name)
        self.node_name_map[name] = idx
        return idx

    def _get_node(self, name: str, alloc_unknown: bool) -> int:
        if name in self.node_name_map:
            return self.node_name_map[name]
        if not alloc_unknown:
            raise ValueError(
                f"undefined node name {name!r}: a layer's input must be the "
                f"output of an earlier layer"
            )
        return self._add_node(name)

    def _set_global_param(self, name: str, val: str) -> None:
        if name == "updater":
            self.updater_type = val
        m = _LABEL_VEC_RE.fullmatch(name)
        if m:
            a, b = int(m.group(1)), int(m.group(2))
            self.label_range.append((a, b))
            self.label_name_map[val] = len(self.label_range) - 1

    def _parse_layer_line(
        self, name: str, val: str, top_node: int, cfg_layer_index: int
    ) -> LayerSpec:
        """Parse ``layer[...] = type[:tag]`` (nnet_config.h:303-360)."""
        body = name[len("layer["):]
        if not body.endswith("]"):
            raise ValueError(f"invalid layer format {name!r}")
        body = body[:-1]
        nindex_in: List[int] = []
        nindex_out: List[int] = []
        if body.startswith("+"):
            # layer[+k] / layer[+1:tag]
            if top_node < 0:
                raise ValueError(
                    "layer[+k] used after a layer with multiple outputs; "
                    "use layer[in->out] instead"
                )
            if ":" in body:
                inc_s, tag = body.split(":", 1)
                inc = int(inc_s[1:])
                nindex_in.append(top_node)
                nindex_out.append(self._get_node(tag, True))
            else:
                inc = int(body[1:])
                nindex_in.append(top_node)
                if inc == 0:
                    nindex_out.append(top_node)  # self-loop
                else:
                    nindex_out.append(self._get_node(f"!node-after-{top_node}", True))
        elif "->" in body:
            src, dst = body.split("->", 1)
            for t in src.split(","):
                nindex_in.append(self._get_node(t, False))
            for t in dst.split(","):
                nindex_out.append(self._get_node(t, True))
        else:
            raise ValueError(f"invalid layer format {name!r}")

        # value: "type" or "type:tag"
        if ":" in val:
            ltype, tag = val.split(":", 1)
        else:
            ltype, tag = val, ""
        spec = LayerSpec(ltype, "", -1, nindex_in, nindex_out)
        if ltype.startswith("share"):
            m = re.match(r"share[a-z]*\[([^\]]+)\]", ltype)
            if not m:
                raise ValueError(
                    "shared layer must specify the tag of the layer to share: shared[tag]"
                )
            s_tag = m.group(1)
            if s_tag not in self.layer_name_map:
                raise ValueError(f"shared layer tag {s_tag!r} not defined before")
            spec.type_name = "shared"
            spec.primary = self.layer_name_map[s_tag]
        elif tag:
            if tag in self.layer_name_map:
                if self.layer_name_map[tag] != cfg_layer_index:
                    raise ValueError(
                        f"layer name {tag!r} does not match the stored structure"
                    )
            else:
                self.layer_name_map[tag] = cfg_layer_index
            spec.name = tag
        return spec

    # ------------------------------------------------------------------
    @property
    def num_nodes(self) -> int:
        return len(self.node_names)

    def layer_index_of(self, name: str) -> int:
        if name not in self.layer_name_map:
            raise ValueError(f"unknown layer name {name!r}")
        return self.layer_name_map[name]

    def node_index_of(self, name: str) -> int:
        if name not in self.node_name_map:
            raise ValueError(f"unknown node name {name!r}")
        return self.node_name_map[name]

    # --- structure (de)serialization ----------------------------------
    def structure_to_json(self) -> str:
        return json.dumps(
            {
                "input_shape": list(self.input_shape),
                "input_layout": self.input_layout,
                "extra_data_num": self.extra_data_num,
                "extra_shape": [list(s) for s in self.extra_shape],
                "node_names": self.node_names,
                "layers": [l.to_json() for l in self.layers],
            }
        )

    @classmethod
    def structure_from_json(cls, s: str) -> "NetGraph":
        d = json.loads(s)
        g = cls()
        g.input_shape = tuple(d["input_shape"])
        g.input_layout = d.get("input_layout", "auto")
        g.extra_data_num = d["extra_data_num"]
        g.extra_shape = [tuple(x) for x in d["extra_shape"]]
        for nm in d["node_names"]:
            g._add_node(nm)
        g.layers = [LayerSpec.from_json(x) for x in d["layers"]]
        g.layercfg = [[] for _ in g.layers]
        for i, l in enumerate(g.layers):
            if l.name:
                g.layer_name_map[l.name] = i
        g._initialized = True
        return g
