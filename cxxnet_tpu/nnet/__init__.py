"""Trainer orchestration: graph parsing, functional net, trainer."""

from .graph import LayerSpec, NetGraph  # noqa: F401
from .net import FunctionalNet  # noqa: F401
