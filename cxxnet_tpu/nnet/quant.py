"""Post-training quantized export: plan, accuracy gate, artifact.

The serve-side half of ROADMAP item 3 (doc/performance.md "Quantized
inference"): a trained f32 checkpoint becomes an int8-weight serving
artifact in one gated step —

1. **plan** — every plain-path conv / fullc kernel is assigned ``int8``
   (per-output-channel symmetric scales, ``ops/quant.py``); convs on an
   opt-in algorithmic path (Winograd, space-to-depth) start at ``bf16``
   so the quantizer never silently overrides a measured kernel choice;
2. **gate** — the quantized model must agree with the f32 model on
   held-out data: top-1 agreement >= ``quant_min_agreement`` (default
   0.99) over ``quant_calib_batches`` eval batches (0 = the whole eval
   set).  While the gate fails, the int8 layer with the worst relative
   quantization error falls back to bf16 (2x instead of 4x) and the
   agreement is re-measured — the eval-gate ethos of the continuous
   loop's publisher applied to precision instead of fine-tuning;
3. **artifact** — on pass, the quantized model is written as
   ``<round>.quant.model`` beside its source through the same atomic
   write + CRC-manifest machinery as every checkpoint, with a ``quant``
   manifest field recording scheme / scales dtype / per-precision layer
   counts / measured agreement.  On reject NOTHING is written — the f32
   artifact keeps serving.

The artifact stores the int8 codes + f32 scales (and bf16 kernels as
tagged uint16 words — npz cannot represent ml_dtypes natively) in the
normal checkpoint container; ``NetTrainer.load_model`` recognizes the
header's ``quant`` block and serves it directly.  ``quant = int8`` at
serve time on a PLAIN checkpoint quantizes on load instead — ungated
(no eval data in the serving process), event-logged as such; use
``task=export_quant`` when the gate matters.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..obs import events as obs_events
from ..ops import quant as opsq
from ..utils import checkpoint as ckpt

__all__ = [
    "SCHEMES", "build_plan", "apply_plan", "top1_agreement",
    "export_quantized", "quant_artifact_path",
]

SCHEMES = ("int8", "bf16")


def quant_artifact_path(model_path: str) -> str:
    """``NNNN.model`` -> ``NNNN.quant.model`` (the sibling artifact the
    serving engine prefers under ``quant = int8``).  The ``.quant.``
    infix keeps it invisible to the plain ``*.model`` round discovery —
    an engine without the key can never accidentally serve codes."""
    if model_path.endswith(".quant.model"):
        return model_path
    if model_path.endswith(".model"):
        return model_path[:-len(".model")] + ".quant.model"
    return model_path + ".quant.model"


# ----------------------------------------------------------------------
# plan
def _layer_kinds(net) -> Dict[str, Tuple[str, object]]:
    """``param_key -> ("conv"|"fullc", layer)`` for quantizable layers:
    exactly the types the quantized forward dispatch handles."""
    from ..layers.conv import ConvolutionLayer
    from ..layers.linear import FullConnectLayer

    out: Dict[str, Tuple[str, object]] = {}
    for i, spec in enumerate(net.graph.layers):
        if spec.type_name == "shared":
            continue
        lay = net.layer_objs[i]
        key = net.param_key[i]
        if type(lay) is ConvolutionLayer:
            out[key] = ("conv", lay)
        elif type(lay) is FullConnectLayer:
            out[key] = ("fullc", lay)
    return out


def build_plan(trainer, scheme: str = "int8") -> Dict[str, str]:
    """``param_key -> "int8" | "bf16"`` for every quantizable layer of
    ``trainer``'s net.  ``scheme = "bf16"`` assigns bf16 everywhere (the
    2x straight-cast scheme, no scales, no gate sensitivity); ``int8``
    starts everything at int8 except convs that opted into an
    algorithmic rewrite path (``conv_wino`` / ``conv_s2d``) — the
    quantized apply runs the direct conv, so quantizing those would
    silently override a measured kernel choice."""
    if scheme not in SCHEMES:
        raise ValueError(f"quant scheme must be one of {SCHEMES}, "
                         f"got {scheme!r}")
    plan: Dict[str, str] = {}
    for key, (kind, lay) in _layer_kinds(trainer.net).items():
        if key not in (trainer.params or {}):
            continue
        if scheme == "bf16":
            plan[key] = "bf16"
        elif kind == "conv" and (lay.conv_wino or lay.conv_s2d):
            plan[key] = "bf16"
        else:
            plan[key] = "int8"
    return plan


def _out_axis(kind: str) -> int:
    return 3 if kind == "conv" else 0  # HWIO vs (nout, nin)


def apply_plan(trainer, plan: Dict[str, str], scheme: str = "int8",
               source_params=None) -> None:
    """Replace ``trainer``'s eligible kernels per ``plan`` (int8 codes +
    scales / bf16 cast), IN PLACE.  ``source_params`` (default: the
    trainer's current params) supplies the f32 masters — pass the
    reference trainer's params when re-applying a revised plan so codes
    are always quantized from the original weights, never from a prior
    quantization.  Marks the trainer inference-only
    (``quant_scheme``) and drops its jit cache."""
    kinds = _layer_kinds(trainer.net)
    src = source_params if source_params is not None else trainer.params
    newp = {}
    for key, tags in src.items():
        kind = plan.get(key)
        if kind is None or key not in kinds:
            newp[key] = dict(tags)
            continue
        entry = {t: v for t, v in tags.items() if t != "wmat"}
        w = np.asarray(tags["wmat"], np.float32)
        if kind == "int8":
            q, s = opsq.quantize_weight(w, _out_axis(kinds[key][0]))
            entry[opsq.QKEY] = jnp.asarray(q)
            entry[opsq.SKEY] = jnp.asarray(s)
        else:  # bf16 fallback
            entry["wmat"] = jnp.asarray(w, jnp.bfloat16)
        newp[key] = entry
    trainer.params = newp
    trainer.quant_scheme = scheme
    trainer.quant_plan = dict(plan)
    trainer._jit_cache.clear()


# ----------------------------------------------------------------------
# gate
def top1_agreement(tr_ref, tr_cand, eval_iter,
                   max_batches: int = 0) -> Tuple[float, int]:
    """``(agreement, rows)``: fraction of held-out instances on which
    the candidate's prediction (argmax / raw-scalar sign bucket — the
    trainer's own ``predict`` semantics) equals the reference's, over
    up to ``max_batches`` eval batches (0 = all)."""
    agree = 0
    total = 0
    batches = 0
    eval_iter.before_first()
    while eval_iter.next():
        batch = eval_iter.value()
        n = batch.batch_size - batch.num_batch_padd
        pr = np.asarray(tr_ref.predict(batch))[:n]
        pc = np.asarray(tr_cand.predict(batch))[:n]
        eq = pr.reshape(n, -1) == pc.reshape(n, -1)
        agree += int(eq.all(axis=1).sum())
        total += n
        batches += 1
        if max_batches and batches >= max_batches:
            break
    if total == 0:
        raise ValueError(
            "top1_agreement: the eval iterator yielded no rows — the "
            "agreement gate needs held-out data")
    return agree / total, total


def _error_ranking(trainer, plan: Dict[str, str]) -> List[Tuple[float, str]]:
    """Int8 layers by relative quantization error, worst first — the
    fallback order when the gate fails."""
    kinds = _layer_kinds(trainer.net)
    rank = []
    for key, kind in plan.items():
        if kind != "int8":
            continue
        w = trainer.params[key]["wmat"]
        rank.append((opsq.quant_error(w, _out_axis(kinds[key][0])), key))
    return sorted(rank, reverse=True)


# ----------------------------------------------------------------------
# export
def _strip_quant_cfg(cfg) -> list:
    """Drop ``quant`` keys: the exporter's trainers must load the f32
    masters verbatim (plans are applied explicitly here)."""
    return [(n, v) for n, v in cfg if n != "quant"]


def export_quantized(
    cfg,
    model_path: str,
    eval_iter=None,
    scheme: str = "int8",
    min_agreement: float = 0.99,
    calib_batches: int = 0,
    out_path: Optional[str] = None,
    silent: bool = True,
) -> dict:
    """The gated export step (``task=export_quant``).  Returns the
    verdict document; writes the artifact only when the gate passes.

    ``min_agreement = 0`` skips the gate (``eval_iter`` may then be
    None) — an explicit opt-out, for benches and offline pipelines that
    gate elsewhere."""
    from .trainer import NetTrainer

    cfg = _strip_quant_cfg(list(cfg))
    reason = ckpt.validate_checkpoint(model_path)
    if reason is not None:
        raise ckpt.CheckpointError(f"{model_path}: {reason}")

    def _load() -> NetTrainer:
        tr = NetTrainer()
        tr.set_params(cfg)
        tr.load_model(model_path)
        return tr

    ref = _load()
    if ref.quant_scheme:
        raise ValueError(
            f"{model_path} is already a quantized artifact "
            f"({ref.quant_scheme}) — export from the f32 checkpoint")
    cand = _load()
    plan = build_plan(ref, scheme)
    if not plan:
        raise ValueError(
            "no quantizable layers (conv/fullc) in this net — nothing "
            "to export")
    gate = min_agreement > 0
    if gate and eval_iter is None:
        raise ValueError(
            "export_quantized: the agreement gate needs an eval "
            "iterator (set quant_min_agreement=0 to export ungated)")
    ranking = _error_ranking(ref, plan)
    agreement, rows = 1.0, 0
    fallbacks: List[str] = []
    while True:
        apply_plan(cand, plan, scheme, source_params=ref.params)
        if not gate:
            break
        agreement, rows = top1_agreement(ref, cand, eval_iter,
                                         max_batches=calib_batches)
        if agreement >= min_agreement:
            break
        demote = next((key for _e, key in ranking
                       if plan.get(key) == "int8"), None)
        if demote is None:
            break  # every layer already bf16: the gate loses
        plan[demote] = "bf16"
        fallbacks.append(demote)
        if not silent:
            print(f"quant: agreement {agreement:.4f} < "
                  f"{min_agreement:g}; falling back {demote} to bf16",
                  flush=True)
    ok = (not gate) or agreement >= min_agreement
    actual, f32_equiv = opsq.weight_bytes(cand.params)
    n_int8 = sum(1 for v in plan.values() if v == "int8")
    n_bf16 = sum(1 for v in plan.values() if v == "bf16")
    verdict = {
        "ok": bool(ok),
        "scheme": scheme,
        "source": model_path,
        "agreement": (agreement if gate else None),
        "min_agreement": min_agreement,
        "gated": gate,
        "eval_rows": rows,
        "calib_batches": calib_batches,
        "layers": dict(plan),
        "int8_layers": n_int8,
        "bf16_layers": n_bf16,
        "fallbacks": fallbacks,
        "weight_bytes": actual,
        "weight_bytes_f32": f32_equiv,
        "bytes_ratio": (f32_equiv / actual) if actual else 0.0,
        "path": None,
    }
    if not ok:
        # reject: nothing reaches disk — the f32 artifact keeps serving
        obs_events.emit("quant.reject", source=model_path,
                        scheme=scheme, agreement=agreement,
                        min_agreement=min_agreement,
                        fallbacks=len(fallbacks))
        _count("rejected")
        if not silent:
            print(f"quant: REJECTED — agreement {agreement:.4f} < "
                  f"{min_agreement:g} even with every layer at bf16",
                  flush=True)
        return verdict
    path = out_path or quant_artifact_path(model_path)
    man = ckpt.read_manifest(model_path) or {}
    round_ = man.get("round")
    if round_ is None:
        round_ = ckpt.checkpoint_round(model_path)
    cand.round = round_ if round_ is not None else 0
    blob = cand.checkpoint_bytes()
    ckpt.write_checkpoint(
        path, blob, round_=round_, net_fp=cand.net_fp(),
        save_ustate=0, silent=silent,
        quant={
            "scheme": scheme,
            "scales_dtype": "float32",
            "int8_layers": n_int8,
            "bf16_layers": n_bf16,
            "agreement": (agreement if gate else None),
            "source_crc32": man.get("crc32"),
        },
    )
    verdict["path"] = path
    obs_events.emit("quant.export", source=model_path, path=path,
                    scheme=scheme,
                    agreement=(agreement if gate else None),
                    int8_layers=n_int8, bf16_layers=n_bf16,
                    bytes_ratio=verdict["bytes_ratio"])
    _count("published")
    if not silent:
        ag = f"{agreement:.4f}" if gate else "ungated"
        print(f"quant: exported {path} (scheme {scheme}, agreement "
              f"{ag}, {n_int8} int8 + {n_bf16} bf16 layers, "
              f"{verdict['bytes_ratio']:.2f}x smaller weights)",
              flush=True)
    return verdict


def _count(decision: str) -> None:
    from ..obs.registry import registry

    registry().counter(
        "quant_export_total",
        "Gated quantized exports by decision: published / rejected.",
        labelnames=("decision",),
    ).labels(decision=decision).inc()
