"""FunctionalNet: a parsed NetGraph compiled into pure JAX functions.

This replaces the reference's mutable ``NeuralNet`` engine
(``/root/reference/src/nnet/neural_net-inl.hpp``): instead of nodes that
double as activation/gradient storage and per-layer hand-written backprop,
the graph is executed as one pure function and ``jax.grad`` differentiates
the summed loss.  XLA sees the whole step and fuses across layer
boundaries — the TPU analog of mshadow's expression fusing, but global.

Semantics preserved:

* node 0 is the input; ``input_shape = C,H,W`` maps to a flat ``(N, W)``
  node when ``C == H == 1`` else an NHWC image node (the reference is
  NCHW; layout is the TPU-native transposition of the same data).
* layers are configured with the global defaults first, then their own
  section (``neural_net-inl.hpp:252-264``).
* self-loop loss layers transform their node in place (downstream sees
  probabilities) and contribute ``grad_scale / (batch_size *
  update_period) * L`` to the total loss
  (``loss_layer_base-inl.hpp:60-63``).
* shared layers reuse the primary layer's parameters.
* label fields: the batch label matrix is sliced by the ``label_vec[a,b)``
  ranges; each loss layer reads its ``target`` field.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..layers import Layer, LossLayer, create_layer
from ..layers.structure import SplitLayer
from .graph import NetGraph

ConfigEntry = Tuple[str, str]


def _opsq():
    """Lazy ``ops.quant`` accessor (keeps the quant helpers out of the
    hot import path for nets that never quantize)."""
    from ..ops import quant

    return quant


class FunctionalNet:
    """Executable form of a NetGraph."""

    def __init__(self, graph: NetGraph) -> None:
        self.graph = graph
        self.batch_size = 0
        self.update_period = 1
        self.compute_dtype = jnp.float32
        self.remat = 0
        # sibling-1x1 conv fusion is ON by default: it is mathematically
        # exact (see _sibling_1x1_groups) and measured +4.3% on GoogLeNet
        # b128 on the v5e chip; `fuse_1x1 = 0` opts out
        self.fuse_1x1 = 1
        self._fuse_cache = None
        # branch-embedding fusion (doc/performance.md "Conv
        # efficiency"): merge sibling odd-k stride-1 SAME convs (the
        # inception 3x3/5x5 branches) into ONE block-kernel conv — an
        # adequately-shaped GEMM for ~3.6x more MACs.  Exact (119->92
        # contractions on GoogLeNet).  Default -1 = AUTO: ON for
        # inference program builds (predict/extract/eval — the serve
        # engine's programs) on ACCELERATOR backends, where the trade
        # buys MXU shape; OFF on CPU, where the extra MACs are just
        # extra work (measured 0.14x predict throughput —
        # tools/wino_bf16_ab.py --bembed-only), and OFF for the train
        # step, whose on-chip A/B is still queued
        # (tools/googlenet_bisect.py bembed).  An explicit 0/1 pins
        # every build.
        self.conv_branch_embed = -1
        # the platform this net's programs actually TARGET (the dev=
        # mesh's platform, bound by the trainer after it builds the
        # mesh) — auto branch-embed keys on it, NOT on the process's
        # default backend: dev=cpu on a TPU host must stay unfused
        self.exec_backend: Optional[str] = None
        self._embed_cache = None
        # on-chip kernel library (ops/kernels/): auto | off | name list.
        # `auto` (default) follows the RECORDED per-backend verdicts in
        # ops/kernels/verdicts.json — a Pallas kernel runs only where a
        # committed promote from tools/kernel_ab.py says it pays, the
        # same discipline as conv_branch_embed=-1 above.  A name list
        # pins those kernels ON (interpret mode off-TPU: exact, slow —
        # the parity/test spelling).  Inference builds only: the Pallas
        # calls carry no custom vjp, so the train forward stays stock.
        self.kernel_lib = "auto"
        self._kernel_sel = None
        # instantiate layers (shared layers alias the primary instance)
        self.layer_objs: List[Layer] = []
        self.param_key: List[Optional[str]] = []  # params pytree key per layer
        for i, spec in enumerate(graph.layers):
            if spec.type_name == "shared":
                primary = self.layer_objs[spec.primary]
                self.layer_objs.append(primary)
                self.param_key.append(self.param_key[spec.primary])
                continue
            lay = create_layer(spec.type_name)
            if isinstance(lay, SplitLayer):
                lay.n_split = len(spec.nindex_out)
            self.layer_objs.append(lay)
            tag = spec.name if spec.name else spec.type_name
            self.param_key.append(f"l{i}_{tag}")
        self._configure_layers()
        self.node_shapes: List[Optional[Tuple[int, ...]]] = []
        # params kept in f32 even under mixed precision (norm layers,
        # whose math runs in f32 — a bf16 round-trip would only lose bits)
        from ..layers.conv import BatchNormLayer
        from ..layers.sequence import LayerNormLayer

        self._f32_param_keys = {
            self.param_key[i]
            for i, lay in enumerate(self.layer_objs)
            if isinstance(lay, (BatchNormLayer, LayerNormLayer))
        }
        # per-tag exemptions (e.g. pipe_transformer's stacked LN params)
        self._f32_tag_map = {
            self.param_key[i]: lay.f32_tags
            for i, lay in enumerate(self.layer_objs)
            if lay.f32_tags
        }

    # ------------------------------------------------------------------
    def _configure_layers(self) -> None:
        g = self.graph
        for name, val in g.defcfg:
            if name == "batch_size":
                self.batch_size = int(val)
            elif name == "update_period":
                self.update_period = int(val)
            elif name == "remat":
                # jax.checkpoint each layer: recompute activations in
                # backprop instead of keeping them in HBM (memory for
                # FLOPs — lets bigger batches fit per chip)
                self.remat = int(val)
            elif name == "fuse_1x1":
                # execute sibling 1x1 convs on one input node as ONE
                # concatenated conv (see _sibling_1x1_groups)
                self.fuse_1x1 = int(val)
            elif name == "conv_branch_embed":
                self.conv_branch_embed = int(val)
            elif name == "kernel_lib":
                from ..ops import kernels as _klib

                # canonicalize AND validate: a kernel-name typo must
                # fail the build, not silently serve the stock path
                self.kernel_lib = _klib.parse_mode(val)
                self._kernel_sel = None
            elif name == "compute_dtype":
                if val in ("bfloat16", "bf16"):
                    self.compute_dtype = jnp.bfloat16
                elif val in ("float32", "fp32"):
                    self.compute_dtype = jnp.float32
                else:
                    raise ValueError(
                        f"compute_dtype must be bfloat16 or float32, got {val!r}"
                    )
        for i, spec in enumerate(g.layers):
            if spec.type_name == "shared":
                continue
            lay = self.layer_objs[i]
            for name, val in g.defcfg:
                self._safe_set(lay, name, val)
            for name, val in g.layercfg[i]:
                self._safe_set(lay, name, val)

    @staticmethod
    def _safe_set(lay: Layer, name: str, val: str) -> None:
        """Layer ``set_param`` ignores unknown keys by design (the elif
        chains fall through silently), so any exception here is a real
        parse/value error on a key the layer *does* claim — propagate it.
        A config typo in layer scope must fail loudly, not vanish."""
        try:
            lay.set_param(name, val)
        except Exception as e:
            raise ValueError(
                f"layer {lay.__class__.__name__}: bad value for "
                f"{name!r} = {val!r}: {e}"
            ) from e

    # ------------------------------------------------------------------
    def input_node_shape(self, batch_size: int) -> Tuple[int, ...]:
        c, h, w = self.graph.input_shape
        if self.graph.input_layout == "seq":
            # sequence node: input_shape = 1,T,D -> (N, T, D)
            return (batch_size, h, w)
        if c == 1 and h == 1:
            return (batch_size, w)
        return (batch_size, h, w, c)

    def extra_node_shape(self, k: int, batch_size: int) -> Tuple[int, ...]:
        c, h, w = self.graph.extra_shape[k]
        if c == 1 and h == 1:
            return (batch_size, w)
        return (batch_size, h, w, c)

    def infer_shapes(self, batch_size: int) -> List[Tuple[int, ...]]:
        """Run shape inference over the DAG; returns per-node shapes."""
        g = self.graph
        shapes: List[Optional[Tuple[int, ...]]] = [None] * g.num_nodes
        shapes[0] = self.input_node_shape(batch_size)
        for k in range(g.extra_data_num):
            shapes[k + 1] = self.extra_node_shape(k, batch_size)
        for i, spec in enumerate(g.layers):
            lay = self.layer_objs[i]
            in_shapes = []
            for n in spec.nindex_in:
                if shapes[n] is None:
                    raise ValueError(
                        f"layer {i} ({spec.type_name}) input node "
                        f"{g.node_names[n]!r} has no shape yet"
                    )
                in_shapes.append(shapes[n])
            out_shapes = lay.infer_shape(in_shapes)
            if len(out_shapes) != len(spec.nindex_out):
                raise ValueError(
                    f"layer {i} ({spec.type_name}): produced {len(out_shapes)} "
                    f"outputs for {len(spec.nindex_out)} output nodes"
                )
            for n, s in zip(spec.nindex_out, out_shapes):
                shapes[n] = tuple(s)
        self.node_shapes = shapes
        return shapes  # type: ignore[return-value]

    # ------------------------------------------------------------------
    def init_aux(self, batch_size: int) -> Dict[str, dict]:
        """Non-gradient layer state (e.g. batch-norm running statistics
        with ``bn_eval = running``); empty dict when no layer carries any."""
        shapes = self.infer_shapes(batch_size)
        aux: Dict[str, dict] = {}
        for i, spec in enumerate(self.graph.layers):
            if spec.type_name == "shared":
                continue
            lay = self.layer_objs[i]
            if hasattr(lay, "init_aux"):
                st = lay.init_aux([shapes[n] for n in spec.nindex_in])
                if st:
                    aux[self.param_key[i]] = st
        return aux

    def init_params(self, key: jax.Array, batch_size: int) -> Dict[str, dict]:
        shapes = self.infer_shapes(batch_size)
        params: Dict[str, dict] = {}
        for i, spec in enumerate(self.graph.layers):
            if spec.type_name == "shared":
                continue
            lay = self.layer_objs[i]
            key, sub = jax.random.split(key)
            in_shapes = [shapes[n] for n in spec.nindex_in]
            p = lay.init_params(sub, in_shapes)
            if p:
                params[self.param_key[i]] = p
        return params

    # ------------------------------------------------------------------
    def _graph_versions(self):
        """Declaration-order dataflow scan shared by the fusion
        planners: per-node write counts, per-layer read keys
        ``(node, version-at-read)``, and ``writers[n][v]`` = the layer
        whose write created version ``v+1`` (version 0 = graph input).
        One implementation so the two planners can never disagree
        about graph provenance."""
        g = self.graph
        writes = [0] * g.num_nodes
        for spec in g.layers:
            for n in spec.nindex_out:
                writes[n] += 1
        version = [0] * g.num_nodes
        writers: Dict[int, List[int]] = {}
        in_keys: List[List[Tuple[int, int]]] = []
        for i, spec in enumerate(g.layers):
            in_keys.append([(n, version[n]) for n in spec.nindex_in])
            for n in spec.nindex_out:  # reads happen before writes
                writers.setdefault(n, []).append(i)
                version[n] += 1
        return writes, in_keys, writers

    def _sibling_1x1_groups(self):
        """Groups of distinct 1x1/s1/p0/ungrouped conv layers sharing one
        input node, to be executed as ONE concatenated conv.

        Inception blocks issue 3-4 narrow 1x1 convs on the same tensor
        (GoogLeNet: 16-192 output channels each); the MXU runs one wide
        GEMM far better than several narrow ones (a 128-lane systolic
        array is mostly idle on a 16-channel output), and XLA does not
        merge separate convolutions itself.  Concatenating the HWIO
        kernels on the O axis and splitting the output channels back is
        mathematically exact, and parameters stay per-layer — the
        checkpoint format, weight getters and updater keys are
        untouched.  Default on (measured +4.3% on GoogLeNet b128 v5e);
        ``fuse_1x1 = 0`` opts out.

        Returns ``(groups, member)``: leader layer index -> all member
        indices (declaration order), and member index -> leader.
        """
        if self._fuse_cache is not None:
            return self._fuse_cache
        from ..layers.conv import ConvolutionLayer

        # group key is (node, write-version at read time): a self-loop
        # layer (layer[a->a] = relu) WRITES the shared node between two
        # sibling declarations, so siblings across that write see
        # different values and must not fuse.  Fused members also run
        # EARLY (at the leader's position), so a member must be the sole
        # writer of its output node — otherwise the declaration-order
        # overwrite sequence changes
        writes, in_keys, _writers = self._graph_versions()
        by_input: Dict[Tuple[int, int, int], List[int]] = {}
        for i, spec in enumerate(self.graph.layers):
            is_candidate = False
            if spec.type_name != "shared":  # aliased params: plain path
                lay = self.layer_objs[i]
                if type(lay) is ConvolutionLayer:
                    p = lay.param
                    # any shared stride fuses (the key carries it): the
                    # reference-shaped nets issue stride-2 1x1 sibling
                    # pairs too — ResNet's stage-boundary blocks read
                    # one node with both the bottleneck-reduce and the
                    # projection-shortcut 1x1 s2 convs
                    is_candidate = (
                        (p.kernel_height, p.kernel_width,
                         p.pad_x, p.pad_y, p.num_group)
                        == (1, 1, 0, 0, 1)
                        and len(spec.nindex_in) == 1
                        and len(spec.nindex_out) == 1
                        and spec.nindex_out[0] != spec.nindex_in[0]
                        and writes[spec.nindex_out[0]] == 1
                    )
            if is_candidate:
                n, v = in_keys[i][0]
                by_input.setdefault((n, v, p.stride), []).append(i)
        groups: Dict[int, List[int]] = {}
        member: Dict[int, int] = {}
        for idxs in by_input.values():
            if len(idxs) < 2:
                continue
            groups[idxs[0]] = idxs
            for j in idxs:
                member[j] = idxs[0]
        self._fuse_cache = (groups, member)
        return self._fuse_cache

    @staticmethod
    def _apply_fused_1x1(stride: int, gparams: List[dict], x,
                         kernels=None):
        """One conv for the whole sibling group; per-member outputs.

        The group kernel is assembled by SCATTERING each member into a
        zeros block (``.at[].set``), NOT ``jnp.concatenate``: under a
        model-parallel mesh the member kernels arrive sharded on their
        output-channel axis, and this jaxlib's GSPMD partitioner
        miscompiles concatenate-along-the-sharded-axis feeding a
        convolution (silently wrong values, ~0.5 absolute on unit-scale
        activations; verified jaxlib 0.4.36 CPU, 2- and 4-way model
        axes).  The dynamic-update-slice lowering partitions correctly
        — bit-identical to the unfused path in the mp=1 case and within
        SPMD parity tolerance under TP (tests/test_parallel.py
        ``test_fuse_1x1_matches_under_mesh``)."""
        from jax import lax

        from ..ops import quant as opsq

        ws = [opsq.effective_wmat(d, x.dtype) for d in gparams]
        cin = ws[0].shape[2]
        nout = sum(w.shape[3] for w in ws)
        wk = jnp.zeros((1, 1, cin, nout), x.dtype)
        off = 0
        for w in ws:
            wk = wk.at[:, :, :, off:off + w.shape[3]].set(w)
            off += w.shape[3]
        if kernels is not None and kernels.active("conv_block", x=x,
                                                  wk=wk):
            # the fused Pallas GEMM: conv + every member's bias in one
            # epilogue.  Members without a bias get zeros (x + 0 == x),
            # so slicing the biased block equals per-member bias adds.
            from ..ops.kernels import conv_block as _kcb

            bias = (jnp.concatenate([
                (d["bias"].astype(x.dtype) if "bias" in d
                 else jnp.zeros((w.shape[3],), x.dtype))
                for d, w in zip(gparams, ws)])
                if any("bias" in d for d in gparams) else None)
            y = _kcb.conv1x1_block(x, wk, bias, stride=stride,
                                   interpret=kernels.interpret)
            outs = []
            off = 0
            for w in ws:
                outs.append(lax.slice_in_dim(
                    y, off, off + w.shape[3], axis=3))
                off += w.shape[3]
            return outs
        y = lax.conv_general_dilated(
            x, wk,
            window_strides=(stride, stride), padding=((0, 0), (0, 0)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        outs = []
        off = 0
        for d, w in zip(gparams, ws):
            part = lax.slice_in_dim(y, off, off + w.shape[3], axis=3)
            off += w.shape[3]
            if "bias" in d:
                part = part + d["bias"].astype(x.dtype)
            outs.append(part)
        return outs

    # ------------------------------------------------------------------
    # branch-embedding fusion (doc/performance.md "Conv efficiency"):
    # inception-style sibling branch convs (3x3 + 5x5, stride 1, SAME
    # padding) become ONE conv whose block kernel holds each member's
    # kernel center-embedded in its own (cin, cout) slice, zeros in the
    # cross-slices.  Exact: with SAME padding and stride 1, the k_max
    # conv of a center-embedded smaller kernel equals the smaller conv.
    # The MXU trades ~3.6x more MACs for one adequately-shaped GEMM per
    # module (K = k_max^2 * sum(cin), N = sum(cout)) — the cuDNN-style
    # algorithmic-rewrite analog, opt-in pending the on-chip A/B.

    # elementwise single-in/single-out layers a provenance walk may
    # step through: they preserve spatial dims, so two convs whose
    # walks meet at one (node, version) see identical (H, W)
    _EMBED_WALK_TYPES = frozenset({
        "relu", "sigmoid", "tanh", "softplus", "xelu", "insanity",
        "prelu", "bias", "batch_norm", "dropout",
    })

    def _branch_embed_plan(self):
        """Compute ``(items, groups)``: an execution plan for forward()
        — ``items`` is a list of ``("L", layer_idx)`` / ``("E",
        leader_idx)`` — plus ``leader -> member idxs``.

        Members of a group are odd-k (3/5/7) stride-1 SAME convs whose
        inputs trace back, through elementwise layers and 1x1/s1/p0
        convs, to the SAME (node, write-version) — the inception
        branch shape.  Because declaration order interleaves the
        branches (the 5x5 reduce sits between the 3x3 conv and the 5x5
        conv), the group executes at the LAST member's position and
        layers that consume member outputs inside that window are
        deferred to after the group; the reorder is only applied when
        every node written in the window is single-writer, which makes
        any dependency-respecting order equivalent."""
        if self._embed_cache is not None:
            return self._embed_cache
        from ..layers.conv import ConvolutionLayer

        g = self.graph
        L = len(g.layers)
        writes, in_keys, writers = self._graph_versions()

        def walkable(p: int) -> bool:
            ps = g.layers[p]
            if len(ps.nindex_in) != 1 or len(ps.nindex_out) != 1:
                return False
            if ps.type_name in self._EMBED_WALK_TYPES:
                return True
            if ps.type_name == "conv":
                lp = self.layer_objs[p].param
                return ((lp.kernel_height, lp.kernel_width, lp.stride,
                         lp.pad_y, lp.pad_x, lp.num_group)
                        == (1, 1, 1, 0, 0, 1))
            return False

        def root_of(i: int) -> Tuple[int, int]:
            n, v = in_keys[i][0]
            while v > 0:
                p = writers[n][v - 1]
                if not walkable(p):
                    break
                n, v = in_keys[p][0]
            return n, v

        by_root: Dict[Tuple[int, int], List[int]] = {}
        for i, spec in enumerate(g.layers):
            if spec.type_name == "shared":
                continue
            lay = self.layer_objs[i]
            if type(lay) is not ConvolutionLayer:
                continue
            p = lay.param
            if not (p.stride == 1 and p.num_group == 1
                    and p.kernel_height == p.kernel_width
                    and p.kernel_height in (3, 5, 7)
                    and p.pad_y == (p.kernel_height - 1) // 2
                    and p.pad_x == (p.kernel_width - 1) // 2
                    and len(spec.nindex_in) == 1
                    and len(spec.nindex_out) == 1
                    and spec.nindex_out[0] != spec.nindex_in[0]
                    and writes[spec.nindex_out[0]] == 1):
                continue
            by_root.setdefault(root_of(i), []).append(i)

        fuse_groups, _fuse_member = (
            self._sibling_1x1_groups() if self.fuse_1x1 else ({}, {})
        )
        key_counts: Dict[Optional[str], int] = {}
        for k in self.param_key:
            key_counts[k] = key_counts.get(k, 0) + 1
        groups: List[Tuple[List[int], List[int]]] = []  # (idxs, moved)
        for idxs in by_root.values():
            if len(idxs) < 2:
                continue
            idxs = sorted(idxs)
            first, last = idxs[0], idxs[-1]
            iset = set(idxs)
            dep_nodes: set = set()
            moved: List[int] = []
            for j in range(first, last + 1):
                sj = g.layers[j]
                if j in iset:
                    dep_nodes.update(sj.nindex_out)
                elif any(n in dep_nodes for n in sj.nindex_in):
                    moved.append(j)
                    dep_nodes.update(sj.nindex_out)
            ok = all(
                writes[n] == 1
                for j in range(first, last + 1)
                for n in g.layers[j].nindex_out
            ) and all(writes[in_keys[j][0][0]] <= 1 for j in idxs)
            # (<= 1 above: a member may read the never-written graph
            # input node directly — trivially stable under deferral)
            # a deferred 1x1-fuse leader would shift its whole sibling
            # group past consumers of the other members — skip
            ok = ok and not any(j in fuse_groups for j in moved)
            # a deferred SHARED STATEFUL layer (e.g. a shared batch_norm
            # chaining running stats) would execute after a later
            # occurrence of itself, reversing the documented state-chain
            # order — node dataflow alone can't see aux-state edges
            ok = ok and not any(
                hasattr(self.layer_objs[j], "apply_stateful")
                and key_counts[self.param_key[j]] > 1
                for j in moved
            )
            if ok:
                groups.append((idxs, moved))
        groups.sort(key=lambda t: t[0][0])

        if not groups:
            self._embed_cache = (None, {})
            return self._embed_cache
        items: List[Tuple[str, int]] = []
        gmap: Dict[int, List[int]] = {}
        pos = 0
        for idxs, moved in groups:
            first, last = idxs[0], idxs[-1]
            if first < pos:       # overlapping window: drop this group
                continue
            iset = set(idxs)
            mset = set(moved)
            items.extend(("L", j) for j in range(pos, first))
            items.extend(
                ("L", j) for j in range(first, last + 1)
                if j not in iset and j not in mset
            )
            items.append(("E", idxs[0]))
            items.extend(("L", j) for j in moved)
            gmap[idxs[0]] = idxs
            pos = last + 1
        items.extend(("L", j) for j in range(pos, L))
        self._embed_cache = (items, gmap)
        return self._embed_cache

    @staticmethod
    def _apply_branch_embed(gparams: List[dict], xs):
        """One block-kernel conv for the whole branch group; per-member
        outputs.  Member kernel/channel geometry comes from each
        ``wmat`` (HWIO) — static under trace."""
        from jax import lax

        if not all(xi.shape[:3] == xs[0].shape[:3] for xi in xs):
            # explicit raise (not assert — stripped under python -O): a
            # planner regression must surface as this message, not as an
            # opaque concatenate shape error downstream
            raise ValueError(
                "branch-embed members must share input spatial dims: "
                f"{[tuple(xi.shape) for xi in xs]}"
            )
        from ..ops import quant as opsq

        ws = [opsq.effective_wmat(d, xs[0].dtype) for d in gparams]
        kmax = max(w.shape[0] for w in ws)
        pad = (kmax - 1) // 2
        x = jnp.concatenate(xs, axis=3)
        C = sum(w.shape[2] for w in ws)
        O = sum(w.shape[3] for w in ws)
        wk = jnp.zeros((kmax, kmax, C, O), x.dtype)
        coff = ooff = 0
        for w in ws:
            k, _, cin, cout = w.shape
            d0 = (kmax - k) // 2
            wk = wk.at[d0:d0 + k, d0:d0 + k,
                       coff:coff + cin, ooff:ooff + cout].set(w)
            coff += cin
            ooff += cout
        y = lax.conv_general_dilated(
            x, wk, window_strides=(1, 1),
            padding=((pad, pad), (pad, pad)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        outs = []
        ooff = 0
        for w, d in zip(ws, gparams):
            part = lax.slice_in_dim(y, ooff, ooff + w.shape[3], axis=3)
            ooff += w.shape[3]
            if "bias" in d:
                part = part + d["bias"].astype(x.dtype)
            outs.append(part)
        return outs

    # ------------------------------------------------------------------
    def forward(
        self,
        params: Dict[str, dict],
        data: jnp.ndarray,
        *,
        labels: Optional[jnp.ndarray] = None,
        extras: Sequence[jnp.ndarray] = (),
        train: bool = False,
        rng: Optional[jax.Array] = None,
        step: Optional[jnp.ndarray] = None,
        aux: Optional[Dict[str, dict]] = None,
        return_aux: bool = False,
        sample_mask: Optional[jnp.ndarray] = None,
    ):
        """Execute the graph.

        Returns ``(node_values, total_scaled_loss)``.  ``labels`` is the
        batch label matrix ``(N, label_width)`` (may be None at predict
        time — loss is then 0 and loss layers only transform).

        ``sample_mask`` (N,) zero-weights padded rows of a short final
        train batch out of every loss term (see LossLayer.loss_masked).
        Masking is exact for row-independent nets; batch_norm's batch
        statistics still see the padded rows (set ``round_batch=1`` on the
        data iterator, or ``bn_eval=running``, when that matters).
        """
        g = self.graph
        cdt = self.compute_dtype
        if cdt != jnp.float32:
            params = self._cast_params(params)
            if not self._node0_wants_ints():
                # embedding nets keep raw token ids in f32 (exact to
                # 2^24); bf16 would corrupt ids above 256
                data = data.astype(cdt)
            extras = [e.astype(cdt) for e in extras]
        out_idx = self.out_node_index()
        # collect per-layer state updates when the caller threads aux in
        new_aux: Optional[Dict[str, dict]] = (
            dict(aux) if (aux is not None and return_aux) else None
        )
        nodes: List[Optional[jnp.ndarray]] = [None] * g.num_nodes
        nodes[0] = data
        for k, e in enumerate(extras):
            nodes[k + 1] = e
        total_loss = jnp.zeros((), jnp.float32)
        batch = self.batch_size if self.batch_size > 0 else data.shape[0]
        fuse_groups, fuse_member = (
            self._sibling_1x1_groups() if self.fuse_1x1 else ({}, {})
        )
        # Pallas kernel library: inference builds only (no custom vjp on
        # the kernel calls — the train forward must stay differentiable)
        kern_lib = None if train else self.bound_kernels()
        embed_items, embed_groups = (
            self._branch_embed_plan() if self.use_branch_embed(train)
            else (None, {})
        )
        items = (embed_items if embed_items is not None
                 else [("L", i) for i in range(len(g.layers))])
        for kind, i in items:
            spec = g.layers[i]
            if kind == "E":
                idxs = embed_groups[i]
                xs = [nodes[g.layers[j].nindex_in[0]] for j in idxs]
                if any(v is None for v in xs):
                    raise ValueError(
                        f"branch-embed group at layer {i}: unset input node")
                gparams = [params.get(self.param_key[j], {}) for j in idxs]
                run_f = (
                    jax.checkpoint(self._apply_branch_embed)
                    if (self.remat and train) else self._apply_branch_embed
                )
                for j, out in zip(idxs, run_f(gparams, xs)):
                    nodes[g.layers[j].nindex_out[0]] = out
                continue
            if i in fuse_member:
                if fuse_member[i] != i:
                    continue  # output produced by its group leader below
                idxs = fuse_groups[i]
                x = nodes[spec.nindex_in[0]]
                if x is None:
                    raise ValueError(f"layer {i}: unset input node")
                gparams = [params.get(self.param_key[j], {}) for j in idxs]
                # stride bound statically (shared by the whole group via
                # the fusion key); jax.checkpoint must not trace it
                fused = functools.partial(
                    self._apply_fused_1x1,
                    self.layer_objs[i].param.stride,
                    kernels=kern_lib,
                )
                run_f = (
                    jax.checkpoint(fused)
                    if (self.remat and train) else fused
                )
                for j, out in zip(idxs, run_f(gparams, x)):
                    nodes[g.layers[j].nindex_out[0]] = out
                continue
            lay = self.layer_objs[i]
            inputs = [nodes[n] for n in spec.nindex_in]
            if any(v is None for v in inputs):
                raise ValueError(f"layer {i}: unset input node")
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            if isinstance(lay, LossLayer):
                logits = inputs[0].astype(jnp.float32)
                if labels is not None:
                    field = self._label_field(labels, lay.target)
                    scale = lay.grad_scale / (batch * self.update_period)
                    total_loss = total_loss + scale * lay.loss_masked(
                        logits, field, sample_mask
                    )
                # transform is f32 math; only downcast if a downstream layer
                # consumes it — the terminal node goes to host metrics in f32
                out = lay.transform(logits)
                if spec.nindex_out[0] != out_idx:
                    out = out.astype(cdt)
                nodes[spec.nindex_out[0]] = out
            else:
                key = self.param_key[i]
                lparams = params.get(key, {})
                if _opsq().is_quantized(lparams):
                    # int8 entry: dequant-free apply (ops/quant.py) —
                    # conv/fullc only, by the exporter's construction
                    nodes[spec.nindex_out[0]] = self._apply_quant_layer(
                        lay, lparams, inputs, kernels=kern_lib
                    )
                    continue
                # shared stateful layers chain their state: a later
                # occurrence reads the state the earlier one produced
                if new_aux is not None:
                    lstate = new_aux.get(key)
                elif aux is not None:
                    lstate = aux.get(key)
                else:
                    lstate = None
                if lstate is not None and hasattr(lay, "apply_stateful"):
                    if self.remat and train:
                        # state outputs are non-differentiable, so
                        # checkpointing the stateful call is safe — a
                        # bn_eval=running net keeps activation recompute
                        def run_st(p, st, xs, lay=lay, lrng=lrng):
                            return lay.apply_stateful(
                                p, st, xs, train=True, rng=lrng, step=step
                            )

                        outs, new_state = jax.checkpoint(run_st)(
                            lparams, lstate, inputs
                        )
                    else:
                        outs, new_state = lay.apply_stateful(
                            lparams, lstate, inputs,
                            train=train, rng=lrng, step=step,
                        )
                    if new_aux is not None:
                        new_aux[key] = new_state
                elif self.remat and train:

                    def run(p, xs, lay=lay, lrng=lrng):
                        return lay.apply(
                            p, xs, train=True, rng=lrng, step=step
                        )

                    outs = jax.checkpoint(run)(lparams, inputs)
                else:
                    outs = lay.apply(
                        lparams, inputs, train=train, rng=lrng, step=step
                    )
                for n, v in zip(spec.nindex_out, outs):
                    nodes[n] = v
        if return_aux:
            return nodes, total_loss, (new_aux if new_aux is not None else {})
        return nodes, total_loss

    def use_branch_embed(self, train: bool,
                         backend: Optional[str] = None) -> bool:
        """Whether THIS program build fuses inception branches: the
        explicit conf value when set, else auto — on for inference
        builds (exact, fewer contractions) on accelerator backends,
        off on CPU (the block kernel's ~3.6x MACs only pay on the
        MXU; measured 0.14x CPU predict throughput), and off for the
        train step until its on-chip A/B lands (doc/performance.md).
        ``backend`` overrides the backend probe (tests)."""
        if self.conv_branch_embed >= 0:
            return bool(self.conv_branch_embed)
        if train:
            return False
        if backend is None:
            backend = self.exec_backend
        if backend is None:
            try:
                backend = jax.default_backend()
            except Exception:  # noqa: BLE001 - no backend: stay plain
                return False
        return backend != "cpu"

    def bound_kernels(self, backend: Optional[str] = None):
        """The kernel library's selector bound to this net's execution
        backend (``ops/kernels/``): what the forward dispatch sites
        consume.  Resolution mirrors ``use_branch_embed`` — the bound
        ``exec_backend`` wins, then the process default; ``backend``
        overrides both (tests)."""
        from ..ops import kernels as _klib

        if self._kernel_sel is None:
            self._kernel_sel = _klib.KernelSelector(self.kernel_lib)
        if backend is None:
            backend = self.exec_backend
        if backend is None:
            try:
                backend = jax.default_backend()
            except Exception:  # noqa: BLE001 - no backend: treat as cpu
                backend = "cpu"
        return self._kernel_sel.bind(backend)

    def _apply_quant_layer(self, lay, lparams, inputs, kernels=None):
        """Dispatch one int8-quantized layer (doc/performance.md
        "Quantized inference"): the compiled op consumes the RAW codes
        (the weight at rest stays int8) and the per-channel rescale is
        folded into the bias add.  The exporter only quantizes plain
        conv / fullc layers, so anything else here is a plan bug."""
        from ..layers.conv import ConvolutionLayer
        from ..layers.linear import FullConnectLayer

        q = _opsq()
        x = inputs[0]
        if type(lay) is ConvolutionLayer:
            p = lay.param
            return q.conv_apply_q(lparams, x, p.stride, p.pad_y, p.pad_x,
                                  groups=p.num_group, kernels=kernels)
        if type(lay) is FullConnectLayer:
            return q.fc_apply_q(lparams, x, kernels=kernels)
        raise ValueError(
            f"quantized params on unsupported layer "
            f"{type(lay).__name__} — the export plan only covers "
            "conv and fullc"
        )

    def _node0_wants_ints(self) -> bool:
        """True when any consumer of the data node (node 0) declares
        ``integer_input`` (the embedding layer) — keyed to the graph,
        not to declaration order.  If a net mixes an embedding with
        other node-0 consumers, data stays f32 for all of them
        (conservative: correct ids; the other branches simply compute
        their first layer in f32)."""
        for i, spec in enumerate(self.graph.layers):
            if 0 in spec.nindex_in and getattr(
                self.layer_objs[i], "integer_input", False
            ):
                return True
        return False

    def _cast_params(self, params: Dict[str, dict]) -> Dict[str, dict]:
        """Mixed precision: layer math (MXU) in the compute dtype, master
        params and loss in f32 — jax.grad through the cast yields f32
        grads.  Norm params are excluded (whole norm layers, plus any
        tags a layer lists in ``f32_tags``, e.g. pipe_transformer's
        stacked LN scales): their math runs in f32, so rounding
        gamma/beta through bf16 would only lose precision."""
        cdt = self.compute_dtype

        def cast(key, tags):
            if key in self._f32_param_keys:
                return tags
            if _opsq().QKEY in tags:
                # int8 entry: codes stay int8 (casting them would undo
                # the 4x), scales/bias stay f32 (the rescale fold runs
                # in the f32 accumulate)
                return tags
            keep = self._f32_tag_map.get(key, ())
            return {
                t: (v if t in keep else v.astype(cdt))
                for t, v in tags.items()
            }

        return {key: cast(key, tags) for key, tags in params.items()}

    def _label_field(self, labels: jnp.ndarray, target: str) -> jnp.ndarray:
        g = self.graph
        if target not in g.label_name_map:
            raise ValueError(f"LossLayer: unknown target={target!r}")
        a, b = g.label_range[g.label_name_map[target]]
        if labels.ndim == 1:
            labels = labels[:, None]
        return labels[:, a:b]

    # convenience -------------------------------------------------------
    def out_node_index(self) -> int:
        """The final node (prediction output), reference trainer semantics."""
        return self.graph.layers[-1].nindex_out[-1] if self.graph.layers else 0

    def loss_fn(
        self,
        params,
        data,
        labels,
        *,
        train: bool = True,
        rng=None,
        step=None,
        extras=(),
    ) -> jnp.ndarray:
        _, loss = self.forward(
            params, data, labels=labels, extras=extras, train=train, rng=rng, step=step
        )
        return loss
