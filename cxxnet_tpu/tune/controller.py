"""Telemetry-driven knob controller: the measure→decide→act loop.

PR 5/7 made every stage of the host pipeline, serve plane and device
plane measurable; this module is the first thing that *acts* on the
measurements (ROADMAP item 5 — the resource-aware-placement thesis of
PAPERS.md arxiv 1901.05803 applied at the host/device boundary).  A
:class:`KnobController` owns a set of runtime-adjustable
:class:`Knob`\\ s (decode-pool workers, queue depth, micro-batcher
size/timeout — anything with a live getter/setter) and hill-climbs them
toward the configuration that maximizes a throughput *objective*,
online, while the workload runs:

* **objective** — a callable returning a MONOTONIC cumulative work
  count (rows decoded, batch rows executed); the controller samples it
  every ``period_s`` and works on interval rates, so any registry
  counter (or a bench driver's own tally) plugs in directly.
* **hill climbing** — one knob moves at a time (round-robin), one
  multiplicative step in its preferred direction; the objective is
  re-measured over ``measure_ticks`` fresh intervals after
  ``settle_ticks`` transition intervals are discarded.
* **noise band** — a move only counts as better/worse when the new
  rate leaves the ``band`` envelope around the pre-move baseline
  (:func:`band_verdict` — the same orientation-aware banding
  ``tools/perf_guard.py`` applies to committed bench history).  Within
  the band the move is *reverted*, never kept: noise must not
  random-walk the knobs.
* **rollback on regression** — a move whose measured rate leaves the
  band downward is rolled back immediately and the knob's preferred
  direction flips.
* **hysteresis** — a knob whose both directions failed goes on a
  ``cooldown_ticks`` cooldown before it is probed again, so a noisy
  plateau costs two bounded probes per cooldown period instead of an
  oscillation.

Every decision is observable: ``tune.adjust`` / ``tune.rollback``
events, ``tune_effective{knob}`` gauges (the satellite contract: what
the controller chose, readable from ``/metricsz`` without the event
log), ``tune_adjustments_total{knob,action}`` /
``tune_rollbacks_total{knob}`` / ``tune_decisions_total{decision}``
counters and a ``tune_objective_rows_per_sec`` gauge.

Drive it manually with :meth:`KnobController.step_once` (tests, bench
harnesses) or as a daemon thread via :meth:`KnobController.start` —
the CLI starts one per task when the conf carries ``controller = 1``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs import events as obs_events
from ..obs.registry import registry as obs_registry

__all__ = [
    "band_verdict",
    "Knob",
    "KnobController",
    "TuneOptions",
    "options_from_cfg",
    "set_effective",
]

ConfigEntry = Tuple[str, str]


def band_verdict(value: float, baseline: Optional[float], band: float,
                 lower_is_better: bool = False) -> str:
    """``"better"`` / ``"worse"`` / ``"noise"`` for ``value`` against
    ``baseline`` with a fractional noise ``band``, orientation-aware.

    The shared banding primitive: the controller's keep/rollback
    verdicts and ``tools/perf_guard.py``'s regression verdicts are the
    same comparison, so a knob move the controller keeps is exactly one
    the perf sentinel would not flag.  A missing/zero baseline is
    ``"noise"`` — nothing can be concluded against it."""
    if baseline is None or baseline <= 0:
        return "noise"
    ratio = float(value) / float(baseline)
    if lower_is_better:
        if ratio > 1.0 + band:
            return "worse"
        if ratio < 1.0 - band:
            return "better"
    else:
        if ratio < 1.0 - band:
            return "worse"
        if ratio > 1.0 + band:
            return "better"
    return "noise"


class Knob:
    """One runtime-adjustable setting: a live getter/setter pair plus
    the move policy (bounds, multiplicative step, integer rounding).

    ``preferred`` / ``tried`` / ``cooldown`` are the controller's
    per-knob search state (direction memory, probed-this-plateau set,
    hysteresis countdown) — they live here so multiple controllers
    never share them."""

    def __init__(self, name: str, getter: Callable[[], float],
                 setter: Callable[[float], object], lo: float, hi: float,
                 scale: float = 2.0, integer: bool = True) -> None:
        if lo > hi:
            raise ValueError(f"knob {name}: lo {lo} > hi {hi}")
        if scale <= 1.0:
            raise ValueError(f"knob {name}: scale must be > 1")
        self.name = name
        self._get = getter
        self._set = setter
        self.lo = lo
        self.hi = hi
        self.scale = float(scale)
        self.integer = bool(integer)
        self.preferred = +1          # last direction that helped
        self.tried: set = set()      # directions probed on this plateau
        self.cooldown = 0            # decision cycles to sit out

    def read(self) -> float:
        v = self._get()
        return int(v) if self.integer else float(v)

    def apply(self, value: float) -> None:
        self._set(int(value) if self.integer else float(value))
        set_effective(self.name, value)

    def propose(self, direction: int) -> Optional[float]:
        """The next value one step in ``direction`` (+1 up / -1 down),
        clamped to the bounds; None when already pinned there."""
        cur = self.read()
        nxt = cur * self.scale if direction > 0 else cur / self.scale
        if self.integer:
            nxt = int(round(nxt))
            # a multiplicative step must always move an integer knob
            if direction > 0 and nxt <= cur:
                nxt = int(cur) + 1
            elif direction < 0 and nxt >= cur:
                nxt = int(cur) - 1
        nxt = min(self.hi, max(self.lo, nxt))
        if self.integer:
            nxt = int(round(nxt))
        return None if nxt == cur else nxt


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


_EFFECTIVE_LOCK = threading.Lock()
_EFFECTIVE_GAUGE = None


def set_effective(knob: str, value: float) -> None:
    """Publish a knob's current effective value as
    ``tune_effective{knob=...}`` — set by every runtime setter (engine,
    pipeline, controller), so operators see what is live even when no
    controller runs."""
    global _EFFECTIVE_GAUGE
    with _EFFECTIVE_LOCK:
        if _EFFECTIVE_GAUGE is None:
            _EFFECTIVE_GAUGE = obs_registry().gauge(
                "tune_effective",
                "Current effective value of a runtime-adjustable knob.",
                labelnames=("knob",))
        g = _EFFECTIVE_GAUGE
    g.labels(knob=knob).set(float(value))


class TuneOptions:
    """Parsed ``controller`` / ``tune_*`` config keys (doc/conf.md)."""

    def __init__(self) -> None:
        self.enabled = 0
        self.period_s = 1.0
        self.band = 0.1
        self.measure_ticks = 2
        self.settle_ticks = 1
        self.cooldown_ticks = 6
        self.targets = "auto"   # auto | comma list of pipeline,batcher

    def wants(self, target: str) -> bool:
        if self.targets.strip() in ("", "auto"):
            return True
        return target in [t.strip() for t in self.targets.split(",")]


def options_from_cfg(cfg: Sequence[ConfigEntry]) -> TuneOptions:
    opt = TuneOptions()
    for name, val in cfg:
        if name == "controller":
            opt.enabled = int(val)
        elif name == "tune_period_s":
            opt.period_s = max(0.05, float(val))
        elif name == "tune_band":
            opt.band = max(0.0, float(val))
        elif name == "tune_measure_ticks":
            opt.measure_ticks = max(1, int(val))
        elif name == "tune_settle_ticks":
            opt.settle_ticks = max(0, int(val))
        elif name == "tune_cooldown_ticks":
            opt.cooldown_ticks = max(0, int(val))
        elif name == "tune_targets":
            opt.targets = val
    return opt


class KnobController:
    """Hill-climb a set of :class:`Knob`\\ s against a throughput
    objective (see the module docstring for the algorithm).

    ``objective()`` must return a monotonic cumulative work count; the
    controller differentiates it per tick.  ``on_tick`` (optional) runs
    at the top of every tick on the controller thread — the serve
    engine hangs its speculative bucket prewarm there.  Exceptions in
    either are swallowed after one logged event: a broken probe must
    never take down the workload it tunes."""

    def __init__(self, objective: Callable[[], float],
                 knobs: Sequence[Knob], period_s: float = 1.0,
                 band: float = 0.1, measure_ticks: int = 2,
                 settle_ticks: int = 1, cooldown_ticks: int = 6,
                 name: str = "tune",
                 on_tick: Optional[Callable[[], object]] = None) -> None:
        if not knobs:
            raise ValueError("KnobController needs at least one knob")
        self._objective = objective
        self.knobs: List[Knob] = list(knobs)
        self.period_s = float(period_s)
        self.band = float(band)
        self.measure_ticks = max(1, int(measure_ticks))
        self.settle_ticks = max(0, int(settle_ticks))
        self.cooldown_ticks = max(0, int(cooldown_ticks))
        self.name = name
        self._on_tick = on_tick
        self._phase = "baseline"     # baseline | settle | measure
        self._window: List[float] = []
        self._baseline: Optional[float] = None
        self._active: Optional[Tuple[Knob, float, float, int]] = None
        self._idx = 0
        self._settle_left = 0
        self._prev_sample: Optional[Tuple[float, float]] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.ticks = 0
        reg = obs_registry()
        self._rate_gauge = reg.gauge(
            "tune_objective_rows_per_sec",
            "Interval rate of the controller's work objective.",
            labelnames=("controller",))
        self._ticks_total = reg.counter(
            "tune_ticks_total", "Controller evaluation ticks.",
            labelnames=("controller",))
        self._adjustments = reg.counter(
            "tune_adjustments_total",
            "Knob moves applied, by knob and direction.",
            labelnames=("knob", "action"))
        self._rollbacks = reg.counter(
            "tune_rollbacks_total",
            "Knob moves rolled back after a measured regression.",
            labelnames=("knob",))
        self._decisions = reg.counter(
            "tune_decisions_total",
            "Concluded move verdicts: keep / rollback / revert.",
            labelnames=("decision",))
        for k in self.knobs:
            set_effective(k.name, k.read())

    # ------------------------------------------------------------------
    def _rate(self, now: float) -> Optional[float]:
        work = float(self._objective())
        prev, self._prev_sample = self._prev_sample, (now, work)
        if prev is None:
            return None
        dt = now - prev[0]
        if dt <= 0:
            return None
        return max(0.0, work - prev[1]) / dt

    def step_once(self, now: Optional[float] = None) -> Dict[str, object]:
        """One controller tick (serialized; the thread and manual
        drivers may interleave).  Returns the decision taken, for tests
        and bench harnesses."""
        with self._lock:
            return self._step_locked(now)

    def _step_locked(self, now: Optional[float]) -> Dict[str, object]:
        self.ticks += 1
        self._ticks_total.labels(controller=self.name).inc()
        if self._on_tick is not None:
            try:
                self._on_tick()
            except Exception as e:  # noqa: BLE001 - probe must not kill us
                obs_events.log_exception_once(
                    f"tune.on_tick.{self.name}", e, kind="tune.error")
        # the tick timestamp is taken AFTER on_tick: a slow hook (the
        # prewarm's XLA compile can take seconds) must count inside the
        # interval, or the work accrued during it gets divided by the
        # short nominal period and inflates the measured rate — enough
        # to make a regressing probe look like an improvement
        if now is None:
            now = time.monotonic()
        try:
            rate = self._rate(now)
        except Exception as e:  # noqa: BLE001 - objective broke; idle
            obs_events.log_exception_once(
                f"tune.objective.{self.name}", e, kind="tune.error")
            return {"action": "error"}
        if rate is None:
            return {"action": "prime"}
        self._rate_gauge.labels(controller=self.name).set(rate)
        if self._phase == "settle":
            self._settle_left -= 1
            if self._settle_left <= 0:
                self._phase = "measure" if self._active else "baseline"
                self._window = []
            return {"action": "settle", "rate": rate}
        self._window.append(rate)
        if len(self._window) < self.measure_ticks:
            return {"action": "collect", "rate": rate}
        value = _median(self._window)
        self._window = []
        if self._phase == "baseline":
            self._baseline = value
            return self._begin_move(value)
        return self._conclude(value)

    # ------------------------------------------------------------------
    def _pick(self) -> Tuple[Optional[Knob], int, Optional[float]]:
        n = len(self.knobs)
        for off in range(n):
            k = self.knobs[(self._idx + off) % n]
            if k.cooldown > 0:
                continue
            for d in (k.preferred, -k.preferred):
                if d in k.tried:
                    continue
                target = k.propose(d)
                if target is not None:
                    self._idx = (self._idx + off) % n
                    return k, d, target
        return None, 0, None

    def _begin_move(self, baseline: float) -> Dict[str, object]:
        knob, direction, target = self._pick()
        if knob is None:
            self._tick_cooldowns()
            return {"action": "idle", "baseline": baseline}
        prev = knob.read()
        try:
            knob.apply(target)
        except Exception as e:  # noqa: BLE001 - a broken setter sits out
            obs_events.log_exception_once(
                f"tune.apply.{knob.name}", e, kind="tune.error")
            knob.cooldown = max(1, self.cooldown_ticks)
            return {"action": "error", "knob": knob.name}
        action = "up" if direction > 0 else "down"
        self._adjustments.labels(knob=knob.name, action=action).inc()
        obs_events.emit("tune.adjust", controller=self.name,
                        knob=knob.name, prev=prev, to=target,
                        direction=action, baseline=baseline)
        self._active = (knob, prev, target, direction)
        self._phase = "settle" if self.settle_ticks else "measure"
        self._settle_left = self.settle_ticks
        return {"action": "adjust", "knob": knob.name, "prev": prev,
                "to": target, "baseline": baseline}

    def _conclude(self, candidate: float) -> Dict[str, object]:
        knob, prev, target, direction = self._active
        self._active = None
        self._phase = "baseline"
        verdict = band_verdict(candidate, self._baseline, self.band)
        out: Dict[str, object] = {
            "knob": knob.name, "baseline": self._baseline,
            "candidate": candidate, "prev": prev, "to": target,
        }
        if verdict == "better":
            # keep and keep climbing this knob in this direction; the
            # just-measured candidate doubles as the next baseline, so
            # a climb costs one settle+measure per rung, not two
            knob.preferred = direction
            knob.tried.clear()
            self._decisions.labels(decision="keep").inc()
            self._baseline = candidate
            out["action"] = "keep"
            self._tick_cooldowns()
            out["next"] = self._begin_move(candidate)["action"]
            return out
        elif verdict == "worse":
            self._apply_guarded(knob, prev)
            knob.preferred = -direction
            knob.tried.add(direction)
            self._rollbacks.labels(knob=knob.name).inc()
            self._decisions.labels(decision="rollback").inc()
            obs_events.emit("tune.rollback", controller=self.name,
                            knob=knob.name, prev=prev, to=target,
                            baseline=self._baseline, candidate=candidate)
            self._finish_knob(knob)
            out["action"] = "rollback"
        else:
            # within the noise band: revert, never keep — noise must
            # not random-walk the knobs (the hysteresis contract)
            self._apply_guarded(knob, prev)
            knob.tried.add(direction)
            self._decisions.labels(decision="revert").inc()
            self._finish_knob(knob)
            out["action"] = "revert"
        self._tick_cooldowns()
        return out

    def _apply_guarded(self, knob: Knob, value: float) -> None:
        """Restore a knob, swallowing setter failures: a rollback that
        raises must neither kill the tick thread nor leave the knob
        silently cooling at the degraded probe value unreported."""
        try:
            knob.apply(value)
        except Exception as e:  # noqa: BLE001 - tuning stays alive
            obs_events.log_exception_once(
                f"tune.restore.{knob.name}", e, kind="tune.error")
            knob.cooldown = max(knob.cooldown, self.cooldown_ticks)

    def _finish_knob(self, knob: Knob) -> None:
        exhausted = all(
            d in knob.tried or knob.propose(d) is None for d in (1, -1)
        )
        if exhausted:
            knob.cooldown = self.cooldown_ticks
            knob.tried.clear()
        self._idx = (self._idx + 1) % len(self.knobs)

    def _tick_cooldowns(self) -> None:
        for k in self.knobs:
            if k.cooldown > 0:
                k.cooldown -= 1

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """Live introspection (bench verdicts, ``/statsz``-style)."""
        with self._lock:
            return {
                "controller": self.name,
                "phase": self._phase,
                "ticks": self.ticks,
                "baseline": self._baseline,
                "knobs": {k.name: k.read() for k in self.knobs},
                "cooldowns": {k.name: k.cooldown for k in self.knobs},
            }

    def start(self) -> "KnobController":
        """Start the background tick thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"cxxnet-tune-{self.name}", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.step_once()
            except Exception as e:  # noqa: BLE001 - the tick thread
                # must survive any single broken tick; the workload it
                # tunes keeps running either way
                obs_events.log_exception_once(
                    f"tune.tick.{self.name}", e, kind="tune.error")

    def stop(self) -> None:
        """Stop the tick thread and ROLL BACK any probe that was
        applied but never measured — otherwise a stop() landing between
        adjust and conclude would leave a deliberately-degraded probe
        value as the 'chosen' configuration (and snapshot() would
        report it as such to the autotune verdicts)."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        self._thread = None
        with self._lock:
            active, self._active = self._active, None
            self._phase = "baseline"
            self._window = []
        if active is not None:
            knob, prev, target, _direction = active
            try:
                knob.apply(prev)
            except Exception as e:  # noqa: BLE001 - best-effort restore
                obs_events.log_exception_once(
                    f"tune.stop_restore.{knob.name}", e, kind="tune.error")
            obs_events.emit("tune.abort_probe", controller=self.name,
                            knob=knob.name, probe=target, restored=prev)
