"""Self-tuning runtime: telemetry-driven knob controller.

``controller = 1`` in a conf arms a background
:class:`~cxxnet_tpu.tune.controller.KnobController` for the task —
hill-climbing the runtime-adjustable knobs (decode-pool workers/window
for train, micro-batcher size/timeout + speculative bucket prewarm for
serve) toward the balance point where the host pipeline and the device
step fully overlap.  See ``doc/performance.md`` (Self-tuning runtime)
and ``doc/conf.md`` (``tune_*`` keys).
"""

from .controller import (
    Knob,
    KnobController,
    TuneOptions,
    band_verdict,
    options_from_cfg,
    set_effective,
)
from .targets import batcher_knobs, find_pipeline, pipeline_knobs

__all__ = [
    "Knob",
    "KnobController",
    "TuneOptions",
    "band_verdict",
    "options_from_cfg",
    "set_effective",
    "batcher_knobs",
    "find_pipeline",
    "pipeline_knobs",
]
