"""Knob factories: bind the controller to the tunable subsystems.

The controller itself is generic (``controller.py``); this module knows
where the live knobs actually live — the decode pool's worker/window
resize API (``io/pipeline.py``), the serve engine's micro-batcher
setters (``serve/engine.py``) — and what sane bounds look like on the
current host.  Imports of io/serve stay inside the factory functions so
``cxxnet_tpu.tune`` itself remains import-cheap for every layer.
"""

from __future__ import annotations

import os
from typing import List, Optional

from .controller import Knob

__all__ = ["find_pipeline", "pipeline_knobs", "batcher_knobs",
           "tenant_round_knobs"]


def find_pipeline(it):
    """Walk an iterator chain (``.base`` / ``.aug`` links) down to its
    :class:`~cxxnet_tpu.io.pipeline.ParallelAugmentIterator`, or None
    when the chain has no parallel decode stage (csv/synthetic/...)."""
    from ..io.pipeline import ParallelAugmentIterator

    seen = set()
    while it is not None and id(it) not in seen:
        if isinstance(it, ParallelAugmentIterator):
            return it
        seen.add(id(it))
        it = getattr(it, "base", None) or getattr(it, "aug", None)
    return None


def pipeline_knobs(pipe, max_workers: Optional[int] = None) -> List[Knob]:
    """Decode-pool knobs over one ``ParallelAugmentIterator``:
    ``num_decode_workers`` (live pool resize; serial chains grow a pool
    at the next epoch boundary) and ``decode_queue_depth`` (in-flight
    chunk window, applied immediately)."""
    cpu = os.cpu_count() or 2
    hi = int(max_workers) if max_workers else max(4, 2 * cpu)
    return [
        Knob("num_decode_workers",
             getter=lambda: max(1, pipe.num_workers),
             setter=pipe.request_workers,
             lo=1, hi=hi),
        Knob("decode_queue_depth",
             getter=lambda: max(1, pipe.queue_depth),
             setter=pipe.set_queue_depth,
             lo=1, hi=64),
    ]


def batcher_knobs(engine) -> List[Knob]:
    """Micro-batcher knobs over one serve :class:`Engine`:
    ``max_batch_size`` (prewarmed before it applies, so the first
    coalesced batch of a new bucket never stalls on a compile) and
    ``batch_timeout_ms`` (live).  The engine's configured
    ``max_batch_size`` is the hard ceiling — it is also the request-
    size validation cap and the largest compiled bucket."""
    return [
        Knob("max_batch_size",
             getter=lambda: engine.batcher.max_batch_size,
             setter=engine.set_max_batch_size,
             lo=1, hi=engine.max_batch_size),
        Knob("batch_timeout_ms",
             getter=lambda: engine.batcher.batch_timeout * 1e3,
             setter=engine.set_batch_timeout_ms,
             lo=0.25, hi=50.0, integer=False),
    ]


def tenant_round_knobs(loops, max_rounds: int = 8) -> List[Knob]:
    """One knob per tenant loop: its fine-tune ``rounds_per_cycle``
    (live setter — the next cycle reads the new value).  These are the
    units the multi-tenant arbiter trades against the shared device
    pool (``loop/tenant.py``): more rounds for a tenant whose extra
    passes keep turning into published improvements, fewer for one
    whose feedback has gone stale."""
    return [
        Knob(f"tenant_rounds:{loop.name or i}",
             getter=(lambda lp=loop: lp.rounds_per_cycle),
             setter=(lambda v, lp=loop: lp.set_rounds_per_cycle(v)),
             lo=1, hi=max(2, int(max_rounds)))
        for i, loop in enumerate(loops)
    ]
