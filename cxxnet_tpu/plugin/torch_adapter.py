"""A graph layer hosting a ``torch.nn.Module`` (CPU) inside the JAX net.

Parity: ``CaffeLayer`` (``/root/reference/src/plugin/caffe_adapter-inl.hpp``)
— blob-for-node data marshalling, foreign params exposed through the weight
visitor as flat ``blob%d`` tags, train/eval phase switching.  Config:

    layer[a->b] = torch:name
      torch_op = torch.nn.Conv2d(3, 8, 3, padding=1)

``torch_op`` is parsed as a whitelisted ``torch.nn.*`` constructor call
(AST-validated, literal arguments only — never ``eval``-uated).  The
module's parameters are pulled into the JAX param pytree (tags ``blob0``,
``blob1``, …) so updaters/checkpoints treat them like any other weights;
forward and backward run under ``jax.pure_callback`` with torch autograd
supplying the VJP.  NHWC node data is marshalled to torch's NCHW and back.

This is a correctness harness, not a fast path: every call round-trips
host memory, exactly like the reference plugin's extra blob copies.
"""

from __future__ import annotations

import ast
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..layers.base import Layer, Params, Shape, register


def _build_torch_expr(expr: str):
    """Construct the module described by ``torch_op`` WITHOUT ``eval``.

    Configs are untrusted input (they get downloaded and shared), so the
    expression grammar is a strict whitelist validated on the AST:

    * calls whose callee is a dotted path rooted at ``torch.nn`` (nested
      calls allowed, e.g. ``torch.nn.Sequential(torch.nn.ReLU())``),
    * literal arguments: numbers, strings, booleans, ``None``, tuples/
      lists of literals, unary minus.

    Anything else — attribute chains escaping ``torch.nn``, subscripts,
    lambdas, comprehensions, dunder tricks — raises ``ValueError``.
    """
    import torch

    def build(node: ast.expr):
        if isinstance(node, ast.Call):
            path = _dotted_path(node.func)
            if not path or path[:2] != ["torch", "nn"] or len(path) < 3:
                raise ValueError(
                    "torch_op: only torch.nn.* constructors are allowed, "
                    f"got {'.'.join(path) if path else ast.dump(node.func)}"
                )
            obj = torch.nn
            for name in path[2:]:
                if name.startswith("_"):
                    raise ValueError(f"torch_op: private attribute {name!r}")
                obj = getattr(obj, name)
            args = [literal(a) for a in node.args]
            kwargs = {}
            for kw in node.keywords:
                if kw.arg is None:
                    raise ValueError("torch_op: **kwargs not allowed")
                kwargs[kw.arg] = literal(kw.value)
            return obj(*args, **kwargs)
        raise ValueError(
            f"torch_op: expected a torch.nn.* call, got {ast.dump(node)}"
        )

    def literal(node: ast.expr):
        if isinstance(node, ast.Call):
            return build(node)  # nested module, e.g. inside Sequential
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            v = literal(node.operand)
            if isinstance(v, (int, float)):
                return -v
        if isinstance(node, (ast.Tuple, ast.List)):
            vals = [literal(e) for e in node.elts]
            return tuple(vals) if isinstance(node, ast.Tuple) else vals
        raise ValueError(
            f"torch_op: argument must be a literal, got {ast.dump(node)}"
        )

    def _dotted_path(node: ast.expr):
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return parts[::-1]
        return None

    tree = ast.parse(expr, mode="eval")
    return build(tree.body)


def _to_torch_layout(x: np.ndarray) -> np.ndarray:
    if x.ndim == 4:
        return np.transpose(x, (0, 3, 1, 2))  # NHWC -> NCHW
    return x


def _from_torch_layout(x: np.ndarray) -> np.ndarray:
    if x.ndim == 4:
        return np.transpose(x, (0, 2, 3, 1))  # NCHW -> NHWC
    return x


@register
class TorchAdapterLayer(Layer):
    type_name = "torch"

    def __init__(self) -> None:
        super().__init__()
        self.torch_op = ""
        self._module = None
        self._pshapes: List[tuple] = []
        self._out_shape: Shape = ()

    def set_param(self, name: str, val: str) -> None:
        if name == "torch_op":
            self.torch_op = val
        else:
            super().set_param(name, val)

    # -- module construction -------------------------------------------
    def _build(self):
        if self._module is None:
            if not self.torch_op:
                raise ValueError("torch layer: must set torch_op")
            self._module = _build_torch_expr(self.torch_op).cpu().float()
            self._pshapes = [
                tuple(p.shape) for p in self._module.parameters()
            ]
        return self._module

    def infer_shape(self, in_shapes: Sequence[Shape]) -> List[Shape]:
        self._check_arity(in_shapes, 1)
        import torch

        mod = self._build()
        x = torch.zeros(*_to_torch_layout(np.zeros(in_shapes[0], np.float32)).shape)
        with torch.no_grad():
            y = mod(x)
        self._out_shape = _from_torch_layout(y.numpy()).shape
        return [tuple(self._out_shape)]

    def init_params(self, key, in_shapes) -> Params:
        mod = self._build()
        # foreign params exposed as blob%d, the reference visitor's tags
        return {
            f"blob{i}": jnp.asarray(p.detach().numpy())
            for i, p in enumerate(mod.parameters())
        }

    # -- forward/backward through pure_callback ------------------------
    def _run_torch(self, xs, need_grads: bool, train_mode: bool):
        """Run the module with the phase from the graph's ``train`` flag.

        The backward pass *recomputes* the forward under torch autograd, so
        the torch RNG is re-seeded deterministically before every run —
        stochastic modules (Dropout) then draw the same mask in the fwd
        call and the bwd recomputation. Stateful eval statistics
        (BatchNorm running stats) update on both runs; like the reference
        caffe adapter, this layer is a correctness harness, not a
        production path.
        """
        import torch

        mod = self._build()
        x_np, *p_np = xs
        with torch.no_grad():
            for p, v in zip(mod.parameters(), p_np):
                p.copy_(torch.from_numpy(np.asarray(v)))
        mod.train(train_mode)
        torch.manual_seed(0)
        xt = torch.from_numpy(_to_torch_layout(np.asarray(x_np)))
        if not need_grads:
            with torch.no_grad():
                y = mod(xt)
            return _from_torch_layout(y.numpy())
        xt.requires_grad_(True)
        y = mod(xt)
        return y, xt

    def apply(self, params, inputs, *, train=False, rng=None, step=None):
        ptags = sorted(params, key=lambda t: int(t[4:]))
        pvals = [params[t] for t in ptags]
        x = inputs[0]
        out_dtype = x.dtype
        out_sd = jax.ShapeDtypeStruct(self._out_shape, jnp.float32)

        @jax.custom_vjp
        def torch_apply(x, *ps):
            return jax.pure_callback(
                lambda *a: np.asarray(
                    self._run_torch(
                        [v.astype(np.float32) for v in a], False, train
                    ),
                    np.float32,
                ),
                out_sd, x, *ps,
            )

        def fwd(x, *ps):
            return torch_apply(x, *ps), (x, ps)

        def bwd(res, g):
            x, ps = res

            def run_bwd(*a):
                import torch

                g_np, x_np, *p_np = a
                y, xt = self._run_torch([x_np, *p_np], True, train)
                gt = torch.from_numpy(
                    _to_torch_layout(np.asarray(g_np, np.float32))
                )
                mod = self._module
                grads = torch.autograd.grad(
                    y, [xt] + list(mod.parameters()), grad_outputs=gt
                )
                dx = _from_torch_layout(grads[0].numpy()).astype(np.float32)
                return (dx,) + tuple(
                    gp.numpy().astype(np.float32) for gp in grads[1:]
                )

            shapes = (jax.ShapeDtypeStruct(np.shape(x), jnp.float32),) + tuple(
                jax.ShapeDtypeStruct(s, jnp.float32) for s in self._pshapes
            )
            outs = jax.pure_callback(
                run_bwd, shapes,
                g.astype(jnp.float32), x.astype(jnp.float32),
                *[p.astype(jnp.float32) for p in ps],
            )
            return tuple(outs)

        torch_apply.defvjp(fwd, bwd)
        y = torch_apply(
            x.astype(jnp.float32), *[p.astype(jnp.float32) for p in pvals]
        )
        return [y.astype(out_dtype)]
