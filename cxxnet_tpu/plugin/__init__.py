"""Foreign-framework layer adapters (off the hot path).

Parity: the reference's Caffe adapter plugin
(``/root/reference/src/plugin/caffe_adapter-inl.hpp``) — a layer that
hosts another framework's implementation "to allow some correct
comparisons": it existed chiefly as the trusted slave in ``pairtest``
differential runs (SURVEY §4.1).  The equivalent foreign framework in
this image is CPU torch; :mod:`torch_adapter` wraps a ``torch.nn.Module``
as a graph layer via ``jax.pure_callback`` so it slots into the same
pairtest discipline.  Like the reference plugin it is opt-in and costs
extra host↔device copies by design.
"""

from .torch_adapter import TorchAdapterLayer  # noqa: F401
