"""cxxnet-tpu: a TPU-native, config-driven convolutional network trainer.

A brand-new JAX/XLA/pjit framework with the capabilities of the cxxnet
reference (``/root/reference``): ``.conf``-file driven layer graphs, a full
CNN layer zoo, SGD/NAG/Adam updaters with learning-rate schedules, a
composable threaded input pipeline, round-based checkpointing, multi-metric
evaluation, and data parallelism over a TPU device mesh in place of the
reference's multi-GPU parameter server.
"""

__version__ = "0.1.0"

from . import config  # noqa: F401
