"""cxxnet-tpu: a TPU-native, config-driven convolutional network trainer.

A brand-new JAX/XLA/pjit framework with the capabilities of the cxxnet
reference (``/root/reference``): ``.conf``-file driven layer graphs, a full
CNN layer zoo, SGD/NAG/Adam updaters with learning-rate schedules, a
composable threaded input pipeline, round-based checkpointing, multi-metric
evaluation, and data parallelism over a TPU device mesh in place of the
reference's multi-GPU parameter server.
"""

__version__ = "0.1.0"

from . import config  # noqa: F401

# DataIter / Net / train pull in the trainer (and therefore JAX); keep the
# package import light for IO-only consumers (tools/im2bin.py) by resolving
# them lazily (PEP 562).
_WRAPPER_EXPORTS = ("DataIter", "Net", "train")


def __getattr__(name):
    if name in _WRAPPER_EXPORTS:
        from . import wrapper

        return getattr(wrapper, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_WRAPPER_EXPORTS))
