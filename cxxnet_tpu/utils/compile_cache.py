"""Persistent XLA compilation cache (``compile_cache_dir``).

Every jitted program in this framework — the fused train step, the
``update_scan`` body, eval/predict programs, the serving engine's
shape-bucket cache entries — is re-compiled from scratch on process
start.  On the v5e AOT runtime a GoogLeNet scan step alone costs ~47 s
of XLA time (doc/performance.md), so a restart, a preemption resume, or
a serve reload stalls exactly that long before the first step runs.

Setting ``compile_cache_dir = <dir>`` (global config key, any task)
points JAX's persistent compilation cache at an on-disk directory:
compiled executables are keyed by (HLO, compile options, backend) and
reloaded on later runs, so warm restarts skip XLA entirely.  The
thresholds are dropped to zero — this framework's programs are few and
large, so caching everything is strictly better than re-jitting.

The cache directory is shared safely between concurrent processes
(JAX writes entries atomically), and a stale entry is just a miss:
an XLA/jaxlib upgrade changes the cache key, never loads wrong code.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

_enabled_dir: Optional[str] = None


def enabled_dir() -> Optional[str]:
    """The directory the cache was pointed at, or None."""
    return _enabled_dir


def enable(path: str, silent: bool = True) -> bool:
    """Point JAX's persistent compilation cache at ``path`` (created if
    missing).  Idempotent; returns True when newly enabled.  Must run
    before the programs it should serve are compiled — config parsing
    order guarantees that for the CLI and the serving engine."""
    global _enabled_dir
    if not path:
        return False
    path = os.path.abspath(os.path.expanduser(path))
    if _enabled_dir == path:
        return False
    os.makedirs(path, exist_ok=True)
    import jax

    # KNOWN SHARP EDGE (jaxlib 0.4.3x, root-caused in PR 8): enabling
    # the cache MID-PROCESS — after donated-buffer programs (the fused
    # train step) have already compiled — intermittently corrupts
    # subsequent re-jitted programs: silent numeric garbage or a glibc
    # SIGSEGV/Abort inside batched_device_put.  This was tier-1's
    # multi-file flake (a test enabled the cache mid-suite; every
    # later trainer rebuild re-jitted through it).  The CLI and the
    # serving engine enable the cache BEFORE any jit (config order
    # guarantees it), which is verified safe; anything else gets a
    # loud warning instead of a latent heisenbug.
    try:
        from jax._src import xla_bridge as _xb

        mid_process = bool(getattr(_xb, "_backends", None))
    except Exception:  # pragma: no cover - jax internals moved
        mid_process = False
    if mid_process:
        from ..obs import events as obs_events

        obs_events.emit("compile_cache.mid_process_enable", dir=path)
        print(
            "WARNING: compile_cache enabled after a JAX backend was "
            "already initialized; on jaxlib 0.4.3x re-jitting donated "
            "programs through a mid-process-enabled cache can corrupt "
            "results or crash — enable compile_cache_dir before the "
            "first jit (the CLI/serve engine order)", flush=True,
        )
    jax.config.update("jax_compilation_cache_dir", path)
    for knob, val in (
        # cache every program no matter how small/fast to compile —
        # the program count here is tiny and restart latency is the
        # thing being bought
        ("jax_persistent_cache_min_entry_size_bytes", -1),
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
    ):
        try:
            jax.config.update(knob, val)
        except Exception:  # pragma: no cover - older jax: defaults apply
            pass
    try:
        # jax initializes the cache backend lazily ONCE; if anything
        # compiled before this point (cache disabled then), the dir
        # update alone would never take effect in this process
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # pragma: no cover - internal API moved
        pass
    _enabled_dir = path
    if not silent:
        print(f"compile cache: persistent XLA cache at {path}", flush=True)
    return True


def configure(cfg: Sequence[Tuple[str, str]], silent: bool = True) -> bool:
    """Scan an ordered config stream for ``compile_cache_dir`` (last
    one wins) and enable it.  No-op without the key."""
    path = ""
    for name, val in cfg or ():
        if name == "compile_cache_dir":
            path = val
    return enable(path, silent=silent) if path else False
