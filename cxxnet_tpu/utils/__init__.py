"""Utility modules: metrics, timing, fault-tolerant checkpointing."""

from .checkpoint import (  # noqa: F401
    CheckpointError,
    DivergenceError,
    PreemptionHandler,
    atomic_write_bytes,
    find_latest_valid,
    retry_io,
    validate_checkpoint,
)
from .metric import MetricSet, create_metric  # noqa: F401
