"""Utility modules: metrics, timing, checkpointing, fault injection."""

from .checkpoint import (  # noqa: F401
    CheckpointError,
    DivergenceError,
    PreemptionHandler,
    atomic_write_bytes,
    find_latest_valid,
    retry_io,
    validate_checkpoint,
)
from .faults import (  # noqa: F401
    BadDataError,
    BadRecordBudget,
    CircuitBreaker,
    FaultInjector,
    InjectedCorruption,
    InjectedFault,
    RetryPolicy,
    Watchdog,
    WatchdogError,
    fault_point,
)
from .metric import MetricSet, create_metric  # noqa: F401
