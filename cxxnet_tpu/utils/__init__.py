"""Utility modules: metrics, timing."""

from .metric import MetricSet, create_metric  # noqa: F401
