"""Recording/injecting disk-I/O layer for the durable writers.

Every writer whose crash-safety the framework *claims* — checkpoint
atomic writes + manifests + the publish pointer (``utils/checkpoint.py``),
feedback-log pages + ``.commit`` sidecars + ``CursorFile``
(``loop/feedback_log.py``), the retention boundary + unlinks
(``loop/retention.py``), the event log (``obs/events.py``) and
telemetry.jsonl (``cli.py``) — routes its file ops through this module.
That buys three things at one choke point:

1. **One fsync contract.**  :func:`write_atomic` is THE atomic-replace
   helper (temp file in the same dir, fsync, ``os.replace``, dir fsync);
   :func:`append_bytes` / :class:`AppendHandle` are THE append paths.
   A durable writer cannot fork its own half-correct variant.
2. **Recording.**  Under :func:`recording`, every op (create / write /
   fsync / fsync_dir / rename / unlink / truncate) is journaled with its
   payload bytes and a stable file id that survives renames.
   ``tools/crash_audit.py`` replays every prefix of that journal into a
   fresh directory — the crash-state simulator below — and runs the real
   recovery paths against each state.
3. **Runtime fault injection.**  The ``enospc`` / ``short`` / ``ioerror``
   kinds of ``utils/faults.py`` fire inside the write path, so disk-full
   behavior is testable in-process.  Any ENOSPC (injected or real)
   increments ``disk_full_total{site}`` and emits a deduped
   ``diskio.disk_full`` event — the loud alert the operator pages on.

Crash-state model (the **ext4-reorder model**):

* ``flush`` variant — every executed op landed (crash after a clean
  sync; the most generous state).
* ``sync`` variant — only *durable* ops survive: a data write/truncate
  survives iff a later ``fsync`` of the same file id precedes the crash
  point; a create/rename survives iff a later dir fsync of its directory
  OR a later file fsync of the same file id precedes it (ext4 semantics:
  fsync of a file also commits its directory entry); an unlink survives
  only via a later dir fsync.  Un-fsynced tails vanish, un-fsynced
  renames roll back, un-fsynced unlinks resurrect files (orphans).
* ``torn`` variant — like ``flush``, but the last not-yet-fsynced write
  is cut at a configurable byte count (a torn tail mid-write).

Writers here only create/append/truncate/replace/unlink — never seek
backwards to overwrite — so per-file data loss in the ``sync`` variant
is always a tail truncation, exactly like delayed allocation on ext4.

Deterministic kill hook: ``CXXNET_DISKIO_KILL_AT=substr[:nth]`` SIGKILLs
the process immediately before executing the nth durable op whose path
contains ``substr`` — how ``tools/elastic_kill.py`` lands kill -9 inside
a consensus checkpoint write sequence, deterministically.

See ``doc/robustness.md`` ("Crash-consistency contract") for the audited
invariant table.
"""

from __future__ import annotations

import contextlib
import errno
import hashlib
import os
import signal
import threading
from typing import Dict, Iterator, List, Optional, Tuple

__all__ = [
    "Recorder",
    "recording",
    "recorder",
    "mark",
    "write_atomic",
    "append_bytes",
    "open_append",
    "AppendHandle",
    "replace",
    "unlink",
    "truncate",
    "fsync_dir",
    "simulate_crash",
    "write_tree",
    "tree_fingerprint",
    "marks_before",
    "VARIANTS",
    "KILL_ENV",
]

KILL_ENV = "CXXNET_DISKIO_KILL_AT"
VARIANTS = ("flush", "sync", "torn")

_LOCK = threading.RLock()
_REC: Optional["Recorder"] = None

# ----------------------------------------------------------------------
# recording


class Recorder:
    """Journal of durable-I/O ops under one root directory.

    Ops are dicts: ``{"op": <kind>, "fid": <int|None>, "path": <rel>,
    ...}`` with payload bytes attached to writes.  File ids are assigned
    at create time and FOLLOW renames, so the simulator can tell "the
    bytes fsynced into the temp file" from "the name they were published
    under".  Paths outside the root are executed but not recorded.
    """

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        self.ops: List[dict] = []
        self._fids: Dict[str, int] = {}
        self._sizes: Dict[int, int] = {}
        self._next_fid = 0

    # -- path / fid bookkeeping ---------------------------------------
    def rel(self, path: str) -> Optional[str]:
        p = os.path.abspath(path)
        if p == self.root:
            return "."
        if not p.startswith(self.root + os.sep):
            return None
        return os.path.relpath(p, self.root)

    def _new_fid(self, rel: str) -> int:
        fid = self._next_fid
        self._next_fid += 1
        self._fids[rel] = fid
        self._sizes[fid] = 0
        return fid

    def note(self, op: dict) -> None:
        self.ops.append(op)

    # -- op emitters (called by the primitives, under _LOCK) ----------
    def ensure_known(self, path: str) -> Optional[int]:
        """Make ``path`` traceable.  A file that predates the recording
        is snapshotted as a durable create+write+fsync prologue tagged
        ``snap`` — the simulator applies snapshot ops at EVERY crash
        point (the file existed before any recorded op, so no crash can
        unmake it), even though they are journaled lazily mid-stream."""
        rel = self.rel(path)
        if rel is None:
            return None
        if rel in self._fids:
            return self._fids[rel]
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            data = f.read()
        fid = self._new_fid(rel)
        self.note({"op": "create", "fid": fid, "path": rel, "snap": True})
        self.note({"op": "write", "fid": fid, "path": rel,
                   "off": 0, "data": data, "snap": True})
        self.note({"op": "fsync", "fid": fid, "path": rel, "snap": True})
        self.note({"op": "fsync_dir", "path": os.path.dirname(rel),
                   "snap": True})
        self._sizes[fid] = len(data)
        return fid

    def note_create(self, path: str) -> Optional[int]:
        rel = self.rel(path)
        if rel is None:
            return None
        fid = self._new_fid(rel)
        self.note({"op": "create", "fid": fid, "path": rel})
        return fid

    def note_write(self, path: str, data: bytes) -> None:
        rel = self.rel(path)
        if rel is None:
            return
        fid = self._fids.get(rel)
        if fid is None:
            fid = self._new_fid(rel)
            self.note({"op": "create", "fid": fid, "path": rel})
        off = self._sizes.get(fid, 0)
        self.note({"op": "write", "fid": fid, "path": rel,
                   "off": off, "data": bytes(data)})
        self._sizes[fid] = off + len(data)

    def note_fsync(self, path: str) -> None:
        rel = self.rel(path)
        if rel is None or rel not in self._fids:
            return
        self.note({"op": "fsync", "fid": self._fids[rel], "path": rel})

    def note_fsync_dir(self, dirpath: str) -> None:
        rel = self.rel(dirpath)
        if rel is None:
            return
        self.note({"op": "fsync_dir", "path": "" if rel == "." else rel})

    def note_truncate(self, path: str, size: int) -> None:
        rel = self.rel(path)
        if rel is None or rel not in self._fids:
            return
        fid = self._fids[rel]
        self.note({"op": "truncate", "fid": fid, "path": rel,
                   "size": int(size)})
        self._sizes[fid] = min(self._sizes.get(fid, 0), int(size))

    def note_replace(self, src: str, dst: str) -> None:
        rsrc, rdst = self.rel(src), self.rel(dst)
        if rsrc is None or rdst is None:
            return
        fid = self._fids.pop(rsrc, None)
        if fid is None:
            return
        self._fids[rdst] = fid
        self.note({"op": "rename", "fid": fid, "src": rsrc, "dst": rdst})

    def note_unlink(self, path: str) -> None:
        rel = self.rel(path)
        if rel is None:
            return
        fid = self._fids.pop(rel, None)
        self.note({"op": "unlink", "fid": fid, "path": rel})

    def note_mark(self, name: str, **fields) -> None:
        op = {"op": "mark", "name": name}
        op.update(fields)
        self.note(op)


def recorder() -> Optional[Recorder]:
    return _REC


@contextlib.contextmanager
def recording(root: str) -> Iterator[Recorder]:
    """Record every diskio op under ``root`` for the scope's duration.
    One active recording per process (the audit is single-threaded)."""
    global _REC
    rec = Recorder(root)
    with _LOCK:
        if _REC is not None:
            raise RuntimeError("diskio: recording already active")
        _REC = rec
    try:
        yield rec
    finally:
        with _LOCK:
            _REC = None


def mark(name: str, **fields) -> None:
    """Record an invariant obligation (e.g. "seqs [a,b) committed",
    "round 5 durable").  No-op outside a recording; the auditor asserts
    every mark before the crash point against the recovered tree."""
    with _LOCK:
        if _REC is not None:
            _REC.note_mark(name, **fields)


# ----------------------------------------------------------------------
# kill hook + disk-full accounting

_kill_spec: Optional[Tuple[str, int]] = None
_kill_parsed = False
_kill_seen = 0


def _maybe_kill(path: str) -> None:
    """SIGKILL self just before the nth matching durable op — the
    deterministic stand-in for "the machine died mid-write"."""
    global _kill_spec, _kill_parsed, _kill_seen
    if not _kill_parsed:
        _kill_parsed = True
        raw = os.environ.get(KILL_ENV, "")
        if raw:
            sub, _, nth = raw.partition(":")
            try:
                _kill_spec = (sub, max(1, int(nth)) if nth else 1)
            except ValueError:
                _kill_spec = (sub, 1)
    if _kill_spec is None:
        return
    sub, nth = _kill_spec
    if sub and sub in path:
        _kill_seen += 1
        if _kill_seen >= nth:
            os.kill(os.getpid(), signal.SIGKILL)


def count_disk_full(site: Optional[str], path: str) -> None:
    """ENOSPC (injected or real) is a page-the-operator event: count it
    and emit one deduped event per site.  Never raises."""
    try:
        from ..obs.registry import registry as obs_registry
        obs_registry().counter(
            "disk_full_total",
            "ENOSPC hits on durable writers (injected or real).",
            labelnames=("site",),
        ).labels(site=site or "unspecified").inc()
    except Exception:
        pass
    try:
        from ..obs import events as obs_events
        obs_events.emit_once(f"diskio.disk_full:{site or 'unspecified'}",
                             "diskio.disk_full", site=site or "unspecified",
                             path=path)
    except Exception:
        pass


def _inject(site: Optional[str], payload: Optional[bytes], path: str):
    """Run the fault point for ``site``.  Returns the byte count a short
    write should keep before re-raising, or None for a full write.
    ENOSPC-class injections are counted before they propagate."""
    if not site:
        return None
    from . import faults
    try:
        faults.fault_point(site, payload)
    except faults.InjectedShortWrite as e:
        count_disk_full(site, path)
        return e
    except OSError as e:
        if getattr(e, "errno", None) == errno.ENOSPC:
            count_disk_full(site, path)
        raise
    return None


# ----------------------------------------------------------------------
# primitives


def fsync_dir(dirpath: str) -> None:
    """Best-effort directory fsync (makes renames/creates durable on
    POSIX; not supported everywhere, hence best-effort)."""
    try:
        dfd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    except OSError:
        return
    finally:
        os.close(dfd)
    with _LOCK:
        if _REC is not None:
            _REC.note_fsync_dir(dirpath)


def write_atomic(path: str, data: bytes, fsync: bool = True,
                 site: Optional[str] = "checkpoint.write") -> None:
    """THE atomic publish: temp file in the same directory, write, fsync,
    ``os.replace``, dir fsync.  A crash at any point leaves either the
    old file or the new file — never a torn one (the temp may linger;
    every consumer ignores ``.*.tmp.*`` names).

    A short-write injection lands its prefix in the TEMP file and
    aborts — the torn bytes never reach ``path`` (the abort-atomically
    contract for checkpoint writes under disk-full).
    """
    path = os.path.abspath(path)
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    data = bytes(data)
    short = _inject(site, data, path)
    tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    try:
        with _LOCK:
            if _REC is not None:
                _REC.ensure_known(path)
                _REC.note_create(tmp)
        _maybe_kill(tmp)
        with open(tmp, "wb") as f:
            part = data if short is None else data[: short.keep]
            try:
                f.write(part)
            except OSError as e:
                if getattr(e, "errno", None) == errno.ENOSPC:
                    count_disk_full(site, path)
                raise
            with _LOCK:
                if _REC is not None:
                    _REC.note_write(tmp, part)
            if short is not None:
                f.flush()
                raise short
            if fsync:
                f.flush()
                os.fsync(f.fileno())
                with _LOCK:
                    if _REC is not None:
                        _REC.note_fsync(tmp)
        _maybe_kill(path)
        os.replace(tmp, path)
        with _LOCK:
            if _REC is not None:
                _REC.note_replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            with contextlib.suppress(OSError):
                os.unlink(tmp)
                with _LOCK:
                    if _REC is not None:
                        _REC.note_unlink(tmp)
    if fsync:
        fsync_dir(d)


class AppendHandle:
    """A recorded append-only file handle (the feedback-log shard file).

    Supports exactly what the durable writers need: append, flush,
    fsync, tell, truncate-then-continue.  Fault sites fire per-write so
    ENOSPC/short-write hit individual pages, not whole sessions.
    """

    def __init__(self, path: str) -> None:
        self.path = os.path.abspath(path)
        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        existed = os.path.exists(self.path)
        with _LOCK:
            if _REC is not None:
                if existed:
                    _REC.ensure_known(self.path)
        self._f = open(self.path, "ab")
        with _LOCK:
            if _REC is not None and not existed:
                _REC.note_create(self.path)

    def write(self, data: bytes, site: Optional[str] = None) -> int:
        data = bytes(data)
        short = _inject(site, data, self.path)
        part = data if short is None else data[: short.keep]
        _maybe_kill(self.path)
        if part:
            try:
                self._f.write(part)
            except OSError as e:
                if getattr(e, "errno", None) == errno.ENOSPC:
                    count_disk_full(site, self.path)
                raise
            with _LOCK:
                if _REC is not None:
                    _REC.note_write(self.path, part)
        if short is not None:
            # land the torn tail on disk before failing, like a real
            # ENOSPC partway through a page
            self._f.flush()
            raise short
        return len(data)

    def flush(self) -> None:
        self._f.flush()

    def fsync(self) -> None:
        self._f.flush()
        _maybe_kill(self.path)
        os.fsync(self._f.fileno())
        with _LOCK:
            if _REC is not None:
                _REC.note_fsync(self.path)

    def tell(self) -> int:
        return self._f.tell()

    def seek(self, pos: int) -> None:
        self._f.seek(pos)

    def truncate(self, size: int) -> None:
        self._f.truncate(size)
        with _LOCK:
            if _REC is not None:
                _REC.note_truncate(self.path, size)

    def fileno(self) -> int:
        return self._f.fileno()

    @property
    def closed(self) -> bool:
        return self._f.closed

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


def open_append(path: str) -> AppendHandle:
    return AppendHandle(path)


def append_bytes(path: str, data: bytes, fsync: bool = False,
                 site: Optional[str] = None) -> None:
    """One-shot recorded append (event-log lines, telemetry records,
    ``.commit`` sidecar entries)."""
    h = AppendHandle(path)
    try:
        h.write(data, site=site)
        h.flush()
        if fsync:
            h.fsync()
    finally:
        h.close()


def replace(src: str, dst: str) -> None:
    """Recorded ``os.replace`` (event-log rotation)."""
    _maybe_kill(dst)
    os.replace(src, dst)
    with _LOCK:
        if _REC is not None:
            _REC.ensure_known(src)
            _REC.ensure_known(dst)
            _REC.note_replace(src, dst)


def unlink(path: str, missing_ok: bool = True) -> bool:
    """Recorded ``os.unlink``.  Returns True when a file was removed."""
    with _LOCK:
        if _REC is not None:
            _REC.ensure_known(path)
    _maybe_kill(path)
    try:
        os.unlink(path)
    except FileNotFoundError:
        if missing_ok:
            return False
        raise
    with _LOCK:
        if _REC is not None:
            _REC.note_unlink(path)
    return True


def truncate(path: str, size: int) -> None:
    """Recorded in-place truncate (event-log emergency reset)."""
    with _LOCK:
        if _REC is not None:
            _REC.ensure_known(path)
    with open(path, "r+b") as f:
        f.truncate(size)
    with _LOCK:
        if _REC is not None:
            _REC.note_truncate(path, size)


# ----------------------------------------------------------------------
# crash-state simulator


def marks_before(ops: List[dict], k: int) -> List[dict]:
    """Marks recorded strictly before crash point ``k`` — the invariant
    obligations that were ACKNOWLEDGED before the crash."""
    return [op for op in ops[:k] if op["op"] == "mark"]


def _durable_sets(ops: List[dict], k: int):
    """Per the ext4-reorder model: indices of fsyncs by fid and dir
    fsyncs by dir, within the crash prefix (plus the pre-existing-file
    snapshot syncs, which hold at every crash point)."""
    fsyncs: Dict[int, List[int]] = {}
    dirsyncs: Dict[str, List[int]] = {}
    for i, op in enumerate(ops):
        if i >= k and not op.get("snap"):
            continue
        if op["op"] == "fsync":
            fsyncs.setdefault(op["fid"], []).append(i)
        elif op["op"] == "fsync_dir":
            dirsyncs.setdefault(op["path"], []).append(i)
    return fsyncs, dirsyncs


def _synced_after(idxs: Optional[List[int]], i: int) -> bool:
    return bool(idxs) and idxs[-1] > i


def simulate_crash(ops: List[dict], k: int, variant: str = "sync",
                   torn_keep: Optional[int] = None,
                   ) -> Optional[Dict[str, bytes]]:
    """Compute the post-crash filesystem tree (rel path -> bytes) for a
    crash immediately before op ``k``.  Returns None when the variant
    adds nothing at this point (e.g. ``torn`` with no unsynced tail, or
    a cut past the write's length) so the caller can skip duplicates.
    """
    if variant not in VARIANTS:
        raise ValueError(f"unknown crash variant {variant!r}")
    fsyncs, dirsyncs = _durable_sets(ops, k)

    torn_idx = -1
    if variant == "torn":
        for i in range(k - 1, -1, -1):
            op = ops[i]
            if op["op"] == "write" and not op.get("snap"):
                if not _synced_after(fsyncs.get(op["fid"]), i):
                    torn_idx = i
                break
        if torn_idx < 0:
            return None
        if torn_keep is None or torn_keep >= len(ops[torn_idx]["data"]):
            return None

    namespace: Dict[str, int] = {}
    contents: Dict[int, bytearray] = {}
    for i, op in enumerate(ops):
        if i >= k and not op.get("snap"):
            continue
        kind = op["op"]
        if kind == "mark":
            continue
        if variant == "sync":
            if kind in ("write", "truncate"):
                if not _synced_after(fsyncs.get(op["fid"]), i):
                    continue
            elif kind in ("create", "rename"):
                d = os.path.dirname(op.get("dst") or op["path"])
                if not (_synced_after(dirsyncs.get(d), i)
                        or _synced_after(fsyncs.get(op["fid"]), i)):
                    continue
            elif kind == "unlink":
                d = os.path.dirname(op["path"])
                if not _synced_after(dirsyncs.get(d), i):
                    continue
        if kind == "create":
            contents.setdefault(op["fid"], bytearray())
            namespace[op["path"]] = op["fid"]
        elif kind == "write":
            buf = contents.setdefault(op["fid"], bytearray())
            data = op["data"]
            if i == torn_idx:
                data = data[:torn_keep]
            off = op["off"]
            if off > len(buf):
                buf.extend(b"\0" * (off - len(buf)))
            buf[off:off + len(data)] = data
        elif kind == "truncate":
            buf = contents.setdefault(op["fid"], bytearray())
            del buf[op["size"]:]
        elif kind == "rename":
            fid = op["fid"]
            if namespace.get(op["src"]) == fid:
                del namespace[op["src"]]
            namespace[op["dst"]] = fid
        elif kind == "unlink":
            namespace.pop(op["path"], None)
    return {path: bytes(contents.get(fid, b""))
            for path, fid in namespace.items()}


def tree_fingerprint(tree: Dict[str, bytes]) -> str:
    h = hashlib.sha1()
    for path in sorted(tree):
        h.update(path.encode())
        h.update(b"\0")
        h.update(hashlib.sha1(tree[path]).digest())
    return h.hexdigest()


def write_tree(tree: Dict[str, bytes], out_root: str) -> None:
    """Materialize a simulated crash state into ``out_root`` (which
    should be fresh/empty) so the real recovery code can run on it."""
    for path, data in tree.items():
        full = os.path.join(out_root, path)
        os.makedirs(os.path.dirname(full) or out_root, exist_ok=True)
        with open(full, "wb") as f:
            f.write(data)
