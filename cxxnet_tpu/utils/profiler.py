"""Step timing + XLA trace capture: the tracing/profiling subsystem.

The reference's only observability was elapsed-time progress lines every
``print_step`` batches (``/root/reference/src/cxxnet_main.cpp:378-386``)
and a ``GetTime`` helper (``src/utils/timer.h``).  SURVEY §5 calls for the
TPU-native upgrade: per-step wall-time statistics plus on-demand XLA
profiler traces (xplane protos viewable in TensorBoard/XProf).

Config keys (all global):

* ``profile = 1`` — capture a jax.profiler trace window
* ``profile_dir = <dir>`` — trace output dir (default ``profile_out``)
* ``profile_start = 5`` — global step index to start the trace
* ``profile_steps = 10`` — number of steps to trace
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..obs.registry import PercentileWindow, registry as _obs_registry

ConfigEntry = Tuple[str, str]


class PercentileTracker(PercentileWindow):
    """Thread-safe sliding-window percentile estimator (serving latency).

    A thin facade over :class:`cxxnet_tpu.obs.registry.PercentileWindow`
    — the shared observability primitive — kept under its historical
    name so serving and pipeline call sites read unchanged.  Unlike
    :class:`StepTimer` (one round of a single-threaded train loop) this
    is written for many concurrent request threads recording into one
    tracker for the whole server lifetime, so it is locked and bounded.

    ``summary()`` reports a window-consistent ``mean`` (same samples as
    p50/p95/p99) plus the all-time ``lifetime_mean``/``count`` — the old
    mixed report (lifetime mean next to window percentiles) read as a
    contradiction whenever behavior shifted mid-run."""


class PipelineStats:
    """Per-stage input-pipeline timing: decode / augment / batch / h2d /
    device_wait (plus any custom stage name), each on a
    :class:`PercentileTracker` with total-time and row accounting.

    One process-wide instance (:func:`pipeline_stats`) so the io/ chain,
    the trainer's transfer path, and the CLI's round loop all record
    into the same registry without plumbing.  Thread-safe — decode pool
    workers record concurrently.  A stage's ``rows_per_sec`` is its
    LOCAL rate (rows / time spent inside the stage), i.e. what the
    stage could sustain if it were the only bottleneck; comparing
    stages shows where the host pipeline's time actually goes
    (``tools/io_bench.py`` emits the same snapshot as JSON).
    """

    STAGES = ("decode", "augment", "batch", "h2d", "device_wait")

    def __init__(self, window: int = 2048) -> None:
        self._window = window
        self._lock = threading.Lock()
        self._stages: Dict[str, list] = {}  # name -> [tracker, total_s, rows]

    def add(self, stage: str, dt_s: float, rows: int = 1) -> None:
        # the whole record happens under the lock: a concurrent reset()
        # swaps the stage dict, and an add must land entirely in one
        # epoch's dict — recording the tracker outside the lock let a
        # reset discard the entry between the totals and the sample
        with self._lock:
            ent = self._stages.get(stage)
            if ent is None:
                ent = [PercentileTracker(self._window), 0.0, 0]
                self._stages[stage] = ent
            ent[1] += float(dt_s)
            ent[2] += int(rows)
            ent[0].add(dt_s)

    def reset(self) -> None:
        """Start a new accounting epoch.  Swap-atomic: the old stage
        dict is replaced wholesale under the lock, so an ``add()``
        racing from a decode-pool worker lands either entirely in the
        discarded epoch or entirely in the new one — never half in
        each, and never into a tracker the snapshot can no longer
        reach."""
        with self._lock:
            self._stages = {}

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """``{stage: {count, rows, total_s, rows_per_sec, mean_ms,
        p50_ms, p95_ms, p99_ms}}`` — every canonical stage is present
        (zeroed when it never ran) so consumers can rely on the schema."""
        with self._lock:
            items = {k: (ent[0], ent[1], ent[2])
                     for k, ent in self._stages.items()}
        out: Dict[str, Dict[str, float]] = {}
        for name in (*self.STAGES, *sorted(set(items) - set(self.STAGES))):
            if name not in items:
                out[name] = {"count": 0, "rows": 0, "total_s": 0.0,
                             "rows_per_sec": 0.0}
                continue
            tracker, total_s, rows = items[name]
            row = {
                "count": float(tracker.count),
                "rows": float(rows),
                "total_s": total_s,
                "rows_per_sec": rows / total_s if total_s > 0 else 0.0,
            }
            summ = tracker.summary(scale=1e3)
            for k, v in summ.items():
                if k != "count":
                    row[f"{k}_ms"] = v
            out[name] = row
        return out

    def report(self) -> str:
        """One line per active stage: local rows/sec + mean ms/op."""
        parts = []
        for name, row in self.snapshot().items():
            if not row["count"]:
                continue
            parts.append(
                f"{name} {row['rows_per_sec']:.0f} rows/s "
                f"({row.get('mean_ms', 0.0):.2f} ms/op)"
            )
        return " | ".join(parts)

    def collect(self):
        """Scrape-time exporter for the metrics registry (registered on
        the process-wide instance), labeled ``{stage=...}`` —
        ``/metricsz`` coverage without double-writing every sample.
        Everything exports as GAUGES: the totals are per-epoch (the
        round loop calls :meth:`reset` each round), and a counter that
        sawtooths to zero would poison ``rate()``/``increase()`` on any
        Prometheus-compatible scraper."""
        snap = self.snapshot()
        fams = []
        for name, kind, help_, field in (
            ("pipeline_stage_rows", "gauge",
             "Rows processed per host-pipeline stage (current epoch; "
             "resets each round).", "rows"),
            ("pipeline_stage_seconds", "gauge",
             "Seconds spent inside each host-pipeline stage "
             "(current epoch; resets each round).", "total_s"),
            ("pipeline_stage_mean_ms", "gauge",
             "Window-mean milliseconds per operation, per stage.",
             "mean_ms"),
            ("pipeline_stage_p99_ms", "gauge",
             "Window p99 milliseconds per operation, per stage.",
             "p99_ms"),
        ):
            samples = [({"stage": st}, row[field])
                       for st, row in snap.items() if field in row]
            fams.append((name, kind, help_, samples))
        return fams


_PIPELINE_STATS = PipelineStats()
_obs_registry().register_collector(_PIPELINE_STATS.collect)


def pipeline_stats() -> PipelineStats:
    """The process-wide per-stage pipeline timing registry."""
    return _PIPELINE_STATS


class StepTimer:
    """Wall-clock statistics over training steps (one round at a time)."""

    def __init__(self) -> None:
        self._times: List[float] = []
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, n_steps: int = 1) -> None:
        """``n_steps > 1``: the timed span covered a multi-step device
        program (update_scan); record the per-step average so the round
        statistics stay per-step comparable."""
        if self._t0 is not None:
            self.add(time.perf_counter() - self._t0, n_steps)
            self._t0 = None

    def add(self, dt: float, n_steps: int = 1) -> None:
        """Record an externally measured span covering ``n_steps`` steps
        (the async-overlap train loop times fence-to-fence laps itself
        so the spans sum to the round's wall time)."""
        per = dt / max(1, n_steps)
        self._times.extend([per] * max(1, n_steps))

    def clear(self) -> None:
        self._times = []
        self._t0 = None

    @property
    def count(self) -> int:
        return len(self._times)

    def summary(self, batch_size: int = 0) -> Dict[str, float]:
        """mean/p50/p99 step ms (+ samples/sec if batch_size given).

        The first step of a round is dropped when there are enough
        samples — it absorbs compile time.
        """
        if not self._times:
            return {}
        ts = sorted(self._times[1:] if len(self._times) > 4 else self._times)
        n = len(ts)
        mean = sum(ts) / n
        out = {
            "steps": float(len(self._times)),
            "mean_ms": mean * 1e3,
            "p50_ms": ts[n // 2] * 1e3,
            "p99_ms": ts[min(n - 1, int(n * 0.99))] * 1e3,
        }
        if batch_size:
            out["samples_per_sec"] = batch_size / mean
        return out

    def report(self, batch_size: int = 0) -> str:
        s = self.summary(batch_size)
        if not s:
            return ""
        msg = (
            f"step {s['mean_ms']:.1f} ms avg "
            f"(p50 {s['p50_ms']:.1f}, p99 {s['p99_ms']:.1f})"
        )
        if "samples_per_sec" in s:
            msg += f", {s['samples_per_sec']:.1f} samples/sec"
        return msg


class TraceController:
    """Starts/stops a jax.profiler trace over a configured step window."""

    def __init__(self) -> None:
        self.enabled = 0
        self.trace_dir = "profile_out"
        self.start_step = 5
        self.num_steps = 10
        self._active = False
        self._done = False

    def set_param(self, name: str, val: str) -> None:
        if name == "profile":
            self.enabled = int(val)
        elif name == "profile_dir":
            self.trace_dir = val
        elif name == "profile_start":
            self.start_step = int(val)
        elif name == "profile_steps":
            self.num_steps = int(val)

    def configure(self, cfg: Sequence[ConfigEntry]) -> None:
        for n, v in cfg:
            self.set_param(n, v)

    def step(self, global_step: int) -> None:
        """Call once per training step with the global step index."""
        if not self.enabled or self._done:
            return
        import jax

        if not self._active and global_step >= self.start_step:
            jax.profiler.start_trace(self.trace_dir)
            self._active = True
            self._stop_at = global_step + self.num_steps
        elif self._active and global_step >= self._stop_at:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self._done = True
