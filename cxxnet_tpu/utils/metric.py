"""Evaluation metrics: error, rmse, logloss, rec@n + MetricSet.

Parity: ``/root/reference/src/utils/metric.h`` —

* ``error``: argmax mismatch (first max wins on ties); 1-column
  predictions threshold at 0 (metric.h:73-90)
* ``rmse``: *sum* of squared errors per instance, averaged over instances
  (the reference never takes the square root despite the name — kept)
* ``logloss``: -log p[target], clamped to [1e-15, 1-1e-15]; binary form
  for 1-column predictions with the built-in NaN check
* ``rec@n``: fraction of the label list present in the top-n predictions.
  Ties are broken RANDOMLY per instance, matching the reference
  (src/utils/metric.h:150-170 shuffles the index vector before its
  partial sort): fresh per-row random jitter from a seeded per-metric
  PRNG is the lexsort secondary key, so equal scores enter the top-n
  in a different random order for every row while runs stay
  reproducible.
* ``MetricSet``: multiple metrics over named label fields; report format
  ``\\tname-metric[field]:value`` (metric.h:193-203)

Config parsing (``nnet_impl-inl.hpp:57-67``): ``metric = error`` binds to
field "label" and the final output node; ``metric[field,node] = error``
selects a label field AND a named graph node to score — each metric
carries its node selector (``None`` = final out), and the trainer feeds
per-metric predictions the way the reference fills one ``eval_req``
entry per metric (``nnet_impl-inl.hpp:363-372``).
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

import numpy as np


class Metric:
    name = ""

    def __init__(self) -> None:
        self.sum_metric = 0.0
        self.cnt_inst = 0

    def clear(self) -> None:
        self.sum_metric, self.cnt_inst = 0.0, 0

    def add_eval(self, pred: np.ndarray, label: np.ndarray) -> None:
        """pred: (N, K) scores; label: (N, L) field columns."""
        self.sum_metric += float(self._batch_sum(pred, label))
        self.cnt_inst += pred.shape[0]

    def get(self) -> float:
        return self.sum_metric / max(self.cnt_inst, 1)

    def _batch_sum(self, pred: np.ndarray, label: np.ndarray) -> float:
        raise NotImplementedError


class MetricError(Metric):
    name = "error"

    def _batch_sum(self, pred, label):
        if pred.shape[1] != 1:
            guess = pred.argmax(axis=1)
        else:
            guess = (pred[:, 0] > 0).astype(np.int64)
        return np.sum(guess != label[:, 0].astype(np.int64))


class MetricRMSE(Metric):
    name = "rmse"

    def _batch_sum(self, pred, label):
        if pred.shape != label.shape:
            raise ValueError("rmse: prediction and label sizes must match")
        return np.sum((pred - label) ** 2)


class MetricLogloss(Metric):
    name = "logloss"

    def _batch_sum(self, pred, label):
        eps = 1e-15
        if pred.shape[1] != 1:
            tgt = label[:, 0].astype(np.int64)
            p = np.clip(pred[np.arange(len(tgt)), tgt], eps, 1 - eps)
            return -np.sum(np.log(p))
        p = np.clip(pred[:, 0], eps, 1 - eps)
        y = label[:, 0]
        res = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        if np.isnan(res).any():
            raise FloatingPointError("logloss: NaN detected!")
        return np.sum(res)


class MetricRecall(Metric):
    def __init__(self, name: str) -> None:
        super().__init__()
        m = re.fullmatch(r"rec@(\d+)", name)
        if not m:
            raise ValueError("must specify n for rec@n")
        self.topn = int(m.group(1))
        self.name = name
        self._rng = np.random.RandomState(0)

    def _batch_sum(self, pred, label):
        if pred.shape[1] < self.topn:
            raise ValueError(
                f"rec@{self.topn} meaningless for prediction list of "
                f"size {pred.shape[1]}"
            )
        # random tie-break (reference parity): sort by score with a
        # fresh per-row random secondary key, so equal scores enter the
        # top-n in random order per instance
        jitter = self._rng.random_sample(pred.shape)
        order = np.lexsort((jitter, -pred), axis=1)
        top = order[:, : self.topn]
        total = 0.0
        for i in range(pred.shape[0]):
            hits = np.isin(label[i].astype(np.int64), top[i]).sum()
            total += hits / label.shape[1]
        return total


class MetricPerplexity(MetricLogloss):
    """exp(mean NLL) — the language-modeling spelling of logloss
    (per-token when the prediction is a sequence; new scope, no
    reference analog)."""

    name = "perplexity"

    def get(self) -> float:
        import math

        return math.exp(self.sum_metric / max(self.cnt_inst, 1))


def create_metric(name: str) -> Metric:
    if name == "error":
        return MetricError()
    if name == "rmse":
        return MetricRMSE()
    if name == "logloss":
        return MetricLogloss()
    if name == "perplexity":
        return MetricPerplexity()
    if name.startswith("rec@"):
        return MetricRecall(name)
    raise ValueError(f"Metric: unknown metric name: {name}")


_METRIC_KEY_RE = re.compile(r"metric(\[(?P<field>[^,\]]+)(,(?P<node>[^\]]+))?\])?")


class MetricSet:
    def __init__(self) -> None:
        self.metrics: List[Metric] = []
        self.fields: List[str] = []
        self.nodes: List[object] = []  # per-metric node name; None = out

    def add_metric(self, name: str, field: str = "label",
                   node: str | None = None) -> None:
        self.metrics.append(create_metric(name))
        self.fields.append(field)
        self.nodes.append(node)

    def try_add_from_config(self, key: str, val: str) -> bool:
        """Parse a ``metric`` / ``metric[field]`` / ``metric[field,node]``
        config entry; returns False if the key is not a metric key."""
        if not key.startswith("metric"):
            return False
        m = _METRIC_KEY_RE.fullmatch(key)
        if not m:
            return False
        field = m.group("field") or "label"
        self.add_metric(val, field, m.group("node"))
        return True

    def need_nodes(self) -> bool:
        """True when any metric scores a non-default graph node."""
        return any(n is not None for n in self.nodes)

    def clear(self) -> None:
        for mt in self.metrics:
            mt.clear()

    def add_eval(
        self,
        pred,
        labels: np.ndarray,
        label_ranges: Dict[str, Tuple[int, int]],
    ) -> None:
        """labels: (N, label_width); label_ranges: field → column span.

        ``pred`` is one (N, K) array applied to every metric, or a list
        with one prediction per metric (the reference's per-metric
        ``eval_req`` scores, metric.h AddEval)."""
        if labels.ndim == 1:
            labels = labels[:, None]
        if isinstance(pred, (list, tuple)):
            if len(pred) != len(self.metrics):
                raise ValueError(
                    f"MetricSet: {len(pred)} predictions for "
                    f"{len(self.metrics)} metrics"
                )
            preds = list(pred)
        else:
            preds = [pred] * len(self.metrics)
        for mt, field, pred in zip(self.metrics, self.fields, preds):
            if field not in label_ranges:
                raise ValueError(f"Metric: unknown target = {field}")
            a, b = label_ranges[field]
            if pred.ndim == 3:
                # per-position sequence predictions (N, T, V) — language
                # models: score each position as an instance; the
                # metric's field must span exactly the T positions
                # (label_vec[a,a+T) = field)
                n, t, v = pred.shape
                if b - a != t:
                    raise ValueError(
                        f"Metric[{field}]: sequence predictions with T={t}"
                        f" positions need a label field of width {t}, got"
                        f" columns [{a},{b})"
                    )
                mt.add_eval(
                    pred.reshape(n * t, v),
                    labels[:, a:b].reshape(n * t, 1),
                )
            else:
                mt.add_eval(pred, labels[:, a:b])

    def reduce_across_processes(self) -> None:
        """Sum (sum_metric, cnt_inst) over all processes of a
        jax.distributed job — the cross-worker eval reduction (the
        reference evaluates on sharded workers too,
        nnet_impl-inl.hpp:224-245).  Collective: every process must
        call.  A no-op single-process.  Correct for sharded iterators
        (disjoint contributions sum to the global metric) and harmless
        for unsharded ones (identical contributions scale numerator and
        denominator alike)."""
        import jax

        if jax.process_count() == 1 or not self.metrics:
            return
        from jax.experimental import multihost_utils

        # the gather runs in float32 (x64 is typically disabled), which
        # would corrupt counters past 2^24 — ship each float64 as a
        # (hi, lo) float32 pair and each count as divmod(2^20) words,
        # then reconstruct in float64 host-side
        rows = []
        for m in self.metrics:
            s_hi = np.float32(m.sum_metric)
            s_lo = np.float32(m.sum_metric - float(s_hi))
            c_hi, c_lo = divmod(int(m.cnt_inst), 1 << 20)
            rows.append([s_hi, s_lo, np.float32(c_hi), np.float32(c_lo)])
        gathered = np.asarray(
            multihost_utils.process_allgather(
                np.asarray(rows, np.float32)
            ),
            np.float64,
        )  # [nproc, nmetric, 4]
        total = gathered.sum(axis=0)
        for m, (s_hi, s_lo, c_hi, c_lo) in zip(self.metrics, total):
            m.sum_metric = float(s_hi) + float(s_lo)
            m.cnt_inst = int(round(c_hi)) * (1 << 20) + int(round(c_lo))

    def print(self, evname: str) -> str:
        out = []
        for mt, field in zip(self.metrics, self.fields):
            tag = f"{evname}-{mt.name}"
            if field != "label":
                tag += f"[{field}]"
            out.append(f"\t{tag}:{mt.get():g}")
        return "".join(out)

    def __len__(self) -> int:
        return len(self.metrics)
