"""Fault-tolerant checkpoint subsystem: atomic writes, manifests, recovery.

The reference assumed one long-lived process: ``LearnTask`` wrote
``NNNN.model`` in place and ``continue=1`` blindly loaded the newest file.
On preemptible machines that breaks — a kill mid-write leaves a truncated
checkpoint that resume then loads.  This module supplies the primitives
the task driver and trainer build fault tolerance from (the TensorFlow
lesson, arXiv:1605.08695 §4.2: consistent checkpointing and automatic
recovery are system requirements, not afterthoughts):

* **atomic writes** — write to a temp file in the same directory, fsync,
  rename; readers never observe a half-written checkpoint;
* **sidecar manifests** — ``NNNN.model.manifest.json`` carrying CRC32,
  byte size, round number, a net-structure fingerprint, and the
  ``save_ustate`` flag, so resume can *prove* a checkpoint is intact
  (and belongs to this net) before loading it;
* **validation + newest-valid selection** — glob all ``*.model`` files
  (no consecutive-scan gap bug), check each against its manifest, fall
  back past corrupt ones instead of crashing;
* **retention** — ``keep_latest = N`` prunes old checkpoints (and their
  sidecars) after each successful save;
* **retry with exponential backoff** — transient I/O flakiness (network
  filesystems) does not kill a multi-hour run;
* **preemption handling** — a SIGTERM/SIGINT handler that *requests* a
  clean stop; the train loop snapshots state at the next safe point and
  exits instead of dying mid-write;
* **divergence guard** — ``DivergenceError`` raised by the trainer when
  a step's loss goes non-finite; the driver's ``divergence_policy``
  decides abort vs rollback-to-last-good-checkpoint.
"""

from __future__ import annotations

import fnmatch
import json
import os
import signal
import time
import zlib
from typing import Callable, List, Optional, Sequence, Tuple

from . import diskio, faults

# model container magic (shared with nnet.trainer, which re-exports it)
MODEL_MAGIC = b"CXTPU001"
MANIFEST_SUFFIX = ".manifest.json"
MANIFEST_FORMAT = 1


class CheckpointError(RuntimeError):
    """A checkpoint failed validation or could not be written/read."""


class DivergenceError(RuntimeError):
    """A training step produced a non-finite loss.

    Raised by ``NetTrainer`` when ``divergence_policy`` is set; carries
    the offending loss value(s) and the epoch range they cover so the
    driver can report precisely where training blew up.
    """

    def __init__(self, message: str, loss=None, epoch: Optional[int] = None):
        super().__init__(message)
        self.loss = loss
        self.epoch = epoch


# ----------------------------------------------------------------------
# atomic I/O + retry
def atomic_write_bytes(path: str, data: bytes, fsync: bool = True) -> None:
    """Write ``data`` to ``path`` atomically: temp file in the same
    directory, flush+fsync, rename, dir fsync.  A crash at any point
    leaves either the old file or the new one, never a truncation.

    The implementation lives in :mod:`~cxxnet_tpu.utils.diskio` (the
    shared, recorded, fault-injectable write path) — every durable
    writer funnels through that one helper so the fsync contract cannot
    fork, and ``tools/crash_audit.py`` can replay every crash point.
    """
    diskio.write_atomic(path, data, fsync=fsync, site="checkpoint.write")


def retry_io(
    fn: Callable,
    attempts: int = 4,
    base_delay: float = 0.05,
    exceptions: Tuple[type, ...] = (OSError,),
    what: str = "checkpoint I/O",
    silent: bool = False,
    _sleep: Callable[[float], None] = time.sleep,
):
    """Legacy retry entry point — now a thin wrapper over the unified
    :class:`~cxxnet_tpu.utils.faults.RetryPolicy` (no jitter, no
    deadline, uncapped backoff: the exact old ``base_delay * 2**k``
    schedule) so there is ONE retry implementation to maintain."""
    return faults.RetryPolicy(
        attempts=attempts, base_delay=base_delay,
        max_delay=float("inf"), jitter=0.0, exceptions=exceptions,
    ).run(fn, what=what, silent=silent, _sleep=_sleep)


# ----------------------------------------------------------------------
# manifests
def crc32_of(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def crc32_file(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            crc = zlib.crc32(b, crc)
    return crc & 0xFFFFFFFF


def net_fingerprint(structure_json: str) -> str:
    """Stable fingerprint of a net's structure (key-order independent)."""
    canon = json.dumps(json.loads(structure_json), sort_keys=True,
                       separators=(",", ":"))
    return f"{crc32_of(canon.encode('utf-8')):08x}"


def manifest_path(model_path: str) -> str:
    return model_path + MANIFEST_SUFFIX


def write_manifest(
    model_path: str,
    round_: Optional[int] = None,
    net_fp: Optional[str] = None,
    save_ustate: int = 0,
    blob: Optional[bytes] = None,
    mesh: Optional[dict] = None,
    quant: Optional[dict] = None,
    probe: Optional[dict] = None,
) -> dict:
    """Write the sidecar manifest for an already-written checkpoint.

    ``blob`` (the exact bytes written) avoids re-reading the file; the
    manifest itself is written atomically, AFTER the checkpoint, so a
    manifest's existence implies its checkpoint was fully durable.
    ``mesh`` (``{"n_data", "n_model", "zero", "processes"}``) records
    the SPMD layout that wrote the checkpoint — informational only: the
    payload always holds GATHERED full arrays (rank-0 gather in
    ``checkpoint_bytes``), and load re-shards onto whatever mesh the
    loading process runs, so resume across device/process counts needs
    no translation step.  The field lets tooling answer "what wrote
    this" without loading it.  ``quant`` (``{"scheme", "scales_dtype",
    "int8_layers", "bf16_layers", ...}``) marks a quantized inference
    artifact (``nnet/quant.py``) — absent on ordinary f32 checkpoints,
    so tooling can tell the two apart without parsing the payload.
    ``probe`` (``{"seed", "rows", "shape", "backend", "crc32"?}``)
    commits the integrity plane's golden-canary probe batch (a
    deterministic spec, plus — when the writer scored it — the golden
    score CRC): the serving engine re-derives the batch from the spec,
    scores it, and holds its own compute to the recorded answer for
    the lifetime of the load (doc/robustness.md "Integrity plane")."""
    if blob is not None:
        crc, size = crc32_of(blob), len(blob)
    else:
        crc, size = crc32_file(model_path), os.path.getsize(model_path)
    man = {
        "format": MANIFEST_FORMAT,
        "crc32": crc,
        "size": size,
        "round": round_,
        "net_fingerprint": net_fp,
        "save_ustate": int(save_ustate),
        "time": time.time(),
    }
    if mesh is not None:
        man["mesh"] = mesh
    if quant is not None:
        man["quant"] = quant
    if probe is not None:
        man["probe"] = probe
    atomic_write_bytes(
        manifest_path(model_path),
        (json.dumps(man, indent=1) + "\n").encode("utf-8"),
    )
    return man


def write_checkpoint(
    path: str,
    blob: bytes,
    round_: Optional[int] = None,
    net_fp: Optional[str] = None,
    save_ustate: int = 0,
    retry: bool = False,
    silent: bool = True,
    mesh: Optional[dict] = None,
    quant: Optional[dict] = None,
    probe: Optional[dict] = None,
) -> None:
    """THE checkpoint write discipline — atomic payload write, then the
    sidecar manifest — shared by every writer (``NetTrainer.save_model``
    and the task driver's ``_save_model``) so the format and ordering
    can never diverge between them.  ``retry=True`` wraps both writes in
    exponential-backoff retries (long-running driver saves on flaky
    filesystems)."""
    def _write():
        atomic_write_bytes(path, blob)

    def _manifest():
        write_manifest(path, round_=round_, net_fp=net_fp,
                       save_ustate=save_ustate, blob=blob, mesh=mesh,
                       quant=quant, probe=probe)

    from ..obs import emit as obs_emit
    from ..obs import trace as obs_trace

    try:
        with obs_trace.span("checkpoint.write", path=path,
                            bytes=len(blob)):
            if retry:
                retry_io(_write, what=f"writing {path}", silent=silent)
                retry_io(_manifest, what=f"writing {manifest_path(path)}",
                         silent=silent)
            else:
                _write()
                _manifest()
    except Exception as e:
        obs_emit("checkpoint.save", ok=False, path=path, round=round_,
                 error=f"{type(e).__name__}: {e}")
        raise
    obs_emit("checkpoint.save", ok=True, path=path, round=round_,
             bytes=len(blob))


def read_manifest(model_path: str) -> Optional[dict]:
    """The checkpoint's manifest, or None if absent/unparseable."""
    p = manifest_path(model_path)
    try:
        with open(p, "r", encoding="utf-8") as f:
            man = json.load(f)
        return man if isinstance(man, dict) else None
    except (OSError, ValueError):
        return None


def validate_checkpoint(
    model_path: str, net_fp: Optional[str] = None
) -> Optional[str]:
    """Check a checkpoint's integrity; return None when valid, else a
    human-readable reason.

    With a manifest: byte size and CRC32 must match (catches truncation
    AND payload byte-flips), and — when both sides carry one — the net
    fingerprint must match the current conf's.  Without a manifest
    (legacy checkpoint): structural validation only (magic, parseable
    header); payload corruption is then caught at load time."""
    try:
        faults.fault_point("checkpoint.read")
        size = os.path.getsize(model_path)
    except OSError as e:
        return f"unreadable: {e}"
    man = read_manifest(model_path)
    if man is not None:
        if man.get("size") != size:
            return f"size mismatch: manifest {man.get('size')}, file {size}"
        try:
            crc = crc32_file(model_path)
        except OSError as e:
            return f"unreadable: {e}"
        if man.get("crc32") != crc:
            return (f"crc32 mismatch: manifest {man.get('crc32'):#010x}, "
                    f"file {crc:#010x}")
        mfp = man.get("net_fingerprint")
        if net_fp is not None and mfp is not None and mfp != net_fp:
            return (f"net fingerprint mismatch: checkpoint {mfp}, "
                    f"current conf {net_fp} (different netconfig)")
        return None
    # no manifest: structural checks only
    try:
        with open(model_path, "rb") as f:
            magic = f.read(8)
            if magic != MODEL_MAGIC:
                return "bad magic (not a cxxnet-tpu model file)"
            raw = f.read(4)
            if len(raw) < 4:
                return "truncated header length"
            import struct

            (hlen,) = struct.unpack("<I", raw)
            hdr = f.read(hlen)
            if len(hdr) < hlen:
                return "truncated header"
            json.loads(hdr.decode("utf-8"))
    except (OSError, ValueError, UnicodeDecodeError) as e:
        return f"corrupt header: {e}"
    return None


# ----------------------------------------------------------------------
# discovery + retention
def checkpoint_round(filename: str) -> Optional[int]:
    """Round number encoded in a ``NNNN.model`` filename, else None."""
    base = os.path.basename(filename)
    stem, dot, ext = base.partition(".")
    if ext != "model" or not stem.isdigit():
        return None
    return int(stem)


def list_checkpoints(model_dir: str) -> List[Tuple[int, str]]:
    """All ``NNNN.model`` files in ``model_dir``, sorted by round —
    a glob, NOT a consecutive scan, so gaps (``save_model > 1``) and
    pruned prefixes (``keep_latest``) are handled."""
    try:
        names = os.listdir(model_dir)
    except OSError:
        return []
    out = []
    for n in fnmatch.filter(names, "*.model"):
        r = checkpoint_round(n)
        if r is not None:
            out.append((r, os.path.join(model_dir, n)))
    return sorted(out)


def find_latest_valid(
    model_dir: str,
    net_fp: Optional[str] = None,
    silent: bool = False,
    before: Optional[int] = None,
) -> Optional[Tuple[int, str]]:
    """Newest checkpoint that passes validation, scanning newest→oldest
    and warning past corrupt ones — resume survives a preemption that
    truncated the most recent write.  ``before`` excludes rounds >= it
    (divergence rollback falling back past a numerically poisoned but
    CRC-valid checkpoint)."""
    for round_, path in reversed(list_checkpoints(model_dir)):
        if before is not None and round_ >= before:
            continue
        reason = validate_checkpoint(path, net_fp=net_fp)
        if reason is None:
            return round_, path
        from ..obs.events import emit_once

        # once per (path, reason): the serve hot-reload poll calls this
        # every period, and an invalid-but-newer checkpoint would
        # otherwise emit the identical event forever
        emit_once(f"checkpoint.skipped:{path}:{reason}",
                  "checkpoint.skipped", path=path, round=round_,
                  reason=reason)
        if not silent:
            print(f"checkpoint {path} skipped: {reason}", flush=True)
    return None


def apply_retention(
    model_dir: str, keep_latest: int, silent: bool = True
) -> List[str]:
    """Prune all but the newest ``keep_latest`` checkpoints (and their
    manifests).  ``keep_latest <= 0`` keeps everything.  Returns the
    removed model paths."""
    if keep_latest <= 0:
        return []
    removed = []
    for _, path in list_checkpoints(model_dir)[:-keep_latest]:
        for p in (path, manifest_path(path)):
            try:
                diskio.unlink(p)
            except OSError:
                continue
        removed.append(path)
        if not silent:
            print(f"retention: removed {path}", flush=True)
    return removed


# ----------------------------------------------------------------------
# publish pointer (closed-loop continuous training, doc/continuous_training.md)
PUBLISH_POINTER = "PUBLISHED.json"


def publish_path(model_dir: str, round_: int) -> str:
    """Canonical checkpoint path for a published round (the same
    ``NNNN.model`` naming the trainer and serve discovery use)."""
    return os.path.join(model_dir, f"{round_:04d}.model")


def pointer_path(model_dir: str) -> str:
    return os.path.join(model_dir, PUBLISH_POINTER)


def write_publish_pointer(
    model_dir: str,
    round_: int,
    path: str,
    net_fp: Optional[str] = None,
    metric: Optional[dict] = None,
    prev_round: Optional[int] = None,
    lineage: Optional[dict] = None,
) -> dict:
    """Atomically flip the publish pointer to ``round_``/``path``.

    The pointer is the loop's "currently blessed version" record: the
    eval-gated publisher writes it after every accepted candidate, and
    rollback (a rejected candidate, or an operator intervention) reads
    it to find the last version that passed the gate.  ``prev`` keeps
    one level of history — enough to answer "what was serving before
    this publish" without scanning manifests.  ``lineage`` records the
    feedback-log id range (+ record/cycle counts) the published weights
    were fine-tuned on — ``tools/obs_dump.py --lineage`` resolves it
    back to the log's committed pages."""
    ptr = {
        "format": MANIFEST_FORMAT,
        "round": int(round_),
        "path": path,
        "net_fingerprint": net_fp,
        "metric": metric,
        "prev": ({"round": int(prev_round)}
                 if prev_round is not None else None),
        "lineage": lineage,
        "time": time.time(),
    }
    atomic_write_bytes(
        pointer_path(model_dir),
        (json.dumps(ptr, indent=1) + "\n").encode("utf-8"),
    )
    return ptr


def read_publish_pointer(model_dir: str) -> Optional[dict]:
    """The current publish pointer, or None if absent/unparseable."""
    try:
        with open(pointer_path(model_dir), "r", encoding="utf-8") as f:
            ptr = json.load(f)
        if isinstance(ptr, dict) and "round" in ptr and "path" in ptr:
            return ptr
    except (OSError, ValueError):
        pass
    return None


# ----------------------------------------------------------------------
# preemption
class PreemptionHandler:
    """Cooperative SIGTERM/SIGINT handling for the train loop.

    First signal sets ``requested`` — the loop checks it at batch/round
    boundaries, snapshots state, and exits cleanly.  A second signal
    restores the previous handlers and re-raises (force quit for an
    operator who really means it)."""

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,
                                                 signal.SIGINT)) -> None:
        self.signals = tuple(signals)
        self.requested = False
        self.signum: Optional[int] = None
        self._prev: dict = {}
        self._installed = False

    def _handle(self, signum, frame):
        if self.requested:
            # second signal: give up on graceful shutdown
            self.uninstall()
            os.kill(os.getpid(), signum)
            return
        self.requested = True
        self.signum = signum
        print(
            f"received signal {signal.Signals(signum).name}: finishing the "
            "current step, then checkpointing and exiting "
            "(signal again to force quit)",
            flush=True,
        )

    def install(self) -> "PreemptionHandler":
        if not self._installed:
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._handle)
            self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            for s, prev in self._prev.items():
                signal.signal(s, prev)
            self._prev.clear()
            self._installed = False

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
