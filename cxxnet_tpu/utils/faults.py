"""Resilience layer: deterministic fault injection + recovery primitives.

The ROADMAP's serving north star requires the TensorFlow-era posture
(arXiv:1605.08695 §4.2): at scale, partial input failure and process
churn are *normal operation*, not crashes.  This module is the substrate
the io/, checkpoint, and serve/ layers build their hardening on — and
the chaos harness that makes the hardening verifiable:

* :class:`FaultInjector` — a registry of **named injection sites**
  (``SITES``) instrumented through the hot paths.  A config key
  ``fault_inject = site:kind:prob[:limit]`` arms a site with a fault
  kind (``ioerror`` / ``corrupt`` / ``latency`` / ``hang``) fired with
  probability ``prob`` per visit, at most ``limit`` times.  Draws come
  from a per-spec RNG seeded by ``fault_seed`` + the site name, so a
  schedule **replays deterministically** — the same seed produces the
  same firing pattern, which is what lets tests assert exact skip
  counts and quarantine offsets.
* :class:`RetryPolicy` — the unified transient-I/O retry: exponential
  backoff with deterministic jitter AND a total deadline, replacing the
  ad-hoc ``retry_io`` call sites (config keys ``retry_attempts``,
  ``retry_base_delay``, ``retry_max_delay``, ``retry_deadline_s``).
* :class:`Watchdog` — detects a hung worker (prefetch producer,
  serve batcher) and fails fast with a diagnostic (including the hung
  thread's stack) instead of blocking the consumer forever.
* :class:`CircuitBreaker` — consecutive-failure breaker for the serve
  hot-reload path: back off instead of retrying a broken reload at
  full poll rate, while the old model keeps serving.
* :class:`BadRecordBudget` — skip-and-quarantine accounting for data
  iterators: corrupt records/pages are skipped and logged up to
  ``max_bad_records`` per epoch; exceeding the budget aborts with a
  summary; quarantined offsets are written to a ``.quarantine``
  sidecar next to the source file.

See ``doc/robustness.md`` for the config surface and the chaos suite.
"""

from __future__ import annotations

import os
import random
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs import events as obs_events
from ..obs.registry import registry as obs_registry

__all__ = [
    "SITES",
    "KINDS",
    "InjectedFault",
    "InjectedCorruption",
    "InjectedDiskFull",
    "InjectedShortWrite",
    "WatchdogError",
    "BadDataError",
    "FaultSpec",
    "FaultInjector",
    "fault_point",
    "install",
    "configure",
    "reset",
    "injector",
    "retried_read_lines",
    "RetryPolicy",
    "Watchdog",
    "CircuitBreaker",
    "BadRecordBudget",
]

#: Every instrumented injection site and the fault kinds it supports.
#: ``tools/chaos_run.sh`` iterates this matrix — adding a site here
#: without a chaos scenario for it fails the fault-matrix lane.
SITES: Dict[str, Tuple[str, ...]] = {
    "imgbin.page": ("ioerror", "corrupt", "latency", "hang"),
    "imgbin.record": ("corrupt",),
    "csv.read": ("ioerror", "latency"),
    "csv.row": ("corrupt",),
    "libsvm.read": ("ioerror", "latency"),
    "libsvm.row": ("corrupt",),
    "text.read": ("ioerror", "latency"),
    "prefetch.producer": ("latency", "hang"),
    "pipeline.worker": ("latency", "hang"),
    # checkpoint atomic write (utils/diskio.py::write_atomic): enospc =
    # disk full before any byte lands (abort atomically, prior round
    # stays loadable), short = ENOSPC mid-write leaving a torn tmp file
    # (same abort contract — the torn file never becomes the target)
    "checkpoint.write": ("ioerror", "latency", "enospc", "short"),
    "checkpoint.read": ("ioerror", "latency"),
    "serve.reload": ("ioerror", "latency"),
    "serve.batch": ("ioerror", "latency", "hang"),
    # feedback-log append (loop/feedback_log.py): an ioerror here must
    # DEGRADE — the record is dropped and counted, the serving request
    # still succeeds (doc/continuous_training.md)
    "loop.append": ("ioerror", "latency", "enospc"),
    # feedback-log page/sidecar commit (loop/feedback_log.py, routed
    # through utils/diskio.py): enospc/short here hit the DURABLE write
    # path — the writer must degrade (drop + count), truncate any torn
    # tail on reopen, and keep every previously committed page readable
    "loop.commit": ("ioerror", "enospc", "short"),
    # observability appends (obs/events.py events.jsonl + cli.py
    # telemetry.jsonl, routed through utils/diskio.py): both are lossy
    # by contract — a full disk means bounded drop + counter, never a
    # raise out of the never-raising wrapper and never a retry spin
    "obs.append": ("ioerror", "enospc"),
    # replica loss (nnet/trainer.py::sync, the elastic pod's collective
    # fence): hang = a peer wedged in a collective (the deadline must
    # surface ReplicaLossError in bounded time), ioerror = the abrupt
    # connection reset a SIGKILLed peer produces (classified into
    # ReplicaLossError by the elastic driver), latency = a STRAGGLER —
    # a slow-but-alive peer stretching every collective fence by
    # ``fault_latency_ms`` (calibrated); the sync step pays it at every
    # per-step fence while ``async_overlap=1, staleness>=1`` pays it
    # once per round boundary (doc/parallel.md "Async data-parallel")
    "mesh.replica": ("hang", "ioerror", "latency"),
    # serving-fleet replica (serve/server.py::replica_fault_probe, the
    # health plane of a task=serve replica process): hang = a wedged
    # replica (probes stall; the fleet supervisor must eject it from
    # rotation within the probe deadline), ioerror = a replica crash
    # (the process exits; the supervisor must restart it with backoff)
    # — doc/robustness.md, doc/serving.md "Serving fleet"
    "serve.replica": ("hang", "ioerror"),
    # data-service RPC (io/dataservice/client.py, the client end of the
    # shared decode fleet): ioerror = transport loss — the client must
    # reconnect, re-OPEN, and resume its (epoch, block) cursor with a
    # bitwise-identical stream (the same path a server SIGKILL takes);
    # latency = a slow service host (the stream completes, slower);
    # hang = a wedged server — the consumer's watchdog must fail fast
    # with WatchdogError instead of stalling the train loop forever
    "dataservice.rpc": ("ioerror", "latency", "hang"),
    # live train state (nnet/trainer.py::start_round): bitflip = a real
    # single-bit flip in a live parameter tensor on THIS process — the
    # silent data corruption the integrity plane's fingerprint vote
    # must detect, name, and quarantine (doc/robustness.md "Integrity
    # plane").  Deterministic by fault_seed: the spec's RNG picks
    # tensor, element, and bit (trainer.inject_bitflip)
    "device.state": ("bitflip",),
}

KINDS = ("ioerror", "corrupt", "latency", "hang", "enospc", "short",
         "bitflip")


class InjectedFault(OSError):
    """Injected transient I/O failure (an ``OSError``, so the retry
    machinery treats it exactly like a real filesystem flake)."""


class InjectedDiskFull(InjectedFault):
    """Injected ENOSPC: ``errno`` is set so callers that special-case
    disk-full (degrade + ``disk_full_total``) classify it exactly like
    the real thing."""

    def __init__(self, site: str) -> None:
        import errno as _errno
        super().__init__(_errno.ENOSPC,
                         f"injected ENOSPC (disk full) at {site}")


class InjectedShortWrite(InjectedDiskFull):
    """Injected short write: disk filled up MID-write.  ``keep`` bytes
    of the payload made it to disk before the failure; the diskio layer
    writes exactly that prefix (a real torn tail) and re-raises.  Sites
    not routed through diskio just see the ENOSPC."""

    def __init__(self, site: str, keep: int) -> None:
        import errno as _errno
        OSError.__init__(self, _errno.ENOSPC,
                         f"injected short write at {site} "
                         f"({keep} bytes landed)")
        self.keep = keep


class InjectedCorruption(ValueError):
    """Injected record/page corruption at a site with no byte payload
    to mutate (sites WITH a payload get real flipped bytes instead, so
    the downstream parser fails the honest way)."""


class WatchdogError(RuntimeError):
    """A monitored worker made no progress within the watchdog timeout."""


class BadDataError(RuntimeError):
    """The ``max_bad_records`` skip budget was exceeded.

    Carries the budget's summary; ``__cause__`` is the parse/decode
    error of the record that broke the budget."""


# ----------------------------------------------------------------------
# fault injection
class FaultSpec:
    """One armed fault: ``site:kind:prob[:limit]``."""

    def __init__(self, site: str, kind: str, prob: float,
                 limit: int = 0) -> None:
        self.site = site
        self.kind = kind
        self.prob = float(prob)
        self.limit = int(limit)  # 0 = unlimited firings
        self.fired = 0
        self.visits = 0

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        lim = f":{self.limit}" if self.limit else ""
        return f"<FaultSpec {self.site}:{self.kind}:{self.prob:g}{lim} fired={self.fired}>"


def _corrupt_bytes(blob: bytes, rng: random.Random) -> bytes:
    """Deterministically flip bytes in ``blob``.  Byte 0 always flips —
    it kills format magics (JPEG SOI, page headers, float headers) so
    the downstream parser reliably fails — plus a few rng positions."""
    b = bytearray(blob)
    if not b:
        return bytes(b)
    b[0] ^= 0xFF
    for _ in range(min(3, len(b) - 1)):
        b[rng.randrange(len(b))] ^= 0xFF
    return bytes(b)


def _corrupt_text(text: str, rng: random.Random) -> str:
    """Corrupt a text record: make its leading field unparseable and
    sprinkle a couple of junk bytes (deterministic positions).  ``~``
    is not a comment character in any supported text format, so the
    corruption is PARSED (and quarantined), never silently skipped."""
    chars = list(text)
    if not chars:
        return "~"
    chars[0] = "~"
    for _ in range(min(2, len(chars) - 1)):
        chars[rng.randrange(len(chars))] = "~"
    return "".join(chars)


class FaultInjector:
    """Deterministic, seed-driven fault-injection registry.

    One process-wide instance (module functions below) so config-driven
    specs reach every instrumented layer without plumbing.  Thread-safe:
    draws are serialized under a lock; per-spec RNGs are seeded from
    ``(seed, site, kind)`` so a site's firing pattern depends only on
    its own visit sequence, not on cross-site interleaving.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_site: Dict[str, List[FaultSpec]] = {}
        self._rngs: Dict[Tuple[str, str], random.Random] = {}
        self.seed = 0
        self.latency_s = 0.05
        self.hang_s = 3600.0
        self._release = threading.Event()

    # ------------------------------------------------------------------
    def install(self, spec: str) -> FaultSpec:
        """Arm one ``site:kind:prob[:limit]`` spec."""
        parts = spec.split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"fault_inject spec {spec!r}: want site:kind:prob[:limit]"
            )
        site, kind = parts[0], parts[1]
        if site not in SITES:
            raise ValueError(
                f"fault_inject: unknown site {site!r}; known: "
                f"{', '.join(sorted(SITES))}"
            )
        if kind not in SITES[site]:
            raise ValueError(
                f"fault_inject: site {site!r} supports kinds "
                f"{SITES[site]}, not {kind!r}"
            )
        prob = float(parts[2])
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"fault_inject: prob must be in [0,1], got {prob}")
        limit = int(parts[3]) if len(parts) == 4 else 0
        fs = FaultSpec(site, kind, prob, limit)
        with self._lock:
            self._by_site.setdefault(site, []).append(fs)
            self._rngs[(site, kind)] = random.Random(
                (self.seed << 16) ^ zlib.crc32(f"{site}:{kind}".encode())
            )
        return fs

    def configure(self, cfg: Sequence[Tuple[str, str]]) -> None:
        """Arm specs from an ordered config stream.  Keys: ``fault_seed``
        (read before any spec it should affect), ``fault_latency_ms``,
        ``fault_hang_s``, and any number of ``fault_inject`` entries."""
        for name, val in cfg:
            if name == "fault_seed":
                self.seed = int(val)
            elif name == "fault_latency_ms":
                self.latency_s = float(val) / 1e3
            elif name == "fault_hang_s":
                self.hang_s = float(val)
            elif name == "fault_inject":
                self.install(val)

    def reset(self) -> None:
        """Disarm everything and release any in-progress hangs (so
        daemon threads blocked at a hang site unblock at teardown)."""
        with self._lock:
            self._by_site.clear()
            self._rngs.clear()
            self.seed = 0
            self.latency_s = 0.05
            self.hang_s = 3600.0
            self._release.set()
            self._release = threading.Event()

    def active(self) -> bool:
        return bool(self._by_site)

    def armed(self, *sites: str) -> bool:
        """Is any spec armed for one of ``sites``?  Lets a fast path
        bypass instrumentation only when ITS sites are quiet, instead
        of degrading for unrelated chaos configs."""
        return any(self._by_site.get(s) for s in sites)

    def specs(self) -> List[FaultSpec]:
        with self._lock:
            return [s for specs in self._by_site.values() for s in specs]

    def fire_counts(self) -> Dict[str, int]:
        return {f"{s.site}:{s.kind}": s.fired for s in self.specs()}

    # ------------------------------------------------------------------
    def fault_point(self, site: str, payload=None):
        """The instrumentation hook: called at a named site with the
        record payload (bytes/str) when one exists.  Returns the
        (possibly corrupted) payload; may sleep, hang, or raise."""
        if not self._by_site:  # fast path: injection disarmed
            return payload
        with self._lock:
            specs = list(self._by_site.get(site, ()))
            firing: List[Tuple[FaultSpec, random.Random]] = []
            for fs in specs:
                fs.visits += 1
                if fs.limit and fs.fired >= fs.limit:
                    continue
                rng = self._rngs[(site, fs.kind)]
                if fs.prob >= 1.0 or rng.random() < fs.prob:
                    fs.fired += 1
                    firing.append((fs, rng))
            release = self._release
        for fs, _rng in firing:
            # every injection is a lifecycle fact: chaos runs become
            # auditable post-hoc from the event log + /metricsz
            obs_events.emit("fault.injected", site=fs.site,
                            fault_kind=fs.kind, fired=fs.fired,
                            visits=fs.visits)
            obs_registry().counter(
                "faults_injected_total", "Chaos-harness fault firings.",
                labelnames=("site", "kind"),
            ).labels(site=fs.site, kind=fs.kind).inc()
        for fs, rng in firing:
            if fs.kind == "latency":
                time.sleep(self.latency_s)
            elif fs.kind == "hang":
                # block on the release event (reset() unblocks) rather
                # than a bare sleep, so teardown never strands a thread
                release.wait(self.hang_s)
            elif fs.kind == "corrupt":
                if isinstance(payload, (bytes, bytearray, memoryview)):
                    payload = _corrupt_bytes(bytes(payload), rng)
                elif isinstance(payload, str):
                    payload = _corrupt_text(payload, rng)
                else:
                    raise InjectedCorruption(
                        f"injected corruption at {site}"
                    )
            elif fs.kind == "bitflip":
                # live-state corruption: the payload (a NetTrainer)
                # flips a real bit in one of its tensors — duck-typed
                # so the site stays decoupled from nnet internals
                if payload is None or not hasattr(payload,
                                                  "inject_bitflip"):
                    raise InjectedCorruption(
                        f"bitflip at {site}: payload has no "
                        "inject_bitflip hook")
                payload.inject_bitflip(rng)
            elif fs.kind == "enospc":
                raise InjectedDiskFull(site)
            elif fs.kind == "short":
                keep = 0
                if isinstance(payload, (bytes, bytearray, memoryview)):
                    n = len(payload)
                    keep = max(1, n // 2) if n else 0
                raise InjectedShortWrite(site, keep)
            else:  # ioerror
                raise InjectedFault(f"injected I/O error at {site}")
        return payload


_INJECTOR = FaultInjector()


def injector() -> FaultInjector:
    return _INJECTOR


def fault_point(site: str, payload=None):
    """Module-level hook the instrumented layers call (near-zero cost
    while no fault is armed)."""
    return _INJECTOR.fault_point(site, payload)


def install(spec: str) -> FaultSpec:
    return _INJECTOR.install(spec)


def configure(cfg: Sequence[Tuple[str, str]]) -> None:
    _INJECTOR.configure(cfg)


def reset() -> None:
    _INJECTOR.reset()


# ----------------------------------------------------------------------
# retry
def _cfg_get(cfg, name, default):
    out = default
    for n, v in cfg or ():
        if n == name:
            out = v
    return out


class RetryPolicy:
    """Unified transient-failure retry: exponential backoff with
    deterministic jitter and a **total deadline**.

    ``attempts`` bounds the try count; ``deadline_s > 0`` additionally
    bounds total time — the policy gives up (re-raising the last error)
    rather than start a sleep that would cross the deadline, so a
    hard-down dependency fails in bounded time no matter how many
    attempts remain.  Jitter is drawn from an RNG seeded per policy, so
    backoff schedules replay deterministically under test."""

    #: the config keys :meth:`from_cfg` understands — iterators route
    #: exactly these through ``set_param`` so every retry knob works
    #: everywhere the policy does
    CONFIG_KEYS = ("retry_attempts", "retry_base_delay", "retry_max_delay",
                   "retry_jitter", "retry_deadline_s")

    def __init__(
        self,
        attempts: int = 4,
        base_delay: float = 0.05,
        max_delay: float = 2.0,
        jitter: float = 0.25,
        deadline_s: float = 0.0,
        exceptions: Tuple[type, ...] = (OSError,),
        seed: int = 0,
    ) -> None:
        if attempts < 1:
            raise ValueError("RetryPolicy: attempts must be >= 1")
        self.attempts = int(attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.deadline_s = float(deadline_s)
        self.exceptions = tuple(exceptions)
        self.seed = int(seed)

    @classmethod
    def from_cfg(cls, cfg, **overrides) -> "RetryPolicy":
        """Build from config keys ``retry_attempts``, ``retry_base_delay``
        (seconds), ``retry_max_delay``, ``retry_jitter``,
        ``retry_deadline_s`` — the knobs the old hard-coded ``retry_io``
        call sites now expose."""
        kw = dict(
            attempts=int(_cfg_get(cfg, "retry_attempts", 4)),
            base_delay=float(_cfg_get(cfg, "retry_base_delay", 0.05)),
            max_delay=float(_cfg_get(cfg, "retry_max_delay", 2.0)),
            jitter=float(_cfg_get(cfg, "retry_jitter", 0.25)),
            deadline_s=float(_cfg_get(cfg, "retry_deadline_s", 0.0)),
        )
        kw.update(overrides)
        return cls(**kw)

    def delay_for(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry ``attempt`` (1-based)."""
        d = min(self.base_delay * (2 ** (attempt - 1)), self.max_delay)
        if self.jitter > 0:
            d *= 1.0 + self.jitter * rng.random()
        return d

    def run(
        self,
        fn: Callable,
        what: str = "I/O",
        silent: bool = False,
        _sleep: Callable[[float], None] = time.sleep,
        _clock: Callable[[], float] = time.monotonic,
    ):
        """Run ``fn()`` under the policy; the last failure propagates."""
        rng = random.Random(self.seed ^ zlib.crc32(what.encode()))
        t0 = _clock()
        for k in range(1, self.attempts + 1):
            try:
                return fn()
            except self.exceptions as e:
                if k == self.attempts:
                    raise
                delay = self.delay_for(k, rng)
                if (self.deadline_s > 0
                        and _clock() - t0 + delay > self.deadline_s):
                    if not silent:
                        print(
                            f"{what} failed ({type(e).__name__}: {e}); "
                            f"retry deadline {self.deadline_s:.2f}s "
                            "exhausted, giving up",
                            flush=True,
                        )
                    raise
                if not silent:
                    print(
                        f"{what} failed ({type(e).__name__}: {e}); "
                        f"retry {k}/{self.attempts - 1} in {delay:.2f}s",
                        flush=True,
                    )
                _sleep(delay)


def retried_read_lines(path: str, site: str, retry_cfg,
                       silent: bool = False) -> List[str]:
    """Whole-file line read under the configured :class:`RetryPolicy`,
    instrumented at ``site``.  ``errors='replace'``: a stray non-UTF8
    byte corrupts ONE row (quarantinable by the caller's budget)
    instead of aborting the whole-file read."""
    def _read():
        fault_point(site)
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            return f.readlines()

    return RetryPolicy.from_cfg(retry_cfg).run(
        _read, what=f"reading {path}", silent=silent)


# ----------------------------------------------------------------------
# watchdog
class Watchdog:
    """Fail-fast stall detector for a background worker.

    The worker calls :meth:`beat` on every unit of progress; a blocked
    consumer calls :meth:`check` (or :meth:`wait`) which raises
    :class:`WatchdogError` — with the worker thread's current stack in
    the message — once no beat has landed for ``timeout_s``.  A
    ``timeout_s <= 0`` watchdog is disabled (all methods no-op)."""

    def __init__(self, what: str = "worker", timeout_s: float = 600.0,
                 thread: Optional[threading.Thread] = None) -> None:
        self.what = what
        self.timeout_s = float(timeout_s)
        self.thread = thread
        self._last = time.monotonic()

    @property
    def enabled(self) -> bool:
        return self.timeout_s > 0

    def beat(self) -> None:
        self._last = time.monotonic()

    def stalled_for(self) -> float:
        return time.monotonic() - self._last

    def diagnostic(self, dt: float) -> str:
        msg = (f"{self.what} made no progress for {dt:.1f}s "
               f"(watchdog_timeout_s={self.timeout_s:g}); failing fast "
               "instead of blocking forever")
        t = self.thread
        if t is not None:
            if not t.is_alive():
                return msg + f"; thread {t.name!r} is DEAD"
            import sys
            import traceback

            frame = sys._current_frames().get(t.ident)
            if frame is not None:
                stack = "".join(traceback.format_stack(frame))
                msg += f"\nhung thread {t.name!r} stack:\n{stack}"
        return msg

    def _fire(self, dt: float) -> WatchdogError:
        obs_events.emit("watchdog.fire", what=self.what, stalled_s=dt,
                        timeout_s=self.timeout_s)
        return WatchdogError(self.diagnostic(dt))

    def check(self) -> None:
        if not self.enabled:
            return
        dt = self.stalled_for()
        if dt > self.timeout_s:
            raise self._fire(dt)

    def wait(self, event: threading.Event, poll: float = 0.2,
             since: Optional[float] = None) -> None:
        """Block on ``event`` with stall checks; raises on a stall.

        ``since`` anchors the stall window for THIS waiter: progress is
        ``max(last beat, since)``, so a worker that was legitimately
        idle before this wait began is not mistaken for hung — without
        the waiters themselves ever touching the shared beat clock
        (which would let steady traffic mask a genuinely hung worker).
        """
        if not self.enabled:
            event.wait()
            return
        if since is None:
            since = time.monotonic()
        while not event.wait(min(poll, self.timeout_s)):
            dt = time.monotonic() - max(self._last, since)
            if dt > self.timeout_s:
                raise self._fire(dt)


# ----------------------------------------------------------------------
# circuit breaker
class CircuitBreaker:
    """Consecutive-failure circuit breaker (closed → open → half-open).

    ``failure_threshold`` consecutive failures OPEN the circuit:
    :meth:`allow` returns False (callers skip the protected operation)
    until ``cooldown_s`` elapses, then exactly one trial call passes
    (HALF-OPEN); its success closes the circuit, its failure re-opens
    and restarts the cooldown.  Thread-safe."""

    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive = 0
        self._opened_at = 0.0
        self.total_failures = 0
        self.total_successes = 0
        self.times_opened = 0

    @property
    def state(self) -> str:
        with self._lock:
            if (self._state == "open"
                    and self._clock() - self._opened_at >= self.cooldown_s):
                return "half-open"
            return self._state

    def allow(self) -> bool:
        """May the protected operation run now?  The half-open trial is
        claimed by the caller that observes it (one at a time)."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._clock() - self._opened_at >= self.cooldown_s:
                # half-open: let one trial through; re-arm the cooldown
                # so concurrent pollers don't all pile in
                self._opened_at = self._clock()
                self._state = "half-open"
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self.total_successes += 1
            self._consecutive = 0
            self._state = "closed"

    def record_failure(self) -> None:
        with self._lock:
            self.total_failures += 1
            self._consecutive += 1
            if (self._state == "half-open"
                    or self._consecutive >= self.failure_threshold):
                if self._state != "open":
                    self.times_opened += 1
                self._state = "open"
                self._opened_at = self._clock()

    def snapshot(self) -> Dict[str, object]:
        return {
            "state": self.state,
            "consecutive_failures": self._consecutive,
            "total_failures": self.total_failures,
            "total_successes": self.total_successes,
            "times_opened": self.times_opened,
        }


# ----------------------------------------------------------------------
# skip-and-quarantine
class BadRecordBudget:
    """Skip-and-quarantine accounting for one data source.

    ``max_bad_records`` bounds skips **per epoch** (``start_epoch``
    resets the counter; a long run over data with a fixed set of bad
    records does not bleed its budget dry across epochs).  Each skipped
    record appends ``offset\\treason`` to a ``<source>.quarantine``
    sidecar (deduped across epochs), so a repack tool can excise the
    exact bad records later.  ``max_bad_records = 0`` keeps the strict
    legacy behavior: the first bad record aborts (as
    :class:`BadDataError` chaining the parse error)."""

    def __init__(self, max_bad_records: int = 0, what: str = "data",
                 silent: bool = False,
                 quarantine_dir: Optional[str] = None) -> None:
        self.max_bad_records = int(max_bad_records)
        self.what = what
        self.silent = silent
        self.quarantine_dir = quarantine_dir
        self.epoch_count = 0          # skips this epoch
        self.total_count = 0
        self.events: List[Tuple[str, object, str]] = []
        self._seen: set = set()
        self._sidecar_warned = False

    def start_epoch(self) -> None:
        self.epoch_count = 0

    def _sidecar_path(self, source: str) -> str:
        if self.quarantine_dir:
            os.makedirs(self.quarantine_dir, exist_ok=True)
            return os.path.join(
                self.quarantine_dir,
                os.path.basename(source) + ".quarantine",
            )
        return source + ".quarantine"

    def record(self, source: str, offset, exc: BaseException,
               note: str = "") -> None:
        """Count one bad record/page; raise :class:`BadDataError` when
        the budget is exhausted.  ``note`` carries collateral the event
        implies (e.g. how many trailing records a skipped page drops) so
        the loss is never under-reported."""
        reason = f"{type(exc).__name__}: {exc}"
        if note:
            reason += f" [{note}]"
        self.epoch_count += 1
        self.total_count += 1
        obs_events.emit("data.quarantined", what=self.what, source=source,
                        offset=offset, reason=reason,
                        epoch_count=self.epoch_count)
        key = (source, offset)
        if key not in self._seen:
            self._seen.add(key)
            self.events.append((source, offset, reason))
            # strict mode (budget 0) aborts without the sidecar side
            # effect — the pre-budget behavior left no files behind
            if self.max_bad_records > 0:
                try:
                    with open(self._sidecar_path(source), "a",
                              encoding="utf-8") as f:
                        f.write(f"{offset}\t{reason}\n")
                except OSError as e:
                    if not self._sidecar_warned:
                        self._sidecar_warned = True
                        print(f"{self.what}: cannot write quarantine "
                              f"sidecar ({e}); continuing without it",
                              flush=True)
        if self.epoch_count > self.max_bad_records:
            obs_events.emit("data.budget_exceeded", what=self.what,
                            epoch_count=self.epoch_count,
                            max_bad_records=self.max_bad_records,
                            source=source, offset=offset)
            raise BadDataError(
                f"{self.what}: bad-record budget exceeded "
                f"({self.epoch_count} bad records this epoch > "
                f"max_bad_records={self.max_bad_records}); last: "
                f"{source} @ {offset}: {reason}\n{self.summary()}"
            ) from exc
        if not self.silent:
            print(f"{self.what}: skipped bad record {source} @ {offset} "
                  f"({reason}) [{self.epoch_count}/"
                  f"{self.max_bad_records} this epoch]", flush=True)

    def summary(self) -> str:
        srcs = sorted({s for s, _, _ in self.events})
        return (f"{self.what}: {self.total_count} bad record(s) skipped "
                f"({len(self.events)} distinct) across "
                f"{len(srcs)} source(s): {', '.join(srcs) or '-'}")
