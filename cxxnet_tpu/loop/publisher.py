"""Eval-gated checkpoint publisher: no update ships without proof.

The gate between the continuous trainer and the serving plane
(doc/continuous_training.md).  A fine-tuned candidate is published —
written as the next ``NNNN.model`` in the engine's watch directory and
hot-reloaded — only when ALL of:

* **divergence guard** — every candidate weight is finite
  (``NetTrainer.weights_finite``, the PR 1 guard applied pre-publish
  instead of post-mortem);
* **eval gate** — the held-out eval metric is at least
  ``publish_min_delta`` better than the SERVING model's recorded
  metric (orientation-aware: error/rmse/logloss improve downward,
  rec@n upward).  ``publish_min_delta = 0`` means "no worse";

On acceptance the checkpoint is written through the atomic manifest
machinery (``utils/checkpoint.write_checkpoint``), the **publish
pointer** (``PUBLISHED.json``) flips to it — recording the previous
version for rollback — and the engine hot-reload hook fires so the new
weights serve immediately.  On rejection nothing reaches the model
directory; the caller (``loop/continuous.py``) rolls its trainer back
to the pointer's current version so fine-tuning never compounds on a
degraded model.  Every decision is emitted to the obs event log
(``loop.publish`` / ``loop.reject``) and counted in
``loop_publish_total{decision}`` — the ``/metricsz`` audit trail.
"""

from __future__ import annotations

import os
import re
from typing import Optional, Tuple

from ..obs import events as obs_events
from ..utils import checkpoint as ckpt
from .feedback_log import loop_metrics

__all__ = ["EvalGatedPublisher", "metric_improvement", "parse_eval_metric"]

#: metrics where a SMALLER value is better; anything else (rec@n) is
#: treated as larger-is-better
LOWER_IS_BETTER_PREFIXES = ("error", "rmse", "logloss")

_METRIC_RE = re.compile(
    r"(\S+?):([-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?)")


def parse_eval_metric(eval_text: str, metric_name: str = "",
                      prefix: str = "") -> Tuple[str, float]:
    """Extract ``(name, value)`` from a trainer eval line
    (``\\tname-metric:value`` format).  ``prefix`` restricts to one
    eval section's metrics (e.g. ``"eval-"`` — the trainer prepends a
    possibly-empty ``train-`` metric to the same line);
    ``metric_name`` further selects by substring; empty picks the
    first remaining metric.  Raises ``ValueError`` when nothing
    matches — a loop without a measurable gate must not silently
    publish."""
    pairs = _METRIC_RE.findall(eval_text or "")
    if prefix:
        pairs = [(n, v) for n, v in pairs if n.startswith(prefix)]
    if metric_name:
        pairs = [(n, v) for n, v in pairs if metric_name in n]
    if not pairs:
        want = " ".join(filter(None, (
            f"prefix {prefix!r}" if prefix else "",
            f"matching {metric_name!r}" if metric_name else "")))
        raise ValueError(
            f"no eval metric {want} in {eval_text!r}; the publish gate "
            "needs an eval section with a metric")
    name, val = pairs[0]
    return name, float(val)


def metric_improvement(name: str, serving: float, candidate: float) -> float:
    """Signed improvement of ``candidate`` over ``serving`` — positive
    is better, orientation-aware by metric name."""
    base = name.rsplit("-", 1)[-1]  # "eval-error[field]" -> "error[field]"
    lower_better = base.startswith(LOWER_IS_BETTER_PREFIXES)
    return (serving - candidate) if lower_better else (candidate - serving)


class EvalGatedPublisher:
    """Gatekeeper of the serving model directory.

    ``engine`` is the live serving engine (its ``model_dir`` is the
    publish target and its ``try_reload`` the hot-swap hook);
    ``eval_iter`` the held-out eval iterator the gate scores on.
    """

    def __init__(
        self,
        engine,
        eval_iter,
        eval_name: str = "eval",
        metric_name: str = "",
        min_delta: float = 0.0,
        silent: bool = True,
    ) -> None:
        if engine.model_dir is None:
            raise ValueError(
                "EvalGatedPublisher needs an engine watching a "
                "model_dir (the publish target)")
        self.engine = engine
        self.eval_iter = eval_iter
        self.eval_name = eval_name
        self.metric_name = metric_name
        self.min_delta = float(min_delta)
        self.silent = silent
        self._m = loop_metrics()
        self.serving_metric: Optional[float] = None
        self.serving_metric_name: Optional[str] = None

    # ------------------------------------------------------------------
    def evaluate(self, trainer) -> Tuple[str, float]:
        """Held-out eval of ``trainer``; returns ``(name, value)``.
        Only the eval section's own metrics qualify (the trainer
        prepends a ``train-`` metric to the same line when
        ``eval_train`` is on — scoring the gate on that would compare
        against an empty-count 0)."""
        text = trainer.evaluate(self.eval_iter, self.eval_name)
        return parse_eval_metric(text, self.metric_name,
                                 prefix=f"{self.eval_name}-")

    def record_serving_baseline(self, trainer) -> float:
        """Score the SERVING weights (``trainer`` must still hold them)
        — the bar every candidate is gated against until a publish
        moves it."""
        name, val = self.evaluate(trainer)
        self.serving_metric, self.serving_metric_name = val, name
        obs_events.emit("loop.baseline", metric=name, value=val,
                        round=self.engine.round)
        if not self.silent:
            print(f"loop: serving baseline {name}:{val:g} "
                  f"(round {self.engine.round})", flush=True)
        return val

    # ------------------------------------------------------------------
    def consider(self, trainer, cycle: int = -1,
                 lineage: Optional[dict] = None) -> bool:
        """Gate one candidate; publish + hot-reload on pass.

        Returns True when the candidate was published.  On any gate
        failure (non-finite weights, eval regression beyond
        ``min_delta``) nothing is written and False returns — the
        caller rolls the trainer back.  ``lineage`` (the feedback-record
        id range + count the candidate was fine-tuned on) rides into the
        publish pointer so a served model is traceable back to the
        requests that trained it."""
        if self.serving_metric is None:
            raise RuntimeError(
                "record_serving_baseline must run before consider()")
        if not trainer.weights_finite():
            self._reject(cycle, reason="non-finite weights",
                         metric=self.serving_metric_name,
                         candidate=None)
            return False
        name, cand = self.evaluate(trainer)
        gain = metric_improvement(name, self.serving_metric, cand)
        if gain < self.min_delta:
            self._reject(
                cycle, reason=f"eval gate: improvement {gain:g} < "
                              f"publish_min_delta {self.min_delta:g}",
                metric=name, candidate=cand)
            return False
        self._publish(trainer, name, cand, gain, cycle, lineage=lineage)
        return True

    # ------------------------------------------------------------------
    def _reject(self, cycle: int, reason: str, metric,
                candidate) -> None:
        self._m.publishes.labels(decision="rejected").inc()
        obs_events.emit("loop.reject", cycle=cycle, reason=reason,
                        metric=metric, candidate=candidate,
                        serving=self.serving_metric,
                        serving_round=self.engine.round)
        if not self.silent:
            print(f"loop: candidate REJECTED ({reason}; serving "
                  f"{metric}:{self.serving_metric:g}"
                  + (f", candidate {candidate:g}"
                     if candidate is not None else "") + ")",
                  flush=True)

    def _publish(self, trainer, name: str, cand: float, gain: float,
                 cycle: int, lineage: Optional[dict] = None) -> None:
        model_dir = self.engine.model_dir
        prev_round = self.engine.round
        latest = ckpt.list_checkpoints(model_dir)
        round_ = max(prev_round, latest[-1][0] if latest else -1) + 1
        path = ckpt.publish_path(model_dir, round_)
        blob = trainer.checkpoint_bytes()
        ckpt.write_checkpoint(
            path, blob, round_=round_, net_fp=trainer.net_fp(),
            save_ustate=trainer.save_ustate, retry=True,
            silent=self.silent,
        )
        ckpt.write_publish_pointer(
            model_dir, round_, path,
            net_fp=trainer.net_fp(),
            metric={"name": name, "value": cand},
            prev_round=prev_round,
            lineage=lineage,
        )
        self.serving_metric, self.serving_metric_name = cand, name
        # the reload hook: the engine swaps to the published round NOW
        # (breaker-gated) instead of waiting for a poll period
        swapped = self.engine.try_reload()
        self._m.publishes.labels(decision="published").inc()
        obs_events.emit("loop.publish", cycle=cycle, round=round_,
                        path=path, metric=name, candidate=cand,
                        gain=gain, swapped=swapped,
                        prev_round=prev_round, lineage=lineage)
        if not self.silent:
            print(f"loop: PUBLISHED round {round_} ({name}:{cand:g}, "
                  f"improvement {gain:g}, reloaded={swapped})",
                  flush=True)

    # ------------------------------------------------------------------
    def rollback_target(self) -> Optional[Tuple[int, str]]:
        """Where a rejected trainer should roll back to: the publish
        pointer's current version when one exists and validates, else
        the newest valid checkpoint in the model directory."""
        model_dir = self.engine.model_dir
        ptr = ckpt.read_publish_pointer(model_dir)
        if ptr is not None:
            path = ptr.get("path")
            if (path and os.path.exists(path)
                    and ckpt.validate_checkpoint(path) is None):
                return int(ptr["round"]), path
        return ckpt.find_latest_valid(model_dir, silent=True)
