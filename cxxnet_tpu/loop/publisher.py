"""Eval-gated checkpoint publisher: no update ships without proof.

The gate between the continuous trainer and the serving plane
(doc/continuous_training.md).  A fine-tuned candidate is published —
written as the next ``NNNN.model`` in the engine's watch directory and
hot-reloaded — only when ALL of:

* **divergence guard** — every candidate weight is finite
  (``NetTrainer.weights_finite``, the PR 1 guard applied pre-publish
  instead of post-mortem);
* **per-slice gate** (``publish_slice_floor >= 0``) — no eval cohort's
  accuracy may regress more than the floor below the serving model's
  recorded cohort vector.  Cohorts are per-class (``class:<k>`` from
  the label's first column) and, with ``publish_source_field = <col>``,
  per-source (``source:<v>`` from that label column) — so a candidate
  cannot buy aggregate accuracy by sacrificing one slice of users, and
  a rejection NAMES the cohort it sacrificed (the reject event also
  carries the cycle's lineage, so the regression is attributable to the
  exact feedback seq range that caused it);
* **eval gate** — the held-out eval metric is at least
  ``publish_min_delta`` better than the SERVING model's recorded
  metric (orientation-aware: error/rmse/logloss improve downward,
  rec@n upward).  ``publish_min_delta = 0`` means "no worse";

On acceptance the checkpoint is written through the atomic manifest
machinery (``utils/checkpoint.write_checkpoint``), the **publish
pointer** (``PUBLISHED.json``) flips to it — recording the previous
version for rollback, the gate metric AND its cohort vector — and the
engine hot-reload hook fires so the new weights serve immediately.
Persisting the bar in the pointer is what makes restarts honest:
:meth:`EvalGatedPublisher.record_serving_baseline` reads the recorded
metric back instead of re-scoring the same weights, so a restarted
loop gates against the bar the serving model actually cleared, not a
fresh re-eval of it.  On rejection nothing reaches the model
directory; the caller (``loop/continuous.py``) rolls its trainer back
to the pointer's current version so fine-tuning never compounds on a
degraded model.  Every decision is emitted to the obs event log
(``loop.publish`` / ``loop.reject``) and counted in
``loop_publish_total{decision}`` — the ``/metricsz`` audit trail.
"""

from __future__ import annotations

import os
import re
from typing import Dict, Optional, Tuple

import numpy as np

from ..obs import events as obs_events
from ..utils import checkpoint as ckpt
from .feedback_log import loop_metrics

__all__ = [
    "EvalGatedPublisher",
    "accumulate_cohort_counts",
    "cohort_accuracy",
    "metric_improvement",
    "parse_eval_metric",
]

#: metrics where a SMALLER value is better; anything else (rec@n) is
#: treated as larger-is-better
LOWER_IS_BETTER_PREFIXES = ("error", "rmse", "logloss")

_METRIC_RE = re.compile(
    r"(\S+?):([-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?)")


def parse_eval_metric(eval_text: str, metric_name: str = "",
                      prefix: str = "") -> Tuple[str, float]:
    """Extract ``(name, value)`` from a trainer eval line
    (``\\tname-metric:value`` format).  ``prefix`` restricts to one
    eval section's metrics (e.g. ``"eval-"`` — the trainer prepends a
    possibly-empty ``train-`` metric to the same line);
    ``metric_name`` further selects by substring; empty picks the
    first remaining metric.  Raises ``ValueError`` when nothing
    matches — a loop without a measurable gate must not silently
    publish."""
    pairs = _METRIC_RE.findall(eval_text or "")
    if prefix:
        pairs = [(n, v) for n, v in pairs if n.startswith(prefix)]
    if metric_name:
        pairs = [(n, v) for n, v in pairs if metric_name in n]
    if not pairs:
        want = " ".join(filter(None, (
            f"prefix {prefix!r}" if prefix else "",
            f"matching {metric_name!r}" if metric_name else "")))
        raise ValueError(
            f"no eval metric {want} in {eval_text!r}; the publish gate "
            "needs an eval section with a metric")
    name, val = pairs[0]
    return name, float(val)


def metric_improvement(name: str, serving: float, candidate: float) -> float:
    """Signed improvement of ``candidate`` over ``serving`` — positive
    is better, orientation-aware by metric name."""
    base = name.rsplit("-", 1)[-1]  # "eval-error[field]" -> "error[field]"
    lower_better = base.startswith(LOWER_IS_BETTER_PREFIXES)
    return (serving - candidate) if lower_better else (candidate - serving)


# ----------------------------------------------------------------------
# cohort metrics (the per-slice gate's eval plane)
def accumulate_cohort_counts(
    counts: Dict[str, list],
    preds: np.ndarray,
    labels: np.ndarray,
    source_field: Optional[int] = None,
) -> None:
    """Fold one eval batch into ``{cohort: [correct, total]}``.

    Cohorts: ``class:<k>`` keyed by the label's first column (the
    classification target), and ``source:<v>`` keyed by label column
    ``source_field`` when given (a request-source/user-segment tag the
    feedback or eval pipeline carries as an extra label field).
    Correctness is prediction == target, i.e. cohort accuracy — one
    orientation regardless of the aggregate gate metric, so floors
    compare the same way for every conf."""
    labels = np.asarray(labels)
    if labels.ndim == 1:
        labels = labels[:, None]
    preds = np.asarray(preds).reshape(labels.shape[0], -1)[:, 0]
    target = labels[:, 0]
    hit = preds == target
    keys = [("class", target)]
    if source_field is not None and 0 <= source_field < labels.shape[1]:
        keys.append(("source", labels[:, source_field]))
    for prefix, col in keys:
        for v in np.unique(col):
            mask = col == v
            tag = f"{prefix}:{int(v) if float(v).is_integer() else v}"
            c = counts.setdefault(tag, [0, 0])
            c[0] += int(hit[mask].sum())
            c[1] += int(mask.sum())


def cohort_accuracy(counts: Dict[str, list],
                    min_count: int = 0) -> Dict[str, float]:
    """``{cohort: accuracy}`` from accumulated counts; cohorts with
    fewer than ``min_count`` eval rows are dropped (too small to gate
    on without noise-rejecting every publish)."""
    return {k: c / t for k, (c, t) in counts.items()
            if t and t >= min_count}


class EvalGatedPublisher:
    """Gatekeeper of the serving model directory.

    ``engine`` is the live serving engine (its ``model_dir`` is the
    publish target and its ``try_reload`` the hot-swap hook);
    ``eval_iter`` the held-out eval iterator the gate scores on.
    """

    def __init__(
        self,
        engine,
        eval_iter,
        eval_name: str = "eval",
        metric_name: str = "",
        min_delta: float = 0.0,
        slice_floor: Optional[float] = None,
        slice_min_count: int = 8,
        source_field: Optional[int] = None,
        tenant: str = "",
        silent: bool = True,
    ) -> None:
        if engine.model_dir is None:
            raise ValueError(
                "EvalGatedPublisher needs an engine watching a "
                "model_dir (the publish target)")
        self.engine = engine
        self.eval_iter = eval_iter
        self.eval_name = eval_name
        self.metric_name = metric_name
        self.min_delta = float(min_delta)
        self.slice_floor = (None if slice_floor is None
                            else float(slice_floor))
        self.slice_min_count = int(slice_min_count)
        self.source_field = source_field
        self.tenant = tenant
        self.silent = silent
        self._m = loop_metrics()
        self.serving_metric: Optional[float] = None
        self.serving_metric_name: Optional[str] = None
        self.serving_cohorts: Optional[Dict[str, float]] = None
        self.last_gain: Optional[float] = None

    def _tag(self) -> dict:
        """Tenant identity folded into every event (multi-tenant runs
        need the audit trail to name whose loop decided)."""
        return {"tenant": self.tenant} if self.tenant else {}

    @property
    def slice_armed(self) -> bool:
        return self.slice_floor is not None and self.slice_floor >= 0

    # ------------------------------------------------------------------
    def evaluate(self, trainer) -> Tuple[str, float]:
        """Held-out eval of ``trainer``; returns ``(name, value)``.
        Only the eval section's own metrics qualify (the trainer
        prepends a ``train-`` metric to the same line when
        ``eval_train`` is on — scoring the gate on that would compare
        against an empty-count 0)."""
        text = trainer.evaluate(self.eval_iter, self.eval_name)
        return parse_eval_metric(text, self.metric_name,
                                 prefix=f"{self.eval_name}-")

    def evaluate_cohorts(self, trainer) -> Dict[str, float]:
        """Per-cohort accuracy of ``trainer`` over the held-out eval
        set (one extra predict pass; only run when the slice gate is
        armed).  Small cohorts (< ``slice_min_count`` rows) are dropped
        — see :func:`cohort_accuracy`."""
        counts: Dict[str, list] = {}
        self.eval_iter.before_first()
        while self.eval_iter.next():
            batch = self.eval_iter.value()
            n = batch.batch_size - batch.num_batch_padd
            if n <= 0:
                continue
            preds = trainer.predict(batch)[:n]
            labels = np.asarray(batch.label)[:n]
            accumulate_cohort_counts(counts, preds, labels,
                                     source_field=self.source_field)
        return cohort_accuracy(counts, min_count=self.slice_min_count)

    def record_serving_baseline(self, trainer) -> float:
        """Establish the bar every candidate is gated against.

        The bar is the RECORDED one when ``PUBLISHED.json`` names the
        round the engine is serving — a restarted loop must gate
        against the metric the serving model actually cleared, not a
        fresh re-eval of the same weights (re-baselining on restart
        silently reset the bar every time the manager bounced).  Only
        when no pointer covers the serving round (first boot of a
        model_dir, or an operator dropped a newer checkpoint in) are
        the serving weights scored fresh — and the result is persisted
        into the pointer so the NEXT restart reads it back."""
        ptr = ckpt.read_publish_pointer(self.engine.model_dir)
        met = (ptr or {}).get("metric") or {}
        recorded = (
            ptr is not None
            and int(ptr.get("round", -1)) == self.engine.round
            and isinstance(met.get("value"), (int, float))
            and (not self.metric_name
                 or self.metric_name in str(met.get("name") or ""))
        )
        live: Optional[Tuple[str, float]] = None
        if recorded and not self.metric_name:
            # no gate metric configured: candidates gate under whatever
            # the eval plane reports FIRST, so the recorded bar is only
            # comparable if that metric still carries the recorded name
            # (an eval-conf change between restarts would otherwise
            # compare values of different, possibly opposite-orientation
            # metrics).  One name-validation eval — the VALUE bar stays
            # recorded when the name matches.
            live = self.evaluate(trainer)
            if live[0] != str(met.get("name") or ""):
                recorded = False
        if recorded:
            name, val = str(met["name"]), float(met["value"])
            cohorts = met.get("cohorts")
            self.serving_cohorts = (dict(cohorts)
                                    if isinstance(cohorts, dict) else None)
            if self.slice_armed and self.serving_cohorts is None:
                # pointer predates slice gating: grow it the cohort
                # vector once, preserving every other recorded field
                self.serving_cohorts = self.evaluate_cohorts(trainer)
                self._write_pointer(
                    ptr["round"], ptr["path"],
                    net_fp=ptr.get("net_fingerprint"),
                    name=name, value=val, cohorts=self.serving_cohorts,
                    prev_round=(ptr.get("prev") or {}).get("round"),
                    lineage=ptr.get("lineage"))
        else:
            name, val = (live if live is not None
                         else self.evaluate(trainer))
            self.serving_cohorts = (self.evaluate_cohorts(trainer)
                                    if self.slice_armed else None)
            if self.engine.model_path is not None:
                self._write_pointer(
                    self.engine.round, self.engine.model_path,
                    net_fp=trainer.net_fp(), name=name, value=val,
                    cohorts=self.serving_cohorts,
                    prev_round=(ptr or {}).get("round"))
        self.serving_metric, self.serving_metric_name = val, name
        obs_events.emit("loop.baseline", metric=name, value=val,
                        round=self.engine.round,
                        source="recorded" if recorded else "evaluated",
                        **self._tag())
        if not self.silent:
            print(f"loop: serving baseline {name}:{val:g} "
                  f"({'recorded' if recorded else 'evaluated'}, "
                  f"round {self.engine.round})", flush=True)
        return val

    def _write_pointer(self, round_, path, net_fp, name, value,
                       cohorts=None, prev_round=None,
                       lineage=None) -> None:
        metric = {"name": name, "value": value}
        if cohorts is not None:
            metric["cohorts"] = {k: round(float(v), 6)
                                 for k, v in cohorts.items()}
        ckpt.write_publish_pointer(
            self.engine.model_dir, int(round_), path, net_fp=net_fp,
            metric=metric, prev_round=prev_round, lineage=lineage)

    # ------------------------------------------------------------------
    def consider(self, trainer, cycle: int = -1,
                 lineage: Optional[dict] = None) -> bool:
        """Gate one candidate; publish + hot-reload on pass.

        Returns True when the candidate was published.  On any gate
        failure (non-finite weights, eval regression beyond
        ``min_delta``) nothing is written and False returns — the
        caller rolls the trainer back.  ``lineage`` (the feedback-record
        id range + count the candidate was fine-tuned on) rides into the
        publish pointer so a served model is traceable back to the
        requests that trained it."""
        if self.serving_metric is None:
            raise RuntimeError(
                "record_serving_baseline must run before consider()")
        self.last_gain = None
        if not trainer.weights_finite():
            self._reject(cycle, reason="non-finite weights",
                         metric=self.serving_metric_name,
                         candidate=None, lineage=lineage)
            return False
        name, cand = self.evaluate(trainer)
        cand_cohorts = (self.evaluate_cohorts(trainer)
                        if self.slice_armed else None)
        # the slice gate runs FIRST: when a cohort regressed beyond the
        # floor, the rejection must name the cohort (the actionable
        # fact) even if the aggregate gate would also have failed
        if self.slice_armed and self.serving_cohorts:
            worst = None  # (drop, cohort, base, got)
            for cohort, base_acc in self.serving_cohorts.items():
                got = cand_cohorts.get(cohort)
                if got is None:
                    continue  # cohort shrank below min_count: not gated
                drop = float(base_acc) - float(got)
                if drop > self.slice_floor and (
                        worst is None or drop > worst[0]):
                    worst = (drop, cohort, float(base_acc), float(got))
            if worst is not None:
                drop, cohort, base_acc, got = worst
                self._reject(
                    cycle,
                    reason=f"slice gate: cohort {cohort} accuracy "
                           f"{base_acc:.4g} -> {got:.4g} (drop {drop:.4g}"
                           f" > publish_slice_floor "
                           f"{self.slice_floor:g})",
                    metric=name, candidate=cand, cohort=cohort,
                    lineage=lineage)
                return False
        gain = metric_improvement(name, self.serving_metric, cand)
        if gain < self.min_delta:
            self._reject(
                cycle, reason=f"eval gate: improvement {gain:g} < "
                              f"publish_min_delta {self.min_delta:g}",
                metric=name, candidate=cand, lineage=lineage)
            return False
        self._publish(trainer, name, cand, gain, cycle, lineage=lineage,
                      cohorts=cand_cohorts)
        return True

    # ------------------------------------------------------------------
    def _reject(self, cycle: int, reason: str, metric, candidate,
                cohort: Optional[str] = None,
                lineage: Optional[dict] = None) -> None:
        self._m.publishes.labels(decision="rejected").inc()
        obs_events.emit("loop.reject", cycle=cycle, reason=reason,
                        metric=metric, candidate=candidate,
                        serving=self.serving_metric,
                        serving_round=self.engine.round,
                        cohort=cohort, lineage=lineage, **self._tag())
        if not self.silent:
            print(f"loop: candidate REJECTED ({reason}; serving "
                  f"{metric}:{self.serving_metric:g}"
                  + (f", candidate {candidate:g}"
                     if candidate is not None else "") + ")",
                  flush=True)

    def _publish(self, trainer, name: str, cand: float, gain: float,
                 cycle: int, lineage: Optional[dict] = None,
                 cohorts: Optional[Dict[str, float]] = None) -> None:
        model_dir = self.engine.model_dir
        prev_round = self.engine.round
        latest = ckpt.list_checkpoints(model_dir)
        round_ = max(prev_round, latest[-1][0] if latest else -1) + 1
        path = ckpt.publish_path(model_dir, round_)
        blob = trainer.checkpoint_bytes()
        ckpt.write_checkpoint(
            path, blob, round_=round_, net_fp=trainer.net_fp(),
            save_ustate=trainer.save_ustate, retry=True,
            silent=self.silent,
        )
        self._write_pointer(
            round_, path, net_fp=trainer.net_fp(),
            name=name, value=cand, cohorts=cohorts,
            prev_round=prev_round, lineage=lineage,
        )
        self.serving_metric, self.serving_metric_name = cand, name
        if cohorts is not None:
            self.serving_cohorts = dict(cohorts)
        self.last_gain = gain
        # the reload hook: the engine swaps to the published round NOW
        # (breaker-gated) instead of waiting for a poll period
        swapped = self.engine.try_reload()
        self._m.publishes.labels(decision="published").inc()
        obs_events.emit("loop.publish", cycle=cycle, round=round_,
                        path=path, metric=name, candidate=cand,
                        gain=gain, swapped=swapped,
                        prev_round=prev_round, lineage=lineage,
                        **self._tag())
        if not self.silent:
            print(f"loop: PUBLISHED round {round_} ({name}:{cand:g}, "
                  f"improvement {gain:g}, reloaded={swapped})",
                  flush=True)

    # ------------------------------------------------------------------
    def rollback_target(self) -> Optional[Tuple[int, str]]:
        """Where a rejected trainer should roll back to: the publish
        pointer's current version when one exists and validates, else
        the newest valid checkpoint in the model directory."""
        model_dir = self.engine.model_dir
        ptr = ckpt.read_publish_pointer(model_dir)
        if ptr is not None:
            path = ptr.get("path")
            if (path and os.path.exists(path)
                    and ckpt.validate_checkpoint(path) is None):
                return int(ptr["round"]), path
        return ckpt.find_latest_valid(model_dir, silent=True)
