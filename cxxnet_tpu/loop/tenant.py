"""Multi-tenant continuous learning: N loops, one device pool.

``task=loop_fleet`` (doc/continuous_training.md "Multi-tenant loops").
The production shape of arXiv 1605.08695 applied to the closed loop:
N named models share one machine, each with its own serving engine,
feedback log, replay/eval streams and :class:`ContinuousLoop`, while a
single scheduler serializes their fine-tune cycles onto the ONE shared
device pool the serve plane also runs on.

* **tenants** — each ``[tenant:<name>]`` conf section
  (``config.split_tenant_sections``) names a model: its ``model_dir``
  (required), optionally its ``feedback_dir``, and any per-tenant
  overrides of the shared loop/publish/iterator keys.  A tenant's
  effective config is the shared stream + its section appended, so the
  usual last-entry-wins rule resolves everything — same net, different
  weights/feedback/knobs.
* **arbiter** — fine-tune rounds per tenant are runtime knobs
  (``tune/targets.tenant_round_knobs``) hill-climbed by a PR-8
  :class:`~cxxnet_tpu.tune.KnobController` whose objective is the
  aggregate published-improvement rate (each publish contributes
  ``1 + max(gain, 0)`` work units), subject to the serve plane's SLO:
  while ANY ``/alertz`` rule fires (e.g. the p99 bound), the scheduler
  SHEDS fine-tune cycles entirely — training is the elastic load, serve
  traffic is not (``loop_shed_total`` counts shed ticks, and the
  controller pauses so the starvation cannot be misread as a knob
  regression).
* **routing** — the serve front-end dispatches by the request's
  ``model`` field through a :class:`~cxxnet_tpu.serve.router.
  ModelRouter` (``/predict`` to the tenant's engine, ``/feedback`` to
  the tenant's log; unknown model → 404 with the machine-readable
  ``unknown_model`` reason token).
* **retention** — every tenant gets a :class:`~cxxnet_tpu.loop.
  retention.Sweeper` compacting consumed feedback shards behind its
  cursor (``feedback_retain_*`` keys; doc/conf.md), swept after every
  trained cycle and on every manager tick.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from .. import config as cfgmod
from ..obs import events as obs_events
from ..obs.registry import registry as obs_registry
from ..tune.controller import KnobController, TuneOptions
from ..tune.targets import tenant_round_knobs
from .continuous import ContinuousLoop
from .feedback_log import FeedbackWriter
from .retention import RetentionOptions, Sweeper

__all__ = ["Tenant", "TenantArbiter", "TenantManager", "TenantOptions"]

ConfigEntry = Tuple[str, str]


class _TenantMetrics:
    def __init__(self) -> None:
        reg = obs_registry()
        self.cycles = reg.counter(
            "tenant_cycles_total",
            "Per-tenant continuous-loop cycles by outcome "
            "(idle / published / rejected / error).",
            labelnames=("tenant", "outcome"))
        self.pending = reg.gauge(
            "tenant_pending_records",
            "Feedback records committed but not yet consumed by a "
            "tenant's cursor.",
            labelnames=("tenant",))
        self.sheds = reg.counter(
            "loop_shed_total",
            "Scheduler ticks where ALL tenants' fine-tune cycles were "
            "shed because an SLO alert was firing.")
        self.tenants = reg.gauge(
            "loop_tenants",
            "Tenants hosted by the running loop-fleet manager.")


_METRICS: Optional[_TenantMetrics] = None
_METRICS_LOCK = threading.Lock()


def _metrics() -> _TenantMetrics:
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            _METRICS = _TenantMetrics()
        return _METRICS


class TenantOptions:
    """Shared loop defaults a tenant section can override (the
    ``loop_*`` / ``publish_*`` / ``feedback_*`` keys, parsed
    last-entry-wins from the tenant's effective config stream).

    ``DEFAULTS`` is the ONE table of these defaults — the CLI driver
    (``cli.LearnTask.__init__``) seeds its ``task=serve_train``
    attributes from it, so the single-tenant and multi-tenant parsers
    cannot drift apart on the same conf."""

    DEFAULTS = {
        "loop_rounds_per_cycle": 2,
        "loop_rounds_max": 8,        # arbiter knob ceiling
        "loop_replay_ratio": 0.25,
        "loop_min_records": 64,
        "loop_max_records": 0,       # per cycle; 0 = everything pending
        "publish_min_delta": 0.0,
        "publish_metric": "",        # substring match; "" = first reported
        "publish_slice_floor": -1.0,  # cohort gate; < 0 = off
        "publish_slice_min_count": 8,
        "publish_source_field": -1,  # label column keying source:<v>
        "feedback_page_bytes": 1 << 20,
        "feedback_rotate_bytes": 8 << 20,
        "feedback_retain_shards": -1,  # retention; < 0 = off
        "feedback_retain_bytes": 0,
    }

    def __init__(self, cfg: Sequence[ConfigEntry]) -> None:
        vals = dict(self.DEFAULTS)
        for name, val in cfg:
            if name in vals:
                vals[name] = type(self.DEFAULTS[name])(val) \
                    if not isinstance(self.DEFAULTS[name], str) else val
        self.__dict__.update(vals)

    @property
    def slice_floor(self) -> Optional[float]:
        return (self.publish_slice_floor
                if self.publish_slice_floor >= 0 else None)

    @property
    def source_field(self) -> Optional[int]:
        return (self.publish_source_field
                if self.publish_source_field >= 0 else None)


class Tenant:
    """One hosted model: engine + feedback log + loop + retention.

    ``cfg`` is the tenant's EFFECTIVE ordered stream (shared entries +
    its section appended); ``make_iters`` builds the tenant's own
    replay/eval iterator instances from it (iterators are stateful —
    they are never shared across tenants).
    """

    def __init__(
        self,
        name: str,
        cfg: List[ConfigEntry],
        make_iters,
        engine_factory,
        loop_dir: str,
        silent: bool = True,
    ) -> None:
        import os

        self.name = name
        self.cfg = cfg
        opts = TenantOptions(cfg)
        self.opts = opts
        model_dir = cfgmod.cfg_get(cfg, "model_dir")
        if not model_dir:
            raise ValueError(
                f"[tenant:{name}] needs a model_dir (its serving "
                "checkpoints and publish target)")
        self.model_dir = model_dir
        self.feedback_dir = cfgmod.cfg_get(
            cfg, "feedback_dir",
            os.path.join(loop_dir, name, "feedback"))
        self.engine = engine_factory(cfg, model_dir)
        self.feedback = FeedbackWriter(
            self.feedback_dir,
            page_bytes=opts.feedback_page_bytes,
            rotate_bytes=opts.feedback_rotate_bytes,
        )
        base_iter, eval_iter, eval_name = make_iters(cfg)
        retention = None
        ropts = RetentionOptions(opts.feedback_retain_shards,
                                 opts.feedback_retain_bytes)
        if ropts.armed:
            retention = Sweeper(self.feedback_dir, ropts, tenant=name,
                                silent=silent)
        self.loop = ContinuousLoop(
            self.engine,
            cfg,
            feedback_dir=self.feedback_dir,
            base_iter=base_iter,
            eval_iter=eval_iter,
            eval_name=eval_name,
            rounds_per_cycle=opts.loop_rounds_per_cycle,
            replay_ratio=opts.loop_replay_ratio,
            min_records=opts.loop_min_records,
            max_records_per_cycle=opts.loop_max_records,
            publish_min_delta=opts.publish_min_delta,
            publish_metric=opts.publish_metric,
            publish_slice_floor=opts.slice_floor,
            publish_slice_min_count=opts.publish_slice_min_count,
            publish_source_field=opts.source_field,
            feedback_writer=self.feedback,
            retention=retention,
            name=name,
            silent=silent,
        )

    def close(self) -> None:
        for closer in (self.loop.stop, self.feedback.close,
                       self.engine.close):
            try:
                closer()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass


class TenantArbiter:
    """SLO-constrained allocator of fine-tune rounds across tenants.

    The PR-8 pattern applied to training effort: per-tenant
    ``rounds_per_cycle`` knobs hill-climbed against a monotonic work
    objective — cumulative published improvement, each publish worth
    ``1 + max(gain, 0)`` so frequency and magnitude both count.  The
    SLO overlay is hard, not hill-climbed: while any alert rule fires
    the scheduler sheds ALL tune cycles (serve traffic owns the pool),
    and the controller does not tick — a shed interval measuring zero
    work must never be attributed to whatever knob happened to be on
    probe.
    """

    def __init__(self, loops, tune_opts: Optional[TuneOptions] = None,
                 max_rounds: int = 8) -> None:
        opts = tune_opts or TuneOptions()
        self._lock = threading.Lock()
        self._work = 0.0
        self.shedding = False
        self._m = _metrics()
        self.controller = KnobController(
            objective=self.work,
            knobs=tenant_round_knobs(loops, max_rounds=max_rounds),
            period_s=opts.period_s,
            band=opts.band,
            measure_ticks=opts.measure_ticks,
            settle_ticks=opts.settle_ticks,
            cooldown_ticks=opts.cooldown_ticks,
            name="tenant_arbiter",
        )

    def work(self) -> float:
        with self._lock:
            return self._work

    def note_publish(self, gain: Optional[float]) -> None:
        with self._lock:
            self._work += 1.0 + max(0.0, float(gain or 0.0))

    # ------------------------------------------------------------------
    def slo_firing(self) -> List[str]:
        """Names of the alert rules currently firing — the shed signal
        (the same evaluator ``/alertz`` serves)."""
        from ..obs import alerts as obs_alerts

        try:
            return obs_alerts.evaluator().firing()
        except Exception:  # noqa: BLE001 - a broken evaluator must
            return []      # not stall every tenant's training forever

    def tick(self, now: Optional[float] = None) -> bool:
        """One scheduler decision: returns True when tune cycles may
        run this tick (no SLO alert firing), False when shed."""
        firing = self.slo_firing()
        if firing:
            if not self.shedding:
                obs_events.emit("tenant.shed", alerts=firing)
            self.shedding = True
            self._m.sheds.inc()
            return False
        if self.shedding:
            self.shedding = False
            obs_events.emit("tenant.shed_cleared")
        self.controller.step_once(now)
        return True


class TenantManager:
    """Host N tenants; schedule their loops onto the shared pool.

    One scheduler thread serializes every tenant's fine-tune cycles
    (round-robin, one cycle per tenant per tick) — the device pool is
    shared with the colocated serve engines, so training never runs
    concurrently with itself, and the arbiter sheds it entirely while
    the serve plane's SLO alerts fire.
    """

    def __init__(
        self,
        shared_cfg: Sequence[ConfigEntry],
        tenant_sections: Sequence[cfgmod.TenantSection],
        engine_factory,
        make_iters,
        loop_dir: str = "loop",
        period_s: float = 2.0,
        tune_opts: Optional[TuneOptions] = None,
        silent: bool = True,
    ) -> None:
        if not tenant_sections:
            raise ValueError(
                "task=loop_fleet needs at least one [tenant:<name>] "
                "section (tenant = <name> .. tenant = end)")
        self.period_s = float(period_s)
        self.silent = silent
        self._m = _metrics()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.tenants: List[Tenant] = []
        shared = list(shared_cfg)
        try:
            for sec in tenant_sections:
                self.tenants.append(Tenant(
                    sec.name, shared + list(sec.entries),
                    make_iters=make_iters, engine_factory=engine_factory,
                    loop_dir=loop_dir, silent=silent))
        except Exception:
            self.close()
            raise
        max_rounds = max(t.opts.loop_rounds_max for t in self.tenants)
        self.arbiter = TenantArbiter(
            [t.loop for t in self.tenants], tune_opts=tune_opts,
            max_rounds=max_rounds)
        self._m.tenants.set(len(self.tenants))
        obs_events.emit("tenant.manager_up",
                        tenants=[t.name for t in self.tenants])

    # ------------------------------------------------------------------
    def tenant(self, name: str) -> Tenant:
        for t in self.tenants:
            if t.name == name:
                return t
        raise KeyError(name)

    def router(self):
        """A :class:`~cxxnet_tpu.serve.router.ModelRouter` over the
        tenants (first tenant is the default route, matching the
        single-model server's behavior for model-less requests)."""
        from ..serve.router import ModelRouter

        r = ModelRouter()
        for i, t in enumerate(self.tenants):
            r.add(t.name, t.engine, feedback=t.feedback,
                  default=(i == 0))
        return r

    # ------------------------------------------------------------------
    def tick_once(self) -> Dict[str, str]:
        """One scheduler pass: arbiter decision, then (unless shed) one
        cycle per tenant, then retention.  Returns each tenant's cycle
        outcome — tests and bench harnesses drive this directly."""
        out: Dict[str, str] = {}
        may_train = self.arbiter.tick()
        for t in self.tenants:
            if not may_train:
                out[t.name] = "shed"
                t.loop.sweep_retention()
                continue
            try:
                outcome = t.loop.run_cycle()
            except Exception as e:  # noqa: BLE001 - one tenant's broken
                # cycle must not starve its neighbors
                outcome = "error"
                obs_events.log_exception_once(
                    f"tenant.cycle.{t.name}", e,
                    kind="loop.cycle_error", tenant=t.name)
            if outcome == "published":
                self.arbiter.note_publish(t.loop.publisher.last_gain)
            self._m.cycles.labels(tenant=t.name, outcome=outcome).inc()
            out[t.name] = outcome
        self._update_pending()
        return out

    def _update_pending(self) -> None:
        for t in self.tenants:
            try:
                self._m.pending.labels(tenant=t.name).set(
                    float(t.loop.reader.pending(
                        t.loop.cursor_file.load())))
            except Exception:  # noqa: BLE001 - gauge only
                pass

    # ------------------------------------------------------------------
    def run(self) -> None:
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                self.tick_once()
            except Exception as e:  # noqa: BLE001 - scheduler survives
                obs_events.log_exception_once(
                    "tenant.tick", e, kind="loop.cycle_error")
            elapsed = time.monotonic() - t0
            self._stop.wait(max(0.05, self.period_s - elapsed))

    def start(self) -> "TenantManager":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.run, name="cxxnet-tenant-manager", daemon=True)
        self._thread.start()
        return self

    def request_stop(self) -> None:
        """Signal the scheduler to stop WITHOUT joining it — what a
        signal handler may safely call (a mid-cycle join would block
        the caller for up to a whole fine-tune cycle)."""
        self._stop.set()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=30.0)
        self._thread = None

    def close(self) -> None:
        self.stop()
        for t in self.tenants:
            t.close()

    # ------------------------------------------------------------------
    def healthz_tenants(self) -> Dict[str, dict]:
        """Per-tenant identity block — one projection, shared with the
        HTTP front-end's ``/healthz`` ``models`` block."""
        return self.router().healthz_models()
