"""Continuous fine-tuning loop: consume the feedback log, train, gate.

``task=serve_train``'s training half (doc/continuous_training.md).
:class:`ContinuousLoop` runs beside a live serving engine — typically on
a daemon thread of the same process — and repeats the cycle:

1. **tail** — read every feedback record committed past the persisted
   cursor (``loop/feedback_log.py``); fewer than ``min_records`` →
   the cycle is idle (counted, no training);
2. **fine-tune** — ``rounds_per_cycle`` passes over the new records,
   each batch mixed with ``replay_ratio`` base-iterator rows (the
   catastrophic-forgetting hedge: fresh feedback never fully displaces
   the original distribution);
3. **gate** — hand the candidate to the
   :class:`~cxxnet_tpu.loop.publisher.EvalGatedPublisher`: divergence
   guard + held-out eval against the serving model's recorded metric.
   Published → the engine hot-reloads.  Rejected → the trainer ROLLS
   BACK to the publish pointer's current version (fine-tuning never
   compounds on a degraded model) and the cursor still advances (the
   poisoned records are consumed, not retried forever);
4. **advance** — persist the cursor only after the cycle resolves, so
   a crash mid-cycle replays the records into the next attempt.

The trainer is a FRESH ``NetTrainer`` loaded from the serving
checkpoint — the live engine's model is never mutated in place; the
only way weights reach serving is a published checkpoint.
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..obs import events as obs_events
from ..obs import trace as obs_trace
from .feedback_log import (
    CursorFile,
    FeedbackReader,
    FeedbackRecord,
    loop_metrics,
)
from .publisher import EvalGatedPublisher

__all__ = ["ContinuousLoop"]

ConfigEntry = Tuple[str, str]


class _ReplayFeed:
    """Endless row source over the base iterator (replay mixing):
    yields ``(data_row, label_row)`` pairs, rewinding at epoch end."""

    def __init__(self, base_iter) -> None:
        self.base = base_iter
        self._rows: List[Tuple[np.ndarray, np.ndarray]] = []
        self._pos = 0

    def take(self, k: int) -> List[Tuple[np.ndarray, np.ndarray]]:
        out = []
        while len(out) < k:
            if self._pos >= len(self._rows):
                if not self._refill():
                    break
            out.append(self._rows[self._pos])
            self._pos += 1
        return out

    def _refill(self) -> bool:
        self._rows, self._pos = [], 0
        if self.base is None:
            return False
        if not self.base.next():
            self.base.before_first()
            if not self.base.next():
                return False
        b = self.base.value()
        n = b.batch_size - b.num_batch_padd
        data = np.asarray(b.data)[:n]
        label = np.asarray(b.label)[:n]
        if label.ndim == 1:
            label = label[:, None]
        self._rows = [(data[i], label[i]) for i in range(n)]
        return bool(self._rows)


class ContinuousLoop:
    """The serve→train→publish cycle driver.

    ``engine`` must watch a ``model_dir`` (that is both where the
    serving model came from and where publishes land); ``cfg`` is the
    full ordered config stream (netconfig + trainer globals — the
    fine-tune trainer is built from it exactly like the engine's).
    """

    def __init__(
        self,
        engine,
        cfg: Sequence[ConfigEntry],
        feedback_dir: str,
        base_iter=None,
        eval_iter=None,
        eval_name: str = "eval",
        rounds_per_cycle: int = 2,
        replay_ratio: float = 0.25,
        min_records: int = 64,
        max_records_per_cycle: int = 0,
        cycle_period_s: float = 2.0,
        publish_min_delta: float = 0.0,
        publish_metric: str = "",
        publish_slice_floor: Optional[float] = None,
        publish_slice_min_count: int = 8,
        publish_source_field: Optional[int] = None,
        cursor_path: Optional[str] = None,
        feedback_writer=None,
        retention=None,
        name: str = "",
        silent: bool = True,
    ) -> None:
        if eval_iter is None:
            raise ValueError(
                "ContinuousLoop needs a held-out eval iterator — the "
                "publish gate is not optional (add an eval section to "
                "the conf)")
        if not 0.0 <= replay_ratio < 1.0:
            raise ValueError("loop_replay_ratio must be in [0, 1)")
        self.engine = engine
        self.cfg = list(cfg)
        self.reader = FeedbackReader(feedback_dir)
        self.cursor_file = CursorFile(
            cursor_path or os.path.join(feedback_dir, "cursor.json"))
        self.replay = _ReplayFeed(base_iter)
        self.rounds_per_cycle = int(rounds_per_cycle)
        self.replay_ratio = float(replay_ratio)
        self.min_records = int(min_records)
        self.max_records_per_cycle = int(max_records_per_cycle)
        self.cycle_period_s = float(cycle_period_s)
        self.feedback_writer = feedback_writer
        self.retention = retention  # loop/retention.py Sweeper or None
        self.name = name
        self.silent = silent
        self._m = loop_metrics()
        self._stop = threading.Event()
        self.cycles = 0
        self.trained_cycles = 0
        # the in-flight cycle's first lineage id: records read but not
        # yet resolved (published/rejected).  Retention must never
        # compact the shard holding this range — a crash mid-cycle
        # replays exactly these records into the next attempt.
        self.pending_first_seq: Optional[int] = None
        self.publisher = EvalGatedPublisher(
            engine, eval_iter, eval_name=eval_name,
            metric_name=publish_metric, min_delta=publish_min_delta,
            slice_floor=publish_slice_floor,
            slice_min_count=publish_slice_min_count,
            source_field=publish_source_field,
            tenant=name, silent=silent,
        )
        self.trainer = self._load_trainer(engine.model_path)
        self._row_shape = tuple(
            self.trainer.net.input_node_shape(1)[1:])
        self.publisher.record_serving_baseline(self.trainer)

    # ------------------------------------------------------------------
    def _load_trainer(self, path: Optional[str]):
        from ..nnet.trainer import NetTrainer

        if path is None:
            raise ValueError(
                "serve_train needs the engine's model to come from a "
                "checkpoint file (model_dir), not an in-memory trainer")
        tr = NetTrainer()
        tr.set_params(self.cfg)
        tr.load_model(path)
        return tr

    # ------------------------------------------------------------------
    def _batches(self, records: List[FeedbackRecord]):
        """Yield ``(data, label)`` training batches: feedback rows
        padded out with ``replay_ratio`` base rows per batch."""
        bs = self.trainer.batch_size
        n_replay = min(int(round(bs * self.replay_ratio)), bs - 1)
        n_fresh = bs - n_replay
        lw = max(r.labels.shape[0] for r in records)
        for lo in range(0, len(records), n_fresh):
            chunk = records[lo: lo + n_fresh]
            rows = [(r.data.reshape(self._row_shape), r.labels)
                    for r in chunk]
            rows += self.replay.take(bs - len(chunk))
            if len(rows) < bs:
                # not enough replay data to fill: replicate (the
                # static-shape pad the reference's AdjustBatchSize did)
                rows += [rows[i % len(rows)]
                         for i in range(bs - len(rows))]
            data = np.stack([d for d, _ in rows]).astype(np.float32)
            labels = np.zeros((bs, max(lw, max(
                np.atleast_1d(l).shape[0] for _, l in rows))),
                np.float32)
            for i, (_, l) in enumerate(rows):
                l = np.atleast_1d(l)
                labels[i, : l.shape[0]] = l
            yield data, labels

    def run_cycle(self) -> str:
        """One cycle; returns ``idle`` / ``published`` / ``rejected``."""
        self.cycles += 1
        if self.feedback_writer is not None:
            # part-full pages are invisible to the reader until
            # committed: cycle boundaries flush so fresh feedback is
            # never stranded behind the page-size threshold
            self.feedback_writer.flush()
        cursor = self.cursor_file.load()
        pending = self.reader.pending(cursor)
        self._m.pending.set(pending)
        if pending < self.min_records:
            self._m.cycles.labels(outcome="idle").inc()
            return "idle"
        records, new_cursor = self.reader.read_since(
            cursor, max_records=self.max_records_per_cycle)
        if len(records) < self.min_records:
            if not records and new_cursor != cursor:
                # every committed page past the cursor was bad (CRC):
                # consume them now, or pending() keeps promising work
                # and every future cycle re-reads + re-counts the same
                # rot forever.  With SOME decodable records the cursor
                # holds so they train once min_records accumulate.
                self.cursor_file.store(new_cursor)
                self._m.pending.set(self.reader.pending(new_cursor))
            self._m.cycles.labels(outcome="idle").inc()
            return "idle"
        t0 = time.monotonic()
        # lineage covers exactly THIS cycle's records: a publish ships
        # only this cycle's fine-tuning (a rejected cycle rolls the
        # trainer back, so earlier consumed records never contribute),
        # and building it fresh per cycle means a cycle that failed
        # mid-training and replays its records cannot double-count them
        lineage = self._cycle_lineage(records)
        self.pending_first_seq = lineage["first_seq"]
        try:
            with obs_trace.span("loop.cycle", cycle=self.cycles,
                                records=len(records)):
                steps = 0
                for _ in range(self.rounds_per_cycle):
                    for data, labels in self._batches(records):
                        self.trainer.update_all(data, labels)
                        steps += 1
                self.trainer.sync()
                published = self.publisher.consider(
                    self.trainer, cycle=self.cycles, lineage=lineage)
                if not published:  # these records are spent either way
                    self._rollback()
            self.cursor_file.store(new_cursor)
        finally:
            # the range is pending until the cursor durably passes it:
            # a cycle that dies mid-training keeps its shard compaction-
            # proof so the replay can actually read the records back
            self.pending_first_seq = None
        self._m.pending.set(self.reader.pending(new_cursor))
        self._m.cycles.labels(outcome="trained").inc()
        self.trained_cycles += 1
        obs_events.emit(
            "loop.cycle", cycle=self.cycles, records=len(records),
            steps=steps, published=published, lineage=lineage,
            elapsed_s=time.monotonic() - t0, **self._tag())
        if not self.silent:
            print(f"loop{self._label()}: cycle {self.cycles}: "
                  f"{len(records)} records, {steps} steps, "
                  f"{'published' if published else 'rejected'} "
                  f"({time.monotonic() - t0:.2f}s)", flush=True)
        self.sweep_retention()
        return "published" if published else "rejected"

    # ------------------------------------------------------------------
    def _tag(self) -> dict:
        return {"tenant": self.name} if self.name else {}

    def _label(self) -> str:
        return f"[{self.name}]" if self.name else ""

    def set_rounds_per_cycle(self, n) -> int:
        """Live setter for the arbiter's per-tenant knob
        (``loop/tenant.py``): fine-tune passes per cycle, floor 1."""
        self.rounds_per_cycle = max(1, int(n))
        return self.rounds_per_cycle

    def sweep_retention(self) -> Optional[dict]:
        """One retention pass over this loop's feedback dir (no-op
        without a sweeper).  The cursor handed over is the PERSISTED
        one — only ranges a resolved cycle has durably consumed are
        behind it — clamped by the in-flight pending range."""
        if self.retention is None:
            return None
        try:
            return self.retention.sweep(
                self.cursor_file.load(),
                pending_first_seq=self.pending_first_seq)
        except Exception as e:  # noqa: BLE001 - retention must not
            # take down the loop; the disk keeps filling, loudly
            obs_events.log_exception_once(
                f"loop.retention.{self.name or 'default'}", e,
                kind="loop.retention_error")
            return None

    @staticmethod
    def _cycle_lineage(records: List[FeedbackRecord]) -> dict:
        """Lineage block for one cycle's consumed records: id range +
        count (records from pre-lineage pages have no seq and only
        count; ``cycles`` is kept for pointer-schema stability)."""
        seqs = [r.seq for r in records if r.seq is not None]
        return {
            "first_seq": min(seqs) if seqs else None,
            "last_seq": max(seqs) if seqs else None,
            "records": len(records),
            "cycles": 1,
        }

    def _rollback(self) -> None:
        """Reload the trainer from the last published/serving version
        so the next cycle fine-tunes from known-good weights."""
        target = self.publisher.rollback_target()
        if target is None:  # no checkpoint left: keep current weights
            obs_events.emit("loop.rollback", ok=False,
                            reason="no valid rollback checkpoint",
                            **self._tag())
            return
        round_, path = target
        self.trainer = self._load_trainer(path)
        self._m.publishes.labels(decision="rollback").inc()
        obs_events.emit("loop.rollback", ok=True, round=round_,
                        path=path, **self._tag())
        if not self.silent:
            print(f"loop{self._label()}: rolled trainer back to round "
                  f"{round_} ({path})", flush=True)

    # ------------------------------------------------------------------
    def run(self, max_cycles: int = 0) -> None:
        """Cycle until :meth:`stop` (or ``max_cycles`` trained cycles).
        Exceptions are contained per cycle: a failed cycle is logged
        and the loop keeps serving-side state intact."""
        while not self._stop.is_set():
            try:
                self.run_cycle()
            except Exception as e:  # noqa: BLE001 - loop must survive
                obs_events.log_exception_once(
                    "loop.cycle", e, kind="loop.cycle_error",
                    cycle=self.cycles)
                if not self.silent:
                    print(f"loop: cycle {self.cycles} failed: "
                          f"{type(e).__name__}: {e}", flush=True)
            if max_cycles and self.trained_cycles >= max_cycles:
                return
            self._stop.wait(self.cycle_period_s)

    def stop(self) -> None:
        self._stop.set()
