"""Closed-loop continuous training: serve → feedback log → fine-tune →
eval-gated publish → hot reload (``task=serve_train``).

Three parts (doc/continuous_training.md):

* :mod:`~cxxnet_tpu.loop.feedback_log` — sharded append-only
  (input, label) log in the imgbin CXBP page format with atomic page
  commits, CRC sidecars, size rotation, and a cursor-tailing reader;
* :mod:`~cxxnet_tpu.loop.continuous` — the fine-tune cycle driver:
  tail the log, mix with base-iterator replay, train, gate, advance
  the cursor;
* :mod:`~cxxnet_tpu.loop.publisher` — the eval gate: divergence guard
  + held-out-metric comparison against the serving model; only passing
  candidates reach the model directory (and the engine's hot reload),
  with a publish pointer recording rollback state.
"""

from .continuous import ContinuousLoop
from .feedback_log import (
    CursorFile,
    FeedbackReader,
    FeedbackRecord,
    FeedbackWriter,
    decode_record,
    encode_record,
    loop_metrics,
)
from .publisher import EvalGatedPublisher, metric_improvement, parse_eval_metric

__all__ = [
    "ContinuousLoop",
    "CursorFile",
    "FeedbackReader",
    "FeedbackRecord",
    "FeedbackWriter",
    "EvalGatedPublisher",
    "decode_record",
    "encode_record",
    "loop_metrics",
    "metric_improvement",
    "parse_eval_metric",
]
