"""Closed-loop continuous training: serve → feedback log → fine-tune →
eval-gated publish → hot reload (``task=serve_train``).

Three parts (doc/continuous_training.md):

* :mod:`~cxxnet_tpu.loop.feedback_log` — sharded append-only
  (input, label) log in the imgbin CXBP page format with atomic page
  commits, CRC sidecars, size rotation, and a cursor-tailing reader;
* :mod:`~cxxnet_tpu.loop.continuous` — the fine-tune cycle driver:
  tail the log, mix with base-iterator replay, train, gate, advance
  the cursor;
* :mod:`~cxxnet_tpu.loop.publisher` — the eval gate: divergence guard
  + held-out-metric comparison against the serving model, plus the
  per-slice cohort gate (``publish_slice_floor``); only passing
  candidates reach the model directory (and the engine's hot reload),
  with a publish pointer recording rollback state, the gate metric and
  its cohort vector;
* :mod:`~cxxnet_tpu.loop.retention` — compaction of consumed feedback
  shards behind the resolved cursor, crash-safe (boundary fsynced
  before unlink);
* :mod:`~cxxnet_tpu.loop.tenant` — ``task=loop_fleet``: N tenants on
  one device pool behind an SLO-constrained round arbiter.
"""

from .continuous import ContinuousLoop
from .feedback_log import (
    CursorFile,
    FeedbackReader,
    FeedbackRecord,
    FeedbackWriter,
    StaleCursorError,
    decode_record,
    encode_record,
    loop_metrics,
)
from .publisher import EvalGatedPublisher, metric_improvement, parse_eval_metric
from .retention import RetentionOptions, Sweeper
from .tenant import Tenant, TenantArbiter, TenantManager

__all__ = [
    "ContinuousLoop",
    "CursorFile",
    "FeedbackReader",
    "FeedbackRecord",
    "FeedbackWriter",
    "EvalGatedPublisher",
    "RetentionOptions",
    "StaleCursorError",
    "Sweeper",
    "Tenant",
    "TenantArbiter",
    "TenantManager",
    "decode_record",
    "encode_record",
    "loop_metrics",
    "metric_improvement",
    "parse_eval_metric",
]
