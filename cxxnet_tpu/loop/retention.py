"""Feedback-log retention: compact consumed shards, crash-safely.

A month of million-user feedback must not eat the disk
(doc/continuous_training.md "Retention").  The cursor + ``.commit``
sidecar protocol makes *safe to delete* computable: a shard is
compactable exactly when

* it lies wholly **behind the consumed-and-published cursor** — the
  :class:`~cxxnet_tpu.loop.continuous.ContinuousLoop` persists its
  cursor only after a cycle RESOLVES (published or rejected), so every
  page behind it has both been trained on and had its publish/reject
  decision recorded;
* it holds **no pending-lineage range** — records a cycle is training
  on right now (read but not yet resolved) must survive a crash so the
  cycle can replay them; and
* it is **not the writer's live shard** — an uncommitted buffered tail
  lives only there (implied by the cursor bound: the cursor can never
  pass uncommitted bytes).

Deletion order is crash-safe: the retention pointer
(``retention.json`` — ``{"compacted_below": k}``) is written atomically
and fsynced BEFORE any unlink.  A ``kill -9`` mid-sweep therefore
leaves either the old boundary with every file intact, or the new
boundary with some below-boundary orphans — readers ignore shards below
the boundary (``feedback_log.FeedbackReader``) and the next sweep
deletes the orphans, so every record a reader can reach stays
CRC-verified.  The reverse order would be a lie: unlink-then-pointer
crashed between the two leaves a boundary claiming deleted shards still
exist, and a stale cursor would silently skip instead of failing with
:class:`~cxxnet_tpu.loop.feedback_log.StaleCursorError`.

Knobs (doc/conf.md): ``feedback_retain_shards`` keeps the newest N
fully-consumed shards as an operator re-read hedge (-1 disables
retention entirely — the serve_train default); ``feedback_retain_bytes``
only deletes while the log exceeds the byte bound (0 = unbounded
deletion of consumed shards).  Every sweep exports
``feedback_disk_bytes{tenant}`` / ``feedback_shards{tenant}`` and each
deleting sweep counts ``loop_compactions_total{tenant}`` /
``loop_compacted_bytes_total{tenant}`` and emits a ``loop.compact``
event naming the shards it reclaimed.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from ..obs import events as obs_events
from ..obs.registry import registry as obs_registry
from ..utils import diskio
from ..utils.checkpoint import atomic_write_bytes
from .feedback_log import (
    COMMIT_SUFFIX,
    RETENTION_FILE,
    _read_commits,
    list_shards,
    read_retention,
)

__all__ = ["RetentionOptions", "Sweeper", "safe_boundary"]


class _RetentionMetrics:
    def __init__(self) -> None:
        reg = obs_registry()
        self.compactions = reg.counter(
            "loop_compactions_total",
            "Retention sweeps that deleted at least one feedback shard.",
            labelnames=("tenant",))
        self.compacted_bytes = reg.counter(
            "loop_compacted_bytes_total",
            "Feedback-log bytes reclaimed by retention compaction.",
            labelnames=("tenant",))
        self.disk_bytes = reg.gauge(
            "feedback_disk_bytes",
            "On-disk bytes of a tenant's feedback log (shards + "
            "sidecars), set at each retention sweep.",
            labelnames=("tenant",))
        self.shards = reg.gauge(
            "feedback_shards",
            "Shard files in a tenant's feedback log, set at each "
            "retention sweep.",
            labelnames=("tenant",))


_METRICS: Optional[_RetentionMetrics] = None
_METRICS_LOCK = threading.Lock()


def _metrics() -> _RetentionMetrics:
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            _METRICS = _RetentionMetrics()
        return _METRICS


class RetentionOptions:
    """Parsed ``feedback_retain_*`` keys.  ``retain_shards < 0`` means
    retention is OFF (nothing is ever deleted)."""

    def __init__(self, retain_shards: int = -1,
                 retain_bytes: int = 0) -> None:
        self.retain_shards = int(retain_shards)
        self.retain_bytes = int(retain_bytes)

    @property
    def armed(self) -> bool:
        return self.retain_shards >= 0


def _shard_containing_seq(dir_: str, seq: int) -> Optional[int]:
    """Index of the shard whose committed pages cover lineage id
    ``seq``; None when no committed page claims it (legacy pages
    without ``seq0``, or the id is still buffered)."""
    for idx, path in list_shards(dir_):
        for ent in _read_commits(path):
            s0 = ent.get("seq0")
            if s0 is not None and s0 <= seq < s0 + int(ent["nrec"]):
                return idx
    return None


def safe_boundary(dir_: str, cursor: Dict,
                  pending_first_seq: Optional[int] = None) -> int:
    """The highest shard index ``k`` such that every shard below ``k``
    is safe to delete: wholly behind the resolved ``cursor`` and not
    holding the in-flight cycle's ``pending_first_seq``.  A pending id
    that cannot be located (legacy pages) conservatively freezes the
    boundary at 0 — never guess about data a crash would need."""
    k = int(cursor.get("shard", 0))
    if pending_first_seq is not None:
        holder = _shard_containing_seq(dir_, int(pending_first_seq))
        if holder is None:
            return 0
        k = min(k, holder)
    return k


def _dir_stats(dir_: str) -> Tuple[int, int]:
    """(shard_count, total_bytes incl. sidecars) of a feedback dir."""
    shards = list_shards(dir_)
    total = 0
    for _idx, path in shards:
        for p in (path, path + COMMIT_SUFFIX):
            try:
                total += os.path.getsize(p)
            except OSError:
                pass
    return len(shards), total


class Sweeper:
    """One tenant's retention policy bound to its feedback directory.

    :meth:`sweep` is idempotent and cheap when there is nothing to do;
    the :class:`~cxxnet_tpu.loop.continuous.ContinuousLoop` calls it at
    the end of every cycle (and the tenant manager on every tick), so
    the log's disk footprint tracks consumption instead of history.
    """

    def __init__(self, dir_: str, opts: RetentionOptions,
                 tenant: str = "default", silent: bool = True) -> None:
        self.dir = dir_
        self.opts = opts
        self.tenant = tenant
        self.silent = silent
        self._m = _metrics()

    # ------------------------------------------------------------------
    def sweep(self, cursor: Dict,
              pending_first_seq: Optional[int] = None) -> Dict:
        """One compaction pass; returns ``{deleted_shards,
        deleted_bytes, compacted_below, disk_bytes, shards}``.

        Delete order per shard: the retention pointer covering the
        whole batch is fsynced FIRST, then shards unlink oldest-first
        (data file before sidecar — a surviving sidecar for a missing
        file is below the boundary and ignored either way)."""
        out = {"deleted_shards": 0, "deleted_bytes": 0}
        if not self.opts.armed:
            return self._finish(out)
        boundary = safe_boundary(self.dir, cursor, pending_first_seq)
        prev_below = read_retention(self.dir)["compacted_below"]
        shards = list_shards(self.dir)
        # candidates: consumed shards below the safe boundary, minus
        # the newest retain_shards of them (the operator re-read hedge)
        candidates = [(idx, path) for idx, path in shards
                      if idx < boundary]
        if self.opts.retain_shards > 0:
            candidates = candidates[: -self.opts.retain_shards] \
                if len(candidates) > self.opts.retain_shards else []
        # byte bound: only delete while the log exceeds retain_bytes
        _, total_bytes = _dir_stats(self.dir)
        doomed: List[Tuple[int, str, int]] = []
        for idx, path in candidates:
            if self.opts.retain_bytes > 0 and total_bytes <= \
                    self.opts.retain_bytes:
                break
            size = 0
            for p in (path, path + COMMIT_SUFFIX):
                try:
                    size += os.path.getsize(p)
                except OSError:
                    pass
            doomed.append((idx, path, size))
            total_bytes -= size
        new_below = max(prev_below,
                        (doomed[-1][0] + 1) if doomed else 0)
        if new_below > prev_below:
            # the crash-safety pivot: boundary durable BEFORE unlink
            atomic_write_bytes(
                os.path.join(self.dir, RETENTION_FILE),
                json.dumps({"compacted_below": new_below}).encode("utf-8"))
        # idempotent cleanup: everything below the (possibly
        # pre-existing) boundary goes, including orphans a previous
        # crashed sweep left behind
        for idx, path in list_shards(self.dir):
            if idx >= new_below:
                continue
            size = 0
            for p in (path, path + COMMIT_SUFFIX):
                try:
                    size += os.path.getsize(p)
                    diskio.unlink(p)
                except OSError:
                    pass  # already gone / transient: next sweep retries
            out["deleted_shards"] += 1
            out["deleted_bytes"] += size
        if out["deleted_shards"]:
            self._m.compactions.labels(tenant=self.tenant).inc()
            self._m.compacted_bytes.labels(tenant=self.tenant).inc(
                out["deleted_bytes"])
            obs_events.emit(
                "loop.compact", tenant=self.tenant,
                deleted_shards=out["deleted_shards"],
                deleted_bytes=out["deleted_bytes"],
                compacted_below=new_below)
            if not self.silent:
                print(f"loop[{self.tenant}]: compacted "
                      f"{out['deleted_shards']} shard(s), "
                      f"{out['deleted_bytes']} bytes reclaimed "
                      f"(boundary {new_below})", flush=True)
        out["compacted_below"] = new_below
        return self._finish(out)

    def _finish(self, out: Dict) -> Dict:
        nshards, nbytes = _dir_stats(self.dir)
        self._m.disk_bytes.labels(tenant=self.tenant).set(nbytes)
        self._m.shards.labels(tenant=self.tenant).set(nshards)
        out["disk_bytes"] = nbytes
        out["shards"] = nshards
        out.setdefault("compacted_below",
                       read_retention(self.dir)["compacted_below"])
        return out
