"""Sharded append-only feedback log: the serve→train data bridge.

The closed loop's durability layer (doc/continuous_training.md): the
serve front-end appends ``(input, label)`` records here, and the
continuous trainer tails them through a persisted cursor.  The on-disk
page layout is imgbin's native ``CXBP`` format (``io/imgbin.py`` — the
same magic/header/length-table byte layout ``iter_cxbp_pages`` reads),
so a full shard can be read back or repacked into a training set with
the existing imgbin tooling.  What this module adds on top is the
**commit protocol** an always-on serving process needs:

* **atomic page commits** — records buffer in RAM until a page fills
  (``page_bytes``) or :meth:`FeedbackWriter.flush` is called; the page
  bytes are appended to the shard and fsynced, and only THEN is the
  page's ``{offset, bytes, crc32, nrec, seq0}`` entry appended (and
  fsynced) to the ``.commit`` JSONL sidecar.  A crash mid-append leaves
  a trailing torn page that no sidecar entry references — readers never
  observe it;
* **lineage sequence ids** — every appended record gets a log-wide id
  (assigned at append, never reused); a page's ``seq0`` anchors the
  contiguous range ``[seq0, seq0 + nrec)`` it holds, so the publish
  pointer can name exactly which records trained a served model
  (doc/continuous_training.md);
* **CRC sidecars** — every committed page carries its CRC32; the reader
  verifies before parsing, and a mismatching page (bit rot, torn
  sidecar replay) is skipped and counted, never served to the trainer;
* **rotation by size** — a shard exceeding ``rotate_bytes`` is closed
  and ``feedback-NNNNNN.bin`` rolls to the next index, so retention can
  prune whole shards without touching the live tail;
* **tailing reader + cursor** — :meth:`FeedbackReader.read_since`
  returns every record committed after a ``(shard, offset)`` cursor;
  :class:`CursorFile` persists the cursor atomically so the trainer
  resumes where it left off across restarts;
* **degrade-don't-fail appends** — the ``loop.append`` fault-injection
  site fires per append; an I/O failure (injected or real) DROPS the
  record and bumps ``loop_feedback_dropped_total`` instead of failing
  the serving request (``drop_on_error=True``, the serving default).

Record encoding (one CXBP blob)::

    u32 nlabel | f4*nlabel labels | u16 h,w,c,pad | f4*h*w*c input

The input tail is exactly ``io.imgbin.encode_raw`` so the blob's data
part round-trips through ``ImageBinIterator._decode_raw``.
"""

from __future__ import annotations

import errno
import json
import os
import re
import struct
import threading
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..io.imgbin import PAGE_MAGIC, encode_raw
from ..obs import events as obs_events
from ..obs.registry import registry as obs_registry
from ..utils import diskio, faults

__all__ = [
    "FeedbackRecord",
    "FeedbackWriter",
    "FeedbackReader",
    "CursorFile",
    "StaleCursorError",
    "encode_record",
    "decode_record",
    "loop_metrics",
    "read_retention",
]

SHARD_RE = re.compile(r"feedback-(\d{6})\.bin$")
COMMIT_SUFFIX = ".commit"
SEQ_FILE = "seq.json"
#: retention pointer (loop/retention.py): ``{"compacted_below": k}``
#: means every shard with index < k has been compacted away — the
#: pointer is fsynced BEFORE any unlink, so a crash mid-compaction
#: leaves orphan files below the boundary (ignored by readers, deleted
#: by the next sweep) instead of a boundary that lies
RETENTION_FILE = "retention.json"
#: lineage ids are handed out from durably RESERVED blocks: one atomic
#: sidecar write reserves this many ids ahead, so an id acknowledged to
#: a /feedback client can never be reassigned after a crash (the
#: unassigned remainder of a block becomes a gap, which readers
#: tolerate) at a cost of one fsynced write per block, not per append
SEQ_RESERVE_BLOCK = 1 << 16


class _LoopMetrics:
    """Process-wide registry families for the closed loop (lazy, shared
    by the writer, reader, continuous trainer, and publisher)."""

    def __init__(self) -> None:
        reg = obs_registry()
        self.appended = reg.counter(
            "loop_feedback_records_total",
            "Feedback records durably committed to the log.")
        self.dropped = reg.counter(
            "loop_feedback_dropped_total",
            "Feedback records dropped on append/commit failure "
            "(degrade-don't-fail).")
        self.bad_pages = reg.counter(
            "loop_feedback_bad_pages_total",
            "Committed pages skipped by the reader (CRC mismatch / "
            "unreadable).")
        self.cycles = reg.counter(
            "loop_cycles_total",
            "Continuous-training cycles by outcome: trained / idle.",
            labelnames=("outcome",),
        )
        self.publishes = reg.counter(
            "loop_publish_total",
            "Eval-gate decisions: published / rejected / rollback.",
            labelnames=("decision",),
        )
        self.pending = reg.gauge(
            "loop_feedback_pending_records",
            "Records committed but not yet consumed by the trainer "
            "cursor (set at each cycle).")


_METRICS: Optional[_LoopMetrics] = None
_METRICS_LOCK = threading.Lock()


def loop_metrics() -> _LoopMetrics:
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            _METRICS = _LoopMetrics()
        return _METRICS


class FeedbackRecord:
    """One decoded (input, labels) feedback instance.

    ``seq`` is the record's log-wide sequence id (lineage): assigned at
    append time, durably recorded per page as the commit entry's
    ``seq0``, and stamped through the training cycle into the publish
    pointer so ``PUBLISHED.json`` can name the exact records that
    trained a served model.  ``None`` for pages committed before the
    lineage format (legacy sidecars without ``seq0``)."""

    __slots__ = ("data", "labels", "seq")

    def __init__(self, data: np.ndarray, labels: np.ndarray,
                 seq: Optional[int] = None) -> None:
        self.data = data
        self.labels = labels
        self.seq = seq


def encode_record(data, labels) -> bytes:
    """Encode one instance: label vector + raw-pixel input blob."""
    arr = np.ascontiguousarray(data, np.float32)
    if arr.ndim == 1:
        arr = arr.reshape(1, 1, -1)
    if arr.ndim != 3:
        raise ValueError(
            f"feedback input must be a (H, W, C) or flat row, got shape "
            f"{arr.shape}")
    lab = np.atleast_1d(np.asarray(labels, np.float32)).reshape(-1)
    return (struct.pack("<I", lab.shape[0]) + lab.tobytes()
            + encode_raw(arr))


def decode_record(blob) -> FeedbackRecord:
    """Inverse of :func:`encode_record` (raises on truncation)."""
    blob = bytes(blob)
    (nlabel,) = struct.unpack_from("<I", blob)
    off = 4 + 4 * nlabel
    labels = np.frombuffer(blob, "<f4", count=nlabel, offset=4).copy()
    h, w, c = struct.unpack_from("<HHH", blob, off)
    data = np.frombuffer(blob, "<f4", offset=off + 8).reshape(h, w, c)
    return FeedbackRecord(data.copy(), labels)


class StaleCursorError(RuntimeError):
    """A reader's cursor points into a shard that retention compacted
    away: the records it expects are GONE, and silently skipping ahead
    would hand the trainer a hole it can never audit.  The holder must
    decide — re-baseline the cursor (a fresh consumer) or treat the
    loss as fatal (a consumer that believed it was caught up)."""

    def __init__(self, cursor: Dict, compacted_below: int,
                 dir_: str) -> None:
        super().__init__(
            f"cursor {cursor} points into a compacted shard of {dir_}: "
            f"every shard below index {compacted_below} was deleted by "
            "retention (records behind the consumed-and-published "
            "cursor); re-baseline the cursor or restore the log")
        self.cursor = dict(cursor)
        self.compacted_below = int(compacted_below)
        self.dir = dir_


def read_retention(dir_: str) -> Dict:
    """The retention pointer: ``{"compacted_below": 0, ...}`` when the
    log was never compacted (or the pointer is unreadable — a missing
    pointer can only UNDER-report the boundary, never invent one)."""
    try:
        with open(os.path.join(dir_, RETENTION_FILE), "r",
                  encoding="utf-8") as f:
            ret = json.load(f)
        if isinstance(ret, dict) and isinstance(
                ret.get("compacted_below"), int):
            return ret
    except (OSError, ValueError):
        pass
    return {"compacted_below": 0}


def _shard_path(dir_: str, idx: int) -> str:
    return os.path.join(dir_, f"feedback-{idx:06d}.bin")


def list_shards(dir_: str) -> List[Tuple[int, str]]:
    """All shard files in the log directory, sorted by index."""
    try:
        names = os.listdir(dir_)
    except OSError:
        return []
    out = []
    for n in names:
        m = SHARD_RE.match(n)
        if m:
            out.append((int(m.group(1)), os.path.join(dir_, n)))
    return sorted(out)


def _read_commits_full(shard_path: str) -> Tuple[List[Dict], int]:
    """Committed-page entries of one shard, plus the sidecar byte length
    they cover (the **clean length**).

    A commit entry counts only when its line is newline-TERMINATED and
    parses with the full schema: the trailing newline is part of the
    fsynced commit record, so a line missing it was torn mid-write and
    never acknowledged — its page is simply uncommitted.  Parsing stops
    at the first bad line (nothing after a tear is trustworthy); the
    clean length is where a recovering writer must truncate before
    appending, so a torn partial line can never fuse with the next
    entry into one unparseable line that hides every commit after it
    (the crash-audit ``torn-commit-sidecar-append`` regression).
    """
    out: List[Dict] = []
    clean_len = 0
    try:
        with open(shard_path + COMMIT_SUFFIX, "rb") as f:
            raw = f.read()
    except OSError:
        return out, 0
    pos = 0
    while pos < len(raw):
        nl = raw.find(b"\n", pos)
        if nl < 0:
            break  # unterminated tail: torn mid-line
        line = raw[pos:nl].strip()
        pos = nl + 1
        if not line:
            clean_len = pos
            continue
        try:
            ent = json.loads(line.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            break
        if isinstance(ent, dict) and {"off", "bytes", "crc32",
                                      "nrec"} <= set(ent):
            out.append(ent)
            clean_len = pos
        else:
            break
    return out, clean_len


def _read_commits(shard_path: str) -> List[Dict]:
    """Committed-page entries of one shard (see
    :func:`_read_commits_full` for the torn-tail rules)."""
    return _read_commits_full(shard_path)[0]


class FeedbackWriter:
    """Thread-safe append side of the log (the serve front-end's handle).

    Appends buffer in RAM; a page is committed when the buffer reaches
    ``page_bytes`` or on :meth:`flush`.  With ``drop_on_error`` (the
    serving default) any I/O failure — injected via the ``loop.append``
    chaos site or real — drops the affected records and counts them in
    ``loop_feedback_dropped_total`` instead of propagating, so a sick
    disk degrades feedback capture without failing predict traffic.
    """

    def __init__(
        self,
        dir_: str,
        page_bytes: int = 1 << 20,
        rotate_bytes: int = 8 << 20,
        fsync: bool = True,
        drop_on_error: bool = True,
    ) -> None:
        self.dir = dir_
        self.page_bytes = int(page_bytes)
        self.rotate_bytes = int(rotate_bytes)
        self.fsync = fsync
        self.drop_on_error = drop_on_error
        self._lock = threading.Lock()
        self._blobs: List[bytes] = []
        self._cur = 0
        self._m = loop_metrics()
        self.appended = 0  # records durably committed
        self.dropped = 0
        os.makedirs(dir_, exist_ok=True)
        shards = list_shards(dir_)
        # resume at the last shard's committed length (a torn tail past
        # it is dead bytes; truncate so offsets stay contiguous); never
        # resume BELOW the retention boundary — if every shard was
        # compacted away, reusing index 0 would put new records behind
        # the boundary where readers must ignore them
        self._shard_idx = max(
            shards[-1][0] if shards else 0,
            read_retention(dir_)["compacted_below"])
        self._f = None
        # lineage: the next record sequence id, resumed past everything
        # ever ASSIGNED — the committed pages' coverage AND the durable
        # reservation sidecar, so ids acknowledged for records that were
        # still buffered at a crash are never reused (they become a gap)
        self._seq_next = self._resume_seq(self.dir, shards)
        self._seq_reserved = self._seq_next
        self._open_shard(truncate_torn=True)

    @staticmethod
    def _resume_seq(dir_: str, shards: List[Tuple[int, str]]) -> int:
        seq = 0
        for _idx, path in shards:
            for ent in _read_commits(path):
                s0 = ent.get("seq0")
                end = (int(s0) + int(ent["nrec"]) if s0 is not None
                       else seq + int(ent["nrec"]))
                seq = max(seq, end)
        try:
            with open(os.path.join(dir_, SEQ_FILE), "r",
                      encoding="utf-8") as f:
                reserved = json.load(f).get("reserved")
            if isinstance(reserved, int):
                seq = max(seq, reserved)
        except (OSError, ValueError, AttributeError):
            pass
        return seq

    def _reserve_seq_locked(self) -> bool:
        """Make sure ``_seq_next`` lies inside a durably reserved block
        (one atomic fsynced write per ``SEQ_RESERVE_BLOCK`` ids).  False
        when the reservation cannot be persisted — the caller must then
        drop rather than hand out an id a restart could reuse."""
        if self._seq_next < self._seq_reserved:
            return True
        from ..utils.checkpoint import atomic_write_bytes

        limit = self._seq_next + SEQ_RESERVE_BLOCK
        try:
            atomic_write_bytes(
                os.path.join(self.dir, SEQ_FILE),
                json.dumps({"reserved": limit}).encode("utf-8"))
        except OSError:
            return False
        self._seq_reserved = limit
        return True

    # ------------------------------------------------------------------
    def _open_shard(self, truncate_torn: bool = False) -> None:
        path = _shard_path(self.dir, self._shard_idx)
        commits, clean_len = _read_commits_full(path)
        committed_end = (commits[-1]["off"] + commits[-1]["bytes"]
                         if commits else 0)
        if truncate_torn:
            # a torn trailing sidecar line must go BEFORE we append the
            # next entry: appending onto a half-written line would fuse
            # them into one unparseable line, and since commit parsing
            # stops at the first bad line, every commit after it would
            # silently vanish (committed records lost)
            cpath = path + COMMIT_SUFFIX
            try:
                if os.path.getsize(cpath) > clean_len:
                    diskio.truncate(cpath, clean_len)
            except OSError:
                pass
        self._f = diskio.open_append(path)
        if truncate_torn and self._f.tell() > committed_end:
            self._f.truncate(committed_end)
            self._f.seek(committed_end)
        self._off = self._f.tell()

    def _rotate_locked(self) -> None:
        self._f.close()
        self._shard_idx += 1
        self._open_shard()

    def append(self, data, labels) -> int:
        """Buffer one record; returns 1, or 0 when it was dropped
        (``drop_on_error``).  Encoding errors (bad shapes) always
        raise — they are caller bugs, not I/O weather."""
        return 1 if self.append_seq(data, labels) is not None else 0

    def append_seq(self, data, labels) -> Optional[int]:
        """Buffer one record and return its lineage sequence id, or
        ``None`` when the record was dropped.  Ids are assigned at
        append time and never reused — a page lost to a commit failure
        leaves a gap, which readers tolerate (ranges come from each
        committed page's ``seq0``)."""
        blob = encode_record(data, labels)
        with self._lock:
            return self._append_blob_locked(blob)

    def _append_blob_locked(self, blob: bytes) -> Optional[int]:
        """Buffer one encoded record under the writer lock (a hang/IO
        fault at the ``loop.append`` site therefore holds the lock —
        exactly what a sick disk would do, since page commits run under
        it too)."""
        try:
            faults.fault_point("loop.append")
            if not self._reserve_seq_locked():
                raise OSError(
                    "cannot persist the lineage id reservation "
                    f"({SEQ_FILE}); refusing to hand out a reusable id")
        except OSError as e:
            if not self.drop_on_error:
                raise
            self._drop_locked(1, e)
            return None
        seq = self._seq_next
        self._seq_next += 1
        self._blobs.append((blob, seq))
        self._cur += len(blob) + 4
        if self._cur + 8 >= self.page_bytes:
            self._commit_page_locked()
        return seq

    def append_batch(self, data, labels) -> int:
        """Append N instances; returns how many were accepted."""
        return self.append_batch_ids(data, labels)[0]

    def append_batch_ids(
        self, data, labels
    ) -> Tuple[int, Optional[int], Optional[int]]:
        """Append N instances; returns ``(accepted, first_seq,
        last_seq)`` — the id range the serve front-end hands back to the
        ``/feedback`` caller (``None``s when every record dropped).
        The whole batch is appended under ONE lock hold, so the range
        covers exactly this caller's records even when concurrent
        ``/feedback`` handlers interleave (per-record locking would let
        another request's ids land inside the reported range)."""
        data = np.asarray(data)
        labels = np.asarray(labels)
        if labels.ndim == 1:
            labels = labels[:, None]
        if data.shape[0] != labels.shape[0]:
            raise ValueError(
                f"feedback batch: {data.shape[0]} rows vs "
                f"{labels.shape[0]} labels")
        blobs = [encode_record(data[i], labels[i])
                 for i in range(data.shape[0])]
        n, first, last = 0, None, None
        with self._lock:
            for blob in blobs:
                seq = self._append_blob_locked(blob)
                if seq is None:
                    continue
                n += 1
                first = seq if first is None else first
                last = seq
        return n, first, last

    def _drop_locked(self, nrec: int, exc: BaseException) -> None:
        """Account a degrade-drop; the caller holds the writer lock
        (the metrics/event sinks take their own locks)."""
        self.dropped += nrec
        self._m.dropped.inc(nrec)
        if getattr(exc, "errno", None) == errno.ENOSPC:
            # disk-full is its own paging alert, not just a drop stat
            diskio.count_disk_full("loop.append", self.dir)
        obs_events.log_exception_once(
            "loop.append", exc, kind="loop.append_error", dropped=nrec)

    def _commit_page_locked(self) -> int:
        """Write the buffered page + its commit entry.  Returns the
        record count committed (0 after a degrade-drop)."""
        if not self._blobs:
            return 0
        blobs, self._blobs, self._cur = self._blobs, [], 0
        page = bytearray(struct.pack("<II", PAGE_MAGIC, len(blobs)))
        for b, _seq in blobs:
            page += struct.pack("<I", len(b))
        for b, _seq in blobs:
            page += b
        page = bytes(page)
        try:
            self._f.write(page, site="loop.commit")
            self._f.flush()
            if self.fsync:
                self._f.fsync()
            # seq0 is the page's lineage anchor: buffered records are
            # committed in append order, so the page covers exactly
            # [seq0, seq0 + nrec) — readers reconstruct per-record ids
            ent = {"off": self._off, "bytes": len(page),
                   "crc32": zlib.crc32(page) & 0xFFFFFFFF,
                   "nrec": len(blobs), "seq0": blobs[0][1]}
            cpath = (_shard_path(self.dir, self._shard_idx)
                     + COMMIT_SUFFIX)
            line = json.dumps(ent, separators=(",", ":")) + "\n"
            diskio.append_bytes(cpath, line.encode("utf-8"),
                                fsync=self.fsync, site="loop.commit")
        except OSError as e:
            # degrade: the page (and its records) are lost, serving
            # is not.  Reopen at the committed tail so the next page
            # starts on a clean offset.
            if not self.drop_on_error:
                raise
            try:
                self._f.close()
            except OSError:
                pass
            self._open_shard(truncate_torn=True)
            self._m.dropped.inc(len(blobs))
            self.dropped += len(blobs)
            obs_events.log_exception_once(
                "loop.commit", e, kind="loop.append_error",
                dropped=len(blobs))
            return 0
        self._off += len(page)
        self.appended += len(blobs)
        self._m.appended.inc(len(blobs))
        if self._off >= self.rotate_bytes:
            self._rotate_locked()
        return len(blobs)

    def flush(self) -> int:
        """Commit the current partial page (cycle boundaries, tests)."""
        with self._lock:
            return self._commit_page_locked()

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "appended": self.appended,
                "dropped": self.dropped,
                "buffered": len(self._blobs),
                "shard": self._shard_idx,
                "shard_bytes": self._off,
                "next_seq": self._seq_next,
            }

    def close(self) -> None:
        with self._lock:
            self._commit_page_locked()
            if self._f is not None:
                self._f.close()
                self._f = None
            # clean shutdown: shrink the reservation to exactly the
            # next id, so an orderly reopen continues gap-free (only a
            # crash leaves the unassigned block remainder as a gap)
            if self._seq_reserved > self._seq_next:
                from ..utils.checkpoint import atomic_write_bytes

                try:
                    atomic_write_bytes(
                        os.path.join(self.dir, SEQ_FILE),
                        json.dumps(
                            {"reserved": self._seq_next}).encode("utf-8"))
                    self._seq_reserved = self._seq_next
                except OSError:
                    pass  # the over-reservation stays: a gap, never reuse

    def __enter__(self) -> "FeedbackWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


Cursor = Dict[str, int]  # {"shard": int, "off": int}


def _cursor(shard: int = 0, off: int = 0) -> Cursor:
    return {"shard": int(shard), "off": int(off)}


class FeedbackReader:
    """Tailing read side: committed pages only, CRC-verified."""

    def __init__(self, dir_: str) -> None:
        self.dir = dir_

    # ------------------------------------------------------------------
    def _shard_commits(self) -> List[Tuple[int, str, List[Dict]]]:
        return [(idx, path, _read_commits(path))
                for idx, path in list_shards(self.dir)]

    def _compacted_below(self, cur: Cursor) -> int:
        """Retention boundary check shared by :meth:`pending` and
        :meth:`read_since`: a cursor pointing below the boundary wants
        records that no longer exist — fail loud, never skip."""
        below = read_retention(self.dir)["compacted_below"]
        if cur["shard"] < below:
            raise StaleCursorError(cur, below, self.dir)
        return below

    def pending(self, cursor: Optional[Cursor] = None) -> int:
        """Committed records past ``cursor`` (cheap: sidecars only).
        Raises :class:`StaleCursorError` for a cursor pointing into a
        compacted shard."""
        cur = cursor or _cursor()
        below = self._compacted_below(cur)
        n = 0
        for idx, _path, commits in self._shard_commits():
            if idx < max(cur["shard"], below):
                continue
            for ent in commits:
                if idx == cur["shard"] and ent["off"] < cur["off"]:
                    continue
                n += ent["nrec"]
        return n

    def read_since(
        self, cursor: Optional[Cursor] = None, max_records: int = 0
    ) -> Tuple[List[FeedbackRecord], Cursor]:
        """Every record committed after ``cursor`` (in commit order),
        plus the advanced cursor to persist once the records are
        consumed.  A CRC-mismatching or unreadable committed page is
        skipped and counted (``loop_feedback_bad_pages_total``) — the
        cursor still advances past it.  ``max_records > 0`` caps the
        read (the cursor then stops at a page boundary).  A cursor
        pointing into a compacted shard raises
        :class:`StaleCursorError`; shards below the retention boundary
        that still exist on disk (a crash between the boundary fsync
        and the unlinks) are ignored — they are already deleted as far
        as the protocol is concerned."""
        cur = dict(cursor) if cursor else _cursor()
        below = self._compacted_below(cur)
        out: List[FeedbackRecord] = []
        m = loop_metrics()
        for idx, path, commits in self._shard_commits():
            if idx < max(cur["shard"], below):
                continue
            for ent in commits:
                if idx == cur["shard"] and ent["off"] < cur["off"]:
                    continue
                if max_records and len(out) >= max_records:
                    return out, cur
                try:
                    with open(path, "rb") as f:
                        f.seek(ent["off"])
                        page = f.read(ent["bytes"])
                    if (len(page) != ent["bytes"]
                            or (zlib.crc32(page) & 0xFFFFFFFF)
                            != ent["crc32"]):
                        raise ValueError(
                            f"page@{ent['off']}: CRC/size mismatch")
                    out.extend(self._parse_page(page, ent.get("seq0")))
                except (OSError, ValueError, struct.error) as e:
                    m.bad_pages.inc()
                    obs_events.emit(
                        "loop.bad_page", shard=idx, off=ent["off"],
                        error=f"{type(e).__name__}: {e}")
                cur = _cursor(idx, ent["off"] + ent["bytes"])
        return out, cur

    @staticmethod
    def _parse_page(page: bytes,
                    seq0: Optional[int] = None) -> Iterator[FeedbackRecord]:
        magic, nrec = struct.unpack_from("<II", page)
        if magic != PAGE_MAGIC:
            raise ValueError(f"bad page magic {magic:#x}")
        lens = struct.unpack_from(f"<{nrec}I", page, 8)
        off = 8 + 4 * nrec
        mv = memoryview(page)
        for i, l in enumerate(lens):
            rec = decode_record(mv[off: off + l])
            if seq0 is not None:
                rec.seq = int(seq0) + i
            yield rec
            off += l


class CursorFile:
    """Atomic persistence for the trainer's read cursor."""

    def __init__(self, path: str) -> None:
        self.path = path

    def load(self) -> Cursor:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                cur = json.load(f)
            if (isinstance(cur, dict)
                    and {"shard", "off"} <= set(cur)):
                return _cursor(cur["shard"], cur["off"])
        except (OSError, ValueError, TypeError):
            pass
        return _cursor()

    def store(self, cursor: Cursor) -> None:
        from ..utils.checkpoint import atomic_write_bytes

        atomic_write_bytes(
            self.path,
            json.dumps(_cursor(**cursor)).encode("utf-8"),
        )
