"""Benchmark: GoogLeNet training throughput, images/sec/chip.

Run on the real TPU chip (no JAX_PLATFORMS override).  Prints ONE JSON
line: ``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.
Baseline: BASELINE.json north star = 2000 images/sec/chip (v5e).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_IMG_S = 2000.0


def main() -> None:
    import jax

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 20

    from __graft_entry__ import _build_googlenet

    # lrn layers self-probe the Pallas kernel (lrn_impl=auto) and fall
    # back to the XLA lowering if the backend can't compile it
    tr = _build_googlenet(batch_size=batch, input_size=224, dev="tpu")
    tr.eval_train = 0  # pure step time; no per-step metric fetch

    rng = np.random.RandomState(0)
    data = rng.randn(batch, 224, 224, 3).astype(np.float32)
    labels = rng.randint(0, 1000, size=(batch, 1)).astype(np.float32)

    # warmup / compile
    for _ in range(3):
        tr.update_all(data, labels)
    jax.block_until_ready(tr.params)

    t0 = time.perf_counter()
    for _ in range(steps):
        tr.update_all(data, labels)
    jax.block_until_ready(tr.params)
    dt = time.perf_counter() - t0

    n_chips = max(1, tr.mesh_plan.n_devices if tr.mesh_plan else 1)
    img_s = batch * steps / dt / n_chips
    print(
        json.dumps(
            {
                "metric": "images/sec/chip (GoogLeNet b{} train)".format(batch),
                "value": round(img_s, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(img_s / BASELINE_IMG_S, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
