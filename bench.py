"""Benchmark: GoogLeNet training throughput, images/sec/chip.

Run on the real TPU chip (no JAX_PLATFORMS override).  Prints ONE JSON
line: ``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.
Baseline: BASELINE.json north star = 2000 images/sec/chip (v5e).

Measurement design (round-2 profile findings, doc/performance.md):

* the fused train step executes in ~64ms on-chip (b128), but each
  per-step dispatch through the remote-tunnel runtime costs ~190ms of
  host time — so the benchmark drives the device-side multi-step path
  (``NetTrainer.update_scan``: ``lax.scan`` over the fused step), the
  same way a real TPU training loop amortizes host costs;
* data is staged on device once (synthetic benchmark mode); on real
  hardware the input pipeline feeds via prefetch (doc/io.md records the
  measured host decode rate);
* a persistent XLA compilation cache under ``.jax_cache/`` makes every
  run after the first skip the multi-minute GoogLeNet compile;
* a provisional JSON line is emitted right after the first timed scan,
  so a timeout mid-measurement still leaves a parseable (conservative)
  number on stdout; the final line overwrites it (drivers take the last
  JSON line).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

BASELINE_IMG_S = 2000.0
CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")

# --- fail-fast + watchdog harness (round-3 postmortem) -------------------
#
# Round 3's BENCH artifact was rc=124/parsed=null: the TPU relay (a
# single-client local tunnel) was dead and the PJRT client blocked
# forever dialing it — 25 minutes of silence, no JSON line, driver
# timeout.  Two defenses, both of which run BEFORE anything can block:
#
# * `_probe_relay()` — a plain TCP connect to the relay port before jax
#   is even imported.  A dead relay turns into a parseable diagnostic
#   JSON line ({"value": null, "error": "relay dead..."}) in ~seconds.
# * `_arm_watchdog()` — a daemon *thread* (not SIGALRM: a Python signal
#   handler cannot run while the main thread is stuck inside a C-level
#   PJRT dial, which is exactly the observed hang) that emits whatever
#   partial measurement exists and `os._exit`s before the driver's
#   budget expires.  The deadline is tunable via BENCH_WATCHDOG_SEC.

RELAY_PORT = int(os.environ.get("AXON_RELAY_PORT", "8082"))
WATCHDOG_SEC = float(os.environ.get("BENCH_WATCHDOG_SEC", "1200"))
_STAGE = {"name": "startup", "t0": time.time()}


def _set_stage(name: str) -> None:
    _STAGE["name"] = name
    print(f"# stage[{name}] t+{time.time() - _STAGE['t0']:.0f}s",
          file=sys.stderr, flush=True)


def _emit_error(err: str) -> None:
    print(json.dumps({
        "metric": "images/sec/chip (bench)",
        "value": None,
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "error": err,
    }), flush=True)
    print(f"# bench[error]: {err}", file=sys.stderr, flush=True)


def _tpu_expected() -> bool:
    """True when this process is going to dial the axon TPU relay: the
    axon site-package is on the path.  JAX_PLATFORMS=cpu does NOT
    disarm the dial — sitecustomize's register() overrides jax_platforms
    to "axon,cpu" after env processing (tests/conftest.py documents
    this), so axon-on-path means the relay gets dialed regardless."""
    return any("axon" in p for p in sys.path + [os.environ.get("PYTHONPATH", "")])


def _probe_relay(port: int = RELAY_PORT, tries: int = 3,
                 timeout: float = 3.0) -> bool:
    """TCP-connect to the relay; a few short retries ride out a restart."""
    import socket

    for i in range(tries):
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=timeout):
                return True
        except OSError:
            if i + 1 < tries:
                time.sleep(2.0)
    return False


TPU_LOCK_PATH = "/tmp/tpu_relay.lock"


def _acquire_tpu_lock() -> bool:
    """Take the single-client TPU lock (the one tools/tpu_queue.sh
    serializes every on-chip run under) for this process's lifetime.

    Returns False if another TPU client holds it — the caller must NOT
    dial (two concurrent dialers wedged the relay for ~8h in round 3).
    Re-entrant under the queue: the queue holds the flock for the whole
    sweep and marks its children via TPU_QUEUE_LOCK_HELD.
    """
    if os.environ.get("TPU_QUEUE_LOCK_HELD") == "1":
        return True
    import fcntl

    fd = os.open(TPU_LOCK_PATH, os.O_CREAT | os.O_WRONLY, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        os.close(fd)
        return False
    _STAGE["tpu_lock_fd"] = fd  # held until process exit
    return True


def _kill_guard() -> None:
    pid = _STAGE.pop("guard_pid", None)
    if pid:
        import signal as _signal

        try:
            os.kill(pid, _signal.SIGKILL)
            os.waitpid(pid, os.WNOHANG)
        except OSError:
            pass


def _fork_guard(deadline_sec: float) -> None:
    """GIL-proof watchdog backstop.  The timer-thread watchdog below
    cannot fire while the main thread is wedged inside a C call that
    never releases the GIL (the observed libtpu metadata fetch) — a
    thread needs the GIL to run.  This forked guard process shares only
    the stdout fd: after the in-process deadline plus a grace period it
    writes the diagnostic JSON line itself and SIGKILLs the wedged
    parent.  Defused by ``_kill_guard`` on any orderly exit; a parent
    that died some other way flips the child's ppid, which also
    defuses."""
    import signal as _signal

    if "jax" in sys.modules or threading.active_count() > 1:
        # forking a multithreaded process is undefined behavior (XLA's
        # native threads — invisible to threading.active_count — and
        # any Python threads hold locks the child inherits mid-flight;
        # jax warns exactly about this).  The guard exists for the
        # pre-import dial phase, where bench.py is still
        # single-threaded; armed any later (e.g. from an in-process
        # test harness with jax loaded) it stands down and leaves the
        # timer-thread watchdog as the only layer.
        return
    try:
        pid = os.fork()
    except OSError:
        return
    if pid:
        _STAGE["guard_pid"] = pid
        return
    ppid = os.getppid()
    end = time.time() + deadline_sec + 5.0
    while time.time() < end:
        time.sleep(0.25)
        if os.getppid() != ppid:
            os._exit(0)
    msg = json.dumps({
        "metric": "images/sec/chip (bench)",
        "value": None,
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "error": (f"watchdog-guard: no final measurement after "
                  f"{deadline_sec:.0f}s and the in-process watchdog "
                  "never fired (GIL-holding C call); killed the "
                  "process"),
    }) + "\n"
    try:
        os.write(1, msg.encode())  # async-signal-safe, no stdio locks
    except OSError:
        pass
    try:
        os.kill(ppid, _signal.SIGKILL)
    except OSError:
        pass
    os._exit(0)


def _arm_watchdog(deadline_sec: float = WATCHDOG_SEC) -> None:
    """Emit a diagnostic and hard-exit before the driver's own timeout
    can strike.  A completed run (any mode) sets ``_STAGE['done']`` on
    its way out, which turns a late fire into a no-op — no null JSON
    line can ever follow a valid final line.  Two layers: a timer
    thread (rich diagnostic, first shot) and a forked guard process
    (``_fork_guard``) for hangs that starve every Python thread."""
    _fork_guard(deadline_sec)

    def fire() -> None:
        _kill_guard()
        if _STAGE.get("done"):
            return
        diag = (f"watchdog: no final measurement after {deadline_sec:.0f}s; "
                f"stuck at stage '{_STAGE['name']}'")
        last = _STAGE.get("last_emit")
        if last is not None:
            # a measurement exists (e.g. the provisional line, with the
            # relay dying mid-final-scan): make IT the last stdout JSON
            # line — a last-line parser must never read null instead of
            # a real number
            print(json.dumps({**last, "watchdog": diag}), flush=True)
            print(f"# bench[error]: {diag} (re-emitted best measurement)",
                  file=sys.stderr, flush=True)
        else:
            _emit_error(diag + " (no measurement was reached)")
        os._exit(3)

    t = threading.Timer(deadline_sec, fire)
    t.daemon = True
    t.start()
    _STAGE["watchdog"] = t


def _emit(tag: str, img_s: float, batch: int) -> None:
    rec = {
        "metric": "images/sec/chip (GoogLeNet b{} train)".format(batch),
        "value": round(img_s, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 4),
    }
    _STAGE["last_emit"] = rec  # the watchdog re-emits this, never null
    # the forked guard cannot see last_emit, so a post-measurement wedge
    # would let it clobber this line with value:null — defuse it the
    # moment a real measurement exists (the guard protects the
    # pre-measurement dial phase; afterwards the timer watchdog and the
    # driver's own timeout both leave a parseable last line)
    _kill_guard()
    print(json.dumps(rec), flush=True)
    print(f"# bench[{tag}]: {img_s:.1f} img/s/chip", file=sys.stderr, flush=True)


def _time_scans(tr, data, labels, scan_k: int, n_scans: int = 3,
                per_step_data: bool = False, step=None) -> float:
    """Warm twice, time n_scans device-side scans, return sec/step —
    the shared measurement harness of every bench mode.  ``step``
    overrides the dispatched program (default: the training
    ``update_scan``); its return value is what gets block-waited."""
    import jax

    if step is None:
        kw = {} if per_step_data else {"n_steps": scan_k}

        def step():
            tr.update_scan(data, labels, **kw)
            return tr.params

    last = None
    for _ in range(2):
        last = step()
    jax.block_until_ready(last)
    t0 = time.perf_counter()
    for _ in range(n_scans):
        last = step()
    jax.block_until_ready(last)
    return (time.perf_counter() - t0) / n_scans / scan_k


def bench_io(batch: int, scan_k: int) -> None:
    """``--io`` mode: the measured path includes the REAL input pipeline
    (imgbin JPEG shards -> native decode pool -> crop/mirror augment ->
    batch -> threadbuffer -> scan_steps staging).  Reported on stderr
    only — the stdout JSON stays the device-rate metric.

    Measures the pipeline BOTH ways (doc/io.md records the results):

    * serial: decode a chunk, then block on its device scan — the rate
      is the harmonic combination of host and device rates;
    * overlapped: async scans with a 2-deep in-flight window (the CLI's
      default train loop) — the device chews chunk k while the host
      decodes k+1, so the rate approaches min(host, device).  On this
      project's 1-core CI host the host side ceilings at ~1.1k
      img/s/core, so "overlap works" shows up as combined ~= host-only.
    """
    import tempfile

    import jax

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "tools"))
    from io_bench import generate_imgbin

    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu.models import googlenet_conf
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.io.data import create_iterator

    n_img = batch * scan_k * 2
    with tempfile.TemporaryDirectory() as workdir:
        t0 = time.perf_counter()
        generate_imgbin(workdir, n_img, 256)
        print(f"# imgbin: {n_img} jpegs in {time.perf_counter() - t0:.0f}s",
              file=sys.stderr, flush=True)
        itcfg = f"""
data = train
iter = imgbin
  image_bin = {workdir}/bench.bin
  image_list = {workdir}/bench.lst
  rand_crop = 1
  rand_mirror = 1
  input_shape = 3,224,224
  batch_size = {batch}
  round_batch = 1
  label_width = 1
iter = threadbuffer
iter = end
"""
        sec = cfgmod.split_sections(cfgmod.parse_pairs(itcfg)).find("data")[0]
        it = create_iterator(sec.entries)
        it.init()
        tr = NetTrainer()
        tr.set_params(cfgmod.parse_pairs(
            googlenet_conf(batch_size=batch, input_size=224,
                           synthetic=False, dev="tpu")
        ))
        tr.eval_train = 0
        tr.init_model()

        import numpy as np_

        def host_only() -> float:
            """Input pipeline alone (test_io discipline): everything the
            train loop pays on the host — batch copy + chunk stack —
            minus only the device dispatch, so the overlap target is the
            honest host ceiling."""
            it.before_first()
            got, pending = 0, []
            t0 = time.perf_counter()
            while it.next():
                b = it.value()
                pending.append((np_.array(b.data), np_.array(b.label)))
                if len(pending) == scan_k:
                    np_.stack([d for d, _ in pending])
                    np_.stack([l for _, l in pending])
                    got += batch * len(pending)
                    pending.clear()
            got += batch * len(pending)
            return got / (time.perf_counter() - t0)

        def epoch(overlap: bool) -> float:
            it.before_first()
            got, pending, in_flight = 0, [], []
            t0 = time.perf_counter()
            while it.next():
                b = it.value()
                pending.append((np_.array(b.data), np_.array(b.label)))
                if len(pending) == scan_k:
                    h = tr.update_scan(
                        np_.stack([d for d, _ in pending]),
                        np_.stack([l for _, l in pending]),
                        sync=not overlap,
                    )
                    if overlap:
                        in_flight.append(h)
                        while len(in_flight) > 1:
                            jax.block_until_ready(in_flight.pop(0))
                    got += batch * len(pending)
                    pending.clear()
            for d, l in pending:
                tr.update_all(d, l)
                got += batch
            jax.block_until_ready(tr.params)
            if in_flight:
                jax.block_until_ready(in_flight)
            return got / (time.perf_counter() - t0)

        epoch(False)  # compile + warm page cache
        host = host_only()
        serial = epoch(False)
        lapped = epoch(True)
        print(
            f"# bench[io]: host-only {host:.0f} img/s | serial "
            f"decode->scan {serial:.0f} img/s | overlapped {lapped:.0f} "
            f"img/s (target: ~= host-only when device is faster)",
            file=sys.stderr, flush=True,
        )


def bench_lm(batch: int, seq_len: int, scan_k: int) -> None:
    """``--lm`` mode: transformer-LM training throughput (stderr only —
    the stdout JSON stays the BASELINE GoogLeNet metric).  d512 h8 L4
    bf16, flash attention, device-side multi-step scan."""
    import jax

    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu.models import transformer_lm_conf
    from cxxnet_tpu.nnet.trainer import NetTrainer

    conf = transformer_lm_conf(
        seq_len=seq_len, dim=512, nhead=8, nlayer=4, batch_size=batch,
        dev="tpu", compute_dtype="bfloat16",
    )
    tr = NetTrainer()
    tr.set_params(cfgmod.parse_pairs(conf))
    tr.eval_train = 0
    tr.init_model()
    rng = np.random.RandomState(0)
    data = rng.randint(0, 255, (scan_k, batch, seq_len)).astype(np.float32)
    labels = rng.randint(0, 255, (scan_k, batch, seq_len)).astype(np.float32)
    dt = _time_scans(tr, data, labels, scan_k, n_scans=1,
                     per_step_data=True)
    print(
        f"# bench[lm]: T={seq_len} b{batch} d512 L4: {dt*1e3:.1f} ms/step "
        f"= {batch*seq_len/dt/1e3:.0f}k tokens/s/chip",
        file=sys.stderr, flush=True,
    )


def bench_flash(seq_lens) -> None:
    """``--flash`` mode: the flash-attention kernel vs the XLA mha path,
    fwd+bwd, causal, b4 h8 d64 bf16 (the doc/performance.md fixture) —
    codifies the round-2 ad-hoc numbers as a reproducible sweep.  The
    XLA path is skipped where its (B,H,T,T) score matrix cannot compile
    (T >= 8192 on a 16 GB v5e)."""
    import jax
    import jax.numpy as jnp

    from cxxnet_tpu.ops.attention import mha
    from cxxnet_tpu.ops.flash import flash_mha

    b, h, d = 4, 8, 64
    rng = np.random.RandomState(0)
    for t in seq_lens:
        # (B, T, H, Dh) — the layout flash_mha and attention.mha share
        qkv = [
            jax.device_put(rng.randn(b, t, h, d).astype(np.float32)
                           .astype(jnp.bfloat16))
            for _ in range(3)
        ]
        # Attention is 2 (T,d)x(d,T)-shaped matmuls forward (QK^T, PV)
        # and 5 backward (dV=P^T dO, dP=dO V^T, dS->dQ, dS->dK, plus the
        # recomputed QK^T under remat), each 2*T*T*d FLOPs per (b,h);
        # causal masking halves the useful work.  Same count applied to
        # flash and the XLA path, so the two TFLOP/s are comparable to
        # each other AND to external causal-MFU numbers.
        matmul = 2 * b * h * t * t * d
        flops = (2 + 5) * matmul / 2  # fwd + bwd, causal

        def timed(fn, tag):
            def loss(q, k, v):
                return (fn(q, k, v).astype(jnp.float32) ** 2).sum()

            g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            try:
                out = g(*qkv)
                jax.block_until_ready(out)
            except Exception as e:  # noqa: BLE001 - report, keep sweeping
                print(f"# bench[flash]: T={t} {tag}: FAILS "
                      f"({type(e).__name__})", file=sys.stderr, flush=True)
                return
            t0 = time.perf_counter()
            for _ in range(10):
                out = g(*qkv)
            jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / 10
            print(f"# bench[flash]: T={t} {tag}: {dt*1e3:.2f} ms "
                  f"fwd+bwd = {flops/dt/1e12:.1f} TFLOP/s",
                  file=sys.stderr, flush=True)

        timed(lambda q, k, v: flash_mha(q, k, v, causal=True), "flash")
        timed(lambda q, k, v: mha(q, k, v, causal=True), "xla")


def _bench_imagenet_conf(tag: str, desc: str, conf: str, batch: int,
                         scan_k: int, input_size: int = 224,
                         num_class: int = 1000,
                         fuse: bool = True, wino: bool = False) -> float:
    """Shared trainer setup + synthetic-data measurement for the
    ImageNet-model bench modes (stderr only — the stdout JSON stays the
    BASELINE GoogLeNet metric).  Also the harness tools/resnet_bisect.py
    times its diagnostic variants with, so bisect numbers stay
    comparable to bench numbers.  Returns sec/step."""
    import jax

    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu.nnet.trainer import NetTrainer

    if not fuse:
        conf += "fuse_1x1 = 0\n"
    if wino:
        # Winograd F(4x4,3x3) on every 3x3 s1 conv (layers/conv.py)
        conf += "conv_wino = 1\n"
    tr = NetTrainer()
    tr.set_params(cfgmod.parse_pairs(conf))
    tr.eval_train = 0
    tr.init_model()
    rng = np.random.RandomState(0)
    data = jax.device_put(
        rng.randn(batch, input_size, input_size, 3).astype(np.float32)
    )
    labels = jax.device_put(
        rng.randint(0, num_class, (batch, 1)).astype(np.float32)
    )
    dt = _time_scans(tr, data, labels, scan_k)
    print(
        f"# bench[{tag}]: {desc} b{batch} bf16: {dt*1e3:.1f} ms/step "
        f"= {batch/dt:.0f} img/s/chip",
        file=sys.stderr, flush=True,
    )
    return dt


def bench_resnet(batch: int, scan_k: int, fuse: bool = True,
                 depth: int = 50, wino: bool = False) -> None:
    """``--resnet`` / ``--resnet101`` / ``--resnet152`` modes: ResNet
    training throughput at the chosen depth."""
    from cxxnet_tpu.models import resnet50_conf

    _bench_imagenet_conf(
        f"resnet{depth}", f"ResNet-{depth}",
        resnet50_conf(batch_size=batch, input_size=224, synthetic=False,
                      dev="tpu", depth=depth),
        batch, scan_k, fuse=fuse, wino=wino,
    )


def bench_vgg(batch: int, scan_k: int, fuse: bool = True,
              depth: int = 16, wino: bool = False) -> None:
    """``--vgg`` / ``--vgg19`` modes: VGG training throughput.
    BASELINE.json's config list names "ImageNet GoogLeNet/VGG-16 DP
    v5e-8"; this is the single-chip number (doc/performance.md has the
    batch curve)."""
    from cxxnet_tpu.models import vgg16_conf

    _bench_imagenet_conf(
        f"vgg{depth}", f"VGG-{depth}",
        vgg16_conf(batch_size=batch, input_size=224, synthetic=False,
                   dev="tpu", depth=depth),
        batch, scan_k, fuse=fuse, wino=wino,
    )


def bench_alexnet(batch: int, scan_k: int, fuse: bool = True,
                  wino: bool = False) -> None:
    """``--alexnet`` mode: AlexNet training throughput (BASELINE.json's
    "ImageNet AlexNet single-chip" config)."""
    from cxxnet_tpu.models import alexnet_conf

    _bench_imagenet_conf(
        "alexnet", "AlexNet",
        alexnet_conf(batch_size=batch, synthetic=False, dev="tpu"),
        batch, scan_k, input_size=227, fuse=fuse, wino=wino,
    )


def bench_pred(batch: int, scan_k: int, fuse: bool = True,
               wino: bool = False) -> None:
    """``--pred`` mode: GoogLeNet INFERENCE throughput (stderr only —
    the stdout JSON stays the training metric).  The reference's
    deployment path (``task=pred``, ``cxxnet_main.cpp:405-441``) runs
    batch-at-a-time; here K staged batches run as ONE device program
    (``lax.map`` over the eval forward), the same dispatch-amortizing
    design as the training scan."""
    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _build_googlenet

    tr = _build_googlenet(batch_size=batch, input_size=224, dev="tpu")
    if not fuse:
        tr.net.fuse_1x1 = 0
    if wino:
        for lay in tr.net.layer_objs:
            if hasattr(lay, "conv_wino"):
                lay.conv_wino = 1
    net = tr.net
    out_idx = net.out_node_index()

    def chunk(params, aux, data):
        # K distinct batches (a loop body that ignored its iterate
        # would invite XLA to hoist the invariant forward out of the
        # loop and fake a Kx number)
        def one(d):
            nodes, _aux = net.forward(params, d, train=False, aux=aux)
            return jnp.argmax(nodes[out_idx], axis=-1)

        return jax.lax.map(one, data)

    fwd = jax.jit(chunk)
    rng = np.random.RandomState(0)
    # fill f32 batch-by-batch: a single randn(K,...) call would make a
    # ~4x float64 transient (~3 GB at the default K=20, b128)
    host = np.empty((scan_k, batch, 224, 224, 3), np.float32)
    for k in range(scan_k):
        host[k] = rng.randn(batch, 224, 224, 3)
    data = jax.device_put(host)
    del host
    dt = _time_scans(tr, None, None, scan_k,
                     step=lambda: fwd(tr.params, tr.aux, data))
    print(
        f"# bench[pred]: GoogLeNet b{batch} bf16 inference: "
        f"{dt*1e3:.2f} ms/batch = {batch/dt:.0f} img/s/chip",
        file=sys.stderr, flush=True,
    )


def bench_bowl(batch: int, scan_k: int) -> None:
    """``--bowl`` mode: Kaggle NDSB plankton convnet throughput.  The
    reference's one semi-quantitative claim is ~5 min for 100 rounds at
    batch 64 on a GTX 780 (BASELINE.md); the printed steps/s implies the
    equivalent 100-round wall time for a 30k-image train set."""
    from cxxnet_tpu.models import kaggle_bowl_conf

    dt = _bench_imagenet_conf(
        "bowl", "NDSB convnet",
        kaggle_bowl_conf(batch_size=batch, synthetic=False, dev="tpu"),
        batch, scan_k, input_size=40, num_class=121,
    )
    rounds100 = 100 * 30000 / (batch / dt)
    print(
        f"# bench[bowl]: 100 rounds x 30k imgs = {rounds100:.0f}s device "
        "time (reference claim: ~300s on a GTX 780)",
        file=sys.stderr, flush=True,
    )


def main() -> None:
    if _tpu_expected():
        if not _probe_relay():
            _emit_error(
                f"relay dead: nothing listening on 127.0.0.1:{RELAY_PORT}; "
                "refusing to dial the TPU tunnel (it would hang, round-3 "
                "mode). For a CPU sanity pass drop .axon_site from "
                "PYTHONPATH (JAX_PLATFORMS=cpu alone is NOT enough — "
                "sitecustomize re-registers the axon backend)."
            )
            raise SystemExit(0)  # rc 0 + parseable diagnostic beats rc 124
        if not _acquire_tpu_lock():
            _emit_error(
                f"another TPU client holds {TPU_LOCK_PATH}; refusing to "
                "double-dial the single-client relay (round-3 wedge mode). "
                "Wait for the running tpu_queue.sh/bench to finish."
            )
            raise SystemExit(0)
    _arm_watchdog()
    try:
        _run()
    finally:
        # every completed mode defuses the watchdog (see _arm_watchdog)
        _STAGE["done"] = True
        wd = _STAGE.get("watchdog")
        if wd is not None:
            wd.cancel()
        _kill_guard()


def _run() -> None:
    _set_stage("jax import")
    import jax

    os.makedirs(CACHE_DIR, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    args = [a for a in sys.argv[1:] if a not in ("--io", "--lm",
                                                 "--resnet", "--vgg",
                                                 "--alexnet", "--bowl",
                                                 "--resnet101",
                                                 "--resnet152", "--vgg19",
                                                 "--flash", "--nofuse",
                                                 "--wino", "--pred")]
    io_mode = "--io" in sys.argv[1:]
    lm_mode = "--lm" in sys.argv[1:]
    resnet_mode = "--resnet" in sys.argv[1:]
    depth_flags = [f for f in ("--resnet", "--resnet101", "--resnet152",
                                "--vgg", "--vgg19") if f in sys.argv[1:]]
    if len(depth_flags) > 1:
        raise SystemExit(f"pick ONE model mode, got {depth_flags}")
    resnet_depth = (101 if "--resnet101" in sys.argv[1:]
                    else 152 if "--resnet152" in sys.argv[1:] else 50)
    resnet_mode = resnet_mode or resnet_depth != 50
    vgg_mode = "--vgg" in sys.argv[1:]
    vgg_depth = 19 if "--vgg19" in sys.argv[1:] else 16
    vgg_mode = vgg_mode or vgg_depth != 16
    alexnet_mode = "--alexnet" in sys.argv[1:]
    bowl_mode = "--bowl" in sys.argv[1:]
    flash_mode = "--flash" in sys.argv[1:]
    pred_mode = "--pred" in sys.argv[1:]
    if "--fuse" in sys.argv[1:]:
        raise SystemExit("--fuse is now the default; use --nofuse for the A/B")
    nofuse_mode = "--nofuse" in sys.argv[1:]  # fuse_1x1=0 A/B on image modes
    wino_mode = "--wino" in sys.argv[1:]  # conv_wino=1 A/B on image modes
    batch_given = len(args) > 0
    batch = int(args[0]) if batch_given else 128
    scan_k = int(args[1]) if len(args) > 1 else 50
    n_scans = int(args[2]) if len(args) > 2 else 3
    if nofuse_mode and (io_mode or lm_mode or bowl_mode):
        # bowl too: its net has no sibling 1x1 convs, so an A/B there
        # would print two identical numbers — refuse instead
        raise SystemExit(
            "--nofuse only applies to the googlenet/resnet/vgg/alexnet modes"
        )
    if flash_mode:
        # positional args are the T sweep (default: the doc fixture Ts)
        bench_flash([int(a) for a in args] or [2048, 4096, 8192, 16384])
        return
    if pred_mode:
        bench_pred(batch, min(scan_k, 20), fuse=not nofuse_mode,
                   wino=wino_mode)
        return
    if io_mode:
        bench_io(batch, min(scan_k, 10))
        return
    if lm_mode:
        bench_lm(batch=batch if batch_given else 8, seq_len=2048,
                 scan_k=min(scan_k, 20))
        return
    if resnet_mode:
        bench_resnet(batch, min(scan_k, 30), fuse=not nofuse_mode,
                     depth=resnet_depth, wino=wino_mode)
        return
    if vgg_mode:
        bench_vgg(batch, min(scan_k, 20), fuse=not nofuse_mode,
                  depth=vgg_depth, wino=wino_mode)
        return
    if alexnet_mode:
        bench_alexnet(batch=batch if batch_given else 256,
                      scan_k=min(scan_k, 30), fuse=not nofuse_mode,
                      wino=wino_mode)
        return
    if bowl_mode:
        bench_bowl(batch=batch if batch_given else 64,
                   scan_k=min(scan_k, 50))
        return

    from __graft_entry__ import _build_googlenet

    _set_stage("model build")
    t_build = time.perf_counter()
    tr = _build_googlenet(batch_size=batch, input_size=224, dev="tpu")
    tr.eval_train = 0  # pure step time; no per-step metric fetch
    if nofuse_mode:
        # sibling 1x1 fusion is default-on; --nofuse is the A/B control
        tr.net.fuse_1x1 = 0
    if wino_mode:
        # Winograd on the 3x3 s1 convs (the inception 3x3 branches)
        for lay in tr.net.layer_objs:
            if hasattr(lay, "conv_wino"):
                lay.conv_wino = 1

    rng = np.random.RandomState(0)
    data = jax.device_put(rng.randn(batch, 224, 224, 3).astype(np.float32))
    labels = jax.device_put(
        rng.randint(0, 1000, size=(batch, 1)).astype(np.float32)
    )
    n_chips = max(1, tr.mesh_plan.n_devices if tr.mesh_plan else 1)

    # warmup / compile (cached across runs via .jax_cache); the second
    # scan reaches steady state (donation layout + persistent-cache write
    # happen on the first)
    _set_stage("compile+warmup")
    for _ in range(2):
        tr.update_scan(data, labels, n_steps=scan_k)
    jax.block_until_ready(tr.params)
    print(
        f"# compile+warmup: {time.perf_counter() - t_build:.1f}s",
        file=sys.stderr,
        flush=True,
    )

    # provisional number after ONE timed scan — parseable even if the
    # driver times the process out mid-measurement
    _set_stage("timed scan (provisional)")
    t0 = time.perf_counter()
    tr.update_scan(data, labels, n_steps=scan_k)
    jax.block_until_ready(tr.params)
    _emit("provisional", batch * scan_k / (time.perf_counter() - t0) / n_chips,
          batch)

    _set_stage("timed scans (final)")
    t0 = time.perf_counter()
    for _ in range(n_scans):
        tr.update_scan(data, labels, n_steps=scan_k)
    jax.block_until_ready(tr.params)
    dt = time.perf_counter() - t0
    _emit("final", batch * scan_k * n_scans / dt / n_chips, batch)


if __name__ == "__main__":
    main()
