"""Accuracy-parity evidence (VERDICT r2 #3).

* the bundled UCI-digits conv recipe must beat the MLP's ~4% and land in
  the reference's ~2%-in-15-rounds class
  (``/root/reference/example/MNIST/README.md``);
* membuffer-overfit smokes for the ImageNet models — cache one batch and
  drive train error to 0 — the reference's own sanity discipline
  (``/root/reference/src/io/iter_mem_buffer-inl.hpp``).
"""

import os
import re
import shutil
import subprocess
import sys

import numpy as np
import pytest

from cxxnet_tpu import config as C
from cxxnet_tpu.io.data import create_iterator
from cxxnet_tpu.models import alexnet_conf, googlenet_conf
from cxxnet_tpu.nnet.trainer import NetTrainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_digits_conv_beats_mlp_bar(tmp_path):
    """example/MNIST/digits_conv.conf through the real CLI: <= 4% test
    error in 15 rounds on real handwritten digits (the committed log
    records 1.6%)."""
    pytest.importorskip("sklearn")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "make_digits_idx.py"),
         str(tmp_path / "data")],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr
    shutil.copy(os.path.join(REPO, "example", "MNIST", "digits_conv.conf"),
                str(tmp_path / "digits_conv.conf"))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO  # drops /root/.axon_site -> pure CPU jax
    r = subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu", "digits_conv.conf",
         "task=train"],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    errs = {
        int(m.group(1)): float(m.group(2))
        for m in re.finditer(r"\[(\d+)\]\ttrain-error:\S+\ttest-error:(\S+)",
                             r.stderr)
    }
    assert 15 in errs, r.stderr[-2000:]
    assert errs[15] <= 0.04, f"round-15 test error {errs[15]:.3f} > 4%"
    # convergence, not luck: the tail of the trajectory stays under 6%
    assert max(errs[k] for k in (13, 14, 15)) <= 0.06


@pytest.mark.parametrize("wino", [1, 2])
def test_digits_conv_bf16_winograd_converges(tmp_path, wino):
    """bf16 Winograd training convergence (VERDICT r4 #3): the F(4x4)
    tile's |8| transform constants amplify bf16 rounding ~15x per op
    (layers/conv.py), so the layer-level pair bound alone can't justify
    a default — this pins the MODEL-scale behavior: digits-conv under
    ``compute_dtype=bfloat16`` + ``conv_wino`` must land in the same
    convergence class as the direct conv (measured A/B:
    example/MNIST/wino_bf16_ab.log — round-15 2.8% F(4x4) / 2.0%
    F(2x2) vs 0.8% direct; bounds leave headroom for run noise)."""
    pytest.importorskip("sklearn")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "make_digits_idx.py"),
         str(tmp_path / "data")],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr
    shutil.copy(os.path.join(REPO, "example", "MNIST", "digits_conv.conf"),
                str(tmp_path / "digits_conv.conf"))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu", "digits_conv.conf",
         "task=train", "compute_dtype=bfloat16", f"conv_wino={wino}"],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    errs = {
        int(m.group(1)): float(m.group(2))
        for m in re.finditer(r"\[(\d+)\]\ttrain-error:\S+\ttest-error:(\S+)",
                             r.stderr)
    }
    assert 15 in errs, r.stderr[-2000:]
    # same acceptance shape as the fp32 test, widened one notch for the
    # documented bf16-Winograd noise: the tail must reach the digits
    # class (<=4%) and must not diverge (<=6% at round 15)
    assert min(errs[k] for k in (13, 14, 15)) <= 0.04, errs
    assert errs[15] <= 0.06, errs


def _overfit_one_cached_batch(conf_text, shape, n_steps):
    """The membuffer discipline: synthetic source + ``iter = membuffer``
    caching ONE batch; training must drive eval-mode error to 0."""
    it = create_iterator(C.split_sections(C.parse_pairs(f"""
data = train
iter = synthetic
  nsample = 8
  input_shape = {shape}
  nclass = 10
  label_width = 1
  batch_size = 8
iter = membuffer
  max_nbatch = 1
iter = end
""")).find("data")[0].entries)
    it.init()
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(conf_text))
    # memorization settings: the ImageNet schedules are tuned for real
    # data at scale, not for saturating 8 noise images
    for k, v in [("updater", "adam"), ("eta", "0.001"),
                 ("wmat:lr", "0.001"), ("bias:lr", "0.001"),
                 ("wd", "0.0"), ("wmat:wd", "0.0")]:
        tr.set_param(k, v)
    tr.eval_train = 0
    tr.init_model()
    it.before_first()
    assert it.next()
    cached = it.value()
    err = 1.0
    for step in range(n_steps):
        it.before_first()
        while it.next():
            tr.update(it.value())
        if (step + 1) % 25 == 0:
            pred = tr.predict(cached)
            err = float((pred != cached.label[:, 0]).mean())
            if err == 0.0:
                break
    assert err == 0.0, f"did not overfit the cached batch: err={err}"
    # and the second epoch really replayed the same cached data
    it.before_first()
    assert it.next()
    np.testing.assert_array_equal(np.asarray(it.value().data),
                                  np.asarray(cached.data))


def test_membuffer_overfit_alexnet():
    _overfit_one_cached_batch(
        alexnet_conf(batch_size=8, num_class=10, synthetic=False,
                     dev="cpu", input_size=67),
        "3,67,67", n_steps=300,
    )


def test_membuffer_overfit_googlenet():
    _overfit_one_cached_batch(
        googlenet_conf(batch_size=8, num_class=10, synthetic=False,
                       dev="cpu", input_size=64),
        "3,64,64", n_steps=300,
    )


def test_membuffer_overfit_resnet50():
    # exercises BN (one-pass stats), eltwise_sum shortcuts, and the
    # strided-fused stage-boundary 1x1 pairs on the convergence path
    from cxxnet_tpu.models import resnet50_conf

    _overfit_one_cached_batch(
        resnet50_conf(batch_size=8, num_class=10, synthetic=False,
                      dev="cpu", input_size=32),
        "3,32,32", n_steps=300,
    )
