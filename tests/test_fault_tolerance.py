"""Fault-injection tests for the checkpoint/recovery subsystem: corrupt
checkpoints (truncation, byte-flips), SIGTERM mid-epoch, NaN divergence
(abort and rollback policies), retention, exact resume, and producer-
thread exception propagation in the prefetch iterator.

Each test injects a REAL fault and asserts the documented recovery:
resume lands on the newest valid checkpoint and training continues."""

import os
import re
import signal
import subprocess
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from conftest import run_cli  # noqa: E402 - shared CLI harness
from test_cli import make_conf  # noqa: E402 - shared conf fixture

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _models(tmp_path):
    d = tmp_path / "models"
    if not d.exists():
        return []
    return sorted(f for f in os.listdir(d) if f.endswith(".model"))


# ----------------------------------------------------------------------
# resume discovery (the consecutive-scan bug) + corrupt-checkpoint fallback
def test_resume_with_gapped_checkpoints(tmp_path):
    """save_model=2 writes 0001, 0003, ... — the old consecutive scan
    from 0000 found nothing and raised FileNotFoundError; the glob-based
    resume must pick the newest.  (Also covers the default momentum-
    restart resume path: save_ustate stays 0.)"""
    conf = make_conf(tmp_path, num_round=4, extra="save_model = 2")
    r1 = run_cli([conf], str(tmp_path))
    assert r1.returncode == 0, r1.stderr + r1.stdout
    assert _models(tmp_path) == ["0001.model", "0003.model"]
    r2 = run_cli([conf, "continue=1", "num_round=6"], str(tmp_path))
    assert r2.returncode == 0, r2.stderr + r2.stdout
    assert "Continue training from round 4" in r2.stdout
    assert "0005.model" in _models(tmp_path)


def test_resume_falls_back_past_truncated_checkpoint(tmp_path):
    """A kill mid-write leaves a truncated newest checkpoint; resume must
    skip it (manifest size/CRC mismatch) and load the previous one
    instead of crashing."""
    conf = make_conf(tmp_path, num_round=3)
    r1 = run_cli([conf], str(tmp_path))
    assert r1.returncode == 0, r1.stderr + r1.stdout
    newest = tmp_path / "models" / "0003.model"
    blob = newest.read_bytes()
    newest.write_bytes(blob[: len(blob) // 3])  # preempted mid-write
    r2 = run_cli([conf, "continue=1", "num_round=4"], str(tmp_path))
    assert r2.returncode == 0, r2.stderr + r2.stdout
    assert "skipped" in r2.stdout and "0003.model" in r2.stdout
    # fell back to 0002 → resumes at round 3
    assert "Continue training from round 3" in r2.stdout
    assert "0004.model" in _models(tmp_path)


def test_resume_falls_back_past_byte_flipped_checkpoint(tmp_path):
    """A byte-flip deep in the payload keeps the file loadable-looking
    (magic + header intact, valid name); only the manifest CRC32 catches
    it.  Resume must fall back to the previous valid checkpoint."""
    conf = make_conf(tmp_path, num_round=3)
    r1 = run_cli([conf], str(tmp_path))
    assert r1.returncode == 0, r1.stderr + r1.stdout
    newest = tmp_path / "models" / "0003.model"
    blob = bytearray(newest.read_bytes())
    blob[-100] ^= 0xFF  # flip one payload byte, length unchanged
    newest.write_bytes(bytes(blob))
    r2 = run_cli([conf, "continue=1", "num_round=4"], str(tmp_path))
    assert r2.returncode == 0, r2.stderr + r2.stdout
    assert "crc32 mismatch" in r2.stdout
    assert "Continue training from round 3" in r2.stdout


def test_resume_with_all_checkpoints_corrupt_fails_clearly(tmp_path):
    conf = make_conf(tmp_path, num_round=1)
    r1 = run_cli([conf], str(tmp_path))
    assert r1.returncode == 0, r1.stderr + r1.stdout
    for m in _models(tmp_path):
        (tmp_path / "models" / m).write_bytes(b"garbage")
    r2 = run_cli([conf, "continue=1"], str(tmp_path))
    assert r2.returncode != 0
    assert "cannot find models for continue training" in (
        r2.stderr + r2.stdout
    )


def test_keep_latest_retention(tmp_path):
    """keep_latest=N prunes old checkpoints+manifests after each save;
    resume still works off the newest survivor."""
    conf = make_conf(tmp_path, num_round=5, extra="keep_latest = 2")
    r1 = run_cli([conf], str(tmp_path))
    assert r1.returncode == 0, r1.stderr + r1.stdout
    assert _models(tmp_path) == ["0004.model", "0005.model"]
    manifests = sorted(f for f in os.listdir(tmp_path / "models")
                       if f.endswith(".manifest.json"))
    assert manifests == ["0004.model.manifest.json",
                         "0005.model.manifest.json"]
    r2 = run_cli([conf, "continue=1", "num_round=6"], str(tmp_path))
    assert r2.returncode == 0, r2.stderr + r2.stdout
    assert "Continue training from round 6" in r2.stdout


# ----------------------------------------------------------------------
# SIGTERM mid-epoch (preemption)
@pytest.mark.slow
def test_sigterm_mid_epoch_saves_and_resumes(tmp_path):
    """Deliver SIGTERM while the train loop is inside a round: the
    process must snapshot state, exit 0 with the preemption message, and
    a continue=1 run must resume from that snapshot and finish."""
    conf = make_conf(tmp_path, num_round=2000, extra="save_model = 100")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    proc = subprocess.Popen(
        [sys.executable, "-m", "cxxnet_tpu", conf],
        cwd=str(tmp_path), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    try:
        # wait until training is inside a round (round 2+ → round 1's
        # state exists), then preempt
        deadline = time.time() + 240
        for line in proc.stdout:
            if line.startswith("update round 2"):
                break
            assert time.time() < deadline, "training never reached round 2"
        proc.send_signal(signal.SIGTERM)
        out = proc.stdout.read()
        rc = proc.wait(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == 0, out
    assert "received signal SIGTERM" in out
    m = re.search(r"preemption: state saved through round (\d+)", out)
    assert m, out
    last = int(m.group(1))
    assert f"{last:04d}.model" in _models(tmp_path)
    # the snapshot validates (atomic write: no truncation despite the kill)
    from cxxnet_tpu.utils import checkpoint as ckpt

    assert ckpt.validate_checkpoint(
        str(tmp_path / "models" / f"{last:04d}.model")
    ) is None
    # resume with per-round checkpointing so the continued run proves it
    # can both train AND checkpoint again after the preemption
    r2 = run_cli([conf, "continue=1", f"num_round={last + 2}",
                  "save_model=1"], str(tmp_path))
    assert r2.returncode == 0, r2.stderr + r2.stdout
    assert f"Continue training from round {last + 1}" in r2.stdout
    assert f"{last + 2:04d}.model" in _models(tmp_path)


# ----------------------------------------------------------------------
# divergence guard
def test_divergence_abort_policy(tmp_path):
    """A NaN loss (injected at update 5, round 1) with
    divergence_policy=abort stops training with a clear error instead of
    silently training on corrupt weights."""
    conf = make_conf(
        tmp_path, num_round=4,
        extra="divergence_policy = abort\ninject_nan_step = 5",
    )
    r = run_cli([conf], str(tmp_path))
    assert r.returncode != 0
    assert "DIVERGENCE" in r.stdout
    assert "non-finite loss" in r.stdout + r.stderr
    # blew up in round 1 (updates 4-7): rounds ≥ 1 never checkpointed
    assert _models(tmp_path) == ["0000.model", "0001.model"] or \
        _models(tmp_path) == ["0000.model"]


def test_divergence_rollback_policy(tmp_path):
    """divergence_policy=rollback: on a NaN loss the driver reloads the
    newest valid checkpoint, backs off the learning rate, and retries
    the round — the run completes all rounds with exit code 0."""
    conf = make_conf(
        tmp_path, num_round=4,
        extra=("divergence_policy = rollback\n"
               "divergence_lr_backoff = 0.5\n"
               "inject_nan_step = 9"),
    )
    r = run_cli([conf], str(tmp_path))
    assert r.returncode == 0, r.stderr + r.stdout
    assert "DIVERGENCE" in r.stdout
    assert "rolled back to round 2" in r.stdout
    assert "lr scale now 0.5" in r.stdout
    # training recovered and ran to completion
    assert "0004.model" in _models(tmp_path)
    lines = [l for l in r.stderr.splitlines() if l.startswith("[")]
    assert len(lines) == 4  # every round reported exactly once


def test_loss_spike_gate_rollback(tmp_path):
    """A FINITE loss explosion (inject_spike_step: x1e6 at update 9)
    trips the ``divergence_loss_ratio`` rolling-median gate even
    though every value passes the non-finite check — the staleness
    blow-up class that stays finite for whole rounds.  The existing
    rollback + lr-backoff path recovers and the run completes."""
    conf = make_conf(
        tmp_path, num_round=4,
        extra=("divergence_policy = rollback\n"
               "divergence_lr_backoff = 0.5\n"
               "divergence_loss_ratio = 50\n"
               "inject_spike_step = 9"),
    )
    r = run_cli([conf], str(tmp_path))
    assert r.returncode == 0, r.stderr + r.stdout
    assert "DIVERGENCE" in r.stdout
    assert "finite loss spike" in r.stdout
    assert "rolled back to round 2" in r.stdout
    assert "lr scale now 0.5" in r.stdout
    # training recovered and ran to completion
    assert "0004.model" in _models(tmp_path)


def _poison_weights(path):
    """Rewrite a checkpoint with NaN in its first weight tensor and a
    MATCHING manifest — CRC-valid, numerically poisoned (models the
    blow-up landing in the last update of the captured round, after its
    losses were measured)."""
    import io
    import struct

    from cxxnet_tpu.utils import checkpoint as ckpt

    raw = open(path, "rb").read()
    (hlen,) = struct.unpack("<I", raw[8:12])
    npz = np.load(io.BytesIO(raw[12 + hlen:]))
    flat = {k: npz[k] for k in npz.files}
    k0 = next(k for k in sorted(flat) if not k.startswith("ust:"))
    flat[k0] = np.full_like(flat[k0], np.nan)
    buf = io.BytesIO()
    np.savez(buf, **flat)
    blob = raw[: 12 + hlen] + buf.getvalue()
    man = ckpt.read_manifest(path)
    ckpt.write_checkpoint(path, blob, round_=man["round"],
                          net_fp=man["net_fingerprint"],
                          save_ustate=man["save_ustate"])


def test_divergence_rollback_skips_nan_poisoned_checkpoint(tmp_path):
    """A CRC-valid checkpoint whose weights are NaN (the divergence was
    baked in before the save) must not trap the rollback loop: resume
    hits a REAL NaN loss, rollback detects the poisoned newest
    checkpoint via the weight-finiteness check, falls back past it to
    round 2, and the run completes."""
    conf = make_conf(tmp_path, num_round=3,
                     extra="divergence_policy = rollback")
    r1 = run_cli([conf], str(tmp_path))
    assert r1.returncode == 0, r1.stderr + r1.stdout
    _poison_weights(str(tmp_path / "models" / "0003.model"))
    r2 = run_cli([conf, "continue=1", "num_round=4"], str(tmp_path))
    assert r2.returncode == 0, r2.stderr + r2.stdout
    assert "DIVERGENCE" in r2.stdout
    assert "non-finite weights; falling back past it" in r2.stdout
    assert "rolled back to round 2" in r2.stdout
    assert "0004.model" in _models(tmp_path)


def test_divergence_guard_in_process():
    """Trainer-level guard: a batch that produces a non-finite loss
    raises DivergenceError (both fused and accumulation paths) when the
    policy is set, and stays silent when it is not."""
    from cxxnet_tpu import config as C
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import DivergenceError, NetTrainer
    from test_trainer import MLP_CFG

    x = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    x[3, 2] = np.nan  # poisoned input → NaN loss
    y = np.zeros((16, 1), np.float32)

    tr = NetTrainer()
    tr.set_params(C.parse_pairs(MLP_CFG + "divergence_policy = rollback\n"))
    tr.init_model()
    with pytest.raises(DivergenceError) as ei:
        tr.update(DataBatch(data=x, label=y))
    assert ei.value.epoch == 0

    # guard disabled (default): no raise — reference behavior preserved
    tr2 = NetTrainer()
    tr2.set_params(C.parse_pairs(MLP_CFG))
    tr2.init_model()
    tr2.update(DataBatch(data=x, label=y))

    # accumulation path (update_period=2): caught at the micro-batch
    tr3 = NetTrainer()
    tr3.set_params(C.parse_pairs(
        MLP_CFG + "update_period = 2\ndivergence_policy = abort\n"
    ))
    tr3.init_model()
    with pytest.raises(DivergenceError):
        tr3.update(DataBatch(data=x, label=y))


def test_divergence_guard_update_scan():
    """update_scan checks every per-step loss; the error names the
    offending update (inject_nan_step fault hook)."""
    from cxxnet_tpu import config as C
    from cxxnet_tpu.nnet.trainer import DivergenceError, NetTrainer
    from test_trainer import MLP_CFG

    rng = np.random.RandomState(1)
    data = rng.randn(3, 16, 8).astype(np.float32)
    labels = np.zeros((3, 16, 1), np.float32)
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(
        MLP_CFG + "eval_train = 0\ndivergence_policy = abort\n"
        "inject_nan_step = 4\n"
    ))
    tr.init_model()
    assert tr.update_scan(data, labels).shape == (3,)  # epochs 0-2: clean
    with pytest.raises(DivergenceError) as ei:
        tr.update_scan(data, labels)  # epochs 3-5: update 4 injected
    assert ei.value.epoch == 4
    # one-shot: the transient fault does not re-arm
    assert tr.inject_nan_step == -1
    assert tr.update_scan(data, labels).shape == (3,)


# ----------------------------------------------------------------------
# exact resume
@pytest.mark.slow
def test_exact_resume_bit_identical(tmp_path):
    """save_ustate=1 + kill + resume must land bit-identical to an
    uninterrupted run: same weights, same updater moments, same epoch."""
    from cxxnet_tpu.nnet.trainer import NetTrainer

    extra = "save_ustate = 1\nshuffle = 0"
    (tmp_path / "a").mkdir(exist_ok=True)
    conf_a = make_conf(tmp_path / "a", num_round=4, extra=extra)
    r_a = run_cli([conf_a], str(tmp_path / "a"))
    assert r_a.returncode == 0, r_a.stderr + r_a.stdout

    (tmp_path / "b").mkdir(exist_ok=True)
    conf_b = make_conf(tmp_path / "b", num_round=2, extra=extra)
    r_b1 = run_cli([conf_b], str(tmp_path / "b"))
    assert r_b1.returncode == 0, r_b1.stderr + r_b1.stdout
    r_b2 = run_cli([conf_b, "continue=1", "num_round=4"], str(tmp_path / "b"))
    assert r_b2.returncode == 0, r_b2.stderr + r_b2.stdout

    ha, pa, _aa, ua = NetTrainer._read_model_file(
        str(tmp_path / "a" / "models" / "0004.model")
    )
    hb, pb, _ab, ub = NetTrainer._read_model_file(
        str(tmp_path / "b" / "models" / "0004.model")
    )
    assert ha["epoch_counter"] == hb["epoch_counter"]
    assert ha["rng_key"] == hb["rng_key"]
    for key in pa:
        for tag in pa[key]:
            np.testing.assert_array_equal(pa[key][tag], pb[key][tag])
    for key in ua:  # momentum state rode along and matches bit-exactly
        for tag in ua[key]:
            for slot in ua[key][tag]:
                np.testing.assert_array_equal(
                    ua[key][tag][slot], ub[key][tag][slot]
                )


# ----------------------------------------------------------------------
# prefetch producer-thread failure propagation
class _FlakyIter:
    """DataIter that raises mid-epoch on its first pass, then recovers."""

    def __init__(self, n_batches=4, fail_after=2):
        from cxxnet_tpu.io.data import DataBatch

        self._mk = lambda i: DataBatch(
            data=np.full((2, 3), i, np.float32), label=np.zeros((2, 1)),
        )
        self.n_batches = n_batches
        self.fail_after = fail_after
        self.epoch = -1
        self.i = 0

    def supports_dist_shard(self):
        return False

    def set_param(self, name, val):
        pass

    def init(self):
        pass

    def before_first(self):
        self.epoch += 1
        self.i = 0

    def next(self):
        self.i += 1
        if self.epoch == 0 and self.i > self.fail_after:
            raise RuntimeError("decode failed (injected)")
        return self.i <= self.n_batches

    def value(self):
        return self._mk(self.i)


def test_prefetch_producer_exception_propagates():
    """An exception in the producer thread must re-raise in the
    consumer's next() (previously: silent thread death, consumer blocked
    forever) — and the iterator must survive into the next epoch."""
    from cxxnet_tpu.io.prefetch import ThreadBufferIterator

    it = ThreadBufferIterator(_FlakyIter())
    it.set_param("silent", "1")
    it.init()
    it.before_first()
    assert it.next() and it.value().data[0, 0] == 1
    assert it.next() and it.value().data[0, 0] == 2

    ex = ThreadPoolExecutor(max_workers=1)
    try:
        # guard with a timeout so a regression fails instead of hanging
        fut = ex.submit(it.next)
        with pytest.raises(RuntimeError, match="decode failed"):
            fut.result(timeout=30)
        # a consumer that swallows the error and retries must see the
        # epoch END, not block on an empty queue
        fut = ex.submit(it.next)
        assert fut.result(timeout=30) is False
    finally:
        ex.shutdown(wait=False)

    # epoch 2: producer recovered; full epoch streams through
    it.before_first()
    got = []
    while it.next():
        got.append(int(it.value().data[0, 0]))
    assert got == [1, 2, 3, 4]
    it.close()
