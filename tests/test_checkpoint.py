"""Unit tests for the fault-tolerant checkpoint subsystem
(``cxxnet_tpu/utils/checkpoint.py``): atomic writes, manifests,
corruption detection, newest-valid discovery, retention, retry backoff,
and the preemption handler."""

import json
import os
import signal
import struct

import numpy as np
import pytest

from cxxnet_tpu.utils import checkpoint as ckpt


def _fake_model_bytes(payload: bytes = b"\x01" * 64) -> bytes:
    header = json.dumps({"structure": {"x": 1}, "epoch_counter": 3})
    hj = header.encode()
    return ckpt.MODEL_MAGIC + struct.pack("<I", len(hj)) + hj + payload


def _write_ckpt(dirpath, round_, payload=b"\x01" * 64, net_fp=None):
    path = os.path.join(str(dirpath), f"{round_:04d}.model")
    blob = _fake_model_bytes(payload)
    ckpt.atomic_write_bytes(path, blob)
    ckpt.write_manifest(path, round_=round_, net_fp=net_fp, blob=blob)
    return path, blob


# ----------------------------------------------------------------------
def test_atomic_write_no_temp_left(tmp_path):
    p = str(tmp_path / "out.bin")
    ckpt.atomic_write_bytes(p, b"hello")
    assert open(p, "rb").read() == b"hello"
    ckpt.atomic_write_bytes(p, b"world")  # overwrite is atomic too
    assert open(p, "rb").read() == b"world"
    assert os.listdir(tmp_path) == ["out.bin"]  # no .tmp debris


def test_atomic_write_failure_preserves_old(tmp_path, monkeypatch):
    p = str(tmp_path / "out.bin")
    ckpt.atomic_write_bytes(p, b"old")

    real_replace = os.replace

    def boom(src, dst):
        raise OSError("disk detached")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError):
        ckpt.atomic_write_bytes(p, b"new")
    monkeypatch.setattr(os, "replace", real_replace)
    assert open(p, "rb").read() == b"old"
    assert os.listdir(tmp_path) == ["out.bin"]


def test_retry_io_backoff_then_success():
    calls = {"n": 0}
    delays = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    out = ckpt.retry_io(flaky, attempts=4, base_delay=0.01, silent=True,
                        _sleep=delays.append)
    assert out == "ok" and calls["n"] == 3
    assert delays == [0.01, 0.02]  # exponential backoff


def test_retry_io_exhausts():
    def always():
        raise OSError("gone")

    with pytest.raises(OSError):
        ckpt.retry_io(always, attempts=3, base_delay=0.0, silent=True,
                      _sleep=lambda d: None)


# ----------------------------------------------------------------------
def test_manifest_roundtrip_and_validation(tmp_path):
    path, blob = _write_ckpt(tmp_path, 2, net_fp="cafe0123")
    man = ckpt.read_manifest(path)
    assert man["round"] == 2 and man["size"] == len(blob)
    assert man["crc32"] == ckpt.crc32_of(blob)
    assert ckpt.validate_checkpoint(path) is None
    assert ckpt.validate_checkpoint(path, net_fp="cafe0123") is None
    # fingerprint mismatch = "different netconfig" → invalid
    assert "fingerprint" in ckpt.validate_checkpoint(path, net_fp="deadbeef")


def test_validate_detects_truncation(tmp_path):
    path, blob = _write_ckpt(tmp_path, 0)
    with open(path, "wb") as f:
        f.write(blob[: len(blob) // 2])
    assert "size mismatch" in ckpt.validate_checkpoint(path)


def test_validate_detects_byte_flip(tmp_path):
    path, blob = _write_ckpt(tmp_path, 0)
    flipped = bytearray(blob)
    flipped[-5] ^= 0xFF  # payload flip; length and name stay plausible
    with open(path, "wb") as f:
        f.write(bytes(flipped))
    assert "crc32 mismatch" in ckpt.validate_checkpoint(path)


def test_validate_legacy_without_manifest(tmp_path):
    # pre-manifest checkpoint: structural validation only
    path = str(tmp_path / "0001.model")
    with open(path, "wb") as f:
        f.write(_fake_model_bytes())
    assert ckpt.validate_checkpoint(path) is None
    # truncated inside the header → caught structurally
    with open(path, "wb") as f:
        f.write(_fake_model_bytes()[:10])
    assert ckpt.validate_checkpoint(path) is not None
    # wrong magic → caught
    with open(path, "wb") as f:
        f.write(b"NOTMAGIC" + b"\x00" * 32)
    assert "magic" in ckpt.validate_checkpoint(path)


# ----------------------------------------------------------------------
def test_list_checkpoints_handles_gaps(tmp_path):
    # save_model=2 leaves gaps: 0001, 0003 — the consecutive-scan bug
    # found nothing here; the glob must find both, newest last
    for r in (1, 3):
        _write_ckpt(tmp_path, r)
    (tmp_path / "notes.txt").write_text("ignore me")
    (tmp_path / "x.model").write_bytes(b"non-numeric stem: ignored")
    rounds = [r for r, _ in ckpt.list_checkpoints(str(tmp_path))]
    assert rounds == [1, 3]
    assert ckpt.list_checkpoints(str(tmp_path / "missing")) == []


def test_find_latest_valid_falls_back_past_corrupt(tmp_path):
    _write_ckpt(tmp_path, 0)
    _write_ckpt(tmp_path, 2)
    path4, blob4 = _write_ckpt(tmp_path, 4)
    with open(path4, "wb") as f:
        f.write(blob4[:20])  # newest truncated (preempted mid-write)
    found = ckpt.find_latest_valid(str(tmp_path), silent=True)
    assert found is not None
    round_, path = found
    assert round_ == 2 and path.endswith("0002.model")


def test_find_latest_valid_none(tmp_path):
    assert ckpt.find_latest_valid(str(tmp_path), silent=True) is None


def test_apply_retention(tmp_path):
    for r in range(5):
        _write_ckpt(tmp_path, r)
    removed = ckpt.apply_retention(str(tmp_path), keep_latest=2)
    assert [os.path.basename(p) for p in removed] == [
        "0000.model", "0001.model", "0002.model"
    ]
    left = sorted(os.listdir(tmp_path))
    assert left == [
        "0003.model", "0003.model" + ckpt.MANIFEST_SUFFIX,
        "0004.model", "0004.model" + ckpt.MANIFEST_SUFFIX,
    ]
    # keep_latest <= 0 keeps everything
    assert ckpt.apply_retention(str(tmp_path), keep_latest=0) == []


# ----------------------------------------------------------------------
def test_net_fingerprint_stable_under_key_order():
    a = json.dumps({"layers": [1, 2], "nodes": 3})
    b = json.dumps({"nodes": 3, "layers": [1, 2]})
    assert ckpt.net_fingerprint(a) == ckpt.net_fingerprint(b)
    c = json.dumps({"nodes": 4, "layers": [1, 2]})
    assert ckpt.net_fingerprint(a) != ckpt.net_fingerprint(c)


def test_preemption_handler_sets_flag_and_restores():
    h = ckpt.PreemptionHandler(signals=(signal.SIGTERM,))
    prev = signal.getsignal(signal.SIGTERM)
    with h:
        assert not h.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert h.requested and h.signum == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) is prev


def test_trainer_save_writes_manifest_and_atomic(tmp_path):
    """NetTrainer.save_model routes through the atomic writer and drops
    a valid sidecar manifest whose fingerprint matches the graph."""
    from cxxnet_tpu.models import mnist_mlp_conf
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu import config as cfgmod

    conf = mnist_mlp_conf(batch_size=4, dev="cpu")
    tr = NetTrainer()
    tr.set_params(cfgmod.parse_pairs(conf))
    tr.init_model()
    path = str(tmp_path / "0007.model")
    tr.save_model(path, round_=7)
    assert ckpt.validate_checkpoint(path) is None
    man = ckpt.read_manifest(path)
    assert man["round"] == 7
    assert man["net_fingerprint"] == ckpt.net_fingerprint(
        tr.graph.structure_to_json()
    )
    assert man["save_ustate"] == 0
    # and the file round-trips
    tr2 = NetTrainer()
    tr2.set_params(cfgmod.parse_pairs(conf))
    tr2.load_model(path)
    for key in tr.params:
        for tag in tr.params[key]:
            np.testing.assert_array_equal(
                np.asarray(tr.params[key][tag]),
                np.asarray(tr2.params[key][tag]),
            )
