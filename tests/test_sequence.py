"""Sequence layers + transformer model: shapes, convergence, ring-SP e2e."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from cxxnet_tpu import config as C
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.models import transformer_conf
from cxxnet_tpu.nnet.trainer import NetTrainer


def _build(seq_parallel=0, model_parallel=1, dev="cpu", dtype="float32",
           **kw):
    kw.setdefault("batch_size", 8)
    kw.setdefault("seq_len", 16)
    kw.setdefault("dim", 32)
    kw.setdefault("nhead", 4)
    kw.setdefault("nlayer", 2)
    kw.setdefault("num_class", 4)
    text = transformer_conf(
        seq_parallel=seq_parallel, dev=dev, compute_dtype=dtype, **kw,
    )
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(text))
    if model_parallel != 1:
        tr.set_param("model_parallel", str(model_parallel))
    tr.init_model()
    return tr


def _toy_seq(n=32, t=16, d=32, nclass=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, t, d).astype(np.float32)
    # learnable rule: class = argmax of the mean over time of 4 fixed dims
    y = x.mean(axis=1)[:, :nclass].argmax(-1).astype(np.float32)[:, None]
    return x, y


def test_transformer_shapes_and_layers():
    tr = _build()
    shapes = tr.net.node_shapes
    assert shapes[0] == (8, 16, 32)  # input_layout=seq
    out = shapes[tr.net.out_node_index()]
    assert out == (8, 4)
    # attention weights exist with the fused qkv layout
    key = [k for k in tr.params if "attn" in k][0]
    assert tr.params[key]["wmat"].shape == (96, 32)
    assert tr.params[key]["wproj"].shape == (32, 32)


def test_transformer_overfits_small_set():
    tr = _build()
    x, y = _toy_seq()
    for _ in range(60):
        for i in range(0, 32, 8):
            tr.update(DataBatch(data=x[i:i+8], label=y[i:i+8]))
    errs = []
    for i in range(0, 32, 8):
        pred = tr.predict(DataBatch(data=x[i:i+8], label=y[i:i+8]))
        errs.append((pred != y[i:i+8, 0]).mean())
    assert float(np.mean(errs)) <= 0.1


def test_ring_sp_training_matches_plain():
    """seq_parallel ring attention == plain attention, same seeds/weights."""
    x, y = _toy_seq()
    t_plain = _build(seq_parallel=0, model_parallel=1)
    t_ring = _build(seq_parallel=1, model_parallel=4, dev="cpu:0-7")
    for tr in (t_plain, t_ring):
        for _ in range(5):
            tr.update(DataBatch(data=x[:8], label=y[:8]))
    for key in t_plain.params:
        for tag in t_plain.params[key]:
            np.testing.assert_allclose(
                np.asarray(t_plain.params[key][tag]),
                np.asarray(t_ring.params[key][tag]),
                rtol=3e-4, atol=3e-5,
                err_msg=f"{key}/{tag} diverged between plain and ring SP",
            )


def test_attention_causal_and_bf16():
    tr = _build(causal=1, dtype="bfloat16")
    x, y = _toy_seq()
    tr.update(DataBatch(data=x[:8], label=y[:8]))
    assert tr.epoch_counter == 1
    for leaf in jax.tree_util.tree_leaves(tr.params):
        assert leaf.dtype == jnp.float32  # master params stay f32


def test_seq_indivisible_ring_raises():
    # exercises the attention layer's T % model_axis divisibility check
    with pytest.raises(ValueError):
        _build(seq_parallel=1, model_parallel=8, dev="cpu:0-7",
               seq_len=20)  # 20 % 8 != 0


MOE_CFG = [
    ("batch_size", "16"),
    ("input_shape", "1,1,10"),
    ("seed", "7"),
    ("eta", "0.1"),
    ("momentum", "0.9"),
    ("netconfig", "start"),
    ("layer[0->1]", "moe:mx"),
    ("nexpert", "4"),
    ("nhidden", "32"),
    ("topk", "2"),
    ("layer[1->2]", "relu:r"),
    ("layer[2->3]", "fullc:fc"),
    ("nhidden", "4"),
    ("layer[3->3]", "softmax"),
    ("netconfig", "end"),
]


def _train_moe(dev, model_parallel=1, steps=5):
    tr = NetTrainer()
    tr.set_params([("dev", dev)] + MOE_CFG)
    if model_parallel != 1:
        tr.set_param("model_parallel", str(model_parallel))
    tr.init_model()
    rng = np.random.RandomState(3)
    for _ in range(steps):
        x = rng.randn(16, 10).astype(np.float32)
        y = rng.randint(0, 4, (16, 1)).astype(np.float32)
        tr.update(DataBatch(data=x, label=y))
    return tr


def test_moe_expert_parallel_matches_single():
    """Expert-parallel MoE (experts sharded over the model axis) computes
    the same weights as the unsharded run."""
    from jax.sharding import PartitionSpec as P

    t1 = _train_moe("cpu")
    tep = _train_moe("cpu:0-7", model_parallel=4)  # 2 data x 4 experts
    w = tep.params["l0_mx"]["wmat"]  # (4, 32, 10): E sharded
    assert w.sharding.spec == P("model", None, None)
    for key in t1.params:
        for tag in t1.params[key]:
            np.testing.assert_allclose(
                np.asarray(t1.params[key][tag]),
                np.asarray(tep.params[key][tag]),
                rtol=3e-4, atol=3e-5,
                err_msg=f"{key}/{tag} diverged under expert parallelism",
            )


def test_moe_topk_masks_gates():
    import jax.numpy as jnp
    from cxxnet_tpu.layers import create_layer

    lay = create_layer("moe")
    lay.set_param("nexpert", "8")
    lay.set_param("nhidden", "4")
    lay.set_param("topk", "2")
    p = lay.init_params(jax.random.PRNGKey(0), [(4, 6)])
    x = jnp.asarray(np.random.RandomState(0).randn(4, 6).astype(np.float32))
    (y,) = lay.apply(p, [x])
    assert y.shape == (4, 4)
    # dense (topk=0) differs from top-2 routing
    lay.topk = 0
    (y0,) = lay.apply(p, [x])
    assert not np.allclose(np.asarray(y), np.asarray(y0))


def test_moe_topk_exact_under_tied_gates():
    """Tied gate logits (x = 0 -> uniform softmax) must still activate
    EXACTLY topk experts — threshold-comparison routing kept every tied
    expert and degenerated toward the dense mixture (ADVICE r1)."""
    import jax.numpy as jnp
    from cxxnet_tpu.layers import create_layer

    lay = create_layer("moe")
    lay.set_param("nexpert", "8")
    lay.set_param("nhidden", "4")
    lay.set_param("topk", "2")
    p = lay.init_params(jax.random.PRNGKey(0), [(4, 6)])
    x = jnp.zeros((4, 6), jnp.float32)
    # reach into the routing math: reconstruct the gate the layer applies
    logits = jnp.einsum("...d,ed->...e", x, p["wgate"]).astype(jnp.float32)
    gate = jax.nn.softmax(logits, axis=-1)
    _, idx = jax.lax.top_k(gate, 2)
    mask = jax.nn.one_hot(idx, 8, dtype=gate.dtype).sum(axis=-2)
    assert int(mask.sum(axis=-1).max()) == 2  # exactly k, despite ties
    # end-to-end: output equals mean of the 2 selected experts' outputs
    (y,) = lay.apply(p, [x])
    h = jnp.einsum("...d,eod->...eo", x, p["wmat"]) + p["bias"]
    want = jnp.einsum("ne,neo->no", mask / 2.0, h)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), rtol=1e-5)
