"""Chaos harness tests: deterministic injection, retry/watchdog/breaker
semantics, and the full fault matrix (every registered site × kind).

The matrix test is the contract ``tools/chaos_run.sh`` runs lane by
lane: a triggered fault must end in **skip / retry / drain / degrade**
per policy — never a hang, a silent drop, or an unhandled crash.
"""

import os
import threading
import time

import numpy as np
import pytest

from cxxnet_tpu.io.data import create_iterator
from cxxnet_tpu.utils import faults
from cxxnet_tpu.utils.faults import (
    SITES,
    BadDataError,
    BadRecordBudget,
    CircuitBreaker,
    InjectedFault,
    RetryPolicy,
    Watchdog,
    WatchdogError,
)


# ----------------------------------------------------------------------
# injector
def test_install_validates_specs():
    with pytest.raises(ValueError, match="unknown site"):
        faults.install("nope.site:ioerror:1")
    with pytest.raises(ValueError, match="supports kinds"):
        faults.install("csv.row:ioerror:1")  # csv.row is corrupt-only
    with pytest.raises(ValueError, match="prob"):
        faults.install("csv.row:corrupt:1.5")
    with pytest.raises(ValueError, match="site:kind:prob"):
        faults.install("csv.row")


def test_sites_registry_is_well_formed():
    for site, kinds in SITES.items():
        assert kinds, site
        assert set(kinds) <= set(faults.KINDS), site


def _corrupt_pattern(seed, n=80):
    faults.reset()
    faults.injector().seed = seed
    faults.install("csv.row:corrupt:0.3")
    pat = [faults.fault_point("csv.row", f"1,{i}").startswith("~")
           for i in range(n)]
    faults.reset()
    return pat


def test_deterministic_replay_of_injection_schedule():
    """Same seed → the exact same firing pattern; a different seed
    diverges.  This is what makes chaos failures reproducible."""
    a, b = _corrupt_pattern(7), _corrupt_pattern(7)
    assert a == b
    assert any(a) and not all(a)  # prob 0.3 actually sampled
    assert _corrupt_pattern(8) != a


def test_limit_caps_firings():
    faults.install("csv.row:corrupt:1:2")
    hits = [faults.fault_point("csv.row", "1,2").startswith("~")
            for _ in range(6)]
    assert hits == [True, True, False, False, False, False]
    assert faults.injector().fire_counts()["csv.row:corrupt"] == 2


def test_ioerror_kind_raises_oserror():
    faults.install("checkpoint.write:ioerror:1:1")
    with pytest.raises(InjectedFault):
        faults.fault_point("checkpoint.write")
    faults.fault_point("checkpoint.write")  # limit spent: clean


# ----------------------------------------------------------------------
# retry policy
def test_retry_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    sleeps = []
    out = RetryPolicy(attempts=5, base_delay=0.05, jitter=0.0).run(
        flaky, what="t", silent=True, _sleep=sleeps.append)
    assert out == "ok" and len(calls) == 3
    assert sleeps == [0.05, 0.1]  # exponential backoff


def test_retry_exhausts_attempts():
    with pytest.raises(OSError, match="always"):
        RetryPolicy(attempts=3, base_delay=0.0).run(
            lambda: (_ for _ in ()).throw(OSError("always")),
            what="t", silent=True, _sleep=lambda d: None)


def test_retry_deadline_gives_up_early():
    """With a total deadline, the policy refuses to start a sleep that
    would cross it — even with attempts left."""
    sleeps = []
    t = {"now": 0.0}

    def sleep(d):
        sleeps.append(d)
        t["now"] += d

    with pytest.raises(OSError):
        RetryPolicy(attempts=50, base_delay=0.05, max_delay=0.05,
                    jitter=0.0, deadline_s=0.12).run(
            lambda: (_ for _ in ()).throw(OSError("down")),
            what="t", silent=True, _sleep=sleep,
            _clock=lambda: t["now"])
    assert len(sleeps) == 2  # 0.05 + 0.05, third sleep would cross 0.12


def test_retry_jitter_is_deterministic():
    p = RetryPolicy(attempts=4, base_delay=0.1, jitter=0.5, seed=3)
    import random as _random

    rng = _random.Random(3 ^ __import__("zlib").crc32(b"x"))
    d1 = [p.delay_for(k, rng) for k in (1, 2)]
    rng2 = _random.Random(3 ^ __import__("zlib").crc32(b"x"))
    d2 = [p.delay_for(k, rng2) for k in (1, 2)]
    assert d1 == d2


def test_retry_from_cfg_reads_config_keys():
    p = RetryPolicy.from_cfg([
        ("retry_attempts", "7"), ("retry_base_delay", "0.5"),
        ("retry_deadline_s", "9"), ("other", "x"),
    ])
    assert (p.attempts, p.base_delay, p.deadline_s) == (7, 0.5, 9.0)


# ----------------------------------------------------------------------
# watchdog
def test_watchdog_beats_prevent_firing():
    wd = Watchdog(what="w", timeout_s=0.2)
    for _ in range(3):
        time.sleep(0.1)
        wd.beat()
        wd.check()  # beats keep it quiet


def test_watchdog_fires_with_thread_stack():
    gate = threading.Event()
    t = threading.Thread(target=gate.wait, name="hungling", daemon=True)
    t.start()
    wd = Watchdog(what="test worker", timeout_s=0.05, thread=t)
    time.sleep(0.1)
    with pytest.raises(WatchdogError, match="hungling") as e:
        wd.check()
    assert "gate.wait" in str(e.value) or "wait" in str(e.value)
    gate.set()
    t.join(1)


def test_watchdog_disabled_at_zero():
    wd = Watchdog(timeout_s=0)
    time.sleep(0.05)
    wd.check()  # never fires


class _StallingIter:
    """DataIter whose next() blocks until released (a hung source)."""

    def __init__(self):
        self.release = threading.Event()
        self.calls = 0

    def supports_dist_shard(self):
        return False

    def set_param(self, name, val):
        pass

    def init(self):
        pass

    def before_first(self):
        pass

    def next(self):
        self.calls += 1
        if self.calls > 1:
            self.release.wait(60)
            return False
        return True

    def value(self):
        from cxxnet_tpu.io.data import DataBatch

        return DataBatch(data=np.zeros((2, 4), np.float32),
                         label=np.zeros((2, 1), np.float32))

    def close(self):
        self.release.set()


def test_watchdog_fires_on_stalled_producer():
    """The satellite contract: a prefetch producer stuck inside the
    wrapped iterator fails the consumer fast with a diagnostic instead
    of blocking next() forever."""
    from cxxnet_tpu.io.prefetch import ThreadBufferIterator

    base = _StallingIter()
    it = ThreadBufferIterator(base)
    it.set_param("silent", "1")
    it.set_param("watchdog_timeout_s", "0.4")
    it.init()
    it.before_first()
    assert it.next()  # first batch flows
    t0 = time.monotonic()
    with pytest.raises(WatchdogError, match="prefetch producer"):
        while it.next():
            pass
    assert time.monotonic() - t0 < 10  # failed fast, not a 60s hang
    base.release.set()
    it.close()
    assert it._thread is None


def test_threadbuffer_close_joins_producer_and_base():
    """Satellite: close() must drain, join the producer, and close the
    wrapped iterator — no daemon-thread accumulation across tests."""
    closed = []

    class _Base(_StallingIter):
        def next(self):
            self.calls += 1
            return self.calls <= 3

        def close(self):
            closed.append(1)

    from cxxnet_tpu.io.prefetch import ThreadBufferIterator

    before = threading.active_count()
    its = []
    for _ in range(4):
        it = ThreadBufferIterator(_Base())
        it.set_param("silent", "1")
        it.init()
        it.before_first()
        while it.next():
            pass
        its.append(it)
    for it in its:
        it.close()
        it.close()  # idempotent
    assert closed == [1, 1, 1, 1]
    deadline = time.monotonic() + 5
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before


# ----------------------------------------------------------------------
# circuit breaker
def test_circuit_breaker_transitions():
    t = {"now": 0.0}
    cb = CircuitBreaker(failure_threshold=2, cooldown_s=10.0,
                        clock=lambda: t["now"])
    assert cb.allow() and cb.state == "closed"
    cb.record_failure()
    assert cb.state == "closed" and cb.allow()
    cb.record_failure()
    assert cb.state == "open" and not cb.allow()
    t["now"] = 5.0
    assert not cb.allow()  # still cooling down
    t["now"] = 10.0
    assert cb.allow()  # half-open: one trial passes
    assert not cb.allow()  # ...and only one (cooldown re-armed)
    cb.record_failure()  # trial failed: back to open
    assert cb.state == "open"
    t["now"] = 20.0
    assert cb.allow()
    cb.record_success()
    assert cb.state == "closed" and cb.allow()
    snap = cb.snapshot()
    assert snap["total_failures"] == 3 and snap["times_opened"] == 2


# ----------------------------------------------------------------------
# bad-record budget
def test_budget_quarantine_and_abort(tmp_path):
    src = str(tmp_path / "data.bin")
    open(src, "w").close()
    b = BadRecordBudget(2, what="t", silent=True)
    b.record(src, 3, ValueError("x"))
    b.record(src, 9, ValueError("y"))
    with pytest.raises(BadDataError, match="max_bad_records=2") as e:
        b.record(src, 11, ValueError("z"))
    assert isinstance(e.value.__cause__, ValueError)
    offsets = [ln.split("\t")[0]
               for ln in open(src + ".quarantine").read().splitlines()]
    assert offsets == ["3", "9", "11"]
    # per-epoch budget: a new epoch resets the skip counter but the
    # sidecar does not duplicate already-quarantined offsets
    b.start_epoch()
    b.record(src, 3, ValueError("x again"))
    offsets = [ln.split("\t")[0]
               for ln in open(src + ".quarantine").read().splitlines()]
    assert offsets == ["3", "9", "11"]


def test_budget_zero_keeps_strict_behavior(tmp_path):
    src = str(tmp_path / "d")
    b = BadRecordBudget(0, what="t", silent=True)
    with pytest.raises(BadDataError):
        b.record(src, 0, ValueError("first bad record aborts"))


# ======================================================================
# the fault matrix: every registered site × kind, one lane each
def _make_imgbin(tmp_path, shards=2, per=4):
    from cxxnet_tpu.io.imgbin import BinPageWriter, encode_raw

    rng = np.random.RandomState(0)
    paths = []
    for s in range(shards):
        bin_p, lst_p = str(tmp_path / f"sh{s}.bin"), str(tmp_path / f"sh{s}.lst")
        w = BinPageWriter(bin_p)
        with open(lst_p, "w") as f:
            for r in range(per):
                img = rng.rand(4, 4, 3).astype(np.float32)
                w.push(encode_raw(img))
                f.write(f"{s * per + r}\t{float(r % 2)}\t/x_{r}.jpg\n")
        w.close()
        paths.append((bin_p, lst_p))
    return paths


def _imgbin_iter(paths, **extra):
    from cxxnet_tpu.io.imgbin import ImageBinIterator

    it = ImageBinIterator()
    for b, l in paths:
        it.set_param("image_bin", b)
        it.set_param("image_list", l)
    it.set_param("raw_pixels", "1")
    it.set_param("native_decoder", "0")
    it.set_param("silent", "1")
    for k, v in extra.items():
        it.set_param(k, str(v))
    it.init()
    return it


def _count_insts(it):
    it.before_first()
    n = 0
    while it.next():
        n += 1
    return n


def _scn_imgbin_page(kind, tmp_path):
    paths = _make_imgbin(tmp_path)
    if kind == "hang":
        # page read hangs inside the prefetch producer → the consumer's
        # watchdog fails fast instead of blocking the train loop
        entries = [("iter", "imgbin")]
        for b, l in paths:
            entries += [("image_bin", b), ("image_list", l)]
        entries += [
            ("raw_pixels", "1"), ("native_decoder", "0"), ("silent", "1"),
            ("batch_size", "2"), ("input_shape", "3,4,4"),
            ("iter", "threadbuffer"), ("watchdog_timeout_s", "0.8"),
            ("silent", "1"),
        ]
        it = create_iterator(entries)
        it.init()
        faults.install("imgbin.page:hang:1:1")
        with pytest.raises(WatchdogError):
            it.before_first()
            while it.next():
                pass
        faults.reset()  # release the hung producer so close() can join
        it.close()
        return
    it = _imgbin_iter(paths, max_bad_records=8)
    faults.install(f"imgbin.page:{kind}:1:1")
    served = _count_insts(it)
    if kind == "latency":
        assert served == 8  # only slowed down, nothing lost
    else:
        # first page of shard 0 poisoned → shard skipped, shard 1 intact
        assert served == 4
        assert it._budget.epoch_count == 1
        q = open(paths[0][0] + ".quarantine").read()
        assert "4 trailing record(s)" in q  # dropped tail is reported
    assert faults.injector().fire_counts()[f"imgbin.page:{kind}"] == 1


def _scn_imgbin_record(kind, tmp_path):
    assert kind == "corrupt"
    paths = _make_imgbin(tmp_path)
    it = _imgbin_iter(paths, max_bad_records=4)
    faults.install("imgbin.record:corrupt:1:2")
    served = _count_insts(it)
    assert served == 6  # records 0 and 1 of shard 0 skipped
    offsets = [ln.split("\t")[0] for ln in
               open(paths[0][0] + ".quarantine").read().splitlines()]
    assert offsets == ["0", "1"]  # exact quarantine offsets
    # next epoch: same corruption already spent (limit), full data flows
    assert _count_insts(it) == 8


def _write_csv(tmp_path, n=6):
    p = str(tmp_path / "d.csv")
    with open(p, "w") as f:
        for i in range(n):
            f.write(f"{i % 2},{i},{i + 1},{i + 2},{i + 3}\n")
    return p


def _scn_csv(site, kind, tmp_path):
    from cxxnet_tpu.io.csv import CSVIterator

    p = _write_csv(tmp_path)
    it = CSVIterator()
    it.set_param("filename", p)
    it.set_param("input_shape", "1,1,4")
    it.set_param("silent", "1")
    if site == "csv.read":
        it.set_param("retry_attempts", "5")
        it.set_param("retry_base_delay", "0.01")
        faults.install(f"csv.read:{kind}:1:2")
        it.init()  # retried past the injected failures
        assert len(it._rows) == 6
    else:
        it.set_param("max_bad_records", "3")
        faults.install("csv.row:corrupt:1:2")
        it.init()
        assert len(it._rows) == 4
        offsets = [ln.split("\t")[0] for ln in
                   open(p + ".quarantine").read().splitlines()]
        assert offsets == ["line1", "line2"]


def _scn_libsvm(site, kind, tmp_path):
    from cxxnet_tpu.io.libsvm import LibSVMIterator

    p = str(tmp_path / "d.libsvm")
    with open(p, "w") as f:
        for i in range(6):
            f.write(f"{i % 2} 0:{i}.0 2:1.5\n")
    it = LibSVMIterator()
    it.set_param("data_path", p)
    it.set_param("batch_size", "2")
    it.set_param("silent", "1")
    if site == "libsvm.read":
        it.set_param("retry_attempts", "5")
        it.set_param("retry_base_delay", "0.01")
        faults.install(f"libsvm.read:{kind}:1:2")
        it.init()
        assert it.num_inst == 6
    else:
        it.set_param("max_bad_records", "3")
        faults.install("libsvm.row:corrupt:1:2")
        it.init()
        assert it.num_inst == 4
        offsets = [ln.split("\t")[0] for ln in
                   open(p + ".quarantine").read().splitlines()]
        assert offsets == ["line1", "line2"]


def _scn_text(kind, tmp_path):
    from cxxnet_tpu.io.text import TextIterator

    p = str(tmp_path / "t.txt")
    with open(p, "wb") as f:
        f.write(b"abcdefgh" * 32)
    it = TextIterator()
    it.set_param("filename", p)
    it.set_param("seq_len", "8")
    it.set_param("batch_size", "4")
    it.set_param("silent", "1")
    it.set_param("retry_attempts", "5")
    it.set_param("retry_base_delay", "0.01")
    faults.install(f"text.read:{kind}:1:2")
    it.init()
    assert it._raw is not None and len(it._raw) == 256


def _scn_prefetch(kind, tmp_path):
    p = _write_csv(tmp_path)
    entries = [
        ("iter", "csv"), ("filename", p), ("batch_size", "2"),
        ("input_shape", "1,1,4"), ("silent", "1"),
        ("iter", "threadbuffer"), ("watchdog_timeout_s", "0.8"),
        ("silent", "1"),
    ]
    it = create_iterator(entries)
    it.init()
    if kind == "latency":
        faults.install("prefetch.producer:latency:1:2")
        it.before_first()
        n = 0
        while it.next():
            n += 1
        assert n == 3  # slowed, complete
        it.close()
        return
    faults.install("prefetch.producer:hang:1:1")
    with pytest.raises(WatchdogError, match="prefetch producer"):
        it.before_first()
        while it.next():
            pass
    faults.reset()
    it.close()


def _scn_checkpoint(site, kind, tmp_path):
    from cxxnet_tpu.obs.registry import registry as obs_registry
    from cxxnet_tpu.utils import checkpoint as ckpt

    if site == "checkpoint.write" and kind in ("enospc", "short"):
        # the disk-full contract: abort ATOMICALLY — no torn target, no
        # stray temp — with the prior round still loadable, and the
        # disk_full_total alert counter bumped
        ckpt.write_checkpoint(str(tmp_path / "0001.model"), b"blob1",
                              round_=1, silent=True)
        faults.install(f"checkpoint.write:{kind}:1")
        disk_full = obs_registry().counter(
            "disk_full_total", "", labelnames=("site",)
        ).labels(site="checkpoint.write")
        before = disk_full.value
        with pytest.raises(OSError):
            ckpt.write_checkpoint(str(tmp_path / "0002.model"), b"blob2",
                                  round_=2, silent=True)
        assert disk_full.value > before
        assert not (tmp_path / "0002.model").exists()
        assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]
        found = ckpt.find_latest_valid(str(tmp_path), silent=True)
        assert found is not None and found[0] == 1
        # disk space back → the retried write lands clean
        faults.reset()
        ckpt.write_checkpoint(str(tmp_path / "0002.model"), b"blob2",
                              round_=2, silent=True)
        assert ckpt.validate_checkpoint(str(tmp_path / "0002.model")) is None
        return
    if site == "checkpoint.write":
        faults.install(f"checkpoint.write:{kind}:1:2")
        path = str(tmp_path / "0001.model")
        ckpt.write_checkpoint(path, b"payload-bytes", round_=1,
                              retry=True, silent=True)
        assert ckpt.validate_checkpoint(path) is None  # retried to done
        return
    for r in (1, 2):
        ckpt.write_checkpoint(str(tmp_path / f"{r:04d}.model"),
                              f"blob{r}".encode(), round_=r, silent=True)
    faults.install(f"checkpoint.read:{kind}:1:1")
    found = ckpt.find_latest_valid(str(tmp_path), silent=True)
    assert found is not None
    if kind == "ioerror":
        assert found[0] == 1  # newest unreadable → skipped, not fatal
    else:
        assert found[0] == 2


def _write_raw_imgbin(tmp_path, n=6):
    from cxxnet_tpu.io.imgbin import BinPageWriter, encode_raw

    rng = np.random.RandomState(0)
    binp = str(tmp_path / "p.bin")
    w = BinPageWriter(binp)
    for _ in range(n):
        w.push(encode_raw(rng.rand(8, 8, 3).astype(np.float32) * 255))
    w.close()
    lst = tmp_path / "p.lst"
    lst.write_text("".join(f"{i}\t{i % 2}\tx.jpg\n" for i in range(n)))
    return binp, str(lst)


def _scn_pipeline(kind, tmp_path):
    binp, lst = _write_raw_imgbin(tmp_path)
    entries = [
        ("iter", "imgbin"), ("image_bin", binp), ("image_list", lst),
        ("raw_pixels", "1"), ("input_shape", "3,8,8"),
        ("batch_size", "2"), ("silent", "1"),
        ("num_decode_workers", "2"), ("decode_chunk", "2"),
        ("watchdog_timeout_s", "0.8"),
    ]
    it = create_iterator(entries)
    it.init()
    if kind == "latency":
        faults.install("pipeline.worker:latency:1:2")
        it.before_first()
        n = 0
        while it.next():
            n += 1
        assert n == 3  # slowed, complete
        it.close()
        return
    faults.install("pipeline.worker:hang:1:1")
    with pytest.raises(WatchdogError, match="decode pool"):
        it.before_first()
        while it.next():
            pass
    faults.reset()  # release the hung worker so close() can join
    it.close()


def _scn_serve_reload(kind, tmp_path):
    from cxxnet_tpu import serve
    from test_serve import MLP_CFG, _save_round, make_trainer, toy_rows

    mdir = str(tmp_path / "models")
    _save_round(make_trainer(seed=1), mdir, 1)
    eng = serve.Engine(cfg=MLP_CFG, model_dir=mdir, max_batch_size=8,
                       batch_timeout_ms=0, reload_breaker_threshold=2,
                       reload_breaker_cooldown_s=30.0)
    try:
        _save_round(make_trainer(seed=2), mdir, 2)
        if kind == "latency":
            faults.install("serve.reload:latency:1:1")
            assert eng.try_reload() and eng.round == 2
            assert eng.healthz()["status"] == "ok"
            return
        faults.install("serve.reload:ioerror:1")
        assert not eng.try_reload()
        assert not eng.try_reload()
        # breaker open: old model serves, health degrades, polls skipped
        assert eng.reload_breaker.state == "open"
        assert eng.healthz()["status"] == "degraded"
        assert eng.round == 1
        assert eng.predict(toy_rows(2)).shape[0] == 2
        st = eng.snapshot_stats()
        assert st["reload_failures"] == 2 and st["last_reload_ok"] is False
        fired = faults.injector().fire_counts()["serve.reload:ioerror"]
        assert not eng.try_reload()  # skipped entirely while open
        assert faults.injector().fire_counts()["serve.reload:ioerror"] == fired
        # recovery: fault gone, cooldown elapsed → swap lands, health ok
        faults.reset()
        eng.reload_breaker.cooldown_s = 0.0
        assert eng.try_reload() and eng.round == 2
        assert eng.healthz()["status"] == "ok"
    finally:
        eng.close()


def _scn_serve_batch(kind, tmp_path):
    from cxxnet_tpu import serve
    from test_serve import make_trainer, toy_rows

    eng = serve.Engine(
        trainer=make_trainer(), max_batch_size=8, batch_timeout_ms=0,
        watchdog_timeout_s=0.8 if kind == "hang" else 600.0,
    )
    x = toy_rows(2)
    try:
        eng.predict(x)  # warm the bucket BEFORE arming the fault
        faults.install(f"serve.batch:{kind}:1:1")
        if kind == "hang":
            with pytest.raises(WatchdogError):
                eng.predict(x)
            faults.reset()  # unblock the worker so close() can join
            return
        if kind == "ioerror":
            with pytest.raises(OSError):
                eng.predict(x)
            st = eng.snapshot_stats()
            assert st["errors"] == 1
        # the engine survives and keeps serving
        assert eng.predict(x).shape[0] == 2
    finally:
        eng.close()


def _scn_loop_append(kind, tmp_path):
    """A feedback-log append fault must DEGRADE: the record drops and is
    counted, nothing raises toward the serving request, and the log
    keeps accepting once the fault clears."""
    import numpy as np

    from cxxnet_tpu.loop import FeedbackReader, FeedbackWriter

    w = FeedbackWriter(str(tmp_path / "log"))
    x = np.ones((1, 16), np.float32)
    y = np.zeros((1, 1), np.float32)
    try:
        if kind == "latency":
            faults.install("loop.append:latency:1:1")
            assert w.append_batch(x, y) == 1  # slow, not lost
            assert w.dropped == 0
            return
        faults.install(f"loop.append:{kind}:1:3")
        assert w.append_batch(x, y) == 0  # dropped, no raise
        assert w.dropped == 1
        if kind == "enospc":
            from cxxnet_tpu.obs.registry import registry as obs_registry
            assert obs_registry().counter(
                "disk_full_total", "", labelnames=("site",)
            ).labels(site="loop.append").value >= 1
        faults.reset()
        assert w.append_batch(x, y) == 1  # fault cleared: accepted
        w.flush()
        recs, _ = FeedbackReader(w.dir).read_since(None)
        assert len(recs) == 1  # exactly the accepted record survived
    finally:
        w.close()


def _scn_loop_commit(kind, tmp_path):
    """A fault on the page/sidecar COMMIT path (the durable writes
    themselves) must degrade exactly like an append fault: the buffered
    page drops and is counted, nothing raises, and after recovery —
    including truncating any torn tail the short-write left — the log
    commits and reads back clean."""
    import numpy as np

    from cxxnet_tpu.loop import FeedbackReader, FeedbackWriter

    w = FeedbackWriter(str(tmp_path / "log"))
    x = np.ones((1, 16), np.float32)
    y = np.zeros((1, 1), np.float32)
    try:
        assert w.append_batch(x, y) == 1  # buffered fine
        faults.install(f"loop.commit:{kind}:1:1")
        assert w.flush() == 0  # commit failed → page dropped, no raise
        assert w.dropped == 1
        faults.reset()
        assert w.append_batch(x, y) == 1
        assert w.flush() == 1  # recovered: clean offset, clean sidecar
        recs, _ = FeedbackReader(w.dir).read_since(None)
        assert len(recs) == 1  # only the post-recovery page is visible
    finally:
        w.close()


def _scn_obs_append(kind, tmp_path):
    """The observability file sink under a sick/full disk: emit never
    raises, the drop is bounded (holdoff skips the I/O attempt instead
    of hammering rotation+open per event) and counted in
    events_dropped_total{sink,reason}; the in-memory ring keeps
    recording throughout."""
    from cxxnet_tpu.obs import events as obs_events
    from cxxnet_tpu.obs.registry import registry as obs_registry

    log = obs_events.event_log()
    log.reset()
    log.path = str(tmp_path / "events.jsonl")
    try:
        faults.install(f"obs.append:{kind}:1")
        fired_before = faults.injector().fire_counts().get(
            f"obs.append:{kind}", 0)
        log.emit("chaos.probe", n=1)  # must not raise
        assert log.dropped == 1
        reason = "disk" if kind == "enospc" else "io"
        dropped = obs_registry().counter(
            "events_dropped_total", "", labelnames=("sink", "reason")
        ).labels(sink="events", reason=reason)
        assert dropped.value >= 1
        # bounded drop: within the holdoff the sink is skipped entirely
        # (no second fault firing), but the drop is still counted and
        # the ring still records
        log.emit("chaos.probe", n=2)
        assert log.dropped == 2
        assert faults.injector().fire_counts()[f"obs.append:{kind}"] \
            == fired_before + 1
        # the ring kept recording (nested bookkeeping events — e.g.
        # fault.injected, diskio.disk_full — land in the ring too)
        assert len(log.recent(50, kind="chaos.probe")) == 2
        if kind == "enospc":
            assert obs_registry().counter(
                "disk_full_total", "", labelnames=("site",)
            ).labels(site="obs.append").value >= 1
        # disk recovers: holdoff over + fault cleared → the sink works
        faults.reset()
        log.holdoff_s = 0.0
        log._skip_until = 0.0
        log.emit("chaos.after", n=3)
        text = (tmp_path / "events.jsonl").read_text()
        assert "chaos.after" in text
    finally:
        log.reset()


class _StubMember:
    """The duck-typed slice of ElasticMember that guarded_call /
    classify_failure consume — lets the mesh.replica chaos lanes run
    without real peer processes."""

    def __init__(self, lost=False, suspects=()):
        self.lost_event = threading.Event()
        if lost:
            self.lost_event.set()
        self.abort_reason = ""
        self._suspects = list(suspects)

    def suspects(self):
        return list(self._suspects)

    def pending_plan(self):
        return None


def _scn_mesh_replica_latency():
    """The STRAGGLER scenario (doc/parallel.md "Async data-parallel"):
    a calibrated per-fence delay at the ``mesh.replica`` site models a
    slow-but-alive peer.  The synchronous loop fences after EVERY step
    (the CLI's per-batch discipline), so its round stalls >= the
    injected delay x steps; ``async_overlap=1, staleness=1`` fences
    once at the round boundary, so the same straggler is absorbed —
    measured round wall-clock must beat sync by >= 1.3x, and the fault
    site must record exactly ONE firing for the whole async round."""
    import time as _time

    import numpy as np

    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer

    delay_s, n_steps = 0.15, 6
    cfg = [
        ("dev", "tpu:0-3"), ("batch_size", "8"),
        ("input_shape", "1,1,16"), ("seed", "7"), ("eta", "0.1"),
        ("eval_train", "0"),
        ("netconfig", "start"),
        ("layer[0->1]", "fullc:fc1"), ("nhidden", "16"),
        ("layer[1->2]", "sigmoid"),
        ("layer[2->3]", "fullc:fc2"), ("nhidden", "4"),
        ("layer[3->3]", "softmax"),
        ("netconfig", "end"),
    ]

    def build(extra):
        tr = NetTrainer()
        tr.set_params(cfg + extra)
        tr.init_model()
        return tr

    def batches(seed=3):
        rng = np.random.RandomState(seed)
        return [
            DataBatch(data=rng.randn(8, 16).astype(np.float32),
                      label=rng.randint(0, 4, (8, 1)).astype(np.float32))
            for _ in range(n_steps)
        ]

    sync_tr = build([("det_reduce", "1")])
    async_tr = build([("async_overlap", "1"), ("staleness", "1"),
                      ("async_resync_period", "1")])
    # warm the compiles BEFORE arming the fault — the measurement must
    # time the straggler, not XLA
    for tr in (sync_tr, async_tr):
        tr.update(batches()[0])
        tr.sync() if tr is sync_tr else tr.async_round_end(0)

    faults.injector().latency_s = delay_s  # = fault_latency_ms / 1e3

    spec = faults.install("mesh.replica:latency:1")
    t0 = _time.perf_counter()
    for b in batches():
        sync_tr.update(b)
        sync_tr.sync()  # the CLI's per-step fence
    sync_wall = _time.perf_counter() - t0
    assert spec.fired == n_steps
    assert sync_wall >= n_steps * delay_s  # stalls >= the injected delay
    faults.reset()

    faults.injector().latency_s = delay_s
    spec = faults.install("mesh.replica:latency:1")
    t0 = _time.perf_counter()
    for b in batches():
        async_tr.update(b)  # no per-step fence
    async_tr.async_round_end(1)  # the ONE round-boundary fence
    async_wall = _time.perf_counter() - t0
    assert spec.fired == 1  # the straggler is paid once per round
    assert async_wall >= delay_s  # the bound: one fence is still real
    assert sync_wall / async_wall >= 1.3, (
        f"async did not absorb the straggler: sync {sync_wall:.2f}s vs "
        f"async {async_wall:.2f}s ({sync_wall / async_wall:.2f}x < 1.3x)")


def _scn_mesh_replica(kind, tmp_path):
    """Replica-loss faults must surface as the TYPED ReplicaLossError in
    bounded time — never an indefinite hang inside a collective.
    ``hang`` models a peer wedged in a collective: the deadline
    (collective_timeout_s) fires while the liveness monitor suspects
    the peer.  ``ioerror`` models the connection-reset a SIGKILLed peer
    produces: the raised error is classified into ReplicaLossError.
    ``latency`` models a straggler — see _scn_mesh_replica_latency."""
    import time as _time

    from cxxnet_tpu.parallel import elastic as par_elastic

    if kind == "latency":
        _scn_mesh_replica_latency()
        return
    if kind == "hang":
        faults.install("mesh.replica:hang:1:1")
        member = _StubMember(suspects=[2])
        t0 = _time.monotonic()
        with pytest.raises(par_elastic.ReplicaLossError) as ei:
            par_elastic.guarded_call(
                lambda: faults.fault_point("mesh.replica"),
                member, timeout_s=0.5, what="chaos collective")
        assert _time.monotonic() - t0 < 5.0  # bounded, not hang_s
        assert ei.value.presumed and ei.value.lost == [2]
        faults.reset()  # release the hung worker thread
        return
    faults.install("mesh.replica:ioerror:1:1")
    member = _StubMember(lost=True)
    with pytest.raises(OSError):
        faults.fault_point("mesh.replica")
    faults.fault_point("mesh.replica")  # limit spent: clean
    err = OSError("injected I/O error at mesh.replica")
    loss = par_elastic.classify_failure(err, member, confirm_s=0.1)
    assert isinstance(loss, par_elastic.ReplicaLossError)
    assert not loss.presumed  # member confirmed the loss


#: minimal replica child for the serve.replica lanes: the REAL fault
#: site (serve.server.replica_fault_probe on every /healthz) behind a
#: stdlib HTTP surface, armed at runtime via POST /arm so the replica
#: first becomes healthy and THEN misbehaves — the order the supervisor
#: must survive.  A hang armed at the site wedges the whole process
#: (data plane included), the real shape of a wedged replica.
_REPLICA_SITE_CHILD = '''
import json, sys, time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from cxxnet_tpu.serve.server import replica_fault_probe
from cxxnet_tpu.utils import faults

port = int(sys.argv[1])


class H(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _reply(self, code, obj):
        b = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(b)))
        self.end_headers()
        self.wfile.write(b)

    def do_GET(self):
        if self.path == "/healthz":
            replica_fault_probe()  # the real serve.replica site
            self._reply(200, {"status": "ok", "round": 1,
                              "model": "site.model", "reasons": []})
        else:
            self._reply(404, {"error": self.path})

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0) or 0)
        obj = json.loads(self.rfile.read(n) or b"{}")
        if self.path == "/arm":
            faults.install(obj["spec"])
            self._reply(200, {"ok": True})
        elif self.path == "/predict":
            if any(s.kind == "hang"
                   for s in faults.injector().specs()):
                time.sleep(3600.0)  # a wedged process serves nothing
            self._reply(200, {"pred": [0], "round": 1})
        else:
            self._reply(404, {"error": self.path})


httpd = ThreadingHTTPServer(("127.0.0.1", port), H)
httpd.daemon_threads = True
httpd.serve_forever(poll_interval=0.5)
'''


def _scn_serve_replica(kind, tmp_path):
    """Serving-fleet replica faults resolve at the FLEET level: the
    process keeps none of its guarantees, the supervisor restores them.
    ``ioerror`` crashes the replica on its next health probe (the real
    ``replica_fault_probe`` path: ``os._exit(13)``) — the supervisor
    must detect the exit and restart it with backoff.  ``hang`` wedges
    the replica (health plane AND data plane) — the supervisor must
    eject it from rotation within the probe deadline and restart it.
    Either way, requests keep succeeding throughout via the router's
    failover onto the healthy replica — availability degrades never,
    throughput only."""
    import json as _json
    import subprocess
    import sys as _sys
    import time as _time
    import urllib.request

    from cxxnet_tpu.serve.fleet import FleetOptions, ServingFleet

    child = tmp_path / "replica_site_child.py"
    child.write_text(_REPLICA_SITE_CHILD, encoding="utf-8")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def spawn(r):
        env = dict(os.environ)
        env["PYTHONPATH"] = repo
        env["JAX_PLATFORMS"] = "cpu"
        return subprocess.Popen(
            [_sys.executable, str(child), str(r.port)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env=env)

    opts = FleetOptions(
        replicas=2, probe_period_s=0.15, probe_timeout_s=0.4,
        slow_probes=3, start_timeout_s=60.0, restart_backoff_s=0.2,
        restart_backoff_max_s=0.5, replica_inflight=8,
        dispatch_retries=2, dispatch_timeout_s=2.0)
    fleet = ServingFleet(opts, spawn_fn=spawn)
    try:
        fleet.supervisor.start()
        assert fleet.supervisor.wait_ready(timeout_s=60.0), \
            [r.snapshot() for r in fleet.supervisor.replicas]
        victim = fleet.supervisor.replicas[0]
        req = urllib.request.Request(
            f"http://{victim.address}/arm",
            data=_json.dumps(
                {"spec": f"serve.replica:{kind}:1"}).encode("utf-8"),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert _json.loads(resp.read())["ok"]
        t_arm = _time.monotonic()

        failures = []
        t_down = None
        recovered = False
        deadline = _time.monotonic() + 25.0
        while _time.monotonic() < deadline:
            status, body = fleet.router.route(
                "/predict", {"data": [[0.5] * 4]})
            if status != 200:
                failures.append((status, body))
            if t_down is None and not victim.in_rotation():
                t_down = _time.monotonic()
            if (victim.restarts >= 1 and victim.state == "healthy"
                    and t_down is not None):
                recovered = True
                break
            _time.sleep(0.05)

        assert recovered, (victim.snapshot(),
                           fleet.supervisor.state_counts())
        # detection within the probe deadline — bounded by the wedge
        # threshold, not by hang_s (3600 s)
        budget = (opts.slow_probes
                  * (opts.probe_period_s + opts.probe_timeout_s))
        assert t_down - t_arm < budget + 6.0
        # restart reason matches the injected failure mode
        assert victim.down_reason == (
            "wedged" if kind == "hang" else "crash")
        # availability: every request during the whole window succeeded
        # (failover onto the healthy replica, never a client-visible 5xx)
        assert not failures, failures[:5]
        assert fleet.supervisor.restarts_total >= 1
    finally:
        fleet.close(drain_timeout_s=0.5)


def _scn_device_state(kind, tmp_path):
    """A real single-bit flip in live parameter state must be DETECTED
    (replica fingerprint vote), NAMED (tensor + strict-minority
    replica) and TYPED — never a silent wrong answer.  The flip lands
    at the ``device.state`` fault point (trainer.start_round) through
    the trainer's own ``inject_bitflip``; the integrity plane's next
    check raises ``IntegrityError{kind="state"}``.  The spec RNG is
    seeded by ``fault_seed``, so the same seed names the same tensor
    on a fresh trainer (the replayable-corruption contract)."""
    assert kind == "bitflip"
    from cxxnet_tpu.integrity import IntegrityError, IntegrityPlane
    from cxxnet_tpu.integrity.plane import check_state
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer

    cfg = [
        ("dev", "tpu:0-3"), ("batch_size", "8"),
        ("input_shape", "1,1,16"), ("seed", "7"), ("eta", "0.1"),
        ("eval_train", "0"), ("det_reduce", "1"), ("silent", "1"),
        ("netconfig", "start"),
        ("layer[0->1]", "fullc:fc1"), ("nhidden", "16"),
        ("layer[1->2]", "sigmoid"),
        ("layer[2->3]", "fullc:fc2"), ("nhidden", "4"),
        ("layer[3->3]", "softmax"),
        ("netconfig", "end"),
    ]
    rng = np.random.RandomState(3)
    batch = DataBatch(data=rng.randn(8, 16).astype(np.float32),
                      label=rng.randint(0, 4, (8, 1)).astype(np.float32))

    def build():
        tr = NetTrainer()
        tr.set_params(cfg)
        tr.init_model()
        tr.update(batch)
        tr.sync()
        return tr

    tr = build()
    assert check_state(tr)["clean"]  # pre-fault baseline
    faults.injector().seed = 9
    spec = faults.install("device.state:bitflip:1:1")
    tr.start_round(1)  # the armed fault point fires here
    assert spec.fired == 1
    verdict = check_state(tr)
    assert not verdict["clean"]
    named = [f["tensor"] for f in verdict["findings"]]
    assert verdict["findings"][0]["replicas"] == 4
    plane = IntegrityPlane(every=1)
    with pytest.raises(IntegrityError) as ei:
        plane.check_round(tr, 0)
    assert ei.value.kind == "state"
    assert ei.value.tensor in named
    faults.reset()
    # determinism: fresh trainer + same fault_seed → the SAME tensor
    # is corrupted and named (the corruption schedule is replayable)
    tr2 = build()
    faults.injector().seed = 9
    faults.install("device.state:bitflip:1:1")
    tr2.start_round(1)
    v2 = check_state(tr2)
    assert [f["tensor"] for f in v2["findings"]] == named


def _dataservice_fixture(tmp_path):
    """A live in-process data-service server over tiny MNIST idx files,
    plus the section/global entries a client or local chain builds
    from."""
    from cxxnet_tpu.io.dataservice.server import DataServiceServer
    from cxxnet_tpu.io.mnist import write_idx_images, write_idx_labels

    rng = np.random.RandomState(3)
    imgs = rng.randint(0, 255, size=(96, 4, 4), dtype=np.uint8)
    labs = (imgs.reshape(96, -1).mean(axis=1) > 127).astype(np.uint8)
    pi, pl = str(tmp_path / "img.idx"), str(tmp_path / "lab.idx")
    write_idx_images(pi, imgs)
    write_idx_labels(pl, labs)
    sec = [("iter", "mnist"), ("path_img", pi), ("path_label", pl),
           ("shuffle", "1"), ("input_flat", "1")]
    glob = [("batch_size", "16"), ("silent", "1"), ("seed_data", "5")]
    srv = DataServiceServer(sec, glob, max_sessions=4,
                            cache_bytes=16 << 20, silent=True)
    srv.start()
    return srv, sec, glob


def _scn_dataservice_rpc(kind, tmp_path):
    srv, sec, glob = _dataservice_fixture(tmp_path)
    client_entries = [
        ("iter", "service"),
        ("data_service_addr", f"127.0.0.1:{srv.port}"),
        ("data_service_retry_delay_s", "0.05"),
        ("watchdog_timeout_s", "0.8"),
    ]
    it = create_iterator(client_entries)
    for n, v in glob:
        it.set_param(n, v)
    it.init()
    try:
        if kind == "hang":
            # a wedged server: the consumer's watchdog fails fast
            faults.install("dataservice.rpc:hang:1:1")
            with pytest.raises(WatchdogError, match="data service client"):
                it.before_first()
                while it.next():
                    pass
            faults.reset()  # release the hung worker so close() joins
            return
        # ioerror: transport loss → the client reconnects and resumes
        # its cursor; the stream must complete AND be bitwise equal to
        # the local chain (the reconnect-resume determinism contract).
        # latency: a slow host — slower, complete, still bitwise equal.
        faults.install(f"dataservice.rpc:{kind}:1:2")
        ref = create_iterator(sec)
        for n, v in glob:
            ref.set_param(n, v)
        ref.init()
        it.before_first()
        ref.before_first()
        n_blocks = 0
        while it.next():
            assert ref.next()
            a, b = ref.value(), it.value()
            assert np.array_equal(a.data, b.data)
            assert np.array_equal(a.label, b.label)
            n_blocks += 1
        assert not ref.next()
        assert n_blocks == 6  # 96 rows / 16
        if kind == "ioerror":
            assert it.reconnects >= 1  # the resume path actually ran
        ref.close()
    finally:
        it.close()
        srv.close()


MATRIX = [
    pytest.param(site, kind, id=f"{site}-{kind}",
                 marks=[pytest.mark.chaos])
    for site, kinds in SITES.items() for kind in kinds
]


@pytest.mark.parametrize("site,kind", MATRIX)
def test_fault_matrix(site, kind, tmp_path):
    """Acceptance: every registered site × kind resolves per policy —
    skip / retry / drain / degrade — never a hang or unhandled crash."""
    if site == "imgbin.page":
        _scn_imgbin_page(kind, tmp_path)
    elif site == "imgbin.record":
        _scn_imgbin_record(kind, tmp_path)
    elif site.startswith("csv."):
        _scn_csv(site, kind, tmp_path)
    elif site.startswith("libsvm."):
        _scn_libsvm(site, kind, tmp_path)
    elif site == "text.read":
        _scn_text(kind, tmp_path)
    elif site == "prefetch.producer":
        _scn_prefetch(kind, tmp_path)
    elif site == "pipeline.worker":
        _scn_pipeline(kind, tmp_path)
    elif site.startswith("checkpoint."):
        _scn_checkpoint(site, kind, tmp_path)
    elif site == "serve.reload":
        _scn_serve_reload(kind, tmp_path)
    elif site == "serve.batch":
        _scn_serve_batch(kind, tmp_path)
    elif site == "loop.append":
        _scn_loop_append(kind, tmp_path)
    elif site == "loop.commit":
        _scn_loop_commit(kind, tmp_path)
    elif site == "obs.append":
        _scn_obs_append(kind, tmp_path)
    elif site == "mesh.replica":
        _scn_mesh_replica(kind, tmp_path)
    elif site == "serve.replica":
        _scn_serve_replica(kind, tmp_path)
    elif site == "device.state":
        _scn_device_state(kind, tmp_path)
    elif site == "dataservice.rpc":
        _scn_dataservice_rpc(kind, tmp_path)
    else:  # a new site without a scenario must fail the matrix
        pytest.fail(f"no chaos scenario for registered site {site!r}")
