"""Binary wire protocol tests (doc/serving.md "Binary wire protocol").

Four layers, outermost first: the pure codec (frame round-trip, every
malformed-frame reason token), the single-engine HTTP surface
(cross-wire parity — binary scores must be BITWISE equal to what the
JSON path serves — plus fuzzing that can never 500), the stdlib stub
replica's binary branch, and the fleet router (opaque relay, pooled
keep-alive dispatch, admission/deadline parity with JSON).
"""

import http.client
import json
import os
import re
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from cxxnet_tpu import serve
from cxxnet_tpu.serve import wire
from test_fleet import make_opts, start_stub_fleet
from test_serve import make_trainer, toy_rows

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RID_RE = re.compile(r"[0-9a-f]{6}-\d+")


def post_raw(port, path, body, ctype, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=body,
                     headers={"Content-Type": ctype})
        r = conn.getresponse()
        return r.status, r.read(), (r.getheader("Content-Type") or "")
    finally:
        conn.close()


# ----------------------------------------------------------------------
# codec
def test_codec_roundtrip():
    x = np.arange(24, dtype="<f4").reshape(2, 3, 4)
    frame = wire.encode_request(x, kind="extract", model="m", node="fc1",
                                priority="batch", deadline_ms=250)
    kind, model, priority, dl, nbytes = wire.peek_header(frame)
    assert (kind, model, priority, dl) == ("extract", "m", "batch", 250.0)
    assert nbytes == x.nbytes
    req = wire.decode_request(bytes(frame))
    assert (req.kind, req.model, req.node) == ("extract", "m", "fc1")
    assert req.priority == "batch" and req.deadline_ms == 250.0
    np.testing.assert_array_equal(req.data, x)
    # zero-copy: the array is a read-only view over the frame bytes
    assert not req.data.flags.writeable

    # the router's in-place deadline patch (no re-encode)
    before = bytes(frame)
    wire.patch_deadline(frame, 17.4)
    assert wire.peek_header(frame)[3] == 17.0
    wire.patch_deadline(frame, 0)
    assert wire.peek_header(frame)[3] is None
    # only the 4 deadline bytes moved
    after = bytes(frame)
    assert before[:wire.DEADLINE_OFFSET] == after[:wire.DEADLINE_OFFSET]
    assert before[wire.DEADLINE_OFFSET + 4:] == \
        after[wire.DEADLINE_OFFSET + 4:]

    out = np.linspace(0, 1, 8, dtype="<f4").reshape(2, 4)
    blob = wire.encode_response(out, "scores", "rid-1")
    k, rid, rows = wire.decode_response(blob)
    assert (k, rid) == ("scores", "rid-1")
    np.testing.assert_array_equal(rows, out)


def test_codec_malformed_reasons():
    """Every reason token is reachable and stable."""
    x = np.ones((2, 4), dtype="<f4")
    good = bytes(wire.encode_request(x))

    def reason(buf):
        with pytest.raises(wire.WireError) as e:
            wire.decode_request(buf)
        return e.value.reason

    assert reason(b"EVIL" + good[4:]) == "bad_magic"
    assert reason(good[:10]) == "truncated_frame"
    assert reason(good[:-3]) == "truncated_body"
    assert reason(good + b"\x00") == "trailing_bytes"
    assert reason(good[:4] + b"\x09" + good[5:]) == "bad_kind"
    assert reason(good[:5] + b"\x07" + good[6:]) == "bad_dtype"
    assert reason(good[:6] + b"\x00" + good[7:]) == "bad_ndim"
    assert reason(good[:7] + b"\x05" + good[8:]) == "bad_priority"
    big = bytearray(good)
    struct.pack_into("<I", big, 16, 0x40000000)  # dim0 -> 2**30 rows
    assert reason(big) == "oversize_shape"
    with pytest.raises(wire.WireError):
        wire.encode_request(x, kind="nope")
    with pytest.raises(wire.WireError):
        wire.encode_request(x, priority="urgent")


# ----------------------------------------------------------------------
# single-engine HTTP surface
@pytest.fixture(scope="module")
def served():
    tr = make_trainer()
    eng = serve.Engine(trainer=tr, max_batch_size=32, batch_timeout_ms=1)
    httpd = serve.make_server(eng, port=0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield eng, httpd.server_port
    httpd.shutdown()
    httpd.server_close()
    eng.close()


def test_http_cross_wire_parity(served):
    """Binary answers must be BITWISE equal to JSON answers — same
    engine, same rows, both planes; same rid minting scheme."""
    eng, port = served
    x = toy_rows(6)

    sj, bj, _ = post_raw(
        port, "/predict",
        json.dumps({"data": x.tolist(), "raw": True}).encode(),
        "application/json")
    assert sj == 200
    jbody = json.loads(bj)
    jscores = np.asarray(jbody["scores"], dtype=np.float32)

    sb, bb, ct = post_raw(port, "/predict",
                          bytes(wire.encode_request(x, kind="scores")),
                          wire.CONTENT_TYPE)
    assert sb == 200 and ct == wire.CONTENT_TYPE
    k, rid, wscores = wire.decode_response(bb)
    assert k == "scores" and wscores.shape == jscores.shape
    # tolist() of f32 round-trips through float64 repr exactly, so the
    # two planes must agree to the bit
    assert np.asarray(wscores, np.float32).tobytes() == jscores.tobytes()
    assert RID_RE.fullmatch(rid), rid
    assert RID_RE.fullmatch(jbody["rid"]), jbody["rid"]

    # predict kind: class ids (as f32 on the wire)
    sp, bp, _ = post_raw(port, "/predict",
                         bytes(wire.encode_request(x, kind="predict")),
                         wire.CONTENT_TYPE)
    assert sp == 200
    _k, _r, pred = wire.decode_response(bp)
    jp = json.loads(post_raw(
        port, "/predict", json.dumps({"data": x.tolist()}).encode(),
        "application/json")[1])["pred"]
    np.testing.assert_array_equal(np.asarray(pred).astype(np.int64),
                                  np.asarray(jp))

    # extract parity
    se, be, _ = post_raw(
        port, "/extract",
        bytes(wire.encode_request(x, kind="extract", node="fc1")),
        wire.CONTENT_TYPE)
    assert se == 200
    _k, _r, feats = wire.decode_response(be)
    jf = np.asarray(json.loads(post_raw(
        port, "/extract",
        json.dumps({"data": x.tolist(), "node": "fc1"}).encode(),
        "application/json")[1])["features"], np.float32)
    assert np.asarray(feats, np.float32).tobytes() == jf.tobytes()


def test_http_malformed_frames_never_500(served):
    """Fuzzed frames: always a JSON 400 with the stable reason token,
    never a 500, and the kept-alive socket survives every reject."""
    _eng, port = served
    x = toy_rows(2)
    good = bytes(wire.encode_request(x))
    big = bytearray(good)
    struct.pack_into("<I", big, 16, 0x7FFFFFF0)
    cases = [
        ("bad_magic", b"EVIL" + good[4:]),
        ("bad_kind", good[:4] + b"\x09" + good[5:]),
        ("bad_dtype", good[:5] + b"\x07" + good[6:]),
        ("bad_priority", good[:7] + b"\x05" + good[8:]),
        ("truncated_frame", good[:8]),
        ("truncated_body", good[:-4]),
        ("trailing_bytes", good + b"\x00\x00"),
        ("oversize_shape", bytes(big)),
    ]
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        for want, payload in cases:
            conn.request("POST", "/predict", body=payload,
                         headers={"Content-Type": wire.CONTENT_TYPE})
            r = conn.getresponse()
            body = json.loads(r.read())
            assert r.status == 400, (want, r.status, body)
            assert body["reason"] == want, (want, body)
        # wrong kind for the route
        conn.request("POST", "/extract", body=good,
                     headers={"Content-Type": wire.CONTENT_TYPE})
        r = conn.getresponse()
        assert (r.status, json.loads(r.read())["reason"]) == \
            (400, "bad_kind")
        # /feedback refuses binary with its own token
        conn.request("POST", "/feedback", body=good,
                     headers={"Content-Type": wire.CONTENT_TYPE})
        r = conn.getresponse()
        assert (r.status, json.loads(r.read())["reason"]) == \
            (400, "wire_unsupported_route")
        # the SAME socket still serves a clean request: no desync
        conn.request("POST", "/predict", body=good,
                     headers={"Content-Type": wire.CONTENT_TYPE})
        r = conn.getresponse()
        assert r.status == 200
        wire.decode_response(r.read())
    finally:
        conn.close()


def test_http_keepalive_socket_reuse(served):
    """Satellite regression: the serving endpoints speak HTTP/1.1 with
    correct Content-Length — two sequential requests (JSON then
    binary) ride ONE socket, and the server never asks to close."""
    _eng, port = served
    x = toy_rows(3)
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        for body, ctype in (
                (json.dumps({"data": x.tolist()}).encode(),
                 "application/json"),
                (bytes(wire.encode_request(x)), wire.CONTENT_TYPE),
                (json.dumps({"data": x.tolist()}).encode(),
                 "application/json")):
            conn.request("POST", "/predict", body=body,
                         headers={"Content-Type": ctype})
            r = conn.getresponse()
            assert r.version == 11 and r.status == 200
            assert not r.will_close, "server dropped keep-alive"
            r.read()
    finally:
        conn.close()


def test_http_wire_disabled_and_cfg_validation():
    tr = make_trainer()
    eng = serve.Engine(trainer=tr, cfg=[("wire", "json")],
                       max_batch_size=8, batch_timeout_ms=1)
    httpd = serve.make_server(eng, port=0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        x = toy_rows(2)
        s, b, _ = post_raw(httpd.server_port, "/predict",
                           bytes(wire.encode_request(x)),
                           wire.CONTENT_TYPE)
        assert s == 400 and json.loads(b)["reason"] == "wire_disabled"
        # JSON is untouched by the gate
        s, b, _ = post_raw(httpd.server_port, "/predict",
                           json.dumps({"data": x.tolist()}).encode(),
                           "application/json")
        assert s == 200 and "pred" in json.loads(b)
    finally:
        httpd.shutdown()
        httpd.server_close()
        eng.close()
    with pytest.raises(ValueError, match="wire must be"):
        serve.Engine(trainer=make_trainer(), cfg=[("wire", "msgpack")])


def test_http_binary_shed_and_deadline_match_json(served):
    """429 (queue full) and 504 (deadline) surface identically on both
    wire formats — same status, JSON error body either way."""
    eng, port = served
    x = toy_rows(1)
    release = threading.Event()
    orig = eng.batcher._runner

    def slow(kind, node, data):
        release.wait(10.0)
        return orig(kind, node, data)

    eng.batcher._runner = slow
    old_limit = eng.batcher.queue_limit
    eng.batcher.queue_limit = 1
    bg = []
    try:
        # occupy the worker, then fill the 1-slot queue
        for _ in range(2):
            t = threading.Thread(
                target=lambda: post_raw(
                    port, "/predict",
                    json.dumps({"data": x.tolist()}).encode(),
                    "application/json"), daemon=True)
            t.start()
            bg.append(t)
            time.sleep(0.2)
        for body, ctype in (
                (json.dumps({"data": x.tolist()}).encode(),
                 "application/json"),
                (bytes(wire.encode_request(x)), wire.CONTENT_TYPE)):
            s, b, rt = post_raw(port, "/predict", body, ctype)
            assert s == 429, (ctype, s, b)
            assert "error" in json.loads(b)
        # deadline expiry while the worker is still held
        for body, ctype in (
                (json.dumps({"data": x.tolist(),
                             "deadline_ms": 1}).encode(),
                 "application/json"),
                (bytes(wire.encode_request(x, deadline_ms=1)),
                 wire.CONTENT_TYPE)):
            s, b, _ = post_raw(port, "/predict", body, ctype)
            assert s in (429, 504), (ctype, s, b)
    finally:
        release.set()
        eng.batcher._runner = orig
        eng.batcher.queue_limit = old_limit
        for t in bg:
            t.join(timeout=15)


# ----------------------------------------------------------------------
# micro-batcher staging assembly
def test_batcher_staging_assembly():
    from cxxnet_tpu.serve.batcher import _Request

    def runner(kind, node, data):
        return data * 2.0

    mb = serve.MicroBatcher(runner, max_batch_size=64,
                            batch_timeout_ms=20.0, queue_limit=128)
    try:
        reqs = [_Request(kind="out", node=None,
                         data=np.full((2, 3), i, np.float32),
                         enqueue_t=0.0, deadline_t=None)
                for i in range(3)]
        out = mb._assemble(reqs)
        np.testing.assert_array_equal(
            out, np.concatenate([r.data for r in reqs]))
        # the staging buffer is REUSED, not reallocated per batch
        buf = mb._staging[((3,), "<f4")]
        assert buf.shape[0] == mb.max_batch_size
        mb._assemble(reqs)
        assert mb._staging[((3,), "<f4")] is buf
        # concurrent submits through the worker stay row-aligned
        xs = [np.full((i + 1, 3), float(i), np.float32)
              for i in range(8)]
        outs = [None] * len(xs)

        def go(i):
            outs[i] = np.array(mb.submit(xs[i]))

        threads = [threading.Thread(target=go, args=(i,))
                   for i in range(len(xs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, x in enumerate(xs):
            np.testing.assert_array_equal(outs[i], x * 2.0)
    finally:
        mb.close()


# ----------------------------------------------------------------------
# stub replica binary branch
def test_stub_binary_predict_and_keepalive():
    from cxxnet_tpu.parallel.elastic import free_port

    port = free_port()
    proc = subprocess.Popen(
        [sys.executable,
         os.path.join(REPO, "cxxnet_tpu", "serve", "stub.py"),
         "--port", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                c = http.client.HTTPConnection("127.0.0.1", port,
                                               timeout=1)
                c.request("GET", "/healthz")
                c.getresponse().read()
                c.close()
                break
            except OSError:
                time.sleep(0.05)
        x = np.round(np.random.RandomState(0).rand(3, 4), 3) \
            .astype(np.float32)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            # JSON leg
            conn.request("POST", "/predict",
                         body=json.dumps({"data": x.tolist()}).encode(),
                         headers={"Content-Type": "application/json"})
            r = conn.getresponse()
            assert r.status == 200 and not r.will_close
            jpred = json.loads(r.read())["pred"]
            # binary leg on the SAME socket — stub agrees bit-for-bit
            conn.request("POST", "/predict",
                         body=bytes(wire.encode_request(x)),
                         headers={"Content-Type": wire.CONTENT_TYPE})
            r = conn.getresponse()
            assert r.status == 200 and not r.will_close
            k, rid, pred = wire.decode_response(r.read())
            assert (k, rid) == ("predict", "stub")
            np.testing.assert_array_equal(
                np.asarray(pred).astype(int), np.asarray(jpred))
            # malformed frame: 400 + reason, socket still in sync
            conn.request("POST", "/predict",
                         body=b"EVIL" + bytes(wire.encode_request(x))[4:],
                         headers={"Content-Type": wire.CONTENT_TYPE})
            r = conn.getresponse()
            assert r.status == 400
            assert json.loads(r.read())["reason"] == "bad_magic"
            conn.request("GET", "/healthz")
            assert conn.getresponse().status == 200
        finally:
            conn.close()
    finally:
        proc.kill()
        proc.wait(timeout=10)


# ----------------------------------------------------------------------
# fleet router: opaque relay + pooled dispatch
def test_fleet_pool_size_cfg():
    from cxxnet_tpu.serve import FleetOptions

    opts = FleetOptions.from_cfg([("replicas", "2"),
                                  ("fleet_pool_size", "3")])
    assert opts.pool_size == 3
    assert FleetOptions.from_cfg([("replicas", "2")]).pool_size == 8
    with pytest.raises(ValueError, match="fleet_pool_size"):
        FleetOptions.from_cfg([("replicas", "2"),
                               ("fleet_pool_size", "0")])


def test_router_binary_relay_pool_and_admission():
    fleet = start_stub_fleet(make_opts())
    try:
        x = np.ones((2, 4), np.float32)
        status, body, ctype = fleet.router.route_wire(
            "/predict", wire.encode_request(x, deadline_ms=5000),
            "interactive", 5000)
        assert status == 200 and ctype == wire.CONTENT_TYPE
        k, rid, pred = wire.decode_response(body)
        assert (k, rid) == ("predict", "stub") and pred.shape == (2,)
        # the JSON plane through the same router agrees
        sj, bj = fleet.router.route("/predict", {"data": x.tolist()})
        assert sj == 200
        np.testing.assert_array_equal(
            np.asarray(pred).astype(int), np.asarray(bj["pred"]))
        # pooled dispatch parked the keep-alive connections
        stats = fleet.router.pool_stats()
        assert sum(stats.values()) >= 1, stats
        # eject/reload hook surface: retiring empties the pool
        addr = max(stats, key=stats.get)
        assert fleet.router.retire_replica_pool(addr) >= 1
        assert fleet.router.pool_stats()[addr] == 0
        # binary admission: zero capacity sheds with a JSON 429 body
        old = fleet.opts.replica_inflight
        fleet.opts.replica_inflight = 0
        s429, b429, ct429 = fleet.router.route_wire(
            "/predict", wire.encode_request(x), "batch")
        assert s429 == 429 and ct429 == "application/json"
        assert "load shed" in json.loads(b429)["error"]
        fleet.opts.replica_inflight = old
        # expired budget before any dispatch: same 504 as JSON
        s504, b504, _ = fleet.router.route_wire(
            "/predict", wire.encode_request(x), "interactive", 1e-6)
        assert s504 == 504 and "deadline" in json.loads(b504)["error"]
    finally:
        fleet.close(drain_timeout_s=0.0)


def test_router_httpd_binary_front_door():
    """End-to-end through the router's OWN HTTP surface: binary frames
    negotiate, relay, and fail safely on one kept-alive socket."""
    fleet = start_stub_fleet(make_opts())
    httpd = fleet.router.make_httpd("127.0.0.1", 0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    port = httpd.server_port
    try:
        x = np.ones((3, 4), np.float32)
        frame = bytes(wire.encode_request(x, deadline_ms=5000))
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            conn.request("POST", "/predict", body=frame,
                         headers={"Content-Type": wire.CONTENT_TYPE})
            r = conn.getresponse()
            body = r.read()
            assert r.status == 200 and not r.will_close
            _k, _rid, pred = wire.decode_response(body)
            assert pred.shape == (3,)
            # malformed at the front door: 400 + token, socket survives
            conn.request("POST", "/predict", body=b"EVIL" + frame[4:],
                         headers={"Content-Type": wire.CONTENT_TYPE})
            r = conn.getresponse()
            assert r.status == 400
            assert json.loads(r.read())["reason"] == "bad_magic"
            # binary to /feedback: refused with the stable token
            conn.request("POST", "/feedback", body=frame,
                         headers={"Content-Type": wire.CONTENT_TYPE})
            r = conn.getresponse()
            assert r.status == 400
            assert json.loads(r.read())["reason"] == \
                "wire_unsupported_route"
            # same socket, JSON plane: still in sync
            conn.request("POST", "/predict",
                         body=json.dumps({"data": x.tolist()}).encode(),
                         headers={"Content-Type": "application/json"})
            r = conn.getresponse()
            assert r.status == 200
            assert json.loads(r.read())["pred"]
        finally:
            conn.close()
    finally:
        httpd.shutdown()
        httpd.server_close()
        fleet.close(drain_timeout_s=0.0)
