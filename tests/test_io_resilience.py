"""Data-pipeline resilience against REAL on-disk corruption (no
injection): skip-and-quarantine semantics, budget enforcement, exact
quarantine offsets, and the end-to-end chaos training run.

Chaos-marked cases run in ``tools/chaos_run.sh``; the cheap ones also
run in tier-1.
"""

import os
import struct

import numpy as np
import pytest

from cxxnet_tpu.io.imgbin import BinPageWriter, ImageBinIterator, encode_raw
from cxxnet_tpu.utils.faults import BadDataError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_shard(bin_p, lst_p, blobs, start_idx=0):
    w = BinPageWriter(str(bin_p))
    with open(lst_p, "w") as f:
        for r, blob in enumerate(blobs):
            w.push(blob)
            f.write(f"{start_idx + r}\t{float(r % 2)}\t/x_{r}.jpg\n")
    w.close()


def _good_blob(seed=0):
    rng = np.random.RandomState(seed)
    return encode_raw(rng.rand(4, 4, 3).astype(np.float32))


def _imgbin(shards, **extra):
    it = ImageBinIterator()
    for b, l in shards:
        it.set_param("image_bin", str(b))
        it.set_param("image_list", str(l))
    it.set_param("raw_pixels", "1")
    it.set_param("native_decoder", "0")
    it.set_param("silent", "1")
    for k, v in extra.items():
        it.set_param(k, str(v))
    it.init()
    return it


def _count(it):
    it.before_first()
    n = 0
    while it.next():
        n += 1
    return n


def _corrupt_page_header(bin_p):
    """Byte-flip the CXBP page magic so the page parser rejects it."""
    with open(bin_p, "r+b") as f:
        head = bytearray(f.read(4))
        head[0] ^= 0xFF
        f.seek(0)
        f.write(head)


# ----------------------------------------------------------------------
@pytest.mark.chaos
def test_corrupt_page_skipped_and_quarantined(tmp_path):
    shards = [(tmp_path / f"s{i}.bin", tmp_path / f"s{i}.lst")
              for i in range(2)]
    for i, (b, l) in enumerate(shards):
        _write_shard(b, l, [_good_blob(r) for r in range(4)], i * 4)
    _corrupt_page_header(shards[0][0])
    it = _imgbin(shards, max_bad_records=4)
    assert _count(it) == 4  # shard 1 intact, shard 0's page skipped
    assert it._budget.epoch_count == 1
    q = open(str(shards[0][0]) + ".quarantine").read()
    assert q.startswith("open\t")  # unreadable at shard-open time
    assert "4 record(s) dropped" in q  # the loss is never under-reported
    # the skip repeats identically next epoch, within a FRESH budget
    assert _count(it) == 4
    assert it._budget.epoch_count == 1


@pytest.mark.chaos
def test_max_bad_records_exceeded_aborts_with_summary(tmp_path):
    shards = [(tmp_path / f"s{i}.bin", tmp_path / f"s{i}.lst")
              for i in range(2)]
    for i, (b, l) in enumerate(shards):
        _write_shard(b, l, [_good_blob(r) for r in range(4)], i * 4)
    for b, _ in shards:
        _corrupt_page_header(b)
    with pytest.raises(BadDataError, match="max_bad_records=1") as e:
        it = _imgbin(shards, max_bad_records=1)
        it.before_first()
        while it.next():
            pass
    assert "skipped" in str(e.value)  # the abort carries the summary


def test_budget_zero_aborts_on_first_bad_record(tmp_path):
    """Default strict behavior is unchanged: no budget, first corrupt
    record kills the epoch."""
    b, l = tmp_path / "s.bin", tmp_path / "s.lst"
    _write_shard(b, l, [b"\x00\x01", _good_blob()])
    it = _imgbin([(b, l)])
    it.before_first()
    with pytest.raises(BadDataError):
        it.next()


@pytest.mark.chaos
def test_exact_quarantine_offsets_for_bad_records(tmp_path):
    """Records 1 and 3 are truncated blobs; the sidecar must name
    exactly those ordinals and the survivors must keep their labels."""
    blobs = [_good_blob(0), b"\x00\x01", _good_blob(2),
             struct.pack("<HHHH", 99, 99, 99, 0), _good_blob(4)]
    b, l = tmp_path / "s.bin", tmp_path / "s.lst"
    _write_shard(b, l, blobs)
    it = _imgbin([(b, l)], max_bad_records=3)
    got = []
    it.before_first()
    while it.next():
        got.append(it.value().index)
    assert got == [0, 2, 4]  # blob↔label alignment preserved past skips
    offsets = [ln.split("\t")[0] for ln in
               open(str(b) + ".quarantine").read().splitlines()]
    assert offsets == ["1", "3"]


@pytest.mark.chaos
def test_csv_corrupt_rows_quarantined(tmp_path):
    p = tmp_path / "d.csv"
    rows = [f"{i % 2},{i},{i},{i},{i}" for i in range(6)]
    rows[1] = "0,not,a,number,row"
    rows[4] = "1,2,3"  # wrong column count
    p.write_text("\n".join(rows) + "\n")
    from cxxnet_tpu.io.csv import CSVIterator

    it = CSVIterator()
    it.set_param("filename", str(p))
    it.set_param("input_shape", "1,1,4")
    it.set_param("silent", "1")
    it.set_param("max_bad_records", "2")
    it.init()
    assert len(it._rows) == 4
    offsets = [ln.split("\t")[0] for ln in
               open(str(p) + ".quarantine").read().splitlines()]
    assert offsets == ["line2", "line5"]

    # strict mode (budget 0) keeps the np.loadtxt fast path and its
    # seed-parity failure mode: the first bad row aborts with ValueError
    strict = CSVIterator()
    strict.set_param("filename", str(p))
    strict.set_param("input_shape", "1,1,4")
    strict.set_param("silent", "1")
    with pytest.raises(ValueError):
        strict.init()


def test_csv_comment_lines_are_not_records(tmp_path):
    """np.loadtxt parity (the pre-resilience reader): '#' comments are
    stripped, never parsed as records — and never quarantined."""
    p = tmp_path / "d.csv"
    p.write_text(
        "# generated by tooling\n"
        "0,1,2,3,4\n"
        "1,5,6,7,8  # trailing comment\n"
        "\n"
        "0,9,10,11,12\n"
    )
    from cxxnet_tpu.io.csv import CSVIterator

    it = CSVIterator()
    it.set_param("filename", str(p))
    it.set_param("input_shape", "1,1,4")
    it.set_param("silent", "1")
    it.init()  # strict mode: any miscounted comment would abort
    assert len(it._rows) == 3
    assert not os.path.exists(str(p) + ".quarantine")


@pytest.mark.chaos
def test_libsvm_corrupt_rows_quarantined(tmp_path):
    p = tmp_path / "d.libsvm"
    lines = [f"{i % 2} 0:{i}.0 2:1.5" for i in range(5)]
    lines[2] = "1 0:zap 2:1.5"  # bad value
    p.write_text("\n".join(lines) + "\n")
    from cxxnet_tpu.io.libsvm import LibSVMIterator

    it = LibSVMIterator()
    it.set_param("data_path", str(p))
    it.set_param("batch_size", "2")
    it.set_param("silent", "1")
    it.set_param("max_bad_records", "1")
    it.init()
    assert it.num_inst == 4
    # the corrupt row's partial features were rolled back: nnz = 2/row
    assert len(it._value) == 8
    offsets = [ln.split("\t")[0] for ln in
               open(str(p) + ".quarantine").read().splitlines()]
    assert offsets == ["line3"]


# ----------------------------------------------------------------------
# acceptance: training over data with < max_bad_records corrupt records
# completes through the same metric code path as a clean run
TRAIN_CONF = """
data = train
iter = csv
  filename = CSVFILE
  batch_size = 4
  input_shape = 1,1,4
  max_bad_records = 5
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[+1:a1] = relu:a1
layer[a1->out] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end

input_shape = 1,1,4
batch_size = 4
dev = cpu
eta = 0.1
num_round = 2
save_model = 0
eval_train = 1
metric = error
print_step = 0
"""


def _write_train_csv(path, corrupt):
    rng = np.random.RandomState(0)
    rows = []
    for i in range(12):
        feats = ",".join(f"{v:.4f}" for v in rng.rand(4))
        rows.append(f"{i % 2},{feats}")
    if corrupt:
        rows[3] = "1,garbage,in,the,row"
        rows[8] = "0,1.0"
    path.write_text("\n".join(rows) + "\n")


@pytest.mark.chaos
@pytest.mark.slow
def test_training_run_with_corrupt_records_matches_clean_code_path(tmp_path):
    from conftest import run_cli

    out = {}
    for tag, corrupt in (("clean", False), ("dirty", True)):
        csv_p = tmp_path / f"{tag}.csv"
        _write_train_csv(csv_p, corrupt)
        conf = tmp_path / f"{tag}.conf"
        conf.write_text(TRAIN_CONF.replace("CSVFILE", str(csv_p)))
        r = run_cli([str(conf)], str(tmp_path))
        assert r.returncode == 0, r.stderr + r.stdout
        out[tag] = r
    for tag in ("clean", "dirty"):
        # both runs reach the same per-round metric reporting
        assert "[1]\ttrain-error:" in out[tag].stderr, out[tag].stderr
        assert "[2]\ttrain-error:" in out[tag].stderr, out[tag].stderr
    # ...and the dirty run reported its skips
    assert "skipped bad record" in out["dirty"].stdout
    assert "2 bad record(s) skipped" in out["dirty"].stdout
    assert "skipped" not in out["clean"].stdout
