"""Data service (io/dataservice/): CXD1 wire, chunk cache, and the
server/client determinism contract — bitwise stream parity vs the
local chain, multi-tenant cache sharing, reconnect-resume across a
server restart, admission shed, and session teardown."""

import json
import socket
import urllib.request

import numpy as np
import pytest

from cxxnet_tpu.io.data import create_iterator
from cxxnet_tpu.io.dataservice import wire
from cxxnet_tpu.io.dataservice.cache import CachedBlock, ChunkCache
from cxxnet_tpu.io.dataservice.server import (DataServiceServer,
                                              dataset_fingerprint)
from cxxnet_tpu.io.mnist import write_idx_images, write_idx_labels


# ----------------------------------------------------------------------
# wire
def test_wire_json_roundtrip():
    for frame, kind, doc in [
        (wire.encode_open(32, 1, 4, 2), wire.OPEN,
         {"batch_size": 32, "rank": 1, "nworker": 4, "window": 2}),
        (wire.encode_opened(7, "cafe0123", 4), wire.OPENED,
         {"session": 7, "fingerprint": "cafe0123", "window": 4}),
        (wire.encode_err("overloaded", "full"), wire.ERR,
         {"reason": "overloaded", "detail": "full"}),
    ]:
        k, payload = wire.decode_kind(frame)
        assert k == kind
        assert wire.decode_json(payload) == doc


def test_wire_fixed_roundtrip():
    k, p = wire.decode_kind(wire.encode_get(3, 17))
    assert k == wire.GET and wire.decode_get(p) == (3, 17)
    k, p = wire.decode_kind(wire.encode_eoe(2, 50))
    assert k == wire.EOE and wire.decode_eoe(p) == (2, 50)
    k, p = wire.decode_kind(wire.encode_close())
    assert k == wire.CLOSE and len(p) == 0


@pytest.mark.parametrize("with_inst", [True, False])
def test_wire_batch_roundtrip(with_inst):
    rng = np.random.RandomState(0)
    data = rng.rand(4, 2, 2, 3).astype(np.float32)
    label = rng.rand(4, 2).astype(np.float32)
    inst = np.arange(4, dtype=np.uint32) if with_inst else None
    parts = wire.encode_batch(data, label, inst, 1, epoch=5, block=9,
                              cache_hit=True)
    body = b"".join(bytes(p) for p in parts)
    k, payload = wire.decode_kind(body)
    assert k == wire.BATCH
    ep, blk, hit, d, lab, i, padd = wire.decode_batch(payload)
    assert (ep, blk, hit, padd) == (5, 9, True, 1)
    assert np.array_equal(d, data) and d.dtype == np.float32
    assert np.array_equal(lab, label)
    if with_inst:
        assert np.array_equal(i, inst)
    else:
        assert i is None


def _batch_body():
    parts = wire.encode_batch(np.zeros((2, 3), np.float32),
                              np.zeros((2, 1), np.float32),
                              None, 0, 0, 0, False)
    return bytearray(b"".join(bytes(p) for p in parts))


@pytest.mark.parametrize("mutate,reason", [
    (lambda b: b"XXXX" + bytes(b[4:]), "bad_magic"),
    (lambda b: bytes(b[:4]) + b"\x63" + bytes(b[5:]), "bad_kind"),
    (lambda b: bytes(b[:-4]), "truncated_body"),
    (lambda b: bytes(b) + b"\x00\x00", "trailing_bytes"),
    (lambda b: bytes(b[:5]), "truncated_body"),
])
def test_wire_malformed_batch(mutate, reason):
    body = mutate(_batch_body())
    with pytest.raises(wire.WireError) as ei:
        k, payload = wire.decode_kind(body)
        assert k == wire.BATCH
        wire.decode_batch(payload)
    assert ei.value.reason == reason


def test_wire_bad_json():
    frame = wire._HDR.pack(wire.MAGIC, wire.OPEN) + b"not json"
    k, payload = wire.decode_kind(frame)
    with pytest.raises(wire.WireError) as ei:
        wire.decode_json(payload)
    assert ei.value.reason == "bad_json"


# ----------------------------------------------------------------------
# fingerprint + cache
def test_dataset_fingerprint(tmp_path):
    p = tmp_path / "d.bin"
    p.write_bytes(b"x" * 64)
    ent = [("iter", "mnist"), ("path_img", str(p))]
    fp = dataset_fingerprint(ent)
    assert fp == dataset_fingerprint(list(ent))  # stable
    assert fp != dataset_fingerprint(ent + [("shuffle", "1")])
    p.write_bytes(b"x" * 65)  # same conf, regenerated file
    assert fp != dataset_fingerprint(ent)


def _blk(nrows=4, ncol=8, seed=0):
    rng = np.random.RandomState(seed)
    return CachedBlock(rng.rand(nrows, ncol).astype(np.float32),
                       rng.rand(nrows, 1).astype(np.float32),
                       np.arange(nrows, dtype=np.uint32), 0)


def test_chunk_cache_lru_and_accounting():
    one = _blk().nbytes
    c = ChunkCache(max_bytes=3 * one)
    for i in range(3):
        c.put(("fp", 0, i), _blk(seed=i))
    assert len(c) == 3 and c.bytes == 3 * one
    assert c.get(("fp", 0, 0)) is not None       # 0 becomes MRU
    c.put(("fp", 0, 3), _blk(seed=3))            # evicts 1 (LRU)
    assert c.get(("fp", 0, 1)) is None
    assert c.get(("fp", 0, 0)) is not None
    st = c.stats()
    assert st["evictions"] == 1 and st["bytes"] == 3 * one
    assert st["hits"] == 2 and st["misses"] == 1
    assert 0 < st["hit_rate"] < 1


def test_chunk_cache_disabled_and_immutable():
    c = ChunkCache(max_bytes=0)
    c.put(("fp", 0, 0), _blk())
    assert c.get(("fp", 0, 0)) is None
    blk = _blk()
    with pytest.raises(ValueError):
        blk.data[0, 0] = 1.0  # cached rows are immutable


# ----------------------------------------------------------------------
# server + client integration
def make_dataset(tmp_path, n=96, seed=3):
    rng = np.random.RandomState(seed)
    imgs = rng.randint(0, 255, size=(n, 4, 4), dtype=np.uint8)
    labs = (imgs.reshape(n, -1).mean(axis=1) > 127).astype(np.uint8)
    pi, pl = str(tmp_path / "img.idx"), str(tmp_path / "lab.idx")
    write_idx_images(pi, imgs)
    write_idx_labels(pl, labs)
    sec = [("iter", "mnist"), ("path_img", pi), ("path_label", pl),
           ("shuffle", "1"), ("input_flat", "1")]
    glob = [("batch_size", "16"), ("silent", "1"), ("seed_data", "5")]
    return sec, glob


def make_server(sec, glob, **kw):
    kw.setdefault("max_sessions", 8)
    kw.setdefault("cache_bytes", 16 << 20)
    kw.setdefault("silent", True)
    srv = DataServiceServer(sec, glob, **kw)
    srv.start()
    return srv


def make_client(port, glob, **params):
    it = create_iterator([
        ("iter", "service"),
        ("data_service_addr", f"127.0.0.1:{port}"),
        ("data_service_retry_delay_s", "0.05"),
        ("watchdog_timeout_s", "20"),
    ] + [(k, str(v)) for k, v in params.items()])
    for n, v in glob:
        it.set_param(n, v)
    it.init()
    return it


def collect(it, epoch=None):
    it.before_first()
    if epoch is not None:
        it.set_param("augment_epoch", str(epoch))
    out = []
    while it.next():
        b = it.value()
        out.append((b.data.copy(), b.label.copy(), b.num_batch_padd))
    return out


def assert_streams_equal(a, b):
    assert len(a) == len(b)
    for (da, la, pa), (db, lb, pb) in zip(a, b):
        assert np.array_equal(da, db)
        assert np.array_equal(la, lb)
        assert pa == pb


def test_service_stream_parity_multi_epoch(tmp_path):
    sec, glob = make_dataset(tmp_path)
    srv = make_server(sec, glob)
    ref = create_iterator(sec)
    for n, v in glob:
        ref.set_param(n, v)
    ref.init()
    it = make_client(srv.port, glob)
    try:
        # epoch pinning out of order: the stream is addressed, so any
        # epoch is servable at any time, bitwise
        for epoch in (0, 2, 1, 2):
            assert_streams_equal(collect(ref, epoch), collect(it, epoch))
    finally:
        it.close()
        ref.close()
        srv.close()


def test_two_clients_share_cache_and_agree(tmp_path):
    sec, glob = make_dataset(tmp_path)
    srv = make_server(sec, glob)
    a = make_client(srv.port, glob)
    b = make_client(srv.port, glob)
    try:
        sa = collect(a, 0)
        sb = collect(b, 0)  # same epoch: all warm
        assert_streams_equal(sa, sb)
        st = srv.plant.cache.stats()
        assert st["hits"] >= len(sb)   # the second pass hit the cache
        assert st["hit_rate"] > 0
    finally:
        a.close()
        b.close()
        srv.close()


def test_block_shard_deal_matches_local_stream(tmp_path):
    """Two rank clients reassemble exactly the local global stream in
    dist_shard=block order: rank r's k-th block is global block
    k*nworker + r."""
    sec, glob = make_dataset(tmp_path)
    srv = make_server(sec, glob)
    ref = create_iterator(sec)
    for n, v in glob:
        ref.set_param(n, v)
    ref.init()
    r0 = make_client(srv.port, glob, dist_num_worker=2,
                     dist_worker_rank=0)
    r1 = make_client(srv.port, glob, dist_num_worker=2,
                     dist_worker_rank=1)
    try:
        local = collect(ref, 0)
        s0, s1 = collect(r0, 0), collect(r1, 0)
        assert len(s0) == len(s1) == len(local) // 2
        for k in range(len(s0)):
            assert np.array_equal(s0[k][0], local[2 * k][0])
            assert np.array_equal(s1[k][0], local[2 * k + 1][0])
    finally:
        r0.close()
        r1.close()
        ref.close()
        srv.close()


def test_reconnect_resumes_identical_stream(tmp_path):
    """Kill the server mid-epoch; a replacement on the same port serves
    the client's re-requested cursor bitwise — the consumer sees one
    uninterrupted, locally-identical stream."""
    sec, glob = make_dataset(tmp_path)
    ref = create_iterator(sec)
    for n, v in glob:
        ref.set_param(n, v)
    ref.init()
    local = collect(ref, 0)
    srv = make_server(sec, glob)
    port = srv.port
    it = make_client(port, glob)
    try:
        it.before_first()
        it.set_param("augment_epoch", "0")
        got = []
        for _ in range(2):
            assert it.next()
            b = it.value()
            got.append((b.data.copy(), b.label.copy(), b.num_batch_padd))
        srv.close()  # SIGKILL analog: every connection drops dead
        srv = make_server(sec, glob, port=port)
        while it.next():
            b = it.value()
            got.append((b.data.copy(), b.label.copy(), b.num_batch_padd))
        assert_streams_equal(got, local)
        assert it.reconnects >= 1
    finally:
        it.close()
        ref.close()
        srv.close()


def test_reconnect_refuses_changed_fingerprint(tmp_path):
    sec, glob = make_dataset(tmp_path)
    srv = make_server(sec, glob)
    port = srv.port
    it = make_client(port, glob)
    try:
        it.before_first()
        it.set_param("augment_epoch", "0")
        assert it.next()
        srv.close()
        # same port, DIFFERENT dataset (fresh paths — the fingerprint
        # keys on entries + file sizes): the client must refuse to
        # splice it into the run rather than resume
        alt = tmp_path / "alt"
        alt.mkdir()
        sec2, _ = make_dataset(alt, seed=11)
        srv = make_server(sec2, glob, port=port)
        with pytest.raises(RuntimeError, match="fingerprint changed"):
            while it.next():
                pass
    finally:
        it.close()
        srv.close()


def _raw_open(port, batch_size=16, rank=0, nworker=1, window=2):
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    wire.write_frame(s, wire.encode_open(batch_size, rank, nworker,
                                         window))
    body = wire.read_frame(s)
    kind, payload = wire.decode_kind(body)
    return s, kind, wire.decode_json(payload)


def test_admission_shed_and_batch_size_gate(tmp_path):
    sec, glob = make_dataset(tmp_path)
    srv = make_server(sec, glob, max_sessions=1)
    try:
        s1, kind, doc = _raw_open(srv.port)
        assert kind == wire.OPENED
        assert doc["fingerprint"] == srv.plant.fingerprint
        # the max_sessions+1-th OPEN is shed 429-style
        s2, kind2, doc2 = _raw_open(srv.port)
        assert kind2 == wire.ERR and doc2["reason"] == "overloaded"
        s2.close()
        from cxxnet_tpu.obs.registry import registry
        shed = registry().counter("dataservice_shed_total", "",
                                  labelnames=("reason",))
        assert shed.labels(reason="overloaded").value >= 1
        s1.close()
        # wrong block size is a refusal, not a silently different deal
        _wait_sessions(srv, 0)
        s3, kind3, doc3 = _raw_open(srv.port, batch_size=8)
        assert kind3 == wire.ERR
        assert doc3["reason"] == "batch_size_mismatch"
        s3.close()
    finally:
        srv.close()


def _wait_sessions(srv, n, timeout=5.0):
    import time
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if len(srv._sessions) == n:
            return
        time.sleep(0.02)
    raise AssertionError(
        f"server still has {len(srv._sessions)} sessions, want {n}")


def test_close_tears_down_session_and_threads(tmp_path):
    import threading
    sec, glob = make_dataset(tmp_path)
    srv = make_server(sec, glob)
    before = set(threading.enumerate())
    it = make_client(srv.port, glob)
    try:
        it.before_first()
        it.set_param("augment_epoch", "0")
        assert it.next()
        _wait_sessions(srv, 1)
    finally:
        it.close()
    _wait_sessions(srv, 0)  # EOF teardown reached the server
    from cxxnet_tpu.obs.registry import registry
    assert registry().gauge("dataservice_sessions", "").get() == 0.0
    leaked = [t for t in set(threading.enumerate()) - before
              if t.is_alive() and t.name == "dataservice-client"]
    assert not leaked  # the client worker joined
    it.close()  # idempotent
    srv.close()
    srv.close()  # idempotent


def test_health_and_stats_planes(tmp_path):
    sec, glob = make_dataset(tmp_path)
    srv = make_server(sec, glob)
    it = make_client(srv.port, glob)
    try:
        collect(it, 0)
        h = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.http_port}/healthz",
            timeout=5).read())
        assert h["status"] == "ok"
        st = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.http_port}/statsz",
            timeout=5).read())
        assert st["fingerprint"] == srv.plant.fingerprint
        assert st["blocks_produced"] == 6
        assert st["epoch_lens"] == {"0": 6}
        assert st["cache"]["misses"] >= 6
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.http_port}/metricsz",
            timeout=5).read().decode()
        assert "dataservice_batches_total" in text
        assert "dataservice_cache_bytes" in text
    finally:
        it.close()
        srv.close()
