"""Torch adapter plugin: the caffe-adapter parity harness (SURVEY §2.2).

Checks the adapter end to end and uses it the way the reference used its
caffe layer — as the trusted slave in a pairtest against the native
implementation.
"""

import numpy as np
import pytest

jaxlib = pytest.importorskip("jax")
torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from cxxnet_tpu.layers import create_layer  # noqa: E402


def test_torch_adapter_linear_forward_and_grad(rng):
    lay = create_layer("torch")
    lay.set_param("torch_op", "torch.nn.Linear(8, 4)")
    (out_shape,) = lay.infer_shape([(2, 8)])
    assert out_shape == (2, 4)
    params = lay.init_params(jax.random.PRNGKey(0), [(2, 8)])
    assert set(params) == {"blob0", "blob1"}

    x = jnp.asarray(rng.randn(2, 8).astype(np.float32))
    (y,) = lay.apply(params, [x])
    # golden: same math in numpy with the extracted blobs
    want = np.asarray(x) @ np.asarray(params["blob0"]).T + np.asarray(
        params["blob1"]
    )
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-5, atol=1e-5)

    # gradients flow to input and foreign params through torch autograd
    def loss(p, x):
        (y,) = lay.apply(p, [x])
        return jnp.sum(y**2)

    gp, gx = jax.grad(loss, argnums=(0, 1))(params, x)
    gw = np.asarray(gp["blob0"])
    want_gy = 2 * want
    want_gw = want_gy.T @ np.asarray(x)
    np.testing.assert_allclose(gw, want_gw, rtol=1e-4, atol=1e-4)
    assert np.abs(np.asarray(gx)).max() > 0


def test_torch_adapter_conv_nhwc_marshalling(rng):
    lay = create_layer("torch")
    lay.set_param("torch_op", "torch.nn.Conv2d(3, 8, 3, padding=1)")
    (out_shape,) = lay.infer_shape([(2, 5, 5, 3)])
    assert out_shape == (2, 5, 5, 8)  # NHWC preserved
    params = lay.init_params(jax.random.PRNGKey(0), [(2, 5, 5, 3)])
    x = jnp.asarray(rng.randn(2, 5, 5, 3).astype(np.float32))
    (y,) = lay.apply(params, [x])
    assert y.shape == (2, 5, 5, 8)


def test_pairtest_native_vs_torch(rng):
    """The reference's raison d'être for the adapter: differential test of
    the native fullc layer against the torch implementation."""
    native = create_layer("fullc")
    native.set_param("nhidden", "4")
    foreign = create_layer("torch")
    foreign.set_param("torch_op", "torch.nn.Linear(8, 4, bias=True)")

    x = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    native.infer_shape([(16, 8)])
    foreign.infer_shape([(16, 8)])
    p_n = native.init_params(jax.random.PRNGKey(1), [(16, 8)])
    # sync weights: native wmat (nout, nin) == torch Linear weight layout
    p_f = {"blob0": p_n["wmat"], "blob1": p_n["bias"]}
    (y_n,) = native.apply(p_n, [x])
    (y_f,) = foreign.apply(p_f, [x])
    np.testing.assert_allclose(
        np.asarray(y_n), np.asarray(y_f), rtol=1e-5, atol=1e-5
    )


def test_torch_op_rejects_non_whitelisted_expressions():
    """torch_op is untrusted config input: anything that is not a literal
    torch.nn.* constructor call must be rejected (never eval'd)."""
    from cxxnet_tpu.plugin.torch_adapter import _build_torch_expr

    bad = [
        "__import__('os').system('true')",
        "torch.load('/etc/passwd')",                      # not torch.nn
        "torch.nn.Linear.__init__.__globals__",           # not a call
        "torch.nn.Linear(8, 4).__class__",                # attribute escape
        "torch.nn.modules.linear.Linear.mro()[1]",        # subscript
        "torch.nn.Linear(open('/etc/passwd'))",           # non-literal arg
        "torch.nn._reduction.legacy_get_string(1, 2)",    # private path
        "(lambda: 1)()",
    ]
    for expr in bad:
        with pytest.raises((ValueError, SyntaxError)):
            _build_torch_expr(expr)


def test_torch_op_accepts_nested_and_literal_forms():
    from cxxnet_tpu.plugin.torch_adapter import _build_torch_expr

    m = _build_torch_expr(
        "torch.nn.Sequential(torch.nn.Linear(8, 4, bias=False), "
        "torch.nn.Hardtanh(-1.0, 1.0))"
    )
    assert isinstance(m, torch.nn.Sequential)
    m2 = _build_torch_expr("torch.nn.AvgPool2d((2, 2), stride=2)")
    assert isinstance(m2, torch.nn.AvgPool2d)
