"""GPipe pipeline parallelism vs sequential execution (8-dev CPU mesh)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from cxxnet_tpu.ops.pipeline import pipeline_apply
from cxxnet_tpu.parallel import make_mesh


def block_fn(p, x):
    return jax.nn.relu(x @ p["w"] + p["b"])


def make_stack(rng, l=8, d=16):
    return {
        "w": jnp.asarray(rng.randn(l, d, d).astype(np.float32) * 0.3),
        "b": jnp.asarray(rng.randn(l, d).astype(np.float32) * 0.1),
    }


def sequential(params, x):
    for i in range(params["w"].shape[0]):
        x = block_fn({"w": params["w"][i], "b": params["b"][i]}, x)
    return x


@pytest.mark.parametrize("stages,micro", [(4, 4), (8, 2), (2, 8)])
def test_pipeline_matches_sequential(rng, stages, micro):
    plan = make_mesh("cpu:0-7", model_parallel=stages)
    params = make_stack(rng)
    x = jnp.asarray(rng.randn(16, 16).astype(np.float32))
    want = sequential(params, x)
    got = pipeline_apply(
        block_fn, params, x, plan.mesh, n_microbatch=micro,
        stage_axis="model",
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_pipeline_gradients_match(rng):
    plan = make_mesh("cpu:0-7", model_parallel=4)
    params = make_stack(rng, l=4)
    x = jnp.asarray(rng.randn(8, 16).astype(np.float32))

    def loss_pipe(p):
        return jnp.sum(
            pipeline_apply(block_fn, p, x, plan.mesh, n_microbatch=2) ** 2
        )

    def loss_seq(p):
        return jnp.sum(sequential(p, x) ** 2)

    gp = jax.grad(loss_pipe)(params)
    gs = jax.grad(loss_seq)(params)
    for k in gs:
        np.testing.assert_allclose(
            np.asarray(gp[k]), np.asarray(gs[k]), rtol=1e-4, atol=1e-5
        )


def test_pipeline_validates_divisibility(rng):
    plan = make_mesh("cpu:0-7", model_parallel=4)
    params = make_stack(rng, l=6)  # 6 % 4 != 0
    x = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    with pytest.raises(ValueError):
        pipeline_apply(block_fn, params, x, plan.mesh, n_microbatch=2)
    params = make_stack(rng, l=8)
    with pytest.raises(ValueError):
        pipeline_apply(block_fn, params, x, plan.mesh, n_microbatch=3)


def test_pipe_mlp_layer_config_e2e(rng):
    """pipeline_parallel=1 from config == unsharded run, params sharded."""
    from jax.sharding import PartitionSpec as P

    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer

    cfg = [
        ("batch_size", "16"),
        ("input_shape", "1,1,16"),
        ("seed", "5"),
        ("eta", "0.05"),
        ("netconfig", "start"),
        ("layer[0->1]", "pipe_mlp:pp"),
        ("nblock", "4"),
        ("n_microbatch", "4"),
        ("pipeline_parallel", "{pp}"),
        ("init_sigma", "0.2"),
        ("layer[1->2]", "fullc:fc"),
        ("nhidden", "4"),
        ("layer[2->2]", "softmax"),
        ("netconfig", "end"),
    ]

    def train(dev, pp, mp):
        tr = NetTrainer()
        tr.set_params(
            [("dev", dev)]
            + [(k, v.format(pp=pp) if k == "pipeline_parallel" else v)
               for k, v in cfg]
        )
        if mp != 1:
            tr.set_param("model_parallel", str(mp))
        tr.init_model()
        r = np.random.RandomState(2)
        for _ in range(4):
            x = r.randn(16, 16).astype(np.float32)
            y = r.randint(0, 4, (16, 1)).astype(np.float32)
            tr.update(DataBatch(data=x, label=y))
        return tr

    t1 = train("cpu", "0", 1)
    tpp = train("cpu:0-7", "1", 4)  # 2 data x 4 pipeline stages
    w = tpp.params["l0_pp"]["wmat"]  # (4, 16, 16) stage-sharded
    assert w.sharding.spec == P("model", None, None)
    for key in t1.params:
        for tag in t1.params[key]:
            np.testing.assert_allclose(
                np.asarray(t1.params[key][tag]),
                np.asarray(tpp.params[key][tag]),
                rtol=3e-4, atol=3e-5,
                err_msg=f"{key}/{tag} diverged under pipeline parallelism",
            )


def test_pipe_transformer_parity_and_sharding(rng):
    """transformer_conf(pipeline_parallel=k) trains to IDENTICAL params as
    the k=1 (plain scanned stack) run on the 8-dev mesh — the VERDICT r1
    'promote PP from toy to capability' fixture: real pre-LN transformer
    blocks (MHA + FFN + residuals), stacked params, gpipe schedule."""
    from jax.sharding import PartitionSpec as P

    from cxxnet_tpu import config as C
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.models import transformer_conf
    from cxxnet_tpu.nnet.trainer import NetTrainer

    def train(pp, dev):
        text = transformer_conf(
            batch_size=16, seq_len=8, dim=16, nhead=2, nlayer=4,
            num_class=4, dev=dev, compute_dtype="float32",
            pipeline_parallel=pp, n_microbatch=4,
        )
        tr = NetTrainer()
        tr.set_params(C.parse_pairs(text))
        tr.init_model()
        r = np.random.RandomState(3)
        for _ in range(3):
            x = r.randn(16, 8, 16).astype(np.float32)
            y = r.randint(0, 4, (16, 1)).astype(np.float32)
            tr.update(DataBatch(data=x, label=y))
        return tr

    t1 = train(1, "cpu")
    tpp = train(4, "cpu:0-7")  # 2 data x 4 pipeline stages
    w = tpp.params["l0_blocks"]["wqkv"]  # (4, 48, 16) stage-sharded
    assert w.sharding.spec == P("model", None, None)
    for key in t1.params:
        for tag in t1.params[key]:
            np.testing.assert_allclose(
                np.asarray(t1.params[key][tag]),
                np.asarray(tpp.params[key][tag]),
                rtol=3e-4, atol=3e-5,
                err_msg=f"{key}/{tag} diverged under pipeline parallelism",
            )


def test_pipe_transformer_block_matches_reference_impl(rng):
    """One pipe_transformer block == hand-computed pre-LN block math."""
    from cxxnet_tpu.layers import create_layer
    from cxxnet_tpu.ops.attention import mha

    lay = create_layer("pipe_transformer")
    lay.nblock = 1
    lay.nhead = 2
    key = jax.random.PRNGKey(0)
    x = jnp.asarray(rng.randn(2, 8, 16).astype(np.float32))
    params = lay.init_params(key, [(2, 8, 16)])
    (y,) = lay.apply(params, [x])

    def ln(v, w, b):
        mu = v.mean(-1, keepdims=True)
        var = ((v - mu) ** 2).mean(-1, keepdims=True)
        return (v - mu) / np.sqrt(var + 1e-6) * w + b

    p = {k: np.asarray(v)[0] for k, v in params.items()}
    xn = np.asarray(x)
    h = ln(xn, p["ln1_w"], p["ln1_b"])
    qkv = h @ p["wqkv"].T + p["bqkv"]
    qkv = qkv.reshape(2, 8, 3, 2, 8)
    o = np.asarray(
        mha(jnp.asarray(qkv[:, :, 0]), jnp.asarray(qkv[:, :, 1]),
            jnp.asarray(qkv[:, :, 2]))
    )
    x1 = xn + o.reshape(2, 8, 16) @ p["wproj"].T + p["bproj"]
    h2 = ln(x1, p["ln2_w"], p["ln2_b"])
    f = (np.asarray(jax.nn.gelu(jnp.asarray(h2 @ p["wff1"].T + p["bff1"])))
         @ p["wff2"].T + p["bff2"])
    np.testing.assert_allclose(np.asarray(y), x1 + f, rtol=1e-4, atol=1e-5)


def test_pipe_transformer_ln_params_stay_f32_under_bf16():
    """Under compute_dtype=bfloat16 the stacked LN scales/biases must
    reach the block math in f32 (Layer.f32_tags exemption), matching the
    standalone LayerNormLayer's mixed-precision policy."""
    from cxxnet_tpu import config as C
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.models import transformer_conf

    text = transformer_conf(
        batch_size=8, seq_len=8, dim=16, nhead=2, nlayer=2, num_class=4,
        dev="cpu", compute_dtype="bfloat16", pipeline_parallel=1,
    )
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(text))
    tr.init_model()
    cast = tr.net._cast_params(tr.params)
    blocks = cast["l0_blocks"]
    for tag in ("ln1_w", "ln1_b", "ln2_w", "ln2_b"):
        assert blocks[tag].dtype == jnp.float32, tag
    for tag in ("wqkv", "wproj", "wff1", "wff2"):
        assert blocks[tag].dtype == jnp.bfloat16, tag
