"""GPipe pipeline parallelism vs sequential execution (8-dev CPU mesh)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from cxxnet_tpu.ops.pipeline import pipeline_apply
from cxxnet_tpu.parallel import make_mesh


def block_fn(p, x):
    return jax.nn.relu(x @ p["w"] + p["b"])


def make_stack(rng, l=8, d=16):
    return {
        "w": jnp.asarray(rng.randn(l, d, d).astype(np.float32) * 0.3),
        "b": jnp.asarray(rng.randn(l, d).astype(np.float32) * 0.1),
    }


def sequential(params, x):
    for i in range(params["w"].shape[0]):
        x = block_fn({"w": params["w"][i], "b": params["b"][i]}, x)
    return x


@pytest.mark.parametrize("stages,micro", [(4, 4), (8, 2), (2, 8)])
def test_pipeline_matches_sequential(rng, stages, micro):
    plan = make_mesh("cpu:0-7", model_parallel=stages)
    params = make_stack(rng)
    x = jnp.asarray(rng.randn(16, 16).astype(np.float32))
    want = sequential(params, x)
    got = pipeline_apply(
        block_fn, params, x, plan.mesh, n_microbatch=micro,
        stage_axis="model",
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_pipeline_gradients_match(rng):
    plan = make_mesh("cpu:0-7", model_parallel=4)
    params = make_stack(rng, l=4)
    x = jnp.asarray(rng.randn(8, 16).astype(np.float32))

    def loss_pipe(p):
        return jnp.sum(
            pipeline_apply(block_fn, p, x, plan.mesh, n_microbatch=2) ** 2
        )

    def loss_seq(p):
        return jnp.sum(sequential(p, x) ** 2)

    gp = jax.grad(loss_pipe)(params)
    gs = jax.grad(loss_seq)(params)
    for k in gs:
        np.testing.assert_allclose(
            np.asarray(gp[k]), np.asarray(gs[k]), rtol=1e-4, atol=1e-5
        )


def test_pipeline_validates_divisibility(rng):
    plan = make_mesh("cpu:0-7", model_parallel=4)
    params = make_stack(rng, l=6)  # 6 % 4 != 0
    x = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    with pytest.raises(ValueError):
        pipeline_apply(block_fn, params, x, plan.mesh, n_microbatch=2)
    params = make_stack(rng, l=8)
    with pytest.raises(ValueError):
        pipeline_apply(block_fn, params, x, plan.mesh, n_microbatch=3)


def test_pipe_mlp_layer_config_e2e(rng):
    """pipeline_parallel=1 from config == unsharded run, params sharded."""
    from jax.sharding import PartitionSpec as P

    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer

    cfg = [
        ("batch_size", "16"),
        ("input_shape", "1,1,16"),
        ("seed", "5"),
        ("eta", "0.05"),
        ("netconfig", "start"),
        ("layer[0->1]", "pipe_mlp:pp"),
        ("nblock", "4"),
        ("n_microbatch", "4"),
        ("pipeline_parallel", "{pp}"),
        ("init_sigma", "0.2"),
        ("layer[1->2]", "fullc:fc"),
        ("nhidden", "4"),
        ("layer[2->2]", "softmax"),
        ("netconfig", "end"),
    ]

    def train(dev, pp, mp):
        tr = NetTrainer()
        tr.set_params(
            [("dev", dev)]
            + [(k, v.format(pp=pp) if k == "pipeline_parallel" else v)
               for k, v in cfg]
        )
        if mp != 1:
            tr.set_param("model_parallel", str(mp))
        tr.init_model()
        r = np.random.RandomState(2)
        for _ in range(4):
            x = r.randn(16, 16).astype(np.float32)
            y = r.randint(0, 4, (16, 1)).astype(np.float32)
            tr.update(DataBatch(data=x, label=y))
        return tr

    t1 = train("cpu", "0", 1)
    tpp = train("cpu:0-7", "1", 4)  # 2 data x 4 pipeline stages
    w = tpp.params["l0_pp"]["wmat"]  # (4, 16, 16) stage-sharded
    assert w.sharding.spec == P("model", None, None)
    for key in t1.params:
        for tag in t1.params[key]:
            np.testing.assert_allclose(
                np.asarray(t1.params[key][tag]),
                np.asarray(tpp.params[key][tag]),
                rtol=3e-4, atol=3e-5,
                err_msg=f"{key}/{tag} diverged under pipeline parallelism",
            )
