"""Serving-fleet tests: supervision, admission control, routing, canary.

Everything here drives the REAL fleet/router machinery
(``serve/fleet.py`` + ``serve/router.py``) against the stdlib stub
replica (``serve/stub.py``) — subprocesses that start in ~100 ms, so
supervision, failover, rolling reload and the canary lifecycle are
exercised end to end without a JAX import per replica.  The heavyweight
variant (real ``task=serve`` CLI replicas, real checkpoints) is the
FLEET=1 tier-1 lane: ``tools/fleet_smoke.py``.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from cxxnet_tpu.serve.fleet import (
    FleetOptions,
    ServingFleet,
    fleet_metrics,
    stub_spawn_fn,
)
from cxxnet_tpu.utils import checkpoint as ckpt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_alerts():
    """Canary tests arm a global alert rule + evaluator; no test leaks
    it into the next one."""
    yield
    from cxxnet_tpu.obs import alerts as obs_alerts

    obs_alerts.reset()


def make_opts(**kw):
    base = dict(
        replicas=2, probe_period_s=0.1, probe_timeout_s=0.5,
        slow_probes=3, start_timeout_s=60.0, restart_backoff_s=0.2,
        restart_backoff_max_s=0.5, replica_inflight=16,
        dispatch_retries=2, dispatch_timeout_s=5.0)
    base.update(kw)
    return FleetOptions(**base)


def start_stub_fleet(opts, per_replica=None, extra=(), model_dir=None):
    """ServingFleet over stub replicas, started and ready (no HTTP
    front door bound — tests drive ``fleet.router.route`` directly)."""
    fleet = ServingFleet(opts, spawn_fn=stub_spawn_fn(
        extra=extra, per_replica=per_replica), model_dir=model_dir)
    fleet.supervisor.start()
    if not fleet.supervisor.wait_ready(timeout_s=60.0):
        snaps = [r.snapshot() for r in fleet.supervisor.replicas]
        fleet.close(drain_timeout_s=0.0)
        raise AssertionError(f"stub fleet never became ready: {snaps}")
    return fleet


# ----------------------------------------------------------------------
# config surface
def test_fleet_options_from_cfg():
    o = FleetOptions.from_cfg([
        ("replicas", "3"), ("fleet_probe_period_s", "0.5"),
        ("fleet_slow_probes", "5"), ("fleet_replica_inflight", "8"),
        ("fleet_batch_shed_ratio", "0.75"), ("canary", "int8"),
        ("canary_replicas", "1"), ("canary_slice", "0.2"),
        ("canary_min_agreement", "0.95"),
    ])
    assert (o.replicas, o.slow_probes, o.replica_inflight) == (3, 5, 8)
    assert o.batch_shed_ratio == 0.75
    assert o.canary == "int8" and o.canary_slice == 0.2

    # "off" spellings disarm the canary
    assert FleetOptions.from_cfg([("canary", "0")]).canary == ""
    assert FleetOptions.from_cfg([("canary", "off")]).canary == ""

    with pytest.raises(ValueError, match="replicas must be"):
        FleetOptions.from_cfg([("replicas", "0")])
    with pytest.raises(ValueError, match="batch_shed_ratio"):
        FleetOptions.from_cfg([("fleet_batch_shed_ratio", "0")])
    with pytest.raises(ValueError, match="at least one baseline"):
        FleetOptions.from_cfg([
            ("replicas", "2"), ("canary", "int8"),
            ("canary_replicas", "2")])
    with pytest.raises(ValueError, match="canary_slice"):
        FleetOptions.from_cfg([
            ("replicas", "3"), ("canary", "int8"),
            ("canary_slice", "1.5")])


def test_cli_spawn_fn_override_passthrough(monkeypatch):
    """Replica children inherit the fleet's CLI overrides: only the
    fleet-controlling keys are pinned.  A `quant=` override passes
    through to every child when no canary is armed (a fleet launched
    with quant=int8 must not silently serve f32); with a canary armed,
    the canary controller owns per-role precision instead."""
    import subprocess

    from cxxnet_tpu.serve import fleet as fleet_mod

    captured = []
    monkeypatch.setattr(
        subprocess, "Popen",
        lambda cmd, **kw: captured.append(cmd) or object())
    overrides = ["quant=int8", "alert=slow:m:>:1", "replicas=5",
                 "serve_port=1234", "batch_timeout_ms=1"]

    spawn = fleet_mod.cli_spawn_fn("net.conf", overrides,
                                   host="127.0.0.1",
                                   opts=make_opts(replicas=2))
    spawn(fleet_mod.Replica(0, 7001))
    cmd = captured[-1]
    assert "quant=int8" in cmd and "alert=slow:m:>:1" in cmd
    assert "batch_timeout_ms=1" in cmd
    # fleet-controlling keys pinned: single-engine child on ITS port
    assert "replicas=1" in cmd and "replicas=5" not in cmd
    assert "serve_port=7001" in cmd and "serve_port=1234" not in cmd

    canary_opts = make_opts(replicas=3, canary="int8",
                            canary_replicas=1)
    spawn = fleet_mod.cli_spawn_fn("net.conf", overrides,
                                   host="127.0.0.1", opts=canary_opts)
    spawn(fleet_mod.Replica(0, 7002, role="serve"))
    base_cmd = captured[-1]
    spawn(fleet_mod.Replica(2, 7003, role="canary"))
    canary_cmd = captured[-1]
    # per-role precision: baseline pinned f32, canary quantized — the
    # user's quant= override yields to the comparison legs
    assert "quant=0" in base_cmd and "quant=int8" not in base_cmd
    assert "quant=int8" in canary_cmd
    assert "alert=slow:m:>:1" in canary_cmd  # alerts still pass through


# ----------------------------------------------------------------------
# admission control
def test_admission_priority_ordering_unit():
    """The shed order, deterministically: batch 429s first (above the
    shed ratio), interactive holds until the full capacity bound, and
    capacity scales with replicas in rotation.  admit() is atomic —
    every None return RESERVES a slot (check and reservation under one
    lock), so concurrent arrivals can never overshoot the bound."""
    opts = make_opts(replica_inflight=10, batch_shed_ratio=0.5)
    fleet = ServingFleet(opts, spawn_fn=None)  # external mode: no procs
    try:
        sup = fleet.supervisor
        r0 = sup.add_replica()
        r0.state = "healthy"
        router = fleet.router
        assert router.capacity() == 10

        for _ in range(5):  # admit to the shed ratio: 5/10 in flight
            assert router.admit("interactive") is None
        assert router.admit("batch") is not None      # batch sheds...
        assert router.admit("interactive") is None    # ...interactive holds

        for _ in range(4):  # fill to capacity: 10/10
            assert router.admit("interactive") is None
        assert "at capacity" in router.admit("interactive")
        assert router.admit("batch") is not None
        assert router.stats.inflight == 10  # sheds reserved nothing

        # capacity shrinks/grows with the rotation: a second healthy
        # replica doubles the bound, so 10 in flight admits again
        r1 = sup.add_replica()
        r1.state = "healthy"
        assert router.capacity() == 20
        assert router.admit("interactive") is None
        assert router.admit("batch") is not None      # 11/20 >= 0.5 still
        for _ in range(11):
            router.stats.leave()
        assert router.admit("batch") is None          # 0/20: pressure gone
        router.stats.leave()
        # arrivals (shed included) and sheds both accounted
        assert router.stats.requests["interactive"] == 12
        assert router.stats.requests["batch"] == 4
        assert router.stats.shed["batch"] == 3
        assert router.stats.shed["interactive"] == 1
    finally:
        fleet.close(drain_timeout_s=0.0)


def test_admission_batch_sheds_first_saturated():
    """End to end under a genuinely saturated queue: slow replicas hold
    the one capacity slot, a batch arrival 429s while an interactive
    arrival at the same occupancy is still served."""
    opts = make_opts(replicas=2, replica_inflight=1,
                     batch_shed_ratio=0.5, dispatch_timeout_s=10.0)
    fleet = start_stub_fleet(opts, extra=("--delay-ms", "600"))
    try:
        results = {}

        def bg(name, priority):
            results[name] = fleet.router.route(
                "/predict", {"data": [[0.1] * 4]}, priority=priority)

        t1 = threading.Thread(target=bg, args=("first", "interactive"))
        t1.start()
        time.sleep(0.2)  # first request is now in flight (1/2 slots)
        status_batch, body_batch = fleet.router.route(
            "/predict", {"data": [[0.1] * 4]}, priority="batch")
        assert status_batch == 429, body_batch
        assert "batch shed" in body_batch["error"]
        t2 = threading.Thread(target=bg, args=("second", "interactive"))
        t2.start()
        t1.join(timeout=30)
        t2.join(timeout=30)
        assert results["first"][0] == 200
        assert results["second"][0] == 200  # same occupancy, admitted
        assert fleet.router.stats.shed["batch"] == 1
        assert fleet.router.stats.shed["interactive"] == 0
    finally:
        fleet.close(drain_timeout_s=0.0)


# ----------------------------------------------------------------------
# deadline budget
def test_deadline_budget_split_route_and_execute():
    opts = make_opts(replicas=1, dispatch_retries=0)
    fleet = start_stub_fleet(opts, extra=("--delay-ms", "100"))
    try:
        # 1. the replica sees only the REMAINING budget: the stub echoes
        # the forwarded deadline_ms, which must be strictly below what
        # the client sent (routing drew from the same budget)
        status, body = fleet.router.route(
            "/predict", {"data": [[0.1] * 4], "deadline_ms": 10000})
        assert status == 200
        assert 0 < body["deadline_ms"] < 10000

        # 2. execute share exhausted: the replica's own deadline check
        # 504s (the stub's delay exceeds the remaining budget) and the
        # router relays it — not a retry, not a 500
        status, body = fleet.router.route(
            "/predict", {"data": [[0.1] * 4], "deadline_ms": 50})
        assert status == 504, body

        # 3. route share exhausted: a budget too small to ever reach a
        # replica 504s locally, before any dispatch
        dispatched_before = fleet.supervisor.replicas[0].dispatched
        status, body = fleet.router.route(
            "/predict", {"data": [[0.1] * 4], "deadline_ms": 1e-4})
        assert status == 504
        assert "before a replica" in body["error"]

        # a non-numeric deadline is a client error (400), matching the
        # single-engine server — never a 500
        status, body = fleet.router.route(
            "/predict", {"data": [[0.1] * 4], "deadline_ms": "abc"})
        assert status == 400 and "deadline_ms" in body["error"]
        assert fleet.supervisor.replicas[0].dispatched == dispatched_before
        assert fleet.router.stats.expired == 1
    finally:
        fleet.close(drain_timeout_s=0.0)


# ----------------------------------------------------------------------
# the k-of-N availability invariant
def test_kill_one_of_three_zero_nonshed_failures():
    """SIGKILL 1 of 3 replicas under sustained concurrent load: every
    request still succeeds (failover + ejection), the fleet /healthz
    degrades while capacity is down, and the supervisor restarts the
    dead replica within its backoff budget."""
    opts = make_opts(replicas=3, probe_period_s=0.1, slow_probes=2,
                     probe_timeout_s=0.4, restart_backoff_s=0.2)
    fleet = start_stub_fleet(opts)
    try:
        statuses = []
        stop = threading.Event()
        lock = threading.Lock()

        def loader():
            while not stop.is_set():
                s, body = fleet.router.route(
                    "/predict", {"data": [[0.2] * 4]})
                with lock:
                    statuses.append((s, body if s != 200 else None))
                time.sleep(0.01)

        loaders = [threading.Thread(target=loader) for _ in range(4)]
        for t in loaders:
            t.start()
        time.sleep(0.5)

        victim = fleet.supervisor.replicas[1]
        victim.proc.kill()  # SIGKILL, mid-load
        degraded_seen = False
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            h = fleet.healthz()
            if h["status"] != "ok":
                degraded_seen = True
            if (degraded_seen and victim.restarts >= 1
                    and victim.state == "healthy"):
                break
            time.sleep(0.05)
        time.sleep(0.3)  # keep load on the restored rotation briefly
        stop.set()
        for t in loaders:
            t.join(timeout=30)

        assert degraded_seen  # the front door reported the lost capacity
        assert victim.restarts >= 1 and victim.state == "healthy"
        assert fleet.supervisor.last_restart_wall_s > 0
        bad = [(s, b) for s, b in statuses if s != 200]
        assert not bad, f"{len(bad)} non-200 of {len(statuses)}: {bad[:5]}"
        assert len(statuses) > 50  # the load was real
    finally:
        fleet.close(drain_timeout_s=0.0)


# ----------------------------------------------------------------------
# integrity quarantine (eject WITHOUT killing, readmit on clean canary)
def test_integrity_quarantine_ejects_without_kill_then_readmits():
    """A replica whose golden canary fails (healthz reason
    ``integrity_failed``) must leave the rotation but keep its process:
    a restart would land on the same possibly-bad device, and the
    still-running canary is what readmits it after a clean score."""
    from cxxnet_tpu.obs import events as obs_events

    opts = make_opts(replicas=3, probe_period_s=0.1)
    fleet = start_stub_fleet(opts)
    try:
        victim = fleet.supervisor.replicas[1]
        pid_before = victim.pid
        restarts_before = victim.restarts

        def stub_post(path, obj):
            req = urllib.request.Request(
                f"http://127.0.0.1:{victim.port}{path}",
                data=json.dumps(obj).encode("utf-8"),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=10) as r:
                return json.loads(r.read().decode("utf-8"))

        # 1. degrade the replica's canary -> supervisor quarantines it
        assert stub_post("/integrity", {"failed": True})["failed"]
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            fleet.supervisor.probe_once()
            if victim.state == "quarantined":
                break
            time.sleep(0.05)
        assert victim.state == "quarantined"
        assert victim not in fleet.supervisor.rotation()
        assert "integrity_failed" in victim.reasons
        # the fleet front door stays up on the two clean replicas
        s, body = fleet.router.route("/predict", {"data": [[0.2] * 4]})
        assert s == 200, body
        # ejected, NOT killed: same process, no restart, still answering
        assert victim.pid == pid_before
        assert victim.restarts == restarts_before
        assert victim.proc.poll() is None
        assert [e for e in obs_events.recent(
            200, kind="fleet.replica_quarantined")
            if e.get("replica") == victim.idx]

        # 2. canary comes back clean -> readmitted, same process
        assert not stub_post("/integrity", {"failed": False})["failed"]
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            fleet.supervisor.probe_once()
            if victim.state == "healthy":
                break
            time.sleep(0.05)
        assert victim.state == "healthy"
        assert victim in fleet.supervisor.rotation()
        assert victim.pid == pid_before
        assert victim.restarts == restarts_before
        assert [e for e in obs_events.recent(
            200, kind="fleet.replica_readmitted")
            if e.get("replica") == victim.idx]
    finally:
        fleet.close(drain_timeout_s=0.0)


# ----------------------------------------------------------------------
# rolling reload
def test_rolling_reload_walks_rotation(tmp_path):
    round_file = tmp_path / "round.txt"
    round_file.write_text("1")
    opts = make_opts(replicas=2)
    fleet = start_stub_fleet(
        opts, extra=("--round-file", str(round_file)))
    try:
        assert fleet.healthz()["round"] == 1
        round_file.write_text("2")
        out = fleet.rolling_reload(target_round=2)
        assert not out["aborted"]
        assert [x["ok"] for x in out["replicas"]] == [True, True]
        assert [x["swapped"] for x in out["replicas"]] == [True, True]
        assert fleet.healthz()["round"] == 2
        # reload again with no new round: a clean noop, breaker closed
        out = fleet.rolling_reload()
        assert [x["swapped"] for x in out["replicas"]] == [False, False]
        assert fleet.reload_breaker.state == "closed"
    finally:
        fleet.close(drain_timeout_s=0.0)


def test_rolling_reload_breaker_aborts_rollout():
    """A rollout that keeps failing stops: the breaker opens and the
    remaining replicas are left serving the old model (aborted result,
    not an emptied rotation)."""
    opts = make_opts(replicas=2, probe_period_s=30.0,  # probes dormant
                     reload_breaker_threshold=1, reload_timeout_s=2.0)
    fleet = start_stub_fleet(opts)
    try:
        # replica 0's process dies; the supervisor (probing every 30 s)
        # has not noticed, so the rollout hits it first and fails
        fleet.supervisor.replicas[0].proc.kill()
        time.sleep(0.2)
        out = fleet.rolling_reload(target_round=9)
        assert out["aborted"] is True
        assert len(out["replicas"]) == 1  # replica 1 never touched
        assert out["replicas"][0]["ok"] is False
        assert fleet.reload_breaker.state in ("open", "half-open")
    finally:
        fleet.close(drain_timeout_s=0.0)


# ----------------------------------------------------------------------
# canary lifecycle
def _canary_fleet(tmp_path, disagree):
    opts = make_opts(
        replicas=3, canary="int8", canary_replicas=1,
        canary_slice=0.25, canary_sample=0.8, canary_min_requests=10,
        canary_min_agreement=0.99, canary_decision_period_s=999.0)

    def per_replica(r):
        if r.role == "canary":
            return ("--quant", "int8", "--disagree", str(disagree))
        return ()

    fleet = start_stub_fleet(opts, per_replica=per_replica,
                             model_dir=str(tmp_path))
    fleet.canary._arm_rule()  # rule only; decisions driven by the test
    return fleet


def _drive_until_compared(fleet, n, timeout_s=30.0):
    deadline = time.monotonic() + timeout_s
    i = 0
    while fleet.canary.compared < n and time.monotonic() < deadline:
        i += 1
        s, _ = fleet.router.route(
            "/predict", {"data": [[0.01 * (i % 50)] * 4]})
        assert s == 200
        time.sleep(0.005)
    assert fleet.canary.compared >= n, (
        f"only {fleet.canary.compared} rows compared after "
        f"{i} requests")


def test_canary_promotes_and_flips_pointer(tmp_path):
    m = fleet_metrics()
    promotes0 = m.canary_total.labels(decision="promote").value
    fleet = _canary_fleet(tmp_path, disagree=0)
    try:
        _drive_until_compared(fleet, fleet.opts.canary_min_requests)
        assert fleet.canary.decide() == "promote"
        assert fleet.canary.state == "promoted"
        assert fleet.canary.agreement() == 1.0
        ptr = ckpt.read_publish_pointer(str(tmp_path))
        assert ptr is not None and ptr["round"] == 1
        assert ptr["metric"]["scheme"] == "int8"
        assert m.canary_total.labels(
            decision="promote").value == promotes0 + 1
        # full weight: a promoted canary is back in the baseline pool
        pool_roles = {r.role for r in (
            fleet.router.pick_replica() for _ in range(8)) if r}
        assert "canary" in {r.role for r in fleet.supervisor.rotation()}
        assert pool_roles  # dispatchable at all
        assert fleet.router._canary_live() is False
    finally:
        fleet.close(drain_timeout_s=0.0)


def test_canary_rollback_through_alert_and_pointer(tmp_path):
    """The rollback acceptance: an injected-disagreement canary is
    detected via the shared metric families, the ``canary_agreement``
    alert fires, the decision rolls back through the publish pointer,
    and the canary replicas relaunch as plain f32 members."""
    from cxxnet_tpu.obs import alerts as obs_alerts

    m = fleet_metrics()
    rollbacks0 = m.canary_total.labels(decision="rollback").value
    fleet = _canary_fleet(tmp_path, disagree=7)
    try:
        canary_replica = fleet.canary.canaries()[0]
        _drive_until_compared(fleet, fleet.opts.canary_min_requests)
        assert fleet.canary.agreement() < 0.99
        assert fleet.canary.decide() == "rollback"
        assert fleet.canary.state == "rolled_back"
        assert "canary_agreement firing" in fleet.canary.decision_reason

        # the pointer records the BASELINE as blessed
        ptr = ckpt.read_publish_pointer(str(tmp_path))
        assert ptr is not None and ptr["round"] == 1
        assert m.canary_total.labels(
            decision="rollback").value == rollbacks0 + 1

        # the canary replica was relaunched as a plain serving member
        assert canary_replica.role == "serve"
        assert canary_replica.restarts >= 1
        assert canary_replica.down_reason == "canary_rollback"

        # the trigger gauge was cleared: /alertz stops firing for a
        # comparison that no longer exists
        ev = obs_alerts.evaluator()
        ev.evaluate_once()
        assert "canary_agreement" not in ev.firing()
    finally:
        fleet.close(drain_timeout_s=0.0)


# ----------------------------------------------------------------------
# HTTP front door
def test_router_http_surface(tmp_path):
    opts = make_opts(replicas=2)
    fleet = ServingFleet(opts, spawn_fn=stub_spawn_fn(), port=0)
    httpd = fleet.start()
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{httpd.server_port}"
    try:
        def post(path, obj, headers=()):
            req = urllib.request.Request(
                base + path, data=json.dumps(obj).encode("utf-8"),
                headers={"Content-Type": "application/json",
                         **dict(headers)})
            try:
                with urllib.request.urlopen(req, timeout=20) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        status, body = post("/predict", {"data": [[0.3] * 4]})
        assert status == 200 and body["pred"]

        # priority via header, and the classifier rejects junk
        status, _ = post("/predict", {"data": [[0.3] * 4]},
                         headers=[("X-Priority", "batch")])
        assert status == 200
        assert fleet.router.stats.requests["batch"] == 1
        status, body = post("/predict", {"data": [[0.3] * 4],
                                         "priority": "bulk"})
        assert status == 400 and "unknown priority" in body["error"]

        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            h = json.loads(r.read())
        assert h["fleet"] is True and h["status"] == "ok"
        assert h["replicas"]["healthy"] == 2
        assert isinstance(h["reasons"], list)
        # the aggregate healthz passes the machine-readable shape check
        hz = tmp_path / "healthz.json"
        hz.write_text(json.dumps(h))
        from conftest import run_cli

        r = run_cli([os.path.join(REPO, "tools", "obs_dump.py"),
                     "--check", "--healthz", str(hz)],
                    cwd=str(tmp_path), module=False)
        assert r.returncode == 0, r.stdout + r.stderr

        with urllib.request.urlopen(base + "/statsz", timeout=10) as r:
            st = json.loads(r.read())
        assert len(st["replicas"]) == 2
        assert st["requests"]["interactive"] >= 1
    finally:
        httpd.shutdown()
        thread.join(timeout=10)
        fleet.close(drain_timeout_s=0.0)
