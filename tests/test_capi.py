"""C ABI end-to-end: build libcxxnet_capi.so + the pure-C smoke host and
run it (training, eval line format, predict, extract, weight and
checkpoint round-trips, error path).

Parity surface: ``/root/reference/wrapper/cxxnet_wrapper.h:36-230`` —
the one reference API that round 1 left without an analog (VERDICT r1
"What's missing" #1).
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")


def _toolchain_ok():
    return (
        shutil.which("make")
        and shutil.which("g++")
        and shutil.which("python3-config")
    )


@pytest.mark.skipif(not _toolchain_ok(), reason="no native toolchain")
def test_capi_smoke_end_to_end():
    r = subprocess.run(
        ["make", "capi"], cwd=NATIVE, capture_output=True, text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stderr
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["CXXNET_TPU_HOME"] = REPO
    env["PYTHONPATH"] = ""  # prove the .so bootstraps the path itself
    r = subprocess.run(
        [os.path.join(NATIVE, "capi_smoke")],
        capture_output=True, text=True, timeout=600, env=env, cwd="/tmp",
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "all checks passed" in r.stderr


@pytest.mark.skipif(not _toolchain_ok(), reason="no native toolchain")
def test_capi_shim_functions_importable():
    """Every C entry point has its shim function (keeps the .cc and the
    python side from drifting apart)."""
    sys.path.insert(0, REPO)
    from cxxnet_tpu import capi_shim

    with open(os.path.join(NATIVE, "cxxnet_capi.cc")) as f:
        src = f.read()
    import re

    called = set(re.findall(r'shim_call\("([a-z_0-9]+)"', src))
    assert called, "no shim_call sites found"
    for fn in called:
        assert hasattr(capi_shim, fn), f"capi_shim.{fn} missing"
