"""On-chip kernel library (``cxxnet_tpu/ops/kernels/``): interpret-mode
parity, selector/verdict discipline, and end-to-end dispatch.

The parity contract everything here pins: each Pallas kernel, run under
``interpret=True`` on CPU, is BIT-EQUAL (``np.array_equal``) to the
JITTED stock lowering it replaces.  The jitted reference is the honest
one — the net's real programs are always compiled, and on CPU the eager
op-by-op spelling differs from its own compiled form (FMA fusion), so
"parity with the stock lowering" means the lowering, not the eager
replay.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu import config as C
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.ops import kernels as klib
from cxxnet_tpu.ops import quant as opsq
from cxxnet_tpu.ops.kernels import conv_block, int8_gemm, update_step
from cxxnet_tpu.updater import SGDUpdater


# ----------------------------------------------------------------------
# conv_block: fused conv+bias(+relu) GEMM vs the stock conv lowering
def _conv_ref(x, wk, bias, stride=1, relu=False):
    y = jax.lax.conv_general_dilated(
        x, wk, window_strides=(stride, stride), padding=((0, 0), (0, 0)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if bias is not None:
        y = y + bias.astype(x.dtype)
    if relu:
        y = jnp.maximum(y, jnp.zeros((), y.dtype))
    return y


def _conv_case(dtype=np.float32, b=4, hw=6, cin=8, cout=16, seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(b, hw, hw, cin).astype(np.float32)).astype(dtype)
    wk = jnp.asarray(
        rng.randn(1, 1, cin, cout).astype(np.float32) * 0.1).astype(dtype)
    bias = jnp.asarray(rng.randn(cout).astype(np.float32)).astype(dtype)
    return x, wk, bias


@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16])
def test_conv_block_bit_parity(dtype):
    x, wk, bias = _conv_case(dtype)
    ref = jax.jit(_conv_ref)(x, wk, bias)
    got = conv_block.conv1x1_block(x, wk, bias, interpret=True)
    assert got.dtype == ref.dtype
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_conv_block_blocked_and_stride_and_relu():
    x, wk, bias = _conv_case(b=4, hw=8, cin=8, cout=16)
    # explicit bm/bn tiling (the MXU shape) keeps the full-K contraction
    got = conv_block.conv1x1_block(x, wk, bias, interpret=True, bm=8, bn=8)
    ref = jax.jit(_conv_ref)(x, wk, bias)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    # stride via host-side subsampling (exact for 1x1/pad-0)
    ref2 = jax.jit(lambda *a: _conv_ref(*a, stride=2))(x, wk, bias)
    got2 = conv_block.conv1x1_block(x, wk, bias, stride=2, interpret=True)
    np.testing.assert_array_equal(np.asarray(ref2), np.asarray(got2))
    # relu folded into the epilogue
    ref3 = jax.jit(lambda *a: _conv_ref(*a, relu=True))(x, wk, bias)
    got3 = conv_block.conv1x1_block(x, wk, bias, relu=True, interpret=True)
    np.testing.assert_array_equal(np.asarray(ref3), np.asarray(got3))


def test_conv_block_no_bias_and_probe():
    x, wk, _ = _conv_case()
    ref = jax.jit(lambda x, w: _conv_ref(x, w, None))(x, wk)
    got = conv_block.conv1x1_block(x, wk, None, interpret=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    assert conv_block.probe("cpu", x=x, wk=wk) is None
    assert "1x1" in conv_block.probe(
        "cpu", x=x, wk=jnp.zeros((3, 3, 8, 16), jnp.float32))
    assert "NHWC" in conv_block.probe("cpu", x=jnp.zeros((4, 8)), wk=wk)
    assert "dtype" in conv_block.probe(
        "cpu", x=jnp.zeros((1, 2, 2, 3), jnp.float16), wk=wk)


# ----------------------------------------------------------------------
# int8_gemm: the epilogue kernel vs the PR-10 dequant-free reference
def _int8_case(m=8, k=24, o=12, seed=1, act=np.float32):
    rng = np.random.RandomState(seed)
    w = rng.randn(o, k).astype(np.float32)
    q, s = opsq.quantize_weight(w, out_axis=0)
    lp = {opsq.QKEY: jnp.asarray(q), opsq.SKEY: jnp.asarray(s),
          "bias": jnp.asarray(rng.randn(o).astype(np.float32))}
    x = jnp.asarray(rng.randn(m, k).astype(np.float32)).astype(act)
    return lp, x


@pytest.mark.parametrize("act", [np.float32, jnp.bfloat16])
def test_int8_gemm_bit_equal_to_dequant_free_reference(act):
    """The acceptance bar: the in-kernel quantize->MXU->rescale epilogue
    is bit-equal to the stock ``fc_apply_q`` lowering (which feeds raw
    codes and folds the rescale into the f32 bias add outside the
    contraction)."""
    lp, x = _int8_case(act=act)
    ref = jax.jit(opsq.fc_apply_q)(lp, x)
    got = int8_gemm.int8_gemm_rescale(
        x, lp[opsq.QKEY], lp[opsq.SKEY], lp["bias"], interpret=True)
    assert got.dtype == ref.dtype
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_int8_gemm_blocked_no_bias_relu():
    lp, x = _int8_case(m=8, k=32, o=16)
    ref = jax.jit(opsq.fc_apply_q)(lp, x)
    got = int8_gemm.int8_gemm_rescale(
        x, lp[opsq.QKEY], lp[opsq.SKEY], lp["bias"], interpret=True,
        bm=4, bn=8)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    lp2 = {k: v for k, v in lp.items() if k != "bias"}
    ref2 = jax.jit(opsq.fc_apply_q)(lp2, x)
    got2 = int8_gemm.int8_gemm_rescale(
        x, lp[opsq.QKEY], lp[opsq.SKEY], None, interpret=True)
    np.testing.assert_array_equal(np.asarray(ref2), np.asarray(got2))
    ref3 = jax.jit(
        lambda lp, x: jnp.maximum(opsq.fc_apply_q(lp, x), 0.0))(lp, x)
    got3 = int8_gemm.int8_gemm_rescale(
        x, lp[opsq.QKEY], lp[opsq.SKEY], lp["bias"], relu=True,
        interpret=True)
    np.testing.assert_array_equal(np.asarray(ref3), np.asarray(got3))


def test_int8_gemm_probe():
    lp, x = _int8_case()
    assert int8_gemm.probe("cpu", x=x, q=lp[opsq.QKEY]) is None
    assert "dtype" in int8_gemm.probe(
        "cpu", x=x.astype(jnp.float16), q=lp[opsq.QKEY])
    assert "int8" in int8_gemm.probe(
        "cpu", x=x, q=np.zeros((3, 3), np.int32))


# ----------------------------------------------------------------------
# zero_update: the fused sgd step vs the stock updater rule
def _sgd(clip="0.0"):
    up = SGDUpdater("wmat")
    for k, v in (("eta", "0.05"), ("momentum", "0.9"),
                 ("wd", "0.0005"), ("clip_gradient", clip)):
        up.set_param(k, v)
    return up


def _upd_case(shape, seed=2, nan_at=None):
    rng = np.random.RandomState(seed)
    w = rng.randn(*shape).astype(np.float32)
    g = rng.randn(*shape).astype(np.float32)
    if nan_at is not None:
        g.reshape(-1)[nan_at] = np.nan
    m = rng.randn(*shape).astype(np.float32)
    return jnp.asarray(w), jnp.asarray(g), jnp.asarray(m)


@pytest.mark.parametrize("shape", [(3, 3, 4, 8), (7,), (256,), (5, 130)])
def test_zero_update_bit_parity(shape):
    up = _sgd()
    w, g, m = _upd_case(shape)
    epoch = jnp.asarray(2)
    ref_w, ref_s = jax.jit(
        lambda w, g, m, e: up.apply(w, g, {"m": m}, e))(w, g, m, epoch)
    p = up.param
    got_w, got_m = update_step.sgd_update(
        w, g, m, p.learning_rate(epoch).astype(w.dtype),
        p.momentum_at(epoch).astype(w.dtype), wd=p.wd,
        clip=p.clip_gradient, interpret=True)
    assert got_w.shape == shape and got_m.shape == shape
    np.testing.assert_array_equal(np.asarray(ref_w), np.asarray(got_w))
    np.testing.assert_array_equal(np.asarray(ref_s["m"]), np.asarray(got_m))


def test_zero_update_clip_nan_and_blocked():
    """The reference's clip quirk (``_nan_clip``: zero NaNs, then clamp
    — only when clip_gradient != 0) survives the fusion, NaNs
    included; row-tiling (``br``) changes nothing."""
    up = _sgd(clip="0.5")
    w, g, m = _upd_case((4, 130), nan_at=7)
    epoch = jnp.asarray(5)
    ref_w, ref_s = jax.jit(
        lambda w, g, m, e: up.apply(w, g, {"m": m}, e))(w, g, m, epoch)
    p = up.param
    for br in (0, 1):
        got_w, got_m = update_step.sgd_update(
            w, g, m, p.learning_rate(epoch).astype(w.dtype),
            p.momentum_at(epoch).astype(w.dtype), wd=p.wd,
            clip=p.clip_gradient, interpret=True, br=br)
        np.testing.assert_array_equal(np.asarray(ref_w), np.asarray(got_w))
        np.testing.assert_array_equal(
            np.asarray(ref_s["m"]), np.asarray(got_m))
    assert np.isfinite(np.asarray(got_w)).all()


def test_zero_update_probe():
    assert update_step.probe("cpu", w=jnp.zeros((3,), jnp.float32),
                             updater=_sgd()) is None
    assert "f32" in update_step.probe(
        "cpu", w=jnp.zeros((3,), jnp.bfloat16), updater=_sgd())

    class FakeAdam:
        type_name = "adam"

    assert "sgd only" in update_step.probe(
        "cpu", w=jnp.zeros((3,), jnp.float32), updater=FakeAdam())


# ----------------------------------------------------------------------
# selector / verdict discipline
def test_parse_mode_canonicalization_and_typo():
    assert klib.parse_mode("auto") == "auto"
    assert klib.parse_mode("-1") == "auto"
    for v in ("off", "0", "", "none"):
        assert klib.parse_mode(v) == "off"
    assert klib.parse_mode("int8_gemm, conv_block") == \
        "conv_block,int8_gemm"
    with pytest.raises(ValueError, match="conv_blok"):
        klib.parse_mode("conv_blok")


def test_auto_follows_recorded_verdicts():
    """``kernel_lib=auto`` runs a kernel exactly where a committed
    promote says it pays — the ``conv_branch_embed=-1`` discipline."""
    v = {"conv_block": {"cpu": {"verdict": "reject"},
                        "tpu": {"verdict": "promote"}}}
    sel = klib.KernelSelector("auto", verdicts=v)
    assert not sel.active("conv_block", "cpu")     # recorded reject
    assert sel.active("conv_block", "tpu")         # recorded promote
    assert not sel.active("int8_gemm", "cpu")      # no verdict = stock
    assert sel.fingerprint("cpu") == ""
    assert sel.fingerprint("tpu") == "conv_block"
    off = klib.KernelSelector("off", verdicts=v)
    assert not off.active("conv_block", "tpu")
    pinned = klib.KernelSelector("conv_block,zero_update", verdicts=v)
    assert pinned.active("conv_block", "cpu")      # list overrides
    assert not pinned.active("int8_gemm", "cpu")
    assert pinned.fingerprint("cpu") == "conv_block+zero_update"
    with pytest.raises(ValueError):
        sel.active("nope", "cpu")


def test_committed_cpu_verdicts_exist_and_auto_honors_them():
    """The package ships measured CPU verdicts (kernel_ab --record):
    every kernel has one, rejects are honest (Pallas-on-CPU is
    interpret emulation), and the default auto selector follows them."""
    doc = klib.load_verdicts()
    sel = klib.KernelSelector("auto")
    for name in klib.KERNELS:
        ent = doc.get(name, {}).get("cpu")
        assert ent, f"{name}: no committed cpu verdict"
        assert ent["verdict"] in ("promote", "reject")
        assert ent["parity"] is True  # never committed on wrong math
        assert sel.active(name, "cpu") == (ent["verdict"] == "promote")
        # nothing recorded for tpu yet: auto stays stock on-chip until
        # tpu_queue.sh drains
        assert not sel.active(name, "tpu")


def test_record_verdict_roundtrip(tmp_path):
    p = str(tmp_path / "verdicts.json")
    klib.record_verdict("int8_gemm", "tpu", "promote", path=p, ratio=1.7)
    klib.record_verdict("int8_gemm", "cpu", "reject", path=p)
    doc = json.load(open(p))
    assert doc["int8_gemm"]["tpu"] == {"verdict": "promote", "ratio": 1.7}
    sel = klib.KernelSelector("auto", verdicts=doc)
    assert sel.active("int8_gemm", "tpu")
    assert not sel.active("int8_gemm", "cpu")
    with pytest.raises(ValueError, match="unknown kernel"):
        klib.record_verdict("nope", "cpu", "reject", path=p)
    with pytest.raises(ValueError, match="promote/reject"):
        klib.record_verdict("int8_gemm", "cpu", "maybe", path=p)


def test_bound_kernels_probe_and_gauge():
    """BoundKernels.active = selected AND capable, and every decision
    lands on the ``kernel_selected{name,backend}`` gauge."""
    from cxxnet_tpu.obs.registry import registry

    sel = klib.KernelSelector("zero_update")
    kb = sel.bind("cpu")
    assert kb.interpret  # off-TPU: the interpret spelling
    assert kb.active("zero_update", w=jnp.zeros((3,), jnp.float32),
                     updater=_sgd())
    g = registry().gauge("kernel_selected", labelnames=("name", "backend"))
    assert g.labels(name="zero_update", backend="cpu").get() == 1.0
    # capable-but-wrong-dtype: probe rejects, gauge drops to 0
    assert not kb.active("zero_update", w=jnp.zeros((3,), jnp.bfloat16),
                         updater=_sgd())
    assert g.labels(name="zero_update", backend="cpu").get() == 0.0


# ----------------------------------------------------------------------
# end-to-end dispatch: net forward / quant predict / train step
def _sibling_trainer(kernel_lib, cfg=None, seed="7"):
    from tests.test_trainer import INCEPTION_CFG

    tr = NetTrainer()
    tr.set_params(C.parse_pairs(
        (cfg or INCEPTION_CFG)
        + f"fuse_1x1 = 1\nkernel_lib = {kernel_lib}\n"))
    tr.set_param("seed", seed)
    tr.init_model()
    return tr


def test_net_forward_parity_conv_block():
    """Scores of the kernel-forced net are bit-equal to the stock net
    (same seed) — including the strided ResNet boundary pair."""
    from tests.test_trainer import RESNET_BOUNDARY_CFG

    rng = np.random.RandomState(5)
    for cfg in (None, RESNET_BOUNDARY_CFG):
        x = jnp.asarray(rng.randn(16, 6, 6, 3).astype(np.float32))
        t0 = _sibling_trainer("off", cfg)
        t1 = _sibling_trainer("conv_block", cfg)
        s0 = np.asarray(t0.predict_fn(None)(t0.params, t0.aux, x, ()))
        s1 = np.asarray(t1.predict_fn(None)(t1.params, t1.aux, x, ()))
        np.testing.assert_array_equal(s0, s1)


def test_net_quant_predict_parity_int8_gemm():
    from cxxnet_tpu.nnet import quant as nquant
    from tests.test_quant import _batch, _conv_trainer

    b = _batch()
    t0 = _conv_trainer((("kernel_lib", "off"),))
    t1 = _conv_trainer((("kernel_lib", "int8_gemm"),))
    for t in (t0, t1):
        nquant.apply_plan(t, nquant.build_plan(t))
    x = jnp.asarray(b.data)
    s0 = np.asarray(t0.predict_fn(None)(t0.params, t0.aux, x, ()))
    s1 = np.asarray(t1.predict_fn(None)(t1.params, t1.aux, x, ()))
    np.testing.assert_array_equal(s0, s1)


def test_train_step_parity_with_kernels_forced():
    """Training with every kernel pinned ON matches stock bit-for-bit:
    the forward stays stock in train builds (Pallas calls carry no vjp)
    and the zero_update kernel replays the sgd rule exactly — params
    AND momentum bitwise after 2 epochs."""
    from tests.test_trainer import batches

    rng = np.random.RandomState(5)
    xd = rng.randn(32, 6, 6, 3).astype(np.float32)
    yd = rng.randint(0, 4, (32, 1)).astype(np.float32)
    t0 = _sibling_trainer("off")
    t1 = _sibling_trainer("conv_block,int8_gemm,zero_update")
    for tr in (t0, t1):
        for _ in range(2):
            for b in batches(xd, yd):
                tr.update(b)
    for tree0, tree1 in ((t0.params, t1.params),
                         (t0.ustates, t1.ustates)):
        l0 = jax.tree_util.tree_leaves(tree0)
        l1 = jax.tree_util.tree_leaves(tree1)
        assert len(l0) == len(l1)
        for a, b in zip(l0, l1):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_update_kernel_actually_fires(monkeypatch):
    """Guard against the silent-stock failure mode: with zero_update
    pinned ON, the trainer's update program must route every sgd tensor
    through the kernel launcher."""
    calls = {"n": 0}
    real = update_step.sgd_update

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(update_step, "sgd_update", counting)
    from tests.test_trainer import batches

    rng = np.random.RandomState(3)
    xd = rng.randn(16, 6, 6, 3).astype(np.float32)
    yd = rng.randint(0, 4, (16, 1)).astype(np.float32)
    tr = _sibling_trainer("zero_update")
    for b in batches(xd, yd):
        tr.update(b)
    # one launch per (key, tag) at trace time: 5 layers x (wmat, bias)
    assert calls["n"] == 10


def test_kernel_lib_conf_typo_fails_at_set_param():
    tr = NetTrainer()
    with pytest.raises(ValueError, match="kernel_lib"):
        tr.set_param("kernel_lib", "conv_blok")


# ----------------------------------------------------------------------
# serve: cache-key isolation + stock/kernel coexistence
def test_bucket_cache_kernel_fingerprint_isolation():
    """The kernel selection rides in the `_run` key (second-to-last —
    the quant scheme stays last): stock and kernel programs of ONE net
    occupy distinct slots and serve side by side, bit-equal."""
    from cxxnet_tpu.serve.cache import ShapeBucketCache

    t_off = _sibling_trainer("off")
    t_on = _sibling_trainer("conv_block")
    c_off = ShapeBucketCache(t_off, 16)
    c_on = ShapeBucketCache(t_on, 16)
    assert c_off.kernel_fp() == ""
    assert c_on.kernel_fp() == "conv_block"
    rng = np.random.RandomState(0)
    x = rng.rand(4, 6, 6, 3).astype(np.float32)
    s_off = c_off.scores(x)
    s_on = c_on.scores(x)
    np.testing.assert_array_equal(s_off, s_on)  # coexisting, identical
    k_off, k_on = c_off.keys_snapshot()[0], c_on.keys_snapshot()[0]
    assert k_off[0] == k_on[0]          # same net fingerprint ...
    assert k_off[-2] == "" and k_on[-2] == "conv_block"  # ... new slot
    assert k_off[-1] == k_on[-1] == ""  # quant scheme stays last
    assert k_off != k_on


# ----------------------------------------------------------------------
# the A/B driver: verdict schema + parity gate, in-process
def test_kernel_ab_emits_schema_valid_verdict(tmp_path):
    import sys as _sys

    _sys.path.insert(0, "tools")
    import kernel_ab
    import perf_guard

    res = kernel_ab.run_kernel("int8_gemm", smoke=True, backend="cpu",
                               reps=1)
    assert res["parity"] is True
    assert res["verdict"] in ("promote", "reject")
    hist = str(tmp_path / "hist.jsonl")
    doc = perf_guard.run_once(
        "kernel_bench", {"backend": "cpu", "kernels": [res]}, hist,
        window=5, band=0.2)
    assert perf_guard.validate_verdict(doc) == []
    m = doc["metrics"]
    assert m["int8_gemm_parity"] == 1.0
    assert m["int8_gemm_stock_ms"] > 0 and m["int8_gemm_kernel_ms"] > 0
    # the lower-is-better orientation lands on the _ms series
    assert perf_guard.lower_is_better("int8_gemm_kernel_ms")
    assert not perf_guard.lower_is_better("int8_gemm_ratio")
