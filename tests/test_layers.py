"""Golden tests: every layer vs a straightforward numpy reference.

This is the PairTest discipline of the reference (SURVEY §4.1) turned into
a real test suite: master = the JAX layer, slave = naive numpy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu import layers as L


def mk(name, cfg=()):
    lay = L.create_layer(name)
    for k, v in cfg:
        lay.set_param(k, v)
    return lay


def run1(lay, x, train=False, rng=None, extra_inputs=None, step=None):
    inputs = [jnp.asarray(x)] + [jnp.asarray(e) for e in (extra_inputs or [])]
    shapes = [i.shape for i in inputs]
    out_shapes = lay.infer_shape(shapes)
    params = lay.init_params(jax.random.PRNGKey(0), shapes)
    outs = lay.apply(params, inputs, train=train, rng=rng, step=step)
    for o, s in zip(outs, out_shapes):
        assert tuple(o.shape) == tuple(s), f"{lay.type_name}: inferred {s} got {o.shape}"
    return [np.asarray(o) for o in outs], params


# ---------------------------------------------------------------- dense


def test_fullc_forward(rng):
    x = rng.randn(4, 7).astype(np.float32)
    lay = mk("fullc", [("nhidden", "5"), ("init_sigma", "0.1")])
    (out,), params = run1(lay, x)
    want = x @ np.asarray(params["wmat"]).T + np.asarray(params["bias"])
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_fullc_no_bias(rng):
    x = rng.randn(3, 4).astype(np.float32)
    lay = mk("fullc", [("nhidden", "2"), ("no_bias", "1")])
    (out,), params = run1(lay, x)
    assert "bias" not in params
    np.testing.assert_allclose(out, x @ np.asarray(params["wmat"]).T, rtol=1e-5)


def test_fullc_rejects_image_input():
    lay = mk("fullc", [("nhidden", "2")])
    with pytest.raises(ValueError):
        lay.infer_shape([(2, 3, 3, 1)])


def test_flatten(rng):
    x = rng.randn(2, 3, 4, 5).astype(np.float32)
    lay = mk("flatten")
    (out,), _ = run1(lay, x)
    np.testing.assert_allclose(out, x.reshape(2, -1))


def test_fixconn(tmp_path, rng):
    w = np.zeros((3, 4), np.float32)
    w[0, 1] = 2.0
    w[2, 3] = -1.5
    f = tmp_path / "w.txt"
    f.write_text("3 4 2\n0 1 2.0\n2 3 -1.5\n")
    x = rng.randn(5, 4).astype(np.float32)
    lay = mk("fixconn", [("nhidden", "3"), ("fixconn_weight", str(f))])
    (out,), params = run1(lay, x)
    assert params == {}
    np.testing.assert_allclose(out, x @ w.T, rtol=1e-5)


# ---------------------------------------------------------------- conv


def conv_ref(x, w, b, stride, pad, ngroup):
    """Naive NHWC grouped conv. w: (kh, kw, cin_g, cout)."""
    n, h, wd, c = x.shape
    kh, kw, cin_g, cout = w.shape
    xp = np.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (wd + 2 * pad - kw) // stride + 1
    out = np.zeros((n, oh, ow, cout), np.float32)
    cout_g = cout // ngroup
    for g in range(ngroup):
        xg = xp[..., g * cin_g : (g + 1) * cin_g]
        wg = w[..., g * cout_g : (g + 1) * cout_g]
        for i in range(oh):
            for j in range(ow):
                patch = xg[:, i * stride : i * stride + kh, j * stride : j * stride + kw, :]
                out[:, i, j, g * cout_g : (g + 1) * cout_g] = np.einsum(
                    "nhwc,hwck->nk", patch, wg
                )
    if b is not None:
        out += b
    return out


@pytest.mark.parametrize("ngroup,pad,stride", [(1, 0, 1), (1, 1, 2), (2, 2, 1)])
def test_conv_forward(rng, ngroup, pad, stride):
    x = rng.randn(2, 8, 8, 4).astype(np.float32)
    lay = mk(
        "conv",
        [
            ("kernel_size", "3"),
            ("nchannel", "6"),
            ("ngroup", str(ngroup)),
            ("pad", str(pad)),
            ("stride", str(stride)),
            ("init_sigma", "0.1"),
        ],
    )
    (out,), params = run1(lay, x)
    want = conv_ref(
        x, np.asarray(params["wmat"]), np.asarray(params["bias"]), stride, pad, ngroup
    )
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_conv_shape_formula():
    lay = mk("conv", [("kernel_size", "11"), ("stride", "4"), ("nchannel", "96")])
    assert lay.infer_shape([(2, 227, 227, 3)]) == [(2, 55, 55, 96)]


# ---------------------------------------------------------------- pooling


def pool_ref(x, k, s, mode):
    """Naive ceil-mode pooling with partial edge windows (reference rule)."""
    n, h, w, c = x.shape
    oh = min(h - k + s - 1, h - 1) // s + 1
    ow = min(w - k + s - 1, w - 1) // s + 1
    out = np.zeros((n, oh, ow, c), np.float32)
    for i in range(oh):
        for j in range(ow):
            win = x[:, i * s : min(i * s + k, h), j * s : min(j * s + k, w), :]
            if mode == "max":
                out[:, i, j] = win.max(axis=(1, 2))
            elif mode == "sum":
                out[:, i, j] = win.sum(axis=(1, 2))
            else:  # avg: always divide by k*k (reference parity)
                out[:, i, j] = win.sum(axis=(1, 2)) / (k * k)
    return out


@pytest.mark.parametrize(
    "name,mode", [("max_pooling", "max"), ("sum_pooling", "sum"), ("avg_pooling", "avg")]
)
@pytest.mark.parametrize("hw,k,s", [(28, 3, 2), (6, 2, 2), (7, 3, 3)])
def test_pooling(rng, name, mode, hw, k, s):
    x = rng.randn(2, hw, hw, 3).astype(np.float32)
    lay = mk(name, [("kernel_size", str(k)), ("stride", str(s))])
    (out,), _ = run1(lay, x)
    np.testing.assert_allclose(out, pool_ref(x, k, s, mode), rtol=1e-5, atol=1e-6)


def test_pooling_ceil_shape():
    # 28x28, k=3, s=2 → 14 (ceil), not 13 (floor)
    lay = mk("max_pooling", [("kernel_size", "3"), ("stride", "2")])
    assert lay.infer_shape([(1, 28, 28, 8)]) == [(1, 14, 14, 8)]


def test_relu_max_pooling(rng):
    x = rng.randn(2, 6, 6, 2).astype(np.float32)
    lay = mk("relu_max_pooling", [("kernel_size", "2"), ("stride", "2")])
    (out,), _ = run1(lay, x)
    np.testing.assert_allclose(out, pool_ref(np.maximum(x, 0), 2, 2, "max"), rtol=1e-5)


def unpool_ref(x, g, k, s):
    """mshadow unpool rule (pooling_layer-inl.hpp:66-75): every input
    position equal to its window's max receives that window's gradient."""
    n, h, w, c = x.shape
    y = pool_ref(x, k, s, "max")
    oh, ow = y.shape[1], y.shape[2]
    dx = np.zeros_like(x)
    for i in range(oh):
        for j in range(ow):
            for ii in range(i * s, min(i * s + k, h)):
                for jj in range(j * s, min(j * s + k, w)):
                    dx[:, ii, jj] += np.where(
                        x[:, ii, jj] == y[:, i, j], g[:, i, j], 0.0
                    )
    return dx


@pytest.mark.parametrize("hw,k,s", [(28, 3, 2), (6, 2, 2), (7, 3, 3), (8, 3, 1)])
def test_maxpool_backward_is_reference_unpool(rng, hw, k, s):
    """The custom-VJP backward (conv._maxpool_eq) == mshadow unpool,
    including gradient duplication to ALL tied max positions (ties are
    common post-relu where windows share zeros)."""
    x = rng.randn(2, hw, hw, 3).astype(np.float32)
    # force ties: zero out a block so multiple window positions tie at 0
    x[:, : hw // 2] = np.maximum(x[:, : hw // 2], 0.0)
    x[0, 0, :] = 0.0
    lay = mk("max_pooling", [("kernel_size", str(k)), ("stride", str(s))])
    out_shape = lay.infer_shape([x.shape])[0]
    g = rng.randn(*out_shape).astype(np.float32)

    def f(v):
        return (lay.apply({}, [jnp.asarray(v)])[0] * jnp.asarray(g)).sum()

    dx = np.asarray(jax.grad(f)(x))
    np.testing.assert_allclose(dx, unpool_ref(x, g, k, s), rtol=1e-4,
                               atol=1e-5)


@pytest.mark.parametrize(
    "hw,k,s,p,cin",
    [(16, 7, 2, 3, 3), (14, 3, 2, 1, 4), (12, 2, 2, 0, 3),
     (18, 4, 2, 1, 2), (13, 3, 2, 2, 3), (23, 11, 4, 0, 3),
     (15, 5, 3, 1, 3), (17, 4, 4, 2, 2)],
)
def test_conv_s2d_matches_plain_strided(rng, hw, k, s, p, cin):
    """conv_s2d=1 (space-to-depth rewrite of strided convs) must match
    the plain strided conv — outputs and weight/input gradients — for
    every stride, including extents not divisible by the stride."""
    x = rng.randn(2, hw, hw + 2, cin).astype(np.float32)
    base = mk("conv", [("kernel_size", str(k)), ("stride", str(s)),
                       ("pad", str(p)), ("nchannel", "8")])
    s2d = mk("conv", [("kernel_size", str(k)), ("stride", str(s)),
                      ("pad", str(p)), ("nchannel", "8"),
                      ("conv_s2d", "1")])
    params = base.init_params(jax.random.PRNGKey(0), [x.shape])
    ya = base.apply(params, [jnp.asarray(x)])[0]
    yb = s2d.apply(params, [jnp.asarray(x)])[0]
    assert ya.shape == yb.shape
    np.testing.assert_allclose(np.asarray(ya), np.asarray(yb),
                               rtol=1e-5, atol=1e-5)

    def loss(lay, pr, v):
        return (lay.apply(pr, [v])[0] ** 2).sum()

    ga = jax.grad(loss, argnums=(1, 2))(base, params, jnp.asarray(x))
    gb = jax.grad(loss, argnums=(1, 2))(s2d, params, jnp.asarray(x))
    for a, b in zip(jax.tree_util.tree_leaves(ga),
                    jax.tree_util.tree_leaves(gb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize(
    "hw,k,s,p",
    [(12, 3, 2, 0), (7, 3, 2, 1), (11, 3, 2, 1), (14, 2, 2, 0),
     (10, 5, 3, 2), (9, 4, 2, 1)],
)
def test_strided_unpool_matches_pad_and_add(rng, hw, k, s, p):
    """conv._unpool_strided (the s>1 parity-decomposed backward) must be
    bit-identical to the pad-and-add transpose it replaced — same math,
    scatter-free assembly (doc/performance.md, round 3)."""
    from jax import lax

    from cxxnet_tpu.layers import conv as C

    x = jnp.asarray(rng.randn(2, hw, hw + 2, 5).astype(np.float32))
    y = C._maxpool_eq(x, k, k, s, p, p)
    g = jnp.asarray(rng.randn(*y.shape).astype(np.float32))
    (got,) = C._maxpool_eq_bwd(k, k, s, p, p, (x, y), g)

    xp, ((plh, _), (plw, _), oh, ow) = C._pad_for_pool(
        x, k, k, s, p, p, -jnp.inf
    )
    hp, wp = xp.shape[1], xp.shape[2]
    zero = jnp.zeros((), g.dtype)
    total = None
    for (dy, dx), xw in C._shifted_slices(xp, k, k, s, oh, ow):
        contrib = jnp.where(xw == y, g, zero)
        exp = lax.pad(
            contrib, zero,
            ((0, 0, 0),
             (dy, hp - (dy + (oh - 1) * s + 1), s - 1),
             (dx, wp - (dx + (ow - 1) * s + 1), s - 1),
             (0, 0, 0)),
        )
        total = exp if total is None else total + exp
    want = total[:, plh : plh + x.shape[1], plw : plw + x.shape[2], :]
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_insanity_pooling_eval_is_maxpool(rng):
    x = rng.randn(2, 6, 6, 2).astype(np.float32)
    lay = mk("insanity_max_pooling", [("kernel_size", "2"), ("stride", "2"), ("keep", "0.7")])
    (out,), _ = run1(lay, x, train=False)
    np.testing.assert_allclose(out, pool_ref(x, 2, 2, "max"), rtol=1e-5)


def test_insanity_pooling_train_bounded(rng):
    # jittered max-pool output values must come from the input tensor
    x = rng.randn(1, 8, 8, 1).astype(np.float32)
    lay = mk("insanity_max_pooling", [("kernel_size", "2"), ("stride", "2"), ("keep", "0.5")])
    (out,), _ = run1(lay, x, train=True, rng=jax.random.PRNGKey(1))
    assert np.isin(np.round(out, 5), np.round(x, 5)).all()


# ---------------------------------------------------------------- norm


def test_lrn(rng):
    x = rng.randn(2, 4, 4, 6).astype(np.float32)
    n = 5
    alpha, beta, knorm = 0.001, 0.75, 1.0
    lay = mk("lrn", [("local_size", str(n)), ("alpha", str(alpha)), ("beta", str(beta)), ("knorm", str(knorm))])
    (out,), _ = run1(lay, x)
    c = x.shape[-1]
    want = np.zeros_like(x)
    half = n // 2
    for ch in range(c):
        lo, hi = max(0, ch - half), min(c, ch + (n - 1 - half) + 1)
        norm = knorm + alpha / n * (x[..., lo:hi] ** 2).sum(-1)
        want[..., ch] = x[..., ch] * norm ** (-beta)
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("shape", [(8, 5, 5, 3), (16, 7)])
def test_batch_norm(rng, shape):
    x = (rng.randn(*shape) * 3 + 1).astype(np.float32)
    lay = mk("batch_norm", [("init_slope", "1.5"), ("init_bias", "0.2")])
    (out,), _ = run1(lay, x, train=True)
    axes = tuple(range(x.ndim - 1))
    mean, var = x.mean(axes), x.var(axes)
    want = (x - mean) / np.sqrt(var + 1e-10) * 1.5 + 0.2
    np.testing.assert_allclose(out, want, rtol=1e-3, atol=1e-4)
    # reference parity: eval ALSO uses minibatch stats
    (out_eval,), _ = run1(lay, x, train=False)
    np.testing.assert_allclose(out_eval, want, rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("shape", [(8, 5, 5, 3), (16, 7)])
def test_batch_norm_onepass_stats_parity(rng, shape):
    # bn_stats = onepass (E[x^2]-E[x]^2, single read) must match the
    # two-pass default to f32 working precision
    x = (rng.randn(*shape) * 3 + 1).astype(np.float32)
    two = mk("batch_norm", [("init_slope", "1.5"), ("init_bias", "0.2")])
    one = mk("batch_norm", [("init_slope", "1.5"), ("init_bias", "0.2"),
                            ("bn_stats", "onepass")])
    (out2,), _ = run1(two, x, train=True)
    (out1,), _ = run1(one, x, train=True)
    np.testing.assert_allclose(out1, out2, rtol=1e-3, atol=1e-4)


# ---------------------------------------------------------------- elemwise


def test_activations(rng):
    x = rng.randn(3, 5).astype(np.float32)
    for name, fn in [
        ("relu", lambda v: np.maximum(v, 0)),
        ("sigmoid", lambda v: 1 / (1 + np.exp(-v))),
        ("tanh", np.tanh),
        ("softplus", lambda v: np.log1p(np.exp(v))),
    ]:
        (out,), _ = run1(mk(name), x)
        np.testing.assert_allclose(out, fn(x), rtol=1e-5, atol=1e-6)


def test_xelu(rng):
    x = rng.randn(3, 5).astype(np.float32)
    (out,), _ = run1(mk("xelu", [("b", "4")]), x)
    np.testing.assert_allclose(out, np.where(x > 0, x, x / 4), rtol=1e-5)


def test_prelu_eval(rng):
    x = rng.randn(2, 4, 4, 3).astype(np.float32)
    lay = mk("prelu", [("init_slope", "0.25")])
    (out,), params = run1(lay, x)
    np.testing.assert_allclose(out, np.where(x > 0, x, 0.25 * x), rtol=1e-5)
    assert params["bias"].shape == (3,)


def test_insanity_eval(rng):
    x = rng.randn(3, 5).astype(np.float32)
    lay = mk("insanity", [("lb", "4"), ("ub", "8")])
    (out,), _ = run1(lay, x)
    np.testing.assert_allclose(out, np.where(x > 0, x, x / 6.0), rtol=1e-5)


def test_dropout(rng):
    x = np.ones((100, 100), np.float32)
    lay = mk("dropout", [("threshold", "0.4")])
    (out_eval,), _ = run1(lay, x, train=False)
    np.testing.assert_allclose(out_eval, x)
    (out_tr,), _ = run1(lay, x, train=True, rng=jax.random.PRNGKey(3))
    vals = np.unique(np.round(out_tr, 4))
    assert set(vals) <= {0.0, np.float32(np.round(1 / 0.6, 4))}
    assert abs((out_tr == 0).mean() - 0.4) < 0.02


def test_bias_layer(rng):
    x = rng.randn(4, 6).astype(np.float32)
    lay = mk("bias", [("init_bias", "0.5")])
    (out,), params = run1(lay, x)
    np.testing.assert_allclose(out, x + 0.5)
    assert params["bias"].shape == (6,)


# ---------------------------------------------------------------- structure


def test_split():
    lay = mk("split")
    lay.n_split = 3
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    outs, _ = run1(lay, x)
    assert len(outs) == 3
    for o in outs:
        np.testing.assert_allclose(o, x)


def test_concat_flat(rng):
    a = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(3, 2).astype(np.float32)
    outs, _ = run1(mk("concat"), a, extra_inputs=[b])
    np.testing.assert_allclose(outs[0], np.concatenate([a, b], axis=1))


def test_ch_concat(rng):
    a = rng.randn(2, 4, 4, 3).astype(np.float32)
    b = rng.randn(2, 4, 4, 5).astype(np.float32)
    outs, _ = run1(mk("ch_concat"), a, extra_inputs=[b])
    np.testing.assert_allclose(outs[0], np.concatenate([a, b], axis=3))


def test_concat_shape_mismatch(rng):
    lay = mk("ch_concat")
    with pytest.raises(ValueError):
        lay.infer_shape([(2, 4, 4, 3), (2, 5, 4, 5)])


# ---------------------------------------------------------------- losses


def test_softmax_loss_grad_matches_reference(rng):
    x = jnp.asarray(rng.randn(6, 10).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 10, size=(6,)))
    lay = mk("softmax")
    g = jax.grad(lambda v: lay.loss(v, y))(x)
    p = np.asarray(jax.nn.softmax(x, axis=-1))
    want = p.copy()
    want[np.arange(6), np.asarray(y)] -= 1.0
    np.testing.assert_allclose(np.asarray(g), want, rtol=1e-4, atol=1e-6)
    # transform is softmax probs
    (out,), _ = run1(lay, x)
    np.testing.assert_allclose(out, p, rtol=1e-5)


def test_l2_loss_grad(rng):
    x = jnp.asarray(rng.randn(4, 3).astype(np.float32))
    y = jnp.asarray(rng.randn(4, 3).astype(np.float32))
    lay = mk("l2_loss")
    g = jax.grad(lambda v: lay.loss(v, y))(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(x - y), rtol=1e-5)


def test_multi_logistic_grad(rng):
    x = jnp.asarray(rng.randn(4, 3).astype(np.float32))
    y = jnp.asarray((rng.rand(4, 3) > 0.5).astype(np.float32))
    lay = mk("multi_logistic")
    g = jax.grad(lambda v: lay.loss(v, y))(x)
    want = np.asarray(jax.nn.sigmoid(x)) - np.asarray(y)
    np.testing.assert_allclose(np.asarray(g), want, rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------- pairtest & registry


def test_pairtest_identical_masters(rng):
    lay = L.create_layer("pairtest-relu-relu")
    x = jnp.asarray(rng.randn(3, 5).astype(np.float32))
    lay.infer_shape([x.shape])
    err = lay.compare({}, [x])
    assert float(err) == 0.0


def test_pairtest_divergent(rng):
    lay = L.create_layer("pairtest-relu-sigmoid")
    x = jnp.asarray(rng.randn(3, 5).astype(np.float32))
    lay.infer_shape([x.shape])
    assert float(lay.compare({}, [x])) > 0.01


def test_registry_covers_reference_zoo():
    want = {
        "fullc", "fixconn", "bias", "softmax", "relu", "sigmoid", "tanh",
        "softplus", "flatten", "dropout", "conv", "relu_max_pooling",
        "max_pooling", "sum_pooling", "avg_pooling", "lrn", "concat",
        "split", "xelu", "insanity", "insanity_max_pooling", "l2_loss",
        "multi_logistic", "ch_concat", "prelu", "batch_norm",
    }
    assert want <= set(L.layer_types())


def test_unknown_layer_type():
    with pytest.raises(ValueError):
        L.create_layer("wombat")


# ---------------------------------------------------------------- init rules


def test_init_distributions():
    import math

    p = L.LayerParam()
    key = jax.random.PRNGKey(0)
    p.random_type, p.init_sigma = 0, 0.05
    w = p.rand_init_weight(key, (200, 200), 200, 200)
    assert abs(float(jnp.std(w)) - 0.05) < 0.005
    p.random_type = 1  # xavier uniform: a = sqrt(3/(in+out))
    w = p.rand_init_weight(key, (200, 200), 100, 100)
    a = math.sqrt(3.0 / 200)
    assert float(jnp.max(jnp.abs(w))) <= a + 1e-6
    assert float(jnp.max(jnp.abs(w))) > 0.8 * a
    p.random_type = 2  # kaiming from nhidden
    p.num_hidden = 50
    w = p.rand_init_weight(key, (200, 200), 0, 0)
    assert abs(float(jnp.std(w)) - math.sqrt(2.0 / 50)) < 0.02


@pytest.mark.parametrize("variant", ["1", "2"])
@pytest.mark.parametrize(
    "hw,p,cin,cout",
    [(14, 1, 12, 8),   # VGG-shaped: pad 1, extent not a multiple of 4
     (16, 1, 16, 8),   # oh=16: exact tile multiple
     (9, 0, 9, 4),     # VALID pad, odd extent, odd cin
     (12, 1, 8, 8),    # cin exactly at the >=8 rewrite gate
     (7, 1, 10, 6)],   # tiny: single partial tile row
)
def test_conv_winograd_matches_direct(rng, hw, p, cin, cout, variant):
    """conv_wino=1 (Winograd F(4x4,3x3), pure-XLA) must match the direct
    3x3 s1 conv — outputs and weight/input gradients — over tile-exact
    and tile-ragged extents.  f32 tolerance covers the transform's
    mild error amplification (A^T rows reach |.|=8)."""
    x = rng.randn(2, hw, hw + 3, cin).astype(np.float32)
    base = mk("conv", [("kernel_size", "3"), ("stride", "1"),
                       ("pad", str(p)), ("nchannel", str(cout))])
    wino = mk("conv", [("kernel_size", "3"), ("stride", "1"),
                       ("pad", str(p)), ("nchannel", str(cout)),
                       ("conv_wino", variant)])
    params = base.init_params(jax.random.PRNGKey(0), [x.shape])
    ya = base.apply(params, [jnp.asarray(x)])[0]
    yb = wino.apply(params, [jnp.asarray(x)])[0]
    assert ya.shape == yb.shape
    np.testing.assert_allclose(np.asarray(ya), np.asarray(yb),
                               rtol=2e-4, atol=2e-4)

    def loss(lay, pr, v):
        return (lay.apply(pr, [v])[0] ** 2).sum()

    ga = jax.grad(loss, argnums=(1, 2))(base, params, jnp.asarray(x))
    gb = jax.grad(loss, argnums=(1, 2))(wino, params, jnp.asarray(x))
    for a, b in zip(jax.tree_util.tree_leaves(ga),
                    jax.tree_util.tree_leaves(gb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


def test_conv_winograd_ignored_off_domain(rng):
    """conv_wino on a strided / non-3x3 / grouped conv silently keeps
    the direct path (the knob is a 3x3-s1-only rewrite)."""
    x = rng.randn(2, 12, 12, 4).astype(np.float32)
    # cin=4 < 8: even a 3x3 s1 conv keeps the direct path (MXU K gate)
    for extra in ([("kernel_size", "3"), ("stride", "2"), ("pad", "1")],
                  [("kernel_size", "3"), ("stride", "1"), ("pad", "1")],
                  [("kernel_size", "5"), ("stride", "1"), ("pad", "2")]):
        base = mk("conv", extra + [("nchannel", "8")])
        wino = mk("conv", extra + [("nchannel", "8"), ("conv_wino", "1")])
        params = base.init_params(jax.random.PRNGKey(1), [x.shape])
        np.testing.assert_array_equal(
            np.asarray(base.apply(params, [jnp.asarray(x)])[0]),
            np.asarray(wino.apply(params, [jnp.asarray(x)])[0]))


def test_conv_winograd_bf16_error_profile(rng):
    """bf16 numerics contract of the two Winograd tiles vs the direct
    bf16 conv (yardstick = each path's max error against the f32
    direct conv): F(2x2) ('conv_wino = 2', transform constants in
    {0, +-1, 1/2}) stays within ~3x of direct; F(4x4) ('conv_wino = 1',
    constants up to |8|) is the max-FLOP-win tile and is allowed the
    known fp16-winograd amplification, bounded here at 25x (measured
    ~15x) so a real regression still fails."""
    x = rng.randn(2, 14, 14, 16).astype(np.float32)
    cfg = [("kernel_size", "3"), ("stride", "1"), ("pad", "1"),
           ("nchannel", "16")]
    base = mk("conv", cfg)
    params = base.init_params(jax.random.PRNGKey(2), [x.shape])
    ref = np.asarray(base.apply(params, [jnp.asarray(x)])[0])
    xb = jnp.asarray(x).astype(jnp.bfloat16)

    def err(lay):
        out = lay.apply(params, [xb])[0].astype(jnp.float32)
        return np.abs(np.asarray(out) - ref).max()

    e_direct = err(base)
    e_f2 = err(mk("conv", cfg + [("conv_wino", "2")]))
    e_f4 = err(mk("conv", cfg + [("conv_wino", "1")]))
    assert e_f2 <= 3 * e_direct + 1e-3, (e_f2, e_direct)
    assert e_f4 <= 25 * e_direct + 1e-3, (e_f4, e_direct)
