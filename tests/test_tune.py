"""Self-tuning runtime tests: controller decision loop, runtime knob
resize (decode pool, micro-batcher), speculative prewarm, and the
double-buffered device feed's bitwise neutrality.

The controller tests drive :meth:`KnobController.step_once` manually
with a synthetic clock and a simulated environment (knob value →
throughput), so every decision sequence is deterministic.
"""

import itertools
import os
import tempfile

import numpy as np
import pytest

from cxxnet_tpu import config as cfgmod
from cxxnet_tpu.tune import (
    Knob,
    KnobController,
    band_verdict,
    batcher_knobs,
    find_pipeline,
    options_from_cfg,
    pipeline_knobs,
)


# ----------------------------------------------------------------------
# primitives
def test_band_verdict_orientation():
    assert band_verdict(120, 100, 0.1) == "better"
    assert band_verdict(80, 100, 0.1) == "worse"
    assert band_verdict(105, 100, 0.1) == "noise"
    # lower-is-better flips the directions (latencies)
    assert band_verdict(80, 100, 0.1, lower_is_better=True) == "better"
    assert band_verdict(120, 100, 0.1, lower_is_better=True) == "worse"
    # nothing can be concluded against a missing/zero baseline
    assert band_verdict(50, None, 0.1) == "noise"
    assert band_verdict(50, 0.0, 0.1) == "noise"


def test_knob_propose_clamps_and_rounds():
    store = {"v": 3}
    k = Knob("k", lambda: store["v"], lambda v: store.__setitem__("v", v),
             lo=1, hi=8)
    assert k.propose(+1) == 6
    assert k.propose(-1) == 2  # 3/2 rounds to 2
    store["v"] = 8
    assert k.propose(+1) is None  # pinned at hi
    store["v"] = 1
    assert k.propose(-1) is None  # pinned at lo
    store["v"] = 7
    assert k.propose(+1) == 8  # clamped, still a move
    f = Knob("f", lambda: 2.0, lambda v: None, lo=0.25, hi=50.0,
             integer=False)
    assert f.propose(+1) == 4.0
    assert f.propose(-1) == 1.0


def test_options_from_cfg():
    opt = options_from_cfg([
        ("controller", "1"), ("tune_period_s", "0.5"),
        ("tune_band", "0.2"), ("tune_targets", "batcher"),
    ])
    assert opt.enabled == 1
    assert opt.period_s == 0.5
    assert opt.band == 0.2
    assert opt.wants("batcher") and not opt.wants("pipeline")
    assert options_from_cfg([]).wants("pipeline")  # auto = everything


# ----------------------------------------------------------------------
# decision loop (synthetic environment: knob value -> rows/sec)
def _drive(ctrl, work, rate_fn, ticks, t0=0.0):
    """Advance a simulated second per tick: accumulate work at the
    CURRENT knob setting, then let the controller observe it."""
    t = t0
    decisions = []
    for _ in range(ticks):
        t += 1.0
        work[0] += rate_fn()
        decisions.append(ctrl.step_once(now=t))
    return decisions, t


def test_controller_climbs_to_plateau():
    state = {"w": 1}
    work = [0.0]
    k = Knob("w", lambda: state["w"],
             lambda v: state.__setitem__("w", v), lo=1, hi=16)
    ctrl = KnobController(lambda: work[0], [k], band=0.1,
                          measure_ticks=2, settle_ticks=1,
                          cooldown_ticks=4, name="t_climb")
    decisions, _ = _drive(ctrl, work,
                          lambda: 100.0 * min(state["w"], 4), 40)
    assert state["w"] == 4  # the plateau knee, not the hi bound
    actions = [d["action"] for d in decisions]
    assert "adjust" in actions and "keep" in actions
    # the move past the knee (4 -> 8) measured as noise and was REVERTED
    assert "revert" in actions


def test_controller_rolls_back_regression_and_flips():
    state = {"w": 4}
    work = [0.0]
    k = Knob("w", lambda: state["w"],
             lambda v: state.__setitem__("w", v), lo=1, hi=16)
    ctrl = KnobController(lambda: work[0], [k], band=0.1,
                          measure_ticks=2, settle_ticks=1,
                          cooldown_ticks=4, name="t_rollback")
    decisions, _ = _drive(ctrl, work, lambda: 100.0 / state["w"], 40)
    actions = [d["action"] for d in decisions]
    assert "rollback" in actions  # the up-probe regressed and reverted
    assert state["w"] == 1        # then climbed DOWN to the optimum


def test_controller_hysteresis_no_oscillation_on_noise():
    state = {"w": 4}
    work = [0.0]
    noise = itertools.cycle([0.97, 1.04, 1.0, 0.95, 1.05])
    k = Knob("w", lambda: state["w"],
             lambda v: state.__setitem__("w", v), lo=1, hi=16)
    ctrl = KnobController(lambda: work[0], [k], band=0.15,
                          measure_ticks=2, settle_ticks=1,
                          cooldown_ticks=6, name="t_noise")
    seen = set()
    t = 0.0
    kept = 0
    for _ in range(80):
        t += 1.0
        work[0] += 100.0 * next(noise)
        d = ctrl.step_once(now=t)
        kept += d["action"] == "keep"
        seen.add(state["w"])
    # every probe was reverted: the value always returns to 4 and no
    # move was ever KEPT on noise — no drift, bounded oscillation
    assert state["w"] == 4
    assert kept == 0
    assert seen <= {2, 4, 8}
    # after both directions failed, the knob cooled down: far fewer
    # probes than free oscillation (80 ticks / ~5-tick decisions)
    snap = ctrl.snapshot()
    assert snap["knobs"]["w"] == 4


def test_controller_round_robins_multiple_knobs():
    state = {"a": 1, "b": 1}
    work = [0.0]
    ka = Knob("a", lambda: state["a"],
              lambda v: state.__setitem__("a", v), lo=1, hi=8)
    kb = Knob("b", lambda: state["b"],
              lambda v: state.__setitem__("b", v), lo=1, hi=8)
    ctrl = KnobController(lambda: work[0], [ka, kb], band=0.1,
                          measure_ticks=2, settle_ticks=1,
                          cooldown_ticks=2, name="t_rr")
    # both knobs contribute independently; both should climb to the
    # knee and stay there (modulo the bounded hysteresis probes that
    # may be in flight at whatever tick the loop happens to stop)
    hist_a, hist_b = [], []
    t = 0.0
    for _ in range(120):
        t += 1.0
        work[0] += (50.0 * min(state["a"], 4)
                    + 50.0 * min(state["b"], 4))
        ctrl.step_once(now=t)
        hist_a.append(state["a"])
        hist_b.append(state["b"])
    for hist in (hist_a, hist_b):
        tail = hist[60:]
        assert max(tail, key=tail.count) == 4  # the settled value
        assert 2 <= min(tail) and max(tail) <= 8  # probes stay bounded


def test_controller_emits_events_and_gauges():
    from cxxnet_tpu.obs import recent
    from cxxnet_tpu.obs.registry import registry

    state = {"w": 1}
    work = [0.0]
    k = Knob("evt_w", lambda: state["w"],
             lambda v: state.__setitem__("w", v), lo=1, hi=8)
    ctrl = KnobController(lambda: work[0], [k], band=0.1,
                          measure_ticks=1, settle_ticks=0,
                          cooldown_ticks=2, name="t_events")
    _drive(ctrl, work, lambda: 100.0 * min(state["w"], 2), 12)
    kinds = [e["kind"] for e in recent(100)]
    assert "tune.adjust" in kinds
    snap = registry().snapshot()
    eff = snap.get("tune_effective", {})
    assert f'tune_effective{{knob="evt_w"}}' in eff
    assert eff[f'tune_effective{{knob="evt_w"}}'] == state["w"]
    assert any(name.startswith("tune_adjustments_total")
               for name in snap.get("tune_adjustments_total", {}))


def test_stop_rolls_back_unconcluded_probe():
    """A stop() landing between adjust and conclude must restore the
    pre-probe value — the autotune verdicts read snapshot()['knobs']
    as the chosen configuration."""
    state = {"w": 4}
    work = [0.0]
    k = Knob("w", lambda: state["w"],
             lambda v: state.__setitem__("w", v), lo=1, hi=16)
    ctrl = KnobController(lambda: work[0], [k], band=0.1,
                          measure_ticks=2, settle_ticks=1,
                          cooldown_ticks=4, name="t_stop")
    t = 0.0
    # drive exactly until a probe is APPLIED (action == adjust), then stop
    for _ in range(20):
        t += 1.0
        work[0] += 100.0
        if ctrl.step_once(now=t)["action"] == "adjust":
            break
    assert state["w"] != 4  # probe applied
    ctrl.stop()
    assert state["w"] == 4  # restored
    assert ctrl.snapshot()["knobs"]["w"] == 4


def test_consecutive_shrinks_never_over_poison():
    """Back-to-back request_workers() shrinks must account for poison
    tokens still in flight: the pool keeps >= target workers and the
    consumer never wedges."""
    with tempfile.TemporaryDirectory() as wd:
        _imgbin(wd)
        it = _chain(wd, 32, 4, queue_depth=2)
        assert it.effective_workers() == 4
        # three shrinks in a row before any token can be consumed
        it.request_workers(3)
        it.request_workers(2)
        it.request_workers(1)
        assert it._poison_pending <= 3  # never more tokens than surplus
        got = _epoch_stream(it)         # consumer must not wedge
        assert len(got) > 0
        assert it.effective_workers() >= 1
        # growth after the shrink burst converges back up
        it.request_workers(3)
        got2 = _epoch_stream(it)
        assert len(got2) == len(got)
        assert it.effective_workers() == 3
        it.close()


def test_controller_objective_error_is_survivable():
    def broken():
        raise RuntimeError("boom")

    k = Knob("x", lambda: 1, lambda v: None, lo=1, hi=4)
    ctrl = KnobController(broken, [k], name="t_broken")
    assert ctrl.step_once(now=1.0)["action"] == "error"
    assert ctrl.step_once(now=2.0)["action"] == "error"


# ----------------------------------------------------------------------
# runtime pipeline resize
def _imgbin(workdir, n=48, size=32):
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import io_bench

    io_bench.generate_imgbin(workdir, n, size)


def _chain(workdir, size, workers, queue_depth=0):
    from cxxnet_tpu.io.augment import AugmentIterator
    from cxxnet_tpu.io.imgbin import ImageBinIterator
    from cxxnet_tpu.io.pipeline import ParallelAugmentIterator

    crop = size - size // 8
    it = ParallelAugmentIterator(AugmentIterator(ImageBinIterator()))
    for k, v in [
        ("image_bin", f"{workdir}/bench.bin"),
        ("image_list", f"{workdir}/bench.lst"),
        ("num_decode_workers", str(workers)),
        ("silent", "1"),
        ("rand_crop", "1"),
        ("rand_mirror", "1"),
        ("input_shape", f"3,{crop},{crop}"),
        ("batch_size", "8"),
        ("label_width", "1"),
    ]:
        it.set_param(k, v)
    if queue_depth:
        it.set_param("decode_queue_depth", str(queue_depth))
    it.init()
    return it


def _epoch_stream(it, epoch=7):
    """One epoch's instances, with the augmentation epoch ANCHORED so
    streams from different iterators / rewind counts compare bitwise
    (the same augment_epoch contract the CLI round loop uses)."""
    out = []
    it.before_first()
    it.set_param("augment_epoch", str(epoch))
    while it.next():
        v = it.value()
        out.append((v.index, np.array(v.data), np.array(v.label)))
    return out


def test_pipeline_runtime_resize_bitwise_and_thread_counts():
    with tempfile.TemporaryDirectory() as wd:
        _imgbin(wd)
        serial = _chain(wd, 32, 0)
        ref = _epoch_stream(serial)
        serial.close()

        it = _chain(wd, 32, 2, queue_depth=1)
        assert it.effective_workers() == 2
        # grow mid-run (applies immediately on a live pool)
        it.request_workers(4)
        it.set_queue_depth(4)
        got = _epoch_stream(it)
        assert it.effective_workers() == 4
        # shrink: poison tokens retire surplus workers
        it.request_workers(1)
        got2 = _epoch_stream(it)
        deadline_threads = it.effective_workers()
        assert deadline_threads <= 2  # drains toward 1; never below
        it.close()
    for a, b in ((got, ref), (got2, ref)):
        assert len(a) == len(b)
        for (ia, da, la), (ib, db, lb) in zip(a, b):
            assert ia == ib and la == lb
            assert np.array_equal(da, db)  # resize is bitwise-neutral


def test_pipeline_serial_to_pool_at_epoch_boundary():
    with tempfile.TemporaryDirectory() as wd:
        _imgbin(wd)
        it = _chain(wd, 32, 1)  # serial pass-through (no pool)
        ref = _epoch_stream(it)
        assert it.effective_workers() == 0
        it.request_workers(2)
        assert it.effective_workers() == 0  # mid-epoch: deferred
        got = _epoch_stream(it)             # before_first grew the pool
        assert it.effective_workers() == 2
        it.close()
    assert len(got) == len(ref)
    for (ia, da, la), (ib, db, lb) in zip(got, ref):
        assert ia == ib and np.array_equal(da, db)


def test_find_pipeline_walks_chain():
    from cxxnet_tpu.io.data import create_iterator

    with tempfile.TemporaryDirectory() as wd:
        _imgbin(wd)
        crop = 32 - 32 // 8
        it = create_iterator([
            ("iter", "imgbin"),
            ("image_bin", f"{wd}/bench.bin"),
            ("image_list", f"{wd}/bench.lst"),
            ("silent", "1"),
            ("input_shape", f"3,{crop},{crop}"),
            ("batch_size", "8"),
            ("label_width", "1"),
            ("iter", "threadbuffer"),
            ("iter", "end"),
        ])
        pipe = find_pipeline(it)
        assert pipe is not None
        knobs = pipeline_knobs(pipe)
        assert [k.name for k in knobs] == ["num_decode_workers",
                                           "decode_queue_depth"]
        it.close()


# ----------------------------------------------------------------------
# serve-side live knobs + prewarm
MLP_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:a1] = relu:a1
layer[a1->out] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = 32
dev = cpu
eta = 0.1
"""


def _engine(**kw):
    from cxxnet_tpu import serve
    from cxxnet_tpu.nnet.trainer import NetTrainer

    tr = NetTrainer()
    tr.set_params(cfgmod.parse_pairs(MLP_CFG))
    tr.set_param("seed", "0")
    tr.init_model()
    kw.setdefault("max_batch_size", 32)
    kw.setdefault("batch_timeout_ms", 1.0)
    return serve.Engine(trainer=tr, **kw)


def test_batcher_live_setters_and_statsz():
    eng = _engine()
    try:
        out1 = eng.predict(np.zeros((4, 16), np.float32))
        eng.set_max_batch_size(8, prewarm=False)
        eng.set_batch_timeout_ms(0.5)
        assert eng.batcher.max_batch_size == 8
        assert eng.batcher.batch_timeout == pytest.approx(0.5e-3)
        out2 = eng.predict(np.zeros((4, 16), np.float32))
        assert np.array_equal(np.asarray(out1), np.asarray(out2))
        stats = eng.snapshot_stats()
        assert stats["tune_effective"]["max_batch_size"] == 8
        assert stats["tune_effective"]["batch_timeout_ms"] == \
            pytest.approx(0.5)
        # request-shape histogram: 4-row requests land in bucket 4
        assert stats["request_buckets"].get("4") == 2
        # clamped to the engine's configured capacity
        assert eng.set_max_batch_size(10_000, prewarm=False) == 32
        from cxxnet_tpu.obs.registry import registry

        eff = registry().snapshot()["tune_effective"]
        assert eff['tune_effective{knob="max_batch_size"}'] == 32
    finally:
        eng.close()


def test_engine_prewarm_from_histogram():
    eng = _engine()
    try:
        eng.predict(np.zeros((3, 16), np.float32))  # bucket 4 (now warm)
        # histogram-driven prewarm: nothing new -> no work
        assert eng.prewarm_buckets() == []
        # a pending bigger bucket in the histogram, not yet compiled
        with eng._req_lock:
            eng._req_buckets[(16, (16,))] = 5
        assert eng.prewarm_buckets() == [16]
        cache_buckets = {k[3] for k in eng._cache.keys_snapshot()}
        assert 16 in cache_buckets
        assert eng.prewarm_buckets() == []  # idempotent
        # buckets above the live limit are never compiled speculatively
        eng.set_max_batch_size(4, prewarm=False)
        with eng._req_lock:
            eng._req_buckets[(32, (16,))] = 9
        assert eng.prewarm_buckets() == []
    finally:
        eng.close()


def test_prewarm_is_row_shape_aware():
    """Programs specialize per row shape: a bucket warm for one shape
    must not mark another shape's program warm (the flat wrapper
    spelling vs the native shape are distinct compiles)."""
    eng = _engine()
    try:
        # simulate traffic of a hypothetical second row shape in the
        # histogram: the warm-check must key on (bucket, shape)
        assert eng._warm_bucket(8, (16,)) is True
        assert eng._warm_bucket(8, (16,)) is False   # now warm
        assert eng._dominant_row_shape() == (16,)    # native fallback
        eng.predict(np.zeros((2, 16), np.float32))
        assert eng._dominant_row_shape() == (16,)
    finally:
        eng.close()


def test_set_max_batch_prewarms_before_apply():
    eng = _engine()
    try:
        eng.predict(np.zeros((1, 16), np.float32))
        before = {k[3] for k in eng._cache.keys_snapshot()}
        assert 16 not in before
        eng.set_max_batch_size(16)  # prewarm=True default
        after = {k[3] for k in eng._cache.keys_snapshot()}
        assert 16 in after
    finally:
        eng.close()


def test_batcher_knobs_bind_engine():
    eng = _engine()
    try:
        knobs = {k.name: k for k in batcher_knobs(eng)}
        assert knobs["max_batch_size"].hi == 32
        knobs["max_batch_size"].apply(8)
        assert eng.batcher.max_batch_size == 8
        knobs["batch_timeout_ms"].apply(4.0)
        assert eng.batcher.batch_timeout == pytest.approx(4e-3)
    finally:
        eng.close()


# ----------------------------------------------------------------------
# double-buffered device feed
def test_stage_batch_bitwise_neutral():
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer

    def make():
        tr = NetTrainer()
        tr.set_params(cfgmod.parse_pairs(MLP_CFG))
        tr.set_param("seed", "0")
        tr.set_param("eval_train", "0")
        tr.set_param("batch_size", "8")
        tr.init_model()
        return tr

    rng = np.random.RandomState(0)
    batches = [
        (rng.randn(8, 16).astype(np.float32),
         rng.randint(0, 4, (8, 1)).astype(np.float32))
        for _ in range(5)
    ]
    plain = make()
    for d, l in batches:
        plain.update(DataBatch(data=d, label=l))
    plain.sync()

    staged = make()
    prev = None
    for d, l in batches:
        nxt = DataBatch(data=d.copy(), label=l.copy())
        if prev is not None:
            staged.update(prev)       # step N dispatched...
            assert staged.stage_batch(nxt)  # ...H2D of N+1 overlaps it
        prev = nxt
    staged.update(prev)
    staged.sync()

    import jax

    for key in plain.params:
        for tag in plain.params[key]:
            wa = np.asarray(jax.device_get(plain.params[key][tag]))
            wb = np.asarray(jax.device_get(staged.params[key][tag]))
            assert np.array_equal(wa, wb), (key, tag)


def test_stage_batch_mismatch_falls_back():
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer

    tr = NetTrainer()
    tr.set_params(cfgmod.parse_pairs(MLP_CFG))
    tr.set_param("seed", "0")
    tr.set_param("eval_train", "0")
    tr.set_param("batch_size", "8")
    tr.init_model()
    rng = np.random.RandomState(1)
    a = DataBatch(data=rng.randn(8, 16).astype(np.float32),
                  label=np.zeros((8, 1), np.float32))
    b = DataBatch(data=rng.randn(8, 16).astype(np.float32),
                  label=np.ones((8, 1), np.float32))
    assert tr.stage_batch(a)
    tr.update(b)   # a DIFFERENT batch: staged arrays must be dropped
    assert tr._staged is None
    tr.update(a)   # and this transfers fresh (no stale reuse)
    tr.sync()
