"""Data parallelism over the 8-virtual-device CPU mesh.

TPU analog of the reference's multi-GPU path: batch split across devices,
gradients combined (mshadow-ps local shared model,
``nnet_impl-inl.hpp:141-185``).  Here the split/combine is XLA SPMD; these
tests assert (a) the dev= grammar, (b) that a sharded train step runs and
shards what it should, and (c) the §4.3 discipline: multi-device training
produces the same weights as single-device (the reference checked this
with ``test_on_server=1`` / ``CheckWeight_``).
"""

import numpy as np
import pytest
import jax

from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.parallel import make_mesh, parse_device


def _assert_params_close(ta, tb, what="1- and 8-device runs"):
    """Per-(layer, tag) weight comparison shared by every parity test."""
    for key in ta.params:
        for tag in ta.params[key]:
            np.testing.assert_allclose(
                np.asarray(ta.params[key][tag]),
                np.asarray(tb.params[key][tag]),
                rtol=2e-4, atol=2e-5,
                err_msg=f"{key}/{tag} diverged between {what}",
            )


def test_parse_device():
    assert parse_device("tpu") == ("tpu", [0])
    assert parse_device("gpu:0-3") == ("gpu", [0, 1, 2, 3])
    assert parse_device("tpu:0,2,5") == ("tpu", [0, 2, 5])
    assert parse_device("cpu:1-2,4") == ("cpu", [1, 2, 4])


def test_make_mesh_counts():
    plan = make_mesh("tpu:0-7")
    assert plan.n_data == 8 and plan.n_model == 1
    plan = make_mesh("tpu:0-7", model_parallel=2)
    assert plan.n_data == 4 and plan.n_model == 2
    with pytest.raises(ValueError):
        make_mesh("tpu:0-7", model_parallel=3)
    with pytest.raises(ValueError):
        make_mesh("tpu:0-99")


def test_batch_divisibility_check():
    plan = make_mesh("tpu:0-7")
    plan.check_batch(16)
    with pytest.raises(ValueError):
        plan.check_batch(12)


MLP_CFG = [
    ("dev", "tpu:0-{n}"),
    ("batch_size", "16"),
    ("input_shape", "1,1,10"),
    ("seed", "7"),
    ("eta", "0.1"),
    ("momentum", "0.9"),
    ("netconfig", "start"),
    ("layer[0->1]", "fullc:fc1"),
    ("nhidden", "32"),
    ("layer[1->2]", "sigmoid"),
    ("layer[2->3]", "fullc:fc2"),
    ("nhidden", "4"),
    ("layer[3->3]", "softmax"),
    ("netconfig", "end"),
]


def _train(ndev: int, steps: int = 5):
    cfg = [(k, v.format(n=ndev - 1) if k == "dev" else v) for k, v in MLP_CFG]
    tr = NetTrainer()
    tr.set_params(cfg)
    tr.init_model()
    rng = np.random.RandomState(0)
    data = rng.randn(steps, 16, 10).astype(np.float32)
    labels = rng.randint(0, 4, size=(steps, 16, 1)).astype(np.float32)
    for i in range(steps):
        tr.update_all(data[i], labels[i])
    return tr


def test_multi_device_matches_single():
    """§4.3 analog: 8-way DP training == single-device training."""
    t1 = _train(1)
    t8 = _train(8)
    _assert_params_close(t1, t8, "1- and 8-device runs")


CONV_S2D_LRN_CFG = [
    ("dev", "tpu:0-{n}"),
    ("batch_size", "16"),
    ("input_shape", "3,10,10"),
    ("eta", "0.1"),
    ("momentum", "0.9"),
    ("netconfig", "start"),
    ("layer[0->1]", "conv:cv1"),
    ("kernel_size", "3"),
    ("stride", "2"),
    ("pad", "1"),
    ("nchannel", "8"),
    ("random_type", "xavier"),
    ("conv_s2d", "1"),
    ("layer[1->1]", "relu"),
    ("layer[1->2]", "lrn"),
    ("local_size", "5"),
    ("lrn_impl", "matmul"),
    ("layer[2->3]", "flatten"),
    ("layer[3->4]", "fullc:fc"),
    ("nhidden", "4"),
    ("random_type", "xavier"),
    ("layer[4->4]", "softmax"),
    ("netconfig", "end"),
]


def _train_s2d(ndev: int, steps: int = 4):
    cfg = [(k, v.format(n=ndev - 1) if k == "dev" else v)
           for k, v in CONV_S2D_LRN_CFG]
    tr = NetTrainer()
    tr.set_params(cfg)
    tr.init_model()
    rng = np.random.RandomState(3)
    data = rng.randn(steps, 16, 10, 10, 3).astype(np.float32)
    labels = rng.randint(0, 4, size=(steps, 16, 1)).astype(np.float32)
    for i in range(steps):
        tr.update_all(data[i], labels[i])
    return tr


def test_conv_s2d_and_matmul_lrn_match_single_under_dp():
    """The space-to-depth conv rewrite and banded-GEMM LRN partition
    cleanly under GSPMD: 8-way DP == single device."""
    t1 = _train_s2d(1)
    t8 = _train_s2d(8)
    _assert_params_close(t1, t8, "1- and 8-device runs")


def test_step_output_is_sharded():
    """Batch-major arrays really are split over the 8-device data axis."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    tr = _train(8, steps=1)
    assert tr.mesh_plan is not None and tr.mesh_plan.n_data == 8
    # params stay replicated
    leaf = jax.tree_util.tree_leaves(tr.params)[0]
    assert leaf.sharding.is_fully_replicated
    # the eval output is data-sharded over all 8 devices
    out = tr._eval_fn()(tr.params, tr.aux, jnp.zeros((16, 10), jnp.float32), ())
    assert out.sharding.spec == P("data")
    assert len(out.sharding.device_set) == 8


def test_indivisible_batch_raises():
    cfg = [(k, v) for k, v in MLP_CFG]
    cfg[0] = ("dev", "tpu:0-4")  # 5 devices, batch 16
    tr = NetTrainer()
    tr.set_params(cfg)
    with pytest.raises(ValueError):
        tr.init_model()


def _train_tp(ndev: int, model_parallel: int, steps: int = 5, extra=()):
    cfg = [(k, v.format(n=ndev - 1) if k == "dev" else v) for k, v in MLP_CFG]
    cfg.append(("model_parallel", str(model_parallel)))
    cfg.extend(extra)
    tr = NetTrainer()
    tr.set_params(cfg)
    tr.init_model()
    rng = np.random.RandomState(0)
    data = rng.randn(steps, 16, 10).astype(np.float32)
    labels = rng.randint(0, 4, size=(steps, 16, 1)).astype(np.float32)
    for i in range(steps):
        tr.update_all(data[i], labels[i])
    return tr


def test_tensor_parallel_matches_single():
    """TP over the model axis computes the same weights as 1 device."""
    t1 = _train(1)
    ttp = _train_tp(8, 4)  # 2-way data x 4-way tensor parallel
    assert ttp.mesh_plan.n_model == 4 and ttp.mesh_plan.n_data == 2
    _assert_params_close(t1, ttp, "DP and DPxTP runs")


def test_2x2_mesh_trains_end_to_end():
    """THE 2x2 data x model mesh (ROADMAP item 1 acceptance): 4 devices
    split (2, 2), sharded weight update on, a net trained end to end,
    weights matching the 1-device run."""
    t1 = _train(1)
    t22 = _train_tp(4, 2, extra=(("shard_weight_update", "1"),))
    assert t22.mesh_plan.n_data == 2 and t22.mesh_plan.n_model == 2
    # TP placement holds AND the updater state took the data-axis
    # sharding on top of it (ZeRO-1 over the 2x2 mesh)
    m = t22.ustates["l0_fc1"]["wmat"]["m"]  # (32, 10)
    assert set(m.sharding.spec) >= {"model", "data"}
    _assert_params_close(t1, t22, "1-device and 2x2-mesh runs")


def test_tensor_parallel_weights_are_sharded():
    from jax.sharding import PartitionSpec as P

    ttp = _train_tp(8, 4)
    w = ttp.params["l0_fc1"]["wmat"]  # (32, 10): nhidden 32 % 4 == 0
    assert w.sharding.spec == P("model", None)
    m = ttp.ustates["l0_fc1"]["wmat"]["m"]  # momentum sharded like w
    assert m.sharding.spec == P("model", None)
    # predictions still correct shape through the sharded eval path
    pred = ttp.predict(
        __import__("cxxnet_tpu.io.data", fromlist=["DataBatch"]).DataBatch(
            data=np.zeros((16, 10), np.float32),
            label=np.zeros((16, 1), np.float32),
        )
    )
    assert pred.shape == (16,)


def test_update_on_server_zero1_state_sharding():
    """update_on_server=1 -> optimizer state sharded over the data axis
    (the server-side-SGD analog); training still matches single-device."""
    from jax.sharding import PartitionSpec as P

    cfg = [(k, v.format(n=7) if k == "dev" else v) for k, v in MLP_CFG]
    cfg.append(("update_on_server", "1"))
    tr = NetTrainer()
    tr.set_params(cfg)
    tr.init_model()
    rng = np.random.RandomState(0)
    data = rng.randn(5, 16, 10).astype(np.float32)
    labels = rng.randint(0, 4, size=(5, 16, 1)).astype(np.float32)
    for i in range(5):
        tr.update_all(data[i], labels[i])
    # momentum for fc1 (32,10): dim0 32 % 8 == 0 -> sharded over data
    m = tr.ustates["l0_fc1"]["wmat"]["m"]
    assert m.sharding.spec == P("data", None)
    t1 = _train(1)
    _assert_params_close(t1, tr, "update_on_server")


def test_tp_step_never_allgathers_weights():
    """Communication sanity for tensor parallelism (VERDICT r1 #7): the
    compiled fused step may all-gather *activations* (channel-sharded
    conv/fullc outputs re-assembling for the next layer) but must never
    all-gather a weight-shaped tensor per step — weights stay sharded on
    the model axis for the whole program."""
    import collections
    import re

    import jax.numpy as jnp

    cfg = [
        ("dev", "cpu:0-7"), ("model_parallel", "2"), ("batch_size", "16"),
        ("input_shape", "3,16,16"), ("eta", "0.1"),
        ("netconfig", "start"),
        ("layer[0->1]", "conv:c1"), ("kernel_size", "3"), ("pad", "1"),
        ("nchannel", "64"),
        ("layer[1->2]", "relu"),
        ("layer[2->3]", "flatten"),
        ("layer[3->4]", "fullc:fc1"), ("nhidden", "128"),
        ("layer[4->5]", "relu"),
        ("layer[5->6]", "fullc:fc2"), ("nhidden", "10"),
        ("layer[6->6]", "softmax"),
        ("netconfig", "end"),
    ]
    tr = NetTrainer()
    tr.set_params(cfg)
    tr.init_model()
    fn = tr._fused_step_fn()
    rng = np.random.RandomState(0)
    d = jnp.asarray(rng.randn(16, 16, 16, 3).astype(np.float32))
    l = jnp.asarray(rng.randint(0, 10, (16, 1)).astype(np.float32))
    mask = jnp.asarray(np.ones(16, np.float32))
    txt = fn.lower(
        tr.params, tr.ustates, tr.aux, d, l, mask,
        jax.random.PRNGKey(0), jnp.asarray(0, jnp.int32), (),
    ).compile().as_text()

    weight_shapes = set()
    for tags in jax.tree_util.tree_leaves(tr.params):
        weight_shapes.add(
            "[" + ",".join(str(s) for s in np.shape(tags)) + "]"
        )
    ag_shapes = [
        m.group(1)
        for m in re.finditer(r"=\s*\S*f32(\[[\d,]*\])\S*\s+all-gather\(", txt)
    ]
    offenders = [s for s in ag_shapes if s in weight_shapes]
    assert not offenders, (
        f"TP step all-gathers weight-shaped tensors {offenders}; "
        "weights must stay model-axis-sharded"
    )
    # gradient sync over the data axis must exist
    assert "all-reduce" in txt


# ----------------------------------------------------------- ZeRO-3 / FSDP
def _train_zero(ndev: int, zero: str, steps: int = 5, extra=()):
    cfg = [(k, v.format(n=ndev - 1) if k == "dev" else v) for k, v in MLP_CFG]
    cfg.append(("zero", zero))
    cfg.extend(extra)
    tr = NetTrainer()
    tr.set_params(cfg)
    tr.init_model()
    rng = np.random.RandomState(0)
    data = rng.randn(steps, 16, 10).astype(np.float32)
    labels = rng.randint(0, 4, size=(steps, 16, 1)).astype(np.float32)
    for i in range(steps):
        tr.update_all(data[i], labels[i])
    return tr


def test_fsdp_matches_single_device():
    """ZeRO-3 param sharding trains the same weights as 1 device — the
    collectives GSPMD inserts (all-gather fwd/bwd, reduce-scatter grads)
    are placement, not math."""
    t1 = _train(1)
    tf = _train_zero(8, "3")
    _assert_params_close(t1, tf, "zero=3")


def test_fsdp_params_really_sharded():
    """After a step, weight arrays live sharded over the data axis:
    per-device addressable memory is 1/8th, not a replica."""
    tf = _train_zero(8, "3", steps=1)
    w = tf.params["l0_fc1"]["wmat"]  # (32, 10): dim0 divides 8
    assert "data" in tuple(w.sharding.spec)
    shard = w.addressable_shards[0].data
    assert shard.shape[0] == w.shape[0] // 8
    # optimizer state (momentum) sharded the same way
    st = tf.ustates["l0_fc1"]["wmat"]
    leaf = jax.tree_util.tree_leaves(st)[0]
    assert "data" in tuple(leaf.sharding.spec)


def test_fsdp_composes_with_tensor_parallel():
    """zero=3 + model_parallel=2: model axis shards first, data axis
    shards the remainder; training still matches single-device."""
    t1 = _train(1)
    tf = _train_zero(8, "3", extra=(("model_parallel", "2"),))
    assert tf.mesh_plan.n_model == 2 and tf.mesh_plan.n_data == 4
    _assert_params_close(t1, tf, "zero=3 + TP")


def test_zero1_is_update_on_server_alias():
    """zero=1 shards only updater state (the update_on_server mapping)."""
    tz = _train_zero(8, "1", steps=1)
    w = tz.params["l0_fc1"]["wmat"]
    assert w.sharding.is_fully_replicated
    st = jax.tree_util.tree_leaves(tz.ustates["l0_fc1"]["wmat"])[0]
    assert "data" in tuple(st.sharding.spec)


def test_zero_rejects_unsupported_levels():
    tr = NetTrainer()
    with pytest.raises(ValueError, match="zero=2"):
        tr.set_param("zero", "2")


CONV_FUSE_CFG = """
netconfig=start
layer[0->stem] = conv:stem
  kernel_size = 3
  pad = 1
  nchannel = 8
  init_sigma = 0.1
layer[stem->stem] = relu
layer[stem->b1] = conv:br1
  kernel_size = 1
  nchannel = 8
  init_sigma = 0.1
layer[stem->b2] = conv:br2
  kernel_size = 1
  nchannel = 8
  init_sigma = 0.1
layer[b1,b2->cat] = ch_concat
layer[cat->fl] = flatten
layer[fl->out] = fullc:fc
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 3,8,8
batch_size = 16
seed = 7
eta = 0.1
momentum = 0.9
"""


@pytest.mark.parametrize("mp", [1, 2])
def test_fuse_1x1_matches_under_mesh(mp):
    """The concatenated sibling conv composes with DP (and DP x TP)
    sharding: fused training over the 8-device mesh equals unfused."""
    from cxxnet_tpu import config as C

    def train(fuse):
        tr = NetTrainer()
        tr.set_params(C.parse_pairs(
            CONV_FUSE_CFG
            + f"dev = tpu:0-7\nmodel_parallel = {mp}\nfuse_1x1 = {fuse}\n"
        ))
        tr.init_model()
        rng = np.random.RandomState(0)
        for _ in range(3):
            tr.update_all(rng.randn(16, 8, 8, 3).astype(np.float32),
                          rng.randint(0, 4, (16, 1)).astype(np.float32))
        return tr

    t0, t1 = train(0), train(1)
    assert t1.net._sibling_1x1_groups()[0]  # groups actually formed
    _assert_params_close(t0, t1)


def test_check_weight_sync_single_process_multi_device():
    """check_weight_sync's intra-process path: 8 local replicas of every
    DP-replicated parameter fingerprint identically (and the call is the
    same code the CLI's test_on_server=1 runs every round)."""
    tr = _train(8, steps=2)
    assert tr.check_weight_sync() == 0.0


def test_check_weight_sync_covers_sharded_params():
    """TP-sharded training passes the shard-granular sync check (every
    DP replica of every TP shard fingerprints identically), and a
    corrupted single replica of one shard is caught — the guard VERDICT
    r3 asked for (async_updater-inl.hpp:148-153 discipline under TP)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = [(k, v.format(n=7) if k == "dev" else v) for k, v in MLP_CFG]
    tr = NetTrainer()
    tr.set_params(cfg + [("model_parallel", "2")])
    tr.init_model()
    rng = np.random.RandomState(0)
    for _ in range(2):
        tr.update_all(rng.randn(16, 10).astype(np.float32),
                      rng.randint(0, 4, (16, 1)).astype(np.float32))
    assert any(
        not leaf.sharding.is_fully_replicated
        for leaf in jax.tree_util.tree_leaves(tr.params)
    ), "test needs at least one TP-sharded parameter"
    assert tr.check_weight_sync() == 0.0

    # corrupt exactly ONE data-axis replica of one model-axis shard
    mesh = tr.mesh_plan.mesh
    sh = NamedSharding(mesh, P("model", None))
    shape = (8, 4)
    base = np.arange(32, dtype=np.float32).reshape(shape)
    bufs = []
    items = sorted(sh.addressable_devices_indices_map(shape).items(),
                   key=lambda kv: kv[0].id)
    for k, (d, idx) in enumerate(items):
        local = base[idx].copy()
        if k == 0:
            local[0, 0] += 1e-3
        bufs.append(jax.device_put(local, d))
    bad = jax.make_array_from_single_device_arrays(shape, sh, bufs)
    tr.params["zz_corrupt"] = {"wmat": bad}
    with pytest.raises(RuntimeError, match="sharded weights have diverged"):
        tr.check_weight_sync()


WINO_CFG = [
    ("dev", "tpu:0-{n}"),
    ("batch_size", "16"),
    ("input_shape", "8,12,12"),
    ("eta", "0.1"),
    ("momentum", "0.9"),
    ("netconfig", "start"),
    ("layer[0->1]", "conv:cv1"),
    ("kernel_size", "3"),
    ("stride", "1"),
    ("pad", "1"),
    ("nchannel", "8"),
    ("random_type", "xavier"),
    ("conv_wino", "1"),
    ("layer[1->1]", "relu"),
    ("layer[1->2]", "flatten"),
    ("layer[2->3]", "fullc:fc"),
    ("nhidden", "4"),
    ("random_type", "xavier"),
    ("layer[3->3]", "softmax"),
    ("netconfig", "end"),
]


@pytest.mark.parametrize("mp", [1, 2])
def test_winograd_conv_matches_single_under_mesh(mp):
    """conv_wino composes with DP (and DP x TP) sharding: training over
    the 8-device mesh equals the 1-device run, same discipline as the
    conv_s2d/matmul-LRN SPMD parity test."""
    def train(ndev):
        cfg = [(k, v.format(n=ndev - 1) if k == "dev" else v)
               for k, v in WINO_CFG]
        tr = NetTrainer()
        tr.set_params(cfg + ([("model_parallel", str(mp))]
                             if ndev > 1 else []))
        tr.init_model()
        rng = np.random.RandomState(5)
        for _ in range(3):
            tr.update_all(rng.randn(16, 12, 12, 8).astype(np.float32),
                          rng.randint(0, 4, (16, 1)).astype(np.float32))
        return tr

    t1, t8 = train(1), train(8)
    assert t8.net.layer_objs[0].conv_wino == 1
    _assert_params_close(t1, t8, "1- and 8-device winograd runs")
