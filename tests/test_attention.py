"""Ring attention vs plain attention (golden), on the 8-device CPU mesh."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from cxxnet_tpu.ops.attention import mha, ring_attention, ring_self_attention
from cxxnet_tpu.parallel import make_mesh


def _qkv(rng, b=2, t=32, h=4, d=16):
    mk = lambda: jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full_attention(rng, causal):
    q, k, v = _qkv(rng)
    plan = make_mesh("cpu:0-7", model_parallel=4)  # seq over 'model' (4-way)
    want = mha(q, k, v, causal=causal)
    got = ring_self_attention(q, k, v, plan.mesh, "model", causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_ring_full_eight_way(rng):
    q, k, v = _qkv(rng, b=8, t=64)
    plan = make_mesh("cpu:0-7", model_parallel=8)  # pure SP ring
    want = mha(q, k, v, causal=True)
    got = ring_self_attention(q, k, v, plan.mesh, "model", causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_ring_gradients_match(rng):
    q, k, v = _qkv(rng, b=2, t=16, h=2, d=8)
    plan = make_mesh("cpu:0-7", model_parallel=4)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_self_attention(q, k, v, plan.mesh, "model", causal=True) ** 2
        )

    def loss_full(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_full):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gf), rtol=5e-4, atol=5e-5
        )


def test_mha_causal_is_lower_triangular(rng):
    """Causal output at position t must not depend on inputs after t."""
    q, k, v = _qkv(rng, b=1, t=8, h=1, d=4)
    base = np.asarray(mha(q, k, v, causal=True))
    v2 = v.at[:, -1].set(999.0)  # poison the last position
    out2 = np.asarray(mha(q, k, v2, causal=True))
    np.testing.assert_allclose(base[:, :-1], out2[:, :-1], rtol=1e-5)
    assert not np.allclose(base[:, -1], out2[:, -1])
