"""Ring attention vs plain attention (golden), on the 8-device CPU mesh."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from cxxnet_tpu.ops.attention import mha, ring_attention, ring_self_attention
from cxxnet_tpu.parallel import make_mesh


def _qkv(rng, b=2, t=32, h=4, d=16):
    mk = lambda: jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full_attention(rng, causal):
    q, k, v = _qkv(rng)
    plan = make_mesh("cpu:0-7", model_parallel=4)  # seq over 'model' (4-way)
    want = mha(q, k, v, causal=causal)
    got = ring_self_attention(q, k, v, plan.mesh, "model", causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_ring_full_eight_way(rng):
    q, k, v = _qkv(rng, b=8, t=64)
    plan = make_mesh("cpu:0-7", model_parallel=8)  # pure SP ring
    want = mha(q, k, v, causal=True)
    got = ring_self_attention(q, k, v, plan.mesh, "model", causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_ring_gradients_match(rng):
    q, k, v = _qkv(rng, b=2, t=16, h=2, d=8)
    plan = make_mesh("cpu:0-7", model_parallel=4)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_self_attention(q, k, v, plan.mesh, "model", causal=True) ** 2
        )

    def loss_full(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_full):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gf), rtol=5e-4, atol=5e-5
        )


def test_mha_causal_is_lower_triangular(rng):
    """Causal output at position t must not depend on inputs after t."""
    q, k, v = _qkv(rng, b=1, t=8, h=1, d=4)
    base = np.asarray(mha(q, k, v, causal=True))
    v2 = v.at[:, -1].set(999.0)  # poison the last position
    out2 = np.asarray(mha(q, k, v2, causal=True))
    np.testing.assert_allclose(base[:, :-1], out2[:, :-1], rtol=1e-5)
    assert not np.allclose(base[:, -1], out2[:, -1])


# ------------------------------------------------- Ulysses all-to-all SP
from cxxnet_tpu.ops.attention import a2a_self_attention


@pytest.mark.parametrize("causal", [False, True])
def test_a2a_matches_full_attention(rng, causal):
    q, k, v = _qkv(rng)  # h=4 divides the 4-way axis
    plan = make_mesh("cpu:0-7", model_parallel=4)
    want = mha(q, k, v, causal=causal)
    got = a2a_self_attention(q, k, v, plan.mesh, "model", causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_a2a_eight_way(rng):
    q, k, v = _qkv(rng, b=8, t=64, h=8)
    plan = make_mesh("cpu:0-7", model_parallel=8)
    want = mha(q, k, v, causal=True)
    got = a2a_self_attention(q, k, v, plan.mesh, "model", causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_a2a_gradients_match(rng):
    q, k, v = _qkv(rng, b=2, t=16, h=4, d=8)
    plan = make_mesh("cpu:0-7", model_parallel=4)

    def loss_a2a(q_, k_, v_):
        return jnp.sum(
            a2a_self_attention(q_, k_, v_, plan.mesh, "model") ** 2
        )

    def loss_full(q_, k_, v_):
        return jnp.sum(mha(q_, k_, v_) ** 2)

    ga = jax.grad(loss_a2a, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, f in zip(ga, gf):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(f), rtol=1e-4, atol=1e-5
        )


def test_attention_layer_seq_parallel_modes(rng):
    """Config grammar: seq_parallel = ring|alltoall|0|1|2 select the SP
    schedule; both produce mha-identical output through the layer."""
    from cxxnet_tpu.layers import create_layer

    x = jnp.asarray(rng.randn(4, 16, 32).astype(np.float32))
    plan = make_mesh("cpu:0-7", model_parallel=4)
    outs = {}
    for mode in ("0", "ring", "alltoall"):
        lay = create_layer("attention")
        lay.set_param("nhead", "4")
        lay.set_param("init_sigma", "0.1")
        lay.set_param("seq_parallel", mode)
        lay.bind_mesh(plan)
        lay.infer_shape([(4, 16, 32)])
        params = lay.init_params(jax.random.PRNGKey(0), [(4, 16, 32)])
        (outs[mode],) = lay.apply(params, [x])
    np.testing.assert_allclose(
        np.asarray(outs["ring"]), np.asarray(outs["0"]), rtol=2e-5,
        atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(outs["alltoall"]), np.asarray(outs["0"]), rtol=2e-5,
        atol=2e-5)
    import pytest as _pytest

    lay = create_layer("attention")
    lay.set_param("nhead", "3")  # 3 % 4 != 0
    lay.set_param("seq_parallel", "alltoall")
    lay.bind_mesh(plan)
    with _pytest.raises(ValueError, match="alltoall"):
        lay.infer_shape([(4, 16, 33)])
