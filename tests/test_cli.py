"""End-to-end CLI tests: full .conf runs through the task driver."""

import os
import subprocess
import sys

import numpy as np
import pytest

from cxxnet_tpu.io.mnist import write_idx_images, write_idx_labels

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_conf(tmp_path, num_round=3, extra=""):
    """A small MNIST-style conf over synthetic idx files."""
    rng = np.random.RandomState(0)
    n, hw = 256, 8
    imgs = rng.randint(0, 256, (n, hw, hw)).astype(np.uint8)
    # learnable labels: derived from mean pixel intensity quartiles
    flat = imgs.reshape(n, -1).astype(np.float32)
    labels = (np.argsort(np.argsort(flat.mean(1))) * 4 // n).astype(np.uint8)
    write_idx_images(str(tmp_path / "tr-img.idx"), imgs)
    write_idx_labels(str(tmp_path / "tr-lab.idx"), labels)
    write_idx_images(str(tmp_path / "te-img.idx"), imgs[:64])
    write_idx_labels(str(tmp_path / "te-lab.idx"), labels[:64])
    conf = f"""
data = train
iter = mnist
  path_img = "{tmp_path}/tr-img.idx"
  path_label = "{tmp_path}/tr-lab.idx"
  shuffle = 1
iter = end
eval = test
iter = mnist
  path_img = "{tmp_path}/te-img.idx"
  path_label = "{tmp_path}/te-lab.idx"
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.1
layer[+1:sg1] = relu
layer[sg1->fc2] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end

input_shape = 1,1,64
batch_size = 64
dev = cpu
save_model = 1
num_round = {num_round}
train_eval = 1
eval_train = 1
eta = 0.3
momentum = 0.9
metric = error
model_dir = {tmp_path}/models
print_step = 100
{extra}
"""
    path = tmp_path / "mnist.conf"
    path.write_text(conf)
    return str(path)


from conftest import run_cli  # noqa: E402 - shared CLI harness


def test_train_task_end_to_end(tmp_path):
    conf = make_conf(tmp_path)
    r = run_cli([conf], str(tmp_path))
    assert r.returncode == 0, r.stderr + r.stdout
    # eval lines on stderr: [round]\ttrain-error:..\ttest-error:..
    lines = [l for l in r.stderr.splitlines() if l.startswith("[")]
    assert len(lines) == 3
    assert "train-error:" in lines[0] and "test-error:" in lines[0]
    # error decreases over rounds
    def err_of(line):
        return float(line.split("test-error:")[1].split()[0])

    assert err_of(lines[-1]) < err_of(lines[0]) + 1e-9
    # checkpoints written each round (each with a sidecar manifest)
    files = os.listdir(tmp_path / "models")
    models = sorted(f for f in files if f.endswith(".model"))
    assert models == ["0000.model", "0001.model", "0002.model", "0003.model"]
    for m in models:
        assert f"{m}.manifest.json" in files


def test_continue_training(tmp_path):
    conf = make_conf(tmp_path, num_round=2)
    r1 = run_cli([conf], str(tmp_path))
    assert r1.returncode == 0, r1.stderr
    # continue for 2 more rounds
    r2 = run_cli([conf, "continue=1", "num_round=4"], str(tmp_path))
    assert r2.returncode == 0, r2.stderr
    assert "Continue training from round" in r2.stdout
    models = sorted(os.listdir(tmp_path / "models"))
    assert "0004.model" in models


def test_pred_task(tmp_path):
    conf = make_conf(tmp_path, num_round=1)
    r1 = run_cli([conf], str(tmp_path))
    assert r1.returncode == 0, r1.stderr
    pred_conf = tmp_path / "pred.conf"
    pred_conf.write_text(
        open(conf).read()
        + f"""
pred = {tmp_path}/pred.txt
iter = mnist
  path_img = "{tmp_path}/te-img.idx"
  path_label = "{tmp_path}/te-lab.idx"
iter = end
"""
    )
    r2 = run_cli(
        [str(pred_conf), "task=pred", f"model_in={tmp_path}/models/0001.model"],
        str(tmp_path),
    )
    assert r2.returncode == 0, r2.stderr + r2.stdout
    preds = np.loadtxt(tmp_path / "pred.txt")
    assert len(preds) == 64
    assert set(np.unique(preds)) <= {0.0, 1.0, 2.0, 3.0}


def test_extract_task(tmp_path):
    conf = make_conf(tmp_path, num_round=1)
    r1 = run_cli([conf], str(tmp_path))
    assert r1.returncode == 0, r1.stderr
    pred_conf = tmp_path / "ext.conf"
    pred_conf.write_text(
        open(conf).read()
        + f"""
pred = {tmp_path}/feat.txt
iter = mnist
  path_img = "{tmp_path}/te-img.idx"
  path_label = "{tmp_path}/te-lab.idx"
iter = end
"""
    )
    r2 = run_cli(
        [
            str(pred_conf),
            "task=extract",
            f"model_in={tmp_path}/models/0001.model",
            "extract_node_name=fc1",
        ],
        str(tmp_path),
    )
    assert r2.returncode == 0, r2.stderr + r2.stdout
    feats = np.loadtxt(tmp_path / "feat.txt")
    assert feats.shape == (64, 32)
    meta = open(tmp_path / "feat.txt.meta").read().strip()
    assert meta.startswith("64,")


def test_finetune_task(tmp_path):
    conf = make_conf(tmp_path, num_round=1)
    r1 = run_cli([conf], str(tmp_path))
    assert r1.returncode == 0, r1.stderr
    r2 = run_cli(
        [conf, "task=finetune", f"model_in={tmp_path}/models/0001.model",
         "num_round=2", f"model_dir={tmp_path}/models2"],
        str(tmp_path),
    )
    assert r2.returncode == 0, r2.stderr + r2.stdout
    assert "Copying layer fc1" in r2.stdout


def test_test_io_mode(tmp_path):
    conf = make_conf(tmp_path, num_round=1)
    r = run_cli([conf, "test_io=1"], str(tmp_path))
    assert r.returncode == 0, r.stderr
    assert "start I/O test" in r.stdout


def test_profiler_utils(tmp_path):
    """StepTimer stats + TraceController trace files on disk."""
    import time as _time

    from cxxnet_tpu.utils.profiler import StepTimer, TraceController

    t = StepTimer()
    for _ in range(6):
        t.start(); _time.sleep(0.002); t.stop()
    s = t.summary(batch_size=16)
    assert s["steps"] == 6 and s["mean_ms"] >= 1.5
    assert s["samples_per_sec"] > 0
    assert "p99" in t.report(16)

    tr = TraceController()
    tr.configure([("profile", "1"), ("profile_dir", str(tmp_path)),
                  ("profile_start", "1"), ("profile_steps", "2")])
    for i in range(5):
        tr.step(i)
    tr.close()
    assert tr._done
    import os
    found = []
    for root, _, files in os.walk(str(tmp_path)):
        found.extend(files)
    assert any("xplane" in f or f.endswith(".json.gz") or "trace" in f
               for f in found), found


def _digits_err(tmp_path, rounds, overrides=()):
    """CLI-train example/MNIST/digits.conf on REAL handwritten digits
    (UCI set, idx-encoded) and return the final test error."""
    import shutil

    from tools.make_digits_idx import write_digits_idx

    write_digits_idx(str(tmp_path / "data"))
    shutil.copy(
        os.path.join(REPO, "example", "MNIST", "digits.conf"),
        tmp_path / "digits.conf",
    )
    r = run_cli(
        ["digits.conf", f"num_round={rounds}", f"max_round={rounds}",
         *overrides],
        str(tmp_path),
    )
    assert r.returncode == 0, r.stderr + r.stdout
    lines = [l for l in r.stderr.splitlines() if l.startswith("[")]
    return float(lines[-1].split("test-error:")[1].split()[0])


def test_real_digits_quick(tmp_path):
    """CI-runnable reduced variant: 5 rounds at eta=0.5 reaches <= 15%
    error (the sigmoid MLP warms up slowly at the reference's eta=0.1;
    measured 11.2%)."""
    assert _digits_err(tmp_path, 5, ("eta=0.5",)) <= 0.15


@pytest.mark.slow
def test_real_digits_full_accuracy(tmp_path):
    """The reference MNIST fixture analog (README.md published number):
    15 rounds of the MNIST.conf MLP recipe on real handwritten digits
    reaches <= 5% test error."""
    assert _digits_err(tmp_path, 15) <= 0.05


def test_pred_raw_task_and_submission_roundtrip(tmp_path):
    """task=pred_raw writes softmax rows; bowl_tools.py submission joins
    them with the .lst into a kaggle csv (the reference kaggle_bowl
    round-trip, gen_img_list.py + make_submission.py analogs)."""
    import csv
    import importlib.util

    conf = make_conf(tmp_path, num_round=1)
    r1 = run_cli([conf], str(tmp_path))
    assert r1.returncode == 0, r1.stderr
    pred_conf = tmp_path / "pred.conf"
    pred_conf.write_text(
        open(conf).read()
        + f"""
pred = {tmp_path}/test.txt
iter = mnist
  path_img = "{tmp_path}/te-img.idx"
  path_label = "{tmp_path}/te-lab.idx"
iter = end
"""
    )
    r2 = run_cli(
        [str(pred_conf), "task=pred_raw",
         f"model_in={tmp_path}/models/0001.model"],
        str(tmp_path),
    )
    assert r2.returncode == 0, r2.stderr + r2.stdout
    rows = np.loadtxt(tmp_path / "test.txt")
    assert rows.shape == (64, 4)
    np.testing.assert_allclose(rows.sum(1), 1.0, atol=1e-3)  # softmax rows

    spec = importlib.util.spec_from_file_location(
        "bowl_tools",
        os.path.join(REPO, "example", "kaggle_bowl", "bowl_tools.py"),
    )
    bt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bt)
    (tmp_path / "sample.csv").write_text(
        "image,c0,c1,c2,c3\nx.jpg,0,0,0,0\n"
    )
    with open(tmp_path / "test.lst", "w") as f:
        for i in range(64):
            f.write(f"{i}\t0\tdir/img_{i}.jpg\n")
    bt.main([
        "submission", str(tmp_path / "sample.csv"),
        str(tmp_path / "test.lst"), str(tmp_path / "test.txt"),
        str(tmp_path / "out.csv"),
    ])
    with open(tmp_path / "out.csv", newline="") as f:
        out = list(csv.reader(f))
    assert out[0] == ["image", "c0", "c1", "c2", "c3"]
    assert len(out) == 65 and out[1][0] == "img_0.jpg"
    assert abs(sum(float(v) for v in out[1][1:]) - 1.0) < 1e-3


def test_bowl_genlist_and_split(tmp_path):
    import csv
    import importlib.util

    from PIL import Image

    spec = importlib.util.spec_from_file_location(
        "bowl_tools",
        os.path.join(REPO, "example", "kaggle_bowl", "bowl_tools.py"),
    )
    bt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bt)
    (tmp_path / "sample.csv").write_text(
        "image,acantharia,copepod\nx.jpg,0,0\n"
    )
    for cls, n in (("acantharia", 3), ("copepod", 2)):
        d = tmp_path / "raw" / cls
        d.mkdir(parents=True)
        for i in range(n):
            Image.new("L", (13, 17), color=i * 40).save(d / f"{cls}_{i}.png")
    bt.main([
        "resize", str(tmp_path / "raw"), str(tmp_path / "train"),
        "--size", "8",
    ])
    img = Image.open(tmp_path / "train" / "copepod" / "copepod_1.png")
    assert img.size == (8, 8)
    bt.main([
        "genlist", "train", str(tmp_path / "sample.csv"),
        str(tmp_path / "train"), str(tmp_path / "train.lst"),
    ])
    with open(tmp_path / "train.lst", newline="") as f:
        rows = list(csv.reader(f, delimiter="\t"))
    assert len(rows) == 5
    assert sorted(int(r[1]) for r in rows) == [0, 0, 0, 1, 1]
    labels = {os.path.basename(r[2]).split("_")[0]: r[1] for r in rows}
    assert labels == {"acantharia": "0", "copepod": "1"}
    bt.main([
        "split", str(tmp_path / "train.lst"), str(tmp_path / "tr.lst"),
        str(tmp_path / "va.lst"), "--n-train", "3",
    ])
    assert len(open(tmp_path / "tr.lst").readlines()) == 3
    assert len(open(tmp_path / "va.lst").readlines()) == 2


def test_scan_steps_trains_identically(tmp_path):
    """scan_steps=k (CLI staging k batches into ONE update_scan dispatch)
    must produce the same eval trajectory as per-batch updates."""
    conf = make_conf(tmp_path, num_round=3)
    r1 = run_cli([conf, "eval_train=0"], str(tmp_path))
    assert r1.returncode == 0, r1.stderr
    lines1 = [l for l in r1.stderr.splitlines() if l.startswith("[")]

    import shutil

    shutil.rmtree(tmp_path / "models")
    r2 = run_cli([conf, "eval_train=0", "scan_steps=4"], str(tmp_path))
    assert r2.returncode == 0, r2.stderr
    lines2 = [l for l in r2.stderr.splitlines() if l.startswith("[")]
    assert lines1 == lines2, (lines1, lines2)


def test_task_summary(tmp_path, capsys):
    """task=summary prints the per-layer table and totals from a bare
    conf (no data files, no model_in)."""
    from cxxnet_tpu import cli as climod
    from cxxnet_tpu.models import mnist_mlp_conf

    conf = tmp_path / "m.conf"
    conf.write_text(mnist_mlp_conf(batch_size=4, dev="cpu"))
    rc = climod.main([str(conf), "task=summary", "silent=1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "total parameters:" in out
    assert "fullc" in out and "softmax" in out
