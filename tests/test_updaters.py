"""Updater math + schedule tests vs closed-form references."""

import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.updater import create_updater
from cxxnet_tpu.updater.param import UpdaterParam


def test_sgd_matches_reference_recurrence():
    up = create_updater("sgd", "wmat")
    up.set_param("lr", "0.1")
    up.set_param("momentum", "0.9")
    up.set_param("wd", "0.01")
    w = jnp.asarray([1.0, -2.0])
    st = up.init_state(w)
    g = jnp.asarray([0.5, 0.5])
    m = np.zeros(2)
    wr = np.array([1.0, -2.0])
    for t in range(3):
        w, st = up.apply(w, g, st, jnp.asarray(t))
        m = 0.9 * m - 0.1 * (np.asarray(g) + 0.01 * wr)
        wr = wr + m
        np.testing.assert_allclose(np.asarray(w), wr, rtol=1e-6)


def test_sgd_nan_zeroed_with_clip():
    up = create_updater("sgd", "wmat")
    up.set_param("lr", "1.0")
    up.set_param("momentum", "0.0")
    up.set_param("clip_gradient", "0.2")
    w = jnp.asarray([0.0, 0.0, 0.0])
    st = up.init_state(w)
    g = jnp.asarray([jnp.nan, 5.0, -5.0])
    w2, _ = up.apply(w, g, st, jnp.asarray(0))
    np.testing.assert_allclose(np.asarray(w2), [0.0, -0.2, 0.2], atol=1e-7)


def test_sgd_nan_propagates_without_clip():
    up = create_updater("sgd", "wmat")
    w = jnp.asarray([0.0])
    st = up.init_state(w)
    w2, _ = up.apply(w, jnp.asarray([jnp.nan]), st, jnp.asarray(0))
    assert np.isnan(np.asarray(w2)).all()


def test_nag_matches_reference_recurrence():
    up = create_updater("nag", "wmat")
    up.set_param("lr", "0.1")
    up.set_param("momentum", "0.9")
    w = jnp.asarray([1.0])
    st = up.init_state(w)
    g = jnp.asarray([1.0])
    m = np.zeros(1)
    wr = np.array([1.0])
    for t in range(3):
        w, st = up.apply(w, g, st, jnp.asarray(t))
        old = m.copy()
        m = 0.9 * m - 0.1 * np.asarray(g)
        wr = wr + (1 + 0.9) * m - 0.9 * old
        np.testing.assert_allclose(np.asarray(w), wr, rtol=1e-6)


def test_adam_matches_reference_recurrence():
    up = create_updater("adam", "wmat")
    up.set_param("lr", "0.01")
    up.set_param("wd", "0.1")
    w = jnp.asarray([2.0])
    st = up.init_state(w)
    g0 = jnp.asarray([1.0])
    m1 = np.zeros(1)
    m2 = np.zeros(1)
    wr = np.array([2.0])
    d1, d2 = 0.1, 0.001
    for t in range(3):
        w, st = up.apply(w, g0, st, jnp.asarray(t))
        g = np.asarray(g0) - 0.1 * wr  # reference: wd subtracted
        fix1 = 1 - (1 - d1) ** (t + 1)
        fix2 = 1 - (1 - d2) ** (t + 1)
        lr_t = 0.01 * np.sqrt(fix2) / fix1
        m1 = m1 + d1 * (g - m1)
        m2 = m2 + d2 * (g * g - m2)
        wr = wr - lr_t * (m1 / (np.sqrt(m2) + 1e-8))
        np.testing.assert_allclose(np.asarray(w), wr, rtol=1e-5)


def test_lr_schedules():
    p = UpdaterParam("wmat")
    p.set_param("lr", "0.1")
    p.set_param("lr:schedule", "expdecay")
    p.set_param("lr:gamma", "0.1")
    p.set_param("lr:step", "100")
    np.testing.assert_allclose(float(p.learning_rate(0)), 0.1, rtol=1e-5)
    np.testing.assert_allclose(float(p.learning_rate(100)), 0.01, rtol=1e-5)
    np.testing.assert_allclose(float(p.learning_rate(50)), 0.1 * 0.1 ** 0.5, rtol=1e-5)

    p2 = UpdaterParam("")
    p2.set_param("eta", "1.0")
    p2.set_param("lr:schedule", "factor")
    p2.set_param("lr:factor", "0.5")
    p2.set_param("lr:step", "10")
    np.testing.assert_allclose(float(p2.learning_rate(25)), 0.25, rtol=1e-5)
    # lr_minimum floor
    p2.set_param("lr:minimum_lr", "0.3")
    np.testing.assert_allclose(float(p2.learning_rate(25)), 0.3, rtol=1e-5)

    p3 = UpdaterParam("")
    p3.set_param("lr", "1.0")
    p3.set_param("lr:schedule", "polydecay")
    p3.set_param("lr:gamma", "1.0")
    p3.set_param("lr:alpha", "1.0")
    p3.set_param("lr:step", "1")
    np.testing.assert_allclose(float(p3.learning_rate(3)), 0.25, rtol=1e-5)


def test_tag_scoped_overrides():
    pw = UpdaterParam("wmat")
    pb = UpdaterParam("bias")
    for p in (pw, pb):
        p.set_param("lr", "0.01")
        p.set_param("wmat:lr", "0.5")
        p.set_param("bias:wd", "0.25")
    assert pw.base_lr == 0.5
    assert pb.base_lr == 0.01
    assert pb.wd == 0.25
    assert pw.wd == 0.0


def test_momentum_saturation_ramp():
    p = UpdaterParam("")
    p.set_param("momentum_schedule", "1")
    p.set_param("base_momentum", "0.5")
    p.set_param("final_momentum", "0.9")
    p.set_param("saturation_epoch", "100")
    np.testing.assert_allclose(float(p.momentum_at(0)), 0.5, rtol=1e-5)
    np.testing.assert_allclose(float(p.momentum_at(50)), 0.7, rtol=1e-5)
    np.testing.assert_allclose(float(p.momentum_at(1000)), 0.9, rtol=1e-5)


def test_unknown_updater():
    with pytest.raises(ValueError):
        create_updater("lbfgs", "wmat")


def test_rmsprop_matches_reference_recurrence():
    up = create_updater("rmsprop", "wmat")
    up.set_param("lr", "0.01")
    up.set_param("rho", "0.9")
    up.set_param("wd", "0.001")
    w = jnp.asarray([1.0, -2.0])
    st = up.init_state(w)
    g = jnp.asarray([0.5, -0.25])
    v = np.zeros(2)
    wr = np.array([1.0, -2.0])
    for t in range(4):
        w, st = up.apply(w, g, st, jnp.asarray(t))
        gr = np.asarray(g) + 0.001 * wr
        v = 0.9 * v + 0.1 * gr * gr
        wr = wr - 0.01 * gr / (np.sqrt(v) + 1e-8)
        np.testing.assert_allclose(np.asarray(w), wr, rtol=1e-6)


def test_adagrad_matches_reference_recurrence():
    up = create_updater("adagrad", "wmat")
    up.set_param("lr", "0.1")
    w = jnp.asarray([1.0, -2.0])
    st = up.init_state(w)
    g = jnp.asarray([0.5, -0.25])
    v = np.zeros(2)
    wr = np.array([1.0, -2.0])
    for t in range(4):
        w, st = up.apply(w, g, st, jnp.asarray(t))
        v = v + np.asarray(g) ** 2
        wr = wr - 0.1 * np.asarray(g) / (np.sqrt(v) + 1e-8)
        np.testing.assert_allclose(np.asarray(w), wr, rtol=1e-6)


def test_rmsprop_trains_end_to_end():
    """updater=rmsprop through the config path overfits a tiny batch."""
    from cxxnet_tpu.nnet.trainer import NetTrainer

    cfg = [
        ("dev", "cpu"),
        ("batch_size", "16"),
        ("input_shape", "1,1,8"),
        ("updater", "rmsprop"),
        ("eta", "0.02"),
        ("netconfig", "start"),
        ("layer[0->1]", "fullc:fc"),
        ("nhidden", "4"),
        ("layer[1->1]", "softmax"),
        ("netconfig", "end"),
    ]
    tr = NetTrainer()
    tr.set_params(cfg)
    tr.init_model()
    rng = np.random.RandomState(0)
    data = rng.randn(16, 8).astype(np.float32)
    labels = rng.randint(0, 4, (16, 1)).astype(np.float32)
    first = last = None
    from cxxnet_tpu.io.data import DataBatch

    for _ in range(60):
        tr.update_all(data, labels)
        out = tr.predict(DataBatch(data=data, label=labels))
        err = (out.ravel() != labels.ravel()).mean()
        first = err if first is None else first
        last = err
    assert last <= 0.25 and last <= first


def test_lars_matches_reference_recurrence():
    up = create_updater("lars", "wmat")
    up.set_param("lr", "0.1")
    up.set_param("momentum", "0.9")
    up.set_param("wd", "0.01")
    up.set_param("trust_coeff", "0.02")
    w = jnp.asarray([1.0, -2.0])
    st = up.init_state(w)
    g = jnp.asarray([0.5, -0.25])
    m = np.zeros(2)
    wr = np.array([1.0, -2.0])
    for t in range(4):
        w, st = up.apply(w, g, st, jnp.asarray(t))
        gr = np.asarray(g) + 0.01 * wr
        wn = np.linalg.norm(wr)
        gn = np.linalg.norm(gr)
        trust = 0.02 * wn / (gn + 1e-9)
        m = 0.9 * m - 0.1 * trust * gr
        wr = wr + m
        np.testing.assert_allclose(np.asarray(w), wr, rtol=1e-5)


def test_lamb_matches_reference_recurrence():
    up = create_updater("lamb", "wmat")
    up.set_param("lr", "0.01")
    up.set_param("wd", "0.1")
    w = jnp.asarray([1.0, -2.0])
    st = up.init_state(w)
    g = jnp.asarray([0.5, -0.25])
    m1 = np.zeros(2)
    m2 = np.zeros(2)
    wr = np.array([1.0, -2.0])
    for t in range(4):
        w, st = up.apply(w, g, st, jnp.asarray(t))
        m1 = 0.9 * m1 + 0.1 * np.asarray(g)
        m2 = 0.999 * m2 + 0.001 * np.asarray(g) ** 2
        u = (m1 / (1 - 0.9 ** (t + 1))) / (
            np.sqrt(m2 / (1 - 0.999 ** (t + 1))) + 1e-6
        )
        u = u + 0.1 * wr
        trust = np.linalg.norm(wr) / np.linalg.norm(u)
        wr = wr - 0.01 * trust * u
        np.testing.assert_allclose(np.asarray(w), wr, rtol=1e-5)


def test_lamb_trains_end_to_end():
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer

    cfg = [
        ("dev", "cpu"),
        ("batch_size", "16"),
        ("input_shape", "1,1,8"),
        ("updater", "lamb"),
        ("eta", "0.05"),
        ("wd", "0.0"),
        ("netconfig", "start"),
        ("layer[0->1]", "fullc:fc"),
        ("nhidden", "4"),
        ("layer[1->1]", "softmax"),
        ("netconfig", "end"),
    ]
    tr = NetTrainer()
    tr.set_params(cfg)
    tr.init_model()
    rng = np.random.RandomState(0)
    data = rng.randn(16, 8).astype(np.float32)
    labels = rng.randint(0, 4, (16, 1)).astype(np.float32)
    last = None
    for _ in range(80):
        tr.update_all(data, labels)
        out = tr.predict(DataBatch(data=data, label=labels))
        last = (np.asarray(out).ravel() != labels.ravel()).mean()
    assert last <= 0.25
