"""Alert evaluator tests (cxxnet_tpu/obs/alerts.py).

Rule parsing, the ok→pending→firing→cleared state machine (including
``for_s`` debounce and the derived interval ``_rate``/``_mean`` series),
the ``GET /alertz`` endpoint's schema (validated with the same
``tools/obs_dump.py`` parser CI uses), and the /healthz degrade+recover
contract: a deliberately-tripped latency rule (threshold 0) fires,
degrades health with its name in the detail, and clears after recovery.
"""

import json
import os
import sys
import threading
import urllib.request

import numpy as np
import pytest

from cxxnet_tpu import config as cfgmod
from cxxnet_tpu import serve
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.obs import alerts as obs_alerts
from cxxnet_tpu.obs.registry import MetricsRegistry, registry

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
from obs_dump import validate_alertz  # noqa: E402

MLP_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:a1] = relu:a1
layer[a1->out] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = 32
dev = cpu
eta = 0.1
"""


@pytest.fixture(autouse=True)
def _fresh_alerts():
    """No test leaks rules (or a firing state that would degrade other
    tests' /healthz) into the next one."""
    obs_alerts.reset()
    yield
    obs_alerts.reset()


# ----------------------------------------------------------------------
# parsing
def test_parse_rule_grammar():
    r = obs_alerts.parse_rule("hi_lat:serve_request_latency_seconds_mean"
                              ":>:0.25:10")
    assert (r.name, r.metric, r.op, r.threshold, r.for_s) == (
        "hi_lat", "serve_request_latency_seconds_mean", ">", 0.25, 10.0)
    # shell-friendly op spellings canonicalize
    assert obs_alerts.parse_rule("a:m:ge:1").op == ">="
    # labeled selector survives the colon split
    r2 = obs_alerts.parse_rule(
        'shed:serve_request_outcomes_total{outcome="shed"}:>:0')
    assert r2.metric == 'serve_request_outcomes_total{outcome="shed"}'
    # label VALUES may contain colons (device labels like tpu:0): the
    # spec parses outside-in, so the metric keeps its colons intact
    r3 = obs_alerts.parse_rule(
        'mem:xla_device_memory_bytes{device="tpu:0",stat="bytes_in_use"}'
        ":>=:8e9:30")
    assert r3.metric == ('xla_device_memory_bytes{device="tpu:0",'
                         'stat="bytes_in_use"}')
    assert (r3.op, r3.threshold, r3.for_s) == (">=", 8e9, 30.0)
    for bad in ("toofew:m:>", "x:m:~:1", "x:m:>:abc", "x::>:1",
                "bad name:m:>:1", "x:m:>:1:2:3"):
        with pytest.raises(ValueError):
            obs_alerts.parse_rule(bad)


def test_duplicate_rule_names_rejected_but_reconfigure_ignored():
    ev = obs_alerts.AlertEvaluator(registry=MetricsRegistry())
    cfg = [("alert", "a:some_gauge:>:1")]
    assert ev.configure(cfg) == 1
    assert ev.configure(cfg) == 0  # idempotent re-configure
    with pytest.raises(ValueError):
        ev.add_rule(obs_alerts.parse_rule("a:other:>:2"))


# ----------------------------------------------------------------------
# state machine
def test_gauge_rule_fires_and_clears():
    reg = MetricsRegistry()
    g = reg.gauge("t_depth", "test gauge")
    ev = obs_alerts.AlertEvaluator(registry=reg)
    ev.add_rule(obs_alerts.parse_rule("deep:t_depth:>:10"))
    g.set(3)
    assert ev.evaluate_once() == [] and ev.firing() == []
    g.set(42)
    events = ev.evaluate_once()
    assert [e["kind"] for e in events] == ["alert.firing"]
    assert events[0]["value"] == 42 and ev.firing() == ["deep"]
    # the registry gauge mirrors the state
    snap = reg.snapshot()
    assert snap["obs_alerts_firing"]['obs_alerts_firing{name="deep"}'] == 1
    g.set(0)
    events = ev.evaluate_once()
    assert [e["kind"] for e in events] == ["alert.cleared"]
    assert ev.firing() == []
    assert reg.snapshot()["obs_alerts_firing"][
        'obs_alerts_firing{name="deep"}'] == 0
    trans = reg.snapshot()["obs_alert_transitions_total"]
    assert trans['obs_alert_transitions_total{name="deep",to="firing"}'] == 1
    assert trans['obs_alert_transitions_total{name="deep",to="cleared"}'] == 1


def test_for_s_debounce():
    reg = MetricsRegistry()
    g = reg.gauge("t_load", "test gauge")
    ev = obs_alerts.AlertEvaluator(registry=reg)
    ev.add_rule(obs_alerts.parse_rule("hot:t_load:>=:1:5"))
    g.set(2)
    assert ev.evaluate_once(now=100.0) == []  # pending, not firing
    assert ev.status()["rules"][0]["state"] == "pending"
    assert ev.evaluate_once(now=103.0) == []  # still inside for_s
    events = ev.evaluate_once(now=105.5)      # held >= 5s -> fires
    assert [e["kind"] for e in events] == ["alert.firing"]
    # a dip resets the debounce clock entirely
    g.set(0)
    ev.evaluate_once(now=106.0)
    g.set(2)
    assert ev.evaluate_once(now=107.0) == []  # pending again from zero
    assert ev.status()["rules"][0]["state"] == "pending"


def test_labeled_family_any_sample_fires():
    reg = MetricsRegistry()
    c = reg.counter("t_outcomes_total", "", labelnames=("outcome",))
    ev = obs_alerts.AlertEvaluator(registry=reg)
    ev.add_rule(obs_alerts.parse_rule(
        't_shed:t_outcomes_total{outcome="shed"}:>:0'))
    ev.add_rule(obs_alerts.parse_rule("t_any:t_outcomes_total:>:2"))
    c.labels(outcome="ok").inc(3)
    ev.evaluate_once()
    assert ev.firing() == ["t_any"]  # bare family matches any labelset
    c.labels(outcome="shed").inc()
    ev.evaluate_once()
    assert ev.firing() == ["t_any", "t_shed"]


def test_derived_rate_and_mean_clear_after_recovery():
    """The deliberately-tripped latency rule of the acceptance bar:
    threshold 0 on the interval mean fires while observations land and
    clears once traffic stops — where the lifetime mean never would."""
    reg = MetricsRegistry()
    h = reg.histogram("t_lat_seconds", "test latency")
    c = reg.counter("t_reqs_total", "test requests")
    ev = obs_alerts.AlertEvaluator(registry=reg)
    ev.add_rule(obs_alerts.parse_rule("lat0:t_lat_seconds_mean:>:0"))
    ev.add_rule(obs_alerts.parse_rule("busy:t_reqs_rate:>:100"))
    ev.evaluate_once(now=10.0)  # baseline snapshot
    for _ in range(300):
        c.inc()
        h.observe(0.02)
    ev.evaluate_once(now=11.0)  # 300 req/s, mean 20ms > 0
    assert ev.firing() == ["busy", "lat0"]
    # recovery: no new observations in the next interval
    events = ev.evaluate_once(now=12.0)
    assert sorted(e["kind"] for e in events) == ["alert.cleared",
                                                "alert.cleared"]
    assert ev.firing() == []


def test_status_is_valid_alertz_schema():
    reg = MetricsRegistry()
    reg.gauge("t_x", "").set(5)
    ev = obs_alerts.AlertEvaluator(registry=reg)
    ev.add_rule(obs_alerts.parse_rule("x_high:t_x:>:1"))
    ev.add_rule(obs_alerts.parse_rule("x_low:t_x:<:0"))
    ev.evaluate_once()
    body = json.loads(json.dumps(ev.status()))  # HTTP round-trip
    assert validate_alertz(body) == []
    assert body["firing"] == ["x_high"]
    states = {r["name"]: r["state"] for r in body["rules"]}
    assert states == {"x_high": "firing", "x_low": "ok"}


# ----------------------------------------------------------------------
# the serve surface: /alertz + /healthz degrade and recover
def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return json.loads(r.read())


def test_alertz_endpoint_and_healthz_degrade(tmp_path):
    """End-to-end acceptance path: a latency rule with threshold 0 on
    the process-wide evaluator fires after real /predict traffic,
    /alertz reports it, /healthz degrades with the rule named, and both
    recover once traffic stops."""
    tr = NetTrainer()
    tr.set_params(cfgmod.parse_pairs(MLP_CFG))
    tr.set_param("seed", "0")
    tr.init_model()
    eng = serve.Engine(trainer=tr, max_batch_size=32, batch_timeout_ms=1)
    ev = obs_alerts.evaluator()  # the singleton the server reads
    ev.configure([
        ("alert", "trip_lat:serve_request_latency_seconds_mean:>:0"),
    ])
    httpd = serve.make_server(eng, port=0)
    port = httpd.server_port
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
    try:
        ev.evaluate_once()  # baseline
        assert _get(port, "/healthz")["status"] == "ok"
        body = _get(port, "/alertz")
        assert validate_alertz(body) == []
        assert [r["name"] for r in body["rules"]] == ["trip_lat"]
        assert body["firing"] == []
        out = _get_post(port, "/predict", {"data": x.tolist()})
        assert len(out["pred"]) == 4 and out["rid"]
        ev.evaluate_once()  # latency observations landed -> fires
        body = _get(port, "/alertz")
        assert validate_alertz(body) == []
        assert body["firing"] == ["trip_lat"]
        h = _get(port, "/healthz")
        assert h["status"] == "degraded" and h["alerts"] == ["trip_lat"]
        # recovery: a quiet interval clears the rule and health returns
        ev.evaluate_once()
        assert _get(port, "/alertz")["firing"] == []
        h = _get(port, "/healthz")
        assert h["status"] == "ok" and "alerts" not in h
    finally:
        httpd.shutdown()
        httpd.server_close()
        eng.close()


def _get_post(port, path, obj):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(obj).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def test_background_thread_lifecycle():
    reg = MetricsRegistry()
    reg.gauge("t_bg", "").set(9)
    ev = obs_alerts.AlertEvaluator(registry=reg, period_s=0.05)
    ev.add_rule(obs_alerts.parse_rule("bg:t_bg:>:1"))
    ev.start()
    try:
        deadline = 5.0
        import time as _t

        t0 = _t.monotonic()
        while ev.firing() != ["bg"] and _t.monotonic() - t0 < deadline:
            _t.sleep(0.02)
        assert ev.firing() == ["bg"]
        assert ev.status()["running"]
    finally:
        ev.stop()
    assert not ev.status()["running"]


def test_configure_via_obs_configure_starts_nothing_without_rules():
    # the CLI path: obs.configure with no alert= keys must not spawn a
    # thread or change evaluator state
    from cxxnet_tpu import obs

    obs.configure([("telemetry", "0")])
    assert not obs_alerts.evaluator().status()["running"]
    assert obs_alerts.evaluator().rules() == []
