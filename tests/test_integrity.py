"""Integrity-plane tests: fingerprints, replica vote, shadow audit,
serve golden canary.

The cross-process paths (allgather vote, quarantine + elastic rebuild)
are covered by the SDC=1 tier-1 lane (``tools/sdc_smoke.py``) and the
chaos matrix (``tests/test_faults.py`` ``device.state:bitflip``); this
file owns the in-process units: the digest algebra, the vote, the
IntegrityPlane driver, the trainer's shadow re-execution, and the
engine's golden-canary lifecycle against real checkpoints.
"""

import os

import numpy as np
import pytest

from cxxnet_tpu import config as cfgmod
from cxxnet_tpu import serve
from cxxnet_tpu.integrity import canary
from cxxnet_tpu.integrity.fingerprint import (
    combine_digests,
    digest_array,
    digest_device_array,
)
from cxxnet_tpu.integrity.plane import (
    IntegrityError,
    IntegrityPlane,
    check_state,
    vote,
)
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.obs import events as obs_events
from cxxnet_tpu.utils import checkpoint as ckpt

MLP_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:a1] = relu:a1
layer[a1->out] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = 32
dev = cpu
eta = 0.1
"""


def make_trainer(seed=0, cfg=MLP_CFG, extra=()):
    tr = NetTrainer()
    tr.set_params(cfgmod.parse_pairs(cfg))
    tr.set_param("seed", str(seed))
    for n, v in extra:
        tr.set_param(n, v)
    tr.init_model()
    return tr


def _flip_bit(a: np.ndarray, elem: int, bit: int) -> np.ndarray:
    out = a.copy().reshape(-1)
    w = out[elem:elem + 1].view(f"u{out.dtype.itemsize}")
    w ^= w.dtype.type(1 << bit)
    return out.reshape(a.shape)


# ----------------------------------------------------------------------
# digest algebra
@pytest.mark.parametrize("dtype", ["float32", "float64", "int32",
                                   "uint8", "float16"])
def test_digest_detects_every_single_bitflip_smallarray(dtype):
    """Exhaustive over a small tensor: EVERY single-bit flip changes
    the digest — the no-false-negative core of the SDC sentinel."""
    rng = np.random.RandomState(7)
    a = (rng.randn(3, 5) * 8).astype(dtype)
    base = digest_array(a)
    itembits = a.dtype.itemsize * 8
    for elem in range(a.size):
        for bit in range(itembits):
            assert digest_array(_flip_bit(a, elem, bit)) != base, (
                f"{dtype}: flip elem={elem} bit={bit} went undetected")


def test_digest_combine_of_slices_equals_whole():
    rng = np.random.RandomState(3)
    a = rng.randn(8, 6).astype(np.float32)
    whole = digest_array(a)
    parts = [
        digest_array(a[0:3], index=(slice(0, 3), slice(0, 6)),
                     shape=a.shape),
        digest_array(a[3:8], index=(slice(3, 8), slice(0, 6)),
                     shape=a.shape),
    ]
    assert combine_digests(parts) == whole
    # order-invariant (modular sums): any shard arrival order agrees
    assert combine_digests(reversed(parts)) == whole
    # column split too (non-contiguous blocks, strided global indices)
    cols = [
        digest_array(a[:, 0:2], index=(slice(0, 8), slice(0, 2)),
                     shape=a.shape),
        digest_array(a[:, 2:6], index=(slice(0, 8), slice(2, 6)),
                     shape=a.shape),
    ]
    assert combine_digests(cols) == whole


def test_digest_is_position_sensitive():
    """s2's index weighting catches element swaps that a plain modular
    sum (s1) cannot."""
    a = np.asarray([1.0, 2.0, 3.0, 4.0], np.float32)
    b = np.asarray([2.0, 1.0, 3.0, 4.0], np.float32)
    da, db = digest_array(a), digest_array(b)
    assert da[0] == db[0]  # same multiset of words
    assert da[1] != db[1]  # different placement


def test_digest_device_array_matches_numpy_oracle():
    import jax.numpy as jnp

    a = np.random.RandomState(1).randn(16, 4).astype(np.float32)
    assert digest_device_array(jnp.asarray(a)) == digest_array(a)


def test_digest_rejects_mismatched_block():
    a = np.zeros((2, 2), np.float32)
    with pytest.raises(ValueError, match="does not match"):
        digest_array(a, index=(slice(0, 3), slice(0, 2)), shape=(4, 2))


# ----------------------------------------------------------------------
# the vote
def _grp(name, members):
    return {(name, ((0, 4, None),)): members}


def test_vote_names_strict_minority_rank():
    good, bad = (11, 22), (11, 23)
    findings = vote(_grp("w", [(0, good), (1, good), (2, bad), (3, good)]))
    assert len(findings) == 1
    f = findings[0]
    assert f["tensor"] == "w" and f["rank"] == 2 and f["ranks"] == [2]
    assert f["replicas"] == 4


def test_vote_two_way_tie_names_no_rank():
    findings = vote(_grp("w", [(0, (1, 1)), (1, (2, 2))]))
    assert len(findings) == 1
    assert findings[0]["rank"] is None
    assert findings[0]["ranks"] == [0, 1]
    # 2-2 split on four replicas: corrupt, but unattributable
    findings = vote(_grp("w", [(0, (1, 1)), (1, (1, 1)),
                               (2, (2, 2)), (3, (2, 2))]))
    assert len(findings) == 1 and findings[0]["rank"] is None


def test_vote_unanimous_and_singleton_are_clean():
    assert vote(_grp("w", [(0, (5, 5)), (1, (5, 5)), (2, (5, 5))])) == []
    assert vote(_grp("w", [(0, (5, 5))])) == []


def test_vote_multiple_bad_replicas_unnamed():
    """Two corrupt minority holders with DIFFERENT digests: the group
    is flagged but no single rank can be named."""
    findings = vote(_grp("w", [(0, (1, 1)), (1, (1, 1)), (2, (1, 1)),
                               (3, (7, 7)), (4, (8, 8))]))
    assert len(findings) == 1
    assert findings[0]["rank"] is None and findings[0]["ranks"] == [3, 4]


# ----------------------------------------------------------------------
# trainer state sweep + IntegrityPlane driver
def test_check_state_clean_then_bitflip_caught_on_mesh():
    """Replicated params on a 4-device trivial mesh: a single injected
    bit flip on ONE device copy turns the sweep's verdict and the
    plane raises the typed error naming the tensor."""
    import random

    tr = make_trainer(extra=(("dev", "tpu:0-3"),))
    assert check_state(tr)["clean"]
    plane = IntegrityPlane(every=2)
    assert not plane.due(0) and plane.due(1)
    assert plane.check_round(tr, 0) is None  # off-cadence: no sweep
    v = plane.check_round(tr, 1)
    assert v is not None and v["clean"] and v["replicas"] == 4
    assert plane.last_clean_round == 1
    flipped = tr.inject_bitflip(random.Random(5))
    verdict = check_state(tr)
    assert not verdict["clean"]
    assert any(f["tensor"] == flipped["tensor"]
               for f in verdict["findings"])
    with pytest.raises(IntegrityError) as ei:
        plane.check_round(tr, 3)
    assert ei.value.kind == "state"
    assert ei.value.tensor == flipped["tensor"]
    assert plane.last_clean_round == 1  # the poisoned round never counts
    assert plane.snapshot()["checks"] == 2  # off-cadence sweeps don't count


def test_shadow_step_clean_and_injected_mismatch():
    tr = make_trainer()
    assert tr.shadow_step(4) is None  # two traces, bitwise-equal grads
    plane = IntegrityPlane(every=1, shadow=1)
    assert plane.check_round(tr, 0)["clean"]
    tr.set_param("inject_shadow_mismatch", "1")
    with pytest.raises(IntegrityError) as ei:
        plane.check_round(tr, 1)
    assert ei.value.kind == "shadow" and ei.value.tensor == "loss"
    assert tr.inject_shadow_mismatch == 0  # one-shot: next check clean
    assert plane.check_round(tr, 2)["clean"]


# ----------------------------------------------------------------------
# canary primitives
def test_probe_batch_deterministic():
    a = canary.probe_batch(0xC0FFEE, 4, (1, 1, 16))
    b = canary.probe_batch(0xC0FFEE, 4, (1, 1, 16))
    np.testing.assert_array_equal(a, b)
    assert a.shape == (4, 1, 1, 16) and a.dtype == np.float32
    assert not np.array_equal(a, canary.probe_batch(0xC0FFED, 4, (1, 1, 16)))


def test_scores_crc_is_bit_and_shape_sensitive():
    s = np.arange(12, dtype=np.float32)
    assert canary.scores_crc(s) == canary.scores_crc(s.copy())
    assert canary.scores_crc(s) != canary.scores_crc(_flip_bit(s, 3, 0))
    # same bytes, different shape: still distinguished (shape header)
    assert (canary.scores_crc(s.reshape(3, 4))
            != canary.scores_crc(s.reshape(4, 3)))


def test_block_matches_pipeline_gates():
    blk = canary.make_probe_block(1, 4, (16,), 0xABCD, "cpu")
    assert canary.block_matches_pipeline(blk, backend="cpu", quant=False)
    assert not canary.block_matches_pipeline(blk, backend="tpu", quant=False)
    assert not canary.block_matches_pipeline(blk, backend="cpu", quant=True)
    no_crc = canary.make_probe_block(1, 4, (16,), None, "cpu")
    assert "crc32" not in no_crc
    assert not canary.block_matches_pipeline(no_crc, backend="cpu",
                                             quant=False)


# ----------------------------------------------------------------------
# engine golden canary end to end
def _save_round(tr, model_dir, round_):
    os.makedirs(model_dir, exist_ok=True)
    tr.round = round_
    tr.save_model(os.path.join(model_dir, f"{round_:04d}.model"))


def _canary_engine(mdir):
    return serve.Engine(cfg=MLP_CFG + "integrity_probe = 1\n",
                        model_dir=mdir, max_batch_size=8,
                        batch_timeout_ms=0, silent=True)


def test_engine_canary_detects_and_recovers(tmp_path):
    mdir = str(tmp_path / "models")
    _save_round(make_trainer(seed=1), mdir, 1)
    eng = _canary_engine(mdir)
    try:
        snap = eng.snapshot_stats()["integrity"]
        assert snap["probe"] == 1 and snap["golden_src"] == "local"
        assert eng.check_canary()  # frozen model reproduces its golden
        assert eng.healthz()["status"] == "ok"
        # injected CRC drift: degrade WITHOUT dying, keep predicting
        eng.inject_canary_mismatch = 1
        assert not eng.check_canary()
        h = eng.healthz()
        assert h["status"] == "degraded"
        assert "integrity_failed" in h["reasons"]
        assert eng.predict(np.zeros((2, 16), np.float32)).shape == (2,)
        assert [e for e in obs_events.recent(100, kind="integrity.detect")
                if e.get("kind_") == "canary"]
        # one-shot fault: the next sweep is clean and clears the latch
        assert eng.check_canary()
        assert eng.healthz()["status"] == "ok"
        assert eng.snapshot_stats()["integrity"]["runs"] == 3
    finally:
        eng.close()


def test_engine_canary_manifest_binding_and_rebase(tmp_path):
    """A manifest probe block whose CRC this engine reproduces is
    binding (src=manifest); a stale/foreign CRC re-bases the golden
    with an event instead of a false alarm."""
    mdir = str(tmp_path / "models")
    _save_round(make_trainer(seed=1), mdir, 1)
    probe_eng = _canary_engine(mdir)
    golden = probe_eng.snapshot_stats()["integrity"]["golden_crc32"]
    rows = max(1, min(8, probe_eng.max_batch_size))
    shape = tuple(probe_eng._row_shapes[0])
    probe_eng.close()

    import jax

    path = os.path.join(mdir, "0001.model")
    man = ckpt.read_manifest(path)

    def rewrite(crc):
        ckpt.write_manifest(
            path, round_=man["round"], net_fp=man["net_fingerprint"],
            save_ustate=man["save_ustate"],
            probe=canary.make_probe_block(0xC0FFEE, rows, shape, crc,
                                          jax.default_backend()))

    rewrite(golden)
    eng = _canary_engine(mdir)
    try:
        snap = eng.snapshot_stats()["integrity"]
        assert snap["golden_src"] == "manifest"
        assert snap["golden_crc32"] == golden
        assert eng.check_canary()
    finally:
        eng.close()

    rewrite(golden ^ 0xDEAD)  # foreign pipeline's answer: rebase
    eng = _canary_engine(mdir)
    try:
        snap = eng.snapshot_stats()["integrity"]
        assert snap["golden_src"] == "rebased"
        assert snap["golden_crc32"] == golden  # re-based to OWN score
        assert eng.check_canary()  # and it is NOT a false alarm
        assert [e for e in obs_events.recent(
            100, kind="integrity.golden_rebased")
            if e.get("manifest_crc32") == (golden ^ 0xDEAD)]
    finally:
        eng.close()


def test_trainer_commits_probe_block_at_save(tmp_path):
    """task=train with integrity_probe=1 writes the probe block (spec +
    single-process golden CRC) into every checkpoint manifest."""
    from conftest import run_cli
    from test_cli import make_conf

    conf = make_conf(tmp_path, num_round=2,
                     extra="integrity_probe = 1\n")
    r = run_cli([conf], str(tmp_path))
    assert r.returncode == 0, r.stdout + r.stderr
    man = ckpt.read_manifest(str(tmp_path / "models" / "0002.model"))
    blk = man.get("probe")
    assert isinstance(blk, dict)
    assert blk["rows"] >= 1 and isinstance(blk["shape"], list)
    assert blk.get("crc32") is not None  # single-process: scored golden
    assert blk["backend"] == "cpu"
    # the committed spec regenerates the batch bit-for-bit
    p = canary.probe_batch(blk["seed"], blk["rows"], tuple(blk["shape"]))
    assert p.shape == (blk["rows"],) + tuple(blk["shape"])
