"""IO pipeline tests: iterators, batching semantics, augmentation, formats."""

import os

import numpy as np
import pytest

from cxxnet_tpu import config as C
from cxxnet_tpu.io.data import create_iterator
from cxxnet_tpu.io.mnist import (
    read_idx_images,
    read_idx_labels,
    write_idx_images,
    write_idx_labels,
)


def make_mnist_files(tmp_path, n=50, hw=8):
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (n, hw, hw)).astype(np.uint8)
    labels = rng.randint(0, 10, n).astype(np.uint8)
    pi, pl = str(tmp_path / "img.idx"), str(tmp_path / "lab.idx")
    write_idx_images(pi, imgs)
    write_idx_labels(pl, labels)
    return pi, pl, imgs, labels


def chain(text):
    cfg = C.parse_pairs(text)
    it = create_iterator(cfg)
    it.init()
    return it


def test_idx_roundtrip(tmp_path):
    pi, pl, imgs, labels = make_mnist_files(tmp_path)
    np.testing.assert_array_equal(read_idx_images(pi), imgs)
    np.testing.assert_array_equal(read_idx_labels(pl), labels)


def test_mnist_iterator_flat(tmp_path):
    pi, pl, imgs, labels = make_mnist_files(tmp_path)
    it = chain(f'iter = mnist\npath_img = "{pi}"\npath_label = "{pl}"\nbatch_size = 16\nsilent=1\n')
    batches = list(it)
    assert len(batches) == 3  # 50 // 16, last partial dropped
    assert batches[0].data.shape == (16, 64)
    np.testing.assert_allclose(
        batches[0].data[0], imgs[0].reshape(-1) / 256.0, rtol=1e-6
    )
    assert batches[0].label[0, 0] == labels[0]
    # second epoch identical
    again = list(it)
    np.testing.assert_allclose(again[0].data, batches[0].data)


def test_mnist_iterator_image_shuffle(tmp_path):
    pi, pl, imgs, labels = make_mnist_files(tmp_path)
    it = chain(
        f'iter = mnist\npath_img = "{pi}"\npath_label = "{pl}"\n'
        f"batch_size = 16\ninput_flat = 0\nshuffle = 1\nsilent=1\n"
    )
    b = next(iter(it))
    assert b.data.shape == (16, 8, 8, 1)
    # shuffled: first instance is (very likely) not original index 0
    assert b.inst_index is not None


def test_csv_iterator(tmp_path):
    rows = ["1,0.5,0.25,0.125,0.0", "0,1,2,3,4"]
    f = tmp_path / "d.csv"
    f.write_text("\n".join(rows) + "\n")
    it = chain(
        f'iter = csv\nfilename = "{f}"\nbatch_size = 2\n'
        f"input_shape = 1,1,4\nlabel_width = 1\nsilent=1\n"
    )
    b = next(iter(it))
    np.testing.assert_allclose(b.data, [[0.5, 0.25, 0.125, 0.0], [1, 2, 3, 4]])
    np.testing.assert_allclose(b.label[:, 0], [1, 0])


def test_round_batch_wraps(tmp_path):
    rows = [f"{i},{i},{i},{i},{i}" for i in range(5)]
    f = tmp_path / "d.csv"
    f.write_text("\n".join(rows) + "\n")
    it = chain(
        f'iter = csv\nfilename = "{f}"\nbatch_size = 4\n'
        f"input_shape = 1,1,4\nround_batch = 1\nsilent=1\n"
    )
    it.before_first()
    assert it.next()
    b1 = it.value()
    assert b1.num_batch_padd == 0
    assert it.next()
    b2 = it.value()
    # one real instance (4) + 3 wrapped from the head
    assert b2.num_batch_padd == 3
    np.testing.assert_allclose(b2.data[:, 0], [4, 0, 1, 2])
    assert not it.next()
    # next epoch: continues after the wrap (reference num_overflow semantics)
    it.before_first()
    assert it.next()
    b3 = it.value()
    np.testing.assert_allclose(b3.data[:, 0], [3, 4, 0, 1])


def test_no_round_batch_pads(tmp_path):
    rows = [f"{i},{i},{i},{i},{i}" for i in range(5)]
    f = tmp_path / "d.csv"
    f.write_text("\n".join(rows) + "\n")
    it = chain(
        f'iter = csv\nfilename = "{f}"\nbatch_size = 4\ninput_shape = 1,1,4\nsilent=1\n'
    )
    bs = list(it)
    assert bs[1].num_batch_padd == 3


def test_membuffer(tmp_path):
    pi, pl, *_ = make_mnist_files(tmp_path)
    it = chain(
        f'iter = mnist\npath_img = "{pi}"\npath_label = "{pl}"\n'
        f"batch_size = 16\nsilent=1\niter = membuffer\nmax_nbatch = 2\n"
    )
    assert len(list(it)) == 2
    assert len(list(it)) == 2  # replays


def test_threadbuffer(tmp_path):
    pi, pl, imgs, labels = make_mnist_files(tmp_path)
    base_batches = list(
        chain(f'iter = mnist\npath_img = "{pi}"\npath_label = "{pl}"\nbatch_size = 16\nsilent=1\n')
    )
    it = chain(
        f'iter = mnist\npath_img = "{pi}"\npath_label = "{pl}"\n'
        f"batch_size = 16\nsilent=1\niter = threadbuffer\n"
    )
    got = list(it)
    assert len(got) == len(base_batches)
    np.testing.assert_allclose(got[0].data, base_batches[0].data)
    got2 = list(it)
    assert len(got2) == len(base_batches)


def test_synthetic_iterator():
    it = chain("iter = synthetic\nnsample = 64\ninput_shape = 1,1,8\nbatch_size = 16\n")
    bs = list(it)
    assert len(bs) == 4
    assert bs[0].data.shape == (16, 8)
    assert set(np.unique(bs[0].label)) <= set(range(10))


def test_augment_crop_and_mirror(tmp_path):
    # build an image .lst + augment chain via imgbin raw pages
    from cxxnet_tpu.io.imgbin import BinPageWriter, encode_raw

    rng = np.random.RandomState(0)
    imgs = rng.rand(6, 12, 12, 3).astype(np.float32) * 255
    binp = str(tmp_path / "d.bin")
    w = BinPageWriter(binp)
    for im in imgs:
        w.push(encode_raw(im))
    w.close()
    lst = tmp_path / "d.lst"
    lst.write_text("".join(f"{i}\t{i % 2}\tx.jpg\n" for i in range(6)))
    it = chain(
        f'iter = imgbin\nimage_bin = "{binp}"\nimage_list = "{lst}"\nraw_pixels = 1\n'
        f"input_shape = 3,8,8\nbatch_size = 6\nsilent = 1\n"
    )
    b = next(iter(it))
    assert b.data.shape == (6, 8, 8, 3)
    # center crop by default: offset (2,2)
    np.testing.assert_allclose(b.data[0], imgs[0][2:10, 2:10], rtol=1e-5)
    # fixed mirror=1 flips horizontally
    it2 = chain(
        f'iter = imgbin\nimage_bin = "{binp}"\nimage_list = "{lst}"\nraw_pixels = 1\n'
        f"input_shape = 3,8,8\nbatch_size = 6\nmirror = 1\nsilent = 1\n"
    )
    b2 = next(iter(it2))
    np.testing.assert_allclose(b2.data[0], imgs[0][2:10, 2:10][:, ::-1], rtol=1e-5)


def test_augment_mean_image_cache(tmp_path):
    from cxxnet_tpu.io.imgbin import BinPageWriter, encode_raw

    imgs = np.ones((4, 8, 8, 3), np.float32) * np.arange(1, 5)[:, None, None, None]
    binp = str(tmp_path / "d.bin")
    w = BinPageWriter(binp)
    for im in imgs:
        w.push(encode_raw(im))
    w.close()
    lst = tmp_path / "d.lst"
    lst.write_text("".join(f"{i}\t0\tx.jpg\n" for i in range(4)))
    meanp = str(tmp_path / "mean.npz")
    spec = (
        f'iter = imgbin\nimage_bin = "{binp}"\nimage_list = "{lst}"\nraw_pixels = 1\n'
        f'input_shape = 3,8,8\nbatch_size = 4\nimage_mean = "{meanp}"\nsilent = 1\n'
    )
    it = chain(spec)
    b = next(iter(it))
    # mean image = 2.5 → instance 0 becomes 1-2.5 = -1.5 everywhere
    np.testing.assert_allclose(b.data[0], -1.5, rtol=1e-5)
    assert os.path.exists(meanp)
    # second run loads the cached mean
    b2 = next(iter(chain(spec)))
    np.testing.assert_allclose(b2.data, b.data)


def test_imgbin_jpeg_roundtrip(tmp_path):
    from PIL import Image

    from cxxnet_tpu.io.imgbin import BinPageWriter, iter_bin_pages

    rng = np.random.RandomState(1)
    img = rng.randint(0, 255, (10, 10, 3)).astype(np.uint8)
    import io as _io

    buf = _io.BytesIO()
    Image.fromarray(img).save(buf, "PNG")
    binp = str(tmp_path / "d.bin")
    w = BinPageWriter(binp)
    w.push(buf.getvalue())
    w.close()
    pages = list(iter_bin_pages(binp))
    assert len(pages) == 1 and len(pages[0]) == 1
    back = np.asarray(Image.open(_io.BytesIO(pages[0][0])))
    np.testing.assert_array_equal(back, img)


def test_attach_txt(tmp_path):
    pi, pl, *_ = make_mnist_files(tmp_path, n=32)
    att = tmp_path / "extra.txt"
    att.write_text("".join(f"{i} {i * 1.0} {i * 2.0}\n" for i in range(32)))
    it = chain(
        f'iter = mnist\npath_img = "{pi}"\npath_label = "{pl}"\nbatch_size = 16\n'
        f'silent=1\niter = attachtxt\nattach_file = "{att}"\n'
    )
    b = next(iter(it))
    assert len(b.extra_data) == 1
    assert b.extra_data[0].shape == (16, 2)
    np.testing.assert_allclose(b.extra_data[0][:, 1], 2.0 * b.inst_index)


def test_test_skipread(tmp_path):
    rows = [f"{i},{i},{i},{i},{i}" for i in range(8)]
    f = tmp_path / "d.csv"
    f.write_text("\n".join(rows) + "\n")
    it = chain(
        f'iter = csv\nfilename = "{f}"\nbatch_size = 4\ninput_shape = 1,1,4\n'
        f"test_skipread = 1\nsilent=1\n"
    )
    it.before_first()
    n = 0
    while it.next() and n < 10:
        n += 1
    assert n == 10  # keeps yielding the same batch without reading


def test_affine_rotate90_exact(tmp_path):
    """Pin the affine matrix: 90° rotation maps (y,x) -> (x, H-1-y)."""
    from cxxnet_tpu.io.augment import AugmentIterator
    from cxxnet_tpu.io.batch import DataInst, InstIterator

    class OneImage(InstIterator):
        def __init__(self, img):
            self.img = img
            self.done = False

        def init(self):
            pass

        def before_first(self):
            self.done = False

        def next(self):
            if self.done:
                return False
            self.done = True
            return True

        def value(self):
            return DataInst(0, self.img, np.zeros(1, np.float32))

    img = np.zeros((9, 9, 1), np.float32)
    img[2, 6, 0] = 100.0
    aug = AugmentIterator(OneImage(img))
    aug.set_param("input_shape", "1,9,9")
    aug.set_param("rotate", "90")
    aug.set_param("fill_value", "0")
    aug.init()
    aug.before_first()
    assert aug.next()
    out = aug.value().data
    # forward M for angle=90: dst_x = src_y, dst_y = -src_x (+center shift)
    # pixel at (row 2, col 6) must land near (row 8-6, col 2) = (2, 2)
    got = np.unravel_index(np.argmax(out[..., 0]), out[..., 0].shape)
    assert abs(got[0] - 2) <= 1 and abs(got[1] - 2) <= 1, got
    assert out.max() > 50  # mass preserved through bilinear resample


def test_imgbin_partition_maker(tmp_path):
    """Shard-splitting tool: size-bounded partitions + direct packing."""
    import subprocess
    import sys as _sys

    from cxxnet_tpu.io.imgbin import iter_bin_pages

    root = tmp_path / "imgs"
    root.mkdir()
    lst = tmp_path / "all.lst"
    with open(lst, "w") as f:
        for i in range(6):
            p = root / f"im{i}.jpg"
            p.write_bytes(b"x" * 2048)
            f.write(f"{i}\t{float(i)}\t{p.name}\n")
    out = tmp_path / "shards"
    r = subprocess.run(
        [_sys.executable, "tools/imgbin_partition_maker.py",
         "--img_list", str(lst), "--img_root", str(root),
         "--prefix", "train", "--out", str(out),
         "--partition_size", "1", "--pack"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r.returncode == 0, r.stderr
    pairs = [ln.split("\t") for ln in r.stdout.strip().splitlines()]
    assert len(pairs) >= 1
    total = 0
    for lst_path, bin_path in pairs:
        assert os.path.exists(lst_path) and os.path.exists(bin_path)
        for page in iter_bin_pages(bin_path):
            total += len(page)
    assert total == 6  # every image landed in some shard


def test_mnist_iterator_dist_sharding(tmp_path):
    """Worker k of n reads disjoint rows k::n (imgbin discipline); the
    shards cover the dataset exactly once."""
    from cxxnet_tpu.io.mnist import (MNISTIterator, write_idx_images,
                                     write_idx_labels)

    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (40, 4, 4)).astype(np.uint8)
    labels = np.arange(40).astype(np.uint8) % 10
    write_idx_images(str(tmp_path / "img.idx"), imgs)
    write_idx_labels(str(tmp_path / "lab.idx"), labels)

    seen = []
    for rank in range(2):
        it = MNISTIterator()
        it.set_param("path_img", str(tmp_path / "img.idx"))
        it.set_param("path_label", str(tmp_path / "lab.idx"))
        it.set_param("batch_size", "10")
        it.set_param("silent", "1")
        it.set_param("dist_num_worker", "2")
        it.set_param("dist_worker_rank", str(rank))
        it.init()
        while it.next():
            seen.extend(it.value().label[:, 0].tolist())
    assert sorted(seen) == sorted(labels.tolist())


# ------------------------------------------------ distributed sharding
def test_shard_rows_equal_and_disjoint():
    from cxxnet_tpu.io.data import shard_rows

    n, w = 63, 2
    shards = [shard_rows(n, k, w) for k in range(w)]
    assert all(len(s) == n // w for s in shards)  # equal => equal steps
    flat = np.concatenate(shards)
    assert len(set(flat.tolist())) == len(flat)  # disjoint
    with pytest.raises(ValueError):
        shard_rows(3, 0, 4)


def test_shard_rows_block_mode_reassembles_global_batches():
    """dist_shard=block contract (the bitwise mesh-parity lane): rank
    p's k-th local batch is exactly rows [k*B*w + p*B, ...+B) of the
    global stream, so interleaving the shards batch-by-batch rebuilds
    the single-process row order."""
    from cxxnet_tpu.io.data import shard_rows

    n, w, block = 70, 4, 8  # 2 full global batches of 32, tail dropped
    shards = [shard_rows(n, k, w, block=block) for k in range(w)]
    assert all(len(s) == 16 for s in shards)  # equal => equal steps
    rebuilt = []
    for k in range(2):  # global batch k = ranks' k-th blocks, in order
        for s in shards:
            rebuilt.extend(s[k * block:(k + 1) * block].tolist())
    assert rebuilt == list(range(64))
    flat = np.concatenate(shards)
    assert len(set(flat.tolist())) == len(flat)  # still disjoint
    with pytest.raises(ValueError):
        shard_rows(31, 0, 4, block=8)  # not even one global batch


def test_mnist_dist_shards_run_equal_batch_counts(tmp_path):
    from cxxnet_tpu.io.mnist import (MNISTIterator, write_idx_images,
                                     write_idx_labels)

    rng = np.random.RandomState(0)
    n = 63  # odd: k::n slicing would give 32 vs 31 rows
    write_idx_images(str(tmp_path / "img"), rng.randint(0, 255, (n, 4, 4)))
    write_idx_labels(str(tmp_path / "lab"), rng.randint(0, 10, (n,)))
    counts, seen = [], []
    for rank in range(2):
        it = MNISTIterator()
        assert it.supports_dist_shard()
        for k, v in (("path_img", str(tmp_path / "img")),
                     ("path_label", str(tmp_path / "lab")),
                     ("batch_size", "16"), ("silent", "1"),
                     ("dist_num_worker", "2"),
                     ("dist_worker_rank", str(rank))):
            it.set_param(k, v)
        it.init()
        it.before_first()
        c = 0
        while it.next():
            seen.extend(it.value().inst_index.tolist())
            c += 1
        counts.append(c)
    assert counts[0] == counts[1]  # unequal => SPMD deadlock
    assert len(set(seen)) == len(seen)  # disjoint shards


def test_csv_dist_shard(tmp_path):
    from cxxnet_tpu.config import parse_pairs, split_sections
    from cxxnet_tpu.io.data import create_iterator

    rows = np.hstack([
        np.arange(21)[:, None] % 3,
        np.random.RandomState(0).randn(21, 4),
    ])
    np.savetxt(tmp_path / "d.csv", rows, delimiter=",")
    got = []
    for rank in range(2):
        text = f"""
data = train
iter = csv
  filename = {tmp_path}/d.csv
  input_shape = 1,1,4
  batch_size = 5
iter = end
"""
        sec = split_sections(parse_pairs(text)).find("data")[0]
        it = create_iterator(sec.entries)
        assert it.supports_dist_shard()
        it.set_param("dist_num_worker", "2")
        it.set_param("dist_worker_rank", str(rank))
        it.init()
        it.before_first()
        n = 0
        while it.next():
            n += 1
        got.append(n)
    assert got[0] == got[1] == 2  # floor(21/2)=10 rows -> 2 batches each


def test_synth_dist_ranks_distinct_data_same_task():
    from cxxnet_tpu.io.synth import SyntheticIterator

    outs = {}
    for rank, nw in ((0, 1), (0, 2), (1, 2)):
        it = SyntheticIterator()
        it.set_param("batch_size", "8")
        it.set_param("nsample", "32")
        it.set_param("input_shape", "1,1,16")
        if nw > 1:
            it.set_param("dist_num_worker", str(nw))
            it.set_param("dist_worker_rank", str(rank))
        it.init()
        outs[(rank, nw)] = np.array(it._data)
    # rank 0 of a dist run sees the exact single-process stream
    np.testing.assert_array_equal(outs[(0, 1)], outs[(0, 2)])
    # other ranks draw different samples
    assert not np.allclose(outs[(0, 2)], outs[(1, 2)])


def test_cli_rejects_unshardable_train_iter_multiproc(monkeypatch, tmp_path):
    """The CLI guard itself: a 2-process run whose train iterator cannot
    shard must fail loudly instead of feeding both processes identical
    data."""
    from cxxnet_tpu import cli as climod
    from cxxnet_tpu.io.synth import SyntheticIterator
    from cxxnet_tpu.parallel import distributed

    monkeypatch.setattr(distributed, "process_info", lambda: (0, 2))
    monkeypatch.setattr(SyntheticIterator, "supports_dist_shard",
                        lambda self: False)
    conf = tmp_path / "t.conf"
    conf.write_text("""
dev = cpu
batch_size = 8
num_round = 1
model_dir = {d}
data = train
iter = synthetic
  nsample = 32
iter = end
netconfig = start
layer[0->1] = fullc:fc
  nhidden = 4
layer[1->1] = softmax
netconfig = end
input_shape = 1,1,16
eta = 0.1
""".format(d=tmp_path))
    from cxxnet_tpu import config as cfgmod

    task = climod.LearnTask()
    for name, val in cfgmod.parse_file(str(conf)):
        task.set_param(name, val)
    with pytest.raises(ValueError, match="dist_num_worker"):
        task.init()


def test_imgbin_rejects_fewer_shards_than_workers(tmp_path):
    from cxxnet_tpu.io.imgbin import BinPageWriter, ImageBinIterator

    w = BinPageWriter(str(tmp_path / "a.bin"))
    w.push(b"xx")
    w.close()
    (tmp_path / "a.lst").write_text("0\t1\tx.jpg\n")
    it = ImageBinIterator()
    it.set_param("image_bin", str(tmp_path / "a.bin"))
    it.set_param("image_list", str(tmp_path / "a.lst"))
    it.set_param("dist_num_worker", "2")
    it.set_param("dist_worker_rank", "0")
    with pytest.raises(ValueError, match="shard file"):
        it.init()


def test_imgbin_epoch_cap_equalizes_steps(tmp_path):
    """Unequal shard files: every worker's epoch is capped at the
    smallest worker's row count (the equal-steps contract)."""
    import io as _pyio

    from PIL import Image

    from cxxnet_tpu.io.imgbin import BinPageWriter, ImageBinIterator

    def jpeg():
        buf = _pyio.BytesIO()
        Image.new("RGB", (4, 4)).save(buf, "JPEG")
        return buf.getvalue()

    for name, n in (("a", 3), ("b", 1)):  # worker0: 3 rows, worker1: 1
        w = BinPageWriter(str(tmp_path / f"{name}.bin"))
        lines = []
        for i in range(n):
            w.push(jpeg())
            lines.append(f"{i}\t0\t{name}{i}.jpg")
        w.close()
        (tmp_path / f"{name}.lst").write_text("\n".join(lines) + "\n")
    counts = []
    for rank in range(2):
        it = ImageBinIterator()
        it.set_param("native_decoder", "0")
        for name in ("a", "b"):
            it.set_param("image_bin", str(tmp_path / f"{name}.bin"))
            it.set_param("image_list", str(tmp_path / f"{name}.lst"))
        it.set_param("dist_num_worker", "2")
        it.set_param("dist_worker_rank", str(rank))
        it.init()
        it.before_first()
        c = 0
        while it.next():
            c += 1
        counts.append(c)
    assert counts == [1, 1]



# ------------------------------------------------ image_conf shorthand
def _write_conf_shards(tmp_path, ids, rows_per_shard=2):
    """<prefix%i>.bin/.lst shard fixtures with per-shard labels = id."""
    import io as _pyio

    from PIL import Image

    from cxxnet_tpu.io.imgbin import BinPageWriter

    def jpeg():
        buf = _pyio.BytesIO()
        Image.new("RGB", (4, 4)).save(buf, "JPEG")
        return buf.getvalue()

    prefix = str(tmp_path / "part_%02d")
    for i in ids:
        w = BinPageWriter((prefix % i) + ".bin")
        lines = []
        for r in range(rows_per_shard):
            w.push(jpeg())
            lines.append(f"{i * 100 + r}\t{i}\tp{i}_{r}.jpg")
        w.close()
        with open((prefix % i) + ".lst", "w") as f:
            f.write("\n".join(lines) + "\n")
    return prefix


def _conf_iter(prefix, ids, rank=0, nworker=1):
    from cxxnet_tpu.io.imgbin import ImageBinIterator

    it = ImageBinIterator()
    it.set_param("native_decoder", "0")
    it.set_param("image_conf_prefix", prefix)
    it.set_param("image_conf_ids", ids)
    if nworker > 1:
        it.set_param("dist_num_worker", str(nworker))
        it.set_param("dist_worker_rank", str(rank))
    return it


def test_image_conf_prefix_expands_range(tmp_path):
    """image_conf_prefix/ids is shard-list shorthand: tr_%02d + 1-3 reads
    part_01..part_03 (iter_thread_imbin-inl.hpp:189-220 parity)."""
    prefix = _write_conf_shards(tmp_path, [1, 2, 3])
    it = _conf_iter(prefix, "1-3")
    it.init()
    labels = []
    while it.next():
        labels.append(int(it.value().label[0]))
    assert labels == [1, 1, 2, 2, 3, 3]  # all shards, id order


def test_image_conf_dist_contiguous_blocks(tmp_path):
    """Workers take CONTIGUOUS id blocks (ceil split), not round-robin:
    4 ids over 2 workers -> {1,2} and {3,4}."""
    prefix = _write_conf_shards(tmp_path, [1, 2, 3, 4])
    per_rank = []
    for rank in range(2):
        it = _conf_iter(prefix, "1-4", rank=rank, nworker=2)
        it.init()
        seen = set()
        while it.next():
            seen.add(int(it.value().label[0]))
        per_rank.append(seen)
    assert per_rank == [{1, 2}, {3, 4}]


def test_image_conf_too_many_workers(tmp_path):
    """4 ids over 3 workers: ceil blocks are 2,2,0 — the empty tail
    worker is an error (reference raises the same)."""
    import pytest

    prefix = _write_conf_shards(tmp_path, [1, 2, 3, 4])
    it = _conf_iter(prefix, "1-4", rank=2, nworker=3)
    with pytest.raises(ValueError, match="too many workers"):
        it.init()


def test_image_conf_exclusive_with_explicit_lists(tmp_path):
    import pytest

    prefix = _write_conf_shards(tmp_path, [1])
    it = _conf_iter(prefix, "1-1")
    it.set_param("image_bin", (prefix % 1) + ".bin")
    it.set_param("image_list", (prefix % 1) + ".lst")
    with pytest.raises(ValueError, match="not both"):
        it.init()


def test_image_conf_bad_prefix_is_labeled_error(tmp_path):
    import pytest

    it = _conf_iter(str(tmp_path / "no_pattern_"), "1-2")
    with pytest.raises(ValueError, match="image_conf_prefix"):
        it.init()


def test_ps_rank_env_overrides_rank_with_conf_workers(tmp_path, monkeypatch):
    """Hadoop-style launch parity: conf sets dist_num_worker, only the
    PS_RANK env carries the per-process rank — rank must apply
    (iter_thread_imbin-inl.hpp:190-194 applies it unconditionally)."""
    prefix = _write_conf_shards(tmp_path, [1, 2, 3, 4])
    from cxxnet_tpu.io.imgbin import ImageBinIterator

    monkeypatch.setenv("PS_RANK", "1")
    it = ImageBinIterator()
    it.set_param("native_decoder", "0")
    it.set_param("image_conf_prefix", prefix)
    it.set_param("image_conf_ids", "1-4")
    it.set_param("dist_num_worker", "2")  # conf knows W, env knows rank
    it.init()
    seen = set()
    while it.next():
        seen.add(int(it.value().label[0]))
    assert seen == {3, 4}  # second contiguous block


# --- libsvm sparse iterator (CSR DataBatch fields, data.h:97-101) -------

def _write_libsvm(tmp_path, lines):
    p = tmp_path / "train.libsvm"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def _libsvm_iter(path, **params):
    from cxxnet_tpu.io.data import create_iterator

    cfg = [("iter", "libsvm"), ("data_path", path)]
    cfg += [(k, str(v)) for k, v in params.items()]
    cfg.append(("iter", "end"))
    it = create_iterator(cfg)
    it.init()
    return it


def test_libsvm_csr_roundtrip(tmp_path):
    """CSR fields carry exactly the file's nonzeros; densify matches."""
    import numpy as np

    path = _write_libsvm(tmp_path, [
        "1 0:1.5 3:2.0",
        "0 1:4.0",
        "2 0:1.0 2:3.0 4:5.0",
        "1 3:7.0",
    ])
    it = _libsvm_iter(path, batch_size=2)
    assert it.next()
    b = it.value()
    assert b.is_sparse()
    assert b.sparse_row_ptr.tolist() == [0, 2, 3]
    assert b.sparse_index.tolist() == [0, 3, 1]
    assert b.sparse_value.tolist() == [1.5, 2.0, 4.0]
    idx, val = b.get_row_sparse(0)
    assert idx.tolist() == [0, 3] and val.tolist() == [1.5, 2.0]
    # densified view agrees with the CSR content
    dense = np.zeros((2, 5), np.float32)
    dense[0, [0, 3]] = [1.5, 2.0]
    dense[1, 1] = 4.0
    np.testing.assert_array_equal(b.data, dense)
    assert b.label[:, 0].tolist() == [1.0, 0.0]
    assert it.next()
    b2 = it.value()
    assert b2.sparse_row_ptr.tolist() == [0, 3, 4]
    assert not it.next()
    it.before_first()
    assert it.next()  # rewind works


def test_libsvm_round_batch_pads_and_marks(tmp_path):
    """Short final batch wraps to the front with num_batch_padd set
    (data.h:86-88 contract), like the dense iterators."""
    path = _write_libsvm(tmp_path, [
        "1 0:1.0", "0 1:2.0", "1 2:3.0",
    ])
    it = _libsvm_iter(path, batch_size=2, round_batch=1, num_feature=4)
    assert it.next() and it.value().num_batch_padd == 0
    assert it.next()
    b = it.value()
    assert b.num_batch_padd == 1
    assert b.batch_size == 2
    assert b.inst_index.tolist() == [2, 0]  # wrapped to the front
    idx, val = b.get_row_sparse(0)
    assert idx.tolist() == [2] and val.tolist() == [3.0]


def test_libsvm_no_round_batch_still_full_size(tmp_path):
    """round_batch=0 must ALSO emit a full-size final batch with
    num_batch_padd set (iter_batch_proc-inl.hpp round_batch=0 branch:
    the batch buffer stays batch_size-shaped, only the padd count
    marks the dead rows) — a shape-varying last batch breaks
    static-shape jit consumers (advisor r4 finding)."""
    path = _write_libsvm(tmp_path, [
        "1 0:1.0", "0 1:2.0", "1 2:3.0",
    ])
    it = _libsvm_iter(path, batch_size=2, round_batch=0, num_feature=4)
    assert it.next() and it.value().num_batch_padd == 0
    assert it.next()
    b = it.value()
    assert b.batch_size == 2              # full-size, NOT take-size
    assert b.data.shape == (2, 4)
    assert b.num_batch_padd == 1
    assert b.inst_index.tolist() == [2, 2]  # replicated, not wrapped
    assert not it.next()


def test_libsvm_dense_batch_rejects_sparse_api(tmp_path):
    import pytest

    from cxxnet_tpu.io.data import DataBatch
    import numpy as np

    b = DataBatch(data=np.zeros((2, 3)), label=np.zeros((2, 1)))
    assert not b.is_sparse()
    with pytest.raises(ValueError, match="dense"):
        b.get_row_sparse(0)


def test_libsvm_round_batch_smaller_file_than_batch(tmp_path):
    """A file smaller than one batch wraps repeatedly instead of
    crashing (code-review r4 finding)."""
    path = _write_libsvm(tmp_path, ["1 0:1.0", "0 1:2.0"])
    it = _libsvm_iter(path, batch_size=5, round_batch=1, num_feature=3)
    assert it.next()
    b = it.value()
    assert b.batch_size == 5
    assert b.num_batch_padd == 3
    assert b.inst_index.tolist() == [0, 1, 0, 1, 0]
    assert not it.next()


def test_attachtxt_preserves_sparse_fields(tmp_path):
    """attachtxt over libsvm keeps the CSR part flowing through the
    wrap (code-review r4 finding: the rebuilt DataBatch dropped it)."""
    path = _write_libsvm(tmp_path, ["1 0:1.0", "0 1:2.0"])
    txt = tmp_path / "extra.txt"
    txt.write_text("0 9.0\n1 8.0\n")
    from cxxnet_tpu.io.data import create_iterator

    it = create_iterator([
        ("iter", "libsvm"), ("data_path", str(path)), ("batch_size", "2"),
        ("iter", "attachtxt"), ("attach_file", str(txt)),
        ("iter", "end"),
    ])
    it.init()
    assert it.next()
    b = it.value()
    assert b.is_sparse() and b.sparse_row_ptr.tolist() == [0, 1, 2]
    assert len(b.extra_data) == 1
