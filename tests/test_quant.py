"""Quantized inference (doc/performance.md "Quantized inference").

Covers the scheme's math (``ops/quant.py``), the gated export
(``nnet/quant.py`` / ``task=export_quant``), the quantized artifact
round trip, the serve-plane integration (bucket-cache key isolation,
weight-bytes identity), and the inference-build branch-embed promotion.
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu import config as C
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.models import mnist_mlp_conf
from cxxnet_tpu.nnet import quant as nquant
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.ops import quant as opsq


# ----------------------------------------------------------------------
# primitives
def test_per_channel_scale_roundtrip():
    """Codes * scales reconstructs each output channel to within half a
    step of its own scale (per-channel, NOT per-tensor: a channel 100x
    smaller than its neighbour keeps its own resolution)."""
    rng = np.random.RandomState(0)
    w = rng.randn(3, 3, 8, 16).astype(np.float32)
    w[..., 3] *= 0.01  # a tiny channel a per-tensor scale would crush
    q, s = opsq.quantize_weight(w, out_axis=3)
    assert q.dtype == np.int8 and s.shape == (16,)
    dq = np.asarray(opsq.dequantize_weight(q, s, out_axis=3))
    for o in range(16):
        np.testing.assert_allclose(
            dq[..., o], w[..., o], atol=float(s[o]) * 0.5 + 1e-8)
    # the tiny channel's scale is proportionally tiny
    assert s[3] < 0.05 * s.max()


def test_symmetric_range_clipping():
    """Codes stay in [-127, 127] (never -128 — negation-exact), the
    max-|w| element maps to exactly +-127, and all-zero channels get
    scale 1 with all-zero codes."""
    w = np.array([[4.0, -8.0, 0.5], [0.0, 0.0, 0.0]], np.float32).T
    # columns are output channels (fullc layout (nout, nin) -> axis 0)
    q, s = opsq.quantize_weight(w.T, out_axis=0)
    assert q.min() >= -127 and q.max() <= 127
    assert q[0].max() == 127 or q[0].min() == -127
    np.testing.assert_array_equal(q[1], 0)
    assert s[1] == 1.0
    # a value far beyond the scale clips, not wraps
    qq, ss = opsq.quantize_weight(
        np.array([[1.0, 1000.0]], np.float32), out_axis=0)
    assert qq.max() == 127


def test_dequant_free_fold_matches_dequantized_math():
    """The serving spelling — raw codes into the GEMM, rescale folded
    after — equals dequantize-then-matmul exactly in f32 (the scale
    commutes out of the contraction)."""
    rng = np.random.RandomState(1)
    w = rng.randn(6, 10).astype(np.float32)  # fullc (nout, nin)
    b = rng.randn(6).astype(np.float32)
    x = jnp.asarray(rng.randn(4, 10).astype(np.float32))
    q, s = opsq.quantize_weight(w, out_axis=0)
    lp = {opsq.QKEY: jnp.asarray(q), opsq.SKEY: jnp.asarray(s),
          "bias": jnp.asarray(b)}
    folded = np.asarray(opsq.fc_apply_q(lp, x))
    dq = np.asarray(opsq.dequantize_weight(q, s, out_axis=0))
    ref = np.asarray(x) @ dq.T + b
    np.testing.assert_allclose(folded, ref, rtol=1e-5, atol=1e-5)


def test_rescale_commutes_out_of_contraction_exactly():
    """The PR-10 exactness claim the kernel epilogue builds on, pinned
    at the bit level: with power-of-two per-channel scales the rescale
    commutes out of the int8 contraction EXACTLY — ``x @ (q*s).T`` is
    bitwise ``(x @ q.T) * s`` — because scaling by 2^k only shifts
    exponents.  The in-kernel spelling (quantize -> MXU -> rescale in
    the epilogue, ``ops/kernels/int8_gemm.py``) and the stock spelling
    (rescale folded into the f32 bias add outside) are therefore the
    same math, and the epilogue kernel is checked bit-equal to the
    jitted dequant-free ``fc_apply_q`` — its bit-level reference."""
    import jax

    from cxxnet_tpu.ops.kernels import int8_gemm

    rng = np.random.RandomState(7)
    q = rng.randint(-127, 128, (6, 10)).astype(np.int8)
    x = jnp.asarray(rng.randn(4, 10).astype(np.float32))

    # power-of-two scales: commuting is bitwise
    s2 = (2.0 ** rng.randint(-8, 3, 6)).astype(np.float32)
    inside = np.asarray(x) @ (q.astype(np.float32) * s2[:, None]).T
    outside = (np.asarray(x) @ q.astype(np.float32).T) * s2
    np.testing.assert_array_equal(inside, outside)

    # general (measured) scales: same value up to one final rounding
    w = rng.randn(6, 10).astype(np.float32)
    qw, sw = opsq.quantize_weight(w, out_axis=0)
    inside = np.asarray(x) @ (qw.astype(np.float32) * sw[:, None]).T
    outside = (np.asarray(x) @ qw.astype(np.float32).T) * sw
    np.testing.assert_allclose(inside, outside, rtol=1e-6, atol=0)

    # the epilogue kernel vs its bit-level reference (the JITTED stock
    # lowering — the net's programs are always compiled, and on CPU the
    # eager spelling differs from its own compiled form via FMA fusion)
    b = rng.randn(6).astype(np.float32)
    lp = {opsq.QKEY: jnp.asarray(qw), opsq.SKEY: jnp.asarray(sw),
          "bias": jnp.asarray(b)}
    ref = np.asarray(jax.jit(opsq.fc_apply_q)(lp, x))
    got = np.asarray(int8_gemm.int8_gemm_rescale(
        x, lp[opsq.QKEY], lp[opsq.SKEY], lp["bias"], interpret=True))
    np.testing.assert_array_equal(ref, got)


def test_conv_apply_q_matches_dequantized_conv():
    from jax import lax

    rng = np.random.RandomState(2)
    w = rng.randn(3, 3, 4, 8).astype(np.float32)
    x = jnp.asarray(rng.randn(2, 9, 9, 4).astype(np.float32))
    q, s = opsq.quantize_weight(w, out_axis=3)
    lp = {opsq.QKEY: jnp.asarray(q), opsq.SKEY: jnp.asarray(s)}
    got = np.asarray(opsq.conv_apply_q(lp, x, 1, 1, 1))
    dq = opsq.dequantize_weight(q, s, out_axis=3)
    ref = np.asarray(lax.conv_general_dilated(
        x, dq, (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC")))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_weight_bytes_accounting():
    params = {
        "a": {opsq.QKEY: np.zeros((100, 10), np.int8),
              opsq.SKEY: np.zeros(100, np.float32),
              "bias": np.zeros(100, np.float32)},
        "b": {"wmat": np.zeros((10, 10), np.float32)},
    }
    actual, f32 = opsq.weight_bytes(params)
    assert actual == 1000 + 400 + 400 + 400
    assert f32 == 4000 + 400 + 400  # scales don't exist in the f32 model


# ----------------------------------------------------------------------
# trainer-level plan / fallback / artifact
CONV_CFG = [
    ("dev", "cpu"),
    ("batch_size", "8"),
    ("input_shape", "4,10,10"),
    ("eta", "0.1"),
    ("netconfig", "start"),
    ("layer[0->1]", "conv:c1"),
    ("kernel_size", "3"), ("pad", "1"), ("nchannel", "8"),
    ("random_type", "xavier"),
    ("layer[1->2]", "relu"),
    ("layer[2->3]", "flatten"),
    ("layer[3->4]", "fullc:fc"),
    ("nhidden", "6"), ("random_type", "xavier"),
    ("layer[4->4]", "softmax"),
    ("netconfig", "end"),
]


def _conv_trainer(extra=()):
    tr = NetTrainer()
    tr.set_params(CONV_CFG + [("seed", "3")] + list(extra))
    tr.init_model()
    return tr


def _batch(n=8, seed=0):
    rng = np.random.RandomState(seed)
    return DataBatch(data=rng.rand(n, 10, 10, 4).astype(np.float32),
                     label=np.zeros((n, 1), np.float32))


def test_plan_and_per_layer_bf16_fallback():
    tr = _conv_trainer()
    plan = nquant.build_plan(tr)
    assert plan == {"l0_c1": "int8", "l3_fc": "int8"}
    ref = tr.predict(_batch())
    tq = _conv_trainer()
    plan["l0_c1"] = "bf16"
    nquant.apply_plan(tq, plan, source_params=tr.params)
    # the fallback layer stores a bfloat16 kernel, the int8 one codes
    assert tq.params["l0_c1"]["wmat"].dtype == jnp.bfloat16
    assert opsq.QKEY in tq.params["l3_fc"]
    assert (tq.predict(_batch()) == ref).mean() >= 0.9
    a, f = opsq.weight_bytes(tq.params)
    assert 1.0 < f / a < 4.0  # between all-f32 and all-int8


def test_wino_conv_starts_at_bf16():
    """A conv that opted into the Winograd path must not be silently
    rerouted through the direct int8 conv — the plan starts it bf16."""
    tr = _conv_trainer(extra=[("conv_wino", "2")])
    plan = nquant.build_plan(tr)
    assert plan["l0_c1"] == "bf16" and plan["l3_fc"] == "int8"


def test_quantized_trainer_is_inference_only():
    tr = _conv_trainer()
    nquant.apply_plan(tr, nquant.build_plan(tr))
    b = _batch()
    with pytest.raises(ValueError, match="inference-only"):
        tr.update(b)
    with pytest.raises(ValueError, match="inference-only"):
        tr.update_scan(np.stack([b.data]), np.stack([b.label]))


def test_artifact_roundtrip_and_manifest(tmp_path):
    tr = _conv_trainer()
    nquant.apply_plan(tr, nquant.build_plan(tr))
    p = str(tmp_path / "0007.quant.model")
    tr.save_model(p, round_=7)
    man = json.load(open(p + ".manifest.json"))
    assert man["quant"]["scheme"] == "int8"
    assert man["quant"]["int8_layers"] == 2
    t2 = NetTrainer()
    t2.set_params(CONV_CFG)
    t2.load_model(p)
    assert t2.quant_scheme == "int8"
    assert t2.quant_plan == {"l0_c1": "int8", "l3_fc": "int8"}
    assert t2.params["l0_c1"][opsq.QKEY].dtype == jnp.int8
    np.testing.assert_array_equal(
        np.asarray(t2.predict(_batch())), np.asarray(tr.predict(_batch())))


def test_bf16_leaves_survive_npz(tmp_path):
    """npz cannot hold ml_dtypes natively; the ~bf16 spelling must
    round-trip the fallback kernels bit-exactly."""
    tr = _conv_trainer()
    plan = {"l0_c1": "bf16", "l3_fc": "bf16"}
    nquant.apply_plan(tr, plan, scheme="bf16")
    p = str(tmp_path / "b.quant.model")
    tr.save_model(p)
    t2 = NetTrainer()
    t2.set_params(CONV_CFG)
    t2.load_model(p)
    for key in ("l0_c1", "l3_fc"):
        a = tr.params[key]["wmat"]
        b = t2.params[key]["wmat"]
        assert b.dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(a).view(np.uint16), np.asarray(b).view(np.uint16))


# ----------------------------------------------------------------------
# the gated export
def _train_mlp(tmp_path, rounds=2):
    cfg = C.parse_pairs(mnist_mlp_conf(batch_size=50, synthetic=True,
                                       dev="cpu"))
    tr = NetTrainer()
    tr.set_params(cfg + [("seed", "11")])
    tr.init_model()
    from cxxnet_tpu.config import split_sections
    from cxxnet_tpu.io.data import create_iterator

    split = split_sections(cfg)
    it = create_iterator(split.find("data")[0].entries)
    ev = create_iterator(split.find("eval")[0].entries)
    for itr in (it, ev):  # the CLI's global-entry application
        for n, v in split.global_entries:
            itr.set_param(n, v)
        itr.init()
    for _ in range(rounds):
        it.before_first()
        while it.next():
            tr.update(it.value())
    path = str(tmp_path / "0002.model")
    tr.round = 2
    tr.save_model(path, round_=2)
    return cfg, path, ev


def test_export_publishes_and_gate_records_agreement(tmp_path):
    cfg, path, ev = _train_mlp(tmp_path)
    v = nquant.export_quantized(cfg, path, eval_iter=ev,
                                calib_batches=4)
    assert v["ok"] and v["path"].endswith("0002.quant.model")
    assert v["agreement"] >= 0.99
    assert v["bytes_ratio"] > 3.5
    man = json.load(open(v["path"] + ".manifest.json"))
    assert man["quant"]["agreement"] == v["agreement"]
    assert man["round"] == 2


def test_export_reject_leaves_f32_serving(tmp_path):
    """An unreachable gate demotes every layer to bf16, then rejects:
    NOTHING is written, and an engine over the model dir still serves
    the plain f32 artifact."""
    from cxxnet_tpu import serve

    cfg, path, ev = _train_mlp(tmp_path)
    v = nquant.export_quantized(cfg, path, eval_iter=ev,
                                min_agreement=1.01, calib_batches=2)
    assert not v["ok"] and v["path"] is None
    assert set(v["layers"].values()) == {"bf16"}  # full demotion tried
    assert not os.path.exists(
        nquant.quant_artifact_path(path))
    eng = serve.Engine(cfg=cfg, model_dir=str(tmp_path),
                       max_batch_size=16)
    try:
        assert eng.healthz()["quant"] == "f32"
        st = eng.snapshot_stats()["model"]
        assert st["weight_bytes"] == st["weight_bytes_f32"]
    finally:
        eng.close()


def test_export_without_eval_requires_explicit_optout(tmp_path):
    cfg, path, _ev = _train_mlp(tmp_path)
    with pytest.raises(ValueError, match="agreement gate"):
        nquant.export_quantized(cfg, path, eval_iter=None)
    v = nquant.export_quantized(cfg, path, eval_iter=None,
                                min_agreement=0.0)
    assert v["ok"] and v["gated"] is False


# ----------------------------------------------------------------------
# serve plane
def test_bucket_cache_key_isolation(tmp_path):
    """f32 and int8 programs of the SAME net never collide: the quant
    scheme is part of the cache key, so a rolling comparison keeps two
    disjoint program sets warm."""
    from cxxnet_tpu.serve.cache import ShapeBucketCache

    tr = _conv_trainer()
    tq = _conv_trainer()
    nquant.apply_plan(tq, nquant.build_plan(tq), source_params=tr.params)
    cf, cq = ShapeBucketCache(tr, 16), ShapeBucketCache(tq, 16)
    x = _batch(4).data
    cf.scores(x)
    cq.scores(x)
    kf, kq = cf.keys_snapshot()[0], cq.keys_snapshot()[0]
    assert kf[0] == kq[0]  # same net fingerprint ...
    assert kf[-1] == "" and kq[-1] == "int8"  # ... different programs
    assert kf != kq


def test_engine_prefers_quant_sibling_and_reports_bytes(tmp_path):
    from cxxnet_tpu import serve

    cfg, path, ev = _train_mlp(tmp_path)
    v = nquant.export_quantized(cfg, path, eval_iter=ev,
                                calib_batches=2)
    assert v["ok"]
    eng = serve.Engine(cfg=cfg + [("quant", "int8")],
                       model_dir=str(tmp_path), max_batch_size=16)
    try:
        h = eng.healthz()
        assert h["quant"] == "int8"
        assert h["model"].endswith(".quant.model")
        assert h["round"] == 2
        st = eng.snapshot_stats()["model"]
        assert st["weight_bytes_f32"] / st["weight_bytes"] > 3.5
        # the engine serves real predictions through the int8 programs
        out = eng.predict(np.random.RandomState(0)
                          .rand(4, 784).astype(np.float32))
        assert out.shape == (4,)
        # registry gauges carry the same identity for /metricsz
        from cxxnet_tpu.obs import registry as obs_registry

        snap = obs_registry().snapshot()
        assert (snap["serve_weight_bytes"]["serve_weight_bytes"]
                == st["weight_bytes"])
        qs = snap["serve_quant_scheme"]
        assert qs['serve_quant_scheme{scheme="int8"}'] == 1.0
        assert qs['serve_quant_scheme{scheme="f32"}'] == 0.0
    finally:
        eng.close()


def test_engine_same_round_sibling_swap_on_reload(tmp_path):
    """A gated export published AFTER serve start (the natural order)
    must still land: the reload poll swaps onto a .quant.model sibling
    of the round ALREADY serving, and rounds never move backward."""
    from cxxnet_tpu import serve

    cfg, path, ev = _train_mlp(tmp_path)
    eng = serve.Engine(cfg=cfg + [("quant", "int8")],
                       model_dir=str(tmp_path), max_batch_size=16)
    try:
        # no sibling yet: serving the base checkpoint (ungated quant)
        assert eng.model_path.endswith("0002.model")
        assert not eng.try_reload()  # nothing new: no-op
        v = nquant.export_quantized(cfg, path, eval_iter=ev,
                                    calib_batches=2)
        assert v["ok"]
        assert eng.try_reload()  # same round, preferred artifact
        assert eng.round == 2
        assert eng.model_path.endswith("0002.quant.model")
        assert not eng.try_reload()  # now stable
    finally:
        eng.close()


def test_engine_falls_back_to_f32_base_on_broken_sibling(tmp_path):
    """A CRC-valid but unloadable .quant.model must not cost the whole
    round: the engine serves that round's f32 base instead of silently
    falling back to an older round."""
    from cxxnet_tpu import serve
    from cxxnet_tpu.utils import checkpoint as ckpt

    cfg, path, _ev = _train_mlp(tmp_path)
    qp = nquant.quant_artifact_path(path)
    # self-consistent manifest over a garbage payload: validates, but
    # load_model explodes on the magic check
    ckpt.write_checkpoint(qp, b"not a model", round_=2,
                          quant={"scheme": "int8"})
    eng = serve.Engine(cfg=cfg + [("quant", "int8")],
                       model_dir=str(tmp_path), max_batch_size=16)
    try:
        assert eng.round == 2
        assert eng.model_path.endswith("0002.model")
        assert eng.healthz()["quant"] == "int8"  # on-load quantization
    finally:
        eng.close()


def test_engine_on_load_quantization_without_artifact(tmp_path):
    """quant=int8 on a plain checkpoint: the trainer quantizes at load
    (ungated) — the engine still reports the scheme and the ~4x."""
    from cxxnet_tpu import serve

    cfg, path, _ev = _train_mlp(tmp_path)
    eng = serve.Engine(cfg=cfg + [("quant", "int8")],
                       model_dir=str(tmp_path), max_batch_size=16)
    try:
        assert eng.healthz()["quant"] == "int8"
        assert eng.healthz()["model"].endswith("0002.model")
        st = eng.snapshot_stats()["model"]
        assert st["weight_bytes_f32"] / st["weight_bytes"] > 3.5
    finally:
        eng.close()


# ----------------------------------------------------------------------
# branch-embed promotion (inference builds)
def test_branch_embed_auto_on_for_accelerator_inference():
    """Default (-1 auto): inference program builds fuse on accelerator
    backends only — the block kernel's ~3.6x MACs pay on the MXU and
    cost 7x on CPU (tools/wino_bf16_ab.py --bembed-only) — and the
    train step never auto-fuses; an explicit 0/1 pins every build.
    Fused-vs-unfused serve predictions agree (the fusion is exact up
    to reassociation)."""
    from tests.test_branch_embed import INCEPTION_CFG

    def build(extra=()):
        tr = NetTrainer()
        tr.set_params([(k, v.format(n=0) if k == "dev" else v)
                       for k, v in INCEPTION_CFG]
                      + [("seed", "11"), ("dev", "cpu")] + list(extra))
        tr.init_model()
        return tr

    auto = build()
    assert auto.net.conv_branch_embed == -1
    assert auto.net.use_branch_embed(train=False, backend="tpu") is True
    assert auto.net.use_branch_embed(train=True, backend="tpu") is False
    assert auto.net.use_branch_embed(train=False, backend="cpu") is False
    off = build([("conv_branch_embed", "0")])
    assert off.net.use_branch_embed(train=False, backend="tpu") is False
    fused = build([("conv_branch_embed", "1")])
    assert fused.net.use_branch_embed(train=True, backend="cpu") is True
    # the serve-path parity: the PINNED-fused inference programs equal
    # the unfused ones on the same weights (same seed -> same init)
    rng = np.random.RandomState(3)
    b = DataBatch(data=rng.randn(16, 12, 12, 8).astype(np.float32),
                  label=np.zeros((16, 1), np.float32))
    sf = fused.extract_feature(b, "top[-1]")
    so = off.extract_feature(b, "top[-1]")
    assert fused.net._branch_embed_plan()[1]  # the group really formed
    np.testing.assert_allclose(sf, so, rtol=2e-4, atol=2e-5)
    np.testing.assert_array_equal(fused.predict(b), off.predict(b))
