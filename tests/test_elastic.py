"""Elastic pod: liveness coordination, the collective deadline, the
pinned-order (shard_map) reduction, and backend re-init.

The end-to-end kill-one-process acceptance (4-process CPU mesh, one
rank SIGKILLed, bitwise parity vs a planned-resize run) lives in the
``ELASTIC=1`` lane (``tools/elastic_kill.py``); these tests pin the
pieces in-process: the coordinator/member state machine, the typed
``ReplicaLossError`` surfacing within ``collective_timeout_s``, the
``det_reduce`` determinism contract, teardown/re-init, and the
observability surface (doc/parallel.md "Elastic pod").
"""

import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.parallel import elastic as E
from cxxnet_tpu.utils import faults

# ----------------------------------------------------------------------
# options parsing


def test_options_from_cfg_defaults_and_keys():
    o = E.ElasticOptions.from_cfg([])
    assert not o.elastic and o.min_replicas == 1
    assert o.collective_timeout_s == 30.0
    o = E.ElasticOptions.from_cfg([
        ("elastic", "1"), ("elastic_min_replicas", "2"),
        ("elastic_rejoin_s", "9"), ("elastic_heartbeat_s", "0.1"),
        ("elastic_timeout_s", "0.7"), ("collective_timeout_s", "3"),
        ("elastic_coordinator", "h:1234"), ("elastic_drop_at", "4"),
        ("elastic_join", "1"), ("elastic_join_at", "6"),
    ])
    assert o.elastic and o.join and o.min_replicas == 2
    assert (o.rejoin_s, o.heartbeat_s, o.timeout_s) == (9.0, 0.1, 0.7)
    assert o.collective_timeout_s == 3.0
    assert (o.coordinator, o.drop_at, o.join_at) == ("h:1234", 4, 6)
    with pytest.raises(ValueError, match="elastic_min_replicas"):
        E.ElasticOptions.from_cfg([("elastic_min_replicas", "0")])


def test_resolve_coordinator_defaults_to_dist_port_plus_one():
    o = E.ElasticOptions()
    assert o.resolve_coordinator("node0:9000") == "node0:9001"
    o.coordinator = "other:7"
    assert o.resolve_coordinator("node0:9000") == "other:7"


# ----------------------------------------------------------------------
# coordinator / member state machine (real TCP, no jax involvement)
def _cluster(n=3, min_replicas=1, timeout_s=0.6):
    opts = E.ElasticOptions(elastic=True, heartbeat_s=0.1,
                            timeout_s=timeout_s,
                            min_replicas=min_replicas)
    m0 = E.ElasticMember("localhost:0", 0, opts, host_coordinator=True,
                         num=n, jax_host="localhost")
    members = [m0.start()]
    for r in range(1, n):
        members.append(E.ElasticMember(m0.addr, r, opts).start())
    return opts, members


def _close_all(members):
    for m in members:
        m.close()


def test_loss_detected_and_survivors_replanned():
    """A member that stops heartbeating is classified LOST within
    elastic_timeout_s; survivors receive a re-ranked generation plan
    (relative order kept, rank 0 stays 0) with a fresh jax port."""
    opts, ms = _cluster(3)
    try:
        time.sleep(0.3)
        ms[2]._stop.set()
        ms[2]._beat_thread.join()
        t0 = time.monotonic()
        assert ms[0].lost_event.wait(5), "loss not detected"
        assert time.monotonic() - t0 < 3.0
        assert ms[1].lost_event.wait(2)
        time.sleep(0.3)
        p0, p1 = ms[0].pending_plan(), ms[1].pending_plan()
        assert p0.reason == "replica_lost" and p0.lost_ranks == [2]
        assert (p0.num, p0.rank) == (2, 0)
        assert (p1.num, p1.rank) == (2, 1)
        assert p0.jax_coordinator == p1.jax_coordinator
        assert p0.generation == p1.generation == 2
        # adopting the plan clears the loss latch
        ms[0].ack_generation(p0)
        assert not ms[0].lost_event.is_set()
        # the gauges recorded the transition
        from cxxnet_tpu.obs.registry import registry

        snap = registry().snapshot()
        assert "mesh_replicas" in snap
        assert snap["mesh_replicas"]['mesh_replicas{state="lost"}'] >= 1.0
    finally:
        _close_all(ms)


def test_planned_shrink_drops_highest_rank_idempotently():
    opts, ms = _cluster(3)
    try:
        plans = [m.plan_shrink(5) for m in ms]  # all ranks, same round
        gens = {p.generation for p in plans}
        assert gens == {2}, "one transition, one generation"
        assert plans[2].rank is None, "highest rank leaves"
        assert (plans[0].rank, plans[1].rank) == (0, 1)
        assert plans[0].num == 2 and plans[0].at_round == 5
        assert plans[0].reason == "planned_shrink"
    finally:
        _close_all(ms)


def test_grow_admits_waiter_and_survives_round_skew():
    """A joiner is admitted at the scheduled boundary; a member whose
    boundary call arrives one round late still receives the SAME plan
    (no split rendezvous)."""
    opts, ms = _cluster(2)
    try:
        waiter = E.ElasticMember(ms[0].addr, -1, opts)
        box = {}
        t = threading.Thread(
            target=lambda: box.update(plan=waiter.join(timeout_s=10)),
            daemon=True)
        t.start()
        time.sleep(0.3)
        ms[0].poll_now()
        g = ms[0].grow_round()
        assert g is not None
        pa = ms[0].plan_grow(g)
        pb = ms[1].plan_grow(g + 1)  # skewed boundary: same plan
        assert pa.generation == pb.generation
        assert pa.num == 3 and pa.reason == "grow"
        t.join(timeout=5)
        assert box["plan"].rank == 2
    finally:
        _close_all(ms)


def test_abort_below_min_replicas():
    opts, ms = _cluster(2, min_replicas=2)
    try:
        ms[1]._stop.set()
        ms[1]._beat_thread.join()
        assert ms[0].lost_event.wait(5)
        time.sleep(0.3)
        ms[0].poll_now()
        assert ms[0].abort_reason, "survivors below min must abort"
        assert "elastic_min_replicas" in ms[0].abort_reason
    finally:
        _close_all(ms)


def test_slow_vs_lost_classification():
    """A briefly silent member is only SUSPECT (mesh.replica_slow) —
    it recovers by beating again; silence past elastic_timeout_s is
    LOST (membership removed)."""
    opts, ms = _cluster(2, timeout_s=1.5)
    try:
        # suspend heartbeats for ~4 intervals: suspect, not lost
        ms[1]._stop.set()
        ms[1]._beat_thread.join()
        time.sleep(0.5)
        ms[0].poll_now()
        assert ms[0].suspects() == [1]
        assert not ms[0].lost_event.is_set()
        # resume beating: suspicion clears
        ms[1]._stop = threading.Event()
        ms[1]._beat_thread = threading.Thread(
            target=ms[1]._beat_loop, daemon=True)
        ms[1]._beat_thread.start()
        time.sleep(0.4)
        ms[0].poll_now()
        assert ms[0].suspects() == []
        assert not ms[0].lost_event.is_set()
    finally:
        _close_all(ms)


# ----------------------------------------------------------------------
# collective deadline + classification
class _Stub:
    def __init__(self, lost=False, suspects=()):
        self.lost_event = threading.Event()
        if lost:
            self.lost_event.set()
        self.abort_reason = ""
        self._s = list(suspects)

    def suspects(self):
        return list(self._s)

    def pending_plan(self):
        return None


def test_replica_loss_surfaces_within_collective_timeout():
    """Acceptance: a dead peer inside a collective surfaces as the
    typed ReplicaLossError within collective_timeout_s — via the
    mesh.replica fault site, no real process death needed."""
    faults.install("mesh.replica:hang:1:1")
    tr = NetTrainer()  # sync() is the instrumented fence
    member = _Stub(suspects=[3])
    t0 = time.monotonic()
    with pytest.raises(E.ReplicaLossError) as ei:
        E.guarded_call(tr.sync, member, timeout_s=0.5, what="step fence")
    elapsed = time.monotonic() - t0
    faults.reset()  # release the hung worker
    assert elapsed < 5.0, f"deadline did not bound the hang ({elapsed})"
    assert ei.value.presumed and ei.value.lost == [3]


def test_confirmed_loss_preempts_deadline():
    member = _Stub(lost=True)
    faults.install("mesh.replica:hang:1:1")
    t0 = time.monotonic()
    with pytest.raises(E.ReplicaLossError) as ei:
        E.guarded_call(lambda: faults.fault_point("mesh.replica"),
                       member, timeout_s=30.0, what="collective")
    faults.reset()
    assert time.monotonic() - t0 < 5.0, "confirmed loss must not wait"
    assert not ei.value.presumed


def test_slow_mesh_keeps_waiting():
    """Past the deadline with NO suspect, the guard logs and keeps
    waiting — a slow replica is not a dead one."""
    member = _Stub()

    def slow():
        time.sleep(0.6)
        return 41 + 1

    assert E.guarded_call(slow, member, timeout_s=0.2,
                          what="slow") == 42


def test_guarded_call_passthrough_without_member():
    assert E.guarded_call(lambda: 7, None) == 7


def test_classify_failure_translates_collective_errors():
    member = _Stub(lost=True)
    loss = E.classify_failure(
        ValueError("Gloo all-reduce failed: Connection reset by peer"),
        member, confirm_s=0.1)
    assert isinstance(loss, E.ReplicaLossError) and not loss.presumed
    # an unrelated error is NOT a replica loss
    assert E.classify_failure(ValueError("shape mismatch"),
                              member) is None
    # without a member there is nothing to classify against
    assert E.classify_failure(ValueError("Gloo says hi"), None) is None
    # a ReplicaLossError passes through unchanged
    orig = E.ReplicaLossError("x", lost=[1])
    assert E.classify_failure(orig, member) is orig


# ----------------------------------------------------------------------
# det_reduce: the shard_map determinism contract
MLP_CFG = [
    ("dev", "tpu:0-3"),
    ("batch_size", "16"),
    ("input_shape", "1,1,16"),
    ("seed", "7"),
    ("eta", "0.1"),
    ("momentum", "0.9"),
    ("netconfig", "start"),
    ("layer[0->1]", "fullc:fc1"),
    ("nhidden", "32"),
    ("layer[1->2]", "sigmoid"),
    ("layer[2->3]", "fullc:fc2"),
    ("nhidden", "8"),
    ("layer[3->3]", "softmax"),
    ("netconfig", "end"),
]


def _build(extra=()):
    tr = NetTrainer()
    tr.set_params(list(MLP_CFG) + list(extra))
    tr.init_model()
    return tr


def _steps(tr, n=4, seed=3):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        tr.update(DataBatch(
            data=rng.randn(16, 16).astype(np.float32),
            label=rng.randint(0, 8, (16, 1)).astype(np.float32),
        ))


def test_det_reduce_matches_gspmd_and_is_reproducible():
    """Pinned-order reduction is placement+order, not different math:
    allclose to the GSPMD step, and bitwise equal across runs."""
    a, b, c = _build(), _build([("det_reduce", "1")]), \
        _build([("det_reduce", "1")])
    for tr in (a, b, c):
        _steps(tr)
    for key in a.params:
        for tag in a.params[key]:
            np.testing.assert_allclose(
                np.asarray(a.params[key][tag]),
                np.asarray(b.params[key][tag]),
                rtol=2e-4, atol=2e-5,
                err_msg=f"{key}/{tag}: det_reduce changed the math")
            np.testing.assert_array_equal(
                np.asarray(b.params[key][tag]),
                np.asarray(c.params[key][tag]),
                err_msg=f"{key}/{tag}: det_reduce not deterministic")


def test_det_reduce_hlo_has_no_allreduce():
    """The compiled step's cross-replica combine is the all-gather +
    ordered fold — no all-reduce whose internal order a backend could
    choose per mesh shape."""
    import jax
    import jax.numpy as jnp

    tr = _build([("det_reduce", "1")])
    fn = tr._fused_step_fn()
    txt = fn.lower(
        tr.params, tr.ustates, tr.aux,
        jnp.zeros((16, 16), jnp.float32), jnp.zeros((16, 1), jnp.float32),
        jnp.ones((16,), jnp.float32), jax.random.PRNGKey(0),
        jnp.asarray(0, jnp.int32), (),
    ).compile().as_text()
    assert "all-gather" in txt
    assert "all-reduce" not in txt


def test_det_reduce_rejects_unsupported_shapes():
    for extra, marker in (
        ([("model_parallel", "2")], "model_parallel"),
        ([("zero", "1")], "zero"),
        ([("update_period", "2")], "update_period"),
    ):
        with pytest.raises(ValueError, match="det_reduce"):
            _build([("det_reduce", "1")] + extra)


def test_det_reduce_rejects_stochastic_layers():
    """Dropout under the shard_map region would draw the SAME mask on
    every shard (replicated rng) — rejected, not silently changed."""
    cfg = [
        ("dev", "tpu:0-3"), ("batch_size", "16"),
        ("input_shape", "1,1,16"), ("seed", "7"), ("eta", "0.1"),
        ("det_reduce", "1"),
        ("netconfig", "start"),
        ("layer[0->1]", "fullc:fc1"), ("nhidden", "32"),
        ("layer[1->2]", "dropout"), ("threshold", "0.5"),
        ("layer[2->3]", "fullc:fc2"), ("nhidden", "8"),
        ("layer[3->3]", "softmax"),
        ("netconfig", "end"),
    ]
    tr = NetTrainer()
    tr.set_params(cfg)
    with pytest.raises(ValueError, match="stochastic"):
        tr.init_model()


def test_det_reduce_single_device_is_noop():
    """On a 1-device mesh there is no cross-replica reduction to pin —
    the key is accepted and training runs the plain path."""
    tr = NetTrainer()
    tr.set_params([("dev", "cpu") if k == "dev" else (k, v)
                   for k, v in MLP_CFG] + [("det_reduce", "1")])
    tr.init_model()
    _steps(tr, n=2)
    assert tr.epoch_counter == 2


# ----------------------------------------------------------------------
# elastic x async data-parallel (doc/parallel.md "Async data-parallel"):
# a rebuild must reset the staleness buffers and generation-stamp
# in-flight aggregates so a dead generation's gradient is never applied
def _params_np(tr):
    return {k: {t: np.asarray(w) for t, w in tags.items()}
            for k, tags in tr.params.items()}


def test_async_rebuild_resets_staleness_buffers():
    """The cli rebuild hook (``NetTrainer.async_abandon``): every
    pending aggregate is dropped, the updater moves to the NEW
    membership generation, and the pipeline keeps working after."""
    from cxxnet_tpu.obs.registry import registry

    tr = NetTrainer()
    tr.set_params(list(MLP_CFG) + [("async_overlap", "1"),
                                   ("staleness", "2"),
                                   ("async_resync_period", "1000")])
    tr.init_model()
    _steps(tr, n=2)
    snap = tr.async_snapshot()
    assert sum(snap["pending"]) > 0 and snap["applies"] == 0
    dropped = tr.async_abandon(generation=5, reason="rebuild")
    assert dropped == sum(snap["pending"])
    snap = tr.async_snapshot()
    assert snap["pending"] == [0] * snap["groups"]
    assert snap["generation"] == 5
    reg = registry().snapshot()
    assert ('async_stale_dropped_total{reason="rebuild"}'
            in reg["async_stale_dropped_total"])
    # the rebuilt-generation pipeline still trains
    _steps(tr, n=3)
    tr.async_round_end(1000)  # resync drains the new-gen aggregates
    assert sum(tr.async_snapshot()["pending"]) == 0
    assert tr.async_snapshot()["applies"] > 0


def test_async_stale_generation_aggregate_is_never_applied():
    """The independent guard behind the reset: even if a dead
    generation's aggregate is still sitting in the buffer when the
    generation moves on, the APPLY path re-checks the stamp and
    discards it — the weights never see it."""
    from cxxnet_tpu.obs.registry import registry

    tr = NetTrainer()
    tr.set_params(list(MLP_CFG) + [("async_overlap", "1"),
                                   ("staleness", "1"),
                                   ("async_resync_period", "1000")])
    tr.init_model()
    init = _params_np(tr)
    _steps(tr, n=1)  # one aggregate pending per group, generation 0
    up = tr._async.updater
    assert sum(len(dq) for dq in up._pending) == len(up.groups)
    up.generation = 1  # the membership moved on; buffers not cleared
    drained = up.drain()
    assert drained == 0  # nothing was APPLIED...
    assert up.dropped == len(up.groups)  # ...everything was discarded
    for key in init:
        for tag in init[key]:
            np.testing.assert_array_equal(
                init[key][tag], np.asarray(tr.params[key][tag]),
                err_msg=f"{key}/{tag}: a dead generation's gradient "
                        "reached the weights")
    reg = registry().snapshot()
    assert ('async_stale_dropped_total{reason="generation"}'
            in reg["async_stale_dropped_total"])


# ----------------------------------------------------------------------
# shutdown/re-init regression (satellite: maybe_init_distributed was
# one-shot init-only).  Runs in a SUBPROCESS: the resilient client's
# poll thread cannot be stopped from Python, so an in-pytest client
# would risk the interpreter-exit destructor abort the CLI guards
# against with its own hard-exit.
_REINIT_SCRIPT = r"""
import os, socket, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
import numpy as np
from cxxnet_tpu.parallel import distributed as D

def free_port():
    s = socket.socket(); s.bind(("localhost", 0))
    p = s.getsockname()[1]; s.close(); return p

def collective(tag):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("data",))
    f = jax.jit(lambda x: x.sum(), out_shardings=NamedSharding(mesh, P()))
    x = jax.device_put(np.ones((4,), np.float32),
                       NamedSharding(mesh, P("data")))
    v = float(jax.block_until_ready(f(x)))
    assert v == 4.0, (tag, v)
    print(f"{tag}: ok nproc={jax.process_count()}", flush=True)

# cycle 1: the stock (config-driven) path
assert D.maybe_init_distributed(
    [("dist_coordinator", f"localhost:{free_port()}"),
     ("dist_num_proc", "1"), ("dist_proc_id", "0")])
assert D.distributed_initialized()
collective("gen1")
assert D.shutdown_distributed()  # clean: every step completes
assert not D.distributed_initialized()
# cycle 2: resilient re-init in the SAME process
D.init_distributed(f"localhost:{free_port()}", 1, 0, resilient=True)
assert D.distributed_initialized()
collective("gen2")
D.shutdown_distributed(graceful=False)
# cycle 3: and again — teardown is safe to call twice per process
D.init_distributed(f"localhost:{free_port()}", 1, 0, resilient=True)
collective("gen3")
print("REINIT-OK", flush=True)
sys.stdout.flush()
os._exit(0)  # skip destructor-order teardown (cli.py does the same)
"""


@pytest.mark.slow
def test_shutdown_and_reinit_twice_in_one_process(tmp_path):
    script = tmp_path / "reinit.py"
    script.write_text(_REINIT_SCRIPT)
    import os as _os

    repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, str(script)], capture_output=True, text=True,
        timeout=240, env={**_os.environ, "PYTHONPATH": repo},
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "REINIT-OK" in out.stdout, out.stdout + out.stderr
    for tag in ("gen1", "gen2", "gen3"):
        assert f"{tag}: ok nproc=1" in out.stdout


# ----------------------------------------------------------------------
# observability surface
def test_healthz_degrades_while_rebuilding():
    from cxxnet_tpu import serve
    from test_serve import make_trainer

    eng = serve.Engine(trainer=make_trainer(), max_batch_size=8,
                       batch_timeout_ms=0)
    try:
        assert eng.healthz()["status"] == "ok"
        E.set_rebuilding(True)
        h = eng.healthz()
        assert h["status"] == "degraded"
        assert h["mesh"] == "rebuilding"
    finally:
        E.set_rebuilding(False)
        eng.close()
    assert not E.rebuild_in_progress()


def test_replica_loss_error_carries_typed_fields():
    e = E.ReplicaLossError("gone", lost=[1, 3], generation=4,
                           presumed=True, fatal=False)
    assert e.lost == [1, 3] and e.generation == 4
    assert e.presumed and not e.fatal
