"""Importing reference cxxnet binary checkpoints (tools/import_ref_model).

The fixture writer below re-implements the reference's serialization
independently from the parser, straight from the cited sources
(cxxnet_main.cpp:173-181, nnet_config.h:126-145, utils/io.h:43-74,
layer SaveModel overrides), so parser bugs can't cancel out.
"""

import os
import struct
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from import_ref_model import install, parse_ref_model  # noqa: E402

CONF = """
netconfig = start
layer[0->1] = conv:c1
  kernel_size = 3
  pad = 1
  nchannel = 4
layer[1->2] = batch_norm:bn1
layer[2->3] = prelu:pr1
layer[3->4] = max_pooling
  kernel_size = 2
  stride = 2
layer[4->5] = flatten
layer[5->6] = fullc:fc1
  nhidden = 6
layer[6->6] = softmax
netconfig = end
input_shape = 3,8,8
batch_size = 4
dev = cpu
"""


def _s(b: bytes) -> bytes:
    return struct.pack("<Q", len(b)) + b


def _vec_i32(v) -> bytes:
    return struct.pack("<Q", len(v)) + struct.pack(f"<{len(v)}i", *v)


def _layer_param(**kw) -> bytes:
    """LayerParam per param.h field order (0-based positions):
    0 num_hidden, 1 init_sigma(f), 2 init_sparse, 3 init_uniform(f),
    4 init_bias(f), 5 num_channel, 6 random_type, 7 num_group,
    8 kernel_height, 9 kernel_width, 10 stride, 11 pad_y, 12 pad_x,
    13 no_bias, 14 temp_col_max, 15 silent, 16 num_input_channel,
    17 num_input_node, then 64 reserved."""
    full = [0] * 82
    full[0] = kw.get("num_hidden", 0)
    full[5] = kw.get("num_channel", 0)
    full[7] = kw.get("num_group", 1)
    full[8] = kw.get("kernel_height", 0)
    full[9] = kw.get("kernel_width", 0)
    full[13] = kw.get("no_bias", 0)
    full[17] = kw.get("num_input_node", 0)
    return struct.pack("<82i", *full)


def _tensor(arr: np.ndarray, with_stride: bool) -> bytes:
    out = struct.pack(f"<{arr.ndim}I", *arr.shape)
    if with_stride:
        out += struct.pack("<I", arr.shape[-1])
    return out + arr.astype("<f4").tobytes()


def _write_model(path, with_stride: bool, seed=0):
    rng = np.random.RandomState(seed)
    w = {
        "c1_w": rng.randn(1, 4, 3 * 3 * 3).astype(np.float32),
        "c1_b": rng.randn(4).astype(np.float32),
        "bn_s": rng.randn(4).astype(np.float32),
        "bn_b": rng.randn(4).astype(np.float32),
        "pr_s": rng.randn(4).astype(np.float32),
        "fc_w": rng.randn(6, 64).astype(np.float32),
        "fc_b": rng.randn(6).astype(np.float32),
    }
    # blob: layers in order; only SaveModel-overriders contribute
    blob = b""
    blob += _layer_param(num_channel=4, num_group=1, kernel_height=3,
                         kernel_width=3)
    blob += _tensor(w["c1_w"], with_stride) + _tensor(w["c1_b"], with_stride)
    blob += _tensor(w["bn_s"], with_stride) + _tensor(w["bn_b"], with_stride)
    blob += _tensor(w["pr_s"], with_stride)
    blob += _layer_param(num_hidden=6, num_input_node=64)
    blob += _tensor(w["fc_w"], with_stride) + _tensor(w["fc_b"], with_stride)

    layers = [
        (10, "c1"), (30, "bn1"), (29, "pr1"), (11, ""), (7, ""),
        (1, "fc1"), (2, ""),
    ]
    out = struct.pack("<i", 0)                      # net_type
    out += struct.pack("<2i", 8, len(layers))        # num_nodes, num_layers
    out += struct.pack("<3I", 3, 8, 8)               # NetParam.input_shape
    if with_stride:
        out += struct.pack("<I", 8)                  # Shape<3>::stride_
    out += struct.pack("<2i", 1, 0)                  # init_end, extra_data_num
    out += b"\0" * (31 * 4)                          # reserved
    for k in range(8):
        out += _s(f"node{k}".encode())
    for k, (tid, name) in enumerate(layers):
        out += struct.pack("<ii", tid, -1)
        out += _s(name.encode())
        out += _vec_i32([k]) + _vec_i32([k + 1])
    out += struct.pack("<q", 42)                     # epoch_counter
    out += _s(blob)
    with open(path, "wb") as f:
        f.write(out)
    return w


def _build_trainer():
    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu.nnet.trainer import NetTrainer

    tr = NetTrainer()
    tr.set_params(cfgmod.parse_pairs(CONF))
    tr.init_model()
    return tr


@pytest.mark.parametrize("with_stride", [False, True])
def test_import_roundtrip(tmp_path, with_stride):
    """Both mshadow Shape encodings parse (auto-detected), and every
    weighted layer lands bit-exactly in the conf-built trainer."""
    path = str(tmp_path / "ref.model")
    w = _write_model(path, with_stride)
    net_type, _nodes, infos, epoch, weights, ishape = parse_ref_model(path)
    assert net_type == 0 and epoch == 42
    assert ishape == (3, 8, 8)
    assert [i["type_name"] for i in infos] == [
        "conv", "batch_norm", "prelu", "max_pooling", "flatten",
        "fullc", "softmax"]

    tr = _build_trainer()
    assert install(tr, infos, weights) == 4  # c1, bn1, pr1, fc1
    np.testing.assert_array_equal(
        tr.get_weight("c1", "wmat"), w["c1_w"].reshape(4, 27))
    np.testing.assert_array_equal(tr.get_weight("c1", "bias"),
                                  w["c1_b"][None, :])
    np.testing.assert_array_equal(tr.get_weight("bn1", "wmat"),
                                  w["bn_s"][None, :])
    np.testing.assert_array_equal(tr.get_weight("bn1", "bias"),
                                  w["bn_b"][None, :])
    np.testing.assert_array_equal(tr.get_weight("pr1", "bias"),
                                  w["pr_s"][None, :])
    np.testing.assert_array_equal(tr.get_weight("fc1", "wmat"), w["fc_w"])
    # and the installed model saves/loads as a native checkpoint
    out = str(tmp_path / "out.model")
    tr.save_model(out)
    tr2 = _build_trainer()
    tr2.load_model(out)
    np.testing.assert_array_equal(tr2.get_weight("fc1", "wmat"), w["fc_w"])


def test_import_type_mismatch_rejected(tmp_path):
    """A conf whose layer type disagrees with the binary is refused."""
    path = str(tmp_path / "ref.model")
    _write_model(path, with_stride=False)
    _, _, infos, _, weights, _ = parse_ref_model(path)
    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu.nnet.trainer import NetTrainer

    bad = CONF.replace("batch_norm:bn1", "xelu:bn1")
    tr = NetTrainer()
    tr.set_params(cfgmod.parse_pairs(bad))
    tr.init_model()
    with pytest.raises(ValueError, match="conf says"):
        install(tr, infos, weights)


def test_import_garbage_rejected(tmp_path):
    path = tmp_path / "junk.model"
    path.write_bytes(b"\xff" * 64)
    with pytest.raises(ValueError):
        parse_ref_model(str(path))


@pytest.mark.parametrize("with_stride", [False, True])
def test_export_roundtrip(tmp_path, with_stride):
    """export_ref_model is install's inverse: a conf-built trainer
    exports to the reference binary layout (either mshadow Shape
    encoding), a fresh trainer imports it, and every weighted layer
    matches bit-exactly; epoch_counter rides along."""
    from import_ref_model import export_ref_model

    tr = _build_trainer()
    tr.epoch_counter = 7000
    path = str(tmp_path / "exported.model")
    assert export_ref_model(tr, path, with_stride=with_stride) == 4
    net_type, _nodes, infos, epoch, weights, ishape = parse_ref_model(path)
    assert epoch == 7000
    # NetParam.input_shape must ride through export (the reference's
    # InitNet shapes node 0 from it, neural_net-inl.hpp:218-220)
    assert ishape == (3, 8, 8)
    assert [i["type_name"] for i in infos] == [
        "conv", "batch_norm", "prelu", "max_pooling", "flatten",
        "fullc", "softmax"]
    tr2 = _build_trainer()
    # fresh init differs from tr (different PRNG fold) until installed
    assert install(tr2, infos, weights) == 4
    for name, tag in [("c1", "wmat"), ("c1", "bias"), ("bn1", "wmat"),
                      ("bn1", "bias"), ("pr1", "bias"), ("fc1", "wmat"),
                      ("fc1", "bias")]:
        np.testing.assert_array_equal(tr.get_weight(name, tag),
                                      tr2.get_weight(name, tag),
                                      err_msg=f"{name}/{tag}")


def test_export_import_fuzz_roundtrip(tmp_path):
    """Property sweep over random weighted-layer stacks and both Shape
    encodings: export -> auto-detected parse must return the graph and
    every tensor bit-exactly.  Guards the byte-layout code (which has
    already had one silent field-omission bug — the advisor-r4
    input_shape finding) against layout drift for ANY layer mix, not
    just the one fixture."""
    import numpy as np

    from import_ref_model import export_ref_model

    rng = np.random.RandomState(7)
    weighted = ["conv", "fullc", "batch_norm", "prelu"]
    for trial in range(6):
        with_stride = bool(trial % 2)
        n_ch = int(rng.randint(2, 7))
        picks = [weighted[int(rng.randint(4))] for _ in range(3)]
        lines = ["netconfig = start"]
        node = 0
        for k, t in enumerate(picks):
            name = f"L{k}"
            if t == "conv":
                lines += [f"layer[{node}->{node + 1}] = conv:{name}",
                          "  kernel_size = 3", "  pad = 1",
                          f"  nchannel = {n_ch}"]
            elif t == "fullc":
                lines += [f"layer[{node}->{node + 1}] = flatten"]
                node += 1
                lines += [f"layer[{node}->{node + 1}] = fullc:{name}",
                          f"  nhidden = {n_ch}"]
            elif t == "batch_norm":
                lines += [f"layer[{node}->{node + 1}] = batch_norm:{name}"]
            else:
                lines += [f"layer[{node}->{node + 1}] = prelu:{name}"]
            node += 1
            # fullc flattens: everything after stays flat
            if t == "fullc":
                break
        lines += [f"layer[{node}->{node + 1}] = flatten",
                  f"layer[{node + 1}->{node + 2}] = fullc:out",
                  "  nhidden = 4",
                  f"layer[{node + 2}->{node + 2}] = softmax",
                  "netconfig = end",
                  "input_shape = 3,6,6", "batch_size = 2", "dev = cpu"]
        conf = "\n".join(lines)
        from cxxnet_tpu import config as cfgmod
        from cxxnet_tpu.nnet.trainer import NetTrainer

        tr = NetTrainer()
        tr.set_params(cfgmod.parse_pairs(conf))
        tr.init_model()
        tr.epoch_counter = 100 + trial
        path = str(tmp_path / f"fuzz{trial}.model")
        n = export_ref_model(tr, path, with_stride=with_stride)
        assert n >= 2
        _nt, _nodes, infos, epoch, weights, ishape = parse_ref_model(path)
        assert epoch == 100 + trial
        assert ishape == (3, 6, 6)
        assert len(infos) == len(tr.graph.layers)
        tr2 = NetTrainer()
        tr2.set_params(cfgmod.parse_pairs(conf))
        tr2.init_model()
        assert install(tr2, infos, weights) == n
        for i, spec in enumerate(tr.graph.layers):
            if not spec.name:
                continue
            for tag in ("wmat", "bias"):
                a = tr.get_weight(spec.name, tag)
                if a is None or a.size == 0:
                    continue
                np.testing.assert_array_equal(
                    a, tr2.get_weight(spec.name, tag),
                    err_msg=f"trial {trial} {spec.name}/{tag}")


def test_parser_survives_truncation_everywhere(tmp_path):
    """Every truncation of a valid model raises ValueError (never a
    hang, struct.error leak, or silent partial parse)."""
    path = str(tmp_path / "ref.model")
    _write_model(path, with_stride=False)
    blob = open(path, "rb").read()
    cut_points = sorted(set(
        list(range(0, 64, 7)) + [len(blob) // 3, len(blob) // 2,
                                 len(blob) - 200, len(blob) - 9,
                                 len(blob) - 1]))
    trunc = str(tmp_path / "trunc.model")
    for cut in cut_points:
        with open(trunc, "wb") as f:
            f.write(blob[:cut])
        with pytest.raises(ValueError):
            parse_ref_model(trunc)


def test_migration_workflow_import_then_cli_finetune(tmp_path):
    """The actual migration path end to end: a reference binary
    checkpoint imports, then the CLI finetunes FROM the imported
    checkpoint on synthetic data — the imported weights are the
    starting point of real training, not just a parse artifact."""
    ref = str(tmp_path / "ref.model")
    w = _write_model(ref, with_stride=False)
    conf_txt = CONF + """
data = train
iter = synthetic
  nsample = 16
  input_shape = 3,8,8
  nclass = 6
  label_width = 1
iter = end
eta = 0.01
num_round = 1
model_dir = models
"""
    conf = tmp_path / "net.conf"
    conf.write_text(conf_txt)
    from conftest import run_cli

    r = run_cli(
        [os.path.join(REPO, "tools", "import_ref_model.py"),
         str(conf), ref, str(tmp_path / "imported.model")],
        str(tmp_path), module=False,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    r = run_cli(
        [str(conf), "task=finetune", f"model_in={tmp_path}/imported.model"],
        str(tmp_path),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    # the imported weights must actually be the starting point: finetune
    # logs each layer it copies (trainer.copy_model_from); a silent
    # name/shape mismatch would skip the copy and train from random init
    for name in ("c1", "bn1", "pr1", "fc1"):
        assert f"Copying layer {name}" in r.stdout, r.stdout
    # finetuning moved the weights off the imported values
    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu.nnet.trainer import NetTrainer

    tr = NetTrainer()
    tr.set_params(cfgmod.split_sections(
        cfgmod.parse_pairs(conf_txt)).global_entries)
    tr.init_model()
    tr.load_model(str(tmp_path / "models" / "0001.model"))
    after = tr.get_weight("fc1", "wmat")
    assert after.shape == w["fc_w"].shape
    assert np.abs(after - w["fc_w"]).max() > 0  # training moved them
