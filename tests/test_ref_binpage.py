"""Reference BinaryPage bit-format: golden bytes, round-trip, end-to-end.

The reference packs JPEGs into fixed 64 MiB pages of little-endian i32s
(``/root/reference/src/utils/io.h:225-300``; writer
``/root/reference/tools/im2bin.cpp``): ``data[0] = nrec``,
``data[1..nrec+1]`` cumulative blob sizes, blobs packed backwards from
the page end.  ``RefBinPageWriter`` must emit that layout byte-for-byte
so cxxnet-era ``.bin`` + ``.lst`` packs train without repacking.
"""

import os
import struct
import subprocess
import sys

import numpy as np
import pytest

from cxxnet_tpu.io.imgbin import (
    REF_PAGE_BYTES,
    ImageBinIterator,
    RefBinPageWriter,
    detect_bin_format,
    iter_bin_pages,
    iter_ref_bin_pages,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _golden_page(blobs):
    """Hand-build one page exactly as BinaryPage::Push/Save would:
    int array [N, 0, cum...] at the front, blobs back-to-front from the
    page end (obj r at [end - off[r+1], end - off[r]))."""
    page = bytearray(REF_PAGE_BYTES)
    cum = np.concatenate([[0], np.cumsum([len(b) for b in blobs])])
    hdr = np.concatenate([[len(blobs)], cum]).astype("<i4")
    page[: hdr.nbytes] = hdr.tobytes()
    for r, b in enumerate(blobs):
        page[REF_PAGE_BYTES - int(cum[r + 1]):
             REF_PAGE_BYTES - int(cum[r])] = b
    return bytes(page)


def test_writer_golden_bytes(tmp_path):
    blobs = [b"hello", b"xyz", b"binpage"]
    p = str(tmp_path / "a.bin")
    w = RefBinPageWriter(p)
    for b in blobs:
        w.push(b)
    w.close()
    raw = open(p, "rb").read()
    assert len(raw) == REF_PAGE_BYTES
    assert raw == _golden_page(blobs)
    # spot-check the C++ field semantics directly
    ints = np.frombuffer(raw, "<i4", count=5)
    assert list(ints) == [3, 0, 5, 8, 15]
    assert raw[REF_PAGE_BYTES - 5:] == b"hello"          # first blob at page end
    assert raw[REF_PAGE_BYTES - 8: REF_PAGE_BYTES - 5] == b"xyz"
    assert raw[REF_PAGE_BYTES - 15: REF_PAGE_BYTES - 8] == b"binpage"


def test_detect_and_roundtrip(tmp_path):
    rng = np.random.RandomState(3)
    blobs = [rng.bytes(rng.randint(1, 5000)) for _ in range(40)]
    p = str(tmp_path / "r.bin")
    w = RefBinPageWriter(p)
    for b in blobs:
        w.push(b)
    w.close()
    assert detect_bin_format(p) == "ref"
    got = [b for page in iter_bin_pages(p) for b in page]
    assert got == blobs


def test_multi_page_spill(tmp_path):
    # three ~25 MiB blobs: two fit a page, the third spills to page 2 —
    # same decision rule as BinaryPage::Push returning false in im2bin
    rng = np.random.RandomState(4)
    mb25 = 25 << 20
    blobs = [rng.bytes(mb25), rng.bytes(mb25), rng.bytes(mb25)]
    p = str(tmp_path / "big.bin")
    w = RefBinPageWriter(p)
    for b in blobs:
        w.push(b)
    w.close()
    assert os.path.getsize(p) == 2 * REF_PAGE_BYTES
    pages = list(iter_ref_bin_pages(p))
    assert [len(pg) for pg in pages] == [2, 1]
    assert [b for pg in pages for b in pg] == blobs


def test_oversize_blob_rejected(tmp_path):
    w = RefBinPageWriter(str(tmp_path / "x.bin"))
    with pytest.raises(ValueError, match="64 MiB page"):
        w.push(b"\0" * (REF_PAGE_BYTES - 4))
    w.close()


def test_oversize_blob_rejected_mid_page(tmp_path):
    # oversize after a valid push must also raise (not corrupt the pack)
    p = str(tmp_path / "y.bin")
    w = RefBinPageWriter(p)
    w.push(b"ok")
    with pytest.raises(ValueError, match="64 MiB page"):
        w.push(b"\0" * REF_PAGE_BYTES)
    w.close()
    assert os.path.getsize(p) == REF_PAGE_BYTES
    assert [b for pg in iter_bin_pages(p) for b in pg] == [b"ok"]


def test_empty_pack_iterates_as_no_pages(tmp_path):
    p = str(tmp_path / "empty.bin")
    w = RefBinPageWriter(p)
    w.close()
    assert os.path.getsize(p) == 0
    assert list(iter_bin_pages(p)) == []


def test_im2bin_rejects_unknown_option(tmp_path):
    lst = str(tmp_path / "i.lst")
    open(lst, "w").write("0\t0\tx.jpg\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "im2bin.py"),
         lst, str(tmp_path), str(tmp_path / "o.bin"), "--fromat", "ref"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert r.returncode == 1
    assert "unknown option" in r.stderr


def _write_jpeg_pack(tmp_path, writer_cls, n=10, size=32):
    from PIL import Image
    import io as _io

    binp = str(tmp_path / "pack.bin")
    lst = str(tmp_path / "pack.lst")
    w = writer_cls(binp)
    arrs = []
    with open(lst, "w") as f:
        for i in range(n):
            # smooth gradients survive JPEG nearly intact (noise wouldn't)
            g = np.arange(size, dtype=np.float32)
            arr = np.stack(
                [
                    np.add.outer(g * 3, g * 2) % 256,
                    np.add.outer(g, g * 5 + i * 17) % 256,
                    np.full((size, size), (i * 29) % 256, np.float32),
                ],
                axis=-1,
            ).astype(np.uint8)
            buf = _io.BytesIO()
            Image.fromarray(arr).save(buf, "JPEG", quality=95)
            w.push(buf.getvalue())
            arrs.append(arr)
            f.write(f"{i}\t{i % 3}\timg{i}.jpg\n")
    w.close()
    return binp, lst, arrs


def test_imgbin_iterator_reads_ref_pack(tmp_path):
    binp, lst, arrs = _write_jpeg_pack(tmp_path, RefBinPageWriter)
    it = ImageBinIterator()
    it.set_param("image_bin", binp)
    it.set_param("image_list", lst)
    it.set_param("silent", "1")
    it.set_param("native_decoder", "0")
    it.init()
    seen = 0
    while it.next():
        inst = it.value()
        assert inst.index == seen
        assert inst.data.shape == arrs[seen].shape
        # JPEG is lossy; just require closeness
        assert np.abs(inst.data - arrs[seen]).mean() < 12.0
        seen += 1
    assert seen == len(arrs)


def test_native_reader_reads_ref_pack(tmp_path):
    from cxxnet_tpu.io import native

    if not native.available():
        pytest.skip("native IO library unavailable")
    binp, lst, arrs = _write_jpeg_pack(tmp_path, RefBinPageWriter)
    it = ImageBinIterator()
    it.set_param("image_bin", binp)
    it.set_param("image_list", lst)
    it.set_param("silent", "1")
    it.set_param("native_decoder", "1")
    it.init()
    assert it._native is not None, "native path should engage on ref packs"
    seen = 0
    while it.next():
        inst = it.value()
        assert np.abs(inst.data - arrs[seen]).mean() < 12.0
        seen += 1
    assert seen == len(arrs)


def test_im2bin_tool_ref_format(tmp_path):
    from PIL import Image

    rng = np.random.RandomState(9)
    root = tmp_path / "imgs"
    root.mkdir()
    lst = str(tmp_path / "i.lst")
    with open(lst, "w") as f:
        for i in range(4):
            arr = rng.randint(0, 256, (16, 16, 3)).astype(np.uint8)
            Image.fromarray(arr).save(str(root / f"{i}.jpg"), "JPEG")
            f.write(f"{i}\t0\t{i}.jpg\n")
    out = str(tmp_path / "o.bin")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "im2bin.py"),
         lst, str(root) + os.sep, out, "--format", "ref"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr
    assert detect_bin_format(out) == "ref"
    assert sum(len(pg) for pg in iter_bin_pages(out)) == 4


def test_train_on_ref_pack_end_to_end(tmp_path):
    """A cxxnet-era pack (ref bit-format .bin + .lst) trains via the conf
    path with zero repacking — the VERDICT #2 'done' criterion."""
    from cxxnet_tpu import config as C
    from cxxnet_tpu.io.data import create_iterator
    from cxxnet_tpu.nnet.trainer import NetTrainer

    binp, lst, _ = _write_jpeg_pack(tmp_path, RefBinPageWriter, n=12, size=16)
    sec = C.split_sections(C.parse_pairs(f"""
data = train
iter = imgbin
  image_bin = "{binp}"
  image_list = "{lst}"
  native_decoder = 0
  input_shape = 3,16,16
  batch_size = 4
  round_batch = 1
  label_width = 1
iter = end
""")).find("data")[0]
    it = create_iterator(sec.entries)
    it.init()
    tr = NetTrainer()
    tr.set_params(C.parse_pairs("""
batch_size = 4
input_shape = 3,16,16
eta = 0.01
netconfig = start
layer[0->1] = flatten
layer[1->2] = fullc:fc
  nhidden = 3
layer[2->2] = softmax
netconfig = end
"""))
    tr.init_model()
    steps = 0
    it.before_first()
    while it.next():
        tr.update(it.value())
        steps += 1
    assert steps == 3
    assert all(
        np.isfinite(np.asarray(w)).all()
        for tags in tr.params.values() for w in tags.values()
    )


def test_truncated_pack_raises(tmp_path):
    p = str(tmp_path / "t.bin")
    open(p, "wb").write(b"\x01\x00")  # 2 bytes: truncation, not empty
    with pytest.raises(Exception):
        list(iter_bin_pages(p))
