"""Pallas kernels vs their XLA golden models (the PairTest discipline,
SURVEY §4.1): identical inputs, compare outputs and input-gradients.

Kernels run in ``interpret=True`` mode on the CPU harness; on TPU the
same code compiles natively.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.ops.lrn import lrn, lrn_matmul, lrn_xla


@pytest.mark.parametrize("shape", [(2, 5, 5, 64), (16, 192), (2, 7, 7, 96)])
@pytest.mark.parametrize("nsize", [3, 5])
def test_lrn_pallas_matches_xla_forward(rng, shape, nsize):
    x = jnp.asarray(rng.randn(*shape).astype(np.float32))
    got = lrn(x, nsize, 0.0001, 0.75, 1.0, True)
    want = lrn_xla(x, nsize, 0.0001, 0.75, 1.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("nsize", [3, 5])
def test_lrn_pallas_matches_xla_grad(rng, nsize):
    x = jnp.asarray(rng.randn(2, 4, 4, 32).astype(np.float32))

    def loss_pallas(x):
        return jnp.sum(lrn(x, nsize, 0.001, 0.75, 1.0, True) ** 2)

    def loss_xla(x):
        return jnp.sum(lrn_xla(x, nsize, 0.001, 0.75, 1.0) ** 2)

    g1 = jax.grad(loss_pallas)(x)
    g2 = jax.grad(loss_xla)(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("c,nsize", [(8, 3), (8, 4), (16, 5), (16, 2)])
def test_lrn_matmul_band_exact(rng, c, nsize):
    """The banded-matmul window (lrn_matmul) must select EXACTLY the
    reduce_window channels, including even-nsize asymmetric windows and
    clipped edges: integer-valued x with beta=1, knorm=0, alpha=n makes
    any band mistake an integer-sized discrepancy."""
    x = jnp.asarray(rng.randint(1, 5, (2, 3, 3, c)).astype(np.float32))
    a = lrn_xla(x, nsize, float(nsize), 1.0, 0.0)
    b = lrn_matmul(x, nsize, float(nsize), 1.0, 0.0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("shape", [(2, 5, 5, 64), (16, 192)])
@pytest.mark.parametrize("nsize", [3, 5])
def test_lrn_matmul_matches_xla(rng, shape, nsize):
    x = jnp.asarray(rng.randn(*shape).astype(np.float32))
    got = lrn_matmul(x, nsize, 0.001, 0.75, 1.0)
    want = lrn_xla(x, nsize, 0.001, 0.75, 1.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    g1 = jax.grad(lambda v: jnp.sum(lrn_matmul(v, nsize, 0.001, 0.75,
                                               1.0) ** 2))(x)
    g2 = jax.grad(lambda v: jnp.sum(lrn_xla(v, nsize, 0.001, 0.75,
                                            1.0) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=2e-4, atol=2e-4)


def test_lrn_layer_matmul_dispatch(rng, monkeypatch):
    """`lrn_impl = matmul` on the LAYER really routes through lrn_matmul
    (call-counted via monkeypatch) and matches the default XLA path."""
    import importlib

    from cxxnet_tpu.layers.base import create_layer

    # NB: the package re-exports the `lrn` FUNCTION as an attribute of
    # cxxnet_tpu.ops, shadowing the module name — go via importlib
    lrn_mod = importlib.import_module("cxxnet_tpu.ops.lrn")

    calls = []
    real = lrn_mod.lrn_matmul
    monkeypatch.setattr(
        lrn_mod, "lrn_matmul",
        lambda *a, **k: (calls.append(1), real(*a, **k))[1],
    )
    x = jnp.asarray(rng.randn(2, 4, 4, 32).astype(np.float32))
    outs = []
    for impl in ("auto", "matmul"):
        lay = create_layer("lrn")
        lay.set_param("local_size", "5")
        lay.set_param("lrn_impl", impl)
        outs.append(lay.apply({}, [x])[0])
    assert len(calls) == 1  # only the matmul-configured layer dispatched
    np.testing.assert_allclose(np.asarray(outs[0]), np.asarray(outs[1]),
                               rtol=2e-5, atol=2e-5)


def test_lrn_pallas_bf16(rng):
    x = jnp.asarray(rng.randn(4, 3, 3, 128).astype(np.float32)).astype(
        jnp.bfloat16
    )
    got = lrn(x, 5, 0.0001, 0.75, 1.0, True)
    assert got.dtype == jnp.bfloat16
    want = lrn_xla(x.astype(jnp.float32), 5, 0.0001, 0.75, 1.0)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want), rtol=2e-2, atol=2e-2
    )


def test_lrn_layer_uses_xla_on_cpu(rng):
    """lrn_impl=auto falls back to stock XLA off-TPU; pallas forced works."""
    from cxxnet_tpu.layers import create_layer

    lay = create_layer("lrn")
    lay.set_param("local_size", "5")
    assert not lay._use_pallas(64, "float32")
    x = jnp.asarray(rng.randn(2, 4, 4, 16).astype(np.float32))
    (y_xla,) = lay.apply({}, [x])
    lay.set_param("lrn_impl", "pallas")
    with pytest.raises(Exception):
        lay.set_param("lrn_impl", "bogus")


# ---------------------------------------------------------------- maxpool
from cxxnet_tpu.layers.conv import _maxpool_eq
from cxxnet_tpu.ops.maxpool import maxpool_fused


@pytest.mark.parametrize("hw,k,s,p", [
    (12, 3, 2, 0), (8, 2, 2, 0), (9, 3, 3, 0), (8, 3, 1, 1), (7, 3, 2, 1),
])
def test_maxpool_pallas_matches_xla(rng, hw, k, s, p):
    """Pallas kernel (interpret mode on CPU) == the XLA unpool-VJP
    expression, forward and gradient, incl. tied maxima."""
    x = rng.randn(3, hw, hw, 8).astype(np.float32)
    x[:, : hw // 2] = np.maximum(x[:, : hw // 2], 0.0)  # force ties
    xj = jnp.asarray(x)
    want = _maxpool_eq(xj, k, k, s, p, p)
    got = maxpool_fused(xj, k, k, s, p, p, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
    g = jnp.asarray(rng.randn(*want.shape).astype(np.float32))
    gw = jax.grad(lambda v: (_maxpool_eq(v, k, k, s, p, p) * g).sum())(xj)
    gg = jax.grad(
        lambda v: (maxpool_fused(v, k, k, s, p, p, True) * g).sum()
    )(xj)
    np.testing.assert_allclose(np.asarray(gg), np.asarray(gw),
                               rtol=1e-5, atol=1e-6)


def test_maxpool_pallas_bf16(rng):
    x = jnp.asarray(rng.randn(2, 8, 8, 16), jnp.bfloat16)
    want = _maxpool_eq(x, 3, 3, 2, 0, 0)
    got = maxpool_fused(x, 3, 3, 2, 0, 0, True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32)
    )


def test_pool_layer_uses_xla_on_cpu(rng):
    from cxxnet_tpu.layers import create_layer

    lay = create_layer("max_pooling")
    lay.set_param("kernel_size", "2")
    lay.set_param("stride", "2")
    assert lay._use_pallas(8, jnp.float32) is False  # auto never picks pallas
    lay.set_param("pool_impl", "pallas")
    assert lay._use_pallas(8, jnp.float32) is True
    with pytest.raises(ValueError):
        lay.set_param("pool_impl", "bogus")


def test_maxpool_pallas_bwd_matches_xla():
    """pool_impl=pallas_bwd: one-pass stride-1 backward kernel equals
    the XLA unpool-equality path, values and gradients."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cxxnet_tpu.layers.conv import _maxpool_eq, _maxpool_eq_pb

    rng = np.random.RandomState(0)
    # ties included: quantized values make equality duplication real
    x = jnp.asarray(
        np.round(rng.randn(2, 9, 9, 8) * 2) / 2, jnp.float32
    )
    for k, pad in ((3, 1), (5, 2)):  # same-size pools
        ref = _maxpool_eq(x, k, k, 1, pad, pad)
        got = _maxpool_eq_pb(x, k, pad, True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   err_msg=f"fwd k={k} pad={pad}")
        gr = jax.grad(lambda v: (_maxpool_eq(v, k, k, 1, pad, pad)
                                 ** 2).sum())(x)
        gp = jax.grad(lambda v: (_maxpool_eq_pb(v, k, pad, True)
                                 ** 2).sum())(x)
        np.testing.assert_allclose(
            np.asarray(gp), np.asarray(gr), rtol=1e-5, atol=1e-5,
            err_msg=f"bwd k={k} pad={pad}",
        )
