"""Async data-parallel (``cxxnet_tpu/parallel/async_ps``): overlapped
per-group gradient exchange + bounded staleness.

The correctness contract (doc/parallel.md "Async data-parallel"):

* ``staleness = 0, async_overlap = 1`` is BITWISE equal to the
  synchronous ``det_reduce`` fused step — same all-gather + ordered
  fold, same updater math, just split into dispatch-ordered per-group
  programs (and allclose to the stock GSPMD step, the bound
  ``det_reduce`` itself carries);
* the compiled pipeline has NO monolithic all-reduce anywhere — the
  per-group reduce programs exist (one per exchange group) and each
  carries its own all-gather;
* ``staleness = k`` delays every apply by exactly k aggregates, the
  hard re-sync barrier (``async_resync_period``) and checkpoint
  serialization drain the pipeline, and the whole thing replays
  deterministically.
"""

import numpy as np
import pytest

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.parallel.async_ps import (
    group_param_counts,
    partition_groups,
)

MLP_CFG = [
    ("dev", "tpu:0-3"),
    ("batch_size", "16"),
    ("input_shape", "1,1,16"),
    ("seed", "7"),
    ("eta", "0.1"),
    ("momentum", "0.9"),
    ("netconfig", "start"),
    ("layer[0->1]", "fullc:fc1"),
    ("nhidden", "32"),
    ("layer[1->2]", "sigmoid"),
    ("layer[2->3]", "fullc:fc2"),
    ("nhidden", "8"),
    ("layer[3->3]", "softmax"),
    ("netconfig", "end"),
]


def _build(extra=()):
    tr = NetTrainer()
    tr.set_params(list(MLP_CFG) + list(extra))
    tr.init_model()
    return tr


def _batches(n=4, seed=3, bs=16, nin=16, nout=8):
    rng = np.random.RandomState(seed)
    return [
        DataBatch(data=rng.randn(bs, nin).astype(np.float32),
                  label=rng.randint(0, nout, (bs, 1)).astype(np.float32))
        for _ in range(n)
    ]


def _params_np(tr):
    return {k: {t: np.asarray(w) for t, w in tags.items()}
            for k, tags in tr.params.items()}


def _assert_params(a, b, exact=True, msg=""):
    for key in a:
        for tag in a[key]:
            if exact:
                np.testing.assert_array_equal(
                    a[key][tag], b[key][tag], err_msg=f"{key}/{tag}: {msg}")
            else:
                np.testing.assert_allclose(
                    a[key][tag], b[key][tag], rtol=2e-4, atol=2e-5,
                    err_msg=f"{key}/{tag}: {msg}")


# ----------------------------------------------------------------------
# group partitioning
def test_partition_groups_balanced_and_contiguous():
    params = {
        f"l{i}": {"wmat": np.zeros((s,)), "bias": np.zeros((1,))}
        for i, s in enumerate([100, 100, 100, 100])
    }
    groups = partition_groups(params, 4)
    assert len(groups) == 4
    flat = [kt for g in groups for kt in g]
    # contiguous: the concatenation is exactly the tensor order
    assert flat == [(f"l{i}", t) for i in range(4)
                    for t in ("wmat", "bias")]
    counts = group_param_counts(params, groups)
    assert all(c >= 100 for c in counts)  # every group got real weight


def test_partition_groups_auto_and_clamp():
    params = {"l0": {"wmat": np.zeros((10,)), "bias": np.zeros((2,))}}
    assert len(partition_groups(params, 0)) == 2   # auto: min(4, n)
    assert len(partition_groups(params, 99)) == 2  # clamped to n
    groups = partition_groups(params, 1)
    assert groups == [[("l0", "wmat"), ("l0", "bias")]]


# ----------------------------------------------------------------------
# exact parity: the acceptance contract
def test_async_staleness0_bitwise_equals_sync_fused():
    """The overlapped pipeline at staleness=0 IS the synchronous fused
    step: bitwise equal to ``det_reduce = 1`` (identical ordered fold +
    updater math), allclose to the stock GSPMD step (the same bound
    det_reduce itself carries vs all-reduce ordering)."""
    sync_gspmd = _build()
    sync_det = _build([("det_reduce", "1")])
    async_tr = _build([("async_overlap", "1")])
    for tr in (sync_gspmd, sync_det, async_tr):
        for b in _batches():
            tr.update(b)
    async_tr.async_round_end(1)
    _assert_params(_params_np(sync_det), _params_np(async_tr),
                   exact=True, msg="async(staleness=0) != det_reduce sync")
    _assert_params(_params_np(sync_gspmd), _params_np(async_tr),
                   exact=False, msg="async drifted from the GSPMD step")
    snap = async_tr.async_snapshot()
    assert snap["pushes"] == snap["applies"] == 4 * snap["groups"]
    assert snap["pending"] == [0] * snap["groups"]


def test_async_is_deterministic():
    a, b = (_build([("async_overlap", "1"), ("async_groups", "3")])
            for _ in range(2))
    for tr in (a, b):
        for batch in _batches():
            tr.update(batch)
        tr.async_round_end(1)
    _assert_params(_params_np(a), _params_np(b), exact=True,
                   msg="async step not deterministic")


def test_async_group_count_key():
    tr = _build([("async_overlap", "1"), ("async_groups", "2")])
    tr.update(_batches(1)[0])
    assert tr.async_snapshot()["groups"] == 2
    auto = _build([("async_overlap", "1")])
    auto.update(_batches(1)[0])
    assert auto.async_snapshot()["groups"] == 4  # 4 tensors -> min(4, 4)


# ----------------------------------------------------------------------
# compiled-HLO contract: per-group collectives, no monolithic all-reduce
def test_async_hlo_per_group_collectives_no_allreduce():
    import jax
    import jax.numpy as jnp

    tr = _build([("async_overlap", "1"), ("async_groups", "2")])
    tr.update(_batches(1)[0])  # builds every program
    stepper = tr._async
    assert len(stepper._reduce_progs) == 2
    assert all(p is not None for p in stepper._reduce_progs)

    grad_txt = stepper._grad_fn().lower(
        tr.params, jnp.zeros((16, 16), jnp.float32),
        jnp.zeros((16, 1), jnp.float32), jnp.ones((16,), jnp.float32),
        jax.random.PRNGKey(0), jnp.asarray(0, jnp.int32),
    ).compile().as_text()
    # the backward carries NO cross-replica collective at all — the
    # exchange belongs to the per-group reduce dispatches
    assert "all-reduce" not in grad_txt

    from cxxnet_tpu.parallel.async_ps.groups import subtree

    n = tr.mesh_plan.n_data
    for gid, group in enumerate(stepper.groups):
        stack = {
            k: {t: jnp.zeros((n,) + np.shape(tr.params[k][t]), jnp.float32)
                for t in tags}
            for k, tags in subtree(tr.params, group).items()
        }
        txt = stepper._reduce_fn(gid).lower(stack).compile().as_text()
        assert "all-gather" in txt, f"group {gid}: no all-gather"
        assert "all-reduce" not in txt, f"group {gid}: monolithic reduce"


# ----------------------------------------------------------------------
# bounded staleness semantics
def test_staleness_delays_applies_by_exactly_k():
    tr = _build([("async_overlap", "1"), ("staleness", "2"),
                 ("async_resync_period", "1000")])
    init = _params_np(tr)
    batches = _batches(5)
    for i, b in enumerate(batches):
        tr.update(b)
        snap = tr.async_snapshot()
        # applies lag pushes by exactly min(steps, k) aggregates
        expect_pending = min(i + 1, 2)
        assert snap["pending"] == [expect_pending] * snap["groups"]
    # first two steps applied nothing: params were still the init for
    # steps 1-2 (the pipeline fill), then moved
    assert tr.async_snapshot()["applies"] == 3 * tr.async_snapshot()["groups"]
    changed = any(
        not np.array_equal(init[k][t], np.asarray(tr.params[k][t]))
        for k in init for t in init[k])
    assert changed


def test_staleness_zero_applies_immediately():
    tr = _build([("async_overlap", "1")])
    init = _params_np(tr)
    tr.update(_batches(1)[0])
    snap = tr.async_snapshot()
    assert snap["pending"] == [0] * snap["groups"]
    assert any(not np.array_equal(init[k][t], np.asarray(tr.params[k][t]))
               for k in init for t in init[k])


def test_resync_period_controls_the_drain():
    tr = _build([("async_overlap", "1"), ("staleness", "1"),
                 ("async_resync_period", "2")])
    tr.update(_batches(1)[0])
    assert sum(tr.async_snapshot()["pending"]) > 0
    assert tr.async_round_end(1) is False  # 1 % 2 != 0: fence only
    assert sum(tr.async_snapshot()["pending"]) > 0
    assert tr.async_round_end(2) is True   # the hard barrier
    assert sum(tr.async_snapshot()["pending"]) == 0


def test_checkpoint_serialization_drains_the_pipeline():
    """Checkpoints are SYNCHRONOUS states: every pushed aggregate is
    applied before the bytes are assembled, and the saved weights load
    back bit-equal."""
    import os
    import tempfile

    tr = _build([("async_overlap", "1"), ("staleness", "2"),
                 ("async_resync_period", "1000")])
    for b in _batches(3):
        tr.update(b)
    assert sum(tr.async_snapshot()["pending"]) > 0
    blob = tr.checkpoint_bytes()
    snap = tr.async_snapshot()
    assert snap["pending"] == [0] * snap["groups"]
    assert snap["applies"] == snap["pushes"]
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.model")
        with open(path, "wb") as f:
            f.write(blob)
        tr2 = NetTrainer()
        tr2.set_params(list(MLP_CFG) + [("async_overlap", "1")])
        tr2.load_model(path)
        _assert_params(_params_np(tr), _params_np(tr2), exact=True,
                       msg="drained checkpoint did not round-trip")


def test_staleness_drained_run_matches_explicit_delayed_math():
    """staleness=1 over T steps + drain applies EVERY pushed gradient
    exactly once, in push order — pushes == applies and two identical
    runs (one drained mid-way via checkpoint, one at the end) agree."""
    a = _build([("async_overlap", "1"), ("staleness", "1"),
                ("async_resync_period", "1000")])
    b = _build([("async_overlap", "1"), ("staleness", "1"),
                ("async_resync_period", "1000")])
    for batch in _batches(4):
        a.update(batch)
        b.update(batch)
    a._async.updater.drain()
    b.checkpoint_bytes()  # drains too
    _assert_params(_params_np(a), _params_np(b), exact=True,
                   msg="drain path order-dependent")


# ----------------------------------------------------------------------
# validation / guard rails
def test_async_rejects_unsupported_shapes():
    for extra in ([("model_parallel", "2")], [("zero", "1")],
                  [("update_period", "2")]):
        with pytest.raises(ValueError, match="async_overlap"):
            _build([("async_overlap", "1")] + extra)


def test_async_rejects_stochastic_layers():
    cfg = [
        ("dev", "tpu:0-3"), ("batch_size", "16"),
        ("input_shape", "1,1,16"), ("seed", "7"), ("eta", "0.1"),
        ("async_overlap", "1"),
        ("netconfig", "start"),
        ("layer[0->1]", "fullc:fc1"), ("nhidden", "32"),
        ("layer[1->2]", "dropout"), ("threshold", "0.5"),
        ("layer[2->3]", "fullc:fc2"), ("nhidden", "8"),
        ("layer[3->3]", "softmax"),
        ("netconfig", "end"),
    ]
    tr = NetTrainer()
    tr.set_params(cfg)
    with pytest.raises(ValueError, match="stochastic"):
        tr.init_model()


def test_staleness_requires_async_overlap():
    with pytest.raises(ValueError, match="staleness"):
        _build([("staleness", "1")])


def test_async_key_value_validation():
    tr = NetTrainer()
    with pytest.raises(ValueError):
        tr.set_param("async_overlap", "2")
    with pytest.raises(ValueError):
        tr.set_param("staleness", "-1")
    with pytest.raises(ValueError):
        tr.set_param("async_resync_period", "0")
    with pytest.raises(ValueError):
        tr.set_param("async_groups", "-1")


def test_async_single_device_is_noop():
    """On a 1-device mesh there is no exchange to overlap — the key is
    accepted and training runs the plain synchronous path."""
    tr = NetTrainer()
    tr.set_params([("dev", "cpu") if k == "dev" else (k, v)
                   for k, v in MLP_CFG]
                  + [("async_overlap", "1"), ("staleness", "1")])
    tr.init_model()
    for b in _batches(2):
        tr.update(b)
    assert tr.epoch_counter == 2
    assert tr._async is None  # the stepper was never built
    assert tr.async_round_end(1) is False


def test_update_scan_rejects_async():
    tr = _build([("async_overlap", "1")])
    data = np.zeros((2, 16, 16), np.float32)
    labels = np.zeros((2, 16, 1), np.float32)
    with pytest.raises(ValueError, match="async"):
        tr.update_scan(data, labels)


# ----------------------------------------------------------------------
# observability
def test_async_metric_families_exported():
    from cxxnet_tpu.obs.registry import registry

    tr = _build([("async_overlap", "1"), ("staleness", "1"),
                 ("async_resync_period", "1")])
    for b in _batches(2):
        tr.update(b)
    tr.async_round_end(1)
    snap = registry().snapshot()
    assert "async_pushes_total" in snap
    assert 'async_pushes_total{group="0"}' in snap["async_pushes_total"]
    assert "async_staleness_steps" in snap
    assert "async_overlap_fraction" in snap
    frac = snap["async_overlap_fraction"]["async_overlap_fraction"]
    assert 0.0 <= frac <= 1.0


def test_async_divergence_guard_sees_the_loss():
    from cxxnet_tpu.utils.checkpoint import DivergenceError

    tr = _build([("async_overlap", "1"),
                 ("divergence_policy", "abort"),
                 ("inject_nan_step", "1")])
    batches = _batches(2)
    tr.update(batches[0])
    with pytest.raises(DivergenceError):
        tr.update(batches[1])


def test_async_eval_train_metrics_match_sync():
    """eval_train metrics consume the async step's out rows — same
    numbers the det-sync step reports for the same stream."""
    a = _build([("det_reduce", "1"), ("eval_train", "1"),
                ("metric", "error")])
    b = _build([("async_overlap", "1"), ("eval_train", "1"),
                ("metric", "error")])
    for tr in (a, b):
        for batch in _batches():
            tr.update(batch)
    line_a = a.evaluate(None, "train")
    b.async_round_end(1)
    line_b = b.evaluate(None, "train")
    assert line_a == line_b


# ----------------------------------------------------------------------
# end to end through the CLI round loop (single process, 4-device mesh)
def _write_cli_conf(tmp_path, overrides):
    import os

    from cxxnet_tpu.io.mnist import write_idx_images, write_idx_labels

    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (64, 4, 4)).astype(np.uint8)
    labels = (imgs.reshape(64, -1).mean(1) > 127).astype(np.uint8)
    write_idx_images(str(tmp_path / "img.idx"), imgs)
    write_idx_labels(str(tmp_path / "lab.idx"), labels)
    conf = tmp_path / "async.conf"
    conf.write_text(f"""
data = train
iter = mnist
  path_img = "{tmp_path}/img.idx"
  path_label = "{tmp_path}/lab.idx"
  shuffle = 1
iter = end
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[fc1->out] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = 16
dev = tpu:0-3
num_round = 2
eval_train = 0
eta = 0.1
momentum = 0.9
seed = 7
metric = error
silent = 1
""")
    mdir = tmp_path / ("models_" + overrides[0].split("=")[0])
    os.makedirs(mdir, exist_ok=True)
    return str(conf), str(mdir)


def _cli_crcs(tmp_path, overrides):
    from cxxnet_tpu.cli import LearnTask
    from cxxnet_tpu.utils import checkpoint as ckpt

    conf, mdir = _write_cli_conf(tmp_path, overrides)
    task = LearnTask()
    rc = task.run([conf, f"model_dir={mdir}"] + overrides)
    assert rc in (0, None)
    out = {}
    for round_, path in ckpt.list_checkpoints(mdir):
        man = ckpt.read_manifest(path)
        assert man is not None
        out[round_] = man["crc32"]
    return out


@pytest.mark.slow
def test_cli_async_round_loop_bitwise_parity(tmp_path):
    """The whole CLI round loop (iterators, padding, telemetry, the
    round-boundary fence) at async_overlap=1 staleness=0 writes
    checkpoint CRCs bitwise equal to the det_reduce synchronous run —
    the in-process twin of the ASYNC=1 4-process lane."""
    sync = _cli_crcs(tmp_path, ["det_reduce=1"])
    async_ = _cli_crcs(tmp_path, ["async_overlap=1", "staleness=0"])
    assert sync and sync == async_, (
        f"CLI CRCs diverged: sync {sync} vs async {async_}")
