"""bench.py must NEVER silently hang on a dead TPU relay (round-3
postmortem: BENCH_r03.json rc=124 with zero output after 25 min).

These tests run bench.py as a subprocess the way the driver does and
assert the fail-fast contract: dead relay -> parseable diagnostic JSON
on stdout within seconds, rc 0.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dead_relay_fails_fast_with_diagnostic_json():
    env = dict(os.environ)
    # simulate the axon production environment: the site path mentions
    # axon (so _tpu_expected() is true) and the relay port is dead
    env["PYTHONPATH"] = REPO + os.pathsep + "/nonexistent/.axon_site"
    env.pop("JAX_PLATFORMS", None)
    env["AXON_RELAY_PORT"] = "1"  # nothing listens on port 1
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=60,
    )
    assert p.returncode == 0, p.stderr[-500:]
    lines = [l for l in p.stdout.splitlines() if l.startswith("{")]
    assert lines, f"no JSON line on stdout: {p.stdout!r}"
    rec = json.loads(lines[-1])
    assert rec["value"] is None
    assert "relay dead" in rec["error"]


def test_cpu_env_with_axon_on_path_still_probes():
    """JAX_PLATFORMS=cpu does NOT disarm the relay dial when .axon_site
    is on PYTHONPATH (sitecustomize re-registers the axon backend after
    env processing — tests/conftest.py documents it), so the probe must
    still fail fast there."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + "/nonexistent/.axon_site"
    env["JAX_PLATFORMS"] = "cpu"
    env["AXON_RELAY_PORT"] = "1"
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=60,
    )
    assert p.returncode == 0
    rec = json.loads([l for l in p.stdout.splitlines()
                      if l.startswith("{")][-1])
    assert rec["value"] is None and "relay dead" in rec["error"]


def test_axon_free_path_skips_probe_and_watchdog_names_stage():
    """Without .axon_site on the path nothing dials the relay: the probe
    is skipped and a genuinely slow run hits the watchdog, which names
    the stuck stage.  (A 3s deadline fires mid-compile — that also
    proves the probe did not block the run.)"""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["AXON_RELAY_PORT"] = "1"
    env["BENCH_WATCHDOG_SEC"] = "3"
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "4", "2", "1"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    lines = [l for l in p.stdout.splitlines() if l.startswith("{")]
    assert lines, f"no JSON line on stdout: {p.stdout!r}"
    rec = json.loads(lines[-1])
    # watchdog fired mid-compile: diagnostic names the stage, rc 3
    assert p.returncode == 3
    assert "watchdog" in rec["error"]
    assert "relay dead" not in rec.get("error", "")


def test_watchdog_reemits_measurement_instead_of_null(capsys):
    """A watchdog fire AFTER a measurement line exists must re-emit that
    measurement as the last stdout JSON line (never clobber it with
    value: null) — last-JSON-line drivers keep the real number."""
    sys.path.insert(0, REPO)
    import bench

    bench._STAGE.pop("done", None)
    bench._emit("provisional", 1234.5, 128)
    capsys.readouterr()
    real_exit = os._exit
    try:
        os._exit = lambda code: None
        bench._STAGE["name"] = "timed scans (final)"
        bench._arm_watchdog(9999)
        t = bench._STAGE["watchdog"]
        t.cancel()       # never let it really fire...
        t.function()     # ...invoke fire() synchronously instead
    finally:
        os._exit = real_exit
        bench._STAGE["done"] = True
        bench._STAGE.pop("last_emit", None)
    out = capsys.readouterr().out
    rec = json.loads([l for l in out.splitlines() if l.startswith("{")][-1])
    assert rec["value"] == 1234.5
    assert "watchdog" in rec and "stuck at stage" in rec["watchdog"]


def test_lock_contention_fails_fast(tmp_path):
    """A second TPU-dialing bench while another client holds the relay
    flock must emit the diagnostic and exit 0 — never double-dial the
    single-client relay (the round-3 wedge)."""
    import fcntl

    # a relay stand-in so the probe passes and the LOCK is the decider
    import socket
    import threading

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(5)
    port = srv.getsockname()[1]
    t = threading.Thread(target=lambda: [srv.accept() for _ in range(9)],
                         daemon=True)
    t.start()

    fd = os.open("/tmp/tpu_relay.lock", os.O_CREAT | os.O_WRONLY, 0o644)
    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    try:
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + "/nonexistent/.axon_site"
        env.pop("JAX_PLATFORMS", None)
        env.pop("TPU_QUEUE_LOCK_HELD", None)
        env["AXON_RELAY_PORT"] = str(port)
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert p.returncode == 0, p.stderr[-400:]
        rec = json.loads([l for l in p.stdout.splitlines()
                          if l.startswith("{")][-1])
        assert rec["value"] is None and "holds" in rec["error"]
        # ...and with the queue's re-entrancy marker the lock is waived
        # (the process then proceeds toward jax; kill it via watchdog)
        env["TPU_QUEUE_LOCK_HELD"] = "1"
        env["BENCH_WATCHDOG_SEC"] = "3"
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, capture_output=True, text=True, timeout=60,
        )
        out = [l for l in p.stdout.splitlines() if l.startswith("{")]
        assert out and "holds" not in json.loads(out[-1]).get("error", "")
    finally:
        os.close(fd)
        srv.close()
