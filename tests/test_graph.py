"""NetGraph parser + FunctionalNet tests against the reference configs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu import config as C
from cxxnet_tpu.nnet import FunctionalNet, NetGraph

MNIST_NET = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 100
  init_sigma = 0.01
layer[+1:sg1] = sigmoid:se1
layer[sg1->fc2] = fullc:fc2
  nhidden = 10
  init_sigma = 0.01
layer[+0] = softmax
netconfig=end
input_shape = 1,1,784
batch_size = 32
"""


def build(text):
    cfg = C.parse_pairs(text)
    g = NetGraph().configure(cfg)
    return g, FunctionalNet(g)


def test_mnist_mlp_graph():
    g, net = build(MNIST_NET)
    assert g.node_names[0] == "in"
    assert [l.type_name for l in g.layers] == ["fullc", "sigmoid", "fullc", "softmax"]
    assert g.layers[0].name == "fc1"
    # layer[+0] self-loop: softmax in node == out node
    assert g.layers[3].is_self_loop
    # node naming: layer[+1:fc1] creates node named fc1
    assert g.node_index_of("fc1") == 1
    assert g.node_index_of("sg1") == 2
    shapes = net.infer_shapes(32)
    assert shapes[0] == (32, 784)
    assert shapes[g.node_index_of("fc1")] == (32, 100)
    assert shapes[g.node_index_of("fc2")] == (32, 10)


def test_mnist_mlp_forward_and_loss():
    g, net = build(MNIST_NET)
    params = net.init_params(jax.random.PRNGKey(0), 32)
    assert set(params) == {"l0_fc1", "l2_fc2"}
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(32, 784).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 10, (32, 1)).astype(np.float32))
    nodes, loss = net.forward(params, x, labels=y, train=True)
    out = nodes[net.out_node_index()]
    assert out.shape == (32, 10)
    np.testing.assert_allclose(np.asarray(out.sum(-1)), 1.0, rtol=1e-5)  # softmax probs
    # scaled loss ≈ mean CE / update_period; CE ~ log(10) at init
    assert 0.9 * np.log(10) / 1 < float(loss) * 1 < 1.1 * np.log(10)
    # gradient flows to all params
    grads = jax.grad(net.loss_fn)(params, x, y)
    assert float(jnp.abs(grads["l0_fc1"]["wmat"]).max()) > 0


def test_numeric_node_graph():
    text = """
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 3
  pad = 1
  stride = 2
  nchannel = 8
layer[1->2] = max_pooling
  kernel_size = 3
  stride = 2
layer[2->3] = flatten
layer[3->3] = dropout
  threshold = 0.5
layer[3->4] = fullc
  nhidden = 10
layer[4->4] = softmax
netconfig=end
input_shape = 1,28,28
batch_size = 16
"""
    g, net = build(text)
    shapes = net.infer_shapes(16)
    assert shapes[1] == (16, 14, 14, 8)
    assert shapes[2] == (16, 7, 7, 8)
    assert shapes[3] == (16, 7 * 7 * 8)
    assert shapes[4] == (16, 10)
    params = net.init_params(jax.random.PRNGKey(1), 16)
    x = jnp.zeros((16, 28, 28, 1))
    y = jnp.zeros((16, 1))
    nodes, loss = net.forward(
        params, x, labels=y, train=True, rng=jax.random.PRNGKey(2)
    )
    assert nodes[4].shape == (16, 10)


def test_split_concat_graph():
    text = """
netconfig=start
layer[0->1,2] = split
layer[1->3] = fullc:a
  nhidden = 4
layer[2->4] = fullc:b
  nhidden = 6
layer[3,4->5] = concat
layer[5->6] = fullc:c
  nhidden = 3
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 4
"""
    g, net = build(text)
    shapes = net.infer_shapes(4)
    assert shapes[5] == (4, 10)
    assert shapes[6] == (4, 3)
    params = net.init_params(jax.random.PRNGKey(0), 4)
    x = jnp.ones((4, 8))
    nodes, _ = net.forward(params, x)
    assert nodes[6].shape == (4, 3)


def test_shared_layer_params():
    text = """
netconfig=start
layer[0->1] = fullc:fc
  nhidden = 8
layer[1->2] = sigmoid
layer[2->3] = shared[fc]
netconfig=end
input_shape = 1,1,8
batch_size = 4
"""
    g, net = build(text)
    assert g.layers[2].type_name == "shared"
    assert g.layers[2].primary == 0
    shapes = net.infer_shapes(4)
    assert shapes[3] == (4, 8)
    params = net.init_params(jax.random.PRNGKey(0), 4)
    assert list(params) == ["l0_fc"]  # one param set, shared
    x = jnp.ones((4, 8))
    nodes, _ = net.forward(params, x)
    # shared layer applies the same weights: node3 = W@sigmoid(W@x+b)+b
    w, b = np.asarray(params["l0_fc"]["wmat"]), np.asarray(params["l0_fc"]["bias"])
    h = 1 / (1 + np.exp(-(np.ones((4, 8)) @ w.T + b)))
    np.testing.assert_allclose(np.asarray(nodes[3]), h @ w.T + b, rtol=1e-4)


def test_shared_layer_rejects_own_config():
    text = """
netconfig=start
layer[0->1] = fullc:fc
  nhidden = 8
layer[1->2] = shared[fc]
  nhidden = 4
netconfig=end
"""
    with pytest.raises(ValueError):
        NetGraph().configure(C.parse_pairs(text))


def test_undefined_input_node_rejected():
    text = """
netconfig=start
layer[nope->1] = fullc
  nhidden = 8
netconfig=end
"""
    with pytest.raises(ValueError):
        NetGraph().configure(C.parse_pairs(text))


def test_label_vec_fields():
    text = """
label_vec[0,1) = label
label_vec[1,3) = aux
netconfig=start
layer[0->1] = fullc
  nhidden = 2
layer[+0] = l2_loss
  target = aux
netconfig=end
input_shape = 1,1,4
batch_size = 2
"""
    g, net = build(text)
    assert g.label_name_map["aux"] == 2
    params = net.init_params(jax.random.PRNGKey(0), 2)
    x = jnp.ones((2, 4))
    labels = jnp.asarray([[9.0, 1.0, 2.0], [9.0, 3.0, 4.0]])
    _, loss = net.forward(params, x, labels=labels)
    # loss uses columns 1:3, not column 0
    pred = np.asarray(net.forward(params, x)[0][1])
    want = 0.5 * ((pred - np.asarray(labels[:, 1:3])) ** 2).sum() / 2
    np.testing.assert_allclose(float(loss), want, rtol=1e-4)


def test_structure_roundtrip():
    g, net = build(MNIST_NET)
    s = g.structure_to_json()
    g2 = NetGraph.structure_from_json(s)
    assert g2.node_names == g.node_names
    assert g2.layers == g.layers
    assert g2.input_shape == g.input_shape
    # re-configuring the loaded graph with the same config validates OK
    g2.configure(C.parse_pairs(MNIST_NET))
    # ...and a mismatched config fails
    with pytest.raises(ValueError):
        NetGraph.structure_from_json(s).configure(
            C.parse_pairs(MNIST_NET.replace("sigmoid", "tanh"))
        )


def test_reference_netconfigs_parse():
    import os

    for rel, nlayers in (
        ("example/MNIST/MNIST.conf", 4),
        ("example/MNIST/MNIST_CONV.conf", 8),
        ("example/ImageNet/ImageNet.conf", 24),
        ("example/kaggle_bowl/bowl.conf", 17),
    ):
        path = os.path.join("/root/reference", rel)
        if not os.path.exists(path):
            pytest.skip("reference not mounted")
        split = C.split_sections(C.parse_file(path))
        g = NetGraph().configure(split.global_entries)
        assert len(g.layers) == nlayers, rel
        net = FunctionalNet(g)
        batch = int(C.cfg_get(split.global_entries, "batch_size", "16"))
        shapes = net.infer_shapes(min(batch, 16))
        assert all(s is not None for s in shapes)


def test_alexnet_forward_compiles():
    """The full AlexNet graph from the reference conf runs under jit."""
    import os

    path = "/root/reference/example/ImageNet/ImageNet.conf"
    if not os.path.exists(path):
        pytest.skip("reference not mounted")
    split = C.split_sections(C.parse_file(path))
    g = NetGraph().configure(split.global_entries)
    net = FunctionalNet(g)
    net.batch_size = 2
    params = net.init_params(jax.random.PRNGKey(0), 2)
    x = jnp.zeros((2, 227, 227, 3))
    y = jnp.zeros((2, 1))

    @jax.jit
    def step(p, x, y):
        return net.loss_fn(p, x, y, train=True, rng=jax.random.PRNGKey(0))

    loss = step(params, x, y)
    assert np.isfinite(float(loss))
