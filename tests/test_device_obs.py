"""Device-plane telemetry tests (cxxnet_tpu/obs/device.py).

The trainer's jitted programs, the serve bucket cache's compiled
predicts, and the loop fine-tuner all flow through the same
instrumentation, so these tests assert the acceptance surface on the
CPU backend: per-program FLOPs/bytes gauges labeled {kind,bucket},
cumulative compile seconds from the jax.monitoring listener, sampled
step fences, disabled-path passthrough, and the telemetry summary.
"""

import numpy as np
import pytest

from cxxnet_tpu import config as cfgmod
from cxxnet_tpu import serve
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.obs import device as obs_device
from cxxnet_tpu.obs.registry import registry

MLP_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:a1] = relu:a1
layer[a1->out] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = 32
dev = cpu
eta = 0.1
"""


@pytest.fixture(autouse=True)
def _default_device_state():
    """Every test starts from the defaults (telemetry on, sampling off)
    and leaks neither a sample_every nor a disabled flag."""
    obs_device.configure([("device_telemetry", "1"),
                          ("device_sample_every", "0")])
    yield
    obs_device.configure([("device_telemetry", "1"),
                          ("device_sample_every", "0")])


def make_trainer(seed=0):
    tr = NetTrainer()
    tr.set_params(cfgmod.parse_pairs(MLP_CFG))
    tr.set_param("seed", str(seed))
    tr.init_model()
    return tr


def _family(name):
    return registry().snapshot().get(name, {})


def _sample(name, **labels):
    for key, v in _family(name).items():
        if all(f'{k}="{val}"' in key for k, val in labels.items()):
            return v
    return None


# ----------------------------------------------------------------------
def test_trainer_programs_report_flops_bytes_and_compile_time():
    tr = make_trainer()
    x = np.random.RandomState(0).rand(32, 1, 1, 16).astype(np.float32)
    y = np.zeros((32, 1), np.float32)
    before = obs_device.summary()
    tr.update_all(x, y)
    tr.sync()
    # the fused train step registered under its kind with the batch
    # size as the bucket, with positive cost estimates
    flops = _sample("xla_program_flops", kind="train_fused", bucket="32")
    nbytes = _sample("xla_program_bytes", kind="train_fused", bucket="32")
    cold = _sample("xla_program_compile_seconds",
                   kind="train_fused", bucket="32")
    assert flops and flops > 0
    assert nbytes and nbytes > 0
    assert cold and cold > 0
    # the monitoring listener accounted the backend compile
    after = obs_device.summary()
    assert after["programs"] > before["programs"]
    assert after["compiles"] > before["compiles"]
    assert after["compile_seconds"] > before["compile_seconds"]
    assert _family("xla_compile_seconds_total")[
        "xla_compile_seconds_total"] > 0
    # a second, identical-shape step is a cache hit: no new program
    tr.update_all(x, y)
    tr.sync()
    assert obs_device.summary()["programs"] == after["programs"]


def test_eval_program_and_serve_buckets_labeled_by_batch_dim():
    tr = make_trainer(seed=1)
    eng = serve.Engine(trainer=tr, max_batch_size=32, batch_timeout_ms=1)
    try:
        eng.predict(np.random.RandomState(1).randn(3, 16)
                    .astype(np.float32))
        eng.predict(np.random.RandomState(2).randn(7, 16)
                    .astype(np.float32))
    finally:
        eng.close()
    # 3 rows pad to bucket 4, 7 rows to bucket 8 — each bucket is its
    # own compiled program and its own labeled gauge sample
    assert _sample("xla_program_flops", kind="eval", bucket="4") > 0
    assert _sample("xla_program_flops", kind="eval", bucket="8") > 0
    # bigger bucket, more estimated work
    assert (_sample("xla_program_flops", kind="eval", bucket="8")
            > _sample("xla_program_flops", kind="eval", bucket="4"))


def test_sampled_step_fences_feed_histogram():
    hist_before = _family("train_step_device_seconds").get(
        "train_step_device_seconds_count", 0.0)
    obs_device.configure([("device_sample_every", "2")])
    tr = make_trainer(seed=2)
    x = np.random.RandomState(3).rand(32, 1, 1, 16).astype(np.float32)
    y = np.zeros((32, 1), np.float32)
    for _ in range(4):
        tr.update_all(x, y)
    count = _family("train_step_device_seconds").get(
        "train_step_device_seconds_count", 0.0)
    assert count == hist_before + 2  # every 2nd of 4 updates fenced
    assert obs_device.summary()["sampled_steps"] >= 2


def test_disabled_telemetry_is_passthrough():
    obs_device.configure([("device_telemetry", "0")])
    try:
        before = obs_device.summary()
        tr = make_trainer(seed=3)
        x = np.random.RandomState(4).rand(32, 1, 1, 16).astype(np.float32)
        tr.update_all(x, np.zeros((32, 1), np.float32))
        tr.sync()
        after = obs_device.summary()
        # no program accounting happened (the jit wrapper was skipped
        # entirely at build time — zero per-call cost)
        assert after["programs"] == before["programs"]
        assert "fused" in tr._jit_cache
        assert not isinstance(tr._jit_cache["fused"],
                              obs_device.InstrumentedJit)
    finally:
        obs_device.configure([("device_telemetry", "1")])


def test_instrumented_wrapper_fails_open():
    calls = []

    class BrokenLower:
        def __call__(self, *args):
            calls.append(args)
            return "out"

        def lower(self, *args):
            raise RuntimeError("no lowering here")

    fn = obs_device.InstrumentedJit(BrokenLower(), kind="t_broken")
    assert fn(np.zeros(3)) == "out"      # accounting failed, call fine
    assert fn(np.zeros(3)) == "out"
    assert len(calls) == 2
    # the failure was event-logged once, not raised
    from cxxnet_tpu.obs import event_log

    assert event_log().suppressed_count("obs.device.lower:t_broken") >= 1


def test_memory_collector_absent_on_cpu_but_scrape_valid():
    """CPU reports no memory_stats, so the family must be ABSENT (not
    zero/sentinel) while the exposition stays schema-valid."""
    import os
    import sys

    obs_device.register_memory_collector()
    text = registry().render_prometheus()
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from obs_dump import validate_prometheus_text

    assert validate_prometheus_text(text) == []
    assert "xla_device_memory_bytes{" not in text


def test_summary_totals_monotonic_and_jsonable():
    import json

    s = obs_device.summary()
    json.dumps(s)
    for key in ("programs", "flops", "bytes", "compiles",
                "compile_seconds", "cold_call_seconds", "sampled_steps"):
        assert key in s and s[key] >= 0
