"""Golden tests: Pallas flash attention vs the XLA ``mha`` reference.

Interpret mode runs the identical kernel code on CPU (the PairTest
discipline, SURVEY §4.1); the on-TPU compile is covered by the layer's
probe machinery.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.ops.attention import mha
from cxxnet_tpu.ops.flash import _pick_block, flash_mha


def _qkv(b=2, t=64, h=2, d=16, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(
        rng.randn(b, t, h, d).astype(np.float32), dtype=dtype
    )
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_mha(causal):
    q, k, v = _qkv()
    ref = mha(q, k, v, causal=causal)
    out = flash_mha(q, k, v, causal, 32, 16, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_mha(causal):
    q, k, v = _qkv(t=32, d=8)

    def loss_ref(q, k, v):
        return (mha(q, k, v, causal=causal) ** 2).sum()

    def loss_fl(q, k, v):
        return (flash_mha(q, k, v, causal, 16, 16, True) ** 2).sum()

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gf, "qkv"):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch",
        )


def test_flash_bf16_close_to_f32_reference():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    ref = mha(q.astype(jnp.float32), k.astype(jnp.float32),
              v.astype(jnp.float32))
    out = flash_mha(q, k, v, False, 32, 32, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=0.05, atol=0.05
    )


def test_flash_uneven_blocks_and_single_block():
    # T smaller than the requested block, and T that only divides by a
    # shrunken power-of-two block
    q, k, v = _qkv(t=24, d=8)
    ref = mha(q, k, v, causal=True)
    out = flash_mha(q, k, v, True, 128, 128, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pick_block():
    assert _pick_block(256, 128) == 128
    assert _pick_block(24, 128) == 24  # whole T fits one block
    assert _pick_block(48, 32) == 16
    assert _pick_block(7, 128) == 7


def test_flash_cross_attention_lengths():
    # Tq != Tk (e.g. decoder cross-attention)
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(2, 16, 2, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 64, 2, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 64, 2, 8).astype(np.float32))
    ref = mha(q, k, v)
    out = flash_mha(q, k, v, False, 16, 32, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


# ------------------------------------------- layer-level attn_impl wiring
def test_attention_layer_attn_impl_pallas_matches_xla():
    """attn_impl = pallas routes the layer through the flash kernel (in
    interpret mode off-TPU) and must match the XLA path bit-for-bit in
    f32 within tolerance."""
    from cxxnet_tpu.layers import create_layer

    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(2, 32, 16).astype(np.float32))
    outs = {}
    for impl in ("xla", "pallas"):
        lay = create_layer("attention")
        lay.set_param("nhead", "2")
        lay.set_param("causal", "1")
        lay.set_param("init_sigma", "0.1")
        lay.set_param("attn_impl", impl)
        lay.infer_shape([(2, 32, 16)])
        params = lay.init_params(jax.random.PRNGKey(0), [(2, 32, 16)])
        (outs[impl],) = lay.apply(params, [x])
    np.testing.assert_allclose(
        np.asarray(outs["pallas"]), np.asarray(outs["xla"]),
        rtol=1e-5, atol=1e-5,
    )
    with pytest.raises(ValueError, match="attn_impl"):
        create_layer("attention").set_param("attn_impl", "cuda")


def test_a2a_with_flash_local_attention():
    """Ulysses SP composed with the flash kernel as the per-device
    full-sequence attention (attn_fn hook)."""
    from cxxnet_tpu.ops.attention import a2a_self_attention
    from cxxnet_tpu.parallel import make_mesh

    rng = np.random.RandomState(11)
    mk = lambda: jnp.asarray(rng.randn(2, 32, 4, 8).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    plan = make_mesh("cpu:0-7", model_parallel=4)
    want = mha(q, k, v, causal=True)

    def attn_fn(q_, k_, v_, causal=True):
        return flash_mha(q_, k_, v_, causal, 16, 16, True)

    got = a2a_self_attention(
        q, k, v, plan.mesh, "model", causal=True, attn_fn=attn_fn
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


# ---------------------------------------------------- flash ring attention
@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_mha(causal):
    from cxxnet_tpu.ops.attention import ring_self_attention_flash
    from cxxnet_tpu.parallel import make_mesh

    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(2, 32, 4, 16).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    plan = make_mesh("cpu:0-7", model_parallel=4)
    want = mha(q, k, v, causal=causal)
    got = ring_self_attention_flash(q, k, v, plan.mesh, "model",
                                    causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_gradients_match():
    """The lse-cotangent VJP: gradients through the log-space hop merge
    must equal full-attention gradients."""
    from cxxnet_tpu.ops.attention import ring_self_attention_flash
    from cxxnet_tpu.parallel import make_mesh

    rng = np.random.RandomState(1)
    mk = lambda: jnp.asarray(rng.randn(2, 16, 2, 8).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    plan = make_mesh("cpu:0-7", model_parallel=4)

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention_flash(
            q, k, v, plan.mesh, "model", causal=True, interpret=True) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gf, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=f"ring-flash d{name} mismatch",
        )


def test_attention_layer_ring_pallas_matches_xla_ring():
    """seq_parallel=ring + attn_impl=pallas routes the layer through the
    flash ring and matches the XLA ring output."""
    from cxxnet_tpu.layers import create_layer
    from cxxnet_tpu.parallel import make_mesh

    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(2, 32, 16).astype(np.float32))
    plan = make_mesh("cpu:0-7", model_parallel=4)
    outs = {}
    for impl in ("xla", "pallas"):
        lay = create_layer("attention")
        lay.set_param("nhead", "2")
        lay.set_param("causal", "1")
        lay.set_param("init_sigma", "0.1")
        lay.set_param("seq_parallel", "ring")
        lay.set_param("attn_impl", impl)
        lay.bind_mesh(plan)
        lay.infer_shape([(2, 32, 16)])
        params = lay.init_params(jax.random.PRNGKey(0), [(2, 32, 16)])
        (outs[impl],) = lay.apply(params, [x])
    np.testing.assert_allclose(
        np.asarray(outs["pallas"]), np.asarray(outs["xla"]),
        rtol=2e-5, atol=2e-5,
    )


def test_flash_lse_fully_masked_rows_are_zero():
    """Misaligned offsets can fully mask a query row inside a live block
    (causal, keys strictly in the row's future): `out` must be zeros for
    that row — not a mean of v (the exp(s - NEG_INF)=1 failure) — so
    `out` is valid standalone, not only jointly with lse."""
    from cxxnet_tpu.ops.flash import flash_mha_lse

    b, t, h, d = 1, 32, 2, 16
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    # keys start 8 positions after the queries: query rows 0..7 see no
    # key at all under the causal mask
    out, lse = flash_mha_lse(q, k, v, q_off=0, k_off=8, causal=True,
                             block_q=16, block_k=16, interpret=True)
    out = np.asarray(out)
    np.testing.assert_array_equal(out[:, :8], np.zeros_like(out[:, :8]))
    # the masked rows' lse stays ~NEG_INF so a ring merge washes them out
    assert np.all(np.asarray(lse)[:, :8] < -1e29)
    # live rows are real attention outputs
    assert np.abs(out[:, 8:]).max() > 0
